"""Benchmark harness — prints ONE JSON line PER METRIC.

Mirrors the reference's published matrix (`benchmark/README.md:37,50,59,
119-133`, harness `benchmark/paddle/image/run.sh:10` `paddle train
--job=time`; values recorded in BASELINE.md) plus the two north-star
metrics from BASELINE.json (ResNet-50 images/s/chip, seq2seq-NMT
tokens/s/chip). For metrics with a published reference number,
`vs_baseline` = reference_ms / our_ms (speedup; >1 is faster). For the
north stars, `vs_baseline` = value / round-1 measured number (README
r1: 1976 img/s, 90k tok/s), i.e. >1 means we improved on our own
previous round.

Run `python bench.py` for the full sweep, or `python bench.py PATTERN`
to run only metrics whose name contains PATTERN. Each metric line is
printed as soon as it is measured, so a partial run still records
results. A failed benchmark prints an "error" key on its line and the
sweep continues.

Capture discipline (VERDICT r4 item 1): the NORTH-STAR rows
(resnet50, NMT both buckets, beam decode, the two sparse rows) run
FIRST; a wall-clock budget (`BENCH_BUDGET_S`, default 2400 s) guards
the tail — rows that would start past the budget print
`{"skipped": "budget"}` instead of dying mid-sweep. A chip-health
probe (chained bf16 matmul; healthy >= ~150 TFLOP/s on v5e, 6-11
observed during throttle) runs once at start and is recorded on every
row (`health_tflops`, plus `throttled: true` when below threshold —
absolute times on a throttled chip are unreliable; only the
interleaved A/B ratio fields remain trustworthy). The sweep ends with
one compact `summary` line repeating every north-star value, so the
record keeps the headline even if earlier lines scroll out of a
bounded tail capture. `bench.py --multichip` runs the DP-scaling
sweep instead (see bench_multichip.py).
"""

import json
import os
import shutil
import sys
import time

import numpy as np

# --- full-row record (ROADMAP item 5b) -------------------------------
#
# The summary trailer keeps only north-star headlines, so rows that
# scroll out of a bounded tail capture (fused-LSTM A/B, longctx, the
# multichip matrix) used to exist in NO committed artifact. Every row
# emitted by bench.py / bench_multichip.py is therefore also appended
# to BENCH_full_rNN.jsonl next to this file (NN = newest committed
# BENCH_rNN.json + 1), which the end-of-round snapshot commits.
# Override with BENCH_FULL_RECORD=<path>; set it empty to disable
# (tests spawning bench subprocesses point it at a tmp file).

_FULL_RECORD = ["unset"]


def _full_record_path():
    p = os.environ.get("BENCH_FULL_RECORD")
    if p is not None:
        return p or None  # "" disables
    import glob
    import re

    here = os.path.dirname(os.path.abspath(__file__))
    rounds = [
        int(m.group(1))
        for f in glob.glob(os.path.join(here, "BENCH_r*.json"))
        if (m := re.search(r"BENCH_r(\d+)\.json$", f))
    ]
    nn = (max(rounds) + 1) if rounds else 1
    return os.path.join(here, f"BENCH_full_r{nn:02d}.jsonl")


def emit(line: dict) -> None:
    """Print a bench row AND append it to the full-row artifact."""
    s = json.dumps(line)
    print(s, flush=True)
    if _FULL_RECORD == ["unset"]:
        _FULL_RECORD[:] = [_full_record_path()]
    path = _FULL_RECORD[0]
    if path:
        try:
            with open(path, "a") as f:
                f.write(s + "\n")
        except OSError:
            pass  # an unwritable record must not kill the sweep


# ms/batch, 1×K40m (BASELINE.md)
BASELINES_MS = {
    "alexnet_bs64": 195.0,
    "alexnet_bs128": 334.0,
    "alexnet_bs256": 602.0,
    "alexnet_bs512": 1629.0,
    "googlenet_bs64": 613.0,
    "googlenet_bs128": 1149.0,
    "googlenet_bs256": 2348.0,
    "smallnet_bs64": 10.463,
    "smallnet_bs128": 18.184,
    "smallnet_bs256": 33.113,
    "smallnet_bs512": 63.039,
    "lstm_bs64_h256": 83.0,
    "lstm_bs64_h512": 184.0,
    "lstm_bs64_h1280": 641.0,
    "lstm_bs128_h256": 110.0,
    "lstm_bs128_h512": 261.0,
    "lstm_bs128_h1280": 1007.0,
    "lstm_bs256_h256": 170.0,
    "lstm_bs256_h512": 414.0,
    "lstm_bs256_h1280": 1655.0,
}

# round-1 measured north stars (README r1) — the bar to beat
R1_RESNET_IMG_S = 1976.0
R1_NMT_TOK_S = 90000.0

# v5e bf16 peak for MFU bookkeeping. The peak is specified in FLOPs
# (2 per MAC), so the model cost must use the same convention:
# ResNet-50 fwd ~4.1 GMACs = 8.2 GFLOP/img; train (fwd+bwd) ~3x
# = 24.6 GFLOP/img. (XLA's own cost analysis of our compiled fwd+bwd
# reports 22.3 GFLOP/img, consistent.) Counting MACs against a FLOP
# peak — as round 1 did — understates MFU by 2x.
TPU_PEAK_FLOPS = 197e12
RESNET50_TRAIN_FLOPS_PER_IMG = 24.6e9


def _setup():
    import jax

    from paddle_tpu.core import flags as _flags

    # mixed precision: float32 master params, bfloat16 compute
    # (paddle_tpu/network.py AMP policy)
    _flags.set_flag("matmul_precision", "bfloat16")
    # rbg PRNG: dropout mask generation off the critical path
    jax.config.update("jax_default_prng_impl", "rbg")
    # Persistent XLA compilation cache (same dir as tests/conftest.py):
    # the sweep is compile-dominated on first run, and the round-4
    # driver capture timed out (BENCH_r04 rc=124) largely on compiles a
    # warm cache would have skipped. Harmless if the backend declines
    # to serialize — cache writes just no-op with a warning.
    try:
        cache_dir = os.environ.get(
            "JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache"
        )
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.3
        )
    except Exception:
        pass


def chip_health_probe(short=32, long=288):
    """Latency-cancelled chip-health probe. Times a chained
    [8192,2048]@[2048,2048] bf16 matmul at TWO chain lengths and
    derives TFLOP/s from the DIFFERENCE — a single timed fetch over
    the axon tunnel includes a 50-100 ms round trip that a naive
    probe misreads as a 6-25 TFLOP/s "throttle" (measured: naive 29
    vs latency-cancelled 134 TFLOP/s in the same minute). Returns
    (tflops, rtt_ms) on TPU, None elsewhere. True sustained throttle
    still reads low (the difference scales with chip clock)."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform not in ("tpu",):
        return None
    x = jnp.ones((8192, 2048), jnp.bfloat16)
    # scale keeps the chain at ~1.0 (2048 * 2^-11 = 1): no inf churn
    w = jnp.full((2048, 2048), 2.0 ** -11, jnp.bfloat16)

    def make(chain):
        @jax.jit
        def f(x, w):
            def body(x, _):
                return x @ w, None

            x, _ = jax.lax.scan(body, x, None, length=chain)
            return jnp.sum(x[0, :8])

        return f

    best = {}
    for chain in (short, long):
        f = make(chain)
        float(f(x, w))  # compile + warm; scalar fetch forces execution
        b = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(f(x, w))
            b = min(b, time.perf_counter() - t0)
        best[chain] = b
    d = max(best[long] - best[short], 1e-6)
    flops = (long - short) * 2 * 8192 * 2048 * 2048
    tflops = flops / d / 1e12
    rtt_ms = max(best[short] - short / (long - short) * d, 0.0) * 1e3
    return tflops, rtt_ms


def dispatch_floor_probe():
    """Wall cost of dispatching a TRIVIAL program, amortized over a
    10-dispatch window — the tunnel's per-program submission floor
    (measured 2-4 ms in round 4, ~10 ms in round-5 sessions). Any
    sequential-dispatch row whose step time is near this floor is
    measuring the tunnel, not the chip; scan-of-steps arms amortize
    it. Returns ms, or None off-TPU."""
    import jax
    import jax.numpy as jnp

    if jax.devices()[0].platform not in ("tpu",):
        return None
    x = jnp.ones((8, 8), jnp.float32)

    @jax.jit
    def triv(x):
        return jnp.sum(x * 1.0001)

    float(triv(x))
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(10):
            r = triv(x)
        float(r)
        best = min(best, (time.perf_counter() - t0) / 10 * 1e3)
    return best


HEALTHY_TFLOPS = 100.0


def _timeline_fields(tl: dict) -> dict:
    """The per-step time-attribution fields (ISSUE 10) every
    north-star row carries — data-wait vs host-dispatch vs
    device-step shares of the measured wall. Bench feeds are staged
    on device up front, so rows built on the synthetic arms report
    their true data_wait of ~0; rows with a real input path (serving)
    report the queue's share. `tools/check_bench_record.py` enforces
    the three keys' presence on every north-star row."""
    data = tl.get("data_s", 0.0)
    disp = tl.get("dispatch_s", 0.0)
    dev = tl.get("device_s", 0.0)
    total = data + disp + dev
    if total <= 0:
        return {"data_wait_frac": 0.0, "host_overhead_frac": 0.0,
                "device_frac": 0.0}
    return {
        "data_wait_frac": round(data / total, 4),
        "host_overhead_frac": round(disp / total, 4),
        "device_frac": round(dev / total, 4),
    }


# `bench.py PATTERN --capture DIR`: rows with a capture path (beam
# decode) write profiler + HLO captures here for trace_attribution
_CAPTURE_DIR = [None]

# metrics whose value is repeated on the final summary line
NORTH_STARS = (
    "resnet50_train_imgs_per_s",
    "nmt_attention_train_tokens_per_s",
    "nmt_attention_train_tokens_per_s_bs512",
    "nmt_attention_train_tokens_per_s_t128",
    "nmt_beam4_decode_tokens_per_s",
    "lm_train_tokens_per_s",
    "lm_decode_paged_tokens_per_s",
    "serve_loadtest",
    "ctr_sparse_step_v_independence",
    "ctr_widedeep_sparse_v_independence",
)


def _build_arm(conf, feed, opt_conf=None, iters=20):
    """Build one measurable training program: returns (warmup_fn,
    window_fn) where window_fn runs `iters` steps and returns ms/step.
    State (params/opt/bn) is carried across calls so every window is a
    steady-state continuation."""
    import jax

    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer
    from paddle_tpu.parallel.dp import TrainStep

    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    opt = create_optimizer(
        opt_conf
        or OptimizationConf(
            learning_method="momentum", learning_rate=0.001, momentum=0.9
        ),
        net.param_confs,
    )
    st = {
        "params": params,
        "opt_state": opt.init_state(params),
        "state": net.init_state(),
        "i": 0,
    }
    step = TrainStep(net, opt)
    # measure compute, not host->device transfer of the synthetic batch
    feed = jax.device_put(feed)
    key = jax.random.key(1)

    # dispatch-vs-wait split for the row's timeline fields: the step
    # submissions are host work, the final scalar fetch is the block
    # on the device (feed is pre-staged, so data_wait is truly 0)
    timeline = {"data_s": 0.0, "dispatch_s": 0.0, "device_s": 0.0}

    def _run(n):
        t0 = time.perf_counter()
        for _ in range(n):
            (
                st["params"],
                st["opt_state"],
                st["state"],
                loss,
                _o,
            ) = step(
                st["params"], st["opt_state"], st["state"], feed,
                st["i"], key,
            )
            st["i"] += 1
        t1 = time.perf_counter()
        # float() fetch forces execution; on the axon tunnel
        # block_until_ready does not force the dependency chain
        out = float(loss)
        timeline["dispatch_s"] += t1 - t0
        timeline["device_s"] += time.perf_counter() - t1
        return out

    def warmup_fn(n=20):
        _run(n)
        # warmup includes trace+compile: reset so the row's timeline
        # fields attribute only the measured windows' dispatch/fetch
        timeline["dispatch_s"] = timeline["device_s"] = 0.0

    def window_fn():
        t0 = time.perf_counter()
        _run(iters)
        return (time.perf_counter() - t0) / iters * 1e3

    window_fn.timeline = timeline
    return warmup_fn, window_fn


def _build_arm_fused(conf, feed, opt_conf=None, inner=20):
    """One jitted program running `inner` train steps (lax.scan over
    the step) — small models sit at the per-dispatch floor (~2-4 ms on
    the tunneled chip), so per-dispatch timing measures the floor, not
    the model. Amortizing the loop inside one dispatch is the
    reference's own --job=time methodology (trainer/
    TrainerBenchmark.cpp averages many batches per timing point).
    window_fn returns ms/step = one-dispatch time / inner."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer

    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    opt = create_optimizer(
        opt_conf
        or OptimizationConf(
            learning_method="momentum", learning_rate=0.001, momentum=0.9
        ),
        net.param_confs,
    )
    feed = jax.device_put(feed)
    root = jax.random.key(1)

    def one(carry, _):
        params, opt_state, state, i = carry
        rng = jax.random.fold_in(root, i)
        (loss, (_outs, new_state)), grads = jax.value_and_grad(
            net.loss_fn, has_aux=True
        )(params, feed, state=state, train=True, rng=rng)
        params, opt_state = opt.update(grads, params, opt_state, i)
        return (params, opt_state, new_state, i + 1), loss

    @jax.jit
    def multi(carry):
        carry, losses = jax.lax.scan(one, carry, None, length=inner)
        return carry, losses[-1]

    st = {
        "carry": (
            params,
            opt.init_state(params),
            net.init_state(),
            jnp.int32(0),
        )
    }

    timeline = {"data_s": 0.0, "dispatch_s": 0.0, "device_s": 0.0}

    def _run():
        t0 = time.perf_counter()
        st["carry"], loss = multi(st["carry"])
        t1 = time.perf_counter()
        out = float(loss)  # fetch forces execution (axon tunnel)
        timeline["dispatch_s"] += t1 - t0
        timeline["device_s"] += time.perf_counter() - t1
        return out

    def warmup_fn(n=2):
        for _ in range(n):
            _run()
        # drop the compile-laden warmup from the attribution fields
        timeline["dispatch_s"] = timeline["device_s"] = 0.0

    def window_fn():
        t0 = time.perf_counter()
        _run()
        return (time.perf_counter() - t0) / inner * 1e3

    window_fn.timeline = timeline
    return warmup_fn, window_fn


def _interleaved_best(window_fns: dict, rounds=5) -> dict:
    """Round-robin the arms' timing windows and keep each arm's best —
    the only honest A/B on the intermittently-preempted tunnel
    (PERF.md methodology). All arms must already be warm."""
    best = {k: float("inf") for k in window_fns}
    for _ in range(rounds):
        for k, fn in window_fns.items():
            best[k] = min(best[k], fn())
    return best


def _time_train(conf, feed, opt_conf=None, iters=20, warmup=20,
                windows=3, fused=False):
    """Build a Network + optimizer from `conf`, run `warmup` steps, then
    time `windows` windows of `iters` steps and return the BEST
    window's ms/step — the chip behind the axon tunnel is occasionally
    preempted, and the minimum window is the robust estimate of
    steady-state step time (mean would blend in preemption stalls).
    fused=True runs each window's steps inside ONE jitted dispatch
    (small models: measures the model, not the dispatch floor)."""
    if fused:
        warmup_fn, window_fn = _build_arm_fused(
            conf, feed, opt_conf, inner=iters
        )
        # `warmup` counts steps; each fused call runs `iters` of them
        warmup_fn(max(2, warmup // iters))
    else:
        warmup_fn, window_fn = _build_arm(conf, feed, opt_conf, iters)
        warmup_fn(warmup)
    return min(window_fn() for _ in range(windows))


def _image_feed(bs, shape=(224, 224, 3), classes=1000, seed=0):
    from paddle_tpu.core.arg import id_arg, non_seq

    rng = np.random.default_rng(seed)
    image = rng.standard_normal((bs, *shape)).astype(np.float32)
    label = rng.integers(0, classes, bs).astype(np.int32)
    return {"image": non_seq(image), "label": id_arg(label)}


def bench_image(model, bs):
    from paddle_tpu import models

    factory = {
        "alexnet": models.alexnet,
        "googlenet": models.googlenet,
        "smallnet": models.smallnet_mnist_cifar,
    }[model]
    shape = (32, 32, 3) if model == "smallnet" else (224, 224, 3)
    classes = 10 if model == "smallnet" else 1000
    conf = factory(image_shape=shape, num_classes=classes)
    if model == "smallnet":
        # smallnet steps sit at the dispatch floor (~2-10 ms through
        # the tunnel): the row drives the PRODUCTION trainer option
        # (SGD steps_per_dispatch, ROADMAP 5d) both ways and A/Bs them
        return _bench_pipelined_trainer(
            conf, _image_feed(bs, shape, classes)
        )
    ms = _time_train(conf, _image_feed(bs, shape, classes))
    return {"value": round(ms, 3), "unit": "ms/batch"}


def _bench_pipelined_trainer(conf, feed, inner=20, opt_conf=None):
    """Small-model A/B through the real trainer (ROADMAP 5d: the
    scan-of-steps bench trick is now `SGD(steps_per_dispatch=N)`, and
    the row measures THAT option, not a bench-only formulation): one
    SGD steps per-batch (N=1, one program dispatch per batch — pays
    the tunnel's dispatch floor every step), the other dispatches
    `inner` batches as one scan-of-steps program. Windows interleave;
    headline = the better arm's ms/step; `pipeline_speedup` =
    per_dispatch_ms / pipelined_ms (>1: the trainer option wins —
    small-model rows now measure the chip, not the tunnel)."""
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.trainer.trainer import SGD

    opt = opt_conf or OptimizationConf(
        learning_method="momentum", learning_rate=0.001, momentum=0.9
    )
    seq_t = SGD(conf, opt, seed=0, steps_per_dispatch=1)
    pip_t = SGD(conf, opt, seed=0, steps_per_dispatch=inner)
    feeds = [feed] * inner

    def seq_window():
        t0 = time.perf_counter()
        for _ in range(inner):
            seq_t.run_step(feed)
        return (time.perf_counter() - t0) / inner * 1e3

    def pip_window():
        t0 = time.perf_counter()
        pip_t.run_steps(feeds)
        return (time.perf_counter() - t0) / inner * 1e3

    seq_window()  # compile + warm both programs
    pip_window()
    best = _interleaved_best(
        {"per_dispatch": seq_window, "pipelined": pip_window},
        rounds=5,
    )
    ms = min(best.values())
    return {
        "value": round(ms, 3),
        "unit": "ms/batch",
        "ms_per_dispatch": round(best["per_dispatch"], 3),
        "ms_pipelined": round(best["pipelined"], 3),
        "pipeline_speedup": round(
            best["per_dispatch"] / best["pipelined"], 3
        ),
        "steps_per_dispatch": inner,
    }


def bench_lstm(bs, hidden):
    """IMDB LSTM text classification (benchmark/paddle/rnn/rnn.py:9-21:
    vocab 30k, emb 128, 2×lstm, fixed length 100)."""
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.models import stacked_lstm_classifier

    T = 100
    conf = stacked_lstm_classifier(
        vocab_size=30000, emb_dim=128, hidden=hidden, num_layers=2,
        num_classes=2,
    )
    rng = np.random.default_rng(0)
    feed = {
        "words": id_arg(
            rng.integers(0, 30000, (bs, T)).astype(np.int32),
            np.full((bs,), T, np.int32),
        ),
        "label": id_arg(rng.integers(0, 2, bs).astype(np.int32)),
    }
    opt = OptimizationConf(learning_method="adam", learning_rate=2e-3)
    # lstm steps are short: measure BOTH formulations interleaved —
    # sequential dispatches and a scan-of-steps inside one dispatch —
    # and report the better one (VERDICT r3 weak #4: per-dispatch rows
    # were noisy/non-monotonic; which formulation wins varies by shape
    # and tunnel weather, so the row carries both)
    seq_w, seq_f = _build_arm(conf, feed, opt, iters=10)
    fus_w, fus_f = _build_arm_fused(conf, feed, opt, inner=10)
    seq_w(20)
    fus_w(2)
    best = _interleaved_best({"seq": seq_f, "fused": fus_f})
    ms = min(best.values())
    return {
        "value": round(ms, 3),
        "unit": "ms/batch",
        "ms_sequential": round(best["seq"], 3),
        "ms_scanned": round(best["fused"], 3),
    }


def longctx_conf(t, d=512, heads=8, layers=2, classes=512,
                 attn_impl="dense", seq_parallel="none",
                 vocab=32000):
    """The long-context self-attention model every longctx row (single
    chip AND the T>=32k ring/Ulysses multichip rows) measures:
    embedding -> N causal MHA blocks with residual fc -> per-token
    classification. One builder so the A/B arms differ ONLY in
    attn_impl / seq_parallel."""
    from paddle_tpu import dsl

    with dsl.model() as m:
        ids = dsl.data("ids", dim=(), is_ids=True, is_seq=True)
        lbl = dsl.data("label", dim=(), is_ids=True, is_seq=True)
        x = dsl.embedding(ids, size=d, vocab_size=vocab)
        for _ in range(layers):
            att = dsl._add(
                "multi_head_attention", [x], size=d,
                num_heads=heads, causal=True,
                seq_parallel=seq_parallel, attn_impl=attn_impl,
            )
            x = dsl.addto(att, dsl.fc(att, size=d, act="relu"))
        out = dsl.fc(x, size=classes, act="")
        dsl.classification_cost(out, lbl)
    return m.conf


def longctx_feed(bs, t, classes=512, vocab=32000, seed=0):
    from paddle_tpu.core.arg import id_arg

    rng = np.random.default_rng(seed)
    lens = np.full((bs,), t, np.int32)
    return {
        "ids": id_arg(
            rng.integers(0, vocab, (bs, t)).astype(np.int32), lens
        ),
        "label": id_arg(
            rng.integers(0, classes, (bs, t)).astype(np.int32), lens
        ),
    }


def _longctx_flops_fwd(bs, t, d, heads, layers, classes):
    # model FLOPs (2/MAC): per layer QKVO projections 4 matmuls *
    # 2*B*T*D^2 + attention 4*B*T^2*D (QK^T and attn@V, 2*B*T^2*D
    # each; causal halves the useful work but both impls compute the
    # full square — the same convention for both A/B arms) + mlp
    # 2*B*T*D^2, plus the output head 2*B*T*D*classes
    return layers * (
        4 * 2 * bs * t * d * d + 2 * 2 * bs * t * t * d
        + 2 * bs * t * d * d
    ) + 2 * bs * t * d * classes


def bench_longctx(bs=4, t=4096, d=512, heads=8, layers=2, classes=512):
    """Long-context causal self-attention training throughput — the
    capability the 2017 reference lacks entirely (SURVEY §5 'no ring
    attention / CP'; its sequence story is padding-free batching).
    Tokens/s counts B*T per optimizer step.

    The row is an interleaved dense-vs-flash A/B (ISSUE 12 / ROADMAP
    1): both attn_impl lowerings of the SAME model are warmed, their
    timing windows round-robined, and the row reports the better arm
    as the headline plus `fused_speedup` = dense_ms / flash_ms — the
    same A/B discipline as the resnet/nmt rows (only interleaved
    ratios are trustworthy on the shared tunnel). Analytic HBM-byte
    accounting (parallel/ring.attention_hbm_bytes) states the byte
    reduction the flash arm is EXPECTED to deliver — dense streams
    O(T^2) score bytes, flash O(T) — so the measured ratio argues
    against a stated expectation; the committed HLO captures
    (tools/traces/longctx_*.attrib.json) prove the same fact
    per-instruction. If one arm cannot build, the row carries
    `ab_skipped` naming why (tools/check_bench_record.py enforces one
    of the two fields)."""
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.parallel.ring import attention_hbm_bytes

    feed = longctx_feed(bs, t, classes)
    opt = OptimizationConf(learning_method="adam", learning_rate=1e-3)
    arms, errors = {}, {}
    for impl in ("dense", "flash"):
        try:
            conf = longctx_conf(
                t, d, heads, layers, classes, attn_impl=impl
            )
            warmup_fn, window_fn = _build_arm(conf, feed, opt, iters=10)
            warmup_fn(10)
            arms[impl] = window_fn
        except Exception as e:  # an unbuildable arm skips the A/B,
            errors[impl] = f"{type(e).__name__}: {e}"[:160]  # not the row
    if not arms:
        raise RuntimeError(f"both attention arms failed: {errors}")
    best = _interleaved_best(arms, rounds=3)
    ms = min(best.values())
    winner = min(best, key=best.get)
    toks = bs * t / (ms / 1e3)
    fwd = _longctx_flops_fwd(bs, t, d, heads, layers, classes)
    mfu = 3 * fwd * (1e3 / ms) / TPU_PEAK_FLOPS
    hd = d // heads
    bytes_dense = layers * attention_hbm_bytes(bs, t, t, heads, hd,
                                               "dense")
    bytes_flash = layers * attention_hbm_bytes(bs, t, t, heads, hd,
                                               "flash")
    out = {
        **_timeline_fields(arms[winner].timeline),
        "value": round(toks, 1),
        "unit": "tokens/s/chip (causal self-attention, T=%d)" % t,
        "ms_per_step": round(ms, 2),
        "analytic_mfu": round(mfu, 3),
        "attn_impl_winner": winner,
        # analytic attention-core HBM bytes (fwd+bwd, per step):
        # the byte-removal expectation the A/B ratio argues against
        "attn_hbm_bytes_dense": bytes_dense,
        "attn_hbm_bytes_flash": bytes_flash,
        "attn_byte_reduction_expected": round(
            bytes_dense / bytes_flash, 1
        ),
    }
    for impl, v in best.items():
        out[f"ms_{impl}"] = round(v, 3)
    if len(arms) == 2:
        out["fused_speedup"] = round(best["dense"] / best["flash"], 3)
    else:
        out["ab_skipped"] = (
            f"{next(iter(errors))} arm failed: "
            f"{next(iter(errors.values()))}"
        )
    return out


def bench_lstm_fused_vs_scan(bs=128, hidden=256):
    """Fused Pallas LSTM (fwd + reverse-time bwd kernels) vs the
    lax.scan lowering, same TRAINING step. value = scan_ms / fused_ms
    (>1: the kernel beats the scan path)."""
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.models import stacked_lstm_classifier

    T = 100
    rng = np.random.default_rng(0)
    feed = {
        "words": id_arg(
            rng.integers(0, 30000, (bs, T)).astype(np.int32),
            np.full((bs,), T, np.int32),
        ),
        "label": id_arg(rng.integers(0, 2, bs).astype(np.int32)),
    }
    opt = OptimizationConf(learning_method="adam", learning_rate=2e-3)

    # Build + compile + warm BOTH arms first, then INTERLEAVE their
    # timing windows in one process and take the min per arm: the
    # tunneled chip is intermittently preempted, and sequential A-then-B
    # timing lets a preemption window bias one arm (exactly what made
    # the round-2 number unusable — BENCH_r02 recorded 0.948 from a
    # scan window that happened to land in a quiet period).
    arms = {}
    for arm_name, use_fused in (("scan", False), ("fused", True)):
        try:
            # the flag is consulted at trace time, so the warmup (which
            # triggers compilation) must run inside the flag context
            _flags.set_flag("use_pallas_rnn", use_fused)
            conf = stacked_lstm_classifier(
                vocab_size=30000, emb_dim=128, hidden=hidden,
                num_layers=2, num_classes=2,
            )
            warmup_fn, window_fn = _build_arm(conf, feed, opt)
            warmup_fn(20)
            arms[arm_name] = window_fn
        finally:
            _flags.set_flag("use_pallas_rnn", None)

    best = _interleaved_best(arms)
    scan_ms, fused_ms = best["scan"], best["fused"]
    from paddle_tpu.layers.recurrent import _use_fused
    from paddle_tpu.ops.pallas_rnn import _lstm_bwd_plan

    plan = _lstm_bwd_plan(bs, T, hidden)
    return {
        "value": round(scan_ms / fused_ms, 3),
        "unit": "speedup (scan_ms / fused_ms)",
        "scan_ms": round(scan_ms, 3),
        "fused_ms": round(fused_ms, 3),
        # whether the reverse-time Pallas backward kernel engages in
        # the fused arm (bb >= 32 plan — see _lstm_bwd_pallas)
        "bwd_kernel": plan is not None and plan[0] >= 32,
        # what production uses at this shape: False = the scan path
        # (PERF.md: the scan wins everywhere on v5e, so the auto
        # policy never engages the kernels; this row keeps the A/B
        # honest in case a future XLA/Mosaic shift flips it)
        "auto_policy_engages": _use_fused(bs, T, hidden),
        "batch_size": bs,
        "hidden": hidden,
    }


def bench_sparse_ctr(touched=65536, inner=20):
    """Large-model sparse update (the CTR workload,
    large_model_dist_train.md): standalone table-update steps —
    touched rows gathered, momentum-updated and written back IN PLACE
    by parallel/sparse.py::SparseUpdater. Measured at 1M and 4M
    rows x 64: value = time(4M)/time(1M). O(touched) gives ~1.0; an
    O(V) dense update would give ~4. vs_baseline = 4/value.

    Load-bearing methodology (VERDICT r3 weak #3): touched=64k rows
    (not 1k — real row work, not just dispatch) and `inner` sequential
    updates amortized inside ONE jitted fori_loop (`run_steps`), so
    both arms measure the update work well above the ~2-4 ms
    per-dispatch floor of the tunneled chip."""
    import jax.numpy as jnp

    from paddle_tpu.parallel.sparse import SparseUpdater

    D = 64

    def upd(p, g, m):
        m2 = 0.9 * m + g
        return p - 0.01 * m2, m2

    rng = np.random.default_rng(0)
    times = {}
    tl = {"dispatch_s": 0.0, "device_s": 0.0}
    for v in (1 << 20, 1 << 22):
        f = SparseUpdater(upd)
        param = f.place(np.zeros((v, D), np.float32))
        mom = f.place(np.zeros((v, D), np.float32))
        # a fresh id set per inner step (realistic batch-to-batch churn)
        ids_seq = jnp.asarray(
            rng.integers(0, v, (inner, touched)), jnp.int32
        )
        grads_seq = jnp.asarray(
            rng.standard_normal((inner, touched, D)), jnp.float32
        )
        for _ in range(3):  # compile + warm
            param, (mom,) = f.run_steps(param, ids_seq, grads_seq, (mom,))
        float(jnp.sum(param[0]))
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            param, (mom,) = f.run_steps(param, ids_seq, grads_seq, (mom,))
            t1 = time.perf_counter()
            float(jnp.sum(param[0]))
            t2 = time.perf_counter()
            tl["dispatch_s"] += t1 - t0
            tl["device_s"] += t2 - t1
            best = min(best, (t2 - t0) / inner * 1e3)
        times[v] = best
    ratio = times[1 << 22] / times[1 << 20]
    return {
        **_timeline_fields(tl),
        "value": round(ratio, 3),
        "unit": "time(4M rows)/time(1M rows)",
        "ms_1m": round(times[1 << 20], 4),
        "ms_4m": round(times[1 << 22], 4),
        "table_dim": D,
        "touched": touched,
        "inner_steps": inner,
    }


def bench_ctr_widedeep_sparse(bs=256, t=64, inner=10):
    """The PRODUCTION large-model CTR path as one timed train step
    (VERDICT r3 weak #3 follow-through; models/ctr.py ctr_wide_deep +
    large_model_dist_train.md): program A gathers the touched rows from
    the placed row-major tables, runs the dense tower fwd+bwd and the
    dense-param update, and emits per-occurrence ROW gradients (the
    SparseRemoteParameterUpdater prefetch->compute->push flow); then
    SparseUpdater applies the row grads to the deep embedding table in
    place. value = time(4M rows)/time(1M rows) of the FULL step —
    O(touched) end to end gives ~1.0."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.sparse import SparseUpdater

    D, H1, H2 = 64, 64, 32
    N = bs * t

    def upd(p, g, m):
        m2 = 0.9 * m + g
        return p - 0.01 * m2, m2

    rng = np.random.default_rng(0)
    dense = {
        "w1": jnp.asarray(
            rng.standard_normal((D, H1)) * 0.05, jnp.float32
        ),
        "b1": jnp.zeros((H1,), jnp.float32),
        "w2": jnp.asarray(
            rng.standard_normal((H1, H2)) * 0.05, jnp.float32
        ),
        "b2": jnp.zeros((H2,), jnp.float32),
        "wo": jnp.asarray(
            rng.standard_normal((H2, 2)) * 0.05, jnp.float32
        ),
    }

    times = {}
    tl = {"dispatch_s": 0.0, "device_s": 0.0}
    for v in (1 << 20, 1 << 22):
        f = SparseUpdater(upd)
        table = f.place(
            (rng.standard_normal((v, D)) * 0.01).astype(np.float32)
        )
        mom = f.place(np.zeros((v, D), np.float32))
        fmt = f._format()

        # program A: gather touched rows from the PLACED table (born
        # row-major — gathers pay no relayout), dense tower fwd+bwd,
        # SGD on the dense params, per-occurrence row grads out
        def stepA(table, dense, ids, labels):
            rows = table[ids.reshape(-1), 0, :].reshape(bs, t, D)

            def loss_fn(dense, rows):
                pooled = jnp.mean(rows, axis=1)
                h = jax.nn.relu(pooled @ dense["w1"] + dense["b1"])
                h = jax.nn.relu(h @ dense["w2"] + dense["b2"])
                logits = h @ dense["wo"]
                logp = jax.nn.log_softmax(logits)
                return -jnp.mean(
                    jnp.take_along_axis(logp, labels[:, None], 1)
                )

            loss, (gd, grows) = jax.value_and_grad(
                loss_fn, argnums=(0, 1)
            )(dense, rows)
            dense = jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, dense, gd
            )
            return dense, grows.reshape(N, D), loss

        stepA_j = jax.jit(stepA, in_shardings=(fmt, None, None, None))

        ids = jnp.asarray(rng.integers(0, v, (bs, t)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 2, bs), jnp.int32)

        def full_step(dense, table, mom):
            dense, grows, loss = stepA_j(table, dense, ids, labels)
            table, (mom,) = f(table, ids, grows, (mom,))
            return dense, table, mom, loss

        for _ in range(5):
            dense, table, mom, loss = full_step(dense, table, mom)
        float(jnp.sum(table[0]))
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            for _ in range(inner):
                dense, table, mom, loss = full_step(dense, table, mom)
            t1 = time.perf_counter()
            # fetch THE TABLE, not the loss: loss is an output of
            # stepA only, and would let the window stop before the
            # final SparseUpdater dispatch has executed
            float(jnp.sum(table[0]))
            t2 = time.perf_counter()
            tl["dispatch_s"] += t1 - t0
            tl["device_s"] += t2 - t1
            best = min(best, (t2 - t0) / inner * 1e3)
        times[v] = best
    ratio = times[1 << 22] / times[1 << 20]
    return {
        **_timeline_fields(tl),
        "value": round(ratio, 3),
        "unit": "full-step time(4M rows)/time(1M rows)",
        "ms_1m": round(times[1 << 20], 4),
        "ms_4m": round(times[1 << 22], 4),
        "batch": bs,
        "seq_len": t,
        "emb_dim": D,
    }


def bench_resnet50(bs=256):
    """North star. Measures BOTH graphs interleaved — the plain
    conv/bn graph and the fused-bottleneck graph (Mosaic BN/ReLU/GEMM
    kernels, layers/fused.py) — and reports the better one as the
    headline, with both visible. Interleaving windows in one process
    is the only honest A/B on the intermittently-preempted tunnel."""
    from paddle_tpu.models import resnet

    arms = {}
    for name, fused in (("plain", False), ("fused", True)):
        conf = resnet(
            depth=50, image_shape=(224, 224, 3), num_classes=1000,
            fused=fused,
        )
        warmup_fn, window_fn = _build_arm(
            conf, _image_feed(bs, (224, 224, 3), 1000)
        )
        warmup_fn(20)
        arms[name] = window_fn
    best = _interleaved_best(arms, rounds=3)
    ms = min(best.values())
    winner = min(best, key=best.get)
    img_s = bs / (ms / 1e3)
    mfu = img_s * RESNET50_TRAIN_FLOPS_PER_IMG / TPU_PEAK_FLOPS
    return {
        "value": round(img_s, 1),
        "unit": "images/s/chip",
        "mfu": round(mfu, 4),
        "ms_per_batch": round(ms, 3),
        "batch_size": bs,
        "ms_plain": round(best["plain"], 3),
        "ms_fused": round(best["fused"], 3),
        "fused_speedup": round(best["plain"] / best["fused"], 3),
        **_timeline_fields(arms[winner].timeline),
    }


def _nmt_train_flops_per_batch(bs, t, hidden, vocab, emb):
    """Analytic NMT train FLOPs (2/MAC, fwd+bwd≈3x fwd) — the same
    convention as the ResNet MFU row, matched to the ACTUAL
    models/text.py architecture: bi-GRU encoder at hidden//2 per
    direction, per-step additive attention (dec-state projection +
    mix/score/context over T), a single tanh FC decoder cell over
    [emb, prev_state, context], and the h->V softmax projection
    (which dominates: ~30.7 of ~35 MFLOP/token at the defaults)."""
    h2 = hidden // 2
    enc = 2 * (3 * 2 * (emb + h2) * h2)  # per src token, both dirs
    att = 2 * hidden * hidden + 5 * t * hidden  # per trg token
    dec = 2 * (emb + 2 * hidden) * hidden  # dec_state tanh FC
    proj = 2 * hidden * vocab  # softmax projection
    return 3 * bs * t * (enc + att + dec + proj)


def _mha_xattn_conf(vocab, emb, d, heads, classes, attn_impl):
    """The dense-vs-flash probe model for the NMT T=128 row: target
    embeddings cross-attending the encoder sequence through the
    multi_head_attention layer (the byte-story analogue of the NMT
    attention at the row's exact B/T/hidden shape). The NMT model's
    own additive attention materializes [B, T] scores per decoder
    step — there is no [T, T] matrix to remove — so the row's flash
    A/B measures this probe, interleaved with the NMT arms; the small
    classification head keeps the probe attention-dominated instead
    of softmax-dominated."""
    from paddle_tpu import dsl

    with dsl.model() as m:
        src = dsl.data("src", dim=(), is_ids=True, is_seq=True)
        trg = dsl.data("trg_in", dim=(), is_ids=True, is_seq=True)
        lbl = dsl.data("label", dim=(), is_ids=True, is_seq=True)
        enc = dsl.embedding(src, size=emb, vocab_size=vocab,
                            name="xenc_emb")
        q = dsl.embedding(trg, size=emb, vocab_size=vocab,
                          name="xq_emb")
        att = dsl._add(
            "multi_head_attention", [q, enc], size=d,
            num_heads=heads, causal=False, attn_impl=attn_impl,
        )
        out = dsl.fc(att, size=classes, act="")
        dsl.classification_cost(out, lbl)
    return m.conf


def bench_nmt(bs=256, t=32, hidden=512, vocab=30000, emb=512,
              flash_ab=False):
    """Seq2seq NMT with attention (north star). Tokens/s counts target
    tokens (the decoder steps driving the attention + softmax work).
    Carries `mfu` from the analytic model-FLOPs convention
    (_nmt_train_flops_per_batch, same as the ResNet row). Measures
    BOTH decoder lowerings interleaved — the generic recurrent_group
    scan and the fused decoder layer (layers/fused_text.py: hoisted
    projections, merged prev-GEMMs) — and reports the better one as
    the headline with both visible (the resnet-row A/B discipline;
    which wins depends on chip health: under throttle per-op compute
    dominates and the arms converge).

    `flash_ab` (the T=128 row): two more interleaved arms run the MHA
    cross-attention probe (_mha_xattn_conf) at the row's exact shape,
    dense vs flash, and `fused_speedup` on THAT row is their ratio —
    the dense-vs-flash A/B ISSUE 12 requires; the decoder-lowering
    ratio moves to `fused_decoder_speedup`. The probe arms never
    touch the headline value (a different model must not redefine the
    row's history)."""
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.models import seq2seq_attention

    rng = np.random.default_rng(0)
    lens = np.full((bs,), t, np.int32)
    feed = {
        "src": id_arg(rng.integers(2, vocab, (bs, t)).astype(np.int32), lens),
        "trg_in": id_arg(
            rng.integers(2, vocab, (bs, t)).astype(np.int32), lens
        ),
        "trg_out": id_arg(
            rng.integers(2, vocab, (bs, t)).astype(np.int32), lens
        ),
    }
    opt = OptimizationConf(learning_method="adam", learning_rate=1e-3)
    arms = {}
    for name, fused in (("plain", False), ("fused", True)):
        conf = seq2seq_attention(
            src_vocab=vocab, trg_vocab=vocab, emb_dim=emb,
            hidden=hidden, fused_decoder=fused,
        )
        warmup_fn, window_fn = _build_arm(conf, feed, opt)
        warmup_fn(20)
        arms[name] = window_fn
    # third arm: scan-of-steps (one dispatch per window) — the tunnel's
    # per-PROGRAM submission cost reached ~10 ms in some round-5
    # sessions (2-4 ms in r4), which sequential dispatch rows absorb
    # in full; the scanned arm amortizes it 10x (same methodology as
    # the lstm rows / reference --job=time)
    conf = seq2seq_attention(src_vocab=vocab, trg_vocab=vocab,
                             emb_dim=emb, hidden=hidden)
    fw, ffn = _build_arm_fused(conf, feed, opt, inner=10)
    fw(2)
    arms["plain_scanned"] = ffn
    ab_err = None
    if flash_ab:
        probe_classes = 512
        rng2 = np.random.default_rng(1)
        probe_feed = {
            "src": feed["src"],
            "trg_in": feed["trg_in"],
            "label": id_arg(
                rng2.integers(0, probe_classes, (bs, t)).astype(
                    np.int32
                ),
                lens,
            ),
        }
        try:
            for impl in ("dense", "flash"):
                pconf = _mha_xattn_conf(
                    vocab, emb, hidden, 8, probe_classes, impl
                )
                pw, pf = _build_arm(pconf, probe_feed, opt, iters=10)
                pw(10)
                arms[f"mha_{impl}"] = pf
        except Exception as e:
            ab_err = f"{type(e).__name__}: {e}"[:160]
            arms.pop("mha_dense", None)
            arms.pop("mha_flash", None)
    best = _interleaved_best(arms, rounds=3)
    # probe arms measure the flash A/B, never the row's headline
    nmt_best = {k: v for k, v in best.items()
                if not k.startswith("mha_")}
    ms = min(nmt_best.values())
    winner = min(nmt_best, key=nmt_best.get)
    tok_s = bs * t / (ms / 1e3)
    flops = _nmt_train_flops_per_batch(bs, t, hidden, vocab, emb)
    mfu = flops / (ms / 1e3) / TPU_PEAK_FLOPS
    out = {
        **_timeline_fields(arms[winner].timeline),
        "value": round(tok_s, 0),
        "unit": "tokens/s/chip",
        "ms_per_batch": round(ms, 3),
        "batch_size": bs,
        "seq_len": t,
        "mfu": round(mfu, 4),
        "flops_per_batch_analytic": flops,
        "ms_plain": round(best["plain"], 3),
        "ms_fused": round(best["fused"], 3),
        "ms_plain_scanned": round(best["plain_scanned"], 3),
    }
    decoder_ratio = round(best["plain"] / best["fused"], 3)
    if not flash_ab:
        out["fused_speedup"] = decoder_ratio
        return out
    # the T=128 row: fused_speedup IS the dense-vs-flash ratio
    out["fused_decoder_speedup"] = decoder_ratio
    if "mha_flash" in best:
        out["ms_mha_dense"] = round(best["mha_dense"], 3)
        out["ms_mha_flash"] = round(best["mha_flash"], 3)
        out["fused_speedup"] = round(
            best["mha_dense"] / best["mha_flash"], 3
        )
        out["ab"] = "mha_crossattn_dense_vs_flash"
    else:
        out["ab_skipped"] = f"mha probe arm failed: {ab_err}"
    return out


def write_decode_hlo(dec, params, statics, boots, path):
    """Dump the compiled decode program's HLO text (gzipped) for
    tools/trace_attribution.py's HLO-capture mode — the per-iteration
    byte accounting behind the beam-decode floor analysis (ROADMAP
    5a / PERF.md round 8). Works on any backend: compilation needs no
    device execution."""
    import gzip

    static_feed, init_carry_mem, b = dec.prepare(statics, boots)
    run = dec._decode_program()
    txt = run.lower(
        params, static_feed, init_carry_mem, b
    ).compile().as_text()
    with gzip.open(path, "wt") as f:
        f.write(txt)
    return path


def write_chunk_hlo(dec, params, statics, boots, n_steps, path):
    """Dump the host rung's K-step chunk program (ISSUE 18:
    `BeamSearchDecoder._chunk_step_program` — the serving ladder's
    per-chunk dispatch unit) as gzipped compiled HLO. This is the
    capture whose audit policy checks DONATION: the carried memories
    are donated into the program and must come back aliased."""
    import gzip

    import jax.numpy as jnp

    from paddle_tpu.beam_search import NEG_INF

    static_feed, mems, b = dec.prepare(statics, boots)
    prog = dec._chunk_step_program(b, n_steps)
    k = dec.k
    words = jnp.full((b, k), dec.bos_id, jnp.int32)
    scores = jnp.full((b, k), NEG_INF, jnp.float32).at[:, 0].set(0.0)
    fin = jnp.zeros((b, k), bool)
    txt = prog.lower(
        params, static_feed, mems, words, scores, fin, jnp.int32(0)
    ).compile().as_text()
    with gzip.open(path, "wt") as f:
        f.write(txt)
    return path


def _decode_chain_probe(vocab=2048, emb=64, hidden=64, bs=8, beam=4,
                        t_src=8, max_len=32, k_tok=8, rounds=3):
    """Interleaved A/B isolating decode DISPATCH-CHAIN depth
    (ISSUE 18). The fat NMT row's per-step compute drowns dispatch
    overhead on CPU, so the chain arms run a small seq2seq config
    where the chain itself is the cost — the same regime the
    committed `nmt_beam4_decode_b32` capture proved the TPU tunnel
    lives in (byte floor 11.8 ms vs 91.4 ms measured). Arms, all
    decoding identical inputs, round-robin interleaved:

    - host_k1 / host_k: the serving host-stepped rung, one jitted
      program per token vs per K-token chunk — the pure chain A/B
      (K arms are bit-identical to K=1, pinned by tests, so the
      tokens/s ratio is chain effect only);
    - jit_k1 / jit_k: the fully-jitted while-program at both K's;
    - spec vs greedy_host_k1: speculative greedy (draft-proposes-K /
      target-verifies-in-one-forward; self-draft = accept-rate upper
      bound) vs the per-token greedy baseline.

    Every reported chain depth is MEASURED — the while-loop carries
    an iteration counter, the host/speculative paths count actual
    dispatches — never derived from config. An eos-banning
    logprob_fn pins every arm to the full max_len walk so depths are
    deterministic and comparable."""
    import jax

    from paddle_tpu.beam_search import NEG_INF
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.decoding import SpeculativeGreedyDecoder
    from paddle_tpu.models.text import (
        seq2seq_attention,
        seq2seq_attention_decoder,
    )
    from paddle_tpu.network import Network
    from paddle_tpu.serving.host_decode import host_generate

    conf = seq2seq_attention(
        src_vocab=vocab, trg_vocab=vocab, emb_dim=emb, hidden=hidden
    )
    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    rng = np.random.default_rng(0)
    src = rng.integers(2, vocab, (bs, t_src)).astype(np.int32)
    lens = np.full((bs,), t_src, np.int32)
    enc_outs, _ = net.forward(
        params, {"src": id_arg(src, lens)},
        outputs=["enc", "dec_boot"],
    )
    statics = [enc_outs["enc"]]
    boots = {"dec_state": enc_outs["dec_boot"].value}

    def ban_eos(lp, t):
        # full-length walks on every arm: deterministic chain depths
        if isinstance(lp, np.ndarray):
            lp = lp.copy()
            lp[..., 1] = NEG_INF
            return lp
        return lp.at[..., 1].set(NEG_INF)

    def mkdec(k_disp, beam_size=beam):
        d = seq2seq_attention_decoder(
            trg_vocab=vocab, emb_dim=emb, hidden=hidden, bos_id=0,
            eos_id=1, beam_size=beam_size, max_length=max_len,
            tokens_per_dispatch=k_disp,
        )
        d.logprob_fn = ban_eos
        return d

    decs = {
        "host_k1": mkdec(1),
        "host_k": mkdec(k_tok),
        "jit_k1": mkdec(1),
        "jit_k": mkdec(k_tok),
        "greedy_host_k1": mkdec(1, beam_size=1),
    }
    spec = SpeculativeGreedyDecoder(
        mkdec(1, beam_size=1), mkdec(1, beam_size=1), propose_k=k_tok
    )

    def host_arm(d):
        def run():
            t0 = time.perf_counter()
            _, ls, _ = host_generate(
                d, params, statics=statics, boots=boots
            )
            np.asarray(ls)
            return (time.perf_counter() - t0) * 1e3

        return run

    def jit_arm(d):
        def run():
            t0 = time.perf_counter()
            _, ls, _ = d.generate(params, statics=statics, boots=boots)
            np.asarray(ls)
            return (time.perf_counter() - t0) * 1e3

        return run

    def spec_arm():
        # self-draft: same params both roles — the accept-rate upper
        # bound, so the measured win is the dispatch effect alone
        t0 = time.perf_counter()
        _, ls, _ = spec.generate(
            params, params, statics=statics, boots=boots,
            draft_statics=statics, draft_boots=boots,
        )
        np.asarray(ls)
        return (time.perf_counter() - t0) * 1e3

    arms = {
        "host_k1": host_arm(decs["host_k1"]),
        "host_k": host_arm(decs["host_k"]),
        "jit_k1": jit_arm(decs["jit_k1"]),
        "jit_k": jit_arm(decs["jit_k"]),
        "greedy_host_k1": host_arm(decs["greedy_host_k1"]),
        "spec": spec_arm,
    }
    for fn in arms.values():
        fn()  # warm: compile every arm's programs
    best = _interleaved_best(arms, rounds=rounds)

    toks = bs * max_len
    return {
        # the gated triple: measured chain depth of the K arm, the
        # K=1 baseline depth, and the interleaved tokens/s ratio
        "dispatch_chain_depth": decs["host_k"].last_chain_depth,
        "dispatch_chain_depth_k1": decs["host_k1"].last_chain_depth,
        "chain_speedup": round(best["host_k1"] / best["host_k"], 3),
        "chain_tokens_per_dispatch": k_tok,
        "chain_tok_s_k1": round(toks / (best["host_k1"] / 1e3), 0),
        "chain_tok_s_k": round(toks / (best["host_k"] / 1e3), 0),
        "chain_jit_ms_k1": round(best["jit_k1"], 3),
        "chain_jit_ms_k": round(best["jit_k"], 3),
        "jit_chain_depth": decs["jit_k"].last_chain_depth,
        "jit_chain_depth_k1": decs["jit_k1"].last_chain_depth,
        "spec_tok_s": round(toks / (best["spec"] / 1e3), 0),
        "spec_speedup": round(best["greedy_host_k1"] / best["spec"], 3),
        "spec_chain_depth": spec.last_chain_depth,
        "spec_chain_depth_k1": decs["greedy_host_k1"].last_chain_depth,
        "spec_accept_rate": round(spec.last_accept_rate, 3),
        "spec_draft": "self",
        "chain_probe": {
            "vocab": vocab, "emb": emb, "hidden": hidden, "bs": bs,
            "beam": beam, "max_len": max_len,
        },
    }


def bench_beam_decode(bs=32, t_src=32, beam=4, max_len=32, hidden=512,
                      vocab=30000, emb=512, capture_dir=None):
    """Beam-search generation on the NMT model (VERDICT r3 next #3;
    reference api/SequenceGenerator.cpp + RecurrentGradientMachine.h:307
    generation mode). value = decoded target tokens/s (best beam),
    beam=4, fully jitted while-loop; `hooks_on_tok_s` measures the same
    decode with a host-side adjust callback registered every step (the
    registerBeamSearchControlCallbacks surface via pure_callback), so
    the host-hook tax is visible.

    `capture_dir` (or `bench.py ... --capture DIR`): after measuring,
    (a) re-runs one hooks-off decode inside jax.profiler.trace(DIR) —
    on TPU that XPlane capture is what tools/trace_attribution.py
    consumes for the on-device decode verdict (ROADMAP 5a) — and
    (b) writes the compiled decode program's HLO to
    DIR/nmt_beam4_decode.hlo.txt.gz for the backend-independent byte
    accounting. The row then carries `capture: DIR`."""
    import jax

    from paddle_tpu.beam_search import BeamHooks
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.models.text import (
        seq2seq_attention,
        seq2seq_attention_decoder,
    )
    from paddle_tpu.network import Network

    conf = seq2seq_attention(
        src_vocab=vocab, trg_vocab=vocab, emb_dim=emb, hidden=hidden
    )
    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    rng = np.random.default_rng(0)
    src = rng.integers(2, vocab, (bs, t_src)).astype(np.int32)
    lens = np.full((bs,), t_src, np.int32)
    enc_outs, _ = net.forward(
        params, {"src": id_arg(src, lens)},
        outputs=["enc", "dec_boot"],
    )
    statics = [enc_outs["enc"]]
    boots = {"dec_state": enc_outs["dec_boot"].value}

    def run_decoder(hooks):
        dec = seq2seq_attention_decoder(
            trg_vocab=vocab, emb_dim=emb, hidden=hidden, bos_id=0,
            eos_id=1, beam_size=beam, max_length=max_len,
        )
        dec.hooks = hooks or dec.hooks
        timeline = {"dispatch_s": 0.0, "device_s": 0.0}

        def once():
            t0 = time.perf_counter()
            seqs, ls, scores = dec.generate(
                params, statics=statics, boots=boots
            )
            t1 = time.perf_counter()
            np.asarray(ls)  # fetch any remaining unfetched outputs
            t2 = time.perf_counter()
            # generate() blocks internally on its measured-counter
            # fetches, so splitting the wall AROUND it attributed the
            # whole device run to dispatch (host_overhead_frac
            # ~0.9999 — ISSUE 19 satellite). Its own last_timeline
            # carries the submit-vs-block split; the trailing fetch
            # of already-computed outputs joins the device window.
            tl = dec.last_timeline
            timeline["dispatch_s"] += tl["dispatch_s"]
            timeline["device_s"] += tl["device_s"] + (t2 - t1)
            return ls

        once()  # compile + warm
        timeline["dispatch_s"] = timeline["device_s"] = 0.0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            once()
            best = min(best, time.perf_counter() - t0)
        return best, timeline, dec, once

    t_off, tl, dec_off, once_off = run_decoder(None)
    tok_s = bs * max_len / t_off
    out = {
        "value": round(tok_s, 0),
        "unit": "decode tokens/s (best beam, hooks off)",
        "beam": beam,
        "max_len": max_len,
        "batch_size": bs,
        "all_beams_tok_s": round(bs * beam * max_len / t_off, 0),
        **_timeline_fields(tl),
    }
    # chain-depth A/B (ISSUE 18): the row's gated
    # dispatch_chain_depth / chain_speedup triple comes from the
    # dispatch-bound probe, interleaved in-row. A failed probe leaves
    # an explicit skip reason the compare pass accepts — the fields
    # cannot silently drop from the record.
    try:
        out.update(_decode_chain_probe(beam=beam, max_len=max_len))
    except Exception as e:
        out["chain_ab_skipped"] = (
            f"chain probe failed: {type(e).__name__}: {e}"[:160]
        )
    capture_dir = capture_dir or _CAPTURE_DIR[0]
    if capture_dir:
        os.makedirs(capture_dir, exist_ok=True)
        from paddle_tpu.core import profiler

        try:
            with profiler.trace(capture_dir):
                once_off()
            write_decode_hlo(
                dec_off, params, statics, boots,
                os.path.join(capture_dir,
                             "nmt_beam4_decode.hlo.txt.gz"),
            )
            # K-token arms of the capture (ISSUE 18): the jitted
            # K=8 while-program and the host rung's donated 8-step
            # chunk program, both at the committed b32 config — the
            # audit_budgets.json entries pin their byte budgets (and
            # the chunk program's input_output_alias) against drift
            dec_k = seq2seq_attention_decoder(
                trg_vocab=vocab, emb_dim=emb, hidden=hidden,
                bos_id=0, eos_id=1, beam_size=beam,
                max_length=max_len, tokens_per_dispatch=8,
            )
            write_decode_hlo(
                dec_k, params, statics, boots,
                os.path.join(
                    capture_dir,
                    f"nmt_beam4_decode_b{bs}_k8.hlo.txt.gz",
                ),
            )
            write_chunk_hlo(
                dec_k, params, statics, boots, 8,
                os.path.join(
                    capture_dir,
                    f"nmt_beam4_decode_b{bs}_chunk8.hlo.txt.gz",
                ),
            )
            out["capture"] = capture_dir
        except Exception as e:
            out["capture_error"] = f"{type(e).__name__}: {e}"[:160]
    try:
        if os.environ.get("BENCH_DECODE_HOOKS_ARM", "1") == "0":
            # escape hatch for boxes where the pure_callback decode
            # wedges outright (observed on single-core CPU runners at
            # production vocab: the callback-bearing while program
            # never finishes its first run). The skip is recorded on
            # the row; hook correctness stays covered by
            # test_beam_search.TestHostHooks + tests/test_decoding.py.
            out["hooks_on"] = (
                "unavailable: skipped (BENCH_DECODE_HOOKS_ARM=0 — "
                "pure_callback decode wedges on this runner)"
            )
        else:
            t_on, _, _, _ = run_decoder(
                BeamHooks(adjust=lambda logp, t: logp)
            )
            out["hooks_on_tok_s"] = round(bs * max_len / t_on, 0)
            out["hooks_overhead_x"] = round(t_on / t_off, 2)
    except Exception as e:
        # the axon tunnel runtime does not support host callbacks
        # (pure_callback raises UNIMPLEMENTED); any OTHER failure is a
        # real hook regression and must surface as an error line.
        # Hook correctness is covered by test_beam_search.TestHostHooks.
        msg = str(e)
        if "UNIMPLEMENTED" not in msg:
            raise  # a real hook regression, not a runtime limitation
        out["hooks_on"] = f"unavailable: {msg}"[:120]
    return out


def bench_lm_train(bs=32, t=128, d=256, heads=4, layers=2,
                   vocab=2048):
    """Transformer-LM training north star (ISSUE 19): tokens/s on the
    decoder-only LM built from the existing layer inventory
    (models.lm.transformer_lm), with the analytic MFU — FLOPs derived
    from the model config via `lm_train_flops_per_batch` (the
    _nmt_train_flops_per_batch discipline, never a profiler) over the
    measured step time against peak. Plain per-step dispatch and the
    fused scan-of-steps program run as interleaved arms; the best arm
    is the row's value and `fused_speedup` records the ratio."""
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.models.lm import (
        LMSpec,
        lm_train_flops_per_batch,
        transformer_lm,
    )

    spec = LMSpec(vocab=vocab, d_model=d, num_heads=heads,
                  num_layers=layers)
    conf = transformer_lm(spec)
    rng = np.random.default_rng(0)
    ids = rng.integers(2, vocab, (bs, t)).astype(np.int32)
    lbl = rng.integers(2, vocab, (bs, t)).astype(np.int32)
    lens = np.full((bs,), t, np.int32)
    feed = {"ids": id_arg(ids, lens), "label": id_arg(lbl, lens)}
    warm_p, win_p = _build_arm(conf, feed, iters=10)
    warm_f, win_f = _build_arm_fused(conf, feed, inner=10)
    warm_p(10)
    warm_f(2)
    best = _interleaved_best({"plain": win_p, "fused": win_f},
                             rounds=3)
    ms = min(best.values())
    winner = "fused" if best["fused"] <= best["plain"] else "plain"
    tl = (win_f if winner == "fused" else win_p).timeline
    flops = lm_train_flops_per_batch(spec, bs, t)
    return {
        "value": round(bs * t / (ms / 1e3), 0),
        "unit": "LM train tokens/s (best interleaved arm)",
        "batch_size": bs,
        "seq_len": t,
        "d_model": d,
        "layers": layers,
        "vocab": vocab,
        "ms_per_step": round(ms, 3),
        "ms_plain": round(best["plain"], 3),
        "ms_fused": round(best["fused"], 3),
        "fused_speedup": round(best["plain"] / best["fused"], 2),
        "winner": winner,
        "analytic_flops_per_step": flops,
        "mfu": round(flops / (ms / 1e3) / TPU_PEAK_FLOPS, 6),
        **_timeline_fields(tl),
    }


def write_lm_prefill_hlo(plm, bs, bucket, path):
    """Compile (never run) the bucketed LM prefill program at the
    committed capture config and write HLO + report sibling — the
    audit pins: flash path (no [T,T] at T=1024), zero host transfers,
    and the donated pool buffers (cache-append aliasing)."""
    import gzip
    import json

    import jax.numpy as jnp

    spec = plm.spec
    ps = plm.cache.page_size
    pool_k, pool_v = plm.cache.ensure_pool()
    prog = plm._prefill_program(bs, bucket)
    n_pages = bucket // ps
    compiled = prog.lower(
        plm.params, pool_k, pool_v,
        jnp.zeros((bs, bucket), jnp.int32),
        jnp.full((bs,), bucket, jnp.int32),
        jnp.arange(bs * n_pages, dtype=jnp.int32).reshape(
            bs, n_pages
        ),
    ).compile()
    with gzip.open(path, "wt") as f:
        f.write(compiled.as_text())
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    report = {
        "model": "decoding.kv_cache prefill program (full causal "
                 "forward + page scatter + fused first top-k)",
        "attn_impl": spec.attn_impl,
        "batch_size": bs,
        "seq_len": bucket,
        "d_model": spec.d_model,
        "heads": spec.num_heads,
        "layers": spec.num_layers,
        "page_size": ps,
        "xla_flops": ca.get("flops", 0),
        "xla_bytes_accessed": ca.get("bytes accessed", 0),
        # the donation audit's contract: the two pool buffers (K, V)
        # must appear in input_output_alias — the cache append is
        # in place, not a copy
        "donated_arg_buffers": 2,
    }
    with open(path.replace(".hlo.txt.gz", ".report.json"), "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")


def write_lm_decode_hlo(plm, bs, path):
    """Compile the fused per-token decode program (gather pages ->
    1-token forward -> in-place append -> argmax+score) and write
    HLO + report — the single-dispatch-per-token program that retires
    ROADMAP residual 2(c)."""
    import gzip
    import json

    import jax.numpy as jnp

    spec = plm.spec
    maxp = plm.cache.max_pages_per_seq
    ps = plm.cache.page_size
    pool_k, pool_v = plm.cache.ensure_pool()
    prog = plm._decode_program(bs)
    compiled = prog.lower(
        plm.params, pool_k, pool_v,
        jnp.zeros((bs,), jnp.int32),
        jnp.full((bs,), ps, jnp.int32),
        jnp.zeros((bs, maxp), jnp.int32),
        jnp.zeros((bs,), jnp.float32),
        jnp.zeros((bs,), bool),
    ).compile()
    with gzip.open(path, "wt") as f:
        f.write(compiled.as_text())
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    report = {
        "model": "decoding.kv_cache fused decode step (forward + "
                 "top-k + cache append + score update, one dispatch)",
        "batch_size": bs,
        "context_len": maxp * ps,
        "d_model": spec.d_model,
        "heads": spec.num_heads,
        "layers": spec.num_layers,
        "page_size": ps,
        "xla_flops": ca.get("flops", 0),
        "xla_bytes_accessed": ca.get("bytes accessed", 0),
        "donated_arg_buffers": 2,
    }
    with open(path.replace(".hlo.txt.gz", ".report.json"), "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")


def write_lm_captures(out_dir):
    """The two committed LM generation captures (ISSUE 19) at their
    audited configs: the T=1024 flash prefill and the b=4 fused
    decode step over a 1024-slot page context. Compile-only, so the
    writer runs on CPU; tools/profile_lm.py is the standalone CLI."""
    import jax

    from paddle_tpu.decoding.kv_cache import PagedKVCache, PagedLM
    from paddle_tpu.models.lm import LMSpec, lm_init_params

    spec = LMSpec(vocab=2048, d_model=256, num_heads=4, num_layers=2,
                  attn_impl="flash")
    params = lm_init_params(spec, jax.random.key(0))
    cache = PagedKVCache(spec, num_pages=256, page_size=16,
                         max_pages_per_seq=64)
    plm = PagedLM(spec, params, cache)
    p1 = os.path.join(out_dir, "lm_prefill_t1024_flash.hlo.txt.gz")
    write_lm_prefill_hlo(plm, 4, 1024, p1)
    p2 = os.path.join(out_dir, "lm_decode_b4.hlo.txt.gz")
    write_lm_decode_hlo(plm, 4, p2)
    return [p1, p2]


def bench_lm_decode(bs=4, t0=128, max_new=32, d=128, heads=4,
                    layers=2, vocab=512, capture_dir=None):
    """Paged KV-cache decode north star (ISSUE 19): greedy generation
    through the page pool — one bucketed prefill dispatch + one fused
    decode dispatch per token — against the full-prefix-recompute
    decode the PR12 verdict condemned, as interleaved arms
    (`cache_speedup`; the paths are pinned token-for-token equal by
    tests/test_lm_kv_cache.py, so this is a pure perf A/B).

    The cache story is MEASURED, not assumed: `cache_hit_frac` and
    `prefix_recompute_bytes_saved` come from the pool's own counters,
    and the eviction sweep (`points`) drives the continuous-batching
    engine at rising eviction pressure — every eviction forces a
    re-prefill, the hit fraction falls, and decode tokens/s must fall
    with it (tools/check_bench_record.py enforces the scaling)."""
    import jax

    from paddle_tpu.decoding.kv_cache import PagedKVCache, PagedLM
    from paddle_tpu.models.lm import (
        LMSpec,
        greedy_decode_recompute,
        lm_init_params,
    )
    from paddle_tpu.serving.lm_engine import LMEngine

    spec = LMSpec(vocab=vocab, d_model=d, num_heads=heads,
                  num_layers=layers)
    params = lm_init_params(spec, jax.random.key(0))
    rng = np.random.default_rng(0)
    ids = rng.integers(2, vocab, (bs, t0)).astype(np.int32)
    lens = np.full((bs,), t0, np.int32)
    cache = PagedKVCache(spec, num_pages=96, page_size=16,
                         max_pages_per_seq=16)
    plm = PagedLM(spec, params, cache, eos_id=1)

    def paged_window():
        t_a = time.perf_counter()
        plm.generate(ids, lens, max_new)
        return (time.perf_counter() - t_a) * 1e3

    def recompute_window():
        t_a = time.perf_counter()
        greedy_decode_recompute(spec, params, ids, lens, max_new, 1)
        return (time.perf_counter() - t_a) * 1e3

    out = {
        "unit": "paged greedy decode tokens/s",
        "batch_size": bs,
        "prompt_len": t0,
        "max_new": max_new,
        "d_model": d,
        "vocab": vocab,
    }
    try:
        paged_window()  # compile + warm both arms
        recompute_window()
        best = _interleaved_best(
            {"paged": paged_window, "recompute": recompute_window},
            rounds=3,
        )
        out.update({
            "value": round(bs * max_new / (best["paged"] / 1e3), 1),
            "ms_paged": round(best["paged"], 2),
            "ms_recompute": round(best["recompute"], 2),
            "cache_speedup": round(
                best["recompute"] / best["paged"], 2
            ),
            # dispatch-chain depth is COUNTED in the running chain
            # (the ISSUE 18 rule), never derived from config
            "dispatch_chain_depth": plm.last_chain_depth,
            **_timeline_fields(plm.last_timeline),
        })
    except Exception as e:
        out["cache_ab_skipped"] = (
            f"paged/recompute A/B failed: "
            f"{type(e).__name__}: {e}"[:160]
        )
        return out

    def engine_point(evict_every):
        """One continuous-batching run at a fixed eviction cadence;
        returns the point's measured counters + throughput."""
        for f in ("appended_tokens", "prefilled_tokens",
                  "cached_prefix_tokens", "evictions"):
            setattr(cache, f, 0)
        eng = LMEngine(plm, slots=bs, max_new=max_new)
        t_a = time.perf_counter()
        for i in range(bs):
            eng.submit(ids[i, :t0])
        steps = 0
        while eng.step():
            steps += 1
            if evict_every and steps % evict_every == 0:
                live = [r for r in eng.slots if r is not None]
                if live:
                    eng.evict(live[0], requeue=True)
                    eng.fill_slots()
        wall = time.perf_counter() - t_a
        total = sum(len(s.out) for s in eng.seqs.values())
        point = {
            "evict_every": evict_every,
            "tok_s": round(total / wall, 1),
            "cache_hit_frac": round(eng.cache_hit_frac, 4),
            "prefix_recompute_bytes_saved":
                int(eng.prefix_recompute_bytes_saved),
            "evictions": cache.evictions,
            "reprefilled_tokens": eng.reprefilled_tokens,
        }
        cache.free(eng._scratch)  # release the engine's scratch page
        return point

    try:
        sweep = (0, 8, 4)
        for e in sweep:  # warm pass compiles the b=1 prefill buckets
            engine_point(e)
        points = []
        for e in sweep:  # measured pass, all programs warm
            a, b = engine_point(e), engine_point(e)
            points.append(a if a["tok_s"] >= b["tok_s"] else b)
        headline = points[0]  # the no-eviction point
        out.update({
            "cache_hit_frac": headline["cache_hit_frac"],
            "prefix_recompute_bytes_saved":
                headline["prefix_recompute_bytes_saved"],
            "points": points,
        })
    except Exception as e:
        # the A/B already succeeded; record the sweep failure without
        # faking the (now missing) measured-counter fields
        out.pop("cache_speedup", None)
        out["cache_ab_skipped"] = (
            f"eviction sweep failed: {type(e).__name__}: {e}"[:160]
        )
        return out
    capture_dir = capture_dir or _CAPTURE_DIR[0]
    if capture_dir:
        os.makedirs(capture_dir, exist_ok=True)
        try:
            write_lm_captures(capture_dir)
            out["capture"] = capture_dir
        except Exception as e:
            out["capture_error"] = f"{type(e).__name__}: {e}"[:160]
    return out


def bench_serve_loadtest(vocab=2048, beam=4, max_len=16,
                         duration_s=None):
    """Offered-load sweep against the continuous-batching inference
    server (paddle_tpu/serving): a capacity probe fixes the saturation
    request rate, then open-loop arrival streams at 0.5x / 1x / 2x
    capacity measure per-point p50/p99 latency, shed fraction, and
    goodput — the serving analogue of the training MFU rows. The
    server's SLO machinery (bounded queue, deadline-aware batch
    formation, explicit shedding) is IN the loop: the 2x point is
    *supposed* to shed, and its p99-over-admitted staying near the
    deadline while goodput holds is the robustness headline.
    `value` = saturation goodput (decoded best-beam tokens/s).
    BENCH_SERVE_SECONDS shrinks the per-point window (CPU smoke)."""
    import threading

    from paddle_tpu import dsl
    from paddle_tpu.beam_search import BeamSearchDecoder
    from paddle_tpu.core.config import ParameterConf
    from paddle_tpu.serving.models import GenerationModel
    from paddle_tpu.serving.server import (
        InferenceServer,
        ServeConfig,
        ServeError,
        ServeRejected,
    )

    import itertools

    duration = (
        duration_s
        if duration_s is not None
        else float(os.environ.get("BENCH_SERVE_SECONDS", "4"))
    )
    deadline_s = 2.0

    def step(word):
        emb = dsl.embedding(
            word, size=vocab, vocab_size=vocab,
            param=ParameterConf(name="serve_bigram"),
        )
        return dsl.mixed(vocab, [(emb, "identity")], act="softmax",
                         bias=False, name="prob")

    from paddle_tpu.core import flags as _fl
    from paddle_tpu.obs import flight_recorder as _fr
    from paddle_tpu.obs import metrics as _om

    # span-derived critical path (ISSUE 11): trace EVERY request for
    # the row's window (trace_serve_period=1) into a ring-only flight
    # recorder, then derive the queued / batch-wait / device split
    # from the spans — cross-checked by the check_bench_record lint
    # against the registry-derived triple below, so the two
    # measurement pipes watch each other
    prev_trace_period = _fl.get_flag("trace_serve_period")
    _fl.set_flag("trace_serve_period", 1)
    _span_rec = _fr.enable_flight_recorder(capacity=1 << 16)
    try:

        # the serving stack publishes queue depth / occupancy / request
        # time attribution into the process registry — the row READS them
        # (delta over this row's window) instead of recomputing its own
        reg = _om.get_registry()
        # counters are delta-corrected against `base` below; the HWM gauge
        # only ever ratchets up, so an earlier server in this process
        # would leak its peak into this row — start it fresh
        reg.gauge("serving.queue_depth_hwm").reset()
        base = {
            "batches": reg.counter("serving.batches").get(model="gen"),
            "batch_requests": reg.counter(
                "serving.batch_requests").get(model="gen"),
            "latency": reg.counter("serving.request_latency_s").get(),
            "queue_wait": reg.counter(
                "serving.request_queue_wait_s").get(),
            "dispatch": reg.counter("serving.request_dispatch_s").get(),
        }

        dec = BeamSearchDecoder(step, n_static=0, bos_id=0, eos_id=1,
                                beam_size=beam, max_length=max_len)
        rng = np.random.default_rng(0)
        table = rng.standard_normal((vocab, vocab)).astype(np.float32)
        import jax.numpy as jnp

        params = {"serve_bigram": jnp.asarray(table)}
        model = GenerationModel(dec, params)
        cfg = ServeConfig(max_queue=64, max_batch=8,
                          default_deadline_s=deadline_s,
                          buckets=(16, 32, 64))
        server = InferenceServer(cfg)
        server.add_model("gen", model)

        # pre-generated request pool: np.random.Generator is not
        # thread-safe, and 16 closed-loop threads draw concurrently
        _pool = [
            rng.integers(2, vocab,
                         (int(rng.integers(4, 17)),)).astype(np.int32)
            for _ in range(256)
        ]
        _pool_i = itertools.count()

        def req_ids():
            return _pool[next(_pool_i) % len(_pool)]

        # warm every batch-bucket program so the sweep measures serving,
        # not first-compile
        bb = 1
        while bb <= cfg.max_batch:
            pend = [server.submit("gen", req_ids(), deadline_s=600.0)
                    for _ in range(bb)]
            for p in pend:
                p.result(timeout=600)
            bb *= 2

        # capacity probe: closed loop, 2x max_batch concurrent clients
        done_tok = [0]
        done_n = [0]
        stop = threading.Event()
        lock = threading.Lock()

        probe_errors = [0]

        def closed_loop():
            while not stop.is_set():
                try:
                    r = server.submit("gen", req_ids(),
                                      deadline_s=deadline_s)
                    out = r.result(timeout=60)
                except (ServeRejected, TimeoutError):
                    continue
                except ServeError:
                    # a transient dispatch failure must not silently kill
                    # the probe thread and deflate measured capacity
                    with lock:
                        probe_errors[0] += 1
                    continue
                with lock:
                    done_tok[0] += len(out["tokens"])
                    done_n[0] += 1

        workers = [threading.Thread(target=closed_loop, daemon=True)
                   for _ in range(2 * cfg.max_batch)]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        time.sleep(duration)
        stop.set()
        for w in workers:
            w.join(timeout=30)
        probe_s = time.perf_counter() - t0
        cap_rps = max(done_n[0] / probe_s, 1.0)
        cap_tok_s = done_tok[0] / probe_s

        points = []
        for mult in (0.5, 1.0, 2.0):
            rate = cap_rps * mult
            spacing = 1.0 / rate
            reqs, shed = [], 0
            t0 = time.perf_counter()
            nxt = t0
            while (now := time.perf_counter()) - t0 < duration:
                if now < nxt:
                    time.sleep(min(nxt - now, 0.005))
                    continue
                nxt += spacing
                try:
                    reqs.append(server.submit("gen", req_ids(),
                                              deadline_s=deadline_s))
                except ServeRejected:
                    shed += 1
            # drain this point's tail before measuring
            deadline = time.monotonic() + deadline_s + 10
            while time.monotonic() < deadline and any(
                r.state == "pending" for r in reqs
            ):
                time.sleep(0.01)
            lat = sorted(r.latency_s for r in reqs if r.state == "done")
            n_done = len(lat)
            n_deadline = sum(r.state == "rejected:deadline" for r in reqs)
            tok = sum(len(r._result["tokens"]) for r in reqs
                      if r.state == "done")
            offered = len(reqs) + shed
            points.append({
                "offered_rps": round(offered / duration, 1),
                "target_x_capacity": mult,
                "completed": n_done,
                "shed_overload": shed,
                "shed_deadline": n_deadline,
                "shed_frac": round((shed + n_deadline) / max(offered, 1), 3),
                "p50_ms": round(lat[n_done // 2] * 1e3, 1) if lat else None,
                "p99_ms": round(lat[int(0.99 * (n_done - 1))] * 1e3, 1)
                if lat else None,
                "goodput_tok_s": round(tok / duration, 1),
            })
        server.shutdown(drain=True)
        sat = max((p["goodput_tok_s"] for p in points), default=0.0)
        # span-derived critical-path split over the whole window: the
        # per-request span trees the scheduler stamped (serve.request over
        # queued / batch_form / dispatch) summed by phase, as fractions of
        # the completed requests' total span time
        span_events = _span_rec.spans()
    finally:
        # restore even when the row errors mid-sweep: a
        # leaked trace_serve_period=1 + attached ring would
        # skew every later row in this process
        _fr.disable_flight_recorder()
        _fl.set_flag("trace_serve_period", prev_trace_period)
    roots_ok = [s for s in span_events
                if s["name"] == "serve.request"
                and s["status"] == "ok"]
    span_total = sum(s["dur_s"] for s in roots_ok)
    # phase sums restricted to children of OK roots: an errored
    # dispatch's children would inflate the numerators while its
    # root is excluded from span_total
    ok_root_ids = {s["span_id"] for s in roots_ok}
    phase = {"serve.queued": 0.0, "serve.batch_form": 0.0,
             "serve.dispatch": 0.0}
    for s in span_events:
        if s["name"] in phase and s["parent_id"] in ok_root_ids:
            phase[s["name"]] += s["dur_s"]
    # registry-sourced serving telemetry (ISSUE 10): queue-depth
    # high-water mark and mean batch occupancy come from the obs
    # registry the server maintains, and the admitted-request time
    # split (queued vs executing vs scheduling) gives this row the
    # same three timeline fields as the training north stars —
    # data_wait = queue wait, device = program execution
    n_batches = reg.counter("serving.batches").get(model="gen") \
        - base["batches"]
    n_breqs = reg.counter("serving.batch_requests").get(model="gen") \
        - base["batch_requests"]
    lat_s = reg.counter("serving.request_latency_s").get() \
        - base["latency"]
    wait_s = reg.counter("serving.request_queue_wait_s").get() \
        - base["queue_wait"]
    disp_s = reg.counter("serving.request_dispatch_s").get() \
        - base["dispatch"]
    return {
        "value": sat,
        "unit": "decode tokens/s goodput at saturation (best beam)",
        "capacity_rps": round(cap_rps, 1),
        "capacity_tok_s": round(cap_tok_s, 1),
        "points": points,
        "deadline_ms": deadline_s * 1e3,
        "queue_bound": cfg.max_queue,
        "max_batch": cfg.max_batch,
        "beam": beam,
        "max_len": max_len,
        "window_s": duration,
        "max_queue_depth": int(
            reg.gauge("serving.queue_depth_hwm").get(default=0)
        ),
        "mean_batch_occupancy": round(n_breqs / n_batches, 2)
        if n_batches else None,
        "data_wait_frac": round(wait_s / lat_s, 4) if lat_s else 0.0,
        "device_frac": round(disp_s / lat_s, 4) if lat_s else 0.0,
        "host_overhead_frac": round(
            max(1.0 - (wait_s + disp_s) / lat_s, 0.0), 4
        ) if lat_s else 0.0,
        "span_queued_frac": round(
            phase["serve.queued"] / span_total, 4
        ) if span_total else 0.0,
        "span_batch_wait_frac": round(
            phase["serve.batch_form"] / span_total, 4
        ) if span_total else 0.0,
        "span_device_frac": round(
            phase["serve.dispatch"] / span_total, 4
        ) if span_total else 0.0,
        "span_requests": len(roots_ok),
        "probe_errors": probe_errors[0],
    }


def bench_serve_fleet_loadtest(window_s=None):
    """Fleet-tier robustness row (ISSUE 16): sweep replica count
    (1/2/3 toy replicas behind a FleetRouter) under sustained
    closed-loop load, then SIGKILL one replica mid-window at the
    widest point and measure through the fault: aggregate goodput,
    p99, and — the headline — `admitted_lost`, which MUST be 0 (a
    request the router admitted is spilled to a sibling or completed,
    never dropped; an explicit `overloaded` shed is a refusal, not a
    loss). The killed replica is then restarted booting from the
    verified AOT cache and must rejoin rotation through the breaker's
    half-open probe. `value` = kill-phase goodput (req/s) — the rate
    the fleet sustains WHILE a replica is dying and rejoining.
    BENCH_FLEET_SECONDS shrinks the per-point window (CPU smoke)."""
    import tempfile
    import threading

    from paddle_tpu import inference
    from paddle_tpu import testing_faults as tf
    from paddle_tpu.serving.fleet import FleetConfig, FleetRouter

    repo = os.path.dirname(os.path.abspath(__file__))
    window = (
        window_s
        if window_s is not None
        else float(os.environ.get("BENCH_FLEET_SECONDS", "3"))
    )
    n_max = 3
    n_clients = 8

    # the cache the killed replica will boot from (small program:
    # this row measures the fleet, the coldstart row measures boot)
    cache_dir = tempfile.mkdtemp(prefix="fleet-cache-")
    fn = tf.replica_program_fn(4, 32)
    inference.store_verified(cache_dir, "fleet",
                             fn, (np.zeros((1, 8), np.float32),))

    procs = {}
    addrs = {}
    for i in range(n_max):
        p, port = tf.start_serving_replica(
            repo, REPLICA_MODE="toy", TOY_DELAY_S=0.002,
            MODEL_TAG="v1", MAX_QUEUE=64)
        if port is None:
            raise RuntimeError(f"replica r{i} failed to boot: "
                               f"{p.boot_line}")
        procs[f"r{i}"] = p
        addrs[f"r{i}"] = f"127.0.0.1:{port}"

    def run_point(router, secs, on_half=None):
        lock = threading.Lock()
        stop = threading.Event()
        lat, shed, lost = [], [0], [0]

        def loop():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    r = router.call("m", [1, 2, 3], deadline_ms=5000,
                                    trace=False)
                except Exception:
                    with lock:
                        lost[0] += 1
                    continue
                if r.get("ok"):
                    with lock:
                        lat.append(time.perf_counter() - t0)
                elif r.get("error") == "overloaded":
                    with lock:
                        shed[0] += 1
                else:
                    with lock:
                        lost[0] += 1

        workers = [threading.Thread(target=loop, daemon=True)
                   for _ in range(n_clients)]
        t0 = time.perf_counter()
        for w in workers:
            w.start()
        if on_half is not None:
            time.sleep(secs / 2)
            on_half()
            time.sleep(secs / 2)
        else:
            time.sleep(secs)
        stop.set()
        for w in workers:
            w.join(timeout=30)
        span = time.perf_counter() - t0
        lat.sort()
        n = len(lat)
        return {
            "completed": n,
            "goodput_rps": round(n / span, 1),
            "p50_ms": round(lat[n // 2] * 1e3, 2) if n else None,
            "p99_ms": round(lat[int(0.99 * (n - 1))] * 1e3, 2)
            if n else None,
            "shed": shed[0],
            "admitted_lost": lost[0],
        }

    try:
        fcfg = FleetConfig(poll_interval_s=0.05, breaker_reset_s=0.4)
        points = []
        for n in range(1, n_max):
            sub = {k: addrs[k] for k in list(addrs)[:n]}
            with FleetRouter(sub, fcfg) as router:
                time.sleep(0.15)  # first telemetry scrape
                pt = run_point(router, window)
                pt["replicas"] = n
                points.append(pt)

        # widest point: SIGKILL r1 mid-window, keep measuring
        router = FleetRouter(dict(addrs), fcfg)
        try:
            time.sleep(0.15)
            victim = "r1"
            rotated = [None]

            def kill_victim():
                tf.kill_process(procs[victim])
                deadline = time.monotonic() + fcfg.breaker_reset_s * 4
                while time.monotonic() < deadline:
                    if router.states()[victim]["breaker"] != "closed":
                        rotated[0] = True
                        return
                    time.sleep(0.01)
                rotated[0] = False

            kill = run_point(router, window, on_half=kill_victim)
            kill["replicas"] = n_max
            kill["rotated_out"] = rotated[0]

            # restart the victim from the verified cache; it must
            # rejoin rotation via the half-open probe
            p, port = tf.start_serving_replica(
                repo, REPLICA_MODE="cache", CACHE_DIR=cache_dir,
                CACHE_KEY="fleet", MODEL_TAG="v2")
            if port is None:
                raise RuntimeError(f"cache reboot refused: "
                                   f"{p.boot_line}")
            procs[victim] = p
            addrs[victim] = f"127.0.0.1:{port}"
            router.set_address(victim, f"127.0.0.1:{port}")
            deadline = time.monotonic() + 10
            rejoined = False
            while time.monotonic() < deadline:
                if router.states()[victim]["breaker"] == "closed":
                    rejoined = True
                    break
                time.sleep(0.02)
            kill["rejoined"] = rejoined
            kill["rejoin_boot"] = "verified-cache"
            points.append(kill)

            # fleet-aggregated observability fields (ISSUE 17): scrape
            # every replica's registry over metricz, merge the
            # admitted-latency histograms bucket-wise, and quote the
            # fleet p99 from the MERGED buckets — cross-checked (by
            # check_bench_record's compare rule) against the router's
            # own end-to-end timing of the same admitted requests
            from paddle_tpu.obs import aggregate as obs_agg
            from paddle_tpu.obs import metrics as obs_metrics
            from paddle_tpu.serving.tcp import ServeClient

            snaps = {}
            bench_scrape_failures = 0
            for name, addr in addrs.items():
                try:
                    c = ServeClient(addr, retries=0, admin_timeout=2.0)
                    resp = c.metricz()
                    c.close()
                    snaps[name] = resp.get("metricz", {})
                except Exception:
                    bench_scrape_failures += 1
            merged = obs_agg.merge_snapshots(snaps)
            fleet_hist = obs_agg.family_histogram(
                merged["histograms"], "serving.admitted_latency_s")
            fleet_p99 = obs_agg.quantile(fleet_hist, 0.99)
            local = obs_metrics.get_registry().snapshot()
            router_hist = obs_agg.family_histogram(
                local["histograms"], "fleet.request_latency_s")
            router_p99 = obs_agg.quantile(router_hist, 0.99)
            fleet_agg = {
                "fleet_p99_ms": round(fleet_p99 * 1e3, 3)
                if fleet_p99 is not None else None,
                "router_p99_ms": round(router_p99 * 1e3, 3)
                if router_p99 is not None else None,
                "fleet_alerts": int(obs_agg.family_total(
                    local["counters"], "fleet.alerts")),
                "fleet_scrape_errors": int(obs_agg.family_total(
                    local["counters"], "fleet.scrape_errors"))
                + bench_scrape_failures,
            }
        finally:
            router.close()
    finally:
        for p in procs.values():
            tf.kill_process(p)
        shutil.rmtree(cache_dir, ignore_errors=True)

    total_lost = sum(pt["admitted_lost"] for pt in points)
    row = {
        "value": kill["goodput_rps"],
        "unit": "fleet goodput req/s through a replica SIGKILL",
        "points": points,
        "kill": {k: kill[k] for k in
                 ("goodput_rps", "p99_ms", "admitted_lost",
                  "rotated_out", "rejoined", "rejoin_boot")},
        "admitted_lost": total_lost,
        "replica_sweep": [pt["replicas"] for pt in points],
        "window_s": window,
        "clients": n_clients,
    }
    row.update(fleet_agg)
    return row


def bench_serve_coldstart(layers=None, d=256):
    """Verified-AOT-cache cold-start row (ISSUE 16): boot the same
    serving replica twice — once compiling its program from scratch,
    once deserializing it from the digest-pinned, hlo_audit-gated
    cache — and record both wall times, process start to model ready
    (interpreter + jax import included in BOTH, so the delta is the
    compile the cache removes). `value` = compile_boot_s /
    cache_boot_s. PR11 context: the stock persistent compilation
    cache deserializes corrupt executables on this runtime, so the
    fast path only counts because the envelope digest + HLO audit
    gate runs before anything executes. BENCH_COLDSTART_LAYERS
    shrinks the program (CPU smoke)."""
    import tempfile

    from paddle_tpu import inference
    from paddle_tpu import testing_faults as tf

    repo = os.path.dirname(os.path.abspath(__file__))
    layers = (
        layers
        if layers is not None
        else int(os.environ.get("BENCH_COLDSTART_LAYERS", "48"))
    )
    cache_dir = tempfile.mkdtemp(prefix="coldstart-cache-")
    fn = tf.replica_program_fn(layers, d)
    t0 = time.perf_counter()
    inference.store_verified(cache_dir, "cold",
                             fn, (np.zeros((1, 8), np.float32),))
    store_s = time.perf_counter() - t0

    def boot(mode, **env):
        p, port = tf.start_serving_replica(
            repo, REPLICA_MODE=mode, FN_LAYERS=layers, FN_DIM=d,
            **env)
        try:
            if port is None:
                raise RuntimeError(f"{mode} boot refused: "
                                   f"{p.boot_line}")
            from paddle_tpu.serving.tcp import ServeClient
            with ServeClient(f"127.0.0.1:{port}") as c:
                out = c.call("m", [1, 2, 3], deadline_ms=30000,
                             timeout=60)
            if not out.get("ok"):
                raise RuntimeError(f"{mode} boot served junk: {out}")
            return tf.replica_boot_seconds(p)
        finally:
            tf.kill_process(p)

    try:
        compile_boot_s = boot("compile")
        cache_boot_s = boot("cache", CACHE_DIR=cache_dir,
                            CACHE_KEY="cold")
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    return {
        "value": round(compile_boot_s / cache_boot_s, 2),
        "unit": "cold-start speedup: compile boot / verified-cache "
                "boot",
        "cache_boot_s": round(cache_boot_s, 3),
        "compile_boot_s": round(compile_boot_s, 3),
        "store_s": round(store_s, 3),
        "layers": layers,
        "d": d,
        "verified": "sha256 envelope + hlo_audit gate before execute",
    }


def build_sweep():
    # North stars FIRST (VERDICT r4 item 1): the authoritative record
    # must contain the headline rows even if the capture window ends
    # before the matrix tail.
    sweep = [
        ("resnet50_train_imgs_per_s", bench_resnet50),
        ("nmt_attention_train_tokens_per_s", bench_nmt),
        ("nmt_attention_train_tokens_per_s_bs512",
         lambda: bench_nmt(bs=512)),
        ("nmt_attention_train_tokens_per_s_t128",
         lambda: bench_nmt(bs=64, t=128, flash_ab=True)),
        ("nmt_beam4_decode_tokens_per_s", bench_beam_decode),
        ("lm_train_tokens_per_s", bench_lm_train),
        ("lm_decode_paged_tokens_per_s", bench_lm_decode),
        ("serve_loadtest", bench_serve_loadtest),
        ("serve_fleet_loadtest", bench_serve_fleet_loadtest),
        ("serve_coldstart", bench_serve_coldstart),
        ("ctr_sparse_step_v_independence", bench_sparse_ctr),
        ("ctr_widedeep_sparse_v_independence",
         bench_ctr_widedeep_sparse),
        ("lstm_train_fused_speedup_vs_scan", bench_lstm_fused_vs_scan),
        ("longctx_selfattn_train_tokens_per_s_t4096", bench_longctx),
        ("longctx_selfattn_train_tokens_per_s_t8192",
         lambda: bench_longctx(bs=1, t=8192)),
    ]
    for bs in (64, 128, 256, 512):
        sweep.append(
            (f"alexnet_bs{bs}", lambda bs=bs: bench_image("alexnet", bs))
        )
    for bs in (64, 128, 256):
        sweep.append(
            (f"googlenet_bs{bs}", lambda bs=bs: bench_image("googlenet", bs))
        )
    for bs in (64, 128, 256, 512):
        sweep.append(
            (f"smallnet_bs{bs}", lambda bs=bs: bench_image("smallnet", bs))
        )
    for bs in (64, 128, 256):
        for h in (256, 512, 1280):
            sweep.append(
                (f"lstm_bs{bs}_h{h}", lambda bs=bs, h=h: bench_lstm(bs, h))
            )
    return sweep


def _annotate_baseline(line, name):
    base = BASELINES_MS.get(name)
    if base is not None:
        line["vs_baseline"] = round(base / line["value"], 2)
        line["baseline_ms"] = base
    elif name.startswith("resnet50"):
        line["vs_baseline"] = round(line["value"] / R1_RESNET_IMG_S, 2)
        line["baseline"] = "round-1 measured 1976 img/s/chip"
    elif name.startswith("nmt_beam4"):
        line["vs_baseline"] = 1.0
        line["baseline"] = "no published reference decode rate"
    elif name == "serve_loadtest":
        line["vs_baseline"] = 1.0
        line["baseline"] = (
            "first measured round (r6): serving tracked like "
            "training MFU from here"
        )
    elif name in ("serve_fleet_loadtest", "serve_coldstart"):
        line["vs_baseline"] = 1.0
        line["baseline"] = (
            "first measured round (r7): fleet robustness and "
            "verified-cache cold start tracked from here"
        )
    elif name.startswith("lm_"):
        line["vs_baseline"] = 1.0
        line["baseline"] = (
            "first measured round (r8): Transformer-LM train MFU and "
            "paged-KV decode tracked from here"
        )
    elif name == "nmt_attention_train_tokens_per_s":
        line["vs_baseline"] = round(line["value"] / R1_NMT_TOK_S, 2)
        line["baseline"] = "round-1 measured 90k tok/s/chip"
    elif name == "nmt_attention_train_tokens_per_s_bs512":
        line["vs_baseline"] = round(line["value"] / R1_NMT_TOK_S, 2)
        line["baseline"] = (
            "round-1 measured 90k tok/s/chip (bs=512 bucket: the "
            "measured batch lever, PERF.md round 5)"
        )
    elif name.startswith("nmt_attention_train"):
        line["vs_baseline"] = 1.0
        line["baseline"] = "T=128 bucket (round-4 row)"
    elif name.startswith("ctr_sparse") or name.startswith("ctr_widedeep"):
        line["vs_baseline"] = round(4.0 / max(line["value"], 1e-9), 2)
        line["baseline"] = "O(V) dense update would be ~4.0"
    elif name.startswith("longctx_"):
        line["vs_baseline"] = 1.0
        line["baseline"] = (
            "no reference capability (2017: no long-context "
            "attention; SURVEY §5)"
        )


def main(argv):
    # parse --capture BEFORE the --multichip dispatch: it must never
    # leak through as a row-filter pattern
    if "--capture" in argv:
        i = argv.index("--capture")
        if i + 1 >= len(argv):
            print("bench.py: --capture needs a directory argument",
                  file=sys.stderr)
            return 2
        _CAPTURE_DIR[0] = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if "--multichip" in argv:
        from bench_multichip import mc_main

        return mc_main([a for a in argv if a != "--multichip"])
    pattern = argv[1] if len(argv) > 1 else ""
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "2400"))
    _setup()
    t_start = time.monotonic()
    health = rtt_ms = None
    floor_ms = None
    try:
        probe = chip_health_probe()
        if probe is not None:
            health, rtt_ms = probe
        floor_ms = dispatch_floor_probe()
    except Exception as e:
        emit({
            "metric": "chip_health",
            "error": f"{type(e).__name__}: {e}"[:200],
        })
    else:
        emit({
            "metric": "chip_health",
            "value": None if health is None else round(health, 1),
            "unit": "TFLOP/s (latency-cancelled chained bf16 matmul)",
            "tunnel_rtt_ms": None if rtt_ms is None else round(rtt_ms, 1),
            "dispatch_floor_ms": (
                None if floor_ms is None else round(floor_ms, 2)
            ),
            "healthy_threshold": HEALTHY_TFLOPS,
            "note": "None = not on TPU",
        })
    throttled = health is not None and health < HEALTHY_TFLOPS
    failures = 0
    north = {}
    skipped = []
    for name, fn in build_sweep():
        if pattern and pattern not in name:
            continue
        elapsed = time.monotonic() - t_start
        if elapsed > budget_s:
            skipped.append(name)
            emit({
                "metric": name, "skipped": "budget",
                "elapsed_s": round(elapsed, 1),
                "budget_s": budget_s,
            })
            continue
        line = {"metric": name}
        try:
            line.update(fn())
            _annotate_baseline(line, name)
        except Exception as e:  # keep sweeping; record the failure
            failures += 1
            line["error"] = f"{type(e).__name__}: {e}"[:300]
            line["value"] = None
            line["vs_baseline"] = 0.0
        if health is not None:
            line["health_tflops"] = round(health, 1)
            if throttled:
                # absolute times unreliable; only interleaved A/B
                # ratio fields (fused_speedup etc.) stay trustworthy
                line["throttled"] = True
        emit(line)
        if name in NORTH_STARS:
            north[name] = {
                "value": line.get("value"),
                "vs_baseline": line.get("vs_baseline"),
            }
            # keep the interleaved A/B ratios in the trailer too: on a
            # throttled capture they are the ONLY trustworthy numbers,
            # and the trailer is what a bounded tail surely keeps
            for k in ("fused_speedup", "mfu", "cache_speedup"):
                if k in line:
                    north[name][k] = line[k]
            if "error" in line:
                north[name]["error"] = line["error"][:80]
    # Compact trailer: repeats the headline so a bounded tail capture
    # still records it even after the full matrix has printed.
    emit({
        "metric": "summary",
        "north_stars": north,
        "health_tflops": None if health is None else round(health, 1),
        "throttled": throttled,
        "rows_skipped_budget": skipped,
        "failures": failures,
        "elapsed_s": round(time.monotonic() - t_start, 1),
    })
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
