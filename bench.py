"""Benchmark harness — prints ONE JSON line.

Headline: AlexNet ms/batch at bs=128, the reference's published number
(benchmark/README.md:37: 334 ms/batch on 1×K40m, `paddle train --job=time`
harness, see BASELINE.md). vs_baseline = reference_ms / our_ms (speedup
factor; >1 means faster than the published reference).
"""

import json
import sys
import time

import numpy as np

BASELINE_ALEXNET_BS128_MS = 334.0


def main():
    import jax

    from paddle_tpu.core import flags as _flags

    # mixed precision: float32 master params, bfloat16 compute
    # (paddle_tpu/network.py AMP policy) — the TPU-native equivalent of
    # the reference's fastest path
    _flags.set_flag("matmul_precision", "bfloat16")
    # rbg PRNG: dropout mask generation off the critical path (~27%
    # faster whole-step than threefry on this model)
    jax.config.update("jax_default_prng_impl", "rbg")

    from paddle_tpu.core.arg import id_arg, non_seq
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.models import alexnet
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer
    from paddle_tpu.parallel.dp import TrainStep

    bs = 128
    conf = alexnet(image_shape=(224, 224, 3), num_classes=1000)
    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    opt = create_optimizer(
        OptimizationConf(
            learning_method="momentum", learning_rate=0.001, momentum=0.9
        ),
        net.param_confs,
    )
    opt_state = opt.init_state(params)
    state = net.init_state()
    step = TrainStep(net, opt)

    rng = np.random.default_rng(0)
    image = rng.standard_normal((bs, 224, 224, 3)).astype(np.float32)
    label = rng.integers(0, 1000, bs).astype(np.int32)
    feed = {"image": non_seq(image), "label": id_arg(label)}
    # measure compute, not host->device transfer of the synthetic batch
    feed = jax.device_put(feed)

    key = jax.random.key(1)
    # warmup / compile (float() fetch forces execution; on the axon
    # tunnel block_until_ready does not force the dependency chain)
    params, opt_state, state, loss, _ = step(
        params, opt_state, state, feed, 0, key
    )
    float(loss)

    iters = 20
    t0 = time.perf_counter()
    for i in range(1, iters + 1):
        params, opt_state, state, loss, _ = step(
            params, opt_state, state, feed, i, key
        )
    float(loss)
    ms = (time.perf_counter() - t0) / iters * 1e3

    print(
        json.dumps(
            {
                "metric": "alexnet_train_ms_per_batch_bs128",
                "value": round(ms, 3),
                "unit": "ms/batch",
                "vs_baseline": round(BASELINE_ALEXNET_BS128_MS / ms, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
