"""Multi-chip DP-scaling benchmark (VERDICT r4 item 3).

Mirrors the reference's published 4-GPU matrix — AlexNet/GoogleNet at
total-batch 128*N / 256*N and the 4-GPU LSTM text-classification rows
at fixed total-batch 256/512 (`/root/reference/benchmark/README.md:
74-93,152-160`; the MultiGradientMachine per-device thread pool it
measured: `gserver/gradientmachines/MultiGradientMachine.h:85-168`).
Here the equivalent is ONE compiled program: the batch is sharded over
the mesh's data axis and XLA emits the gradient allreduce over ICI
(parallel/dp.py::TrainStep).

Runs on whatever devices exist, zero edits either way:
- a real multi-chip slice (`jax.devices()` >= 2 TPU chips): real
  throughput rows, `vs_baseline` against the 4xK40m table;
- this box (one tunneled chip): re-execs itself onto a forced
  8-virtual-device host-CPU mesh — a correctness/shape smoke with tiny
  per-device batches, every row marked `"synthetic": true` and no
  throughput claim.

Invocation: `python bench.py --multichip` or `python bench_multichip.py
[PATTERN]`. On a pod slice, run it under the multi-host launcher the
same way as training (`python -m paddle_tpu.launch --hosts ... --
python bench_multichip.py`); each host sees the global mesh via
`jax.distributed` (paddle_tpu/core/mesh.py::distributed_init).

Each row also measures a ONE-device arm at the per-device batch and
reports `speedup` = ms_1dev * N / ms_Ndev — the reference's own
speedup formula (benchmark/README.md:79-84: (334*4)/347 = 3.85).
"""

import json
import os
import sys
import time

import numpy as np

# 4xK40m ms/batch, keyed (model, total_batch) — BASELINE.md rows 22-25,
# 29-30; benchmark/README.md:74-93 (images), :152-160 (lstm)
MC_BASELINES_MS = {
    ("alexnet", 512): 347.0,
    ("alexnet", 1024): 622.0,
    ("googlenet", 512): 1178.0,
    ("googlenet", 1024): 2367.0,
    ("lstm_h256", 256): 90.0,
    ("lstm_h256", 512): 118.0,
    ("lstm_h512", 256): 189.0,
    ("lstm_h512", 512): 268.0,
}
BASELINE_DEVICES = 4


def _ensure_devices(pattern):
    """Return (n_devices, synthetic). When only one device exists (the
    tunneled single chip, or a plain CPU), re-exec under a forced
    8-virtual-device host-CPU mesh so the sharded program still
    compiles and runs — the shape/correctness smoke. The re-exec
    command is rebuilt from the caller's PATTERN, not raw sys.argv —
    flags the caller already consumed (bench.py's --multichip) must
    not leak through as a filter that silently empties the sweep."""
    import jax

    if os.environ.get("_BENCH_MC_REEXEC"):
        # the env pin (JAX_PLATFORMS=axon) survives exec; the config
        # update is what actually selects CPU (verify-skill gotcha)
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if len(devs) >= 2:
        return len(devs), devs[0].platform != "tpu"
    if os.environ.get("_BENCH_MC_REEXEC"):
        raise RuntimeError("cpu-mesh fallback still sees <2 devices")
    env = dict(os.environ)
    env["_BENCH_MC_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    xf = env.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in xf:
        env["XLA_FLAGS"] = (
            xf + " --xla_force_host_platform_device_count=8"
        ).strip()
    sys.stdout.flush()
    os.execve(
        sys.executable,
        [sys.executable, os.path.abspath(__file__)]
        + ([pattern] if pattern else []),
        env,
    )


from bench import _setup  # one source of truth for AMP/PRNG/cache setup
from bench import emit  # every row also lands in BENCH_full_rNN.jsonl


def _mesh_arm(conf, feed, opt_conf, mesh, iters):
    """Build one (possibly mesh-sharded) training program; returns
    (warmup_fn, window_fn) with state carried across calls, same
    contract as bench.py::_build_arm."""
    import jax

    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer
    from paddle_tpu.parallel.dp import TrainStep, shard_batch

    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    opt = create_optimizer(opt_conf, net.param_confs)
    step = TrainStep(net, opt, mesh=mesh, donate=False)
    st = {
        "params": params,
        "opt_state": opt.init_state(params),
        "state": net.init_state(),
        "i": 0,
    }
    if mesh is not None:
        st["params"], st["opt_state"], st["state"] = step.place(
            st["params"], st["opt_state"], st["state"]
        )
        feed = shard_batch(feed, mesh)
    else:
        feed = jax.device_put(feed)
    key = jax.random.key(1)

    # dispatch-vs-block split for the row's attribution triple (the
    # same convention as bench.py::_build_arm: submissions are host
    # work, the final scalar fetch is the device block; the feed is
    # pre-staged so data_wait is truly 0)
    timeline = {"data_s": 0.0, "dispatch_s": 0.0, "device_s": 0.0}

    def _run(n):
        t0 = time.perf_counter()
        for _ in range(n):
            (
                st["params"],
                st["opt_state"],
                st["state"],
                loss,
                _o,
            ) = step(
                st["params"], st["opt_state"], st["state"], feed,
                st["i"], key,
            )
            st["i"] += 1
        t1 = time.perf_counter()
        out = float(loss)  # scalar fetch forces execution (tunnel)
        timeline["dispatch_s"] += t1 - t0
        timeline["device_s"] += time.perf_counter() - t1
        return out

    def warmup_fn(n):
        _run(n)
        # drop the compile-laden warmup from the attribution fields
        timeline["dispatch_s"] = timeline["device_s"] = 0.0

    def window_fn():
        t0 = time.perf_counter()
        _run(iters)
        return (time.perf_counter() - t0) / iters * 1e3

    window_fn.timeline = timeline
    return warmup_fn, window_fn


def _image_conf_feed(model, bs):
    from paddle_tpu import models
    from paddle_tpu.core.arg import id_arg, non_seq

    factory = {"alexnet": models.alexnet, "googlenet": models.googlenet}
    conf = factory[model](image_shape=(224, 224, 3), num_classes=1000)
    rng = np.random.default_rng(0)
    feed = {
        "image": non_seq(
            rng.standard_normal((bs, 224, 224, 3)).astype(np.float32)
        ),
        "label": id_arg(rng.integers(0, 1000, bs).astype(np.int32)),
    }
    return conf, feed


def _lstm_conf_feed(hidden, bs, t=100):
    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.models import stacked_lstm_classifier

    conf = stacked_lstm_classifier(
        vocab_size=30000, emb_dim=128, hidden=hidden, num_layers=2,
        num_classes=2,
    )
    rng = np.random.default_rng(0)
    feed = {
        "words": id_arg(
            rng.integers(0, 30000, (bs, t)).astype(np.int32),
            np.full((bs,), t, np.int32),
        ),
        "label": id_arg(rng.integers(0, 2, bs).astype(np.int32)),
    }
    return conf, feed


def _bench_row(model, total_bs, n_dev, synthetic):
    """One DP row: N-device arm at total_bs (sharded), plus — on real
    hardware — a 1-device arm at total_bs/N for the reference speedup
    formula. Synthetic (CPU-mesh) rows shrink the batch to a shape
    smoke and skip the 1-device arm."""
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.core.mesh import DATA_AXIS, make_mesh

    if synthetic:
        run_bs, iters, warmup, windows = 2 * n_dev, 2, 2, 1
    else:
        run_bs, iters, warmup, windows = total_bs, 10, 15, 3

    if model.startswith("lstm"):
        hidden = int(model.split("_h")[1])
        # the smoke checks sharding/shape plumbing, not throughput —
        # a short sequence keeps the one-core CI mesh fast
        conf, feed = _lstm_conf_feed(hidden, run_bs,
                                     t=16 if synthetic else 100)
        opt = OptimizationConf(learning_method="adam", learning_rate=2e-3)
    else:
        conf, feed = _image_conf_feed(model, run_bs)
        opt = OptimizationConf(
            learning_method="momentum", learning_rate=0.001, momentum=0.9
        )

    mesh = make_mesh({DATA_AXIS: n_dev})
    w, f = _mesh_arm(conf, feed, opt, mesh, iters)
    w(warmup)
    ms = min(f() for _ in range(windows))
    out = {
        "value": round(ms, 3),
        "unit": "ms/batch",
        "devices": n_dev,
        "total_batch": run_bs,
        "per_device_batch": run_bs // n_dev,
    }
    if synthetic:
        out["synthetic"] = True
        out["note"] = (
            "host-CPU virtual mesh shape smoke - no throughput claim"
        )
        return out

    base = MC_BASELINES_MS.get((model, total_bs))
    if base is not None:
        out["vs_baseline"] = round(base / ms, 2)
        out["baseline_ms"] = base
        out["baseline_devices"] = BASELINE_DEVICES
    # reference speedup formula: time_1dev(per_dev_bs) * N / time_Ndev
    if model.startswith("lstm"):
        conf1, feed1 = _lstm_conf_feed(
            int(model.split("_h")[1]), run_bs // n_dev
        )
    else:
        conf1, feed1 = _image_conf_feed(model, run_bs // n_dev)
    w1, f1 = _mesh_arm(conf1, feed1, opt, None, iters)
    w1(warmup)
    ms1 = min(f1() for _ in range(windows))
    out["ms_1dev_per_dev_batch"] = round(ms1, 3)
    out["speedup"] = round(ms1 * n_dev / ms, 2)
    out["scaling_efficiency"] = round(ms1 * n_dev / ms / n_dev, 3)
    return out


def _bench_longctx_sharded(mode, t, n_dev, synthetic, bs=1):
    """The T>=32k long-context rows (ISSUE 12 tentpole: leave the
    reference's 2017 world): the SAME longctx model as bench.py's
    single-chip rows (bench.longctx_conf), but with the time dimension
    sharded over the mesh `seq` axis — `mode` "ring" (K/V blocks
    rotate over ICI, online softmax across AND inside ring steps:
    score tiles capped at RING_BLOCK_K) or "ulysses" (all-to-all
    seq->heads reshard with FLASH local attention, attn_impl="flash").
    Dense single-chip attention cannot play at these shapes at all —
    at T=32k the [B,H,T,T] scores alone mean ~69 GB of HBM traffic
    per layer per FORWARD (4 round trips x 8 heads x T^2 x 2 bytes;
    `attn_hbm_bytes_dense_equiv` states the fwd+bwd figure on the
    row); the flash shardings stream O(T) score bytes per chip.

    Real slice: measures tokens/s at the full T with the standard
    data_wait/host/device attribution triple. Single-device hosts
    re-exec onto the 8-virtual-device CPU mesh (synthetic=True): the
    row shrinks to a shape smoke (scaled-down T, same code path
    end-to-end — mesh, shard_map collectives, scan-of-blocks, bwd) so
    the mode cannot rot in CI; no throughput claim."""
    import jax

    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.core.mesh import (
        DATA_AXIS, SEQ_AXIS, make_mesh, set_mesh,
    )
    from paddle_tpu.parallel.ring import attention_hbm_bytes

    from bench import (
        TPU_PEAK_FLOPS,
        _longctx_flops_fwd,
        longctx_conf,
        longctx_feed,
    )

    heads_adjusted = False
    if synthetic:
        # shape smoke: T scaled down but still sharded (T % n_dev == 0
        # and heads % n_dev == 0 for the ulysses head split)
        t_run, d, heads, layers, classes = 32 * n_dev, 64, n_dev, 1, 64
        iters, warmup, windows = 2, 2, 1
    else:
        t_run, d, heads, layers, classes = t, 512, 8, 2, 512
        iters, warmup, windows = 5, 5, 3
        if mode == "ulysses" and heads % n_dev:
            # the ulysses head split must divide the seq axis; record
            # the substitution ON the row — a 16-head arm is not the
            # 8-head model the ring row measures
            if d % n_dev:
                raise RuntimeError(
                    f"ulysses needs heads divisible by the seq axis "
                    f"({n_dev}) and d={d} % {n_dev} != 0 — pick a "
                    f"mesh whose seq axis divides {d}"
                )
            heads = n_dev
            heads_adjusted = True
    conf = longctx_conf(
        t_run, d, heads, layers, classes,
        attn_impl="flash", seq_parallel=mode,
    )
    feed = longctx_feed(bs, t_run, classes)
    mesh = make_mesh({DATA_AXIS: 1, SEQ_AXIS: n_dev})
    set_mesh(mesh)
    opt = OptimizationConf(learning_method="adam", learning_rate=1e-3)
    try:
        w, f = _mesh_arm(conf, feed, opt, mesh, iters)
        w(warmup)
        ms = min(f() for _ in range(windows))
    finally:
        set_mesh(make_mesh())  # later rows expect the default mesh
    toks = bs * t_run / (ms / 1e3)
    fwd = _longctx_flops_fwd(bs, t_run, d, heads, layers, classes)
    hd = d // heads
    from bench import _timeline_fields

    out = {
        **_timeline_fields(f.timeline),
        "value": round(toks, 1),
        "unit": "tokens/s (%s-sharded flash attention, T=%d)"
                % (mode, t_run),
        "ms_per_step": round(ms, 2),
        "analytic_mfu_per_chip": round(
            3 * fwd * (1e3 / ms) / TPU_PEAK_FLOPS / n_dev, 4
        ),
        "devices": n_dev,
        "seq_len": t_run,
        "seq_parallel": mode,
        "attn_impl": "flash",
        "heads": heads,
        "batch": bs,
        # what the 2017-semantics dense path WOULD stream through HBM
        # in attention-score bytes at this shape — the reason these
        # rows exist only as flash shardings
        "attn_hbm_bytes_dense_equiv": layers * attention_hbm_bytes(
            bs, t_run, t_run, heads, hd, "dense"
        ),
        "attn_hbm_bytes_flash": layers * attention_hbm_bytes(
            bs, t_run, t_run, heads, hd, "flash"
        ),
    }
    if heads_adjusted:
        out["heads_adjusted"] = True  # NOT the ring rows' 8-head model
    if synthetic:
        out["synthetic"] = True
        out["note"] = (
            "host-CPU virtual mesh shape smoke at scaled-down T - "
            "no throughput claim"
        )
    return out


def _bench_checkpoint_overhead(n_dev, synthetic):
    """Per-step cost of checkpointing at a fixed cadence, sync vs
    async (ROADMAP item 4: pod-scale snapshots must not stall
    training). Three arms over the same mesh-sharded program:

      base   — no saves (the floor)
      sync   — blocking `checkpoint.save_pass` every `cadence` steps
               (device_get + serialize + write on the training thread)
      async  — `AsyncCheckpointer.save` at the same cadence (only the
               host snapshot blocks; serialize + atomic write overlap
               the next steps)

    Headline `value` = mean training-thread stall per async save;
    `sync_save_ms` is what the same save costs when synchronous. The
    CPU-mesh smoke asserts async stall < sync save — the contract that
    makes async mode worth shipping."""
    import shutil
    import tempfile

    import jax

    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.core.mesh import DATA_AXIS, make_mesh
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer
    from paddle_tpu.parallel.dp import TrainStep, shard_batch
    from paddle_tpu.trainer import checkpoint as ckpt
    from paddle_tpu.trainer import async_checkpoint as actp

    if synthetic:
        bs, t, steps, cadence = 2 * n_dev, 16, 8, 2
    else:
        bs, t, steps, cadence = 8 * n_dev, 64, 30, 5
    # the 30k-vocab embedding makes the checkpoint tens of MB — a save
    # whose serialize+write cost is visible against the step time
    conf, feed = _lstm_conf_feed(256, bs, t=t)
    opt_conf = OptimizationConf(learning_method="adam",
                                learning_rate=2e-3)
    mesh = make_mesh({DATA_AXIS: n_dev})

    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    opt = create_optimizer(opt_conf, net.param_confs)
    step = TrainStep(net, opt, mesh=mesh, donate=False)
    st = {
        "params": params,
        "opt_state": opt.init_state(params),
        "state": net.init_state(),
        "i": 0,
    }
    st["params"], st["opt_state"], st["state"] = step.place(
        st["params"], st["opt_state"], st["state"]
    )
    feed = shard_batch(feed, mesh)
    key = jax.random.key(1)

    def one_step():
        (
            st["params"], st["opt_state"], st["state"], loss, _o,
        ) = step(
            st["params"], st["opt_state"], st["state"], feed,
            st["i"], key,
        )
        st["i"] += 1
        return float(loss)  # scalar fetch forces execution

    one_step()
    one_step()  # warm both the program and the dispatch path
    ckpt_bytes = sum(
        a.nbytes for a in actp.snapshot_shards(
            {"params": st["params"], "opt_state": st["opt_state"]}
        ).values()
    )

    def run_arm(save_fn):
        """Returns (ms_per_step over the loop, mean ms per save)."""
        stalls = []
        t0 = time.perf_counter()
        for i in range(steps):
            one_step()
            if save_fn is not None and (i + 1) % cadence == 0:
                s0 = time.perf_counter()
                save_fn((i + 1) // cadence)
                stalls.append(time.perf_counter() - s0)
        total = time.perf_counter() - t0
        stall_ms = (
            sum(stalls) / len(stalls) * 1e3 if stalls else 0.0
        )
        return total / steps * 1e3, stall_ms

    base_ms, _ = run_arm(None)

    sync_dir = tempfile.mkdtemp(prefix="bench_ckpt_sync_")
    async_dir = tempfile.mkdtemp(prefix="bench_ckpt_async_")
    try:
        def sync_save(pass_id):
            ckpt.save_pass(
                sync_dir, pass_id,
                jax.device_get(st["params"]),
                jax.device_get(st["opt_state"]),
                jax.device_get(st["state"]),
                meta={"global_step": st["i"]},
            )

        sync_ms, sync_save_ms = run_arm(sync_save)

        writer = actp.AsyncCheckpointer(async_dir, keep_last=2)

        def async_save(pass_id):
            writer.save(
                pass_id, st["params"], st["opt_state"], st["state"],
                meta={"global_step": st["i"]},
            )

        async_ms, async_stall_ms = run_arm(async_save)
        d0 = time.perf_counter()
        writer.close()  # drain; surfaces any background write error
        drain_ms = (time.perf_counter() - d0) * 1e3
        # the drained checkpoints really committed (manifest-complete,
        # checksums verified) — reported on the row so the smoke can
        # assert it, raised here so a silent writer can't score a row
        committed = [
            p for p in actp.list_passes(async_dir)
            if actp.verify_pass(async_dir, p)[0]
        ]
        if not committed:
            raise RuntimeError(
                "async writer committed no complete pass"
            )
    finally:
        shutil.rmtree(sync_dir, ignore_errors=True)
        shutil.rmtree(async_dir, ignore_errors=True)

    out = {
        "value": round(async_stall_ms, 3),
        "unit": "ms training-thread stall per async save",
        "sync_save_ms": round(sync_save_ms, 3),
        "async_stall_ms": round(async_stall_ms, 3),
        "stall_vs_sync": round(
            async_stall_ms / sync_save_ms, 3
        ) if sync_save_ms else None,
        "base_ms_per_step": round(base_ms, 3),
        "sync_ms_per_step": round(sync_ms, 3),
        "async_ms_per_step": round(async_ms, 3),
        "async_drain_ms": round(drain_ms, 3),
        "async_committed_passes": len(committed),
        "save_cadence_steps": cadence,
        "steps": steps,
        "checkpoint_mb": round(ckpt_bytes / 1e6, 1),
        "devices": n_dev,
        "total_batch": bs,
    }
    if synthetic:
        out["synthetic"] = True
        out["note"] = (
            "host-CPU virtual mesh smoke - stall RATIO is the claim, "
            "absolute times are not"
        )
    return out


def _bench_preempt_recovery(n_dev, synthetic):
    """Permanent recovery row (ISSUE 9): elasticity measured like
    throughput. Two arms, both against the REAL trainer:

      sigterm — a preemptible SGD worker subprocess is SIGTERMed
                mid-pass; it finishes the in-flight batch, flushes a
                mid-pass async checkpoint, exits EXIT_PREEMPTED (75);
                a respawn auto-resumes. Measured: flush latency
                (SIGTERM->exit), time-to-recover (respawn->first newly
                trained batch, jit compile included — that IS the
                recovery cost), and batches lost/retrained across the
                whole run (both must be 0: the global-step record must
                cover every batch exactly once).
      nan     — an in-process trainer hits one poisoned batch with
                skip_budget=0, forcing the rollback rung. Measured:
                detection latency in batches (contract: 1), rollback
                wall time, and batches of progress the rollback
                discarded (bounded by the checkpoint cadence).

    CPU smoke: timings are machine-relative; the loss-zero claims are
    exact. `value` (headline) = time_to_recover seconds."""
    import shutil
    import signal
    import tempfile

    from paddle_tpu.testing_faults import (
        read_metrics_records,
        read_worker_records,
        start_preemptible_trainer,
    )
    from paddle_tpu.trainer import watchdog as wdg

    repo = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="bench_preempt_")
    save = os.path.join(work, "ckpt")
    out_file = os.path.join(work, "out.jsonl")
    num_passes, batches = 3, 16
    total_steps = num_passes * batches

    def _lines():
        return read_worker_records(out_file)

    try:
        # ---- arm 1: SIGTERM mid-pass ----
        p = start_preemptible_trainer(
            repo, save, out_file, NUM_PASSES=num_passes,
            BATCHES=batches, BATCH_SLEEP=0.05,
        )
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if sum("loss" in ln for ln in _lines()) >= batches + 4:
                break
            time.sleep(0.05)
        p.send_signal(signal.SIGTERM)
        t0 = time.monotonic()
        rc = p.wait(timeout=120)
        flush_s = time.monotonic() - t0
        if rc != wdg.EXIT_PREEMPTED:
            raise RuntimeError(
                f"worker exited {rc}, want {wdg.EXIT_PREEMPTED}: "
                f"{p.stderr.read()[-500:]}"
            )
        steps_before = {ln["step"] for ln in _lines() if "loss" in ln}

        t1 = time.monotonic()
        p2 = start_preemptible_trainer(
            repo, save, out_file, NUM_PASSES=num_passes,
            BATCHES=batches,
        )
        recover_s = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            new = {ln["step"] for ln in _lines()
                   if "loss" in ln} - steps_before
            if new:
                recover_s = time.monotonic() - t1
                break
            time.sleep(0.05)
        rc2 = p2.wait(timeout=300)
        if rc2 != 0 or recover_s is None:
            raise RuntimeError(
                f"resume failed rc={rc2}: {p2.stderr.read()[-500:]}"
            )
        steps = [ln["step"] for ln in _lines() if "loss" in ln]
        lost = total_steps - len(set(steps))
        retrained = len(steps) - len(set(steps))

        # ---- arm 2: injected NaN -> rollback ----
        shutil.rmtree(work, ignore_errors=True)
        os.makedirs(work, exist_ok=True)
        nan_at = 2 * batches + 4  # mid-pass 2: passes 0-1 checkpointed
        metrics_file = os.path.join(work, "metrics.jsonl")
        p3 = start_preemptible_trainer(
            repo, save, out_file, NUM_PASSES=num_passes,
            BATCHES=batches, NAN_AT=nan_at, SKIP_BUDGET=0,
            GOOD_BATCHES=2, METRICS_FILE=metrics_file,
        )
        t2 = time.monotonic()
        rc3 = p3.wait(timeout=600)
        nan_wall_s = time.monotonic() - t2
        if rc3 != 0:
            raise RuntimeError(
                f"nan arm exited {rc3}: {p3.stderr.read()[-500:]}"
            )
        report = next(ln["report"] for ln in _lines()
                      if "report" in ln)
        # the watchdog's structured series on the obs METRICS stream
        # (ISSUE 10) is the measurement source now — the report stays
        # as a cross-check that stream and report cannot disagree
        wd_events = read_metrics_records(metrics_file, kind="watchdog")
        skips = [e for e in wd_events if e["event"] == "skip"]
        rollbacks = [e for e in wd_events if e["event"] == "rollback"]
        if not rollbacks:
            raise RuntimeError(
                f"no rollback on metrics stream: {wd_events}"
            )
        if len(rollbacks) != report["rollbacks"]:
            raise RuntimeError(
                f"metrics stream ({len(rollbacks)} rollbacks) "
                f"disagrees with report ({report['rollbacks']})"
            )
        # detection latency, MEASURED from the event stream: the skip
        # event's global_step minus the injected batch's step, plus 1
        # (the contract is "within 1 batch" — fires ON the poisoned
        # batch). A lagging verdict would read 2+ here, not stay 1.
        detect_batches = (
            skips[0]["global_step"] - nan_at + 1 if skips else -1
        )
        # progress discarded = steps from the restored checkpoint to
        # the fault (they retrain after rollback)
        batches_lost_nan = nan_at - rollbacks[0]["global_step"]
        # per-pass step-timeline records from the same stream give
        # this row the attribution triple every permanent row carries
        timelines = read_metrics_records(metrics_file, kind="timeline")
        tl = timelines[-1] if timelines else {}
    finally:
        shutil.rmtree(work, ignore_errors=True)

    out = {
        "value": round(recover_s, 3),
        "unit": "s to first trained batch after preemption respawn",
        "sigterm_flush_s": round(flush_s, 3),
        "sigterm_batches_lost": lost,
        "sigterm_batches_retrained": retrained,
        "sigterm_exit_code": rc,
        "nan_detect_batches": detect_batches,
        "nan_rollbacks": report["rollbacks"],
        "nan_batches_lost": batches_lost_nan,
        "nan_run_wall_s": round(nan_wall_s, 3),
        "devices": n_dev,
        "passes": num_passes,
        "batches_per_pass": batches,
        "data_wait_frac": tl.get("data_wait_frac", 0.0),
        "host_overhead_frac": tl.get("host_overhead_frac", 0.0),
        "device_frac": tl.get("device_frac", 0.0),
    }
    if synthetic:
        out["synthetic"] = True
        out["note"] = (
            "CPU smoke - loss-zero/exactly-once claims are exact, "
            "absolute times are not"
        )
    return out


def _bench_ctr_bigvocab(n_dev, synthetic):
    """Permanent elastic sparse-CTR row (ISSUE 20): the sharded
    embedding tier's robustness story, measured like throughput.
    Three phases against the REAL stack:

      kill    — the sharded-CTR worker subprocess (per-shard hot
                caches over an n_dev CPU mesh, async sharded-table
                generations) is SIGKILLed mid-epoch with a
                generation in flight; a respawn recovers from the
                per-shard manifests. Measured: kill_recover_s
                (respawn exec -> first NEWLY acknowledged batch) and
                the commit-acknowledged ledger's exactly-once
                verdict: batches_lost / batches_retrained, both
                required to be 0.
      scale   — rows_total / rows_touched_frac from the finished
                worker: the 2**30-row logical table where only the
                hot set ever materialized (V-independence priced).
      swap    — one ctr replica serves the worker's committed
                generations through a FleetRouter while a request
                stream runs; a rollout() hot-swaps to the newest
                generation mid-stream. Measured:
                swap_downtime_requests_lost (required 0) and the
                swap latency.

    CPU smoke: timings are machine-relative; the zero claims are
    exact. `value` (headline) = kill_recover_s."""
    import shutil
    import tempfile

    from paddle_tpu.serving.fleet import FleetConfig, FleetRouter
    from paddle_tpu.testing_faults import (
        kill_process,
        read_worker_records,
        start_serving_replica,
        start_sharded_ctr_trainer,
    )

    repo = os.path.dirname(os.path.abspath(__file__))
    work = tempfile.mkdtemp(prefix="bench_ctr_bigvocab_")
    save = os.path.join(work, "gens")
    os.makedirs(save)
    out_file = os.path.join(work, "ledger.jsonl")
    rows_total = 1 << 30
    if synthetic:
        batches, capacity, num_slots, hot = 16, 64, 48, 96
    else:
        batches, capacity, num_slots, hot = 48, 4096, 1024, 4096
    env = dict(SHARDS=n_dev, ROWS_TOTAL=rows_total, BATCHES=batches,
               CAPACITY=capacity, NUM_SLOTS=num_slots, HOT=hot,
               BATCH=8, FEATS=4, BATCH_SLEEP=0.05)

    def _trained():
        return [ln["trained"] for ln in read_worker_records(out_file)
                if "trained" in ln]

    router = None
    replica = None
    try:
        # ---- phase 1: SIGKILL mid-epoch, manifest recovery ----
        p = start_sharded_ctr_trainer(repo, save, out_file, **env)
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if len(_trained()) >= 3:
                break
            if p.poll() is not None:
                raise RuntimeError(
                    "worker died early: " + p.stderr.read()[-500:]
                )
            time.sleep(0.05)
        kill_process(p)  # SIGKILL: no flush, the generation in
        acked_before = set(_trained())  # flight stays torn on disk
        t1 = time.monotonic()
        p2 = start_sharded_ctr_trainer(repo, save, out_file, **env)
        kill_recover_s = None
        deadline = time.monotonic() + 300
        while time.monotonic() < deadline:
            if set(_trained()) - acked_before:
                kill_recover_s = time.monotonic() - t1
                break
            time.sleep(0.05)
        rc = p2.wait(timeout=300)
        if rc != 0 or kill_recover_s is None:
            raise RuntimeError(
                f"resume failed rc={rc}: {p2.stderr.read()[-500:]}"
            )
        trained = _trained()
        lost = len(set(range(batches)) - set(trained))
        retrained = len(trained) - len(set(trained))
        done = [ln for ln in read_worker_records(out_file)
                if ln.get("done")][-1]
        touched_frac = done["rows_materialized"] / done["rows_total"]

        # ---- phase 2: serve the generations, hot-swap mid-stream --
        proc, port = start_serving_replica(
            repo, REPLICA_MODE="ctr", MODEL_NAME="ctr",
            MODEL_TAG="pre-swap", MODEL_DIR=save)
        replica = proc
        if not port:
            raise RuntimeError(
                f"ctr replica refused: {proc.boot_line}"
            )
        router = FleetRouter({"r0": f"127.0.0.1:{port}"},
                             FleetConfig(monitor=False))
        ids = [1, 2, 3, 4]
        swap_lost = served = 0
        swap_s = None
        n_requests = 60 if synthetic else 400
        for i in range(n_requests):
            if i == n_requests // 2:
                t2 = time.monotonic()
                router.rollout("ctr", tag="post-swap")
                swap_s = time.monotonic() - t2
            resp = router.call("ctr", ids, deadline_ms=10_000)
            served += 1
            if not resp.get("ok"):
                swap_lost += 1
        final = router.call("ctr", ids, deadline_ms=10_000)
        if final.get("tag") != "post-swap":
            raise RuntimeError(f"swap did not land: {final}")
    finally:
        if router is not None:
            router.close()
        if replica is not None:
            kill_process(replica)
        shutil.rmtree(work, ignore_errors=True)

    out = {
        "value": round(kill_recover_s, 3),
        "unit": "s from respawn to first newly acknowledged batch",
        "rows_total": rows_total,
        "rows_touched_frac": touched_frac,
        "kill_recover_s": round(kill_recover_s, 3),
        "batches_lost": lost,
        "batches_retrained": retrained,
        "swap_downtime_requests_lost": swap_lost,
        "swap_s": round(swap_s, 3),
        "swap_requests_served": served,
        "batches": batches,
        "hot_capacity_per_shard": capacity,
        "devices": n_dev,
    }
    if synthetic:
        out["synthetic"] = True
        out["note"] = (
            "CPU smoke - exactly-once/zero-loss claims are exact, "
            "absolute times are not"
        )
    return out


def build_rows(n_dev):
    rows = []
    for model in ("alexnet", "googlenet"):
        for per_dev in (128, 256):
            total = per_dev * n_dev
            rows.append((f"mc_{model}_tbs{total}_dp{n_dev}",
                         model, total))
    # reference lstm rows keep TOTAL batch fixed at 256/512
    for hidden in (256, 512):
        for total in (256, 512):
            rows.append(
                (f"mc_lstm_h{hidden}_tbs{total}_dp{n_dev}",
                 f"lstm_h{hidden}", total)
            )
    return rows


def mc_main(argv):
    pattern = argv[1] if len(argv) > 1 else ""
    n_dev, synthetic = _ensure_devices(pattern)  # may re-exec
    _setup()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "2400"))
    t_start = time.monotonic()
    import jax

    emit({
        "metric": "mc_config",
        "devices": n_dev,
        "platform": jax.devices()[0].platform,
        "synthetic": synthetic,
    })
    failures = 0
    rows = [
        (name, lambda m=model, t=total: _bench_row(m, t, n_dev,
                                                   synthetic))
        for name, model, total in build_rows(n_dev)
    ]
    # permanent long-context rows (ISSUE 12 / ROADMAP 1): ring- and
    # Ulysses-sharded flash attention at T >= 32k — the sequence
    # lengths the 2017 reference (and our own dense path) cannot
    # reach; tools/check_bench_record.py pins the row names so the
    # matrix cannot silently drop them
    rows.append((
        f"mc_longctx_ring_t32768_sp{n_dev}",
        lambda: _bench_longctx_sharded("ring", 32768, n_dev,
                                       synthetic),
    ))
    rows.append((
        f"mc_longctx_ulysses_t32768_sp{n_dev}",
        lambda: _bench_longctx_sharded("ulysses", 32768, n_dev,
                                       synthetic),
    ))
    rows.append((
        f"mc_longctx_ring_t131072_sp{n_dev}",
        lambda: _bench_longctx_sharded("ring", 131072, n_dev,
                                       synthetic),
    ))
    # permanent elasticity rows (ROADMAP item 4 / ISSUE 9): checkpoint
    # stalls and preemption recovery are tracked like MFU, not assumed
    # away
    rows.append((
        f"mc_checkpoint_overhead_dp{n_dev}",
        lambda: _bench_checkpoint_overhead(n_dev, synthetic),
    ))
    rows.append((
        f"mc_preempt_recovery_dp{n_dev}",
        lambda: _bench_preempt_recovery(n_dev, synthetic),
    ))
    # permanent elastic sparse-CTR row (ISSUE 20): SIGKILL the
    # sharded-table worker mid-epoch, recover from per-shard
    # manifests, hot-swap the serving model mid-stream — the
    # exactly-once ledger and zero-downtime swap are enforced
    # field-by-field by tools/check_bench_record.py
    rows.append((
        f"ctr_bigvocab_dp{n_dev}",
        lambda: _bench_ctr_bigvocab(n_dev, synthetic),
    ))
    for name, fn in rows:
        if pattern and pattern not in name:
            continue
        elapsed = time.monotonic() - t_start
        if elapsed > budget_s:
            emit({
                "metric": name, "skipped": "budget",
                "elapsed_s": round(elapsed, 1),
            })
            continue
        line = {"metric": name}
        try:
            line.update(fn())
        except Exception as e:
            failures += 1
            line["error"] = f"{type(e).__name__}: {e}"[:300]
            line["value"] = None
        emit(line)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(mc_main(sys.argv))
