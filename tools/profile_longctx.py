"""Long-context attention capture tool (ISSUE 12 / PERF.md round 8):
compile the longctx train step (the exact model bench.bench_longctx
measures) with `attn_impl` dense AND flash, and write per-arm
captures next to the committed traces:

  tools/traces/longctx_t{T}_{impl}.hlo.txt.gz   compiled HLO module
  tools/traces/longctx_t{T}_{impl}.report.json  shape + XLA cost
                                                analysis (flops,
                                                bytes accessed) +
                                                optional measured ms

`tools/trace_attribution.py CAPTURE.hlo.txt.gz` then produces the
committed `*.attrib.json` byte attribution whose `attention` category
proves the flash byte removal on the real compiled program — the
no-TPU-needed half of the proof. On a TPU host, add `--trace-dir` to
also capture an XPlane profile of the same step (the time half, same
as tools/profile_resnet.py), and `--run` to measure step wall time on
whatever backend this runs on.

Compilation allocates no tensors, so the dense arm compiles at the
full bench shape (B=4, T=4096) even on a laptop; `--run` at that
shape needs the memory for the real [B,H,T,T] scores — that being
prohibitive is the point.

Usage: python tools/profile_longctx.py [--t 4096] [--bs 4]
       [--impls dense,flash] [--out-dir tools/traces] [--run]
       [--trace-dir DIR]
"""

import argparse
import gzip
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def build_step(conf, feed, seed=0):
    """jitted fwd+bwd+update-free grad step (the byte-dominant part of
    the train step; optimizer elementwise adds O(params) bytes
    identically to both arms)."""
    import jax

    from paddle_tpu.network import Network

    net = Network(conf)
    params = net.init_params(jax.random.key(seed))
    state = net.init_state()
    key = jax.random.key(1)

    def loss(p, f):
        return net.loss_fn(p, f, state=state, rng=key, train=True)[0]

    gf = jax.jit(lambda p, f: jax.grad(loss)(p, f))
    return gf, params


def build_update_step(conf, feed, seed=0):
    """jitted fwd+bwd+SGD-update step with DONATED param/opt buffers —
    the capture the donation audit (analysis/hlo_audit.py, ISSUE 13)
    runs on: every donated buffer must appear in the compiled
    module's input_output_alias map, else the step keeps params live
    twice and HBM footprint silently doubles. Returns
    (jitted_fn, params, opt_state, donated_buffer_count)."""
    import jax

    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer

    net = Network(conf)
    params = net.init_params(jax.random.key(seed))
    state = net.init_state()
    opt = create_optimizer(
        OptimizationConf(learning_method="momentum",
                         learning_rate=0.01, momentum=0.9),
        net.param_confs,
    )
    opt_state = opt.init_state(params)
    key = jax.random.key(1)

    def update(p, ost, f):
        def loss(p, f):
            return net.loss_fn(
                p, f, state=state, rng=key, train=True
            )[0]

        grads = jax.grad(loss)(p, f)
        return opt.update(grads, p, ost, 0)

    uf = jax.jit(update, donate_argnums=(0, 1))
    donated = len(jax.tree_util.tree_leaves(params)) + len(
        jax.tree_util.tree_leaves(opt_state)
    )
    return uf, params, opt_state, donated


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--t", type=int, default=4096)
    ap.add_argument("--bs", type=int, default=4)
    ap.add_argument("--d", type=int, default=512)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--classes", type=int, default=512)
    ap.add_argument("--impls", default="dense,flash")
    ap.add_argument("--update-step", action="store_true",
                    help="capture the full train-update step with "
                         "DONATED param/opt buffers (writes "
                         "longctx_t{T}_{impl}_train.* — the donation"
                         "-audit capture, ISSUE 13)")
    ap.add_argument("--out-dir", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "traces"))
    ap.add_argument("--run", action="store_true",
                    help="also execute + time 3 steps per arm")
    ap.add_argument("--trace-dir", default="",
                    help="XPlane profiler capture dir (TPU hosts)")
    args = ap.parse_args()

    import jax

    from paddle_tpu.core import flags as _flags

    _flags.set_flag("matmul_precision", "bfloat16")
    jax.config.update("jax_default_prng_impl", "rbg")

    from bench import longctx_conf, longctx_feed
    from paddle_tpu.parallel.ring import attention_hbm_bytes

    os.makedirs(args.out_dir, exist_ok=True)
    feed = longctx_feed(args.bs, args.t, args.classes)
    for impl in args.impls.split(","):
        conf = longctx_conf(
            args.t, args.d, args.heads, args.layers, args.classes,
            attn_impl=impl,
        )
        if args.update_step:
            uf, params, opt_state, donated = build_update_step(
                conf, feed
            )
            compiled = uf.lower(params, opt_state, feed).compile()
            stem = os.path.join(
                args.out_dir, f"longctx_t{args.t}_{impl}_train"
            )
            with gzip.open(stem + ".hlo.txt.gz", "wt") as f:
                f.write(compiled.as_text())
            report = {
                "model": "bench.longctx_conf full update step "
                         "(donated params+opt buffers)",
                "attn_impl": impl,
                "batch_size": args.bs,
                "seq_len": args.t,
                "d_model": args.d,
                "heads": args.heads,
                "layers": args.layers,
                "backend": jax.default_backend(),
                # the donation audit's contract: at least this many
                # input buffers must appear in input_output_alias
                "donated_arg_buffers": donated,
            }
            with open(stem + ".report.json", "w") as f:
                json.dump(report, f, indent=2)
                f.write("\n")
            print(json.dumps({"impl": impl, **report}))
            continue
        gf, params = build_step(conf, feed)
        compiled = gf.lower(params, feed).compile()
        ca = compiled.cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        try:
            temp_bytes = compiled.memory_analysis().temp_size_in_bytes
        except Exception:
            temp_bytes = None  # not every backend reports it
        stem = os.path.join(
            args.out_dir, f"longctx_t{args.t}_{impl}"
        )
        with gzip.open(stem + ".hlo.txt.gz", "wt") as f:
            f.write(compiled.as_text())
        hd = args.d // args.heads
        report = {
            "model": "bench.longctx_conf (the longctx bench rows)",
            "attn_impl": impl,
            "batch_size": args.bs,
            "seq_len": args.t,
            "d_model": args.d,
            "heads": args.heads,
            "layers": args.layers,
            "backend": jax.default_backend(),
            "xla_flops": ca.get("flops", 0),
            "xla_bytes_accessed": ca.get("bytes accessed", 0),
            # peak temp memory: the reason dense T>=32k cannot exist
            # on one chip at all (the [B,H,T,T] scores), independent
            # of bandwidth
            "hbm_temp_bytes": temp_bytes,
            "analytic_attn_hbm_bytes": args.layers
            * attention_hbm_bytes(
                args.bs, args.t, args.t, args.heads, hd, impl
            ),
        }
        if args.run:
            import jax.numpy as jnp

            dfeed = jax.device_put(feed)
            r = gf(params, dfeed)
            float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                r = gf(params, dfeed)
                float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
                best = min(best, time.perf_counter() - t0)
            report["fwd_bwd_ms"] = round(best * 1e3, 2)
            report["tokens_per_s"] = round(
                args.bs * args.t / best, 0
            )
        with open(stem + ".report.json", "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(json.dumps({"impl": impl, **report}))
        if args.trace_dir:
            from paddle_tpu.core import profiler

            tdir = os.path.join(args.trace_dir, impl)
            dfeed = jax.device_put(feed)
            with profiler.trace(tdir):
                for _ in range(3):
                    r = gf(params, dfeed)
                float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
            print(f"trace written to {tdir}")


if __name__ == "__main__":
    main()
