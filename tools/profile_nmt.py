"""NMT (seq2seq+attention) train-step profile: timings, XLA cost
analysis, analytic-FLOP MFU, and an XPlane trace — the ResNet-style
accounting for the second north star (VERDICT r3 weak #2; reference
benchmark/paddle/rnn/rnn.py + benchmark/README.md:139).

Usage: python tools/profile_nmt.py [--bs 256] [--t 32]
       [--trace-dir /tmp/nmt-trace]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=256)
    ap.add_argument("--t", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=512)
    ap.add_argument("--emb", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=30000)
    ap.add_argument("--trace-dir", default="")
    args = ap.parse_args()

    import jax

    from paddle_tpu.core import flags as _flags

    _flags.set_flag("matmul_precision", "bfloat16")
    jax.config.update("jax_default_prng_impl", "rbg")

    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.models import seq2seq_attention
    from paddle_tpu.network import Network

    bs, t = args.bs, args.t
    conf = seq2seq_attention(
        src_vocab=args.vocab, trg_vocab=args.vocab,
        emb_dim=args.emb, hidden=args.hidden,
    )
    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    state = net.init_state()
    rng = np.random.default_rng(0)
    lens = np.full((bs,), t, np.int32)
    feed = jax.device_put({
        "src": id_arg(
            rng.integers(2, args.vocab, (bs, t)).astype(np.int32), lens
        ),
        "trg_in": id_arg(
            rng.integers(2, args.vocab, (bs, t)).astype(np.int32), lens
        ),
        "trg_out": id_arg(
            rng.integers(2, args.vocab, (bs, t)).astype(np.int32), lens
        ),
    })
    key = jax.random.key(1)

    def loss(p, f):
        return net.loss_fn(p, f, state=state, rng=key, train=True)[0]

    gf = jax.jit(lambda p, f: jax.grad(loss)(p, f))
    c = gf.lower(params, feed).compile()
    ca = c.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    ma = c.memory_analysis()

    def bench(f, *a, n=10):
        for _ in range(5):
            r = f(*a)
        float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                r = f(*a)
            float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e3

    ms = bench(gf, params, feed)

    # same conventions as bench.py (the single source of truth; the
    # repo root is already on sys.path from the top of this file)
    import bench as bench_mod

    analytic = bench_mod._nmt_train_flops_per_batch(
        bs, t, args.hidden, args.vocab, args.emb
    )
    peak = bench_mod.TPU_PEAK_FLOPS
    xla_flops = ca.get("flops", 0)
    xla_bytes = ca.get("bytes accessed", 0)
    report = {
        "batch_size": bs,
        "seq_len": t,
        "fwd_bwd_ms": round(ms, 2),
        "tokens_per_s": round(bs * t / ms * 1e3, 0),
        "analytic_flops_per_batch": analytic,
        "xla_flops": xla_flops,
        "xla_bytes_accessed": xla_bytes,
        "hbm_temp_bytes": ma.temp_size_in_bytes,
        "mfu_analytic": round(analytic / (ms / 1e3) / peak, 4),
        "mfu_xla": round(xla_flops / (ms / 1e3) / peak, 4),
        # arithmetic intensity vs the v5e ridge (~240 FLOP/byte):
        # below it the step is HBM-bound and the MFU ceiling is
        # intensity/ridge
        "flop_per_byte_xla": round(xla_flops / max(xla_bytes, 1), 1),
    }
    print(json.dumps(report, indent=2))

    if args.trace_dir:
        from paddle_tpu.core import profiler

        with profiler.trace(args.trace_dir):
            for _ in range(3):
                r = gf(params, feed)
            float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        print(f"trace written to {args.trace_dir}")


if __name__ == "__main__":
    main()
