#!/usr/bin/env python
"""framework_lint — the single driver for every static-analysis pass
(ISSUE 13).

Registered passes (run one by name, `--fast`, or `--all`):

  ast          paddle_tpu/analysis/ast_lint.py source passes over the
               tree: jax-import fence, duplicate dict keys, unfenced
               timing around async dispatch, unlocked container
               mutation. Pure AST, jax-free, fast — run BEFORE the
               test shards.
  bench-static tools/check_bench_record.py `static` mode (bench rows
               must flow through emit(); permanent rows registered;
               NORTH_STARS/TIMELINE_ROWS drift tripwire), subsumed
               here as a registered pass.
  obs          check_bench_record `obs` mode (no module-scope jax in
               paddle_tpu/obs/; required modules present), subsumed.
  hlo-audit    paddle_tpu/analysis/hlo_audit.py over every capture
               named in tools/traces/audit_budgets.json: donation/
               aliasing, host-transfer budget, byte budgets vs the
               committed baseline, forbidden-op patterns (no [T,T] on
               flash captures, no AMP f32 upcasts). Also verifies the
               committed *.audit.json reports still match the
               captures they describe — a stale report is itself a
               violation. `--write-audit` refreshes them after an
               intentional perf change (then re-baseline
               audit_budgets.json by hand: budgets never auto-widen).
  spmd-audit   paddle_tpu/analysis/spmd_audit.py over every SPMD-
               policy capture (the mc_* rows from
               tools/profile_multichip.py): partition-count pin,
               replication floor (no tensor above the floor may ride
               replicated on a sharded program), collective byte
               budgets + required/forbidden collective kinds, and
               schedule safety (channel uniqueness, data-dependent
               channel order, collective-permute ring validity).
               Same freshness discipline and --write-audit flow as
               hlo-audit; the two passes split the budgets file by
               policy kind so `--all` audits every stem exactly once.

Runtime tripwires live next door and are driven elsewhere: the
recompile guard (analysis/recompile_guard.py) arms inside the trainer
/serving batcher, and the lock-order checker (analysis/lock_order.py)
instruments the known locks when the faults shard runs with
PADDLE_LOCK_CHECK=1 (tests/run_suite.sh).

Usage:
    python tools/framework_lint.py --all
    python tools/framework_lint.py --fast          # jax-free AST tier
    python tools/framework_lint.py ast obs ...     # specific passes
    python tools/framework_lint.py hlo-audit --write-audit
    python tools/framework_lint.py --list

Exit 0 = clean, 1 = violations (printed to stderr), 2 = usage error.
Everything here is pure stdlib — no jax, no device runtime.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
_TOOLS = os.path.dirname(os.path.abspath(__file__))
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)

TRACES_DIR = os.path.join(_TOOLS, "traces")


# ---- passes -------------------------------------------------------
def pass_ast(repo: str, _args) -> list:
    from paddle_tpu.analysis import ast_lint

    return ast_lint.run_passes(repo)


def pass_bench_static(repo: str, _args) -> list:
    import check_bench_record as cbr

    return [f"[bench-static] {v}" for v in cbr.check_static(repo)]


def pass_obs(repo: str, _args) -> list:
    import check_bench_record as cbr

    return [f"[obs] {v}" for v in cbr.check_obs_imports(repo)]


def _audit_pass(repo: str, args, tag: str, only=None) -> list:
    """Shared body of the capture-audit passes: run the auditor over
    every budgets entry `only` selects, then enforce committed-report
    freshness — the *.audit.json next to each capture must be exactly
    what the capture audits to today; a stale report lies about what
    the lint enforces. `--write-audit` regenerates them after an
    intentional change (then re-baseline audit_budgets.json by hand:
    budgets never auto-widen)."""
    from paddle_tpu.analysis import hlo_audit

    traces = os.path.join(repo, "tools", "traces")
    if not os.path.isdir(traces):
        traces = TRACES_DIR
    budgets = os.path.join(traces, "audit_budgets.json")
    if not os.path.exists(budgets):
        return [
            f"[{tag}] {budgets}: missing — the byte-budget "
            f"baselines are gone; the audit has nothing to enforce"
        ]
    reports = hlo_audit.audit_dir(traces, budgets, only=only)
    violations = [
        f"[{tag}] {v}" for v in hlo_audit.violations(reports)
    ]
    for stem, rep in sorted(reports.items()):
        out_path = os.path.join(traces, stem + ".audit.json")
        if getattr(args, "write_audit", False):
            with open(out_path, "w") as f:
                json.dump(rep, f, indent=2)
                f.write("\n")
            print(f"framework_lint: wrote {out_path}")
            continue
        if not os.path.exists(out_path):
            violations.append(
                f"[{tag}] {stem}: no committed audit report "
                f"({os.path.basename(out_path)}) — run "
                f"`python tools/framework_lint.py {tag} "
                f"--write-audit` and commit it"
            )
            continue
        with open(out_path) as f:
            committed = json.load(f)
        if committed != rep:
            violations.append(
                f"[{tag}] {stem}: committed audit report is "
                f"STALE (capture or auditor changed since it was "
                f"written) — regenerate with --write-audit"
            )
    return violations


def pass_hlo_audit(repo: str, args) -> list:
    from paddle_tpu.analysis import spmd_audit

    # non-SPMD stems only: the SPMD-policy captures belong to the
    # spmd-audit pass (one pass per stem, so `--all` audits every
    # stem exactly once and the two passes can't double-write a
    # report)
    return _audit_pass(
        repo, args, "hlo-audit",
        only=lambda p: not spmd_audit.is_spmd_policy(p),
    )


def pass_spmd_audit(repo: str, args) -> list:
    from paddle_tpu.analysis import spmd_audit

    return _audit_pass(
        repo, args, "spmd-audit", only=spmd_audit.is_spmd_policy
    )


PASSES = {
    "ast": pass_ast,
    "bench-static": pass_bench_static,
    "obs": pass_obs,
    "hlo-audit": pass_hlo_audit,
    "spmd-audit": pass_spmd_audit,
}
# the jax-free tier cheap enough to gate every suite run up front
FAST_PASSES = ("ast", "bench-static", "obs")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="framework_lint",
        description=__doc__.splitlines()[0],
    )
    ap.add_argument("passes", nargs="*",
                    help=f"pass names ({', '.join(PASSES)})")
    ap.add_argument("--all", action="store_true",
                    help="run every registered pass")
    ap.add_argument("--fast", action="store_true",
                    help=f"run the fast jax-free tier "
                         f"({', '.join(FAST_PASSES)})")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes")
    ap.add_argument("--repo", default=_REPO,
                    help="repo root to lint (default: this checkout)")
    ap.add_argument("--write-audit", action="store_true",
                    help="(hlo-audit) regenerate the committed "
                         "*.audit.json reports")
    args = ap.parse_args(argv)

    if args.list:
        for name in PASSES:
            print(name)
        return 0
    names = list(args.passes)
    if args.all:
        names = list(PASSES)
    elif args.fast:
        names = list(FAST_PASSES)
    if not names:
        ap.print_usage(sys.stderr)
        print(
            "framework_lint: name at least one pass, or --all/--fast",
            file=sys.stderr,
        )
        return 2
    unknown = [n for n in names if n not in PASSES]
    if unknown:
        print(
            f"framework_lint: unknown pass(es) {unknown}; "
            f"registered: {list(PASSES)}",
            file=sys.stderr,
        )
        return 2

    violations = []
    for name in names:
        violations.extend(PASSES[name](args.repo, args))
    for v in violations:
        print(f"framework_lint: {v}", file=sys.stderr)
    if not violations:
        print(f"framework_lint: OK ({', '.join(names)})")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
