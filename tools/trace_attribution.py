#!/usr/bin/env python
"""Attribute device time (or compiled-program bytes) to HLO categories.

The tool behind ROADMAP item 2's attribution requirement: given a
profiler capture (the Chrome-trace `trace.json.gz` that
`jax.profiler`/`tools/profile_resnet.py` writes from the XPlane — the
committed `tools/traces/*.trace.json.gz` files), name where the
device's wall time goes:

- per-category device-time shares — **conv**, **gemm**, **attention**
  (ops inside the attention named_scopes and Pallas/Mosaic
  custom-call attention kernels — so flash time is attributed, not
  lumped into "other"), **bn_elementwise** (BN statistics,
  activations, reductions, loop fusions), **layout** (copies,
  transposes, dtype converts, HBM<->scratch slices), **collective**,
  **infeed**, **other** — plus **bubble** = wall minus device-busy
  (union of op intervals inside the stepped window), the share no
  per-op table can show;
- a top-N HLOs-by-total-time table with per-op achieved HBM
  bandwidth (`bytes_accessed / duration`), which separates
  memory-bound fusions from compute-bound ones at a glance;
- a machine-readable `*.attrib.json` report, committed next to the
  trace so the roofline campaign argues from evidence.

Works on `.json` / `.json.gz` Chrome traces. Raw `.xplane.pb`
captures must first be exported to a trace (TensorBoard's profile
plugin or `tensorflow.python.profiler` does this); the committed
captures are already trace.json.gz.

**HLO-module captures** (`*.hlo.txt[.gz]`, written by
tools/profile_longctx.py or bench.write_decode_hlo): when no device
profiler is reachable (this container has no TPU and the CPU profiler
emits no per-op plane), the same classifier attributes the REAL
compiled program's **bytes** statically — every top-level instruction
is charged its operand + output bytes (fusion internals excluded:
only fusion boundaries cross HBM), bucketed by the same categories.
That is how the committed longctx captures prove the flash byte
removal per-instruction: the dense program's attention category
carries the O(T^2) score tensors, the flash program's does not
(PERF.md round 8). While-loop bodies are counted once (the longctx
captures are loop-free by construction — the blocked flash unrolls at
the capture shape; the decode capture's per-iteration bytes are
multiplied by max_len in the PERF analysis, and the report carries
`while_instructions` so the caveat is machine-visible).

Usage:
    python tools/trace_attribution.py TRACE.json[.gz]
        [--out X.attrib.json] [--top 10] [--json]
    python tools/trace_attribution.py CAPTURE.hlo.txt[.gz] [...]

No jax / device runtime needed — pure stdlib, runs anywhere.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import re
import sys
from collections import defaultdict

# v5e reference numbers for the table's context columns
HBM_PEAK_GBPS = 819.0

CATEGORIES = (
    "conv", "gemm", "attention", "bn_elementwise", "layout",
    "collective", "infeed", "other",
)

_COLLECTIVE_TOKENS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective", "send", "recv",
)
_LAYOUT_NAME_PREFIXES = (
    "copy", "transpose", "bitcast", "reshape", "convert_element_type",
    "slice-start", "slice-done", "dynamic_slice", "dynamic-update",
    "pad",
)
# attention bucketing (ISSUE 12): ops under the attention
# named_scopes (parallel/ring.py stamps dense_attention /
# flash_attention / ring/ulysses scopes into HLO metadata op_name,
# which trace events carry in long_name/tf_op) and Pallas/Mosaic
# custom-call attention kernels
_ATTENTION_TOKENS = (
    "dense_attention", "flash_attention", "ring_attention",
    "ulysses_attention", "flash_att",
)
_ATTENTION_CUSTOM_CALL_TOKENS = ("mosaic", "tpu_custom_call")


def classify(name: str, category: str, long_name: str) -> str:
    """Map one device op to a report category. `category` is XLA's own
    `hlo_category` arg (or the HLO opcode in hlo-module captures);
    `long_name` the HLO text incl. metadata (both may be '')."""
    n = name.lower()
    c = (category or "").lower()
    ln = (long_name or "").lower()
    if any(t in n or t in c for t in _COLLECTIVE_TOKENS):
        return "collective"
    if "infeed" in n or "outfeed" in n or "infeed" in c or "outfeed" in c:
        return "infeed"
    # attention BEFORE conv/gemm: the attention scopes' dots/fusions
    # must land here, and a Pallas flash kernel is a custom-call whose
    # only category hint is its target/metadata
    if any(t in n or t in ln for t in _ATTENTION_TOKENS):
        return "attention"
    if ("custom-call" in c or "custom_call" in c
            or n.startswith("custom")) and any(
        t in n or t in ln for t in _ATTENTION_CUSTOM_CALL_TOKENS
    ):
        return "attention"
    if "convolution" in c or "convolution(" in ln or n.startswith("conv_"):
        return "conv"
    if ("dot(" in ln or "dot " in ln or "gemm" in n or "gemm" in c
            or c == "dot" or n.startswith("dot")):
        return "gemm"
    # layout/data-movement BEFORE elementwise: convert_element_type is
    # a dtype/layout relayout even though XLA categorizes it
    # "non-fusion elementwise", and the async slice-start/done pairs
    # are HBM<->scratch staging copies
    if (c in ("copy", "copy-start", "copy-done", "data formatting",
              "dynamic-slice", "async-start", "async-done")
            or n.startswith(_LAYOUT_NAME_PREFIXES)):
        return "layout"
    if ("fusion" in c or "elementwise" in c or "reduce" in c
            or "scatter" in c or "select-and-scatter" in c
            or n.startswith(("fusion", "add", "multiply", "reduce",
                             "select_and_scatter", "broadcast"))):
        return "bn_elementwise"
    return "other"


def _load_trace(path: str) -> dict:
    if path.endswith((".pb", ".xplane.pb")):
        raise SystemExit(
            f"{path}: raw XPlane protobuf — export it to a Chrome "
            "trace.json(.gz) first (TensorBoard profile plugin); the "
            "committed captures under tools/traces/ already are."
        )
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _union_us(intervals) -> float:
    """Total covered length of possibly-overlapping [start, end)."""
    total = 0.0
    cur_s = cur_e = None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def analyze(path: str, top: int = 10) -> dict:
    """Parse one trace and return the attribution report dict."""
    doc = _load_trace(path)
    evs = doc.get("traceEvents", [])
    proc_names: dict = {}
    thread_names: dict = {}
    for e in evs:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"]["name"]
        elif e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = e["args"]["name"]

    device_pids = {
        pid for pid, n in proc_names.items()
        if n.startswith("/device:")
    }
    if not device_pids:
        raise SystemExit(f"{path}: no /device:* process in trace")

    op_tids = {
        k for k, n in thread_names.items()
        if k[0] in device_pids and n == "XLA Ops"
    }
    step_tids = {
        k for k, n in thread_names.items()
        if k[0] in device_pids and n == "Steps"
    }

    ops = [
        e for e in evs
        if e.get("ph") == "X" and (e["pid"], e.get("tid")) in op_tids
    ]
    steps = [
        e for e in evs
        if e.get("ph") == "X" and (e["pid"], e.get("tid")) in step_tids
    ]
    if not ops:
        raise SystemExit(f"{path}: no XLA Ops events")

    # the measured window: the REAL steps (the profiler also logs
    # sub-ms pseudo-steps for trailing host fetches — drop anything
    # under half the longest step)
    if steps:
        max_dur = max(s["dur"] for s in steps)
        real = [s for s in steps if s["dur"] >= 0.5 * max_dur]
        w0 = min(s["ts"] for s in real)
        w1 = max(s["ts"] + s["dur"] for s in real)
        n_steps = len(real)
        step_ms = sum(s["dur"] for s in real) / n_steps / 1e3
    else:
        w0 = min(o["ts"] for o in ops)
        w1 = max(o["ts"] + o["dur"] for o in ops)
        n_steps, step_ms = 0, None
    wall_us = w1 - w0

    in_window = [
        o for o in ops if o["ts"] < w1 and o["ts"] + o["dur"] > w0
    ]
    busy_us = _union_us(
        (max(o["ts"], w0), min(o["ts"] + o["dur"], w1))
        for o in in_window
    )

    cat_time = defaultdict(float)
    cat_ops = defaultdict(int)
    cat_bytes = defaultdict(int)
    by_name: dict = {}
    for o in in_window:
        args = o.get("args", {})
        cat = classify(o["name"], args.get("hlo_category", ""),
                       args.get("long_name", ""))
        dur = o["dur"]
        nbytes = int(args.get("bytes_accessed", 0) or 0)
        cat_time[cat] += dur
        cat_ops[cat] += 1
        cat_bytes[cat] += nbytes
        rec = by_name.setdefault(
            o["name"],
            {"name": o["name"], "category": cat, "time_us": 0.0,
             "count": 0, "bytes_accessed": 0},
        )
        rec["time_us"] += dur
        rec["count"] += 1
        rec["bytes_accessed"] += nbytes

    # overlapping (async) ops can make the per-category sum exceed the
    # busy union; scale so category shares + bubble sum to exactly 1
    raw_sum = sum(cat_time.values())
    scale = busy_us / raw_sum if raw_sum > busy_us > 0 else 1.0

    categories = {}
    for cat in CATEGORIES:
        t = cat_time.get(cat, 0.0) * scale
        if cat_ops.get(cat, 0) == 0:
            continue
        categories[cat] = {
            "time_us": round(t, 1),
            "share": round(t / wall_us, 4) if wall_us else 0.0,
            "n_ops": cat_ops[cat],
            "bytes_accessed": cat_bytes[cat],
            "achieved_gbps": round(
                cat_bytes[cat] / (cat_time[cat] * 1e-6) / 1e9, 1
            ) if cat_time[cat] else 0.0,
        }

    bubble_us = max(wall_us - busy_us, 0.0)
    shares = {c: v["share"] for c, v in categories.items()}
    shares["bubble"] = round(bubble_us / wall_us, 4) if wall_us else 0.0

    top_hlos = sorted(
        by_name.values(), key=lambda r: -r["time_us"]
    )[:top]
    for r in top_hlos:
        r["time_us"] = round(r["time_us"], 1)
        r["share_of_busy"] = round(
            r["time_us"] / busy_us, 4
        ) if busy_us else 0.0
        r["avg_us"] = round(r["time_us"] / r["count"], 1)
        r["achieved_gbps"] = round(
            r["bytes_accessed"] / (r["time_us"] * 1e-6) / 1e9, 1
        ) if r["time_us"] else 0.0

    report = {
        "source": os.path.basename(path),
        "capture_kind": "profiler_trace",
        "devices": len(device_pids),
        "steps": n_steps,
        "step_ms": round(step_ms, 3) if step_ms else None,
        "wall_us": round(wall_us, 1),
        "device_busy_us": round(busy_us, 1),
        "bubble_us": round(bubble_us, 1),
        "overlap_scale": round(scale, 6),
        "hbm_peak_gbps": HBM_PEAK_GBPS,
        "shares": shares,
        "categories": categories,
        "top_hlos": top_hlos,
    }
    # the profiler run's own summary (flops, bytes, img/s) sits next
    # to the trace as <stem>.report.json — fold it in for context
    stem = path
    for suf in (".trace.json.gz", ".trace.json", ".json.gz", ".json"):
        if stem.endswith(suf):
            stem = stem[: -len(suf)]
            break
    sibling = stem + ".report.json"
    if os.path.exists(sibling):
        with open(sibling) as f:
            report["capture_report"] = json.load(f)
    return report


_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
    "u64": 8, "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1,
    "f8e5m2": 1,
}
_SHAPE_RE = re.compile(
    r"\b(" + "|".join(_DTYPE_BYTES) + r")\[([0-9,]*)\]"
)
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*"      # instruction name
    r"((?:\([^=]*?\))|\S+)\s+"                   # output shape (or tuple)
    r"([\w\-]+)\("                               # opcode
)
# instructions that move no HBM bytes of their own: reads are charged
# at the consuming op, parameters/constants at their users, tuple
# plumbing is free
_FREE_OPCODES = {
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "partition-id", "replica-id", "iota",
}


def _shape_bytes(text: str) -> int:
    """Total bytes of every dtype[shape] occurrence in `text` (tuples
    sum their elements; scalars count their dtype size)."""
    total = 0
    for dt, dims in _SHAPE_RE.findall(text):
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dt]
    return total


def _operand_section(rest: str) -> str:
    """`rest` starts right after the opcode's '(' — return the operand
    text up to its matching ')' (attributes/metadata excluded)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i]
    return rest


# categories with a positive token/opcode signal; the fallback buckets
# (bn_elementwise / layout / other) are WEAK — a weak op whose operand
# was produced by an attention op inherits "attention" (dataflow
# closure). XLA's backward-pass fission drops metadata from some
# fusions (e.g. the [T,T] softmax-backward convert fusions in the
# dense longctx capture carry no op_name at all), and without the
# closure those score-matrix bytes silently leak into bn_elementwise.
_STRONG_CATEGORIES = ("collective", "infeed", "attention", "conv",
                      "gemm")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")


def analyze_hlo(path: str, top: int = 10) -> dict:
    """Static byte attribution of one compiled HLO module (the
    `*.hlo.txt[.gz]` captures): each top-level instruction is charged
    its output + operand bytes — at fusion granularity, exactly the
    tensors that cross HBM — and bucketed with the same classify() as
    the trace path (plus the weak-op dataflow inheritance above).
    Instructions inside %fused_computation bodies are skipped (they
    live in registers/scratch); other non-entry computations (while
    bodies, reduce appliers) count once, with the while-instruction
    count reported so the caveat is visible."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        lines = f.read().splitlines()

    cat_bytes = defaultdict(int)
    cat_ops = defaultdict(int)
    by_name = {}
    prod_cat: dict = {}  # instruction -> category (dataflow closure)
    total = 0
    n_instr = 0
    n_while = 0
    largest_output = 0
    inherited = 0
    in_fused = False
    depth_at_fused = 0
    brace_depth = 0
    for line in lines:
        stripped = line.strip()
        opens = line.count("{") - line.count("}")
        if not in_fused and (
            stripped.startswith("%fused_computation")
            or stripped.startswith("fused_computation")
        ) and "{" in line:
            in_fused = True
            depth_at_fused = brace_depth
        brace_depth += opens
        if in_fused:
            if brace_depth <= depth_at_fused:
                in_fused = False
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, out_shape, opcode = m.groups()
        if opcode in _FREE_OPCODES:
            continue
        n_instr += 1
        if opcode == "while":
            n_while += 1
        rest = line[m.end():]
        operands = _operand_section(rest)
        out_bytes = _shape_bytes(out_shape)
        largest_output = max(largest_output, out_bytes)
        nbytes = out_bytes + _shape_bytes(operands)
        cat = classify(name, opcode, line)
        if cat not in _STRONG_CATEGORIES:
            for op_name in _OPERAND_NAME_RE.findall(operands):
                if prod_cat.get(op_name) == "attention":
                    cat = "attention"
                    inherited += 1
                    break
        prod_cat[name] = cat
        cat_bytes[cat] += nbytes
        cat_ops[cat] += 1
        total += nbytes
        rec = by_name.setdefault(
            name, {"name": name, "category": cat, "bytes": 0,
                   "count": 0},
        )
        rec["bytes"] += nbytes
        rec["count"] += 1

    if n_instr == 0:
        raise SystemExit(f"{path}: no HLO instructions found")

    categories = {}
    for cat in CATEGORIES:
        if cat_ops.get(cat, 0) == 0:
            continue
        categories[cat] = {
            "bytes": cat_bytes[cat],
            "share": round(cat_bytes[cat] / total, 4) if total else 0.0,
            "n_ops": cat_ops[cat],
        }
    top_hlos = sorted(by_name.values(), key=lambda r: -r["bytes"])[:top]
    for r in top_hlos:
        r["share_of_bytes"] = round(r["bytes"] / total, 4) if total \
            else 0.0

    report = {
        "source": os.path.basename(path),
        "capture_kind": "hlo_module",
        "total_bytes": total,
        "n_instructions": n_instr,
        # while bodies are charged ONCE; a loopy capture must fold its
        # trip count in by hand (the decode analysis multiplies by
        # max_len) — 0 means the byte table is exact
        "while_instructions": n_while,
        # the footprint pin: the biggest single tensor the program
        # materializes (dense longctx: the [B,H,T,T] scores; flash:
        # a [B,H,T,block_k] tile)
        "largest_output_bytes": largest_output,
        "attention_inherited_ops": inherited,
        "shares": {c: v["share"] for c, v in categories.items()},
        "categories": categories,
        "top_hlos": top_hlos,
    }
    stem = path
    for suf in (".hlo.txt.gz", ".hlo.txt"):
        if stem.endswith(suf):
            stem = stem[: -len(suf)]
            break
    sibling = stem + ".report.json"
    if os.path.exists(sibling):
        with open(sibling) as f:
            report["capture_report"] = json.load(f)
    return report


def render_hlo_text(report: dict) -> str:
    lines = [
        f"== hlo byte attribution: {report['source']} ==",
        f"instructions={report['n_instructions']} "
        f"total={report['total_bytes'] / 1e6:.1f} MB "
        f"(while bodies counted once: "
        f"{report['while_instructions']} while op(s))",
        "",
        f"{'category':16s} {'share':>7s} {'MB':>10s} {'ops':>6s}",
    ]
    cats = sorted(
        report["categories"].items(), key=lambda kv: -kv[1]["bytes"]
    )
    for cat, v in cats:
        lines.append(
            f"{cat:16s} {v['share'] * 100:6.2f}% "
            f"{v['bytes'] / 1e6:10.2f} {v['n_ops']:6d}"
        )
    lines += [
        "",
        f"top {len(report['top_hlos'])} HLOs by bytes:",
        f"{'hlo':40s} {'category':15s} {'share':>7s} {'MB':>10s}",
    ]
    for r in report["top_hlos"]:
        lines.append(
            f"{r['name'][:40]:40s} {r['category']:15s} "
            f"{r['share_of_bytes'] * 100:6.2f}% {r['bytes'] / 1e6:10.2f}"
        )
    return "\n".join(lines)


def render_text(report: dict) -> str:
    lines = [
        f"== trace attribution: {report['source']} ==",
        f"devices={report['devices']} steps={report['steps']} "
        f"step={report['step_ms']} ms  wall={report['wall_us']:.0f} us "
        f"busy={report['device_busy_us']:.0f} us "
        f"bubble={report['shares'].get('bubble', 0) * 100:.2f}%",
        "",
        f"{'category':16s} {'share':>7s} {'time_ms':>9s} {'ops':>6s} "
        f"{'GB/s':>8s}",
    ]
    cats = sorted(
        report["categories"].items(), key=lambda kv: -kv[1]["time_us"]
    )
    for cat, v in cats:
        lines.append(
            f"{cat:16s} {v['share'] * 100:6.2f}% "
            f"{v['time_us'] / 1e3:9.2f} {v['n_ops']:6d} "
            f"{v['achieved_gbps']:8.1f}"
        )
    lines.append(
        f"{'bubble':16s} {report['shares'].get('bubble', 0) * 100:6.2f}%"
    )
    lines += [
        "",
        f"top {len(report['top_hlos'])} HLOs by device time "
        f"(of busy; GB/s vs HBM peak {report['hbm_peak_gbps']:.0f}):",
        f"{'hlo':34s} {'category':15s} {'share':>7s} {'time_ms':>9s} "
        f"{'n':>4s} {'GB/s':>8s}",
    ]
    for r in report["top_hlos"]:
        lines.append(
            f"{r['name'][:34]:34s} {r['category']:15s} "
            f"{r['share_of_busy'] * 100:6.2f}% "
            f"{r['time_us'] / 1e3:9.2f} {r['count']:4d} "
            f"{r['achieved_gbps']:8.1f}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "trace",
        help="trace.json[.gz] (profiler capture) or hlo.txt[.gz] "
             "(compiled-module capture)",
    )
    ap.add_argument("--out", default="",
                    help="write the attribution report here "
                         "(default: <trace stem>.attrib.json)")
    ap.add_argument("--no-out", action="store_true",
                    help="print only, write no report file")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--json", action="store_true",
                    help="print the JSON report instead of the table")
    args = ap.parse_args(argv)

    is_hlo = args.trace.endswith((".hlo.txt", ".hlo.txt.gz"))
    if is_hlo:
        report = analyze_hlo(args.trace, top=args.top)
    else:
        report = analyze(args.trace, top=args.top)
    if args.json:
        print(json.dumps(report, indent=2))
    elif is_hlo:
        print(render_hlo_text(report))
    else:
        print(render_text(report))
    if not args.no_out:
        out = args.out
        if not out:
            stem = args.trace
            for suf in (".hlo.txt.gz", ".hlo.txt", ".trace.json.gz",
                        ".trace.json", ".json.gz", ".json"):
                if stem.endswith(suf):
                    stem = stem[: -len(suf)]
                    break
            out = stem + ".attrib.json"
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"\nwrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
