#!/usr/bin/env python
"""Attribute device time (or compiled-program bytes) to HLO categories.

The tool behind ROADMAP item 2's attribution requirement: given a
profiler capture (the Chrome-trace `trace.json.gz` that
`jax.profiler`/`tools/profile_resnet.py` writes from the XPlane — the
committed `tools/traces/*.trace.json.gz` files), name where the
device's wall time goes:

- per-category device-time shares — **conv**, **gemm**, **attention**
  (ops inside the attention named_scopes and Pallas/Mosaic
  custom-call attention kernels — so flash time is attributed, not
  lumped into "other"), **bn_elementwise** (BN statistics,
  activations, reductions, loop fusions), **layout** (copies,
  transposes, dtype converts, HBM<->scratch slices), **collective**,
  **infeed**, **other** — plus **bubble** = wall minus device-busy
  (union of op intervals inside the stepped window), the share no
  per-op table can show;
- a top-N HLOs-by-total-time table with per-op achieved HBM
  bandwidth (`bytes_accessed / duration`), which separates
  memory-bound fusions from compute-bound ones at a glance;
- a machine-readable `*.attrib.json` report, committed next to the
  trace so the roofline campaign argues from evidence.

Works on `.json` / `.json.gz` Chrome traces. Raw `.xplane.pb`
captures must first be exported to a trace (TensorBoard's profile
plugin or `tensorflow.python.profiler` does this); the committed
captures are already trace.json.gz.

**HLO-module captures** (`*.hlo.txt[.gz]`, written by
tools/profile_longctx.py or bench.write_decode_hlo): when no device
profiler is reachable (this container has no TPU and the CPU profiler
emits no per-op plane), the same classifier attributes the REAL
compiled program's **bytes** statically — every top-level instruction
is charged its operand + output bytes (fusion internals excluded:
only fusion boundaries cross HBM), bucketed by the same categories.
That is how the committed longctx captures prove the flash byte
removal per-instruction: the dense program's attention category
carries the O(T^2) score tensors, the flash program's does not
(PERF.md round 8). While-loop bodies are counted once (the longctx
captures are loop-free by construction — the blocked flash unrolls at
the capture shape; the decode capture's per-iteration bytes are
multiplied by max_len in the PERF analysis, and the report carries
`while_instructions` so the caveat is machine-visible).

Usage:
    python tools/trace_attribution.py TRACE.json[.gz]
        [--out X.attrib.json] [--top 10] [--json]
    python tools/trace_attribution.py CAPTURE.hlo.txt[.gz] [...]

No jax / device runtime needed — pure stdlib, runs anywhere.
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
from collections import defaultdict

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# the HLO parser + op classifier live in paddle_tpu/analysis/hlo_text
# (ISSUE 13): one parser shared with the static auditor
# (analysis/hlo_audit.py, tools/framework_lint.py), so the audit
# argues about the exact bytes this tool attributes. Names re-exported
# here for back-compat with existing callers/tests.
from paddle_tpu.analysis.hlo_text import (  # noqa: E402
    CATEGORIES,
    analyze_hlo,
    classify,
)

# v5e reference numbers for the table's context columns
HBM_PEAK_GBPS = 819.0


def _load_trace(path: str) -> dict:
    if path.endswith((".pb", ".xplane.pb")):
        raise SystemExit(
            f"{path}: raw XPlane protobuf — export it to a Chrome "
            "trace.json(.gz) first (TensorBoard profile plugin); the "
            "committed captures under tools/traces/ already are."
        )
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def _union_us(intervals) -> float:
    """Total covered length of possibly-overlapping [start, end)."""
    total = 0.0
    cur_s = cur_e = None
    for s, e in sorted(intervals):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    if cur_e is not None:
        total += cur_e - cur_s
    return total


def analyze(path: str, top: int = 10) -> dict:
    """Parse one trace and return the attribution report dict."""
    doc = _load_trace(path)
    evs = doc.get("traceEvents", [])
    proc_names: dict = {}
    thread_names: dict = {}
    for e in evs:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e["pid"]] = e["args"]["name"]
        elif e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = e["args"]["name"]

    device_pids = {
        pid for pid, n in proc_names.items()
        if n.startswith("/device:")
    }
    if not device_pids:
        raise SystemExit(f"{path}: no /device:* process in trace")

    op_tids = {
        k for k, n in thread_names.items()
        if k[0] in device_pids and n == "XLA Ops"
    }
    step_tids = {
        k for k, n in thread_names.items()
        if k[0] in device_pids and n == "Steps"
    }

    ops = [
        e for e in evs
        if e.get("ph") == "X" and (e["pid"], e.get("tid")) in op_tids
    ]
    steps = [
        e for e in evs
        if e.get("ph") == "X" and (e["pid"], e.get("tid")) in step_tids
    ]
    if not ops:
        raise SystemExit(f"{path}: no XLA Ops events")

    # the measured window: the REAL steps (the profiler also logs
    # sub-ms pseudo-steps for trailing host fetches — drop anything
    # under half the longest step)
    if steps:
        max_dur = max(s["dur"] for s in steps)
        real = [s for s in steps if s["dur"] >= 0.5 * max_dur]
        w0 = min(s["ts"] for s in real)
        w1 = max(s["ts"] + s["dur"] for s in real)
        n_steps = len(real)
        step_ms = sum(s["dur"] for s in real) / n_steps / 1e3
    else:
        w0 = min(o["ts"] for o in ops)
        w1 = max(o["ts"] + o["dur"] for o in ops)
        n_steps, step_ms = 0, None
    wall_us = w1 - w0

    in_window = [
        o for o in ops if o["ts"] < w1 and o["ts"] + o["dur"] > w0
    ]
    busy_us = _union_us(
        (max(o["ts"], w0), min(o["ts"] + o["dur"], w1))
        for o in in_window
    )

    cat_time = defaultdict(float)
    cat_ops = defaultdict(int)
    cat_bytes = defaultdict(int)
    by_name: dict = {}
    for o in in_window:
        args = o.get("args", {})
        cat = classify(o["name"], args.get("hlo_category", ""),
                       args.get("long_name", ""))
        dur = o["dur"]
        nbytes = int(args.get("bytes_accessed", 0) or 0)
        cat_time[cat] += dur
        cat_ops[cat] += 1
        cat_bytes[cat] += nbytes
        rec = by_name.setdefault(
            o["name"],
            {"name": o["name"], "category": cat, "time_us": 0.0,
             "count": 0, "bytes_accessed": 0},
        )
        rec["time_us"] += dur
        rec["count"] += 1
        rec["bytes_accessed"] += nbytes

    # overlapping (async) ops can make the per-category sum exceed the
    # busy union; scale so category shares + bubble sum to exactly 1
    raw_sum = sum(cat_time.values())
    scale = busy_us / raw_sum if raw_sum > busy_us > 0 else 1.0

    categories = {}
    for cat in CATEGORIES:
        t = cat_time.get(cat, 0.0) * scale
        if cat_ops.get(cat, 0) == 0:
            continue
        categories[cat] = {
            "time_us": round(t, 1),
            "share": round(t / wall_us, 4) if wall_us else 0.0,
            "n_ops": cat_ops[cat],
            "bytes_accessed": cat_bytes[cat],
            "achieved_gbps": round(
                cat_bytes[cat] / (cat_time[cat] * 1e-6) / 1e9, 1
            ) if cat_time[cat] else 0.0,
        }

    bubble_us = max(wall_us - busy_us, 0.0)
    shares = {c: v["share"] for c, v in categories.items()}
    shares["bubble"] = round(bubble_us / wall_us, 4) if wall_us else 0.0

    top_hlos = sorted(
        by_name.values(), key=lambda r: -r["time_us"]
    )[:top]
    for r in top_hlos:
        r["time_us"] = round(r["time_us"], 1)
        r["share_of_busy"] = round(
            r["time_us"] / busy_us, 4
        ) if busy_us else 0.0
        r["avg_us"] = round(r["time_us"] / r["count"], 1)
        r["achieved_gbps"] = round(
            r["bytes_accessed"] / (r["time_us"] * 1e-6) / 1e9, 1
        ) if r["time_us"] else 0.0

    report = {
        "source": os.path.basename(path),
        "capture_kind": "profiler_trace",
        "devices": len(device_pids),
        "steps": n_steps,
        "step_ms": round(step_ms, 3) if step_ms else None,
        "wall_us": round(wall_us, 1),
        "device_busy_us": round(busy_us, 1),
        "bubble_us": round(bubble_us, 1),
        "overlap_scale": round(scale, 6),
        "hbm_peak_gbps": HBM_PEAK_GBPS,
        "shares": shares,
        "categories": categories,
        "top_hlos": top_hlos,
    }
    # the profiler run's own summary (flops, bytes, img/s) sits next
    # to the trace as <stem>.report.json — fold it in for context
    stem = path
    for suf in (".trace.json.gz", ".trace.json", ".json.gz", ".json"):
        if stem.endswith(suf):
            stem = stem[: -len(suf)]
            break
    sibling = stem + ".report.json"
    if os.path.exists(sibling):
        with open(sibling) as f:
            report["capture_report"] = json.load(f)
    return report


def render_hlo_text(report: dict) -> str:
    lines = [
        f"== hlo byte attribution: {report['source']} ==",
        f"instructions={report['n_instructions']} "
        f"total={report['total_bytes'] / 1e6:.1f} MB "
        f"(while bodies counted once: "
        f"{report['while_instructions']} while op(s))",
        "",
        f"{'category':16s} {'share':>7s} {'MB':>10s} {'ops':>6s}",
    ]
    cats = sorted(
        report["categories"].items(), key=lambda kv: -kv[1]["bytes"]
    )
    for cat, v in cats:
        lines.append(
            f"{cat:16s} {v['share'] * 100:6.2f}% "
            f"{v['bytes'] / 1e6:10.2f} {v['n_ops']:6d}"
        )
    lines += [
        "",
        f"top {len(report['top_hlos'])} HLOs by bytes:",
        f"{'hlo':40s} {'category':15s} {'share':>7s} {'MB':>10s}",
    ]
    for r in report["top_hlos"]:
        lines.append(
            f"{r['name'][:40]:40s} {r['category']:15s} "
            f"{r['share_of_bytes'] * 100:6.2f}% {r['bytes'] / 1e6:10.2f}"
        )
    return "\n".join(lines)


def render_text(report: dict) -> str:
    lines = [
        f"== trace attribution: {report['source']} ==",
        f"devices={report['devices']} steps={report['steps']} "
        f"step={report['step_ms']} ms  wall={report['wall_us']:.0f} us "
        f"busy={report['device_busy_us']:.0f} us "
        f"bubble={report['shares'].get('bubble', 0) * 100:.2f}%",
        "",
        f"{'category':16s} {'share':>7s} {'time_ms':>9s} {'ops':>6s} "
        f"{'GB/s':>8s}",
    ]
    cats = sorted(
        report["categories"].items(), key=lambda kv: -kv[1]["time_us"]
    )
    for cat, v in cats:
        lines.append(
            f"{cat:16s} {v['share'] * 100:6.2f}% "
            f"{v['time_us'] / 1e3:9.2f} {v['n_ops']:6d} "
            f"{v['achieved_gbps']:8.1f}"
        )
    lines.append(
        f"{'bubble':16s} {report['shares'].get('bubble', 0) * 100:6.2f}%"
    )
    lines += [
        "",
        f"top {len(report['top_hlos'])} HLOs by device time "
        f"(of busy; GB/s vs HBM peak {report['hbm_peak_gbps']:.0f}):",
        f"{'hlo':34s} {'category':15s} {'share':>7s} {'time_ms':>9s} "
        f"{'n':>4s} {'GB/s':>8s}",
    ]
    for r in report["top_hlos"]:
        lines.append(
            f"{r['name'][:34]:34s} {r['category']:15s} "
            f"{r['share_of_busy'] * 100:6.2f}% "
            f"{r['time_us'] / 1e3:9.2f} {r['count']:4d} "
            f"{r['achieved_gbps']:8.1f}"
        )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "trace",
        help="trace.json[.gz] (profiler capture) or hlo.txt[.gz] "
             "(compiled-module capture)",
    )
    ap.add_argument("--out", default="",
                    help="write the attribution report here "
                         "(default: <trace stem>.attrib.json)")
    ap.add_argument("--no-out", action="store_true",
                    help="print only, write no report file")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--json", action="store_true",
                    help="print the JSON report instead of the table")
    args = ap.parse_args(argv)

    is_hlo = args.trace.endswith((".hlo.txt", ".hlo.txt.gz"))
    if is_hlo:
        report = analyze_hlo(args.trace, top=args.top)
    else:
        report = analyze(args.trace, top=args.top)
    if args.json:
        print(json.dumps(report, indent=2))
    elif is_hlo:
        print(render_hlo_text(report))
    else:
        print(render_text(report))
    if not args.no_out:
        out = args.out
        if not out:
            stem = args.trace
            for suf in (".hlo.txt.gz", ".hlo.txt", ".trace.json.gz",
                        ".trace.json", ".json.gz", ".json"):
                if stem.endswith(suf):
                    stem = stem[: -len(suf)]
                    break
            out = stem + ".attrib.json"
        with open(out, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"\nwrote {out}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
