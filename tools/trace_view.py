#!/usr/bin/env python
"""Reconstruct per-request critical paths from span streams/bundles.

The read side of ISSUE 11's tracing: given one or more JSONL event
streams (`obs.metrics.enable_event_stream` output, `kind == "span"`
records) and/or flight-recorder bundles (`obs.flight_recorder`
JSON, schema paddle-tpu-flight-bundle/v1), this tool

- groups spans by `trace_id` — streams from SEVERAL processes can be
  passed together, so a trace that crosses the client/server or
  trainer/master boundary reassembles into one tree;
- picks each trace's root (the span whose parent is not in the trace;
  longest wins when a trace has several, e.g. a trainer trace made of
  many sampled train.step roots);
- walks the tree into a **critical path**: the time-ordered leaf
  segments that cover the root's duration, with uncovered gaps
  attributed to the enclosing span as "<name> (self)" — the
  "where did THIS request's time go" answer;
- prints the top-N slowest traces (or one trace by id) with their
  paths, or emits the whole analysis as JSON.

Pure stdlib, no jax (same contract as trace_attribution.py): span
analytics must run on any machine the stream was copied to.

Usage:
    python tools/trace_view.py FILE [FILE ...]
        [--top N] [--trace TRACE_ID] [--json]
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict

BUNDLE_SCHEMA = "paddle-tpu-flight-bundle/v1"
INCIDENT_SCHEMA = "paddle-tpu-fleet-incident/v1"


def _incident_events(doc: dict) -> list:
    """Stitch a fleet-incident bundle's events: the router's own ring
    plus every replica's flightz ring dump — the cross-process span
    set one trace_id ties back together."""
    events = list(doc.get("events", []))
    for ring in (doc.get("replicas") or {}).values():
        if isinstance(ring, dict):
            events.extend(ring.get("events", []))
    return events


def load_spans(path: str) -> list:
    """Spans from a JSONL stream, a flight-recorder bundle, or a
    fleet-incident bundle; the format is sniffed from content, not
    the filename."""
    with open(path) as f:
        first = f.read(1)
        f.seek(0)
        if first != "{":
            return []
        # try one-document bundle first; fall back to JSONL
        try:
            doc = json.load(f)
        except ValueError:
            doc = None
        if isinstance(doc, dict) and doc.get("schema") == BUNDLE_SCHEMA:
            events = doc.get("events", [])
        elif isinstance(doc, dict) and doc.get("schema") == INCIDENT_SCHEMA:
            events = _incident_events(doc)
        elif isinstance(doc, dict):
            events = [doc]
        else:
            f.seek(0)
            events = []
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                try:
                    events.append(json.loads(ln))
                except ValueError:
                    continue
    return [e for e in events
            if isinstance(e, dict) and e.get("kind") == "span"]


def group_traces(spans: list) -> dict:
    traces = defaultdict(list)
    for s in spans:
        tid = s.get("trace_id")
        if tid and s.get("span_id"):
            traces[tid].append(s)
    return dict(traces)


def _root_of(group: list):
    # root semantics are mirrored in paddle_tpu/__main__.py
    # _metrics_spans (this file stays standalone-stdlib, so it is not
    # importable from there without breaking portability) — change
    # both together
    ids = {s["span_id"] for s in group}
    roots = [s for s in group if s.get("parent_id", "") not in ids]
    pool = roots or group
    return max(pool, key=lambda s: float(s.get("dur_s", 0.0)))


def critical_path(group: list) -> dict:
    """One trace's analysis: root, total duration, and the ordered
    leaf segments covering it. Children are clipped to their parent's
    interval and to each other (clock skew between processes shows up
    as overlap, never as negative segments)."""
    children = defaultdict(list)
    ids = {s["span_id"] for s in group}
    for s in group:
        p = s.get("parent_id", "")
        if p and p in ids and p != s["span_id"]:
            children[p].append(s)
    root = _root_of(group)
    segments = []

    def walk(span, lo, hi):
        t0 = float(span.get("ts", 0.0))
        t1 = t0 + float(span.get("dur_s", 0.0))
        t0, t1 = max(t0, lo), min(t1, hi)
        if t1 <= t0 and span is not root:
            return
        kids = sorted(
            children.get(span["span_id"], ()),
            key=lambda s: float(s.get("ts", 0.0)),
        )
        if not kids:
            segments.append({
                "name": span.get("name", "?"),
                "dur_s": max(t1 - t0, 0.0),
                "status": span.get("status", "ok"),
            })
            return
        cur = t0
        for k in kids:
            k0 = float(k.get("ts", 0.0))
            if k0 > cur:
                segments.append({
                    "name": f"{span.get('name', '?')} (self)",
                    "dur_s": k0 - cur,
                    "status": span.get("status", "ok"),
                })
            walk(k, max(cur, t0), t1)
            cur = max(cur, k0 + float(k.get("dur_s", 0.0)))
        if cur < t1:
            segments.append({
                "name": f"{span.get('name', '?')} (self)",
                "dur_s": t1 - cur,
                "status": span.get("status", "ok"),
            })

    walk(root, float("-inf"), float("inf"))
    total = float(root.get("dur_s", 0.0))
    for seg in segments:
        seg["dur_ms"] = round(seg.pop("dur_s") * 1e3, 3)
        seg["frac"] = round(
            seg["dur_ms"] / (total * 1e3), 4
        ) if total > 0 else 0.0
    return {
        "trace_id": root.get("trace_id"),
        "root": root.get("name"),
        "status": root.get("status", "ok"),
        "dur_ms": round(total * 1e3, 3),
        "spans": len(group),
        "critical_path": segments,
    }


def analyze(paths: list, top: int = 10,
            trace_id: str = None) -> dict:
    spans = []
    for p in paths:
        spans.extend(load_spans(p))
    traces = group_traces(spans)
    if trace_id is not None:
        matches = [t for t in traces if t.startswith(trace_id)]
        if not matches:
            raise SystemExit(f"trace {trace_id!r} not found in "
                             f"{len(traces)} traces")
        picked = {t: traces[t] for t in matches}
    else:
        picked = traces
    analyzed = sorted(
        (critical_path(g) for g in picked.values()),
        key=lambda a: a["dur_ms"], reverse=True,
    )
    return {
        "files": paths,
        "span_count": len(spans),
        "trace_count": len(traces),
        "traces": analyzed[: max(top, 1)],
    }


def render(report: dict) -> str:
    lines = [
        f"{report['span_count']} spans / {report['trace_count']} "
        f"traces from {len(report['files'])} file(s); "
        f"slowest {len(report['traces'])}:"
    ]
    for t in report["traces"]:
        lines.append(
            f"trace {t['trace_id'][:16]:16s} root={t['root']:<22s} "
            f"{t['dur_ms']:10.3f} ms  {t['spans']:3d} spans  "
            f"status={t['status']}"
        )
        for seg in t["critical_path"]:
            lines.append(
                f"    {seg['name']:32s} {seg['dur_ms']:10.3f} ms "
                f"{100 * seg['frac']:6.1f}%"
                + ("" if seg["status"] == "ok"
                   else f"  [{seg['status']}]")
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("files", nargs="+",
                    help="JSONL span streams and/or flight bundles")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--trace", default=None,
                    help="show one trace (id prefix ok)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = analyze(args.files, top=args.top, trace_id=args.trace)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
