"""LM prefill/decode capture tool (ISSUE 19): compile the two paged
KV-cache generation programs at their committed audit configs and
write the captures next to the committed traces:

  tools/traces/lm_prefill_t1024_flash.hlo.txt.gz   bucketed prefill
      (full flash causal forward + page scatter + fused first top-k)
  tools/traces/lm_decode_b4.hlo.txt.gz             fused decode step
      (page gather -> 1-token forward -> in-place cache append ->
      argmax + score update, ONE dispatch per token)

plus a `.report.json` sibling per capture carrying the audit inputs
(`attn_impl`, `seq_len`, `donated_arg_buffers` — the two pool buffers
the append must alias in place). `tools/framework_lint.py hlo-audit
--write-audit` then pins each capture against its
tools/traces/audit_budgets.json policy: byte budgets, zero host
transfers inside the programs, the pool-donation check, and no [T,T]
materialization on the flash prefill at T=1024.

Compilation allocates no live model state beyond the toy-sized params
and the page pool (~8 MB/buffer), so both captures build on CPU — the
same no-TPU-needed discipline as tools/profile_longctx.py.

Usage: python tools/profile_lm.py [--out-dir tools/traces]
"""

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser(
        description="write the committed LM prefill/decode captures"
    )
    ap.add_argument("--out-dir", default="tools/traces")
    args = ap.parse_args()

    from bench import write_lm_captures

    os.makedirs(args.out_dir, exist_ok=True)
    for path in write_lm_captures(args.out_dir):
        print(path)
    return 0


if __name__ == "__main__":
    sys.exit(main())
