#!/usr/bin/env python
"""Render fleet incident bundles (ISSUE 17).

The read side of the fleet observability plane: given a
`paddle-tpu-fleet-incident/v1` JSON bundle (written by the router's
FleetMonitor when a burn-rate alert fires), this tool

- prints the incident header: reason, active alerts with their burn
  rates / p99s, the offending replica the alerts implicate, and the
  router's per-replica state table at trigger time;
- summarizes the merged fleet view (admitted/shed counter deltas,
  fleet p50/p99 from the merged le-buckets) from the bundle's scrape
  history;
- stitches the span events — the router's own flight ring PLUS every
  replica's `flightz` ring dump — and reuses `trace_view`'s
  grouping/critical-path machinery on the combined set, marking each
  trace with the set of processes it crossed. A cross-process trace
  is one whose spans came from more than one ring (router + replica,
  or two replicas), i.e. the request path the incident interrupted.

Pure stdlib + sibling `trace_view` (same portability contract: copy
the two files to any box and they run — no jax, no package install).

Usage:
    python tools/fleet_view.py BUNDLE [--top N] [--trace ID] [--json]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import trace_view  # noqa: E402  (sibling import, kept standalone)

INCIDENT_SCHEMA = "paddle-tpu-fleet-incident/v1"


def load_bundle(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or doc.get("schema") != INCIDENT_SCHEMA:
        raise SystemExit(
            f"{path}: not a fleet incident bundle "
            f"(schema={doc.get('schema') if isinstance(doc, dict) else '?'!r};"
            f" expected {INCIDENT_SCHEMA})"
        )
    return doc


def stitched_spans(doc: dict) -> list:
    """All span events in the bundle, each annotated with the process
    it came from: `"router"` for the router's own ring, the replica
    name for a flightz ring. The annotation (`_origin`) is what makes
    "this trace crossed N processes" checkable after stitching."""
    spans = []
    for e in doc.get("events", []):
        if isinstance(e, dict) and e.get("kind") == "span":
            spans.append(dict(e, _origin="router"))
    for name, ring in (doc.get("replicas") or {}).items():
        if not isinstance(ring, dict):
            continue
        for e in ring.get("events", []):
            if isinstance(e, dict) and e.get("kind") == "span":
                spans.append(dict(e, _origin=name))
    return spans


def analyze(path: str, top: int = 10, trace_id: str = None) -> dict:
    doc = load_bundle(path)
    spans = stitched_spans(doc)
    traces = trace_view.group_traces(spans)
    if trace_id is not None:
        matches = [t for t in traces if t.startswith(trace_id)]
        if not matches:
            raise SystemExit(f"trace {trace_id!r} not found in "
                             f"{len(traces)} traces")
        traces = {t: traces[t] for t in matches}
    analyzed = []
    for group in traces.values():
        a = trace_view.critical_path(group)
        a["processes"] = sorted({s.get("_origin", "?") for s in group})
        a["cross_process"] = len(a["processes"]) > 1
        analyzed.append(a)
    # cross-process traces first (they are what an incident is about),
    # then by duration
    analyzed.sort(key=lambda a: (not a["cross_process"], -a["dur_ms"]))
    fleet = doc.get("fleet") or {}
    delta = fleet.get("delta") or {}
    merged = fleet.get("merged") or {}
    return {
        "bundle": path,
        "schema": doc.get("schema"),
        "reason": doc.get("reason"),
        "ts": doc.get("ts"),
        "alerts": doc.get("alerts", []),
        "offending": doc.get("offending"),
        "states": doc.get("states", {}),
        "replica_rings": {
            name: {
                "enabled": bool(ring.get("enabled", False)),
                "events": len(ring.get("events", [])),
            } if isinstance(ring, dict) else {"enabled": False,
                                              "events": 0}
            for name, ring in (doc.get("replicas") or {}).items()
        },
        "fleet_quantiles": _fleet_quantiles(delta or merged),
        "span_count": len(spans),
        "trace_count": len(traces),
        "traces": analyzed[: max(top, 1)],
    }


def _fleet_quantiles(snapshot: dict) -> dict:
    """p50/p99 per merged admitted-latency series (one per model)."""
    out = {}
    for name, h in (snapshot.get("histograms") or {}).items():
        if not name.split("{", 1)[0].endswith("admitted_latency_s"):
            continue
        out[name] = {}
        for q, key in ((0.50, "p50_ms"), (0.99, "p99_ms")):
            v = _quantile(h, q)
            out[name][key] = round(v * 1e3, 3) if v is not None \
                else None
    return out


def _quantile(h: dict, q: float):
    # the upper-bound bucket-walk estimate, duplicated from
    # paddle_tpu/obs/aggregate.py::quantile — this file must stay
    # standalone-stdlib (copyable next to trace_view.py without the
    # package); change both together
    buckets = h.get("buckets")
    bounds = h.get("bounds")
    if not buckets or bounds is None:
        return None
    total = sum(buckets)
    if total <= 0:
        return None
    rank = max(int(math.ceil(q * total)), 1)
    cum = 0
    for i, n in enumerate(buckets):
        cum += n
        if cum >= rank:
            if i < len(bounds):
                return float(bounds[i])
            break
    mx = h.get("max")
    return float(mx) if mx is not None else float(bounds[-1])


def render(report: dict) -> str:
    lines = [
        f"fleet incident {report['bundle']}",
        f"  reason={report['reason']}  ts={report['ts']}  "
        f"offending={report['offending'] or '?'}",
    ]
    for a in report["alerts"]:
        lines.append("  alert: " + json.dumps(a, sort_keys=True))
    if report["states"]:
        lines.append("  replica states at trigger:")
        for name, st in sorted(report["states"].items()):
            lines.append(
                f"    {name:12s} breaker={st.get('breaker'):9s} "
                f"queue={st.get('queue_depth')} "
                f"inflight={st.get('inflight')} "
                f"stale={st.get('stale')} "
                f"scrape_failures={st.get('scrape_failures')}"
            )
    if report["fleet_quantiles"]:
        lines.append("  fleet latency (merged buckets, last delta):")
        for name, qs in sorted(report["fleet_quantiles"].items()):
            lines.append(f"    {name}: p50={qs['p50_ms']} ms "
                         f"p99={qs['p99_ms']} ms")
    rings = report["replica_rings"]
    ring_txt = ", ".join(
        f"{n}={r['events']}ev" + ("" if r["enabled"] else " (off)")
        for n, r in sorted(rings.items())
    )
    lines.append(f"  rings: router + {ring_txt}")
    lines.append(
        f"  {report['span_count']} stitched spans / "
        f"{report['trace_count']} traces; top {len(report['traces'])}:"
    )
    for t in report["traces"]:
        procs = "+".join(t["processes"])
        tag = "cross-process " if t["cross_process"] else ""
        lines.append(
            f"  trace {t['trace_id'][:16]:16s} root={t['root']:<20s} "
            f"{t['dur_ms']:10.3f} ms  {tag}[{procs}]  "
            f"status={t['status']}"
        )
        for seg in t["critical_path"]:
            lines.append(
                f"      {seg['name']:32s} {seg['dur_ms']:10.3f} ms "
                f"{100 * seg['frac']:6.1f}%"
                + ("" if seg["status"] == "ok"
                   else f"  [{seg['status']}]")
            )
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("bundle", help="fleet incident bundle (JSON)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--trace", default=None,
                    help="show one trace (id prefix ok)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    report = analyze(args.bundle, top=args.top, trace_id=args.trace)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(render(report))
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
