"""Multi-chip HLO capture tool (ISSUE 15): compile the sharded
programs the CPU-mesh smokes measure (bench_multichip) and write
per-row captures next to the committed traces:

  tools/traces/<row>.hlo.txt.gz     compiled partitioned HLO module
  tools/traces/<row>.report.json    mesh/shape context + the parsed
                                    collective byte table

Rows (all compile-only — no tensor is ever materialized, so the full
T=32768 ring/ulysses programs capture fine on a laptop):

  mc_longctx_ring_t32768     ring-sharded flash train grad step
  mc_longctx_ulysses_t32768  ulysses (all-to-all) flash train step
  mc_dp_train                data-parallel train step (grad allreduce)
  mc_sparse_lookup           row-sharded embedding gather + psum
  mc_sparse_update           its backward: the row-sparse scatter
  mc_sparse_shard_step       elastic hot-cache tier: fused sparse
                             lookup+update step over per-shard caches

The committed captures are what `tools/framework_lint.py spmd-audit`
(analysis/spmd_audit.py) audits against tools/traces/
audit_budgets.json: replication floor, collective byte budgets,
schedule safety. After an INTENTIONAL sharding/perf change, re-run
this tool, re-baseline the budgets by hand, and refresh the committed
*.audit.json with `framework_lint.py spmd-audit --write-audit`.

Usage: python tools/profile_multichip.py [--rows a,b,...]
       [--devices 8] [--t 32768] [--out-dir tools/traces]
       [--synthetic]   # scaled-down shapes (CI smoke; not committed)
"""

import argparse
import gzip
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

ROWS = (
    "mc_longctx_ring_t32768",
    "mc_longctx_ulysses_t32768",
    "mc_dp_train",
    "mc_sparse_lookup",
    "mc_sparse_update",
    "mc_sparse_shard_step",
)


def _ensure_cpu_mesh(n: int) -> None:
    """Force an n-virtual-device CPU backend BEFORE jax initializes
    (same trick as bench_multichip's re-exec, minus the re-exec: this
    tool owns its process from main())."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def _write(out_dir, row, text, report):
    from paddle_tpu.analysis import hlo_text as _hlo

    lines = text.splitlines()
    stem = os.path.join(out_dir, row)
    with gzip.open(stem + ".hlo.txt.gz", "wt") as f:
        f.write(text)
    report = {
        **report,
        "num_partitions": _hlo.num_partitions(text),
        # the parsed collective byte table — the baseline the
        # collective byte budgets in audit_budgets.json pin (+~10%)
        "collectives": _hlo.collective_summary(
            _hlo.parse_collectives(lines)
        ),
    }
    with open(stem + ".report.json", "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps({"row": row, **report}))


def capture_longctx(mode, t, n_dev, out_dir, synthetic):
    """The mc_longctx ring/ulysses rows: the SAME model
    bench_multichip._bench_longctx_sharded measures (bench.py
    longctx_conf with seq_parallel=mode), time dim sharded over the
    mesh `seq` axis, fwd+bwd grad step."""
    import jax

    from bench import longctx_conf, longctx_feed
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.core.mesh import (
        DATA_AXIS, SEQ_AXIS, make_mesh, set_mesh,
    )
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer
    from paddle_tpu.parallel.dp import TrainStep

    bs = 1
    if synthetic:
        t_run, d, heads, layers, classes = 32 * n_dev, 64, n_dev, 1, 64
    else:
        t_run, d, heads, layers, classes = t, 512, 8, 2, 512
    conf = longctx_conf(
        t_run, d, heads, layers, classes,
        attn_impl="flash", seq_parallel=mode,
    )
    feed = longctx_feed(bs, t_run, classes)
    mesh = make_mesh({DATA_AXIS: 1, SEQ_AXIS: n_dev})
    set_mesh(mesh)  # the ring/ulysses layers resolve it via get_mesh
    try:
        net = Network(conf)
        params = net.init_params(jax.random.key(0))
        opt = create_optimizer(
            OptimizationConf(learning_method="adam",
                             learning_rate=1e-3),
            net.param_confs,
        )
        step = TrainStep(net, opt, mesh=mesh, donate=False)
        params, opt_state, state = step.place(
            params, opt.init_state(params), net.init_state()
        )
        # aot() compiles without executing — the T=32768 program is
        # captured, never run
        _run, text = step.aot(
            params, opt_state, state, feed, 0, jax.random.key(1)
        )
    finally:
        set_mesh(make_mesh())
    row = f"mc_longctx_{mode}_t{t_run}"
    _write(out_dir, row, text, {
        "model": "bench.longctx_conf full train step "
                 "(the bench_multichip mc_longctx rows)",
        "seq_parallel": mode,
        "attn_impl": "flash",
        "batch_size": bs,
        "seq_len": t_run,
        "d_model": d,
        "heads": heads,
        "layers": layers,
        "mesh": {"data": 1, "seq": n_dev},
        "backend": jax.default_backend(),
        "synthetic": synthetic,
    })


def capture_dp_train(n_dev, out_dir, synthetic):
    """The data-parallel train step: batch sharded over `data`, params
    replicated BY DESIGN (so no replication floor in its policy) —
    the captured invariant is the gradient all-reduce."""
    import numpy as np

    import jax

    from paddle_tpu.core.arg import id_arg
    from paddle_tpu.core.config import OptimizationConf
    from paddle_tpu.core.mesh import DATA_AXIS, make_mesh
    from paddle_tpu.dsl import (
        classification_cost, data, embedding, fc, model, seq_pool,
    )
    from paddle_tpu.network import Network
    from paddle_tpu.optimizers import create_optimizer
    from paddle_tpu.parallel.dp import TrainStep

    D, T, CLS = (16, 8, 4) if synthetic else (128, 32, 64)
    V = 64 if synthetic else 8192
    with model() as m:
        ids = data("ids", dim=(), is_ids=True, is_seq=True)
        lbl = data("label", dim=(), is_ids=True)
        emb = embedding(ids, size=D, vocab_size=V)
        pooled = seq_pool(emb, pool_type="average")
        h = fc(pooled, size=2 * D, act="relu")
        out = fc(h, size=CLS, act="softmax")
        classification_cost(out, lbl)
    net = Network(m.conf)
    mesh = make_mesh({DATA_AXIS: n_dev})
    params = net.init_params(jax.random.key(0))
    opt = create_optimizer(
        OptimizationConf(learning_method="momentum",
                         learning_rate=0.01, momentum=0.9),
        net.param_confs,
    )
    step = TrainStep(net, opt, mesh=mesh, donate=False)
    params, opt_state, state = step.place(
        params, opt.init_state(params), net.init_state()
    )
    b = 8 * n_dev
    feed = {
        "ids": id_arg(
            np.zeros((b, T), np.int32),
            seq_lens=np.full((b,), T, np.int32),
        ),
        "label": id_arg(np.zeros((b,), np.int32)),
    }
    _run, text = step.aot(
        params, opt_state, state, feed, 0, jax.random.key(1)
    )
    _write(out_dir, "mc_dp_train", text, {
        "model": "embedding+fc classifier, dp train step "
                 "(grad allreduce witness)",
        "batch_size": b,
        "vocab": V,
        "d_model": D,
        "mesh": {"data": n_dev},
        "backend": jax.default_backend(),
        "synthetic": synthetic,
    })


def _sparse_setup(n_dev, synthetic):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.core.mesh import MODEL_AXIS, make_mesh

    V, D, N = (64 * n_dev, 16, 32) if synthetic else (65536, 64, 4096)
    mesh = make_mesh({MODEL_AXIS: n_dev})
    table = jax.device_put(
        jnp.zeros((V, D), jnp.float32),
        NamedSharding(mesh, P(MODEL_AXIS, None)),
    )
    ids = jax.device_put(
        jnp.zeros((N,), jnp.int32), NamedSharding(mesh, P())
    )
    return mesh, table, ids, V, D, N


def capture_sparse_lookup(n_dev, out_dir, synthetic):
    """Row-sharded embedding gather: every shard takes its own rows,
    one psum combines partials. The audit pins the table SHARDED
    (replication floor below the table bytes) and forbids the
    all-gather repartition that would pull the whole table onto every
    chip."""
    import jax

    from paddle_tpu.parallel.sparse import embedding_lookup

    mesh, table, ids, V, D, N = _sparse_setup(n_dev, synthetic)
    text = jax.jit(
        lambda tbl, i: embedding_lookup(tbl, i, mesh)
    ).lower(table, ids).compile().as_text()
    _write(out_dir, "mc_sparse_lookup", text, {
        "model": "parallel/sparse.py embedding_lookup "
                 "(row-sharded table, psum combine)",
        "vocab": V, "dim": D, "ids": N,
        "mesh": {"model": n_dev},
        "backend": jax.default_backend(),
        "synthetic": synthetic,
    })


def capture_sparse_update(n_dev, out_dir, synthetic):
    """The lookup's backward: the row-sparse scatter-add into the
    sharded table. The cotangent arrives replicated, each shard
    scatters only its own rows — NO collective should touch the [V,D]
    table, and its gradient must stay sharded."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.parallel.sparse import embedding_lookup

    mesh, table, ids, V, D, N = _sparse_setup(n_dev, synthetic)
    y = jax.device_put(jnp.ones((N, D), jnp.float32))

    def loss(tbl, i, y):
        return jnp.sum(embedding_lookup(tbl, i, mesh) * y)

    text = jax.jit(
        jax.grad(loss)
    ).lower(table, ids, y).compile().as_text()
    _write(out_dir, "mc_sparse_update", text, {
        "model": "embedding_lookup backward: row-sparse scatter into "
                 "the sharded table",
        "vocab": V, "dim": D, "ids": N,
        "mesh": {"model": n_dev},
        "backend": jax.default_backend(),
        "synthetic": synthetic,
    })


def capture_sparse_shard_step(n_dev, out_dir, synthetic):
    """The elastic sparse-CTR tier (ISSUE 20): one fused
    lookup+update step over the per-shard HOT caches of a logically
    2**30-row table (sparse_shard.step_program). The program's shapes
    are (hot-cache, batch) ONLY — rows_total never reaches the
    device, so this capture at 2**30 is byte-identical to one at
    2**20: the audit-visible V-independence claim. Policy: one psum
    (all-reduce) combines lookup partials; the update is a LOCAL
    masked delta scatter — any all-gather here means the hot caches
    were repartitioned onto every chip, which is exactly the failure
    the tier exists to avoid."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.core.mesh import MODEL_AXIS, make_mesh
    from paddle_tpu.parallel import sparse_shard as ss

    if synthetic:
        C, D, N = 64, 8, 32
    else:
        C, D, N = 131072, 64, 4096
    k, n_state = N, 1
    rows_total = 1 << 30  # documentation only: NOT a program shape
    mesh = make_mesh({MODEL_AXIS: n_dev})
    S = n_dev * C
    sharded = NamedSharding(mesh, P(MODEL_AXIS, None))
    repl = NamedSharding(mesh, P())
    cache = jax.device_put(jnp.zeros((S, D), jnp.float32), sharded)
    state = (jax.device_put(jnp.zeros((S, D), jnp.float32),
                            sharded),)
    slots = jax.device_put(jnp.zeros((N,), jnp.int32), repl)
    uslots = jax.device_put(jnp.zeros((k,), jnp.int32), repl)
    inv = jax.device_put(jnp.zeros((N,), jnp.int32), repl)
    grads = jax.device_put(jnp.zeros((N, D), jnp.float32), repl)
    prog = ss.step_program(
        mesh, MODEL_AXIS, S, D, N, k, n_state, "float32",
        ss.adagrad_row_update(0.01),
    )
    text = prog.lower(cache, state, slots, uslots, inv,
                      grads).compile().as_text()
    _write(out_dir, "mc_sparse_shard_step", text, {
        "model": "parallel/sparse_shard.py step_program (fused "
                 "lookup psum + local adagrad delta scatter over "
                 "per-shard hot caches)",
        "rows_total": rows_total,
        "hot_capacity_per_shard": C, "dim": D, "ids": N,
        "num_slots": k, "optimizer": "adagrad(1 slot)",
        "mesh": {"model": n_dev},
        "backend": jax.default_backend(),
        "synthetic": synthetic,
    })


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", default=",".join(ROWS))
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--t", type=int, default=32768)
    ap.add_argument("--out-dir", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "traces"))
    ap.add_argument("--synthetic", action="store_true",
                    help="scaled-down shapes (smoke/tests; NOT for "
                         "the committed captures)")
    args = ap.parse_args(argv)

    _ensure_cpu_mesh(args.devices)
    os.makedirs(args.out_dir, exist_ok=True)

    rows = [r.strip() for r in args.rows.split(",") if r.strip()]
    unknown = [r for r in rows if r not in ROWS]
    if unknown:
        raise SystemExit(
            f"unknown row(s) {unknown}; registered: {list(ROWS)}"
        )
    for row in rows:
        if row == "mc_longctx_ring_t32768":
            capture_longctx("ring", args.t, args.devices,
                            args.out_dir, args.synthetic)
        elif row == "mc_longctx_ulysses_t32768":
            capture_longctx("ulysses", args.t, args.devices,
                            args.out_dir, args.synthetic)
        elif row == "mc_dp_train":
            capture_dp_train(args.devices, args.out_dir,
                             args.synthetic)
        elif row == "mc_sparse_lookup":
            capture_sparse_lookup(args.devices, args.out_dir,
                                  args.synthetic)
        elif row == "mc_sparse_update":
            capture_sparse_update(args.devices, args.out_dir,
                                  args.synthetic)
        elif row == "mc_sparse_shard_step":
            capture_sparse_shard_step(args.devices, args.out_dir,
                                      args.synthetic)


if __name__ == "__main__":
    main()
