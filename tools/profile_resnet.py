"""ResNet-50 train-step profile: timings, XLA cost analysis, and an
XPlane trace (core/profiler.py) — the evidence behind PERF.md.

Usage: python tools/profile_resnet.py [--trace-dir /tmp/rn50-trace]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bs", type=int, default=256)
    ap.add_argument("--trace-dir", default="")
    ap.add_argument("--fused", action="store_true",
                    help="profile the fused-bottleneck graph "
                         "(layers/fused.py Mosaic kernels)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import flags as _flags

    _flags.set_flag("matmul_precision", "bfloat16")
    jax.config.update("jax_default_prng_impl", "rbg")

    from paddle_tpu.core.arg import id_arg, non_seq
    from paddle_tpu.models import resnet
    from paddle_tpu.network import Network

    bs = args.bs
    conf = resnet(depth=50, image_shape=(224, 224, 3),
                  num_classes=1000, fused=args.fused)
    net = Network(conf)
    params = net.init_params(jax.random.key(0))
    state = net.init_state()
    rng = np.random.default_rng(0)
    feed = jax.device_put({
        "image": non_seq(
            rng.standard_normal((bs, 224, 224, 3)).astype(np.float32)
        ),
        "label": id_arg(rng.integers(0, 1000, bs).astype(np.int32)),
    })
    key = jax.random.key(1)

    def loss(p, f):
        return net.loss_fn(p, f, state=state, rng=key, train=True)[0]

    gf = jax.jit(lambda p, f: jax.grad(loss)(p, f))
    c = gf.lower(params, feed).compile()
    ca = c.cost_analysis()
    ca = ca[0] if isinstance(ca, list) else ca
    ma = c.memory_analysis()

    def bench(f, *a, n=10):
        for _ in range(5):
            r = f(*a)
        float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(n):
                r = f(*a)
            float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
            best = min(best, (time.perf_counter() - t0) / n)
        return best * 1e3

    ms = bench(gf, params, feed)
    report = {
        "graph": "fused" if args.fused else "plain",
        "batch_size": bs,
        "fwd_bwd_ms": round(ms, 2),
        "xla_flops": ca.get("flops", 0),
        "xla_bytes_accessed": ca.get("bytes accessed", 0),
        "hbm_temp_bytes": ma.temp_size_in_bytes,
        "img_per_s": round(bs / ms * 1e3, 1),
        "mfu_at_24p6_gflop_img": round(
            bs / ms * 1e3 * 24.6e9 / 197e12, 4
        ),
    }
    print(json.dumps(report, indent=2))

    if args.trace_dir:
        from paddle_tpu.core import profiler

        with profiler.trace(args.trace_dir):
            for _ in range(3):
                r = gf(params, feed)
            float(jax.tree_util.tree_leaves(r)[0].ravel()[0])
        print(f"trace written to {args.trace_dir}")


if __name__ == "__main__":
    main()
