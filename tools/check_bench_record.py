#!/usr/bin/env python
"""Lint: every bench row must land in the full-row artifact.

ROADMAP 5b's guarantee — bench.py/bench_multichip.py append EVERY
emitted row to `BENCH_full_rNN.jsonl` — regresses silently the moment
someone prints a row without going through `bench.emit`. Two checks,
both run by `tests/test_check_bench_record.py`:

- **static**: AST-scan bench.py and bench_multichip.py. Any
  `json.dumps(...)` call OUTSIDE `def emit` is a row (or the makings
  of one) that can bypass the artifact — rows must flow through
  emit(), which owns both the print and the append; bench_multichip
  must import `emit` from bench and define no rival emitter.
- **compare**: given a captured bench stdout and the jsonl artifact of
  the same run, assert the multiset of stdout row ids ("metric" keys)
  is contained in the artifact. A stdout row missing from the record
  is exactly the regression 5b forbids.
- the static pass also asserts the PERMANENT rows — the elasticity
  rows (`mc_checkpoint_overhead`, `mc_preempt_recovery`) AND the
  T>=32k long-context rows (`mc_longctx_ring_t32768`,
  `mc_longctx_ulysses_t32768`, `mc_longctx_ring_t131072`, ISSUE 12) —
  are still registered in bench_multichip.py: deleting a permanent
  row is a perf-record regression, not a cleanup.
- **A/B tripwire** (ISSUE 12, compare mode): the longctx t4096/t8192
  and NMT-T128 rows must carry `fused_speedup` (the interleaved
  dense-vs-flash ratio) or an explicit `ab_skipped` reason; the
  mc_longctx rows must carry the timeline triple like every other
  permanent row.
- **timeline fields** (ISSUE 10): every north-star row must carry the
  per-step time-attribution triple `data_wait_frac` /
  `host_overhead_frac` / `device_frac`. compare mode checks the
  recorded rows; static mode checks `TIMELINE_ROWS` here still equals
  bench.py's `NORTH_STARS` tuple (drift tripwire).
- **obs import hygiene** (`obs` subcommand): no module under
  `paddle_tpu/obs/` may import jax/jaxlib at module top level — the
  metrics registry must stay importable in serving front ends and
  data workers without pulling in the device runtime. The scan also
  pins the package's REQUIRED modules (metrics, timeline, tracing,
  flight_recorder): deleting one is an observability regression, not
  a cleanup.
- **serve span split** (ISSUE 11, compare mode): a measured
  `serve_loadtest` row must carry the span-derived critical-path
  split (`span_queued_frac` / `span_batch_wait_frac` /
  `span_device_frac`) AND it must agree with the registry-derived
  triple the row already carries, within SPAN_SPLIT_TOL — two
  independent measurement paths cross-checking each other.
- **fleet rows** (ISSUE 16): static mode pins the permanent
  `serve_fleet_loadtest` / `serve_coldstart` rows in bench.py;
  compare mode requires the fleet row's kill-phase dict (goodput
  through the SIGKILL + `admitted_lost`, which must be 0 at both row
  and kill scope) and the coldstart row's raw
  `cache_boot_s`/`compile_boot_s` pair.
- **ctr_bigvocab** (ISSUE 20): static mode pins the elastic
  sparse-CTR row in bench_multichip.py; compare mode requires its
  full field set (pod-scale table stats + recovery time) and that
  `batches_lost` / `batches_retrained` /
  `swap_downtime_requests_lost` are PRESENT AND ZERO — the
  exactly-once ledger and the zero-downtime swap are correctness
  invariants recorded per run, never implied.
- **bundle schema** (`bundle` subcommand): static lint of
  flight-recorder bundles (obs/flight_recorder.py) AND fleet
  incident bundles (serving/fleet.py FleetMonitor, ISSUE 17) —
  schema tag, required top-level fields, well-formed span events
  (for an incident bundle: across the stitched router + replica
  rings), alert list shape.

The enforced row lists (REQUIRED_MC_ROWS / AB_ROWS / TIMELINE_ROWS)
live in `paddle_tpu/analysis/rows.py` — ONE source of truth consumed
by the static pass, the compare pass, and the
`tools/framework_lint.py` driver (ISSUE 13), which also runs the
`static` and `obs` modes here as its `bench-static` / `obs` passes.

Usage:
    python tools/check_bench_record.py static [repo_dir]
    python tools/check_bench_record.py compare STDOUT_FILE RECORD_FILE
    python tools/check_bench_record.py obs [repo_dir]
    python tools/check_bench_record.py bundle BUNDLE.json [...]

Exit 0 = clean, 1 = violation (printed to stderr).
"""

from __future__ import annotations

import ast
import json
import os
import sys
from collections import Counter

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# the row lists both the static AST pass and the compare pass enforce
# come from ONE source of truth (ISSUE 13 satellite): each pass used
# to hard-code its own copy and they had started to drift.
# paddle_tpu/analysis/rows.py is pure stdlib — importable with jax
# blocked, like this whole tool.
from paddle_tpu.analysis.rows import (  # noqa: E402
    AB_ROWS,
    COLDSTART_FIELDS,
    CTR_BIGVOCAB_FIELDS,
    CTR_BIGVOCAB_ROW,
    CTR_BIGVOCAB_ZERO_FIELDS,
    DECODE_CHAIN_FIELDS,
    DECODE_CHAIN_ROW,
    DECODE_CHAIN_SPEEDUP_FLOOR,
    FLEET_AGG_FIELDS,
    FLEET_KILL_FIELDS,
    FLEET_P99_ABS_TOL_MS,
    FLEET_P99_RATIO_TOL,
    LM_CACHE_SPEEDUP_FLOOR,
    LM_DECODE_FIELDS,
    LM_DECODE_ROW,
    LM_TRAIN_FIELDS,
    LM_TRAIN_ROW,
    REQUIRED_MC_ROWS,
    REQUIRED_SERVE_ROWS,
    TIMELINE_FIELDS,
    TIMELINE_ROWS,
    needs_timeline,
)

BENCH_FILES = ("bench.py", "bench_multichip.py")

# serve_loadtest span-derived split (ISSUE 11): required fields and
# the cross-check tolerance against the registry triple. The two
# sides time the SAME requests via independent pipes (span stamps vs
# registry counters), so they agree closely; the tolerance absorbs
# rejected-request asymmetry and CPU-smoke scheduling noise.
SERVE_SPAN_FIELDS = (
    "span_queued_frac", "span_batch_wait_frac", "span_device_frac",
)
SPAN_SPLIT_TOL = 0.15

# paddle_tpu/obs/ modules the obs lint additionally REQUIRES to exist
REQUIRED_OBS_MODULES = (
    "metrics.py", "timeline.py", "tracing.py", "flight_recorder.py",
    "aggregate.py",
)

BUNDLE_SCHEMA = "paddle-tpu-flight-bundle/v1"
BUNDLE_REQUIRED_FIELDS = (
    "schema", "reason", "ts", "pid", "seq", "events", "metrics",
)
# fleet incident bundles (ISSUE 17): the router's cross-process
# stitch — alerts + per-replica flightz rings + the merged fleet view
# ride beside the router's own event ring
INCIDENT_SCHEMA = "paddle-tpu-fleet-incident/v1"
INCIDENT_REQUIRED_FIELDS = (
    "schema", "reason", "ts", "pid", "seq", "alerts", "events",
    "replicas", "fleet",
)
SPAN_EVENT_FIELDS = (
    "name", "trace_id", "span_id", "parent_id", "ts", "dur_s",
    "status",
)


def _is_json_dumps(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "dumps"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "json"
    )


def check_static(repo_dir: str) -> list:
    """Return a list of violation strings (empty = clean)."""
    violations = []
    for fname in BENCH_FILES:
        path = os.path.join(repo_dir, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), path)

        emit_bodies = []
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == "emit":
                emit_bodies.extend(ast.walk(node))
        inside_emit = set(map(id, emit_bodies))

        for node in ast.walk(tree):
            if id(node) in inside_emit:
                continue
            if _is_json_dumps(node):
                # json.dumps ANYWHERE outside emit() is how a row gets
                # printed without reaching the artifact (directly or
                # via an intermediate variable) — rows must flow
                # through emit(), which owns both the print and the
                # append
                violations.append(
                    f"{fname}:{node.lineno}: json.dumps outside "
                    f"emit() — a serialized row here can bypass "
                    f"BENCH_full_rNN.jsonl"
                )
    # bench_multichip must route rows through bench.emit
    mc = os.path.join(repo_dir, "bench_multichip.py")
    with open(mc) as f:
        mc_tree = ast.parse(f.read(), mc)
    imports_emit = any(
        isinstance(n, ast.ImportFrom)
        and n.module == "bench"
        and any(a.name == "emit" for a in n.names)
        for n in ast.walk(mc_tree)
    )
    if not imports_emit:
        violations.append(
            "bench_multichip.py: does not import emit from bench — "
            "its rows cannot reach the full-row artifact"
        )
    # the permanent elasticity rows must still be registered (string
    # literals in the row-name f-strings/constants)
    with open(mc) as f:
        mc_src = f.read()
    for row in REQUIRED_MC_ROWS:
        if row not in mc_src:
            violations.append(
                f"bench_multichip.py: permanent row {row!r} is no "
                f"longer registered — the elasticity record would "
                f"silently stop being captured"
            )
    # the elastic sparse-CTR row (ISSUE 20) is permanent the same
    # way: kill/resume with the sharded table + the rollout swap
    if CTR_BIGVOCAB_ROW not in mc_src:
        violations.append(
            f"bench_multichip.py: permanent row "
            f"{CTR_BIGVOCAB_ROW!r} is no longer registered — the "
            f"elastic sparse-CTR record (exactly-once ledger, "
            f"zero-downtime swap) would silently stop being captured"
        )
    # the serving-fleet rows (ISSUE 16) are permanent the same way:
    # the kill sweep and the verified-cache cold start must stay in
    # bench.py's sweep
    with open(os.path.join(repo_dir, "bench.py")) as f:
        bench_src = f.read()
    for row in REQUIRED_SERVE_ROWS:
        if row not in bench_src:
            violations.append(
                f"bench.py: permanent row {row!r} is no longer "
                f"registered — the fleet robustness record would "
                f"silently stop being captured"
            )
    # the Transformer-LM north stars (ISSUE 19) are permanent the
    # same way: the MFU train row and the paged-decode cache row
    for row in (LM_TRAIN_ROW, LM_DECODE_ROW):
        if row not in bench_src:
            violations.append(
                f"bench.py: permanent row {row!r} is no longer "
                f"registered — the LM north-star record would "
                f"silently stop being captured"
            )
    # TIMELINE_ROWS here must equal bench.py's NORTH_STARS, else the
    # compare-mode timeline enforcement silently stops covering a row
    bench_path = os.path.join(repo_dir, "bench.py")
    with open(bench_path) as f:
        bench_tree = ast.parse(f.read(), bench_path)
    north = None
    for node in bench_tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "NORTH_STARS"
            for t in node.targets
        ):
            try:
                north = tuple(ast.literal_eval(node.value))
            except ValueError:
                violations.append(
                    "bench.py NORTH_STARS is no longer a literal "
                    "tuple — the TIMELINE_ROWS drift tripwire cannot "
                    "read it; keep it a plain literal"
                )
                return violations
    if north is None:
        violations.append(
            "bench.py NORTH_STARS assignment not found — the "
            "TIMELINE_ROWS drift tripwire has nothing to compare "
            "against"
        )
    elif north != TIMELINE_ROWS:
        violations.append(
            "bench.py NORTH_STARS != check_bench_record.TIMELINE_ROWS "
            "— update both together or timeline-field enforcement "
            f"drifts (bench: {north}, lint: {TIMELINE_ROWS})"
        )
    # every committed multichip capture must carry its audit report
    # (ISSUE 15): an mc_*.hlo.txt.gz without a sibling *.audit.json is
    # a sharded program CI never audits — it can replicate or
    # over-gather without anyone noticing. (Report CONTENT freshness
    # is the spmd-audit pass's job; this is the cheap jax-free
    # existence gate that runs before the shards.)
    traces = os.path.join(repo_dir, "tools", "traces")
    if os.path.isdir(traces):
        for f in sorted(os.listdir(traces)):
            if not (f.startswith("mc_") and f.endswith(".hlo.txt.gz")):
                continue
            stem = f[: -len(".hlo.txt.gz")]
            if not os.path.exists(
                os.path.join(traces, stem + ".audit.json")
            ):
                violations.append(
                    f"tools/traces/{f}: committed multichip capture "
                    f"has no {stem}.audit.json — run `python "
                    f"tools/framework_lint.py spmd-audit "
                    f"--write-audit` and commit the report"
                )
    return violations


def check_obs_imports(repo_dir: str) -> list:
    """No `paddle_tpu/obs/` module may import jax/jaxlib at module
    scope (function-local imports are fine). Module scope includes
    try/if blocks and class bodies — anything that executes at import
    time."""
    violations = []
    obs_dir = os.path.join(repo_dir, "paddle_tpu", "obs")
    if not os.path.isdir(obs_dir):
        return [f"{obs_dir}: missing — the telemetry package is gone"]
    for required in REQUIRED_OBS_MODULES:
        if not os.path.exists(os.path.join(obs_dir, required)):
            violations.append(
                f"paddle_tpu/obs/{required}: missing — a required "
                f"observability module was deleted"
            )

    def walk_module_scope(node):
        """Yield nodes reachable at import time (skip function
        bodies, whose imports are lazy)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                continue
            yield child
            yield from walk_module_scope(child)

    for fname in sorted(os.listdir(obs_dir)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(obs_dir, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), path)
        for node in walk_module_scope(tree):
            mods = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                mods = [node.module or ""]
            for m in mods:
                root = m.split(".")[0]
                if root in ("jax", "jaxlib"):
                    violations.append(
                        f"paddle_tpu/obs/{fname}:{node.lineno}: "
                        f"imports {m!r} at module top level — the "
                        f"registry must stay importable without the "
                        f"device runtime (use a function-local "
                        f"import)"
                    )
    return violations


def check_compare(stdout_path: str, record_path: str) -> list:
    """Every JSON row printed to stdout must appear in the record, at
    least as many times as it was printed; and every successfully
    measured north-star row must carry the timeline triple."""
    def parse(path):
        out = []
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln.startswith("{"):
                    continue
                try:
                    d = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(d, dict) and "metric" in d:
                    out.append(d)
        return out

    def counts(rows):
        return Counter(d["metric"] for d in rows)

    printed_rows = parse(stdout_path)
    printed = counts(printed_rows)
    recorded = counts(parse(record_path))
    violations = []
    for metric, n in printed.items():
        if recorded[metric] < n:
            violations.append(
                f"row {metric!r}: printed {n}x but recorded "
                f"{recorded[metric]}x in {record_path} — a bench row "
                f"is missing from the full-row artifact"
            )
    if not printed:
        violations.append(f"{stdout_path}: no bench rows found")
    # timeline enforcement (ISSUE 10): a north-star row that measured
    # successfully (no error, not budget-skipped) without the
    # attribution triple means an input-pipeline bubble could hide
    for d in printed_rows:
        m = d["metric"]
        if needs_timeline(m) \
                and "error" not in d and "skipped" not in d:
            missing = [f for f in TIMELINE_FIELDS if f not in d]
            if missing:
                violations.append(
                    f"row {m!r}: missing timeline field(s) "
                    f"{missing} — north-star rows must attribute "
                    f"their step time (data-wait / host / device)"
                )
        if m == "serve_loadtest" and "error" not in d \
                and "skipped" not in d:
            violations.extend(_check_serve_span_split(d))
        if m == "serve_fleet_loadtest" and "error" not in d \
                and "skipped" not in d:
            violations.extend(_check_fleet_row(d))
        if m == "serve_coldstart" and "error" not in d \
                and "skipped" not in d:
            violations.extend(_check_coldstart_row(d))
        # elastic sparse-CTR gate (ISSUE 20): the ctr_bigvocab row's
        # zero-invariants must be present and exactly zero
        if (m == CTR_BIGVOCAB_ROW
                or m.startswith(CTR_BIGVOCAB_ROW + "_")) \
                and "error" not in d and "skipped" not in d:
            violations.extend(_check_ctr_bigvocab_row(d))
        # decode-chain gate (ISSUE 18): the beam-decode row's
        # measured dispatch_chain_depth / chain_speedup must be
        # present, genuinely reduced, and above the floor
        if m == DECODE_CHAIN_ROW and "error" not in d \
                and "skipped" not in d:
            violations.extend(_check_decode_chain_row(d))
        # LM north stars (ISSUE 19): analytic MFU on the train row;
        # the measured cache story on the paged-decode row
        if m == LM_TRAIN_ROW and "error" not in d \
                and "skipped" not in d:
            violations.extend(_check_lm_train_row(d))
        if m == LM_DECODE_ROW and "error" not in d \
                and "skipped" not in d:
            violations.extend(_check_lm_decode_row(d))
        # A/B tripwire (ISSUE 12): a measured longctx/NMT-T128 row
        # without a flash A/B verdict means the dense-vs-flash
        # comparison silently dropped out of the record
        if m in AB_ROWS and "error" not in d and "skipped" not in d \
                and "fused_speedup" not in d and "ab_skipped" not in d:
            violations.append(
                f"row {m!r}: carries neither 'fused_speedup' nor an "
                f"explicit 'ab_skipped' reason — the interleaved "
                f"dense-vs-flash A/B must not silently drop"
            )
    return violations


def _check_decode_chain_row(row: dict) -> list:
    """nmt_beam4 decode rows (ISSUE 18): the chain-depth A/B is the
    row's whole point — the committed capture proved decode is
    dispatch-chain-bound (7.7x over the byte floor), so a measured
    row must show the chain actually shrinking and paying off. An
    explicit `chain_ab_skipped` reason (probe failure) is the only
    accepted absence, mirroring AB_ROWS' ab_skipped."""
    if "chain_ab_skipped" in row:
        return []
    missing = [f for f in DECODE_CHAIN_FIELDS if f not in row]
    if missing:
        return [
            f"row {DECODE_CHAIN_ROW!r}: missing chain field(s) "
            f"{missing} and no 'chain_ab_skipped' reason — the "
            f"measured dispatch-chain A/B must not silently drop"
        ]
    violations = []
    depth = row["dispatch_chain_depth"]
    base = row["dispatch_chain_depth_k1"]
    speedup = row["chain_speedup"]
    ok_num = all(
        isinstance(x, (int, float)) and not isinstance(x, bool)
        for x in (depth, base, speedup)
    )
    if not ok_num:
        return [
            f"row {DECODE_CHAIN_ROW!r}: non-numeric chain fields "
            f"(depth={depth!r}, k1={base!r}, speedup={speedup!r})"
        ]
    if not (0 < depth < base):
        violations.append(
            f"row {DECODE_CHAIN_ROW!r}: dispatch_chain_depth={depth} "
            f"vs k1 baseline {base} — the K-token arm no longer "
            f"shortens the dispatch chain (depth must satisfy "
            f"0 < depth < baseline, measured not assumed)"
        )
    if speedup < DECODE_CHAIN_SPEEDUP_FLOOR:
        violations.append(
            f"row {DECODE_CHAIN_ROW!r}: chain_speedup={speedup} under "
            f"the {DECODE_CHAIN_SPEEDUP_FLOOR}x floor — the chain "
            f"reduction stopped paying for itself (interleaved "
            f"K-token vs K=1 tokens/s)"
        )
    return violations


def _check_lm_train_row(row: dict) -> list:
    """lm_train rows (ISSUE 19): MFU is the row's point — the
    analytic FLOPs/step (model-config-derived, the
    _nmt_train_flops_per_batch discipline) over the measured step
    time against peak. It must be recorded as a sane fraction."""
    missing = [f for f in LM_TRAIN_FIELDS if f not in row]
    if missing:
        return [
            f"row {LM_TRAIN_ROW!r}: missing field(s) {missing} — the "
            f"LM train north star must record its analytic MFU"
        ]
    mfu = row["mfu"]
    if not (isinstance(mfu, (int, float))
            and not isinstance(mfu, bool) and 0 < mfu <= 1.0):
        return [
            f"row {LM_TRAIN_ROW!r}: mfu={mfu!r} is not a fraction in "
            f"(0, 1] — analytic FLOPs over measured wall against "
            f"peak cannot leave that range"
        ]
    return []


def _check_lm_decode_row(row: dict) -> list:
    """lm_decode_paged rows (ISSUE 19): the measured cache story —
    hit fraction, bytes the recompute baseline would have paid, and
    the interleaved paged-vs-recompute speedup (floored: a KV pool
    that stops beating full prefix recompute is overhead, not an
    optimization). The row's eviction-sweep points must show decode
    throughput actually SCALING with the hit fraction. An explicit
    `cache_ab_skipped` reason is the only accepted absence for the
    A/B-derived fields, mirroring AB_ROWS."""
    if "cache_ab_skipped" in row:
        return []
    missing = [f for f in LM_DECODE_FIELDS if f not in row]
    if missing:
        return [
            f"row {LM_DECODE_ROW!r}: missing cache field(s) "
            f"{missing} and no 'cache_ab_skipped' reason — the "
            f"measured cache story must not silently drop"
        ]
    violations = []
    hit = row["cache_hit_frac"]
    saved = row["prefix_recompute_bytes_saved"]
    speedup = row["cache_speedup"]
    if not (isinstance(hit, (int, float))
            and not isinstance(hit, bool) and 0.0 <= hit <= 1.0):
        violations.append(
            f"row {LM_DECODE_ROW!r}: cache_hit_frac={hit!r} is not a "
            f"fraction in [0, 1]"
        )
    if not (isinstance(saved, (int, float))
            and not isinstance(saved, bool) and saved > 0):
        violations.append(
            f"row {LM_DECODE_ROW!r}: prefix_recompute_bytes_saved="
            f"{saved!r} — a measured paged-decode run must have "
            f"served cached prefix tokens (positive bytes), else the "
            f"pool never did its job"
        )
    if not (isinstance(speedup, (int, float))
            and not isinstance(speedup, bool)):
        violations.append(
            f"row {LM_DECODE_ROW!r}: cache_speedup={speedup!r} is "
            f"not a number"
        )
    elif speedup < LM_CACHE_SPEEDUP_FLOOR:
        violations.append(
            f"row {LM_DECODE_ROW!r}: cache_speedup={speedup} under "
            f"the {LM_CACHE_SPEEDUP_FLOOR}x floor — reading the KV "
            f"pool stopped beating full prefix recompute "
            f"(interleaved paged vs recompute tokens/s)"
        )
    pts = row.get("points")
    if isinstance(pts, list):
        scored = [
            p for p in pts
            if isinstance(p, dict)
            and isinstance(p.get("cache_hit_frac"), (int, float))
            and isinstance(p.get("tok_s"), (int, float))
        ]
        if len(scored) >= 2:
            lo = min(scored, key=lambda p: p["cache_hit_frac"])
            hi = max(scored, key=lambda p: p["cache_hit_frac"])
            if hi["cache_hit_frac"] > lo["cache_hit_frac"] \
                    and hi["tok_s"] <= lo["tok_s"]:
                violations.append(
                    f"row {LM_DECODE_ROW!r}: throughput does not "
                    f"scale with cache hits — "
                    f"{hi['tok_s']} tok/s at hit_frac="
                    f"{hi['cache_hit_frac']} vs {lo['tok_s']} tok/s "
                    f"at hit_frac={lo['cache_hit_frac']} (the "
                    f"eviction sweep must show the cache paying off)"
                )
    return violations


def _check_serve_span_split(row: dict) -> list:
    """serve_loadtest rows must carry the span-derived critical-path
    split and it must reconcile with the registry triple (ISSUE 11):
    span queued + batch-wait vs the registry's data_wait (both are
    "before the program ran"), span device vs the registry's device
    share."""
    missing = [f for f in SERVE_SPAN_FIELDS if f not in row]
    if missing:
        return [
            f"row 'serve_loadtest': missing span field(s) {missing} "
            f"— the row must carry the span-derived critical-path "
            f"split beside the registry triple"
        ]
    violations = []
    span_wait = row["span_queued_frac"] + row["span_batch_wait_frac"]
    reg_wait = row.get("data_wait_frac")
    if reg_wait is not None and abs(span_wait - reg_wait) \
            > SPAN_SPLIT_TOL:
        violations.append(
            f"row 'serve_loadtest': span wait "
            f"(queued+batch_wait={span_wait:.4f}) disagrees with the "
            f"registry data_wait_frac={reg_wait:.4f} beyond "
            f"tol={SPAN_SPLIT_TOL} — one of the two measurement "
            f"paths is broken"
        )
    reg_dev = row.get("device_frac")
    if reg_dev is not None and abs(row["span_device_frac"] - reg_dev) \
            > SPAN_SPLIT_TOL:
        violations.append(
            f"row 'serve_loadtest': span_device_frac="
            f"{row['span_device_frac']:.4f} disagrees with the "
            f"registry device_frac={reg_dev:.4f} beyond "
            f"tol={SPAN_SPLIT_TOL}"
        )
    return violations


def _check_fleet_row(row: dict) -> list:
    """serve_fleet_loadtest rows (ISSUE 16): the kill-phase dict must
    carry its goodput and loss fields, and `admitted_lost` — both the
    row total and the kill phase — must be exactly 0. A fleet that
    loses an admitted request while one replica is SIGKILLed is a
    robustness regression; dropping the kill-phase goodput field is
    the same regression hidden by omission."""
    violations = []
    kill = row.get("kill")
    if not isinstance(kill, dict):
        return [
            "row 'serve_fleet_loadtest': missing 'kill' dict — the "
            "SIGKILL-mid-sweep phase is the point of the row and must "
            "be recorded"
        ]
    missing = [f for f in FLEET_KILL_FIELDS if f not in kill]
    if missing:
        violations.append(
            f"row 'serve_fleet_loadtest': kill phase missing "
            f"field(s) {missing} — goodput-through-the-fault and the "
            f"loss counter must both be recorded"
        )
    for scope, holder in (("row", row), ("kill phase", kill)):
        lost = holder.get("admitted_lost")
        if lost is not None and lost != 0:
            violations.append(
                f"row 'serve_fleet_loadtest': {scope} reports "
                f"admitted_lost={lost} — an admitted request must be "
                f"spilled or completed, never lost (0 required)"
            )
    if "admitted_lost" not in row:
        violations.append(
            "row 'serve_fleet_loadtest': missing 'admitted_lost' — "
            "the zero-loss invariant must be recorded, not implied"
        )
    # fleet-aggregated observability fields (ISSUE 17): the row must
    # carry the merged-histogram fleet p99, the router's own p99 of
    # the same requests, and the alert/scrape-failure accounting
    missing = [f for f in FLEET_AGG_FIELDS if f not in row]
    if missing:
        violations.append(
            f"row 'serve_fleet_loadtest': missing fleet-aggregated "
            f"field(s) {missing} — the merged-histogram view and its "
            f"router-side cross-check must both be recorded"
        )
        return violations
    fleet_p99 = row["fleet_p99_ms"]
    router_p99 = row["router_p99_ms"]
    if not (isinstance(fleet_p99, (int, float)) and fleet_p99 > 0):
        violations.append(
            f"row 'serve_fleet_loadtest': fleet_p99_ms="
            f"{fleet_p99!r} — the merged-bucket quantile must be a "
            f"positive number (empty merge means the scrape chain "
            f"is broken)"
        )
        return violations
    if isinstance(router_p99, (int, float)) and router_p99 > 0:
        ratio = max(fleet_p99, router_p99) / min(fleet_p99,
                                                 router_p99)
        if ratio > FLEET_P99_RATIO_TOL and \
                abs(fleet_p99 - router_p99) > FLEET_P99_ABS_TOL_MS:
            violations.append(
                f"row 'serve_fleet_loadtest': fleet_p99_ms="
                f"{fleet_p99:.3f} vs router_p99_ms={router_p99:.3f} "
                f"disagree beyond {FLEET_P99_RATIO_TOL}x and "
                f"{FLEET_P99_ABS_TOL_MS}ms — the replica-histogram "
                f"merge and the router's own timing measure the same "
                f"requests; one of the pipes is broken"
            )
    return violations


def _check_ctr_bigvocab_row(row: dict) -> list:
    """ctr_bigvocab rows (ISSUE 20): the elastic sparse-CTR record.
    Every field in CTR_BIGVOCAB_FIELDS must be present — the
    pod-scale table stats (rows_total, rows_touched_frac), the
    recovery price (kill_recover_s), and the three zero-invariants —
    and the zero-invariants must be EXACTLY 0. One batch lost means
    the per-shard manifests failed their whole purpose; one batch
    retrained means the commit-acknowledged ledger double-counted;
    one request lost during the rollout swap means the hot swap had
    downtime. All three are correctness regressions, not slow rows,
    synthetic or not."""
    m = row.get("metric", CTR_BIGVOCAB_ROW)
    violations = []
    missing = [f for f in CTR_BIGVOCAB_FIELDS if f not in row]
    if missing:
        violations.append(
            f"row {m!r}: missing field(s) {missing} — the elastic "
            f"sparse-CTR record must carry the pod-scale table "
            f"stats, the recovery time, and the zero-invariants"
        )
    for f in CTR_BIGVOCAB_ZERO_FIELDS:
        v = row.get(f)
        if v is not None and v != 0:
            violations.append(
                f"row {m!r}: {f}={v!r} — must be exactly 0 (the "
                f"exactly-once ledger / zero-downtime swap is a "
                f"correctness invariant, not a metric)"
            )
    rt = row.get("rows_total")
    if rt is not None and rt < (1 << 27):
        violations.append(
            f"row {m!r}: rows_total={rt!r} — the pod-scale claim "
            f"needs a logical vocabulary of at least 2**27 rows "
            f"(V-independence makes the big number free; shrinking "
            f"it un-proves the claim)"
        )
    frac = row.get("rows_touched_frac")
    if frac is not None and not (0 <= frac < 0.01):
        violations.append(
            f"row {m!r}: rows_touched_frac={frac!r} — the hot set "
            f"must be a vanishing fraction of the logical table "
            f"(< 1%); anything larger means the row stopped "
            f"exercising the eviction tier"
        )
    return violations


def _check_coldstart_row(row: dict) -> list:
    """serve_coldstart rows must carry both raw boot times so the
    speedup `value` stays auditable."""
    missing = [f for f in COLDSTART_FIELDS if f not in row]
    if missing:
        return [
            f"row 'serve_coldstart': missing field(s) {missing} — "
            f"the verified-cache vs compile boot comparison must "
            f"record both raw measurements"
        ]
    return []


def check_bundle(path: str) -> list:
    """Static schema lint for one bundle file — flight-recorder
    bundles AND fleet incident bundles (ISSUE 17), dispatched on the
    schema tag. For an incident bundle the span-event check runs over
    the STITCHED event set: the router's own ring plus every
    replica's flightz ring."""
    violations = []
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        return [f"{path}: unreadable bundle ({e})"]
    if not isinstance(doc, dict):
        return [f"{path}: bundle is not a JSON object"]
    if doc.get("schema") == INCIDENT_SCHEMA:
        return _check_incident_bundle(path, doc)
    if doc.get("schema") != BUNDLE_SCHEMA:
        violations.append(
            f"{path}: schema {doc.get('schema')!r} != "
            f"{BUNDLE_SCHEMA!r}"
        )
    for field in BUNDLE_REQUIRED_FIELDS:
        if field not in doc:
            violations.append(f"{path}: missing field {field!r}")
    violations.extend(_check_events(path, "events", doc.get("events")))
    prof = doc.get("profile")
    if prof is not None and (not isinstance(prof, dict)
                             or "captured" not in prof):
        violations.append(
            f"{path}: 'profile' stanza malformed (needs 'captured')"
        )
    return violations


def _check_events(path: str, where: str, events) -> list:
    violations = []
    if not isinstance(events, list):
        return [f"{path}: '{where}' is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict) or "kind" not in ev:
            violations.append(
                f"{path}: {where}[{i}] has no 'kind'"
            )
            continue
        if ev["kind"] == "span":
            missing = [f for f in SPAN_EVENT_FIELDS if f not in ev]
            if missing:
                violations.append(
                    f"{path}: {where}[{i}] span missing {missing}"
                )
            elif not (isinstance(ev["dur_s"], (int, float))
                      and ev["dur_s"] >= 0):
                violations.append(
                    f"{path}: {where}[{i}] span dur_s "
                    f"{ev['dur_s']!r} is not a non-negative number"
                )
    return violations


def _check_incident_bundle(path: str, doc: dict) -> list:
    violations = []
    for field in INCIDENT_REQUIRED_FIELDS:
        if field not in doc:
            violations.append(f"{path}: missing field {field!r}")
    alerts = doc.get("alerts")
    if not isinstance(alerts, list):
        violations.append(f"{path}: 'alerts' is not a list")
    else:
        for i, a in enumerate(alerts):
            if not isinstance(a, dict) or "alert" not in a:
                violations.append(
                    f"{path}: alerts[{i}] has no 'alert' kind"
                )
    fleet = doc.get("fleet")
    if fleet is not None and (not isinstance(fleet, dict)
                              or "merged" not in fleet):
        violations.append(
            f"{path}: 'fleet' stanza malformed (needs 'merged')"
        )
    violations.extend(_check_events(path, "events", doc.get("events")))
    replicas = doc.get("replicas")
    if not isinstance(replicas, dict):
        violations.append(f"{path}: 'replicas' is not a dict")
        replicas = {}
    for name, ring in replicas.items():
        if not isinstance(ring, dict):
            violations.append(
                f"{path}: replicas[{name!r}] is not a dict"
            )
            continue
        if "events" in ring:
            violations.extend(_check_events(
                path, f"replicas[{name!r}].events", ring["events"]
            ))
    return violations


def main(argv) -> int:
    if len(argv) >= 2 and argv[1] in ("static", "obs"):
        repo = argv[2] if len(argv) > 2 else os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        violations = (
            check_static(repo) if argv[1] == "static"
            else check_obs_imports(repo)
        )
    elif len(argv) == 4 and argv[1] == "compare":
        violations = check_compare(argv[2], argv[3])
    elif len(argv) >= 3 and argv[1] == "bundle":
        violations = []
        for path in argv[2:]:
            violations.extend(check_bundle(path))
    else:
        print(__doc__, file=sys.stderr)
        return 2
    for v in violations:
        print(f"check_bench_record: {v}", file=sys.stderr)
    if not violations:
        print("check_bench_record: OK")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
