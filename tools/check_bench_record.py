#!/usr/bin/env python
"""Lint: every bench row must land in the full-row artifact.

ROADMAP 5b's guarantee — bench.py/bench_multichip.py append EVERY
emitted row to `BENCH_full_rNN.jsonl` — regresses silently the moment
someone prints a row without going through `bench.emit`. Two checks,
both run by `tests/test_check_bench_record.py`:

- **static**: AST-scan bench.py and bench_multichip.py. Any
  `json.dumps(...)` call OUTSIDE `def emit` is a row (or the makings
  of one) that can bypass the artifact — rows must flow through
  emit(), which owns both the print and the append; bench_multichip
  must import `emit` from bench and define no rival emitter.
- **compare**: given a captured bench stdout and the jsonl artifact of
  the same run, assert the multiset of stdout row ids ("metric" keys)
  is contained in the artifact. A stdout row missing from the record
  is exactly the regression 5b forbids.
- the static pass also asserts the PERMANENT elasticity rows
  (`mc_checkpoint_overhead`, `mc_preempt_recovery`) are still
  registered in bench_multichip.py — deleting a permanent row is a
  perf-record regression, not a cleanup.

Usage:
    python tools/check_bench_record.py static [repo_dir]
    python tools/check_bench_record.py compare STDOUT_FILE RECORD_FILE

Exit 0 = clean, 1 = violation (printed to stderr).
"""

from __future__ import annotations

import ast
import json
import os
import sys
from collections import Counter

BENCH_FILES = ("bench.py", "bench_multichip.py")

# permanent rows the multichip sweep must keep registering (ROADMAP 4 /
# ISSUE 9: elasticity is measured, not assumed)
REQUIRED_MC_ROWS = ("mc_checkpoint_overhead", "mc_preempt_recovery")


def _is_json_dumps(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr == "dumps"
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id == "json"
    )


def check_static(repo_dir: str) -> list:
    """Return a list of violation strings (empty = clean)."""
    violations = []
    for fname in BENCH_FILES:
        path = os.path.join(repo_dir, fname)
        with open(path) as f:
            tree = ast.parse(f.read(), path)

        emit_bodies = []
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef) and node.name == "emit":
                emit_bodies.extend(ast.walk(node))
        inside_emit = set(map(id, emit_bodies))

        for node in ast.walk(tree):
            if id(node) in inside_emit:
                continue
            if _is_json_dumps(node):
                # json.dumps ANYWHERE outside emit() is how a row gets
                # printed without reaching the artifact (directly or
                # via an intermediate variable) — rows must flow
                # through emit(), which owns both the print and the
                # append
                violations.append(
                    f"{fname}:{node.lineno}: json.dumps outside "
                    f"emit() — a serialized row here can bypass "
                    f"BENCH_full_rNN.jsonl"
                )
    # bench_multichip must route rows through bench.emit
    mc = os.path.join(repo_dir, "bench_multichip.py")
    with open(mc) as f:
        mc_tree = ast.parse(f.read(), mc)
    imports_emit = any(
        isinstance(n, ast.ImportFrom)
        and n.module == "bench"
        and any(a.name == "emit" for a in n.names)
        for n in ast.walk(mc_tree)
    )
    if not imports_emit:
        violations.append(
            "bench_multichip.py: does not import emit from bench — "
            "its rows cannot reach the full-row artifact"
        )
    # the permanent elasticity rows must still be registered (string
    # literals in the row-name f-strings/constants)
    with open(mc) as f:
        mc_src = f.read()
    for row in REQUIRED_MC_ROWS:
        if row not in mc_src:
            violations.append(
                f"bench_multichip.py: permanent row {row!r} is no "
                f"longer registered — the elasticity record would "
                f"silently stop being captured"
            )
    return violations


def check_compare(stdout_path: str, record_path: str) -> list:
    """Every JSON row printed to stdout must appear in the record, at
    least as many times as it was printed."""
    def rows(path):
        out = Counter()
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if not ln.startswith("{"):
                    continue
                try:
                    d = json.loads(ln)
                except ValueError:
                    continue
                if isinstance(d, dict) and "metric" in d:
                    out[d["metric"]] += 1
        return out

    printed, recorded = rows(stdout_path), rows(record_path)
    violations = []
    for metric, n in printed.items():
        if recorded[metric] < n:
            violations.append(
                f"row {metric!r}: printed {n}x but recorded "
                f"{recorded[metric]}x in {record_path} — a bench row "
                f"is missing from the full-row artifact"
            )
    if not printed:
        violations.append(f"{stdout_path}: no bench rows found")
    return violations


def main(argv) -> int:
    if len(argv) >= 2 and argv[1] == "static":
        repo = argv[2] if len(argv) > 2 else os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))
        )
        violations = check_static(repo)
    elif len(argv) == 4 and argv[1] == "compare":
        violations = check_compare(argv[2], argv[3])
    else:
        print(__doc__, file=sys.stderr)
        return 2
    for v in violations:
        print(f"check_bench_record: {v}", file=sys.stderr)
    if not violations:
        print("check_bench_record: OK")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
