"""paddle.v2.parameters — the numpy-facing parameter pool.

Reference: python/paddle/v2/parameters.py:43 (class Parameters — dict of
numpy arrays keyed by parameter name), :304/:323 (to_tar/from_tar in the
reference's tar wire format) and parameters.create(topology) which
allocates and randomizes every parameter of a topology.

The tar codec is paddle_tpu.trainer.checkpoint's reference-interoperable
implementation (ParameterConfig protobuf sidecars included), so tars
written here load in the reference and vice versa.
"""

from __future__ import annotations

import jax
import numpy as np

from paddle_tpu.network import Network
from paddle_tpu.trainer import checkpoint as _ckpt

from .topology import Topology

__all__ = ["Parameters", "create"]


def create(layers, seed: int = 0):
    """Allocate + randomize the parameters of the topology reaching
    `layers` (reference parameters.py create())."""
    topo = layers if isinstance(layers, Topology) else Topology(layers)
    net = Network(topo.proto())
    params = net.init_params(jax.random.PRNGKey(seed))
    pool = Parameters()
    pool.__param_confs__ = dict(net.param_confs)
    for name, v in params.items():
        pool.__params__[name] = np.asarray(v)
    return pool


class Parameters:
    def __init__(self):
        self.__params__: dict[str, np.ndarray] = {}
        self.__param_confs__: dict = {}

    def __append_config__(self, param_conf):
        """Register a ParameterConfig and allocate its (zeroed) buffer
        (reference parameters.py __append_config__; shape is dims when
        set, else (1, size) — reference get_shape()). Accepts the
        paddle.proto.ParameterConfig_pb2 shim or anything with
        name/size/dims."""
        if not param_conf.IsInitialized():
            raise ValueError("param_conf must be initialized")
        if param_conf.name in self.__params__:
            raise ValueError(f"duplicated parameter {param_conf.name}")
        dims = tuple(int(d) for d in param_conf.dims) or (
            1,
            int(param_conf.size),
        )
        from paddle_tpu.core.config import ParameterConf as _PC

        self.__param_confs__[param_conf.name] = _PC(
            name=param_conf.name, dims=dims
        )
        self.__params__[param_conf.name] = np.zeros(dims, np.float32)

    # --- dict surface (parameters.py:43 "plain numpy dict") ---
    def names(self):
        return list(self.__params__)

    def keys(self):
        return self.names()

    def has_key(self, key):
        return key in self.__params__

    def __contains__(self, key):
        return key in self.__params__

    def __iter__(self):
        return iter(self.__params__)

    def __len__(self):
        return len(self.__params__)

    def get(self, parameter_name):
        return self.__getitem__(parameter_name)

    def __getitem__(self, key):
        return self.__params__[key]

    def set(self, parameter_name, value):
        self.__setitem__(parameter_name, value)

    def __setitem__(self, key, value):
        value = np.asarray(value, np.float32)
        if key in self.__params__:
            have = self.__params__[key].shape
            if int(np.prod(have)) != int(np.prod(value.shape)):
                raise ValueError(
                    f"parameter {key!r} expects {have} "
                    f"({int(np.prod(have))} elems), got {value.shape}"
                )
            value = value.reshape(have)
        self.__params__[key] = value

    def get_shape(self, key):
        return tuple(self.__params__[key].shape)

    # --- checkpoint (parameters.py:304 to_tar, :323 from_tar) ---
    def to_tar(self, f):
        _ckpt.to_tar(f, self.__params__, self.__param_confs__ or None)

    @staticmethod
    def from_tar(f) -> "Parameters":
        pool = Parameters()
        for name, v in _ckpt.from_tar(f).items():
            pool.__params__[name] = np.asarray(v, np.float32)
        return pool

    def init_from_tar(self, f):
        """Overwrite matching parameters from a tar (reference
        init_from_tar: only names present in this pool are applied)."""
        for name, v in _ckpt.from_tar(f).items():
            if name in self.__params__:
                self.__setitem__(name, v)

    # --- jax bridge (internal; replaces append_gradient_machine) ---
    def _to_device(self) -> dict:
        return {k: jax.numpy.asarray(v) for k, v in self.__params__.items()}

    def _sync_from(self, params: dict):
        for k, v in params.items():
            self.__params__[k] = np.asarray(v)
