"""paddle.v2.attr — Param/Extra attribute aliases.

Reference: python/paddle/v2/attr.py (Param = ParameterAttribute,
Extra = ExtraLayerAttribute).
"""

from paddle_tpu.compat.layers_v1 import ParamAttr as Param
from paddle_tpu.compat.config_parser import ExtraLayerAttribute as Extra

ParamAttr = Param
ExtraAttr = Extra

__all__ = ["Param", "Extra", "ParamAttr", "ExtraAttr"]
