"""paddle.v2.framework.op — the Operator factory.

Reference: python/paddle/v2/framework/op.py (OperatorFactory over
get_all_op_protos(): `Operator(type, SlotName="var", ..., attr=value)`
builds an op wiring slot names to scope variable names). Slot
signatures come from the engine registry's OpProto declarations
(paddle_tpu.framework.op.op_signature).
"""

from __future__ import annotations

from paddle_tpu.framework.op import (
    EMPTY_VAR,
    create_op,
    op_signature,
    op_types,
)

# reference spellings kept importable (add_op.cc REGISTER_OP(add_two))
_ALIASES = {"add_two": "add"}


def _resolve(type_name: str) -> str:
    return _ALIASES.get(type_name, type_name)


class _OperatorFactory:
    """Operator(type, **kwargs): slot-name kwargs select variable names,
    attr-name kwargs set attributes (reference op.py __impl__)."""

    def __call__(self, type_name: str, **kwargs):
        t = _resolve(type_name)
        in_slots, out_slots, attr_names = op_signature(t)
        inputs, outputs, attrs = {}, {}, {}
        for k, v in kwargs.items():
            if k in in_slots:
                inputs[k] = v
            elif k in out_slots:
                outputs[k] = v
            elif k in attr_names:
                attrs[k] = v
            else:
                raise ValueError(
                    f"{type_name}: {k!r} is not an input/output/attr "
                    f"(inputs {in_slots}, outputs {out_slots}, "
                    f"attrs {attr_names})"
                )
        for slot in in_slots:
            inputs.setdefault(slot, EMPTY_VAR)
        for slot in out_slots:
            outputs.setdefault(slot, EMPTY_VAR)
        return create_op(t, inputs, outputs, attrs)

    @staticmethod
    def get_op_input_names(type_name: str):
        return list(op_signature(_resolve(type_name))[0])

    @staticmethod
    def get_op_output_names(type_name: str):
        return list(op_signature(_resolve(type_name))[1])

    @staticmethod
    def get_op_attr_names(type_name: str):
        return list(op_signature(_resolve(type_name))[2])

    @staticmethod
    def types():
        return op_types()


Operator = _OperatorFactory()
