"""Reference import location for the op-test harness
(python/paddle/v2/framework/tests/): re-exports the reusable modules so
`from paddle.v2.framework.tests import gradient_checker` and
reference-style `from paddle.v2.framework.tests.op_test_util import
OpTestMeta` both resolve."""

from paddle.v2.framework import gradient_checker, op_test_util  # noqa: F401
