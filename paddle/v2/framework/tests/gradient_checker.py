from paddle.v2.framework.gradient_checker import *  # noqa: F401,F403
from paddle.v2.framework.gradient_checker import (  # noqa: F401
    GradientChecker,
    create_op,
    get_numeric_gradient,
    grad_var_name,
)
