from paddle.v2.framework.op_test_util import OpTestMeta  # noqa: F401
