"""paddle.v2.framework.core — the engine-object module.

Reference: the pybind module `core` (paddle/framework/pybind.cc) exposing
Scope, places and Operator.backward. The TPU engine keeps values as jax
arrays inside paddle_tpu Scopes, so tensors need no set_dims/alloc
choreography — `new_var` + `set_value`/numpy round-trips cover the same
test surface.
"""

from __future__ import annotations

from paddle_tpu.framework.backward import backward as _build_backward
from paddle_tpu.framework.scope import Variable  # noqa: F401
from paddle_tpu.framework.scope import Scope as _Scope


class Scope(_Scope):
    """Reference core.Scope surface (framework/scope.h:36):
    new_var/find_var/new_scope/drop_kids."""

    def new_var(self, name: str) -> Variable:
        return self.var(name)

    def drop_kids(self) -> None:
        # child scopes are plain Python objects; dropping the
        # references is the whole job (scope.h DropKids frees C++ kids)
        self._kids.clear()


class CPUPlace:
    """Single-host place marker (platform/place.h). Kernels are jax —
    the actual device is whatever backend jax runs on (TPU under jit,
    CPU in the eager test harness)."""

    def __repr__(self) -> str:  # pragma: no cover
        return "CPUPlace"


def is_compile_gpu() -> bool:
    """The reference gates GPUPlace test arms on this; the TPU build
    has no CUDA arm."""
    return False


class Operator:
    """core.Operator static surface used by tests:
    Operator.backward(fwd_op, no_grad_set) -> backward net."""

    @staticmethod
    def backward(forward_op, no_grad_set=frozenset()):
        from paddle_tpu.framework.op import EMPTY_VAR

        # reference pybind semantics: the CALLER seeds the forward
        # outputs' gradients in the scope before running the net, so
        # they must not be zero-filled by the builder
        seeded = {
            n
            for ns in forward_op.outputs.values()
            for n in ns
            if n != EMPTY_VAR
        }
        return _build_backward(
            forward_op, set(no_grad_set), seeded=seeded
        )
