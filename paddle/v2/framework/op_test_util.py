"""OpTestMeta — declarative per-op forward tests.

Reference: python/paddle/v2/framework/tests/op_test_util.py — a
metaclass injecting `test_all` into a TestCase: the subclass declares
`self.type`, `self.inputs`, `self.outputs` (and optionally
`self.attrs`) in setUp; test_all builds the op by slot name, runs it
in a fresh scope, and compares every declared output.
"""

from __future__ import annotations

import numpy as np

from paddle.v2.framework.core import Scope
from paddle.v2.framework.op import Operator

__all__ = ["OpTestMeta"]


class OpTestMeta(type):
    def __new__(cls, name, bases, attrs):
        obj = super().__new__(cls, name, bases, attrs)

        def test_all(self):
            scope = Scope()
            kwargs = {}
            for in_name in Operator.get_op_input_names(self.type):
                if hasattr(self, "inputs") and in_name in self.inputs:
                    kwargs[in_name] = in_name
                    scope.set(in_name, np.asarray(self.inputs[in_name]))
            for out_name in Operator.get_op_output_names(self.type):
                if not hasattr(self, "outputs"):
                    raise ValueError("the test op must set self.outputs")
                if out_name not in self.outputs:
                    raise ValueError(
                        f"{out_name} is not in self.outputs"
                    )
                kwargs[out_name] = out_name
            for attr_name in Operator.get_op_attr_names(self.type):
                if hasattr(self, "attrs") and attr_name in self.attrs:
                    kwargs[attr_name] = self.attrs[attr_name]

            op = Operator(self.type, **kwargs)
            op.run(scope)

            for out_name in Operator.get_op_output_names(self.type):
                actual = np.asarray(scope.get(out_name))
                expect = np.asarray(self.outputs[out_name])
                np.testing.assert_allclose(
                    actual, expect, rtol=1e-4, atol=1e-5,
                    err_msg=f"output {out_name} has diff",
                )

        obj.test_all = test_all
        return obj
