"""paddle.v2.framework — the new-op-framework namespace.

Reference: python/paddle/v2/framework/__init__.py (which exposes the
pybind `core` module, the Operator factory in `op.py`, and
`default_scope_funcs`). Here the engine is paddle_tpu.framework
(pure-jax op kernels over Scopes — SURVEY.md §2 rows 25-26); this
namespace reproduces the reference's user-facing module layout,
including the generic test harness (`gradient_checker`,
`op_test_util`) that reference op tests import.
"""

__all__ = ["core", "op", "default_scope_funcs"]
