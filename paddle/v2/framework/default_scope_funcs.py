"""Thread-local default-scope stack.

Reference: python/paddle/v2/framework/default_scope_funcs.py — a
thread-local stack of Scopes; new_var/find_var act on the top;
scoped_function runs a callable inside a fresh local scope.
"""

from __future__ import annotations

import threading

from paddle.v2.framework.core import Scope

__tl_scope__ = threading.local()

__all__ = [
    "get_cur_scope",
    "enter_local_scope",
    "leave_local_scope",
    "new_var",
    "find_var",
    "scoped_function",
]


def get_cur_scope() -> Scope:
    stack = getattr(__tl_scope__, "cur_scope", None)
    if stack is None:
        stack = __tl_scope__.cur_scope = []
    if not stack:
        stack.append(Scope())
    return stack[-1]


def enter_local_scope() -> None:
    cur = get_cur_scope()
    __tl_scope__.cur_scope.append(cur.new_scope())


def leave_local_scope() -> None:
    __tl_scope__.cur_scope.pop()
    get_cur_scope().drop_kids()


def new_var(name: str):
    return get_cur_scope().new_var(name)


def find_var(name: str):
    return get_cur_scope().find_var(name)


def scoped_function(fn) -> None:
    enter_local_scope()
    try:
        fn()
    finally:
        leave_local_scope()
