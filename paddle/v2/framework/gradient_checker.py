"""Generic numeric gradient checking for framework ops.

Reference: python/paddle/v2/framework/tests/gradient_checker.py — a
reusable, per-op harness: `get_numeric_gradient` central-differences any
op's input against the sum of one output; `GradientChecker.check_grad`
runs the registered backward op and compares, with the reference's
relative-error rule (abs error where the analytic grad is ~0).

TPU-first divergence: kernels are pure jax functions, so the numeric
probe perturbs a host numpy copy and re-runs the eager kernel — no
tensor set_dims/alloc choreography — and the analytic side comes from
the op-transposition backward net (paddle_tpu.framework.backward), the
same graph jit would compile.
"""

from __future__ import annotations

import unittest

import numpy as np

from paddle.v2.framework.core import Scope
from paddle.v2.framework.op import Operator
from paddle_tpu.framework.backward import backward as _build_backward
from paddle_tpu.framework.op import EMPTY_VAR, GRAD_SUFFIX

__all__ = ["get_numeric_gradient", "GradientChecker", "create_op",
           "grad_var_name"]


def grad_var_name(var_name: str) -> str:
    return var_name + GRAD_SUFFIX


def create_op(op_type: str):
    """Op with every slot wired to its own name (reference
    gradient_checker.create_op)."""
    kwargs = {}
    for name in Operator.get_op_input_names(op_type):
        kwargs[name] = name
    for name in Operator.get_op_output_names(op_type):
        kwargs[name] = name
    return Operator(op_type, **kwargs)


def _run_forward(op, input_values: dict) -> Scope:
    import jax.numpy as jnp

    scope = Scope()
    for name, value in input_values.items():
        # kernels are jax functions (e.g. scatter's .at[] updates);
        # integer index arrays keep their dtype
        scope.set(name, jnp.asarray(value))
    op.run(scope)
    return scope


def get_numeric_gradient(op, input_values: dict, output_name: str,
                         input_to_check: str, delta: float = 0.005):
    """d(sum(output_name)) / d(input_to_check) by central differences.
    Perturbs one element at a time, exactly the reference's method."""
    base = {}
    for k, v in input_values.items():
        a = np.asarray(v)
        # float inputs get float64 probes; integer inputs (indices,
        # labels) keep their dtype
        base[k] = (
            a.astype(np.float64)
            if np.issubdtype(a.dtype, np.floating)
            else a.copy()
        )
    x = base[input_to_check]
    grad = np.zeros(x.size, np.float64)

    def out_sum() -> float:
        return float(np.sum(np.asarray(_run_forward(op, base).get(
            output_name))))

    flat = x.reshape(-1)
    for i in range(x.size):
        origin = flat[i]
        flat[i] = origin + delta
        y_pos = out_sum()
        flat[i] = origin - delta
        y_neg = out_sum()
        flat[i] = origin
        grad[i] = (y_pos - y_neg) / (2.0 * delta)
    return grad.reshape(x.shape).astype(np.float32)


class GradientChecker(unittest.TestCase):
    """Reusable base class: subclass and call check_grad with any
    registered op (reference GradientChecker.check_grad)."""

    def assert_is_close(self, numeric_grads: dict, scope: Scope,
                        max_relative_error: float, msg_prefix: str):
        for name, a in numeric_grads.items():
            b = np.asarray(scope.get(grad_var_name(name)))
            abs_a = np.abs(a)
            # near-zero analytic entries use absolute error (reference
            # rule: relative error blows up around 0)
            abs_a[abs_a < 1e-3] = 1.0
            diff = np.abs(a - b) / abs_a
            max_diff = float(np.max(diff))
            self.assertLessEqual(
                max_diff, max_relative_error,
                f"{msg_prefix} variable {name}: max gradient diff "
                f"{max_diff} over limit {max_relative_error}",
            )

    def check_grad(self, forward_op, input_vars: dict,
                   inputs_to_check, output_name: str,
                   no_grad_set=None, max_relative_error: float = 0.005):
        no_grad_set = set(no_grad_set or ())
        in_names = forward_op.input_vars()
        for no_grad in no_grad_set:
            if no_grad not in in_names:
                raise ValueError(f"no_grad {no_grad!r} not an op input")

        # numeric side
        numeric = {
            name: get_numeric_gradient(
                forward_op, input_vars, output_name, name
            )
            for name in inputs_to_check
        }

        # analytic side: forward once, seed d(output)=ones, run the
        # transposed net
        scope = _run_forward(forward_op, input_vars)
        backward_op = _build_backward(
            forward_op, no_grad_set, seeded={output_name}
        )
        for names in forward_op.outputs.values():
            for n in names:
                if n != EMPTY_VAR:
                    out = np.asarray(scope.get(n))
                    scope.set(
                        grad_var_name(n),
                        np.ones(out.shape, np.float32)
                        if n == output_name
                        else np.zeros(out.shape, np.float32),
                    )
        backward_op.run(scope)
        self.assert_is_close(
            numeric, scope, max_relative_error, "gradient check:"
        )
