"""paddle.v2.layer — the v2 layer namespace.

Reference: python/paddle/v2/layer.py — v2 wraps every
trainer_config_helpers layer function, renaming per __convert_name__
(layer.py:56-74): strip the `_layer` suffix, `maxid_layer` -> `max_id`,
keep `*memory`/`*_seq`/`*_sim`/`hsigmoid`/`*_cost` spellings, and give
the bare cross-entropy family a `_cost` suffix. `layer.data` takes a
`paddle.v2.data_type` InputType instead of a raw size (layer.py:89-93).

Every call lands in the ambient global graph (config_base); Topology
later prunes to the ancestor closure of the requested outputs.
"""

from __future__ import annotations

from paddle_tpu.compat import layers_v1 as _v1

from . import config_base

__all__ = ["data", "parse_network"]

_KEEP = {"memory"}  # callables re-exported under their v1 name


def __convert_name__(inname: str) -> str:
    if inname == "maxid_layer":
        return "max_id"
    if (
        inname.endswith("memory")
        or inname.endswith("_seq")
        or inname.endswith("_sim")
        or inname == "hsigmoid"
    ):
        return inname
    if inname in (
        "cross_entropy",
        "multi_binary_label_cross_entropy",
        "cross_entropy_with_selfnorm",
    ):
        return inname + "_cost"
    if inname.endswith("_cost"):
        return inname
    if inname.endswith("_layer"):
        return inname[: -len("_layer")]
    return inname


def _wrap(fn, new_name):
    def wrapped(*args, **kwargs):
        config_base.global_graph()  # ensure the ambient scope exists
        return fn(*args, **kwargs)

    wrapped.__name__ = new_name
    wrapped.__doc__ = fn.__doc__
    return wrapped


for _name in _v1.__all__:
    if _name in ("model_scope",):
        continue
    _obj = getattr(_v1, _name)
    _new = __convert_name__(_name)
    if callable(_obj) and not isinstance(_obj, type):
        globals()[_new] = _wrap(_obj, _new)
    else:
        globals()[_new] = _obj
    if _new not in __all__:
        __all__.append(_new)


def data(name, type, **kwargs):
    """v2 data layer: width and slot-ness come from the InputType
    (reference layer.py:89 __data_layer__)."""
    config_base.global_graph()
    t = type
    l = _v1.data_layer(
        name,
        t.size,
        is_ids=(t.kind == "ids"),
        is_seq=(t.seq >= 1),
        has_subseq=(t.seq == 2),
        **kwargs,
    )
    config_base.DATA_TYPES[l.name] = t
    # expose the slot type on the handle (reference layer.py:90 sets
    # l.data_type; the mnist api driver reads `images.type`)
    object.__setattr__(l, "type", t)
    object.__setattr__(l, "data_type", t)
    return l


def parse_network(*outputs, **kwargs):
    """Return the pruned ModelConf for the given output layers
    (reference layer.py:263 parse_network -> ModelConfig proto)."""
    from .topology import Topology

    return Topology(list(outputs), kwargs.get("extra_layers")).proto()
