"""paddle.v2.pooling — v2 names for pooling types.

Reference: python/paddle/v2/pooling.py (Max = MaxPooling, ...).
"""

from paddle_tpu.compat.config_parser import (
    AvgPooling as Avg,
    MaxPooling as Max,
    SqrtAvgPooling as SqrtAvg,
    SumPooling as Sum,
)

CudnnMax = Max
CudnnAvg = Avg

__all__ = ["Max", "Avg", "Sum", "SqrtAvg", "CudnnMax", "CudnnAvg"]
