"""paddle.v2.image — image loading/augmentation helpers.

Reference: python/paddle/v2/image.py. Backed by paddle_tpu.image.
"""

from paddle_tpu.image import *  # noqa: F401,F403
from paddle_tpu.image import __all__  # noqa: F401
