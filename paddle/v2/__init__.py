"""paddle.v2 — the reference's flagship user API, TPU-native.

Reference: python/paddle/v2/__init__.py — `import paddle.v2 as paddle`
then paddle.init(...), paddle.layer.*, paddle.batch, paddle.trainer.SGD,
paddle.infer. Layer calls build an ambient graph (the analogue of
cp.begin_parse()'s global config); Topology prunes it per trainer/infer.

The SWIG/GradientMachine substrate is replaced by paddle_tpu's
jit-compiled Network/TrainStep; `use_gpu` and co. map onto paddle_tpu
flags where a TPU-side equivalent exists and are ignored (with the
reference's permissive env-var semantics) where they are GPU-specific.
"""

from __future__ import annotations

import os

from . import (  # noqa: F401
    activation,
    attr,
    config_base,
    data_feeder,
    data_type,
    dataset,
    evaluator,
    event,
    image,
    inference,
    layer,
    master,
    minibatch,
    model,
    networks,
    op,
    optimizer,
    parameters,
    plot,
    pooling,
    reader,
    topology,
    trainer,
)

__all__ = [
    "optimizer", "layer", "activation", "parameters", "init", "trainer",
    "event", "data_type", "attr", "pooling", "dataset", "reader",
    "topology", "networks", "infer", "plot", "evaluator", "image",
    "master", "model", "batch",
]

# open the ambient config graph (the reference's cp.begin_parse())
config_base.global_graph()

batch = minibatch.batch
infer = inference.infer

# v2 init kwargs / PADDLE_INIT_* env vars -> paddle_tpu flags
_FLAG_MAP = {
    "seed": "seed",
    "log_period": "log_period",
    "show_parameter_stats_period": "show_parameter_stats_period",
    "save_dir": "save_dir",
    "saving_period": "saving_period",
    "start_pass": "start_pass",
    "beam_size": "beam_size",
}


def init(**kwargs):
    """paddle.init(use_gpu=..., trainer_count=..., seed=...) — the
    reference collects PADDLE_INIT_* env vars plus kwargs into gflags
    (v2/__init__.py:63-88). Device placement is the mesh's job here:
    trainer_count maps to the data-mesh axis; use_gpu is accepted and
    ignored (the backend is TPU/XLA)."""
    from paddle_tpu.core import flags as _flags

    args = {}
    for ek, ev in os.environ.items():
        if ek.startswith("PADDLE_INIT_"):
            args[ek.replace("PADDLE_INIT_", "").lower()] = str(ev)
    args.update(kwargs)

    for k, v in args.items():
        if k in _FLAG_MAP:
            _flags.set_flag(_FLAG_MAP[k], type(_flags.get_flag(_FLAG_MAP[k]))(v)
                            if _flags.get_flag(_FLAG_MAP[k]) is not None
                            else v)
        elif k == "trainer_count":
            n = int(v)
            if n > 1:
                _flags.set_flag("mesh_shape", {"data": n})
        # use_gpu, gpu_id, parallel_nn, ... are device-model specific
        # to the reference; accepted and ignored.
