"""paddle.v2.inference — run a topology forward on numpy samples.

Reference: python/paddle/v2/inference.py:9 (class Inference) and :93
(infer(output_layer, parameters, input, feeding, field)).
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.network import Network

from .data_feeder import DataFeeder
from .topology import Topology

__all__ = ["Inference", "infer"]


class Inference:
    def __init__(self, output_layer, parameters):
        if not isinstance(output_layer, (list, tuple)):
            output_layer = [output_layer]
        self.__topology__ = Topology(list(output_layer), with_evaluators=False)
        self.__net__ = Network(self.__topology__.proto())
        self.__params__ = parameters._to_device()
        self.__outputs__ = [getattr(x, "name", x) for x in output_layer]

    def infer(self, input, feeding=None, field="value"):
        types = self.__topology__.data_type()
        feeder = DataFeeder(types, feeding)
        feed = feeder(input)
        outs, _ = self.__net__.forward(
            self.__params__, feed, train=False, outputs=self.__outputs__
        )
        fields = [field] if isinstance(field, str) else list(field)
        results = []
        for f in fields:
            cols = []
            for name in self.__outputs__:
                a = outs[name]
                if f == "value":
                    cols.append(np.asarray(a.value))
                elif f == "id":
                    cols.append(np.asarray(a.ids))
                else:
                    raise ValueError(f"unsupported field {f!r}")
            results.append(cols[0] if len(cols) == 1 else cols)
        return results[0] if isinstance(field, str) else results


def infer(output_layer, parameters, input, feeding=None, field="value"):
    return Inference(output_layer, parameters).infer(
        input, feeding=feeding, field=field
    )
