"""paddle.v2.evaluator — declare metric evaluators on the topology.

Reference: python/paddle/v2/evaluator.py — v2 re-exports the
trainer_config_helpers evaluator declarations with the `_evaluator`
suffix stripped (classification_error_evaluator ->
evaluator.classification_error). A declaration attaches to the ambient
graph; trainer.SGD picks up every evaluator whose input layers are in
the trained topology and reports it through event metrics.
"""

from __future__ import annotations

from . import config_base

__all__ = []


def _uniquify(base: str) -> str:
    """First free name among base, base_1, base_2, ... — the reference
    config parser auto-suffixes repeated evaluator names so two
    same-type declarations don't shadow each other in the trainer
    metrics dict."""
    taken = {e.get("name") for e in config_base.EVALUATORS}
    ev_name, i = base, 0
    while ev_name in taken:
        i += 1
        ev_name = f"{base}_{i}"
    return ev_name


def _declare(type_, input=None, label=None, name=None, **kw):
    config_base.global_graph()
    if isinstance(input, (list, tuple)):
        # one conf per input; names must stay distinct or their metrics
        # would shadow each other in the trainer's results dict — and
        # the derived base must itself be uniquified when defaulted, or
        # a second list declaration of the same type collides
        base = name if name is not None else _uniquify(type_)
        return [
            _declare(type_, x, label, f"{base}_{i}" if i else base, **kw)
            for i, x in enumerate(input)
        ]
    conf = {"type": type_}
    conf["name"] = name if name is not None else _uniquify(type_)
    if input is not None:
        conf["input"] = getattr(input, "name", input)
    if label is not None:
        conf["label"] = getattr(label, "name", label)
    for k, v in kw.items():
        if v is not None:
            conf[k] = v
    config_base.EVALUATORS.append(conf)
    return conf


def _make(new_name, type_):
    def fn(input, label=None, name=None, **kw):
        return _declare(type_, input, label, name, **kw)

    fn.__name__ = new_name
    fn.__doc__ = f"v2 declaration of the {type_!r} evaluator"
    __all__.append(new_name)
    return fn


classification_error = _make("classification_error", "classification_error")
sum = _make("sum", "sum")
column_sum = _make("column_sum", "column_sum")
precision_recall = _make("precision_recall", "precision_recall")
pnpair = _make("pnpair", "pnpair")
auc = _make("auc", "rankauc")
chunk = _make("chunk", "chunk")
ctc_error = _make("ctc_error", "ctc_edit_distance")
value_printer = _make("value_printer", "value_printer")
gradient_printer = _make("gradient_printer", "gradient_printer")
maxid_printer = _make("maxid_printer", "max_id_printer")
maxframe_printer = _make("maxframe_printer", "max_frame_printer")
seqtext_printer = _make("seqtext_printer", "seq_text_printer")
classification_error_printer = _make(
    "classification_error_printer", "classification_error_printer"
)
detection_map = _make("detection_map", "detection_map")
