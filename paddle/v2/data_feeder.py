"""paddle.v2.data_feeder — DataFeeder re-export.

Reference: python/paddle/v2/data_feeder.py (DataFeeder(data_types,
feeding) converting sample tuples into swig Arguments via
DataProviderConverter). Backed by paddle_tpu.data.feeder.DataFeeder
(ragged -> packed dense batches) for the trainer path; the returned
batch ALSO exposes the reference Arguments slot surface
(getSlotValue/getSlotIds/getSlotSequenceStartPositions/
getSlotFrameHeight...), slot-indexed in data_types order, so reference
programs that inspect the converted batch run unmodified
(python/paddle/v2/tests/test_data_feeder.py).
"""

import jax
import numpy as np

from paddle_tpu.compat import swig_api as _api
from paddle_tpu.data.feeder import DataFeeder as _DataFeeder

__all__ = ["DataFeeder"]


class FeedBatch(dict):
    """The feed dict (layer name -> Arg) with the reference Arguments
    slot surface layered on top. Slot i is data_types[i]; accessors
    compute from the raw sample column, so sparse slots report their
    original indices (not the densified packing the trainer consumes).
    """

    def __init__(self, feed, slots):
        super().__init__(feed)
        self._slots = slots  # [(name, InputType, raw_column)]

    def getSlotNum(self):
        return len(self._slots)

    def getSlotValue(self, i) -> _api.Matrix:
        _, t, col = self._slots[i]
        if t.kind in ("sparse_binary", "sparse_float"):
            # sequence slots flatten timesteps into rows (the
            # reference's padding-free (sum_T, dim) matrix)
            rows = (
                [step for s in col for step in s] if t.seq else col
            )
            return _api.SparseMatrix(
                rows, t.shape[0], with_values=t.kind == "sparse_float"
            )
        if t.seq:
            rows = [
                np.asarray(s, np.float32).reshape(-1, t.size)
                for s in col
            ]
            return _api.Matrix.createDenseFromNumpy(
                np.concatenate(rows, axis=0)
            )
        flat = [np.asarray(s, np.float32).ravel() for s in col]
        return _api.Matrix.createDenseFromNumpy(np.stack(flat))

    def getSlotIds(self, i) -> _api.IVector:
        _, t, col = self._slots[i]
        if t.seq:
            return _api.IVector(
                np.concatenate(
                    [np.asarray(s, np.int32).ravel() for s in col]
                )
            )
        return _api.IVector(np.asarray(col, np.int32).ravel())

    def _row_counts(self, i):
        """Timesteps per sample — id slots count ids, dense slots count
        dim-wide rows, sparse slots count per-step index lists."""
        _, t, col = self._slots[i]
        if t.kind == "ids":
            return [len(np.asarray(s).ravel()) for s in col]
        if t.kind == "dense":
            return [
                np.asarray(s, np.float32).reshape(-1, t.size).shape[0]
                for s in col
            ]
        return [len(s) for s in col]

    def getSlotSequenceStartPositions(self, i) -> _api.IVector:
        lens = self._row_counts(i)
        return _api.IVector(np.concatenate([[0], np.cumsum(lens)]))

    def getSlotFrameHeight(self, i) -> int:
        _, _, col = self._slots[i]
        a = np.asarray(col[0])
        return int(a.shape[-2]) if a.ndim >= 2 else 0

    def getSlotFrameWidth(self, i) -> int:
        _, _, col = self._slots[i]
        a = np.asarray(col[0])
        return int(a.shape[-1]) if a.ndim >= 2 else 0


def _feed_batch_flatten(fb):
    keys = sorted(fb.keys())
    return [fb[k] for k in keys], tuple(keys)


def _feed_batch_unflatten(keys, vals):
    # a plain feed dict — the slot columns are host-side metadata and
    # don't survive tracing
    return dict(zip(keys, vals))


# jit sees FeedBatch as the feed dict (dict subclasses aren't pytrees
# by default; without this the trainer can't take a FeedBatch feed)
jax.tree_util.register_pytree_node(
    FeedBatch, _feed_batch_flatten, _feed_batch_unflatten
)


class DataFeeder(_DataFeeder):
    def __init__(self, feeding, types=None):
        # v2 call shape: DataFeeder(data_types, feeding) where
        # data_types is [(name, InputType)]; internal call shape:
        # DataFeeder(feeding_dict, types_dict)
        self._slot_order = None
        if types is None or (
            isinstance(feeding, (list, tuple))
            and feeding
            and isinstance(feeding[0], (list, tuple))
        ):
            data_types, feeding = feeding, types
            types = dict(data_types)
            self._slot_order = [n for n, _ in data_types]
            if feeding is None:
                feeding = {n: i for i, (n, _) in enumerate(data_types)}
            elif isinstance(feeding, (list, tuple)):
                feeding = {n: i for i, n in enumerate(feeding)}
            feeding = {k: v for k, v in feeding.items() if k in types}
        super().__init__(feeding, types)

    def convert(self, batch):
        feed = super().convert(batch)
        order = self._slot_order or list(self.feeding)
        slots = [
            (
                n,
                self.types[n],
                [sample[self.feeding[n]] for sample in batch],
            )
            for n in order
        ]
        return FeedBatch(feed, slots)
