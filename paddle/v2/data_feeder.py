"""paddle.v2.data_feeder — DataFeeder re-export.

Reference: python/paddle/v2/data_feeder.py (DataFeeder(data_types,
feeding) converting sample tuples into Arguments). Backed by
paddle_tpu.data.feeder.DataFeeder (ragged -> packed dense batches).
"""

from paddle_tpu.data.feeder import DataFeeder as _DataFeeder

__all__ = ["DataFeeder"]


class DataFeeder(_DataFeeder):
    def __init__(self, feeding, types=None):
        # v2 call shape: DataFeeder(data_types, feeding) where
        # data_types is [(name, InputType)]; internal call shape:
        # DataFeeder(feeding_dict, types_dict)
        if types is None or (
            isinstance(feeding, (list, tuple))
            and feeding
            and isinstance(feeding[0], (list, tuple))
        ):
            data_types, feeding = feeding, types
            types = dict(data_types)
            if feeding is None:
                feeding = {n: i for i, (n, _) in enumerate(data_types)}
            elif isinstance(feeding, (list, tuple)):
                feeding = {n: i for i, n in enumerate(feeding)}
            feeding = {k: v for k, v in feeding.items() if k in types}
        super().__init__(feeding, types)
