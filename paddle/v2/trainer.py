"""paddle.v2.trainer — the event-driven SGD training loop.

Reference: python/paddle/v2/trainer.py:24 (class SGD) and :145-176 (the
pass/batch loop: BeginPass, per batch BeginIteration -> feed ->
forwardBackward+update -> eval -> EndIteration(cost, batch evaluator),
per pass test/EndPass(pass evaluator)). The SWIG GradientMachine +
ParameterUpdater pair collapses into one paddle_tpu jit-compiled
TrainStep; `is_local=False` needs no pserver processes — the same
program runs SPMD over the device mesh.
"""

from __future__ import annotations

from paddle_tpu.evaluators import create_evaluator
from paddle_tpu.trainer.trainer import SGD as _Engine

from . import event as v2_event
from . import optimizer as v2_optimizer
from . import parameters as v2_parameters
from .data_feeder import DataFeeder
from .topology import Topology

__all__ = ["SGD"]


def default_event_handler(event):
    pass


class SGD:
    def __init__(
        self,
        cost,
        parameters,
        update_equation,
        extra_layers=None,
        is_local=True,
        pserver_spec=None,
        use_etcd=True,
    ):
        if not isinstance(parameters, v2_parameters.Parameters):
            raise TypeError("parameters should be parameters")
        if not isinstance(update_equation, v2_optimizer.Optimizer):
            raise TypeError(
                "update equation parameter must be "
                "paddle.v2.optimizer.Optimizer"
            )
        topology = Topology(cost, extra_layers=extra_layers)
        self.__optimizer__ = update_equation
        self.__topology__ = topology
        self.__parameters__ = parameters
        self.__data_types__ = topology.data_type()
        self.__engine__ = _Engine(
            topology.proto(),
            update_equation.conf,
            evaluators=topology.evaluator_confs,
            params=parameters._to_device(),
        )

    def __make_feeder__(self, feeding):
        if feeding is None:
            feeding = {name: i for i, (name, _) in
                       enumerate(self.__data_types__)}
        elif isinstance(feeding, (list, tuple)):
            feeding = {name: i for i, name in enumerate(feeding)}
        types = dict(self.__data_types__)
        used = {n for n, _ in self.__data_types__}
        return DataFeeder(
            {k: v for k, v in feeding.items() if k in used}, types
        )

    def save_parameter_to_tar(self, f):
        self.__parameters__._sync_from(self.__engine__.params)
        self.__parameters__.__param_confs__ = dict(
            self.__engine__.net.param_confs
        )
        self.__parameters__.to_tar(f)

    def train(self, reader, num_passes=1, event_handler=None, feeding=None):
        """Train num_passes over the batched reader (reference
        trainer.py:110,145-176)."""
        if event_handler is None:
            event_handler = default_event_handler
        if not callable(reader):
            raise TypeError(
                "train reader should be a function returning an iterator"
            )
        if not callable(event_handler):
            raise TypeError("event handler should be a function")
        feeder = self.__make_feeder__(feeding)
        engine = self.__engine__
        for pass_id in range(num_passes):
            event_handler(v2_event.BeginPass(pass_id))
            pass_evals = [
                create_evaluator(c) for c in self.__topology__.evaluator_confs
            ]
            for batch_id, data_batch in enumerate(reader()):
                event_handler(
                    v2_event.BeginIteration(pass_id=pass_id,
                                            batch_id=batch_id)
                )
                feed = feeder(data_batch)
                # run_step understands the engine's watchdog mode
                # (the step returns a [loss, finite] health vector and
                # skips non-finite updates on device)
                cost, _finite, outs = engine.run_step(feed)
                batch_results = {}
                for conf, ev in zip(
                    self.__topology__.evaluator_confs, pass_evals
                ):
                    ev.add_batch(outs, feed)
                    batch_ev = create_evaluator(conf)
                    batch_ev.add_batch(outs, feed)
                    batch_results[batch_ev.name] = batch_ev.result()
                event_handler(
                    v2_event.EndIteration(
                        pass_id=pass_id,
                        batch_id=batch_id,
                        cost=cost,
                        evaluator=v2_event.EvalResults(batch_results),
                    )
                )
            self.__parameters__._sync_from(engine.params)
            event_handler(
                v2_event.EndPass(
                    pass_id,
                    evaluator=v2_event.EvalResults(
                        {ev.name: ev.result() for ev in pass_evals}
                    ),
                )
            )

    def test(self, reader, feeding=None):
        """Evaluate over the batched reader; returns TestResult
        (reference trainer.py:178-205)."""
        feeder = self.__make_feeder__(feeding)
        res = self.__engine__.test(reader, feeder)
        return v2_event.TestResult(
            evaluator=v2_event.EvalResults(res["evaluators"]),
            cost=res["cost"],
        )


def __check_train_args__(reader, event_handler, **kwargs):
    if not callable(reader):
        raise TypeError("train_data_reader should be a function")
    if not callable(event_handler):
        raise TypeError("event handler should be a function")
