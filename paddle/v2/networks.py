"""paddle.v2.networks — prebuilt network compositions.

Reference: python/paddle/v2/networks.py re-exports
trainer_config_helpers.networks under the same names
(simple_img_conv_pool networks.py:145, img_conv_group :333,
vgg_16_network :465, simple_lstm :548, simple_gru :975,
bidirectional_lstm :1207, simple_attention :1298).
"""

from paddle_tpu.compat.layers_v1 import (
    bidirectional_lstm,
    img_conv_group,
    simple_attention,
    simple_gru,
    simple_img_conv_pool,
    simple_lstm,
    small_vgg,
    vgg_16_network,
)

__all__ = [
    "simple_img_conv_pool", "img_conv_group", "vgg_16_network",
    "simple_lstm", "simple_gru", "bidirectional_lstm",
    "simple_attention", "small_vgg",
]
