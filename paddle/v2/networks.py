"""paddle.v2.networks — prebuilt network compositions.

Reference: python/paddle/v2/networks.py re-exports
trainer_config_helpers.networks under the same names — the FULL set
(networks.py __all__): conv-pool groups (sequence_conv_pool :41 /
text_conv_pool alias, simple_img_conv_pool :145, img_conv_bn_pool
:232, img_conv_group :333, small_vgg :438, vgg_16_network :465), the
rnn helpers (simple_lstm :548, lstmemory_unit :633, lstmemory_group
:744, gru_unit :840, gru_group :902, simple_gru :975, simple_gru2
:1061, bidirectional_gru :1122, bidirectional_lstm :1207) and
simple_attention :1298.
"""

from paddle_tpu.compat.layers_v1 import (
    bidirectional_gru,
    bidirectional_lstm,
    gru_group,
    gru_unit,
    img_conv_bn_pool,
    img_conv_group,
    lstmemory_group,
    lstmemory_unit,
    sequence_conv_pool,
    simple_attention,
    simple_gru,
    simple_gru2,
    simple_img_conv_pool,
    simple_lstm,
    small_vgg,
    text_conv_pool,
    vgg_16_network,
)

# the reference v2 module deliberately EXCLUDES inputs/outputs from
# its re-export (python/paddle/v2/networks.py skips them by name);
# they remain available on the v1 surface (compat/config_parser)
__all__ = [
    "sequence_conv_pool", "simple_lstm", "simple_img_conv_pool",
    "img_conv_bn_pool", "lstmemory_group", "lstmemory_unit",
    "small_vgg", "img_conv_group", "vgg_16_network", "gru_unit",
    "gru_group", "simple_gru", "simple_attention", "simple_gru2",
    "bidirectional_gru", "text_conv_pool", "bidirectional_lstm",
]
