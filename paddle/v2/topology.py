"""Topology — extract the sub-graph reaching a set of output layers.

Reference: python/paddle/v2/topology.py:25 (class Topology over the
parsed ModelConfig proto) and layer.py __get_used_layers__ pruning.
Here the ambient graph is a paddle_tpu ModelConf under construction;
Topology computes the ancestor closure of the requested outputs (plus
extra_layers), keeps declaration order, carries the recurrent-group
sub-models whose layers intersect the closure, and exposes the
data-layer types for DataFeeder.
"""

from __future__ import annotations

import copy

from paddle_tpu.core.config import ModelConf

from . import config_base


def _as_names(layers):
    """Flatten arbitrarily nested layer/list arguments to names —
    reference parse_network accepts mixed nesting
    (`parse_network([maxpool, spp], other)`, layer.py:263)."""
    if layers is None:
        return []
    if not isinstance(layers, (list, tuple)):
        layers = [layers]
    out = []
    for x in layers:
        if isinstance(x, (list, tuple)):
            out.extend(_as_names(x))
        else:
            out.append(getattr(x, "name", x))
    return out


class Topology:
    def __init__(self, layers, extra_layers=None, with_evaluators=True):
        self.output_names = _as_names(layers)
        if not self.output_names:
            raise ValueError("Topology needs at least one output layer")
        extra = _as_names(extra_layers)
        g = config_base.global_graph()
        self._src_builder = g
        src = g.conf

        by_name = {lc.name: lc for lc in src.layers}
        # extra outputs ('producer@arg') resolve to their producer
        def resolve(n):
            return n.split("@")[0] if n not in by_name and "@" in n else n

        keep = set()
        frontier = [resolve(n) for n in self.output_names + extra]
        while frontier:
            n = frontier.pop()
            if n in keep:
                continue
            if n not in by_name:
                raise KeyError(f"layer {n!r} not found in the config graph")
            keep.add(n)
            frontier.extend(
                resolve(i) for i in by_name[n].input_names()
            )
            # a layer inside a recurrent group pulls in the whole group
            for sm in src.sub_models:
                if n in sm.layer_names:
                    frontier.extend(sm.layer_names)
                    for link in list(sm.in_links) + list(sm.out_links):
                        frontier.append(resolve(link["layer_name"]))
                    for mem in sm.memories:
                        for k in ("layer_name", "link_name", "boot_layer_name"):
                            v = mem.get(k)
                            if v:
                                frontier.append(resolve(v))

        conf = ModelConf(
            layers=[copy.deepcopy(lc) for lc in src.layers if lc.name in keep],
            sub_models=[
                copy.deepcopy(sm)
                for sm in src.sub_models
                if any(n in keep for n in sm.layer_names)
            ],
            output_layer_names=list(self.output_names),
        )
        conf.input_layer_names = [
            lc.name for lc in conf.layers if lc.type == "data"
        ]
        self.conf = conf
        # Reference semantics (layer.py __get_used_evaluators__): prune
        # from outputs only, then keep evaluators whose inputs are
        # already inside the closure — a declared-but-unrelated
        # evaluator must not widen the topology or add required feeds.
        self.evaluator_confs = (
            [
                ev
                for ev in config_base.EVALUATORS
                if all(
                    resolve(ev[k]) in keep
                    for k in ("input", "label", "query_id")
                    if k in ev
                )
            ]
            if with_evaluators
            else []
        )

    def proto(self) -> ModelConf:
        """The pruned ModelConf (the analogue of topology.proto())."""
        return self.conf

    def get_layer(self, name: str):
        """The layer handle for `name` (reference topology.py
        get_layer). LayerRef equality is structural (frozen dataclass
        over (name, graph)), so this compares equal to the handle the
        original layer call returned."""
        from paddle_tpu import dsl

        if not any(lc.name == name for lc in self.conf.layers):
            raise ValueError(f"layer {name!r} not in this topology")
        return dsl.LayerRef(name, self._src_builder)

    def data_type(self):
        """[(data_layer_name, InputType)] in declaration order
        (reference topology.py data_type())."""
        out = []
        for lc in self.conf.layers:
            if lc.type != "data":
                continue
            t = config_base.DATA_TYPES.get(lc.name)
            if t is None:
                raise ValueError(
                    f"data layer {lc.name!r} has no v2 data type — declare "
                    f"it with paddle.v2.layer.data(name=..., type=...)"
                )
            out.append((lc.name, t))
        return out

    def data_layers(self):
        return [lc.name for lc in self.conf.layers if lc.type == "data"]
