"""paddle.v2.minibatch.batch — group a sample reader into mini-batches.

Reference: python/paddle/v2/minibatch.py:22-41 (yields the trailing
partial batch too).
"""


from paddle_tpu.data.reader import batched


def batch(reader, batch_size):
    return batched(reader, batch_size, drop_last=False)


__all__ = ["batch"]
