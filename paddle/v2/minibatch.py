"""paddle.v2.minibatch.batch — group a sample reader into mini-batches.

Reference: python/paddle/v2/minibatch.py:22-41 (yields the trailing
partial batch too).
"""


def batch(reader, batch_size):
    def batch_reader():
        b = []
        for instance in reader():
            b.append(instance)
            if len(b) == batch_size:
                yield b
                b = []
        if b:
            yield b

    return batch_reader


__all__ = ["batch"]
