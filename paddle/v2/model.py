"""paddle.v2.model — save/load parameters to a shared filesystem path.

Reference: python/paddle/v2/model.py (save_model/load_model with the
cloud TRAINER_ID election reduced to the coordinator process here —
model-save election on TPU pods is process_id == 0, the same exactly-
one-writer guarantee go/master/service.go:467-495 provides via etcd).
"""

from __future__ import annotations

import errno
import os

from paddle_tpu.core import flags as _flags

__all__ = ["save_model", "load_model"]


def mkdir_p(path):
    try:
        os.makedirs(path)
    except OSError as exc:
        if exc.errno != errno.EEXIST or not os.path.isdir(path):
            raise


def save_model(parameters, path):
    if _flags.get_flag("process_id") != 0:
        return  # exactly one writer
    d = os.path.dirname(path)
    if d:
        mkdir_p(d)
    with open(path, "wb") as f:
        parameters.to_tar(f)


def load_model(parameters, path):
    with open(path, "rb") as f:
        parameters.init_from_tar(f)
