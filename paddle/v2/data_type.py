"""paddle.v2.data_type — input type declarations.

Reference: python/paddle/v2/data_type.py (re-exports the
PyDataProvider2 input types). Maps onto paddle_tpu.data.feeder's
InputType constructors; `sparse_vector` is the v2 spelling of
sparse_float_vector.
"""

from paddle_tpu.data.feeder import (
    InputType,
    dense_vector,
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_float_vector,
)

sparse_vector = sparse_float_vector

# variable-shape dense feature (PyDataProvider2.py:147 dense_array =
# dense_slot); the feeder reads frame height/width off 3-D samples
dense_array = dense_vector


def dense_vector_sub_sequence(dim):
    return dense_vector(dim, 2)


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, 1)


def sparse_vector_sequence(dim):
    return sparse_float_vector(dim, 1)


sparse_float_vector_sequence = sparse_vector_sequence

__all__ = [
    "InputType",
    "dense_vector", "dense_vector_sequence", "dense_vector_sub_sequence",
    "integer_value", "integer_value_sequence", "integer_value_sub_sequence",
    "sparse_binary_vector", "sparse_binary_vector_sequence",
    "sparse_float_vector", "sparse_float_vector_sequence",
    "sparse_vector", "sparse_vector_sequence",
    "dense_array",
]
