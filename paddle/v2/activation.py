"""paddle.v2.activation — v2 names for the activation objects.

Reference: python/paddle/v2/activation.py (strips the `Activation`
suffix from trainer_config_helpers.activations class names).
"""

from paddle_tpu.compat.layers_v1 import _make_act as __make

Linear = __make("Linear", "")
Identity = Linear
Relu = __make("Relu", "relu")
Sigmoid = __make("Sigmoid", "sigmoid")
Softmax = __make("Softmax", "softmax")
SequenceSoftmax = __make("SequenceSoftmax", "sequence_softmax")
Tanh = __make("Tanh", "tanh")
STanh = __make("STanh", "stanh")
BRelu = __make("BRelu", "brelu")
SoftRelu = __make("SoftRelu", "softrelu")
Abs = __make("Abs", "abs")
Square = __make("Square", "square")
Exp = __make("Exp", "exponential")
Log = __make("Log", "log")
Sqrt = __make("Sqrt", "sqrt")
Reciprocal = __make("Reciprocal", "reciprocal")

__all__ = [
    "Linear", "Identity", "Relu", "Sigmoid", "Softmax", "SequenceSoftmax",
    "Tanh", "STanh", "BRelu", "SoftRelu", "Abs", "Square", "Exp", "Log",
    "Sqrt", "Reciprocal",
]
