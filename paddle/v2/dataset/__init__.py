"""paddle.v2.dataset — the 13 auto-downloading datasets.

Reference: python/paddle/v2/dataset/__init__.py. Each submodule is the
paddle_tpu.data.dataset module of the same name, aliased into this
package so both `paddle.v2.dataset.mnist.train()` and
`import paddle.v2.dataset.mnist` resolve.
"""

import importlib
import sys

__all__ = [
    "mnist", "imikolov", "imdb", "cifar", "movielens", "conll05",
    "sentiment", "uci_housing", "wmt14", "mq2007", "flowers", "voc2012",
    "common",
]

for _name in __all__:
    _mod = importlib.import_module(f"paddle_tpu.data.dataset.{_name}")
    sys.modules[f"{__name__}.{_name}"] = _mod
    globals()[_name] = _mod
