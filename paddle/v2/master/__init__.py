"""paddle.v2.master — client for the elastic-input master server.

Reference: python/paddle/v2/master/client.py:15 (ctypes wrapper over the
Go master's C bridge). Backed by paddle_tpu.data.master_client, which
speaks the same task-lease protocol to native/src/master_server.cc.
"""

from paddle_tpu.data.master_client import MasterClient as client

__all__ = ["client"]
