"""paddle.v2.optimizer — optimizer (update equation) objects.

Reference: python/paddle/v2/optimizer.py:58-70 (Optimizer base whose
create_updater picks local/remote) and the concrete classes :103-297
(Momentum, Adam, Adamax, AdaGrad, DecayedAdaGrad, AdaDelta, RMSProp),
each forwarding settings kwargs (learning_rate, regularization =
L1/L2Regularization, model_average = ModelAverage, gradient clipping,
LR schedules) to trainer_config_helpers.optimizers.settings.

Here an Optimizer owns a paddle_tpu OptimizationConf; on TPU the
"updater" is the sharded jit step itself (parallel/dp.py), so
create_updater collapses away.
"""

from __future__ import annotations

from paddle_tpu.core.config import OptimizationConf
from paddle_tpu.compat.config_parser import (  # re-exported for user code
    L1Regularization,
    L2Regularization,
    ModelAverage,
)

__all__ = [
    "Optimizer", "Momentum", "Adam", "Adamax", "AdaGrad",
    "DecayedAdaGrad", "AdaDelta", "RMSProp",
    "L1Regularization", "L2Regularization", "ModelAverage",
]


class Optimizer:
    def __init__(self, learning_method="sgd", **kwargs):
        o = OptimizationConf()
        o.learning_method = learning_method
        o.learning_rate = kwargs.pop("learning_rate", 0.01)
        o.batch_size = kwargs.pop("batch_size", 1)
        o.learning_rate_decay_a = kwargs.pop("learning_rate_decay_a", 0.0)
        o.learning_rate_decay_b = kwargs.pop("learning_rate_decay_b", 0.0)
        schedule = kwargs.pop("learning_rate_schedule", None)
        if schedule:
            o.learning_rate_schedule = schedule
        o.learning_rate_args = kwargs.pop("learning_rate_args", "")
        gct = kwargs.pop("gradient_clipping_threshold", None)
        if gct is not None:
            o.gradient_clipping_threshold = gct
        for setting_kw in ("regularization", "model_average"):
            setting = kwargs.pop(setting_kw, None)
            if setting is not None:
                for k, v in setting.fields.items():
                    setattr(o, k, v)
        for k, v in kwargs.items():  # direct OptimizationConf fields
            if hasattr(o, k):
                setattr(o, k, v)
        self.conf = o

    def enable_types(self):
        """Parameter buffer kinds the optimizer maintains (reference
        optimizer.py:44-54); informational here — opt state lives in
        the jit step's optimizer-state pytree."""
        return ["value", "gradient"]

    def create_local_updater(self):
        """api-driven training path (reference optimizer.py:56-58):
        returns the swig-api ParameterUpdater for this optimizer."""
        from paddle_tpu.compat.swig_api import ParameterUpdater

        return ParameterUpdater.createLocalUpdater(self.conf)


class Momentum(Optimizer):
    def __init__(self, momentum=None, sparse=False, **kwargs):
        super().__init__(
            "momentum", momentum=momentum or 0.0, **kwargs
        )


class Adam(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(
            "adam", adam_beta1=beta1, adam_beta2=beta2,
            adam_epsilon=epsilon, **kwargs
        )


class Adamax(Optimizer):
    def __init__(self, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(
            "adamax", adam_beta1=beta1, adam_beta2=beta2, **kwargs
        )


class AdaGrad(Optimizer):
    def __init__(self, **kwargs):
        super().__init__("adagrad", **kwargs)


class DecayedAdaGrad(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        super().__init__(
            "decayed_adagrad", ada_rou=rho, ada_epsilon=epsilon, **kwargs
        )


class AdaDelta(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        super().__init__(
            "adadelta", ada_rou=rho, ada_epsilon=epsilon, **kwargs
        )


class RMSProp(Optimizer):
    def __init__(self, rho=0.95, epsilon=1e-6, **kwargs):
        super().__init__(
            "rmsprop", ada_rou=rho, ada_epsilon=epsilon, **kwargs
        )
