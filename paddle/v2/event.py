"""paddle.v2.event — training events with evaluator metrics.

Reference: python/paddle/v2/event.py: BeginPass/EndPass,
BeginIteration/EndIteration, TestResult; the End* events carry an
evaluator whose `.metrics` property maps metric name -> value
(event.py:15-31 WithMetric). Here the evaluator handle wraps the
already-reduced results of paddle_tpu evaluators, and also offers the
reference Evaluator getter surface (getNames/getValue) used by
api-style drivers.
"""

from __future__ import annotations


class EvalResults:
    """Dict-backed stand-in for the SWIG api.Evaluator handle."""

    def __init__(self, results: dict | None = None):
        self._results = dict(results or {})

    def getNames(self):
        return list(self._results)

    def getValue(self, name):
        return self._results[name]

    def __repr__(self):
        return " ".join(f"{k}={v}" for k, v in self._results.items())


class WithMetric:
    def __init__(self, evaluator):
        if isinstance(evaluator, dict):
            evaluator = EvalResults(evaluator)
        self.__evaluator__ = evaluator

    @property
    def metrics(self):
        return {n: self.__evaluator__.getValue(n)
                for n in self.__evaluator__.getNames()}


class TestResult(WithMetric):
    def __init__(self, evaluator, cost):
        super().__init__(evaluator)
        self.cost = cost


class BeginPass:
    def __init__(self, pass_id):
        self.pass_id = pass_id


class EndPass(WithMetric):
    def __init__(self, pass_id, evaluator):
        self.pass_id = pass_id
        super().__init__(evaluator)


class BeginIteration:
    def __init__(self, pass_id, batch_id):
        self.pass_id = pass_id
        self.batch_id = batch_id


class EndIteration(WithMetric):
    def __init__(self, pass_id, batch_id, cost, evaluator):
        self.pass_id = pass_id
        self.batch_id = batch_id
        self.cost = cost
        super().__init__(evaluator)


__all__ = [
    "EvalResults", "WithMetric", "TestResult",
    "BeginPass", "EndPass", "BeginIteration", "EndIteration",
]
