"""paddle.v2.plot — training-curve plotting.

Reference: python/paddle/v2/plot/. Backed by paddle_tpu.plot.
"""

from paddle_tpu.plot import PlotData, Ploter

__all__ = ["Ploter", "PlotData"]
