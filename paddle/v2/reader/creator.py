"""paddle.v2.reader.creator — readers from arrays/files.

Reference: python/paddle/v2/reader/creator.py (np_array, text_file).
"""

from paddle_tpu.data.reader import np_array, text_file

__all__ = ["np_array", "text_file"]
