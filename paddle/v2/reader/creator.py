"""paddle.v2.reader.creator — readers from arrays/files.

Reference: python/paddle/v2/reader/creator.py (np_array, text_file,
recordio). `recordio` reads the reference recordio wire format
(snappy-framed chunks of pickled records) as well as this framework's
native chunk files.
"""

from paddle_tpu.data.reader import np_array, text_file
from paddle_tpu.data.reader import recordio_interop as recordio

__all__ = ["np_array", "text_file", "recordio"]
