"""paddle.v2.reader — functional reader combinators.

Reference: python/paddle/v2/reader/decorator.py:26-292 and creator.py.
Backed by paddle_tpu.data.reader (same combinator semantics).
"""

from paddle_tpu.data.reader import (
    ComposeNotAligned,
    buffered,
    cache,
    chain,
    compose,
    firstn,
    map_readers,
    shuffle,
)

from . import creator


def xmap_readers(mapper, reader, process_num=1, buffer_size=None, order=False):
    """Reference decorator.xmap_readers: map `mapper` over the reader
    with worker processes. TPU-side the data path is already
    prefetched natively (native/src/recordio.cc), so this is a
    semantically-equal serial map."""

    def new_reader():
        for sample in reader():
            yield mapper(sample)

    return new_reader


__all__ = [
    "map_readers", "buffered", "compose", "chain", "shuffle",
    "ComposeNotAligned", "firstn", "xmap_readers", "cache", "creator",
]
