"""paddle.v2.op — elementwise math ops over layers.

Reference: python/paddle/v2/op.py (unary math ops as identity-projection
mixed layers with the activation applied; add/sub via dsl arithmetic).
"""

from __future__ import annotations

from paddle_tpu.compat import layer_math  # noqa: F401  (patches +,-,*
#    onto LayerRef — reference op.py registers the same operators,
#    op.py __register_binary_math_op__)
from paddle_tpu.compat import layers_v1 as _v1

from . import activation as act
from . import config_base

__all__ = []


def __register_unary_math_op__(op_name, activation):
    def op(input, name=None):
        config_base.global_graph()
        return _v1.mixed_layer(
            0, [_v1.identity_projection(input)], name=name,
            act=activation, bias_attr=False,
        )

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


__register_unary_math_op__("exp", act.Exp())
__register_unary_math_op__("log", act.Log())
__register_unary_math_op__("abs", act.Abs())
__register_unary_math_op__("sigmoid", act.Sigmoid())
__register_unary_math_op__("tanh", act.Tanh())
__register_unary_math_op__("square", act.Square())
__register_unary_math_op__("relu", act.Relu())
__register_unary_math_op__("sqrt", act.Sqrt())
__register_unary_math_op__("reciprocal", act.Reciprocal())
