"""Ambient config state for the v2 API.

Reference: python/paddle/v2/config_base.py + the global config that
`paddle.trainer.config_parser.begin_parse()` opens at import
(python/paddle/v2/__init__.py:62). In v2, layer functions are called at
script top level with no explicit graph scope; every call appends to one
process-global graph, and `Topology(cost)` later extracts the ancestor
closure of the requested outputs.

Here the global graph is a paddle_tpu.dsl.GraphBuilder pushed
permanently onto the dsl scope stack, plus two side tables the v2
surface needs: data-layer input types (v2's `layer.data(type=...)`)
and evaluator declarations (`paddle.v2.evaluator.*`).
"""

from __future__ import annotations

from paddle_tpu import dsl

# data-layer name -> paddle_tpu.data.feeder.InputType
DATA_TYPES: dict = {}
# evaluator conf dicts ({"type", "input", "label", ...}) in declaration
# order; consumed by trainer.SGD for the topologies that contain them
EVALUATORS: list = []

_GLOBAL: dsl.GraphBuilder | None = None


def global_graph() -> dsl.GraphBuilder:
    """The ambient v2 graph (created on first use, pushed at the BOTTOM
    of the dsl scope stack so explicit `with dsl.model()` scopes still
    nest above it)."""
    global _GLOBAL
    if _GLOBAL is None:
        _GLOBAL = dsl.GraphBuilder()
        dsl._stack.insert(0, _GLOBAL)
    return _GLOBAL


def reset():
    """Drop all ambient state (test isolation; the reference gets this
    by running each config in a fresh process)."""
    global _GLOBAL
    if _GLOBAL is not None and _GLOBAL in dsl._stack:
        dsl._stack.remove(_GLOBAL)
    _GLOBAL = None
    DATA_TYPES.clear()
    EVALUATORS.clear()
