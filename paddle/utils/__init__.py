"""paddle.utils — operator-facing tool scripts.

Reference: python/paddle/utils/ — plotcurve, dump_config,
make_model_diagram, show_pb, image_util, preprocess_img. Each module
here is runnable (`python -m paddle.utils.plotcurve ...`) and delegates
to the paddle_tpu machinery (plot/make_diagram/config/
data.proto_provider/image).

Deliberately out of scope (documented, like PARITY.md scope-outs):
torch2paddle (torch-binary weight import — the tar interop in
paddle.v2.parameters covers model exchange) and image_multiproc (the
feeder's prefetch covers the multi-process decode role).
"""

__all__ = [
    "dump_config",
    "image_util",
    "make_model_diagram",
    "plotcurve",
    "preprocess_img",
    "show_pb",
]
