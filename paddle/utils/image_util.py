"""Image helpers for dataset preprocessing and serving feeds.

Reference: python/paddle/utils/image_util.py — PIL/ndarray helpers
(shorter-edge resize, crop with padding, flips, 10-crop oversampling,
mean-image handling, ImageTransformer). Implemented fresh over numpy +
PIL with the same call signatures; the newer v2-style transforms live
in paddle_tpu.image (paddle.v2.image).
"""

from __future__ import annotations

import io

import numpy as np

__all__ = [
    "resize_image",
    "flip",
    "crop_img",
    "decode_jpeg",
    "preprocess_img",
    "load_meta",
    "load_image",
    "oversample",
    "ImageTransformer",
]


def _pil():
    from PIL import Image

    return Image


def resize_image(img, target_size):
    """Resize a PIL image so its shorter edge equals target_size."""
    Image = _pil()
    scale = target_size / float(min(img.size))
    new_size = (
        int(round(img.size[0] * scale)),
        int(round(img.size[1] * scale)),
    )
    return img.resize(new_size, Image.LANCZOS)


def flip(im: np.ndarray) -> np.ndarray:
    """Horizontal flip; im is HxW or KxHxW (last axis = width)."""
    return im[..., ::-1]


def crop_img(im: np.ndarray, inner_size: int, color: bool = True,
             test: bool = True) -> np.ndarray:
    """inner_size x inner_size crop of a (K,H,W) (color) or (H,W)
    array, zero-padding images smaller than the crop. test=True crops
    the center; test=False crops randomly and flips half the time."""
    im = np.asarray(im, np.float32)
    h_ax, w_ax = (1, 2) if color else (0, 1)
    height = max(inner_size, im.shape[h_ax])
    width = max(inner_size, im.shape[w_ax])
    pad_shape = (
        (im.shape[0], height, width) if color else (height, width)
    )
    padded = np.zeros(pad_shape, np.float32)
    y0 = (height - im.shape[h_ax]) // 2
    x0 = (width - im.shape[w_ax]) // 2
    sl = (
        np.s_[:, y0 : y0 + im.shape[1], x0 : x0 + im.shape[2]]
        if color
        else np.s_[y0 : y0 + im.shape[0], x0 : x0 + im.shape[1]]
    )
    padded[sl] = im
    if test:
        y = (height - inner_size) // 2
        x = (width - inner_size) // 2
    else:
        y = np.random.randint(0, height - inner_size + 1)
        x = np.random.randint(0, width - inner_size + 1)
    out = (
        padded[:, y : y + inner_size, x : x + inner_size]
        if color
        else padded[y : y + inner_size, x : x + inner_size]
    )
    if not test and np.random.randint(2) == 0:
        out = flip(out)
    return out


def decode_jpeg(jpeg_bytes: bytes) -> np.ndarray:
    """JPEG bytes -> (K,H,W) (color) or (H,W) ndarray."""
    Image = _pil()
    arr = np.array(Image.open(io.BytesIO(jpeg_bytes)))
    if arr.ndim == 3:
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def preprocess_img(im: np.ndarray, img_mean: np.ndarray,
                   crop_size: int, is_train: bool,
                   color: bool = True) -> np.ndarray:
    """Crop (+augment when training), subtract the mean image, and
    flatten to the trainer's dense-vector layout."""
    pic = crop_img(
        np.asarray(im, np.float32), crop_size, color, test=not is_train
    )
    return (pic - img_mean).flatten()


def load_meta(meta_path: str, mean_img_size: int, crop_size: int,
              color: bool = True) -> np.ndarray:
    """Load the dataset mean image and center-crop it to crop_size.
    The meta file is either an npz or a pickled dict (what
    preprocess_img writes) with a 'data_mean' entry."""
    try:
        meta = np.load(meta_path, allow_pickle=True)
    except (OSError, ValueError):
        import pickle

        with open(meta_path, "rb") as f:
            meta = pickle.load(f)
    mean = np.asarray(meta["data_mean"]).reshape(-1)
    border = (mean_img_size - crop_size) // 2
    if color:
        assert mean_img_size * mean_img_size * 3 == mean.shape[0]
        mean = mean.reshape(3, mean_img_size, mean_img_size)
        out = mean[
            :, border : border + crop_size, border : border + crop_size
        ]
    else:
        assert mean_img_size * mean_img_size == mean.shape[0]
        mean = mean.reshape(mean_img_size, mean_img_size)
        out = mean[
            border : border + crop_size, border : border + crop_size
        ]
    return out.astype(np.float32)


def load_image(img_path: str, is_color: bool = True):
    """Load a PIL image (is_color selects RGB vs L on convert)."""
    Image = _pil()
    img = Image.open(img_path)
    img.load()
    return img.convert("RGB" if is_color else "L")


def oversample(imgs, crop_dims):
    """Ten crops per image — 4 corners + center, and their mirrors.
    imgs: iterable of (H,W,K) arrays; returns [10*N, ch, cw, K]."""
    imgs = list(imgs)
    im_shape = np.array(imgs[0].shape)
    ch, cw = int(crop_dims[0]), int(crop_dims[1])
    ys = (0, im_shape[0] - ch)
    xs = (0, im_shape[1] - cw)
    boxes = [(y, x) for y in ys for x in xs]
    cy = int(round((im_shape[0] - ch) / 2.0))
    cx = int(round((im_shape[1] - cw) / 2.0))
    boxes.append((cy, cx))
    out = np.empty((10 * len(imgs), ch, cw, im_shape[-1]), np.float32)
    i = 0
    for im in imgs:
        for y, x in boxes:
            out[i] = im[y : y + ch, x : x + cw, :]
            i += 1
        out[i : i + 5] = out[i - 5 : i, :, ::-1, :]  # mirrors
        i += 5
    return out


class ImageTransformer:
    """Channel-order / transpose / mean pipeline for serving feeds
    (reference ImageTransformer: set_transpose, set_channel_swap,
    set_mean, transformer)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color: bool = True):
        self.is_color = is_color
        self.transpose = None
        self.channel_swap = None
        self.mean = None
        if transpose is not None:
            self.set_transpose(transpose)
        if channel_swap is not None:
            self.set_channel_swap(channel_swap)
        if mean is not None:
            self.set_mean(mean)

    def set_transpose(self, order):
        if self.is_color:
            assert len(order) == 3
        self.transpose = tuple(order)

    def set_channel_swap(self, order):
        if self.is_color:
            assert len(order) == 3
        self.channel_swap = tuple(order)

    def set_mean(self, mean):
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:  # per-channel mean -> broadcastable (K,1,1)
            mean = mean[:, np.newaxis, np.newaxis]
        self.mean = mean

    def transformer(self, data: np.ndarray) -> np.ndarray:
        out = np.asarray(data, np.float32)
        if self.transpose is not None:
            out = out.transpose(self.transpose)
        if self.channel_swap is not None:
            out = out[self.channel_swap, :, :]
        if self.mean is not None:
            out = out - self.mean
        return out
