"""Print the DataHeader and DataSamples of a binary proto data file.

Reference: python/paddle/utils/show_pb.py — reads the varint-delimited
DataFormat.proto stream (proto/DataFormat.proto) and prints each
message. The wire decoding lives in paddle_tpu.data.proto_provider
(the same codec the ProtoDataProvider uses).

usage: python -m paddle.utils.show_pb PROTO_DATA_FILE
"""

from __future__ import annotations

import sys

__all__ = ["show", "main"]

_SLOT_NAMES = {
    0: "VECTOR_DENSE",
    1: "VECTOR_SPARSE_NON_VALUE",
    2: "VECTOR_SPARSE_VALUE",
    3: "INDEX",
    4: "VAR_MDIM_DENSE",
    5: "VAR_MDIM_INDEX",
    6: "STRING",
}


def show(path: str, out=None) -> int:
    from paddle_tpu.data.proto_provider import read_proto_data_raw

    out = out or sys.stdout
    header, rows, begins = read_proto_data_raw(path)
    out.write("DataHeader {\n")
    for t, dim in header:
        out.write(
            f"  slot_defs {{ type: {_SLOT_NAMES.get(t, t)} "
            f"dim: {dim} }}\n"
        )
    out.write("}\n")
    for row, beg in zip(rows, begins):
        out.write("DataSample {\n")
        out.write(f"  is_beginning: {str(bool(beg)).lower()}\n")
        for (t, _dim), slot in zip(header, row):
            out.write(
                f"  {_SLOT_NAMES.get(t, t).lower()}: {slot!r}\n"
            )
        out.write("}\n")
    return 0


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if len(argv) != 1:
        sys.stderr.write(
            "usage: python -m paddle.utils.show_pb PROTO_DATA_FILE\n"
        )
        return 1
    return show(argv[0])


if __name__ == "__main__":
    sys.exit(main())
