"""Plot training/testing curves from a trainer log.

Reference: python/paddle/utils/plotcurve.py — reads a paddle log from a
file or stdin, extracts `key=value` scores for the requested keys
(default AvgCost), separates the pass-test segments, and writes a
matplotlib figure to a file or stdout.

usage: python -m paddle.utils.plotcurve [-i LOG] [-o FIG.png] [key ...]
"""

from __future__ import annotations

import argparse
import re
import sys

__all__ = ["plot_paddle_curve", "main"]


def _extract(keys, lines):
    """{key: ([train values], [test values])} in log order."""
    out = {k: ([], []) for k in keys}
    for line in lines:
        is_test = "pass-test" in line or "Test samples" in line or (
            "Test" in line and "=" in line
        )
        for k in keys:
            for m in re.finditer(
                rf"{re.escape(k)}=([-+0-9.eE]+)", line
            ):
                out[k][1 if is_test else 0].append(float(m.group(1)))
    return out


def plot_paddle_curve(keys, inputfile, outputfile, format="png",
                      show_fig=False):
    """Render one curve per key (train solid, test dashed) to
    `outputfile` (a path or binary file object)."""
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    data = _extract(keys, inputfile)
    if not any(tr or te for tr, te in data.values()):
        sys.stderr.write("no matching score keys found in the log\n")
        return 1
    plt.figure(figsize=(8, 5))
    for k, (train, test) in data.items():
        if train:
            plt.plot(range(len(train)), train, label=f"{k} (train)")
        if test:
            plt.plot(
                range(len(test)), test, "--", label=f"{k} (test)"
            )
    plt.xlabel("log point")
    plt.legend()
    plt.grid(True, alpha=0.3)
    if hasattr(outputfile, "write"):
        plt.savefig(outputfile, format=format)
    else:
        plt.savefig(outputfile)
    plt.close()
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Plot training and testing curves from a trainer "
        "log file."
    )
    p.add_argument("-i", "--input", default=None,
                   help="log file (default: stdin)")
    p.add_argument("-o", "--output", default=None,
                   help="figure file (default: stdout)")
    p.add_argument("--format", default="png",
                   help="figure format (png|pdf|ps|eps|svg)")
    p.add_argument("key", nargs="*", default=[],
                   help="score keys to plot (default AvgCost)")
    a = p.parse_args(argv)
    keys = a.key or ["AvgCost"]
    inp = open(a.input) if a.input else sys.stdin
    try:
        if a.output:
            return plot_paddle_curve(keys, inp, a.output, a.format)
        return plot_paddle_curve(
            keys, inp, sys.stdout.buffer, a.format
        )
    finally:
        if a.input:
            inp.close()


if __name__ == "__main__":
    sys.exit(main())
