"""Emit a graphviz diagram of a model config.

Reference: python/paddle/utils/make_model_diagram.py (config -> .dot).
Delegates to paddle_tpu.plot.make_diagram via the CLI verb.

usage: python -m paddle.utils.make_model_diagram CONFIG [OUT.dot]
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        sys.stderr.write(
            "usage: python -m paddle.utils.make_model_diagram CONFIG "
            "[OUT.dot]\n"
        )
        return 1
    from paddle_tpu.__main__ import main as cli_main

    args = ["make_diagram", "--config", argv[0]]
    if len(argv) > 1:
        args += ["--output", argv[1]]
    return cli_main(args)


if __name__ == "__main__":
    sys.exit(main())
