"""Print a parsed model config.

Reference: python/paddle/utils/dump_config.py (parse a trainer config
and print the TrainerConfig proto). Works on v1 trainer configs and
paddle_tpu get_config modules alike — delegates to the CLI's
dump_config verb.

usage: python -m paddle.utils.dump_config CONFIG [CONFIG_ARGS]
"""

from __future__ import annotations

import sys

__all__ = ["main"]


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        sys.stderr.write(
            "usage: python -m paddle.utils.dump_config CONFIG "
            "[CONFIG_ARGS]\n"
        )
        return 1
    from paddle_tpu.__main__ import main as cli_main

    args = ["dump_config", "--config", argv[0]]
    if len(argv) > 1:
        args += ["--config_args", argv[1]]
    return cli_main(args)


if __name__ == "__main__":
    sys.exit(main())
