"""Preprocess an image-classification dataset directory into batches.

Reference: python/paddle/utils/preprocess_img.py (+ preprocess_util) —
walk a directory whose sub-directories are label names, resize every
image, split train/test, write pickled batch files plus a meta file
holding the dataset mean image (what image_util.load_meta reads) and a
labels list. The batch layout feeds the image dataprovider the same
way the reference's batches did.

usage: python -m paddle.utils.preprocess_img -i DATA_DIR
           [-s TARGET_SIZE] [-c IS_COLOR] [-n TEST_RATIO]
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys

import numpy as np

from paddle.utils.image_util import load_image, resize_image

__all__ = ["ImageClassificationDatasetCreater", "main"]

_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".gif")


class ImageClassificationDatasetCreater:
    """data_path: directory of <label>/<image> files. Produces
    data_path/batches/{batches_train/,batches_test/,labels.txt,
    batches.meta} (meta holds data_mean, the flattened mean image)."""

    def __init__(self, data_path: str, target_size: int,
                 color: bool = True, num_per_batch: int = 1024,
                 test_ratio: float = 0.1):
        self.data_path = data_path
        self.target_size = target_size
        self.color = color
        self.num_per_batch = num_per_batch
        self.test_ratio = test_ratio

    def _load_one(self, path: str) -> np.ndarray:
        img = resize_image(
            load_image(path, self.color), self.target_size
        )
        arr = np.array(img)
        # center-crop to square target_size x target_size
        h, w = arr.shape[:2]
        y = (h - self.target_size) // 2
        x = (w - self.target_size) // 2
        arr = arr[y : y + self.target_size, x : x + self.target_size]
        if self.color:  # HWC -> flattened CHW (trainer layout)
            arr = arr.transpose(2, 0, 1)
        return arr.astype(np.float32).flatten()

    def create_dataset_from_dir(self, path: str = None) -> str:
        path = path or self.data_path
        labels = sorted(
            d for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d))
        )
        if not labels:
            raise ValueError(f"no label sub-directories under {path}")
        samples = []
        for li, label in enumerate(labels):
            for fn in sorted(os.listdir(os.path.join(path, label))):
                if fn.lower().endswith(_EXTS):
                    samples.append(
                        (os.path.join(path, label, fn), li)
                    )
        rng = np.random.default_rng(0)
        rng.shuffle(samples)
        n_test = int(len(samples) * self.test_ratio)
        if n_test >= len(samples):
            raise ValueError(
                f"no training samples: {len(samples)} images found "
                f"under {path} with test_ratio={self.test_ratio}"
            )
        splits = {
            "test": samples[:n_test],
            "train": samples[n_test:],
        }
        out_dir = os.path.join(path, "batches")
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, "labels.txt"), "w") as f:
            for li, label in enumerate(labels):
                f.write(f"{li} {label}\n")
        mean_acc, mean_n = None, 0
        for split, items in splits.items():
            split_dir = os.path.join(out_dir, f"batches_{split}")
            os.makedirs(split_dir, exist_ok=True)
            names = []
            for start in range(0, len(items), self.num_per_batch):
                chunk = items[start : start + self.num_per_batch]
                data = np.stack(
                    [self._load_one(p) for p, _ in chunk]
                )
                lab = np.asarray([l for _, l in chunk], np.int32)
                bname = f"batch_{start // self.num_per_batch:05d}"
                with open(os.path.join(split_dir, bname), "wb") as f:
                    pickle.dump(
                        {"data": data, "labels": lab}, f, protocol=2
                    )
                names.append(os.path.join(split_dir, bname))
                if split == "train":
                    s = data.sum(axis=0)
                    mean_acc = s if mean_acc is None else mean_acc + s
                    mean_n += len(chunk)
            with open(
                os.path.join(out_dir, f"{split}.list"), "w"
            ) as f:
                f.write("\n".join(names) + ("\n" if names else ""))
        meta = {
            "data_mean": (
                mean_acc / max(mean_n, 1)
            ).astype(np.float32),
            "image_size": self.target_size,
            "color": self.color,
            "num_classes": len(labels),
        }
        with open(os.path.join(out_dir, "batches.meta"), "wb") as f:
            pickle.dump(meta, f, protocol=2)
        return out_dir


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Preprocess an image dataset directory into "
        "train/test batches + mean-image meta."
    )
    p.add_argument("-i", "--input", required=True,
                   help="dataset dir (sub-dirs are labels)")
    p.add_argument("-s", "--size", type=int, default=32,
                   help="target image size")
    p.add_argument("-c", "--color", type=int, default=1)
    p.add_argument("-n", "--test_ratio", type=float, default=0.1)
    a = p.parse_args(argv)
    creater = ImageClassificationDatasetCreater(
        a.input, a.size, bool(a.color), test_ratio=a.test_ratio
    )
    out = creater.create_dataset_from_dir()
    print(out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
