"""`paddle.trainer_config_helpers` shim: the complete star-import
authoring surface of the reference's v1 config helpers
(python/paddle/trainer_config_helpers/{layers,networks,optimizers,
attrs,poolings,activations}.py), backed by paddle_tpu.compat.
"""

from paddle_tpu.compat.config_parser import *  # noqa: F401,F403
from paddle_tpu.compat.layers_v1 import *  # noqa: F401,F403
from paddle_tpu.compat import layer_math  # noqa: F401  (patches LayerRef ops)
