"""`paddle.trainer_config_helpers.config_parser_utils` shim.

Reference: python/paddle/trainer_config_helpers/config_parser_utils.py
— thin wrappers that split parse_config into network / optimizer /
trainer flavors (parse_network_config drives parse_config with a
callable; reset_parser restarts the ambient parse state).
"""

from paddle_tpu.compat.config_parser import parse_config as _parse_config

__all__ = [
    "parse_trainer_config",
    "parse_network_config",
    "parse_optimizer_config",
    "reset_parser",
]


def parse_trainer_config(trainer_conf, config_arg_str=""):
    return _parse_config(trainer_conf, config_arg_str)


def parse_network_config(network_conf, config_arg_str=""):
    config = _parse_config(network_conf, config_arg_str)
    return config.model_config


def parse_optimizer_config(optimizer_conf, config_arg_str=""):
    config = _parse_config(optimizer_conf, config_arg_str)
    return config.opt_config


def reset_parser():
    """Reference reset_parser -> config_parser.begin_parse(): drop all
    ambient graph state so the next parse starts fresh."""
    from paddle.v2 import config_base

    config_base.reset()
