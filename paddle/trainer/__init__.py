"""Shim for the reference's `paddle.trainer` package."""
