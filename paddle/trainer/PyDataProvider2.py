"""`paddle.trainer.PyDataProvider2` shim: the data-provider declaration
API reference providers star-import (python/paddle/trainer/
PyDataProvider2.py:39-329), backed by paddle_tpu.data.
"""

from paddle_tpu.data.feeder import (  # noqa: F401
    InputType,
    dense_vector,
    dense_vector_sequence,
    integer_value,
    integer_value_sequence,
    integer_value_sub_sequence,
    sparse_binary_vector,
    sparse_float_vector,
)
from paddle_tpu.data.provider import CacheType, provider  # noqa: F401


class DataType:
    """Slot kind enum (reference PyDataProvider2.py:32)."""

    Dense = 0
    SparseNonValue = 1
    SparseValue = 2
    Index = 3


class SequenceType:
    """Sequence level enum (reference PyDataProvider2.py:25)."""

    NO_SEQUENCE = 0
    SEQUENCE = 1
    SUB_SEQUENCE = 2


# older alias used by some reference providers
sparse_vector = sparse_float_vector


def sparse_binary_vector_sequence(dim):
    return sparse_binary_vector(dim, seq_type=1)


def sparse_vector_sequence(dim):
    return sparse_float_vector(dim, seq_type=1)
integer_sequence = integer_value_sequence
