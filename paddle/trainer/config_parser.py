"""`paddle.trainer.config_parser` shim — the reference's parse_config
entry point (python/paddle/trainer/config_parser.py:3724) backed by
paddle_tpu.compat.config_parser.
"""

from paddle_tpu.compat.config_parser import (  # noqa: F401
    get_config_arg,
    parse_config,
)
