"""`paddle.trainer.config_parser` shim — the reference's parse_config
entry point (python/paddle/trainer/config_parser.py:3724) backed by
paddle_tpu.compat.config_parser.
"""

import logging

from paddle_tpu.compat.config_parser import (  # noqa: F401
    get_config_arg,
    parse_config,
)


def parse_config_and_serialize(trainer_config, config_arg_str=""):
    """Reference config_parser.py:3756 — parse + SerializeToString."""
    return parse_config(trainer_config, config_arg_str).SerializeToString()

# the reference module's glog-backed logger the api demo drivers import
# (v1_api_demo/vae/vae_train.py:23)
logger = logging.getLogger("paddle_tpu.config_parser")
