"""`paddle` import-namespace shim: lets UNMODIFIED reference v1 config
files and data providers (`from paddle.trainer_config_helpers import *`,
`from paddle.trainer.PyDataProvider2 import *`) execute against
paddle_tpu. Exec configs via
`paddle_tpu.compat.config_parser.parse_config` (or `paddle.trainer.
config_parser.parse_config`, the reference's own entry point —
python/paddle/trainer/config_parser.py:3724).
"""
