"""`paddle.proto.ParameterConfig_pb2` shim.

Reference: proto/ParameterConfig.proto (ParameterConfig message,
fields name=1, size=2, dims=9, plus the optimizer scalars). A plain
Python message class with the same attribute surface; required-field
semantics for IsInitialized() match the proto (name and size are
required, everything else optional with proto defaults).
"""

__all__ = ["ParameterConfig"]


class ParameterConfig:
    def __init__(self, **kwargs):
        self.name = None
        self.size = None
        self.learning_rate = 1.0
        self.momentum = 0.0
        self.initial_mean = 0.0
        self.initial_std = 0.01
        self.decay_rate = 0.0
        self.decay_rate_l1 = 0.0
        self.dims = []
        self.device = -1
        self.initial_strategy = 0
        self.initial_smart = False
        self.num_batches_regularization = 1
        self.is_sparse = False
        self.format = ""
        self.sparse_remote_update = False
        self.gradient_clipping_threshold = 0.0
        self.is_static = False
        self.para_id = 0
        self.need_compact = False
        self.sparse_update = False
        self.is_shared = False
        self.parameter_block_size = 0
        for k, v in kwargs.items():
            setattr(self, k, v)

    def IsInitialized(self) -> bool:
        # proto2 required fields: name (=1), size (=2)
        return self.name is not None and self.size is not None

    def __repr__(self):
        return (
            f"ParameterConfig(name={self.name!r}, size={self.size}, "
            f"dims={list(self.dims)})"
        )
