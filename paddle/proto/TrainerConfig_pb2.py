"""`paddle.proto.TrainerConfig_pb2` shim — OptimizationConfig is the
name reference code imports (proto/TrainerConfig.proto); it aliases the
framework's OptimizationConf IR (same field names: batch_size,
learning_rate, learning_method, ...)."""

from paddle_tpu.core.config import OptimizationConf as OptimizationConfig

__all__ = ["OptimizationConfig"]
