"""`paddle.proto` shim — the proto-message surface reference user code
imports (proto/ParameterConfig.proto et al.), backed by plain Python
message classes instead of generated protobuf bindings. The framework's
IR is paddle_tpu.core.config; these classes exist so reference programs
that build/inspect proto messages directly (e.g.
python/paddle/v2/tests/test_parameters.py) run unmodified.
"""
