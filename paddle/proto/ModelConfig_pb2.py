"""`paddle.proto.ModelConfig_pb2` shim — ModelConfig/SubModelConfig as
reference code imports them (proto/ModelConfig.proto:608,579), aliased
to the framework's ModelConf IR (layers/parameters/input+output names
carry the same meaning)."""

from paddle_tpu.core.config import ModelConf as ModelConfig
from paddle_tpu.core.config import ModelConf as SubModelConfig

__all__ = ["ModelConfig", "SubModelConfig"]
