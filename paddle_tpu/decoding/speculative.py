"""Speculative greedy decoding (ISSUE 18 tentpole, rung b).

The committed `nmt_beam4_decode_b32` capture proved decode latency is
dispatch-chain depth, not bytes or FLOPs (ROADMAP item 2). Multi-token
dispatch (`BeamSearchDecoder.tokens_per_dispatch`) shortens the chain
by scanning the SAME net K times per program; speculative decoding —
Leviathan et al.'s draft-proposes / target-verifies scheme, greedy
variant — shortens it with a CHEAPER net: a small draft model proposes
K tokens autoregressively inside one compiled scan program, then the
target model verifies all K positions in ONE batched forward (also a
compiled scan — every position's input is already known, so the
target's K steps carry no host round-trips between them). The host
accepts the longest agreeing prefix plus the target's one corrected
token, so every round emits >= 1 token for <= 2 dispatches: the chain
shrinks from `max_len` to at most `2*ceil(max_len/accepted_per_round)`
and the OUTPUT IS EXACTLY THE TARGET'S GREEDY OUTPUT, token for token,
no matter how bad the draft is (a worthless draft only costs speed,
never correctness — pinned by tests/test_decoding.py).

Per-round bookkeeping is numpy on the host (the degradation-ladder
discipline from serving/host_decode.py): rows advance at different
rates, so each round gathers, per row, the stacked per-step memories
matching that row's accepted prefix from the scan programs' outputs.

Both nets are plain `BeamSearchDecoder`s with beam_size=1 — the draft
constructor below builds one from the same DSL layer inventory the
target uses. Chain depth is measured (dispatches counted), never
derived: `last_chain_depth` after each generate(), same contract as
the beam decoder.

Caveat: a `logprob_fn` must be position-independent (ignore its `t`
argument) to compose with speculative decoding — rows progress at
per-row rates, while one scan program stamps a single base `t0`.

Module scope is jax-free (this package sits in the ast_lint import
fence); tracing imports jax function-locally.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


def make_draft_decoder(step: Callable, n_static: int, bos_id: int,
                       eos_id: int, max_length: int,
                       logprob_fn: Optional[Callable] = None,
                       static_sizes: Optional[list] = None):
    """Build a draft model from the existing DSL layer inventory: the
    same `step(word, *statics)` authoring contract as the target
    decoder, forced to beam_size=1 (speculative verification is
    greedy). Keep the draft's layer/param NAMES distinct from the
    target's — the two nets carry separate param dicts."""
    from paddle_tpu.beam_search import BeamSearchDecoder

    return BeamSearchDecoder(
        step, n_static, bos_id=bos_id, eos_id=eos_id, beam_size=1,
        max_length=max_length, logprob_fn=logprob_fn,
        static_sizes=static_sizes,
    )


class SpeculativeGreedyDecoder:
    """Draft/target speculative wrapper around two beam_size=1
    decoders.

        spec = SpeculativeGreedyDecoder(target_dec, draft_dec,
                                        propose_k=4)
        seqs, lens, scores = spec.generate(params, draft_params,
                                           statics=[...], boots={...})

    Outputs match `target_dec.generate(...)` (greedy reference)
    token-for-token; shapes are the decoder's [B, 1, max_length] /
    [B, 1] contract so the serving batcher can swap it in unchanged.
    """

    def __init__(self, target, draft, propose_k: int = 4):
        assert target.k == 1 and draft.k == 1, (
            "speculative decoding verifies greedily: both target and "
            f"draft need beam_size=1 (got {target.k}/{draft.k})"
        )
        assert propose_k >= 1
        assert target.bos_id == draft.bos_id, "bos_id mismatch"
        assert target.eos_id == draft.eos_id, "eos_id mismatch"
        self.target, self.draft = target, draft
        self.propose_k = int(propose_k)
        # measured diagnostics of the last generate(): sequential
        # dispatches issued, and the proposal accept rate
        self.last_chain_depth: Optional[int] = None
        self.last_steps: Optional[int] = None
        self.last_accept_rate: Optional[float] = None
        self._progs = {}
        self._recompile_guard = None

    def _guard(self):
        if self._recompile_guard is None:
            from paddle_tpu.analysis.recompile_guard import (
                RecompileGuard,
            )

            self._recompile_guard = RecompileGuard("spec_decode")
        return self._recompile_guard

    def _scan_program(self, role: str, dec, b: int, n: int,
                      self_feed: bool):
        """N decode steps of `dec`'s step net as ONE jitted scan
        program: (params, static_feed, mems, first_word [B], words
        [N,B], t0) -> (greedy [N,B], greedy_logp [N,B], mems_stack
        {name: [N,B,size]}).

        self_feed=True (draft propose): each step consumes the
        previous step's own argmax, starting from first_word — the
        K-token autoregressive proposal in one dispatch. False (target
        verify): step j consumes words[j] — all inputs known up front,
        the 'verify K positions in one batched forward'."""
        key = (role, b, n, self_feed, dec.logprob_fn, dec.eos_id)
        if key not in self._progs and len(self._progs) >= 16:
            self._progs.pop(next(iter(self._progs)))
        if key not in self._progs:
            import jax
            import jax.numpy as jnp

            from paddle_tpu.core.arg import Arg

            net, memories, out_name = dec._net, dec.memories, \
                dec.out_name
            lpf = dec.logprob_fn
            guard = self._guard()

            def prog(params, static_feed, mems, first_word, words, t0):
                guard.note(static_feed, mems, b=b, n=n, role=role)

                def substep(carry, inp):
                    mems, word = carry
                    j, w_in = inp
                    w = word if self_feed else w_in
                    feed = dict(static_feed)
                    feed["@word"] = Arg(ids=w)
                    for m in memories:
                        feed[m["link"]] = Arg(value=mems[m["layer"]])
                    outs, _ = net.forward(params, feed, train=False)
                    prob = outs[out_name].value  # [B, V]
                    # f32 score math regardless of AMP, matching the
                    # target decoder's pinned accumulator dtype
                    logp = jnp.log(jnp.maximum(prob, 1e-20))
                    logp = logp.reshape(b, 1, -1).astype(jnp.float32)
                    if lpf is not None:
                        logp = lpf(logp, t0 + j)
                    logp = logp[:, 0, :]
                    # argmax picks the first max — the same
                    # lower-index tie-break as lax.top_k(k=1)
                    g = jnp.argmax(logp, axis=-1).astype(jnp.int32)
                    glp = jnp.max(logp, axis=-1)
                    new_mems = {
                        m["layer"]: outs[m["layer"]].value
                        for m in memories
                    }
                    return (new_mems, g), (g, glp, new_mems)

                (_, _), (gs, glps, mstack) = jax.lax.scan(
                    substep, (mems, first_word),
                    (jnp.arange(n), words),
                )
                return gs, glps, mstack

            self._progs[key] = jax.jit(prog)
        return self._progs[key]

    @property
    def recompile_guards(self):
        return [self._guard()]

    def generate(self, params: dict, draft_params: dict,
                 statics: list = None, boots: dict = None,
                 batch_size: int = None, draft_statics: list = None,
                 draft_boots: dict = None):
        """Greedy-decode with draft/target speculation. `params` /
        `statics` / `boots` condition the target exactly like
        `target.generate`; the draft gets its own param dict and
        (optionally) its own conditioning. Returns (seqs [B, 1,
        max_length] int32, lens [B, 1] int32, scores [B, 1] float32) —
        token-for-token the target's greedy output."""
        import jax.numpy as jnp

        tgt, drf, kp = self.target, self.draft, self.propose_k
        t_max, eos, bos = tgt.max_length, tgt.eos_id, tgt.bos_id
        t_feed, t_mems, b = tgt.prepare(statics or [], boots,
                                        batch_size)
        d_feed, d_mems, _ = drf.prepare(draft_statics or [],
                                        draft_boots, batch_size=b)

        seqs = np.full((b, 1, t_max), eos, np.int32)
        scores = np.zeros((b,), np.float32)
        last = np.full((b,), bos, np.int32)
        pos = np.zeros((b,), np.int64)
        finished = np.zeros((b,), bool)
        rows = np.arange(b)
        dispatches = proposed = accepted = 0

        while not finished.all():
            base = int(pos[~finished].min())
            n = min(kp, t_max - base)
            t0 = jnp.int32(base)
            # 1 dispatch: draft proposes n tokens autoregressively
            propose = self._scan_program("draft", drf, b, n, True)
            props, _, d_stack = propose(
                draft_params, d_feed, d_mems, jnp.asarray(last),
                jnp.zeros((n, b), jnp.int32), t0,
            )
            dispatches += 1
            props_np = np.asarray(props)  # [n, B]
            # 1 dispatch: target verifies all n positions at once —
            # position j consumes [last, props[:-1]][j]
            vwords = np.concatenate([last[None, :], props_np[:-1]], 0)
            verify = self._scan_program("target", tgt, b, n, False)
            gs, glps, t_stack = verify(
                params, t_feed, t_mems, jnp.asarray(last),
                jnp.asarray(vwords), t0,
            )
            dispatches += 1
            gs = np.asarray(gs)  # [n, B] the target's greedy tokens
            glps = np.asarray(glps)

            # host accept: longest agreeing prefix + the target's one
            # corrected token. Since agreed positions have g == p, the
            # accepted tokens are exactly gs[:n_acc] — the target's
            # own greedy continuation.
            agree = gs == props_np
            live = ~finished
            proposed += n * int(live.sum())
            roll_idx = np.zeros((b,), np.int64)
            for r in rows[live]:
                mism = np.nonzero(~agree[:, r])[0]
                n_acc = int(mism[0]) + 1 if mism.size else n
                roll_idx[r] = n_acc - 1
                n_app = min(n_acc, t_max - int(pos[r]))
                toks = gs[:n_app, r]
                hit = np.nonzero(toks == eos)[0]
                if hit.size:
                    n_app = int(hit[0]) + 1
                    toks = toks[:n_app]
                    finished[r] = True
                seqs[r, 0, pos[r]:pos[r] + n_app] = toks
                scores[r] += glps[:n_app, r].sum()
                pos[r] += n_app
                accepted += n_app
                if pos[r] >= t_max:
                    finished[r] = True
                last[r] = toks[-1]
            # roll both nets' states to the per-row accepted prefix:
            # stack index i holds the state after consuming
            # [last, props[:i]] — identical feeds on both nets, so the
            # same index applies to each
            t_mems = {
                name: jnp.asarray(np.asarray(st)[roll_idx, rows])
                for name, st in t_stack.items()
            }
            d_mems = {
                name: jnp.asarray(np.asarray(st)[roll_idx, rows])
                for name, st in d_stack.items()
            }

        self.last_chain_depth = dispatches
        self.last_steps = int(pos.max())
        self.last_accept_rate = (
            accepted / proposed if proposed else None
        )

        is_eos = seqs == eos
        any_eos = np.any(is_eos, axis=-1)
        first_eos = np.argmax(is_eos, axis=-1)
        lens = np.where(any_eos, first_eos + 1, t_max).astype(np.int32)
        return seqs, lens, scores[:, None].copy()
