"""Decoding strategies beyond the plain beam-search program (ISSUE 18).

This package is inside the jax-import fence (`analysis/ast_lint.py`
JAX_FREE_DIRS): module scope stays importable with jax blocked so the
serving/observability layers can reach the constructors cheaply;
anything that traces or dispatches imports jax function-locally.
"""

from paddle_tpu.decoding.kv_cache import (  # noqa: F401
    PagedKVCache,
    PagedLM,
    PoolExhausted,
    SpeculativePagedLM,
)
from paddle_tpu.decoding.speculative import (  # noqa: F401
    SpeculativeGreedyDecoder,
    make_draft_decoder,
)
