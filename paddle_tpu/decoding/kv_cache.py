"""Paged KV-cache pool + prefill/decode disaggregation (ISSUE 19).

The committed `nmt_beam4_decode_b32` capture proved decode is
dispatch-chain bound (7.7x over an ~11.8 ms byte floor — the PR12
verdict), and every model before this one still RECOMPUTES the whole
prefix per emitted token on top of that. This module removes the
recompute: generation splits into two compiled programs over a pool
of fixed-size KV pages —

- **prefill** (one per length bucket): full causal forward over the
  prompt, per-layer K/V scattered into the sequence's pages, and the
  first next-token selection (top-k + score) FUSED into the same
  dispatch. Buckets are page-aligned powers of two so the serving
  program cache stays small (`PagedKVCache.bucket_for`).
- **decode** (one per batch width): ONE dispatch per token that
  gathers the page context, runs the new token through every block,
  appends its K/V into the pool *in place* (the pool buffers are
  donated — `input_output_alias` is audited like the chunk rung's
  memories), selects the next token (argmax / beam top-k), and
  updates the running score. Forward + top-k + cache append + score
  update in one program retires ROADMAP residual 2(c).

Decode cost now scales with NEW tokens: the per-step attention reads
the cached pages ([B, 1, S] scores — no [T, T] anywhere) instead of
re-running a length-T forward. Pages are a host-side free list; a
sequence holds `ceil(len/page_size)` pages (+1 as it grows), so the
serving engine (`paddle_tpu/serving/lm_engine.py`) can evict a
request mid-generation by freeing its pages and re-prefill it later
byte-identically — continuous batching over a bounded pool.

The speculative rung (PR18's draft-proposes/target-verifies scheme)
composes: `SpeculativePagedLM` runs the draft's K-token proposal as
one scan that APPENDS to the draft's own pool, then verifies all K
positions in one chunked dispatch that appends to the target's pool
(`lm_decode_chunk` with n=K). Accepted-prefix bookkeeping stays on
the host; stale entries past the accept point are masked by the
position-based attention mask and overwritten next round.

All math lives in `paddle_tpu/models/lm.py` and is shared with the
full-recompute references, so the pinned tests compare ONLY cache vs
recompute. Module scope is jax-free (ast_lint import fence): jax is
imported function-locally, like every decoding/ module.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

__all__ = [
    "PoolExhausted", "PagedKVCache", "PagedLM", "SpeculativePagedLM",
]


class PoolExhausted(RuntimeError):
    """The page free list cannot satisfy an allocation — the serving
    engine's cue to evict (or shed) before retrying."""


class PagedKVCache:
    """Fixed-size-page KV pool for one LM: the device-side K/V arrays
    ([L, num_pages, page_size, H, hd] each), a host-side page free
    list, and the measured counters the decode bench row reports.

    Slot addressing: absolute position p of a sequence lives in its
    `pages[p // page_size]` at offset `p % page_size`; a gathered
    page-table context therefore has slot s == absolute position s,
    which is what `models.lm.lm_decode_chunk` assumes.
    """

    def __init__(self, spec, num_pages: int, page_size: int = 16,
                 max_pages_per_seq: Optional[int] = None):
        assert page_size >= 1 and num_pages >= 1
        self.spec = spec
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_pages_per_seq = int(max_pages_per_seq or num_pages)
        self._lock = threading.Lock()
        self._free = list(range(self.num_pages))
        self.pool = None  # (pool_k, pool_v) jax arrays, lazy
        # measured counters (the decode row's cache story)
        self.appended_tokens = 0        # tokens written by decode
        self.prefilled_tokens = 0       # tokens written by prefill
        self.cached_prefix_tokens = 0   # sum of prefix lengths served
        self.evictions = 0              # from the pool per decode row

    @property
    def max_seq_len(self) -> int:
        return self.max_pages_per_seq * self.page_size

    def bucket_for(self, length: int) -> int:
        """Smallest page-aligned power-of-two-pages bucket >= length —
        len-bucketed prefill keeps the compiled-program cache small."""
        assert 1 <= length <= self.max_seq_len, (
            f"length {length} outside pool capacity {self.max_seq_len}"
        )
        pages = 1
        while pages * self.page_size < length:
            pages *= 2
        return min(pages, self.max_pages_per_seq) * self.page_size

    def ensure_pool(self):
        if self.pool is None:
            import jax.numpy as jnp

            s = self.spec
            shape = (s.num_layers, self.num_pages, self.page_size,
                     s.num_heads, s.head_dim)
            self.pool = (jnp.zeros(shape, jnp.float32),
                         jnp.zeros(shape, jnp.float32))
        return self.pool

    def free_page_count(self) -> int:
        with self._lock:
            return len(self._free)

    def alloc(self, n: int) -> list:
        with self._lock:
            if n > len(self._free):
                raise PoolExhausted(
                    f"need {n} pages, {len(self._free)} free"
                )
            pages, self._free = self._free[:n], self._free[n:]
            return pages

    def free(self, pages) -> None:
        with self._lock:
            self._free.extend(pages)

    def pages_for_len(self, length: int) -> int:
        """Pages a sequence of `length` tokens holds, plus the page
        its NEXT append lands in (decode writes at pos == length)."""
        return min(length // self.page_size + 1,
                   self.max_pages_per_seq)


def _page_table(page_lists, maxp):
    """Stack ragged per-row page lists into the [rows, maxp] int32
    table the programs take; unused slots point at page 0 but are
    never read (position mask) nor written (host capacity
    invariant)."""
    tbl = np.zeros((len(page_lists), maxp), np.int32)
    for r, pages in enumerate(page_lists):
        tbl[r, :len(pages)] = pages
    return tbl


class PagedLM:
    """Compiled prefill + fused decode programs for one LM over one
    PagedKVCache. Host-side loops live here for direct generate()
    use; the serving engine drives `prefill()`/`decode_step()` itself
    to interleave admissions and evictions between dispatches.

    Chain depth is MEASURED (dispatches counted into
    `last_chain_depth`), and `last_timeline` splits each generate
    into dispatch-vs-device seconds the honest way: the submission
    window is host/dispatch work, the blocking fetch of the selected
    tokens is device time (the satellite-6 rule)."""

    _MAX_PROGS = 8

    def __init__(self, spec, params, cache: PagedKVCache,
                 eos_id: int = 1):
        assert cache.spec == spec
        self.spec = spec
        self.params = params
        self.cache = cache
        self.eos_id = int(eos_id)
        self._progs = {}
        self._recompile_guard = None
        self.last_chain_depth: Optional[int] = None
        self.last_timeline: Optional[dict] = None

    # -- program cache ----------------------------------------------
    def _guard(self):
        if self._recompile_guard is None:
            from paddle_tpu.analysis.recompile_guard import (
                RecompileGuard,
            )

            self._recompile_guard = RecompileGuard("paged_lm")
        return self._recompile_guard

    @property
    def recompile_guards(self):
        return [self._guard()]

    def _cached(self, key, build):
        if key not in self._progs and len(self._progs) >= \
                self._MAX_PROGS:
            self._progs.pop(next(iter(self._progs)))
        if key not in self._progs:
            self._progs[key] = build()
        return self._progs[key]

    # -- prefill: bucketed full forward + page scatter + first top-k
    def _prefill_program(self, b: int, t: int, beam_k: int = 0):
        """t is the page-aligned bucket length; beam_k=0 builds the
        greedy variant (argmax + score), beam_k>0 the beam-init
        variant (top-k expansion fused into the prefill dispatch)."""
        ps = self.cache.page_size
        assert t % ps == 0
        key = ("prefill", b, t, beam_k)

        def build():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.models import lm as lmm

            spec, guard = self.spec, self._guard()
            n_pages = t // ps

            def prog(params, pool_k, pool_v, ids, lens, pages):
                guard.note(ids, pages, b=b, t=t, beam_k=beam_k,
                           kind="prefill")
                logits, ks, vs = lmm.lm_forward(
                    spec, params, ids, lens=lens, with_kv=True
                )
                shp = (spec.num_layers, b, n_pages, ps,
                       spec.num_heads, spec.head_dim)
                pool_k = pool_k.at[:, pages].set(ks.reshape(shp))
                pool_v = pool_v.at[:, pages].set(vs.reshape(shp))
                last = jnp.take_along_axis(
                    logits, (lens - 1)[:, None, None], axis=1
                )[:, 0, :]
                logp = lmm.lm_logp(last)
                if beam_k:
                    scores, toks = lmm.beam_init_select(logp, beam_k)
                else:
                    toks = jnp.argmax(logp, axis=-1).astype(jnp.int32)
                    scores = jnp.take_along_axis(
                        logp, toks[:, None], axis=1
                    )[:, 0]
                return pool_k, pool_v, toks, scores

            return jax.jit(prog, donate_argnums=(1, 2))

        return self._cached(key, build)

    # -- decode: gather pages -> 1-token forward -> append -> select
    def _decode_program(self, b: int):
        key = ("decode", b)

        def build():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.models import lm as lmm

            spec, guard = self.spec, self._guard()
            ps = self.cache.page_size
            maxp = self.cache.max_pages_per_seq
            eos = self.eos_id

            def prog(params, pool_k, pool_v, tok, pos, page_tbl,
                     scores, finished):
                guard.note(tok, page_tbl, b=b, kind="decode")
                s = maxp * ps
                ctx_k = pool_k[:, page_tbl].reshape(
                    spec.num_layers, b, s, spec.num_heads,
                    spec.head_dim,
                )
                ctx_v = pool_v[:, page_tbl].reshape(
                    spec.num_layers, b, s, spec.num_heads,
                    spec.head_dim,
                )
                logits, nk, nv = lmm.lm_decode_chunk(
                    spec, params, tok[:, None], pos, ctx_k, ctx_v
                )
                pp = jnp.take_along_axis(
                    page_tbl, (pos // ps)[:, None], axis=1
                )[:, 0]
                pool_k = pool_k.at[:, pp, pos % ps].set(nk[:, :, 0])
                pool_v = pool_v.at[:, pp, pos % ps].set(nv[:, :, 0])
                logp = lmm.lm_logp(logits[:, 0])
                nxt = jnp.argmax(logp, axis=-1).astype(jnp.int32)
                nxt = jnp.where(finished, eos, nxt)
                sc = jnp.where(
                    finished, scores,
                    scores + jnp.take_along_axis(
                        logp, nxt[:, None], axis=1
                    )[:, 0],
                )
                fin = finished | (nxt == eos)
                return pool_k, pool_v, nxt, sc, fin

            return jax.jit(prog, donate_argnums=(1, 2))

        return self._cached(key, build)

    # -- beam decode: parent page-copy + flat step + beam select ----
    def _beam_decode_program(self, b: int, k: int):
        key = ("beam_decode", b, k)

        def build():
            import jax
            import jax.numpy as jnp

            from paddle_tpu.models import lm as lmm

            spec, guard = self.spec, self._guard()
            ps = self.cache.page_size
            maxp = self.cache.max_pages_per_seq
            eos = self.eos_id

            def prog(params, pool_k, pool_v, toks, parent, pos,
                     page_tbl, scores, finished):
                # page_tbl [b, k, maxp]; toks/parent/scores/finished
                # [b, k]; pos [b]. Step order: (1) adopt the parent
                # beam's cache by physically copying its page contents
                # into this row's pages (a production pool would COW
                # the page REFERENCES; copying keeps the programs
                # single-dispatch and the tests exact), (2) append
                # toks, (3) select the next expansion.
                guard.note(toks, page_tbl, b=b, k=k, kind="beam")
                pidx = parent[None, :, :, None, None, None, None]
                gk = pool_k[:, page_tbl]
                gv = pool_v[:, page_tbl]
                pool_k = pool_k.at[:, page_tbl].set(
                    jnp.take_along_axis(gk, pidx, axis=2)
                )
                pool_v = pool_v.at[:, page_tbl].set(
                    jnp.take_along_axis(gv, pidx, axis=2)
                )
                r = b * k
                s = maxp * ps
                flat_tbl = page_tbl.reshape(r, maxp)
                ctx_k = pool_k[:, flat_tbl].reshape(
                    spec.num_layers, r, s, spec.num_heads,
                    spec.head_dim,
                )
                ctx_v = pool_v[:, flat_tbl].reshape(
                    spec.num_layers, r, s, spec.num_heads,
                    spec.head_dim,
                )
                start = jnp.repeat(pos, k)
                logits, nk, nv = lmm.lm_decode_chunk(
                    spec, params, toks.reshape(r, 1), start,
                    ctx_k, ctx_v,
                )
                pp = jnp.take_along_axis(
                    flat_tbl, (start // ps)[:, None], axis=1
                )[:, 0]
                pool_k = pool_k.at[:, pp, start % ps].set(nk[:, :, 0])
                pool_v = pool_v.at[:, pp, start % ps].set(nv[:, :, 0])
                logp = lmm.lm_logp(logits[:, 0]).reshape(b, k, -1)
                sc, par, tok, fin = lmm.beam_step_select(
                    scores, logp, finished, eos
                )
                return pool_k, pool_v, tok, par, sc, fin

            return jax.jit(prog, donate_argnums=(1, 2))

        return self._cached(key, build)

    # -- host-side primitives (engine entry points) -----------------
    def prefill(self, ids, lens, page_lists, beam_k: int = 0):
        """Run the bucketed prefill for rows whose pages are already
        allocated (page_lists[r] must hold >= bucket//page_size
        pages). ids [B, bucket] int32. Updates the pool in place and
        returns (toks, scores) as UNFETCHED device arrays — [B]/[B]
        greedy or [B, K] beam — so callers can chain dispatches
        without a host round-trip."""
        import jax.numpy as jnp

        b, t = ids.shape
        ps = self.cache.page_size
        assert t % ps == 0 and t >= int(np.max(lens))
        pages = np.asarray([p[:t // ps] for p in page_lists],
                           np.int32)
        pool_k, pool_v = self.cache.ensure_pool()
        prog = self._prefill_program(b, t, beam_k)
        pool_k, pool_v, toks, scores = prog(
            self.params, pool_k, pool_v, jnp.asarray(ids),
            jnp.asarray(lens), jnp.asarray(pages),
        )
        self.cache.pool = (pool_k, pool_v)
        self.cache.prefilled_tokens += int(np.sum(lens))
        return toks, scores

    def decode_step(self, tok, pos, page_lists, scores, finished):
        """One fused decode dispatch: append `tok` (the pending token
        at absolute position pos[r]) and select the next. `pos` and
        `page_lists` are host-side; tok/scores/finished may stay
        unfetched device arrays so the chain never blocks. Returns
        (next_tok, scores, finished) device arrays."""
        import jax.numpy as jnp

        b = len(tok)
        tbl = _page_table(page_lists, self.cache.max_pages_per_seq)
        pool_k, pool_v = self.cache.ensure_pool()
        prog = self._decode_program(b)
        pool_k, pool_v, nxt, sc, fin = prog(
            self.params, pool_k, pool_v, jnp.asarray(tok),
            jnp.asarray(pos), jnp.asarray(tbl),
            jnp.asarray(scores), jnp.asarray(finished),
        )
        self.cache.pool = (pool_k, pool_v)
        self.cache.appended_tokens += b
        self.cache.cached_prefix_tokens += int(np.sum(pos))
        return nxt, sc, fin

    def _grow(self, page_lists, pos):
        """Allocate the next page for any row whose append position
        crossed its last page boundary."""
        need = 0
        ps = self.cache.page_size
        for r, p in enumerate(page_lists):
            while len(p) * ps <= int(pos[r]):
                p.extend(self.cache.alloc(1))
                need += 1
        return need

    # -- whole-call generation (tests / bench) ----------------------
    def generate(self, ids, lens, max_new: int):
        """Greedy paged generation: bucketed prefill + max_new-1
        fused decode dispatches. Returns (tokens [B, max_new] int32,
        scores [B] f32) — token-for-token equal to
        models.lm.greedy_decode_recompute (pinned)."""
        import time

        b = ids.shape[0]
        lens = np.asarray(lens, np.int32)
        bucket = self.cache.bucket_for(int(lens.max()))
        ps = self.cache.page_size
        padded = np.zeros((b, bucket), np.int32)
        padded[:, :min(bucket, ids.shape[1])] = \
            ids[:, :bucket]
        page_lists = [self.cache.alloc(bucket // ps)
                      for _ in range(b)]
        t0 = time.perf_counter()
        toks, scores = self.prefill(padded, lens, page_lists)
        chain = 1
        # trim: keep the pages the live prefix (and the next append)
        # occupies, return the bucket's tail pages to the pool
        for r, p in enumerate(page_lists):
            keep = self.cache.pages_for_len(int(lens[r]))
            if len(p) > keep:
                self.cache.free(p[keep:])
                del p[keep:]
        # the whole chain runs WITHOUT a host round-trip: each decode
        # feeds the previous dispatch's unfetched token array, and the
        # single blocking fetch at the end is the device-time window
        # (the satellite-6 attribution rule)
        finished = toks == self.eos_id
        step_toks = [toks]
        pos = lens.copy()
        for _ in range(1, max_new):
            self._grow(page_lists, pos)
            toks, scores, finished = self.decode_step(
                toks, pos, page_lists, scores, finished
            )
            chain += 1
            step_toks.append(toks)
            pos += 1
        t1 = time.perf_counter()
        out = np.stack([np.asarray(x) for x in step_toks], axis=1)
        scores = np.asarray(scores, np.float32)
        t2 = time.perf_counter()
        self.last_chain_depth = chain
        self.last_timeline = {"dispatch_s": t1 - t0,
                              "device_s": t2 - t1}
        for p in page_lists:
            self.cache.free(p)
        return out.astype(np.int32), scores

    def beam_generate(self, ids, lens, beam_k: int, max_new: int):
        """Paged beam search. Returns (tokens [B, K, max_new] int32,
        scores [B, K] f32) — equal to beam_decode_recompute under the
        shared expansion rule (pinned)."""
        b = ids.shape[0]
        k = int(beam_k)
        lens = np.asarray(lens, np.int32)
        bucket = self.cache.bucket_for(int(lens.max()))
        ps = self.cache.page_size
        padded = np.zeros((b, bucket), np.int32)
        padded[:, :min(bucket, ids.shape[1])] = ids[:, :bucket]
        # beam row (g, j) owns its own pages; prefill fills row j=0,
        # the first decode's parent=0 copy fans the prefix out
        rows = [[self.cache.alloc(bucket // ps) for _ in range(k)]
                for _ in range(b)]
        import time

        disp_s = dev_s = 0.0
        t0 = time.perf_counter()
        toks_d, scores = self.prefill(
            padded, lens, [r[0] for r in rows], beam_k=k
        )
        t1 = time.perf_counter()
        toks = np.asarray(toks_d)
        t2 = time.perf_counter()
        disp_s += t1 - t0
        dev_s += t2 - t1
        chain = 1
        for g in range(b):
            keep = self.cache.pages_for_len(int(lens[g]))
            for p in rows[g]:
                if len(p) > keep:
                    self.cache.free(p[keep:])
                    del p[keep:]
        hist = np.zeros((b, k, max_new), np.int32)
        hist[:, :, 0] = toks
        finished = toks == self.eos_id
        parent = np.zeros((b, k), np.int32)
        pos = lens.copy()
        gi = np.arange(b)[:, None]
        for t in range(1, max_new):
            flat = [p for g in rows for p in g]
            self._grow(flat, np.repeat(pos, k))
            t0 = time.perf_counter()
            toks_d, par_d, scores, finished = self._beam_step(
                toks, parent, pos, rows, scores, finished
            )
            t1 = time.perf_counter()
            # host reorder of the emitted history needs the parent
            # pointers — this fetch IS the device-time window
            toks = np.asarray(toks_d)
            parent = np.asarray(par_d)
            t2 = time.perf_counter()
            disp_s += t1 - t0
            dev_s += t2 - t1
            chain += 1
            hist = hist[gi, parent]
            hist[:, :, t] = toks
            pos += 1
        self.last_chain_depth = chain
        self.last_timeline = {"dispatch_s": disp_s,
                              "device_s": dev_s}
        for g in rows:
            for p in g:
                self.cache.free(p)
        return hist, np.asarray(scores, np.float32)

    def _beam_step(self, toks, parent, pos, rows, scores, finished):
        import jax.numpy as jnp

        b, k = toks.shape
        maxp = self.cache.max_pages_per_seq
        tbl = np.zeros((b, k, maxp), np.int32)
        for g in range(b):
            for j in range(k):
                tbl[g, j, :len(rows[g][j])] = rows[g][j]
        pool_k, pool_v = self.cache.ensure_pool()
        prog = self._beam_decode_program(b, k)
        pool_k, pool_v, tok, par, sc, fin = prog(
            self.params, pool_k, pool_v, jnp.asarray(toks),
            jnp.asarray(parent), jnp.asarray(pos),
            jnp.asarray(tbl), jnp.asarray(scores),
            jnp.asarray(finished),
        )
        self.cache.pool = (pool_k, pool_v)
        self.cache.appended_tokens += b * k
        self.cache.cached_prefix_tokens += int(np.sum(pos)) * k
        return tok, par, sc, fin


class SpeculativePagedLM:
    """PR18's draft-proposes/target-verifies speculation THROUGH the
    paged pool (satellite 1): the draft's K-token proposal is one
    compiled scan appending to the draft's own pages; the target
    verifies all K positions in one chunked dispatch
    (`lm_decode_chunk` with n=K) appending to the target's pages. The
    host accepts the longest agreeing prefix + the target's corrected
    token, so output is token-for-token the target's greedy KV output
    no matter how bad the draft is — pinned by
    tests/test_lm_kv_cache.py. Stale cache entries past an accept
    point are never read (position mask) and are overwritten by the
    next round's appends."""

    def __init__(self, target: PagedLM, draft: PagedLM,
                 propose_k: int = 4):
        assert propose_k >= 1
        assert target.eos_id == draft.eos_id
        self.target, self.draft = target, draft
        self.propose_k = int(propose_k)
        self._progs = {}
        self.last_chain_depth: Optional[int] = None
        self.last_accept_rate: Optional[float] = None

    def _propose_program(self, b: int, n: int):
        key = (b, n)
        if key not in self._progs and len(self._progs) >= 8:
            self._progs.pop(next(iter(self._progs)))
        if key not in self._progs:
            import jax
            import jax.numpy as jnp

            from paddle_tpu.models import lm as lmm

            drf = self.draft
            spec = drf.spec
            ps = drf.cache.page_size
            maxp = drf.cache.max_pages_per_seq
            s = maxp * ps

            def prog(params, pool_k, pool_v, first, pos, page_tbl):
                drf._guard().note(first, page_tbl, b=b, n=n,
                                  kind="propose")

                def substep(carry, j):
                    pool_k, pool_v, w = carry
                    ctx_k = pool_k[:, page_tbl].reshape(
                        spec.num_layers, b, s, spec.num_heads,
                        spec.head_dim,
                    )
                    ctx_v = pool_v[:, page_tbl].reshape(
                        spec.num_layers, b, s, spec.num_heads,
                        spec.head_dim,
                    )
                    p = pos + j
                    logits, nk, nv = lmm.lm_decode_chunk(
                        spec, params, w[:, None], p, ctx_k, ctx_v
                    )
                    pp = jnp.take_along_axis(
                        page_tbl, (p // ps)[:, None], axis=1
                    )[:, 0]
                    pool_k = pool_k.at[:, pp, p % ps].set(
                        nk[:, :, 0]
                    )
                    pool_v = pool_v.at[:, pp, p % ps].set(
                        nv[:, :, 0]
                    )
                    g = jnp.argmax(
                        lmm.lm_logp(logits[:, 0]), axis=-1
                    ).astype(jnp.int32)
                    return (pool_k, pool_v, g), g

                (pool_k, pool_v, _), props = jax.lax.scan(
                    substep, (pool_k, pool_v, first), jnp.arange(n)
                )
                return pool_k, pool_v, props

            self._progs[key] = jax.jit(prog, donate_argnums=(1, 2))
        return self._progs[key]

    def _verify_program(self, b: int, n: int):
        key = ("verify", b, n)
        if key not in self._progs and len(self._progs) >= 8:
            self._progs.pop(next(iter(self._progs)))
        if key not in self._progs:
            import jax
            import jax.numpy as jnp

            from paddle_tpu.models import lm as lmm

            tgt = self.target
            spec = tgt.spec
            ps = tgt.cache.page_size
            maxp = tgt.cache.max_pages_per_seq
            s = maxp * ps

            def prog(params, pool_k, pool_v, words, pos, page_tbl):
                tgt._guard().note(words, page_tbl, b=b, n=n,
                                  kind="verify")
                ctx_k = pool_k[:, page_tbl].reshape(
                    spec.num_layers, b, s, spec.num_heads,
                    spec.head_dim,
                )
                ctx_v = pool_v[:, page_tbl].reshape(
                    spec.num_layers, b, s, spec.num_heads,
                    spec.head_dim,
                )
                logits, nk, nv = lmm.lm_decode_chunk(
                    spec, params, words, pos, ctx_k, ctx_v
                )
                idx = pos[:, None] + jnp.arange(n)[None, :]
                pp = jnp.take_along_axis(page_tbl, idx // ps, axis=1)
                pool_k = pool_k.at[:, pp, idx % ps].set(nk)
                pool_v = pool_v.at[:, pp, idx % ps].set(nv)
                logp = lmm.lm_logp(logits)  # [b, n, V]
                gs = jnp.argmax(logp, axis=-1).astype(jnp.int32)
                glp = jnp.take_along_axis(
                    logp, gs[..., None], axis=2
                )[..., 0]
                return pool_k, pool_v, gs, glp

            self._progs[key] = jax.jit(prog, donate_argnums=(1, 2))
        return self._progs[key]

    def generate(self, ids, lens, max_new: int):
        """Speculative greedy KV generation. Returns (tokens
        [B, max_new] int32, scores [B] f32) — token-for-token the
        target PagedLM.generate output."""
        import jax.numpy as jnp

        tgt, drf, kp = self.target, self.draft, self.propose_k
        b = ids.shape[0]
        lens = np.asarray(lens, np.int32)
        eos = tgt.eos_id

        def _prefill(plm):
            bucket = plm.cache.bucket_for(int(lens.max()))
            padded = np.zeros((b, bucket), np.int32)
            padded[:, :min(bucket, ids.shape[1])] = ids[:, :bucket]
            pages = [plm.cache.alloc(bucket // plm.cache.page_size)
                     for _ in range(b)]
            toks, scores = plm.prefill(padded, lens, pages)
            for r, p in enumerate(pages):
                keep = plm.cache.pages_for_len(int(lens[r]))
                if len(p) > keep:
                    plm.cache.free(p[keep:])
                    del p[keep:]
            return (pages, np.array(toks, np.int32),
                    np.array(scores, np.float32))

        t_pages, pending, scores = _prefill(tgt)
        d_pages, _d_toks, _d_sc = _prefill(drf)
        dispatches = 2
        proposed = accepted = 0

        out = np.zeros((b, max_new), np.int32)
        out[:, 0] = pending
        emitted = np.ones((b,), np.int64)
        finished = pending == eos
        pos = lens.astype(np.int64).copy()
        rows = np.arange(b)

        while not (finished | (emitted >= max_new)).all():
            live = ~(finished | (emitted >= max_new))
            rem = int((max_new - emitted[live]).max())
            cap = min(tgt.cache.max_seq_len,
                      drf.cache.max_seq_len) - int(pos.max())
            n = max(1, min(kp, rem, cap))
            # grow both pools to cover pos .. pos+n-1
            grow_to = pos + n - 1
            tgt._grow(t_pages, grow_to)
            drf._grow(d_pages, grow_to)
            d_tbl = _page_table(d_pages,
                                drf.cache.max_pages_per_seq)
            t_tbl = _page_table(t_pages,
                                tgt.cache.max_pages_per_seq)
            # 1 dispatch: draft proposes n tokens, appending to its
            # own pool as it goes
            dk, dv = drf.cache.ensure_pool()
            dk, dv, props = self._propose_program(b, n)(
                drf.params, dk, dv, jnp.asarray(pending),
                jnp.asarray(pos.astype(np.int32)),
                jnp.asarray(d_tbl),
            )
            drf.cache.pool = (dk, dv)
            dispatches += 1
            props_np = np.asarray(props)  # [n, B]
            # 1 dispatch: target verifies all n positions as one
            # chunk, appending to its pool
            words = np.concatenate(
                [pending[None, :], props_np[:n - 1]], axis=0
            ).T.astype(np.int32)  # [B, n]
            tk, tv = tgt.cache.ensure_pool()
            tk, tv, gs, glp = self._verify_program(b, n)(
                tgt.params, tk, tv, jnp.asarray(words),
                jnp.asarray(pos.astype(np.int32)),
                jnp.asarray(t_tbl),
            )
            tgt.cache.pool = (tk, tv)
            tgt.cache.appended_tokens += b * n
            tgt.cache.cached_prefix_tokens += int(pos.sum())
            dispatches += 1
            gs_np = np.asarray(gs)    # [B, n]
            glp_np = np.asarray(glp)  # [B, n]

            proposed += n * int(live.sum())
            for r in rows[live]:
                agree = gs_np[r, :n - 1] == props_np[:n - 1, r]
                mism = np.nonzero(~agree)[0]
                n_acc = int(mism[0]) + 1 if mism.size else n
                take = int(min(n_acc, max_new - emitted[r]))
                for j in range(take):
                    t = gs_np[r, j]
                    if finished[r]:
                        t = eos
                    else:
                        scores[r] += glp_np[r, j]
                    out[r, emitted[r]] = t
                    emitted[r] += 1
                    if t == eos:
                        finished[r] = True
                accepted += take
                pos[r] += n_acc
                pending[r] = gs_np[r, n_acc - 1]

        self.last_chain_depth = dispatches
        self.last_accept_rate = (
            accepted / proposed if proposed else None
        )
        for p in t_pages:
            tgt.cache.free(p)
        for p in d_pages:
            drf.cache.free(p)
        # rows that hit eos stop emitting; the greedy reference keeps
        # emitting eos to max_new, so pad the tails to match it
        for r in rows:
            out[r, emitted[r]:] = eos
        return out, np.asarray(scores, np.float32)
