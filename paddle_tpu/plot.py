"""Training-curve plotting helper.

Reference: python/paddle/v2/plot/plot.py (Ploter with per-title
PlotData, append/plot/reset; falls back to text output when matplotlib
or a display is unavailable — the DISABLE_PLOT env toggle)."""

from __future__ import annotations

import os


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    def __init__(self, *titles):
        self.__args__ = titles
        self.__plot_data__ = {t: PlotData() for t in titles}
        self.__disable_plot__ = os.environ.get("DISABLE_PLOT", "")

    def __plot_is_disabled__(self):
        return self.__disable_plot__ == "True"

    def append(self, title, step, value):
        assert title in self.__plot_data__, f"unknown title {title!r}"
        self.__plot_data__[title].append(step, value)

    def _print_latest(self):
        for title in self.__args__:
            d = self.__plot_data__[title]
            if d.step:
                print(f"{title}: step {d.step[-1]} = {d.value[-1]}")

    def plot(self, path: str = None):
        """Render to `path` (PNG) with matplotlib; with no path, show
        the figure when a GUI backend is available, else print the
        latest values. Text output only when matplotlib itself is
        missing — save errors (bad path, full disk) propagate."""
        if self.__plot_is_disabled__():
            return
        try:
            import matplotlib

            if path:
                matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            # backend resolution is deferred to first figure creation —
            # a broken GUI backend on a headless box fails HERE, which
            # still means "matplotlib unavailable": fall back to text
            fig, ax = plt.subplots()
        except Exception:
            self._print_latest()
            return
        try:
            for title in self.__args__:
                d = self.__plot_data__[title]
                ax.plot(d.step, d.value, label=title)
            ax.legend()
            # exact names only: GUI backends like GTK3Cairo/TkCairo must
            # NOT match; "agg" as a suffix covers only the pure
            # rasterizer ("agg"/"macosx" etc. are distinct names)
            backend = matplotlib.get_backend().lower()
            headless = backend in (
                "agg", "pdf", "svg", "ps", "template", "cairo", "pgf",
            )
            if path:
                fig.savefig(path)  # save errors propagate
            elif headless and not matplotlib.is_interactive():
                # nothing would be displayed — print instead of a
                # silent plt.show() no-op
                self._print_latest()
            else:
                plt.show()
        finally:
            plt.close(fig)

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()


def make_diagram(model_conf, title: str = "model") -> str:
    """Graphviz dot text for a ModelConf's layer graph — the `paddle
    make_diagram` subcommand (paddle/scripts/submit_local.sh.in:3-13 →
    python/paddle/utils/make_model_diagram.py). Pure text, no graphviz
    dependency: render with `dot -Tpng model.dot -o model.png`."""
    shapes = {"data": "box", "mixed": "hexagon"}

    def esc(s):
        # single escaping rule for EVERY quoted dot string (ids,
        # labels, and the digraph title)
        return str(s).replace('"', "'")

    def q(name):
        return '"' + esc(name) + '"'

    lines = [
        f"digraph {q(title)} {{",
        "  rankdir=TB;",
        '  node [fontsize=10, shape=ellipse, style=filled,'
        ' fillcolor="#e8eef7"];',
    ]

    for lc in model_conf.layers:
        shape = shapes.get(lc.type, "ellipse")
        fill = "#f7e8e8" if "cost" in lc.type or lc.type in (
            "classification_cost", "cross_entropy", "mse_cost",
        ) else ("#e8f7ea" if lc.type == "data" else "#e8eef7")
        label = f"{esc(lc.name)}\\n{esc(lc.type)}"
        if lc.size:
            label += f" [{lc.size}]"
        lines.append(
            f"  {q(lc.name)} [label=\"{label}\", shape={shape},"
            f" fillcolor=\"{fill}\"];"
        )
    for lc in model_conf.layers:
        for src in lc.input_names():
            lines.append(f"  {q(src)} -> {q(lc.name)};")
    for out in model_conf.output_layer_names:
        lines.append(f"  {q(out)} [penwidth=2];")
    lines.append("}")
    return "\n".join(lines) + "\n"
