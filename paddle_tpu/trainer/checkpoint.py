"""Checkpoint save/load.

Reference: per-pass parameter dirs with rotation and resume
(trainer/ParamUtil.h:77-108 saveParameters/loadParameters, save_only_one,
start_pass), v2 tar format (python/paddle/v2/parameters.py:304,323
to_tar/from_tar), and model merge for deployment (trainer/MergeModel.cpp).

Format: a directory per pass (`pass-%05d/`) holding `params.npz`,
`opt_state.npz` (flattened pytree), `state.npz` and `meta.json`. A merged
single-file deployable (config JSON + weights) is `model.npz` via
`merge_model`, the MergeModel.cpp analogue. Multi-host: only process 0
writes (the save-model election of go/master/service.go:467-495 collapses
to a process-id check under jax.distributed).
"""

from __future__ import annotations

import json
import os
import re
import shutil

import jax
import numpy as np

_PASS_DIR_RE = re.compile(r"^pass-(\d{5})$")


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _unflatten(flat: dict) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = tree
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return tree


def _save_npz(path, tree):
    np.savez(path, **_flatten(tree))


def _load_npz(path):
    with np.load(path) as z:
        return _unflatten({k: z[k] for k in z.files})


def save_pass(
    save_dir: str,
    pass_id: int,
    params: dict,
    opt_state=None,
    state=None,
    meta=None,
    save_only_one=False,
):
    """Crash-safe: everything is written into a `pass-%05d.tmp/` staging
    directory and atomically renamed into place, so a SIGKILL mid-save
    never leaves a loadable-looking partial pass directory — the reader
    either sees the previous pass or the complete new one. Re-saving an
    existing pass parks the old dir at `pass-%05d.old` for the duration
    of the swap; the loader falls back to `.old` if a crash lands
    between the two renames, so even that window never loses the only
    checkpoint."""
    if jax.process_index() != 0:
        return None
    d = os.path.join(save_dir, f"pass-{pass_id:05d}")
    staging, old = d + ".tmp", d + ".old"
    shutil.rmtree(staging, ignore_errors=True)  # stale crash litter
    os.makedirs(staging)
    _save_npz(os.path.join(staging, "params.npz"), params)
    if opt_state is not None:
        _save_npz(os.path.join(staging, "opt_state.npz"), opt_state)
    if state:
        _save_npz(os.path.join(staging, "state.npz"), state)
    with open(os.path.join(staging, "meta.json"), "w") as f:
        json.dump({"pass_id": pass_id, **(meta or {})}, f)
        f.flush()
        os.fsync(f.fileno())
    if os.path.isdir(d):  # re-save of the same pass: two-rename swap
        shutil.rmtree(old, ignore_errors=True)
        os.replace(d, old)
    os.replace(staging, d)
    # committed: sweep the parked old copy (also heals a stale .old
    # left by a crash mid-swap on a previous run)
    shutil.rmtree(old, ignore_errors=True)
    if save_only_one:
        for name in os.listdir(save_dir):
            base = name
            for suf in (".tmp", ".old"):
                if name.endswith(suf):
                    base = name[: -len(suf)]
            if base != f"pass-{pass_id:05d}" and _PASS_DIR_RE.match(base):
                shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)
    return d


def _resolve_pass_dir(save_dir: str, pass_id: int):
    """The directory to read pass `pass_id` from: the committed dir,
    else its `.old` sibling (crash mid re-save swap), else None."""
    d = os.path.join(save_dir, f"pass-{pass_id:05d}")
    for cand in (d, d + ".old"):
        if os.path.exists(os.path.join(cand, "meta.json")):
            return cand
    return None


def list_sync_passes(save_dir: str) -> list:
    """Completed sync pass ids, ascending — `.tmp` staging dirs from an
    interrupted save are not passes, but a `.old` dir orphaned by a
    crash mid re-save swap still counts (the loader reads it)."""
    if not os.path.isdir(save_dir):
        return []
    out = set()
    for n in os.listdir(save_dir):
        base = n[:-4] if n.endswith(".old") else n
        m = _PASS_DIR_RE.match(base)
        if m and _resolve_pass_dir(save_dir, int(m.group(1))):
            out.add(int(m.group(1)))
    return sorted(out)


def load_pass(save_dir: str, pass_id: int = -1):
    """pass_id=-1 loads the latest. Returns (params, opt_state, state, meta)."""
    if pass_id < 0:
        passes = list_sync_passes(save_dir)
        if not passes:
            raise FileNotFoundError(f"no pass-* checkpoints in {save_dir}")
        pass_id = passes[-1]
    d = _resolve_pass_dir(save_dir, pass_id) or os.path.join(
        save_dir, f"pass-{pass_id:05d}"
    )
    params = _load_npz(os.path.join(d, "params.npz"))
    opt_state = state = None
    if os.path.exists(os.path.join(d, "opt_state.npz")):
        opt_state = _load_npz(os.path.join(d, "opt_state.npz"))
    if os.path.exists(os.path.join(d, "state.npz")):
        state = _load_npz(os.path.join(d, "state.npz"))
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    return params, opt_state, state, meta


def load_parameter_file(path: str, dims=None) -> "np.ndarray":
    """One parameter in the reference's raw binary format
    (parameter/Parameter.cpp Parameter::load: a 16-byte
    {version=0, valueSize=4, size} header + float32 payload — also the
    per-member format inside v2 tars). `dims` reshapes the flat
    vector."""
    import struct

    with open(path, "rb") as f:
        head = f.read(16)
        version, vsize, n = struct.unpack(_TAR_HEADER, head)
        if version != 0 or vsize != 4:
            raise ValueError(
                f"{path}: unsupported parameter header "
                f"version={version} valueSize={vsize}"
            )
        arr = np.frombuffer(f.read(4 * n), np.float32).copy()
    if arr.size != n:
        raise ValueError(f"{path}: truncated parameter payload")
    return arr.reshape(dims) if dims is not None else arr


def save_parameter_file(path: str, arr) -> None:
    """Write one parameter in the reference's raw binary format
    (Parameter::save: the same 16-byte header + float32 payload
    load_parameter_file reads)."""
    import struct

    arr = np.asarray(arr, np.float32)
    with open(path, "wb") as f:
        f.write(struct.pack(_TAR_HEADER, 0, 4, arr.size))
        f.write(arr.tobytes())


def load_parameter_dir(model_dir: str, param_confs: dict) -> dict:
    """A reference model directory (trainer/ParamUtil.h loadParameters:
    one raw binary file per parameter, named by parameter) -> params
    dict shaped by `param_confs`."""
    params = {}
    for name, pc in param_confs.items():
        params[name] = load_parameter_file(
            os.path.join(model_dir, name), tuple(pc.dims)
        )
    return params


def merge_model(path: str, model_conf, params: dict, state=None):
    """Single-file deployable: config JSON + weights (MergeModel.cpp /
    capi merged model analogue)."""
    flat = _flatten({"params": params, "state": state or {}})
    np.savez(path, __config__=np.frombuffer(
        model_conf.to_json().encode(), dtype=np.uint8
    ), **flat)


def load_merged(path: str):
    from paddle_tpu.core.config import ModelConf

    with np.load(path) as z:
        conf = ModelConf.from_json(bytes(z["__config__"]).decode())
        tree = _unflatten({k: z[k] for k in z.files if k != "__config__"})
    return conf, tree.get("params", {}), tree.get("state", {})


# --- multi-host sharded checkpoints -----------------------------------
#
# Every process saves ITS addressable shards and restores them on
# restart — the Go pserver's per-shard checkpoint/recover intent
# (go/pserver/service.go:76-126: each pserver checkpoints its own
# parameter shard; recovery reassembles the global state). The
# snapshot/assemble machinery is shared with the async manifested
# format (trainer/async_checkpoint.py): same key scheme, same
# replication dedup, same exact slice-map reassembly — this is the
# bare per-process flavor without manifest/checksums/rotation.


def _walk_arrays(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_walk_arrays(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = tree
    return out


def save_sharded(save_dir: str, tree, tag: str = "ckpt") -> str:
    """Write this process's addressable shards of a (possibly globally
    sharded) pytree. Call from EVERY process; each atomically commits
    `{tag}.p{process_index}.npz` (keys `<name>##<device id>` /
    `<name>##r<process>`, plus the slice map — see
    async_checkpoint.snapshot_shards)."""
    from paddle_tpu.trainer import async_checkpoint as actp

    os.makedirs(save_dir, exist_ok=True)
    payload = actp.snapshot_shards(tree)
    path = os.path.join(
        save_dir, f"{tag}.p{jax.process_index()}.npz"
    )
    tmp = path[:-4] + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def load_sharded(save_dir: str, template, tag: str = "ckpt"):
    """Restore this process's shards written by `save_sharded` and
    reassemble global arrays. `template` is a pytree of arrays (or
    ShapeDtypeStructs) carrying the target global shape + sharding."""
    from paddle_tpu.trainer import async_checkpoint as actp

    path = os.path.join(
        save_dir, f"{tag}.p{jax.process_index()}.npz"
    )
    flat, idxmeta = actp.merge_npz_shards([path])
    return actp.assemble_with_template(flat, idxmeta, template)


# --- v2 tar checkpoint format, wire-compatible with the reference ---
#
# parameters.py:280-302 serialize/deserialize: each parameter tar member is
# a 16-byte struct.pack("IIQ", 0, 4, size) header followed by raw
# little-endian float32 bytes; parameters.py:304-321 to_tar adds a
# "<name>.protobuf" member holding the serialized ParameterConfig proto
# (ParameterConfig.proto: name=1 string, size=2 uint64, dims=9 repeated
# uint64). We hand-encode that wire format (proto2, unpacked varints) so
# tars round-trip with the reference without a protobuf dependency.

_TAR_HEADER = "<IIQ"  # version=0, elem_size=4, num_elems


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _encode_param_config(name: str, shape, conf=None) -> bytes:
    """ParameterConfig wire message. `conf` (a core.config.ParameterConf)
    contributes the optional scalar fields the reference persists:
    learning_rate=3, momentum=4, initial_mean=5, initial_std=6,
    decay_rate=7, decay_rate_l1=8 (doubles), is_static=18,
    sparse_update=22 (bools)."""
    import struct

    size = 1
    for d in shape:
        size *= int(d)
    out = bytearray()
    nb = name.encode()
    out += b"\x0a" + _varint(len(nb)) + nb  # field 1, string
    out += b"\x10" + _varint(size)  # field 2, uint64

    def put_double(field, v):
        out.extend(_varint(field << 3 | 1) + struct.pack("<d", float(v)))

    if conf is not None:
        for field, attr in (
            (3, "learning_rate"),
            (4, "momentum"),
            (5, "initial_mean"),
            (6, "initial_std"),
            (7, "decay_rate"),
            (8, "decay_rate_l1"),
        ):
            v = getattr(conf, attr, None)
            if v is not None:
                put_double(field, v)
    for d in shape:  # field 9, repeated uint64 (unpacked)
        out += b"\x48" + _varint(int(d))
    if conf is not None:
        if getattr(conf, "is_static", False):
            out += _varint(18 << 3) + b"\x01"
        if getattr(conf, "sparse_update", False):
            out += _varint(22 << 3) + b"\x01"
    return bytes(out)


def _decode_param_config(data: bytes):
    """Return (name, dims) from a ParameterConfig wire message, skipping
    unknown fields (learning_rate etc. are irrelevant for loading)."""
    name, dims = None, []
    i, n = 0, len(data)

    def read_varint(i):
        v = s = 0
        while True:
            b = data[i]
            i += 1
            v |= (b & 0x7F) << s
            if not b & 0x80:
                return v, i
            s += 7

    while i < n:
        tag, i = read_varint(i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = read_varint(i)
            if field == 9:
                dims.append(v)
        elif wire == 1:
            i += 8
        elif wire == 2:
            ln, i = read_varint(i)
            if field == 1:
                name = data[i : i + ln].decode()
            i += ln
        elif wire == 5:
            i += 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
    return name, dims


def to_tar(f, params: dict, param_confs: dict = None):
    """Write parameters as a reference-compatible v2 checkpoint tar
    (python/paddle/v2/parameters.py:304 to_tar): one member per parameter
    holding a 16-byte (version=0, elem_size=4, num_elems) header + raw
    little-endian float32 bytes, plus a `<name>.protobuf` member with the
    serialized ParameterConfig (name/size/dims). `f` is a writable binary
    file object or a path."""
    import io
    import struct
    import tarfile

    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "wb") if own else f
    try:
        with tarfile.open(fileobj=fh, mode="w") as tar:

            def add(name, data: bytes):
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

            for name in sorted(params):
                # NOT ascontiguousarray: it promotes 0-d arrays to 1-d,
                # losing the () shape; tobytes() copies to C order anyway
                arr = np.asarray(params[name], dtype=np.float32)
                header = struct.pack(_TAR_HEADER, 0, 4, arr.size)
                add(name, header + arr.tobytes())
                conf = (param_confs or {}).get(name)
                add(
                    name + ".protobuf",
                    _encode_param_config(name, arr.shape, conf),
                )
    finally:
        if own:
            fh.close()


def from_tar(f) -> dict:
    """Read a v2 checkpoint tar back into {name: np.ndarray}
    (parameters.py:323 from_tar). Accepts tars written by `to_tar` or by
    the reference itself: skips the 16-byte member header and reshapes by
    the dims recorded in the `<name>.protobuf` sidecar."""
    import tarfile

    own = isinstance(f, (str, os.PathLike))
    raw: dict = {}
    shapes: dict = {}
    tar = tarfile.open(f) if own else tarfile.open(fileobj=f)
    with tar:
        for member in tar.getmembers():
            if not member.isfile():
                continue
            if member.name.endswith(".conf"):
                raise ValueError(
                    "legacy paddle_tpu tar (pre-reference-format, "
                    "'.conf' JSON sidecars); re-save with to_tar"
                )
            data = tar.extractfile(member).read()
            if member.name.endswith(".protobuf"):
                pname, dims = _decode_param_config(data)
                if pname is None:
                    pname = member.name[: -len(".protobuf")]
                shapes[pname] = dims
            else:
                # copy: frombuffer over tar bytes is read-only
                raw[member.name] = np.frombuffer(
                    data[16:], np.float32
                ).copy()
    return {
        k: v.reshape(shapes[k]) if k in shapes else v
        for k, v in raw.items()
    }
