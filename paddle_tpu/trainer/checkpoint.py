"""Checkpoint save/load.

Reference: per-pass parameter dirs with rotation and resume
(trainer/ParamUtil.h:77-108 saveParameters/loadParameters, save_only_one,
start_pass), v2 tar format (python/paddle/v2/parameters.py:304,323
to_tar/from_tar), and model merge for deployment (trainer/MergeModel.cpp).

Format: a directory per pass (`pass-%05d/`) holding `params.npz`,
`opt_state.npz` (flattened pytree), `state.npz` and `meta.json`. A merged
single-file deployable (config JSON + weights) is `model.npz` via
`merge_model`, the MergeModel.cpp analogue. Multi-host: only process 0
writes (the save-model election of go/master/service.go:467-495 collapses
to a process-id check under jax.distributed).
"""

from __future__ import annotations

import json
import os
import shutil

import jax
import numpy as np


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _unflatten(flat: dict) -> dict:
    tree: dict = {}
    for key, v in flat.items():
        parts = key.split("/")
        d = tree
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        d[parts[-1]] = v
    return tree


def _save_npz(path, tree):
    np.savez(path, **_flatten(tree))


def _load_npz(path):
    with np.load(path) as z:
        return _unflatten({k: z[k] for k in z.files})


def save_pass(
    save_dir: str,
    pass_id: int,
    params: dict,
    opt_state=None,
    state=None,
    meta=None,
    save_only_one=False,
):
    if jax.process_index() != 0:
        return None
    d = os.path.join(save_dir, f"pass-{pass_id:05d}")
    os.makedirs(d, exist_ok=True)
    _save_npz(os.path.join(d, "params.npz"), params)
    if opt_state is not None:
        _save_npz(os.path.join(d, "opt_state.npz"), opt_state)
    if state:
        _save_npz(os.path.join(d, "state.npz"), state)
    with open(os.path.join(d, "meta.json"), "w") as f:
        json.dump({"pass_id": pass_id, **(meta or {})}, f)
    if save_only_one:
        for name in os.listdir(save_dir):
            if name.startswith("pass-") and name != f"pass-{pass_id:05d}":
                shutil.rmtree(os.path.join(save_dir, name), ignore_errors=True)
    return d


def load_pass(save_dir: str, pass_id: int = -1):
    """pass_id=-1 loads the latest. Returns (params, opt_state, state, meta)."""
    if pass_id < 0:
        passes = sorted(
            int(n.split("-")[1])
            for n in os.listdir(save_dir)
            if n.startswith("pass-")
        )
        if not passes:
            raise FileNotFoundError(f"no pass-* checkpoints in {save_dir}")
        pass_id = passes[-1]
    d = os.path.join(save_dir, f"pass-{pass_id:05d}")
    params = _load_npz(os.path.join(d, "params.npz"))
    opt_state = state = None
    if os.path.exists(os.path.join(d, "opt_state.npz")):
        opt_state = _load_npz(os.path.join(d, "opt_state.npz"))
    if os.path.exists(os.path.join(d, "state.npz")):
        state = _load_npz(os.path.join(d, "state.npz"))
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    return params, opt_state, state, meta


def merge_model(path: str, model_conf, params: dict, state=None):
    """Single-file deployable: config JSON + weights (MergeModel.cpp /
    capi merged model analogue)."""
    flat = _flatten({"params": params, "state": state or {}})
    np.savez(path, __config__=np.frombuffer(
        model_conf.to_json().encode(), dtype=np.uint8
    ), **flat)


def load_merged(path: str):
    from paddle_tpu.core.config import ModelConf

    with np.load(path) as z:
        conf = ModelConf.from_json(bytes(z["__config__"]).decode())
        tree = _unflatten({k: z[k] for k in z.files if k != "__config__"})
    return conf, tree.get("params", {}), tree.get("state", {})


def to_tar(f, params: dict, param_confs: dict = None):
    """Write parameters as a tar archive — the v2 checkpoint format
    (python/paddle/v2/parameters.py:304 to_tar): one member per
    parameter holding raw little-endian float32 bytes, plus a
    `<name>.conf` JSON sidecar with its config (the reference stores
    the ParameterConfig proto the same way). `f` is a writable binary
    file object or a path."""
    import io
    import tarfile

    own = isinstance(f, (str, os.PathLike))
    fh = open(f, "wb") if own else f
    try:
        with tarfile.open(fileobj=fh, mode="w") as tar:

            def add(name, data: bytes):
                info = tarfile.TarInfo(name=name)
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))

            for name in sorted(params):
                arr = np.ascontiguousarray(
                    np.asarray(params[name]), np.float32
                )
                add(name, arr.tobytes())
                conf = {"shape": list(arr.shape)}
                if param_confs and name in param_confs:
                    pc = param_confs[name]
                    conf["config"] = (
                        pc.to_dict() if hasattr(pc, "to_dict") else {}
                    )
                add(name + ".conf", json.dumps(conf).encode())
    finally:
        if own:
            fh.close()


def from_tar(f) -> dict:
    """Read a to_tar archive back into {name: np.ndarray}
    (parameters.py:323 from_tar)."""
    import tarfile

    own = isinstance(f, (str, os.PathLike))
    params: dict = {}
    shapes: dict = {}
    tar = tarfile.open(f) if own else tarfile.open(fileobj=f)
    with tar:
        for member in tar.getmembers():
            data = tar.extractfile(member).read()
            if member.name.endswith(".conf"):
                shapes[member.name[: -len(".conf")]] = json.loads(
                    data.decode()
                )["shape"]
            else:
                # copy: frombuffer over tar bytes is read-only
                params[member.name] = np.frombuffer(
                    data, np.float32
                ).copy()
    return {
        k: v.reshape(shapes[k]) if k in shapes else v
        for k, v in params.items()
    }
