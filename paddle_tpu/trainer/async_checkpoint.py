"""Asynchronous, overlapped sharded checkpointing.

The synchronous `checkpoint.save_pass` stalls training for the whole
device_get + serialize + write; at pod scale that stall is the
difference between elastic training and a training-time tax on every
snapshot (the reference's Go pserver checkpoints each shard from its
own goroutine for the same reason, go/pserver/service.go:76-126).
Here the only training-blocking work is the device->host snapshot;
serialization and the atomic-rename write happen on a background
thread behind a bounded queue.

Format (`async-shard-v1`) — one directory per pass:

    save_dir/pass-00007/
        manifest.json        # {"pass_id", "num_shards", "meta", ...}
                             # written by process 0
        shard-p0.npz         # process 0's addressable shards,
                             # keys "<tree path>##<device id>"
        shard-p0.ok.json     # per-shard commit record: keys, nbytes,
                             # sha256 — written AFTER the npz rename
        shard-p1.npz ...     # one pair per process

A pass is COMPLETE iff the manifest exists and every shard it names
has a matching `.ok.json` whose checksum verifies. Every file lands
via write-to-tmp + `os.replace`, so a SIGKILL at any instant leaves
either the previous complete pass or an incomplete new one — never a
loadable-looking lie. Torn or truncated shards fail the checksum and
the loader falls back to the newest older pass that verifies.

Failure contract: the background writer never lets an exception vanish
in a daemon thread. The first error is latched; the next `save()` or
`wait()` re-raises it as `AsyncCheckpointError`. Normal interpreter
exit drains every live writer via an atexit hook, so an
enqueued-but-unwritten pass survives a caller that forgets wait().
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import queue
import random
import re
import shutil
import threading
import time
import weakref

import jax
import numpy as np

from paddle_tpu.analysis.lock_order import named_lock
from paddle_tpu.trainer.checkpoint import _unflatten, _walk_arrays

MANIFEST = "manifest.json"
FORMAT = "async-shard-v1"
_PASS_RE = re.compile(r"^pass-(\d{5})$")

# ---- sharded-table generations (ISSUE 20) ---------------------------
# A second on-disk format for ShardedEmbeddingTable checkpoints. One
# directory per GENERATION; unlike async-shard-v1 the manifest is
# written FIRST (it names every table shard the generation will
# contain), so a writer SIGKILLed between shard N and N+1 leaves a
# manifest referencing a missing shard — exactly the torn state
# `verify_table_generation` must detect AND NAME, and
# `recover_table` must quarantine.
#
#     save_dir/gen-00012/
#         table_manifest.json   # format, generation, num_shards, meta
#         table-s0.npz          # shard 0 payload (sparse_shard
#                               # export_shards dict)
#         table-s0.ok.json      # keys, nbytes, sha256 — AFTER rename
#         table-s1.npz ...
TABLE_MANIFEST = "table_manifest.json"
TABLE_FORMAT = "sharded-table-v1"
_GEN_RE = re.compile(r"^gen-(\d{5})$")
QUARANTINE_DIR = "quarantine"


class AsyncCheckpointError(RuntimeError):
    """A background checkpoint write failed (re-raised on the caller)."""


# Every live AsyncCheckpointer; drained at interpreter exit so a pass
# that was enqueued but not yet written cannot be dropped by a normal
# `exit()` (daemon writer threads die mid-write at teardown). atexit
# runs while daemon threads are still scheduled, so q.join() drains.
# SIGKILL still loses the queue — that is what the manifest/fallback
# protocol is for.
_LIVE_CHECKPOINTERS: "weakref.WeakSet" = weakref.WeakSet()


@atexit.register
def _flush_live_checkpointers() -> None:
    import logging

    for cp in list(_LIVE_CHECKPOINTERS):
        try:
            cp.close()
        except Exception:
            logging.getLogger("paddle_tpu.trainer").exception(
                "async checkpoint flush at interpreter exit failed"
            )


def _pass_dir(save_dir: str, pass_id: int) -> str:
    return os.path.join(save_dir, f"pass-{pass_id:05d}")


def _shard_name(process_index: int) -> str:
    return f"shard-p{process_index}.npz"


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while chunk := f.read(1 << 20):
            h.update(chunk)
    return h.hexdigest()


def _atomic_write_json(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


INDEX_KEY = "__shard_index__"  # reserved payload entry, JSON as uint8


def _index_sig(index, shape) -> list:
    """Canonical JSON-able [[start, stop], ...] for a shard's slice
    tuple (None bounds resolved against the global shape)."""
    out = []
    for sl, dim in zip(index, shape):
        out.append([sl.start or 0,
                    dim if sl.stop is None else sl.stop])
    return out


def snapshot_shards(tree) -> dict:
    """Device->host snapshot of this process's addressable shards of a
    (possibly globally sharded) pytree — the only part of an async save
    that blocks training.

    Keys: `<tree path>##<device id>` for genuinely sharded arrays —
    ONE entry per DISTINCT shard index, so replicas (full or partial,
    e.g. replicated over the data axis while sharded over the model
    axis) are never copied twice; `<tree path>##r<process index>` for
    arrays with a single distinct shard on this process. That dedup is
    what keeps the training-blocking stall flat as the mesh grows (a
    DP-replicated model on 8 devices would otherwise snapshot 8x the
    bytes).

    Sharded entries also record their exact global shape + slice in a
    reserved `__shard_index__` payload entry, so loaders reassemble by
    slice assignment — any sharding layout, not just axis-0 rows."""
    payload = {}
    idxmeta = {}
    rtag = f"r{jax.process_index()}"
    for name, arr in _walk_arrays(tree).items():
        if not hasattr(arr, "addressable_shards"):
            arr = jax.numpy.asarray(arr)
        distinct = {}  # index signature -> shard (first replica wins)
        for sh in arr.addressable_shards:
            sig = tuple(
                tuple(p) for p in _index_sig(sh.index, arr.shape)
            )
            distinct.setdefault(sig, sh)
        # np.asarray(shard.data) is ZERO-COPY on the CPU backend
        # (OWNDATA=False: a view over the device buffer). The payload
        # outlives this call — the background writer serializes it
        # while the training loop is already DONATING these very
        # buffers to the next step — so the snapshot must own its
        # bytes or the written checkpoint can be torn (ISSUE 13:
        # post-rollback restores nondeterministically produced
        # wrong-finite params; the copy is the blocking "host
        # snapshot" cost the async design already budgets for).
        if len(distinct) == 1:
            sh = next(iter(distinct.values()))
            payload[f"{name}##{rtag}"] = np.array(sh.data, copy=True)
        else:
            entries = {}
            for sig, sh in distinct.items():
                payload[f"{name}##{sh.device.id}"] = np.array(
                    sh.data, copy=True
                )
                entries[str(sh.device.id)] = [list(p) for p in sig]
            idxmeta[name] = {
                "global_shape": list(arr.shape),
                "index": entries,
            }
    if idxmeta:
        payload[INDEX_KEY] = np.frombuffer(
            json.dumps(idxmeta).encode(), np.uint8
        ).copy()
    return payload


def write_shard(save_dir: str, pass_id: int, payload: dict,
                meta=None, num_shards: int = None,
                process_index: int = None) -> str:
    """Commit one process's shard of `pass_id` (atomic npz + .ok.json
    checksum sidecar); process 0 also writes the manifest. Safe to call
    from any thread/process; used by both the async writer thread and
    synchronous callers that want the manifested format."""
    pidx = jax.process_index() if process_index is None else process_index
    nsh = jax.process_count() if num_shards is None else num_shards
    d = _pass_dir(save_dir, pass_id)
    os.makedirs(d, exist_ok=True)
    shard = os.path.join(d, _shard_name(pidx))
    # savez appends ".npz" to a name without it; stage, fsync, rename
    tmp = shard[:-4] + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, shard)
    _atomic_write_json(shard[:-4] + ".ok.json", {
        "keys": sorted(payload),
        "nbytes": os.path.getsize(shard),
        "sha256": _sha256(shard),
    })
    if pidx == 0:
        _atomic_write_json(os.path.join(d, MANIFEST), {
            "format": FORMAT,
            "pass_id": pass_id,
            "num_shards": nsh,
            "meta": dict(meta or {}),
        })
    return d


# ---- sharded-table generation API (ISSUE 20) ------------------------


def _gen_dir(save_dir: str, generation: int) -> str:
    return os.path.join(save_dir, f"gen-{generation:05d}")


def _table_shard_name(shard_id: int) -> str:
    return f"table-s{shard_id}.npz"


def begin_table_generation(save_dir: str, generation: int,
                           num_shards: int, meta=None) -> str:
    """Open generation `generation`: write the manifest naming every
    shard it WILL contain. Written first on purpose — completeness is
    judged against this promise, so a writer killed mid-stride leaves
    a manifest pointing at a missing shard (detected, named, and
    quarantined by the recovery path) instead of a shorter manifest
    that lies about what the generation was meant to hold."""
    d = _gen_dir(save_dir, generation)
    os.makedirs(d, exist_ok=True)
    _atomic_write_json(os.path.join(d, TABLE_MANIFEST), {
        "format": TABLE_FORMAT,
        "generation": generation,
        "num_shards": num_shards,
        "meta": dict(meta or {}),
    })
    return d


def write_table_shard(save_dir: str, generation: int, shard_id: int,
                      payload: dict) -> str:
    """Commit one table shard: atomic npz + .ok.json sha256 sidecar
    (same tear-proof discipline as async-shard-v1)."""
    d = _gen_dir(save_dir, generation)
    shard = os.path.join(d, _table_shard_name(shard_id))
    tmp = shard[:-4] + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, shard)
    _atomic_write_json(shard[:-4] + ".ok.json", {
        "keys": sorted(payload),
        "nbytes": os.path.getsize(shard),
        "sha256": _sha256(shard),
    })
    return shard


def write_table_generation(save_dir: str, generation: int,
                           payloads, meta=None) -> str:
    """Synchronous convenience: manifest + every shard in order. The
    async writer (`AsyncCheckpointer.save_table`) commits through the
    same two functions, so both paths tear identically under kill."""
    d = begin_table_generation(save_dir, generation, len(payloads),
                               meta=meta)
    for s, payload in enumerate(payloads):
        write_table_shard(save_dir, generation, s, payload)
    return d


def list_table_generations(save_dir: str) -> list:
    """Manifested generation ids, ascending (quarantine excluded)."""
    if not os.path.isdir(save_dir):
        return []
    out = []
    for name in os.listdir(save_dir):
        m = _GEN_RE.match(name)
        if m and os.path.exists(
            os.path.join(save_dir, name, TABLE_MANIFEST)
        ):
            out.append(int(m.group(1)))
    return sorted(out)


def verify_table_generation(save_dir: str, generation: int) -> tuple:
    """(ok, reason). Complete iff the manifest exists and EVERY shard
    it names has a matching npz + .ok.json whose size and sha256
    verify. The reason always NAMES the offending table shard — the
    operator of a 1B-row table needs 'table shard 3 of 8 torn', not
    'checkpoint bad'."""
    d = _gen_dir(save_dir, generation)
    try:
        with open(os.path.join(d, TABLE_MANIFEST)) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"table manifest unreadable: {e}"
    if man.get("format") != TABLE_FORMAT:
        return False, f"unknown table format {man.get('format')!r}"
    for s in range(man["num_shards"]):
        shard = os.path.join(d, _table_shard_name(s))
        ok_path = shard[:-4] + ".ok.json"
        if not os.path.exists(shard):
            return False, (
                f"table shard {s} of {man['num_shards']}: npz missing"
            )
        try:
            with open(ok_path) as f:
                ok = json.load(f)
        except (OSError, ValueError):
            return False, (
                f"table shard {s} of {man['num_shards']}: "
                f"missing/unreadable {ok_path}"
            )
        if os.path.getsize(shard) != ok["nbytes"]:
            return False, (
                f"table shard {s} of {man['num_shards']}: size "
                f"{os.path.getsize(shard)} != committed "
                f"{ok['nbytes']} (torn write)"
            )
        if _sha256(shard) != ok["sha256"]:
            return False, (
                f"table shard {s} of {man['num_shards']}: "
                f"checksum mismatch (corrupt)"
            )
    return True, "ok"


def latest_good_table_generation(save_dir: str) -> int:
    """Newest generation that verifies, or -1 (torn ones skipped with
    a warning naming the shard)."""
    import logging

    for gen in reversed(list_table_generations(save_dir)):
        ok, reason = verify_table_generation(save_dir, gen)
        if ok:
            return gen
        logging.getLogger("paddle_tpu.trainer").warning(
            "table gen-%05d rejected (%s); falling back", gen, reason,
        )
    return -1


def quarantine_table_generation(save_dir: str, generation: int,
                                reason: str = "") -> str:
    """Move a torn generation aside into `quarantine/` (never delete:
    a half-written 1B-row table is evidence, and most of its shards
    are intact bytes an operator may still want). A `reason.txt`
    records why. Returns the quarantine path."""
    qdir = os.path.join(save_dir, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    src = _gen_dir(save_dir, generation)
    dst = os.path.join(qdir, f"gen-{generation:05d}")
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = os.path.join(qdir, f"gen-{generation:05d}.{n}")
    os.replace(src, dst)
    with open(os.path.join(dst, "reason.txt"), "w") as f:
        f.write(reason + "\n")
    return dst


def load_table_generation(save_dir: str, generation: int = -1) -> tuple:
    """Load one VERIFIED generation. Returns
    (generation, [shard payload dict, ...], meta). `generation=-1`
    loads the newest complete one; an explicit torn generation
    raises, naming the shard."""
    if generation < 0:
        generation = latest_good_table_generation(save_dir)
        if generation < 0:
            raise FileNotFoundError(
                f"no complete sharded-table generation in {save_dir}"
            )
    else:
        ok, reason = verify_table_generation(save_dir, generation)
        if not ok:
            raise ValueError(
                f"table gen-{generation:05d} incomplete: {reason}"
            )
    d = _gen_dir(save_dir, generation)
    with open(os.path.join(d, TABLE_MANIFEST)) as f:
        man = json.load(f)
    payloads = []
    for s in range(man["num_shards"]):
        with np.load(os.path.join(d, _table_shard_name(s))) as z:
            payloads.append({k: z[k] for k in z.files})
    return generation, payloads, man["meta"]


def recover_table(save_dir: str) -> tuple:
    """Quarantine-and-rebuild (the elastic resume entry point): every
    generation NEWER than the last good one that fails verification
    is moved to quarantine (reason names the shard), then the last
    good generation is loaded. Returns
    (generation, payloads, meta, [quarantine records]) — generation
    is -1 with empty payloads when nothing has committed yet (cold
    start)."""
    quarantined = []
    good = latest_good_table_generation(save_dir)
    for gen in list_table_generations(save_dir):
        if gen <= good:
            continue
        ok, reason = verify_table_generation(save_dir, gen)
        if not ok:
            path = quarantine_table_generation(save_dir, gen, reason)
            quarantined.append(
                {"generation": gen, "reason": reason, "path": path}
            )
    if good < 0:
        return -1, [], {}, quarantined
    gen, payloads, meta = load_table_generation(save_dir, good)
    return gen, payloads, meta, quarantined


def list_passes(save_dir: str) -> list:
    """Manifested pass ids, ascending (staging/.tmp names excluded)."""
    if not os.path.isdir(save_dir):
        return []
    out = []
    for name in os.listdir(save_dir):
        m = _PASS_RE.match(name)
        if m and os.path.exists(
            os.path.join(save_dir, name, MANIFEST)
        ):
            out.append(int(m.group(1)))
    return sorted(out)


def verify_pass(save_dir: str, pass_id: int) -> tuple:
    """(ok, reason). A pass verifies iff the manifest exists and every
    shard it names has an .ok.json whose size and sha256 match the npz
    on disk — a torn/truncated shard fails here, not at np.load."""
    d = _pass_dir(save_dir, pass_id)
    try:
        with open(os.path.join(d, MANIFEST)) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return False, f"manifest unreadable: {e}"
    if man.get("format") != FORMAT:
        return False, f"unknown format {man.get('format')!r}"
    for i in range(man["num_shards"]):
        shard = os.path.join(d, _shard_name(i))
        ok_path = shard[:-4] + ".ok.json"
        try:
            with open(ok_path) as f:
                ok = json.load(f)
        except (OSError, ValueError):
            return False, f"shard {i}: missing/unreadable {ok_path}"
        if not os.path.exists(shard):
            return False, f"shard {i}: npz missing"
        if os.path.getsize(shard) != ok["nbytes"]:
            return False, (
                f"shard {i}: size {os.path.getsize(shard)} != "
                f"committed {ok['nbytes']} (truncated?)"
            )
        if _sha256(shard) != ok["sha256"]:
            return False, f"shard {i}: checksum mismatch (corrupt)"
    return True, "ok"


def latest_complete_pass(save_dir: str) -> int:
    """Newest pass id that verifies, or -1. Incomplete/torn passes are
    skipped with a warning — the fall-back-to-previous-pass semantics
    of the reference's snapshot recovery (go/master/service.go:166)."""
    import logging

    for pid in reversed(list_passes(save_dir)):
        ok, reason = verify_pass(save_dir, pid)
        if ok:
            return pid
        logging.getLogger("paddle_tpu.trainer").warning(
            "checkpoint pass-%05d rejected (%s); falling back",
            pid, reason,
        )
    return -1


def merge_npz_shards(paths) -> tuple:
    """Host-side merge of shard npz files. Returns
    (flat {tree key ##tag -> np}, index metadata {name -> {"global_shape",
    "index": {device id -> [[start, stop], ...]}}} unioned across
    files)."""
    flat = {}
    idxmeta: dict = {}
    for path in paths:
        with np.load(path) as z:
            for k in z.files:
                if k == INDEX_KEY:
                    meta = json.loads(bytes(z[k]).decode())
                    for name, m in meta.items():
                        cur = idxmeta.setdefault(
                            name,
                            {"global_shape": m["global_shape"],
                             "index": {}},
                        )
                        cur["index"].update(m["index"])
                else:
                    flat[k] = z[k]
    return flat, idxmeta


def _merge_shard_files(d: str, num_shards: int) -> tuple:
    return merge_npz_shards(
        os.path.join(d, _shard_name(i)) for i in range(num_shards)
    )


def _assemble_by_index(name: str, flat: dict, meta: dict):
    """Exact reassembly of one sharded array from its recorded slice
    map. Verifies full coverage — a shard map that leaves holes (e.g.
    a process count mismatch) is an error, not silent garbage."""
    shape = tuple(meta["global_shape"])
    first = flat[f"{name}##{next(iter(meta['index']))}"]
    out = np.empty(shape, first.dtype)
    covered = np.zeros(shape, bool)
    for dev, sig in meta["index"].items():
        sl = tuple(slice(a, b) for a, b in sig)
        out[sl] = flat[f"{name}##{dev}"]
        covered[sl] = True
    if not covered.all():
        raise ValueError(
            f"shard map for {name!r} does not cover the global shape "
            f"{shape} ({int(covered.sum())}/{covered.size} elements)"
        )
    return out


def load_pass(save_dir: str, pass_id: int = -1, template=None):
    """Load an async-format pass; `pass_id=-1` = newest COMPLETE pass.
    Returns (tree, meta).

    Without `template`, arrays are reassembled on host: per tree key the
    per-device entries are concatenated along axis 0 when their shapes
    tile the way a data/row sharding does, else (replicated) the first
    entry wins. With `template` (pytree of arrays/ShapeDtypeStructs
    carrying global shape + sharding), each process device_puts exactly
    its addressable shards — the multi-host restore path."""
    if pass_id < 0:
        pass_id = latest_complete_pass(save_dir)
        if pass_id < 0:
            raise FileNotFoundError(
                f"no complete async checkpoint pass in {save_dir}"
            )
    else:
        ok, reason = verify_pass(save_dir, pass_id)
        if not ok:
            raise ValueError(
                f"checkpoint pass-{pass_id:05d} incomplete: {reason}"
            )
    d = _pass_dir(save_dir, pass_id)
    with open(os.path.join(d, MANIFEST)) as f:
        man = json.load(f)
    man["meta"] = {"pass_id": man["pass_id"], **man["meta"]}

    flat, idxmeta = _merge_shard_files(d, man["num_shards"])

    if template is not None:
        return (
            assemble_with_template(flat, idxmeta, template),
            man["meta"],
        )

    by_name: dict = {}
    for k, v in flat.items():
        name, tag = k.rsplit("##", 1)
        by_name.setdefault(name, []).append((tag, v))
    out = {}
    for name, entries in by_name.items():
        if name in idxmeta:
            # exact slice map recorded at save time: reassemble any
            # sharding layout (axis 1, 2D tiles, ...) — never guess
            out[name] = _assemble_by_index(name, flat, idxmeta[name])
            continue
        arrs = [v for _, v in sorted(entries, key=lambda e: e[0])]
        same = all(a.shape == arrs[0].shape for a in arrs)
        if len(arrs) == 1 or (same and all(
            np.array_equal(a, arrs[0]) for a in arrs[1:]
        )):
            out[name] = arrs[0]  # replicated (or single shard)
        elif same and all(e[0].isdigit() for e in entries):
            # hand-built payload without a slice map (write_shard API
            # callers): device-id order concatenates along axis 0 —
            # only row sharding is expressible this way
            arrs = [
                v for _, v in sorted(entries, key=lambda e: int(e[0]))
            ]
            out[name] = np.concatenate(arrs, axis=0)
        else:
            raise ValueError(
                f"cannot reassemble {name!r} without a template "
                f"(shapes {[a.shape for a in arrs]})"
            )
    return _unflatten(out), man["meta"]


def assemble_with_template(flat: dict, idxmeta: dict, template):
    """Re-place host shard entries onto devices per `template` (a
    pytree of arrays/ShapeDtypeStructs carrying global shape +
    sharding). Per target device: its exact saved entry, else the
    saved shard whose recorded slice equals the device's slice under
    the template sharding (same-topology restart with renumbered
    devices), else this process's replicated copy."""
    rtag = f"r{jax.process_index()}"
    out = {}
    for name, t in _walk_arrays(template).items():
        sharding = t.sharding
        meta = idxmeta.get(name)
        sig_to_key = {}
        if meta:
            sig_to_key = {
                tuple(tuple(p) for p in sig): f"{name}##{dev}"
                for dev, sig in meta["index"].items()
            }
            dev_sigs = {
                dev: tuple(
                    tuple(p)
                    for p in _index_sig(idx, tuple(t.shape))
                )
                for dev, idx in sharding.addressable_devices_indices_map(
                    tuple(t.shape)
                ).items()
            }
        bufs = []
        for dev in sharding.addressable_devices:
            key = f"{name}##{dev.id}"
            if key not in flat and meta:
                key = sig_to_key.get(dev_sigs[dev], key)
            if key not in flat:
                key = f"{name}##{rtag}"
            bufs.append(jax.device_put(flat[key], dev))
        out[name] = jax.make_array_from_single_device_arrays(
            t.shape, sharding, bufs
        )
    return _unflatten(out)


class AsyncCheckpointer:
    """Overlapped checkpoint writer.

    `save()` blocks only for the device->host snapshot (and for queue
    backpressure when `queue_depth` saves are already in flight), then
    returns; a single background thread serializes and commits shards.
    `wait()` drains the queue and raises the first latched write error.
    """

    def __init__(self, save_dir: str, keep_last: int = 0,
                 queue_depth: int = 2, retries: int = 3,
                 retry_base_s: float = 0.05,
                 retry_max_s: float = 0.5):
        """`keep_last=0` keeps every pass; `keep_last=n` rotates all but
        the newest n COMPLETE passes (the reference's save_only_one is
        keep_last=1, trainer/ParamUtil.h:77).

        `retries`: transient per-shard write failures (OSError from
        the background writer — NFS hiccup, momentary ENOSPC) are
        retried up to this many times with bounded jittered
        exponential backoff (`retry_base_s` doubling to
        `retry_max_s`) BEFORE latching into `last_error`. One blip
        must not poison the checkpointer; a persistent failure still
        surfaces on the next save()/wait()."""
        self.save_dir = save_dir
        self.keep_last = keep_last
        self.retries = max(0, int(retries))
        self.retry_base_s = retry_base_s
        self.retry_max_s = retry_max_s
        # instance-level write hooks: the fault-injection tests wrap
        # these (testing_faults.TransientFault) to fail N writes
        # deterministically without monkeypatching the module
        self._write_shard = write_shard
        self._write_table_shard = write_table_shard
        self._begin_table_generation = begin_table_generation
        self._q: queue.Queue = queue.Queue(maxsize=max(1, queue_depth))
        # known locks (ISSUE 13): instrumented under the faults
        # shard's lock-order checker (analysis/lock_order.py)
        self._snap_lock = named_lock("ckpt.snapshot")
        self._err_lock = named_lock("ckpt.error")
        self._last_error: Exception | None = None
        self._verified: set = set()  # pass ids already proven complete
        self._thread = threading.Thread(
            target=self._worker, name="async-ckpt-writer", daemon=True
        )
        self._thread.start()
        self._closed = False
        _LIVE_CHECKPOINTERS.add(self)

    # ---- error contract ----
    @property
    def last_error(self) -> Exception | None:
        with self._err_lock:
            return self._last_error

    def _raise_if_failed(self):
        # surfacing CLEARS the latch: once the caller has seen the
        # error, the writer is usable again (a transient ENOSPC must
        # not poison every later run on the same trainer instance)
        with self._err_lock:
            err, self._last_error = self._last_error, None
        if err is not None:
            raise AsyncCheckpointError(
                f"background checkpoint write failed: {err!r}"
            ) from err

    # ---- producer ----
    def save(self, pass_id: int, params, opt_state=None, state=None,
             meta=None) -> None:
        """Snapshot to host and enqueue the write. The tree layout
        mirrors `checkpoint.save_pass` (params/opt_state/state roots)
        so loaders can hand back the same triple."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_if_failed()
        tree = {"params": params}
        if opt_state is not None:
            tree["opt_state"] = opt_state
        if state:
            tree["state"] = state
        with self._snap_lock:
            payload = snapshot_shards(tree)
        self._q.put(("pass", pass_id, payload, dict(meta or {})))

    def save_table(self, generation: int, payloads, meta=None) -> None:
        """Enqueue one sharded-table generation (`sharded-table-v1`):
        manifest first, then every shard payload with its sha256
        sidecar, all on the background writer. `payloads` must
        already own their bytes (ShardedEmbeddingTable.export_shards
        copies) — the table keeps training while this writes."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        self._raise_if_failed()
        self._q.put(("table", generation, list(payloads),
                     dict(meta or {})))

    # ---- consumer ----
    def _retrying(self, fn, *args, **kwargs):
        """Run one write, retrying TRANSIENT failures (OSError) with
        bounded jittered exponential backoff. Anything else — or an
        OSError that outlives the retry budget — propagates to the
        latch. Every file involved lands via write-to-tmp +
        os.replace, so a failed attempt never leaves a
        loadable-looking partial for the retry to trip over."""
        delay = self.retry_base_s
        for attempt in range(self.retries + 1):
            try:
                return fn(*args, **kwargs)
            except OSError:
                if attempt >= self.retries:
                    raise
                time.sleep(delay * (0.5 + random.random()))
                delay = min(delay * 2.0, self.retry_max_s)

    def _commit(self, item):
        kind = item[0]
        if kind == "pass":
            _k, pass_id, payload, meta = item
            self._retrying(self._write_shard, self.save_dir, pass_id,
                           payload, meta=meta)
            if self.keep_last and jax.process_index() == 0:
                self._rotate(pass_id)
        else:
            _k, generation, payloads, meta = item
            self._retrying(self._begin_table_generation,
                           self.save_dir, generation, len(payloads),
                           meta=meta)
            for s, payload in enumerate(payloads):
                self._retrying(self._write_table_shard, self.save_dir,
                               generation, s, payload)

    def _worker(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            try:
                self._commit(item)
            except Exception as e:  # latch; surface on save()/wait()
                with self._err_lock:
                    if self._last_error is None:
                        self._last_error = e
            finally:
                self._q.task_done()

    def _rotate(self, newest_pass: int):
        """Prune old passes, keeping the newest `keep_last` COMPLETE
        ones. Never removes a complete pass until enough newer complete
        ones exist — a crash mid-rotation still leaves a loadable
        checkpoint. Stale staging litter is swept too.

        Completeness verdicts are memoized: re-hashing every retained
        checkpoint on every save would make the background writer
        O(total checkpoint bytes) per save and backpressure the
        bounded queue into the training thread. (Rotation is not the
        integrity gate — load re-verifies from disk.)"""
        complete = []
        for p in list_passes(self.save_dir):
            if p not in self._verified and verify_pass(
                self.save_dir, p
            )[0]:
                self._verified.add(p)
            if p in self._verified:
                complete.append(p)
        for pid in complete[: -self.keep_last]:
            shutil.rmtree(
                _pass_dir(self.save_dir, pid), ignore_errors=True
            )
        for name in os.listdir(self.save_dir):
            if name.endswith(".tmp") and _PASS_RE.match(name[:-4]):
                shutil.rmtree(
                    os.path.join(self.save_dir, name),
                    ignore_errors=True,
                )

    # ---- draining ----
    def wait(self) -> None:
        """Block until every enqueued save has committed; raise the
        first background write error if one occurred."""
        self._q.join()
        self._raise_if_failed()

    def close(self) -> None:
        """Drain, stop the writer thread, surface any error."""
        if self._closed:
            return
        _LIVE_CHECKPOINTERS.discard(self)
        self._q.join()
        self._closed = True
        self._q.put(None)
        self._thread.join(timeout=60)
        self._raise_if_failed()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
