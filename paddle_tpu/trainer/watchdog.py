"""Training watchdog: divergence detection, rollback, preemption.

The reference's Go master/pserver tier made training survive *process*
death (lease requeue go/master/service.go:313, snapshot recovery
service.go:166-207); rounds 7-8 rebuilt that for SIGKILL and torn
checkpoints. This module covers the failures that do NOT kill the
process:

- a NaN/Inf loss or gradient (bad data, fp overflow) that would
  silently poison the parameters,
- a loss spike that destroys hours of progress while every health
  check stays green,
- a TPU preemption (SIGTERM) that would drop everything since the
  last pass boundary.

Detection is split so the happy path costs nothing extra on the host:
the all-finite reduction runs ON DEVICE inside the jitted train step
(parallel/dp.py::TrainStep watchdog mode) and rides back in the same
2-float fetch as the loss; a non-finite batch's update is skipped
on-device (params/opt-state/state keep their old values), so by the
time the host learns about the bad batch it has already been absorbed.

The host-side `Watchdog` then runs the escalation ladder:

    skip          non-finite batch: update already skipped on device;
                  decrement the bounded skip budget (one per bad batch)
    backoff       finite loss but > EWMA spike threshold: scale LR by
                  `lr_backoff` and re-warm linearly over
                  `lr_rewarm_batches` (the PaLM-style spike response)
    rollback      skip budget exhausted, or spikes keep coming during
                  backoff: restore the last GOOD checkpoint (promoted
                  only after `good_batches` healthy batches — a
                  checkpoint saved just before divergence is never
                  trusted) via the async_checkpoint manifests
    abort         no good checkpoint to roll back to, or
                  `max_rollbacks` exceeded: raise `WatchdogAbort`
                  carrying a structured `WatchdogReport`

Preemption safety: `PreemptionGuard` turns SIGTERM into a flag the
training loop checks AFTER the in-flight batch completes; the loop
flushes a mid-pass checkpoint and raises `Preempted`, which the CLI
maps to `EXIT_PREEMPTED` (75, EX_TEMPFAIL) — the exit code
`launch.py` recognizes and respawns, making `kill -TERM` lossless.

Import-light on purpose (no jax): launch.py and the CLI import the
exit-code contract without paying for a device runtime. (obs.metrics
is jax-free by lint, so the telemetry wiring keeps that property.)

Telemetry (ISSUE 10): every ladder event is simultaneously (a) kept
on the structured `WatchdogReport`, (b) counted in the process
registry (`watchdog.events{kind=...}`), and (c) emitted on the JSONL
event stream with its `global_step` stamp — so NaN-detect latency and
rollback cost are computed from the stream by the bench rows instead
of grepped out of logs.
"""

from __future__ import annotations

import math
import signal
import threading
from dataclasses import dataclass, field
from typing import List, Optional

from paddle_tpu.obs import metrics as _metrics
from paddle_tpu.obs import flight_recorder as _flight

# ladder rungs that count as anomalies: each one trips a (rate-
# limited) flight-recorder dump so the spans/timeline/events leading
# up to the rung survive the process. promote/rewarmed are healthy.
ANOMALY_RUNGS = frozenset(
    {"skip", "spike", "backoff", "rollback", "abort"}
)

# EX_TEMPFAIL: "temporary failure, retry" — the one exit code in the
# sysexits range that means exactly what a preemption is. launch.py
# respawns ranks that exit with it instead of failing the job.
EXIT_PREEMPTED = 75

# observe() verdicts
OK = "ok"
SKIP = "skip"
BACKOFF = "backoff"
ROLLBACK = "rollback"
ABORT = "abort"


@dataclass
class WatchdogConfig:
    """Knobs for the escalation ladder. Defaults are deliberately
    conservative: healthy training must never trip them (a false
    rollback costs more than a late one)."""

    # non-finite handling: bad batches skipped on-device; after
    # `skip_budget` skips with no healthy batch in between the run is
    # presumed diverged and escalates to rollback
    skip_budget: int = 5
    # EWMA spike detector: loss is a spike when it exceeds
    # mean + spike_sigma * std (EWMA estimates) AND mean * spike_ratio
    # (the ratio guard keeps near-zero-variance phases from flagging
    # ordinary noise). Armed only after `warmup_batches` observations.
    ewma_alpha: float = 0.05
    spike_sigma: float = 10.0
    spike_ratio: float = 2.0
    warmup_batches: int = 20
    # backoff rung: on a spike, scale LR by `lr_backoff` and re-warm
    # linearly back to 1.0 over `lr_rewarm_batches`; `spikes_to_rollback`
    # spikes within one backoff episode escalate to rollback
    lr_backoff: float = 0.5
    lr_rewarm_batches: int = 50
    spikes_to_rollback: int = 3
    # a checkpoint is promoted to "good" (= a rollback target) only
    # after this many consecutive healthy batches follow its save
    good_batches: int = 8
    # rollbacks per run before the watchdog gives up and aborts
    max_rollbacks: int = 2


@dataclass
class WatchdogEvent:
    kind: str  # skip | spike | backoff | rewarmed | promote | rollback | abort
    global_step: int
    detail: dict = field(default_factory=dict)


@dataclass
class WatchdogReport:
    """Structured record of everything the watchdog did — attached to
    `WatchdogAbort`, exposed as `SGD.last_watchdog_report`, and the
    thing a postmortem reads instead of grepping logs."""

    skipped_batches: int = 0
    spikes: int = 0
    backoffs: int = 0
    rollbacks: int = 0
    aborted: bool = False
    abort_reason: str = ""
    last_good_pass: Optional[int] = None
    events: List[WatchdogEvent] = field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "skipped_batches": self.skipped_batches,
            "spikes": self.spikes,
            "backoffs": self.backoffs,
            "rollbacks": self.rollbacks,
            "aborted": self.aborted,
            "abort_reason": self.abort_reason,
            "last_good_pass": self.last_good_pass,
            "events": [
                {"kind": e.kind, "global_step": e.global_step,
                 **e.detail}
                for e in self.events
            ],
        }


class WatchdogAbort(RuntimeError):
    """The escalation ladder ran out of rungs. Carries the report."""

    def __init__(self, report: WatchdogReport):
        self.report = report
        super().__init__(
            f"training aborted by watchdog: {report.abort_reason} "
            f"(skipped={report.skipped_batches}, "
            f"spikes={report.spikes}, rollbacks={report.rollbacks})"
        )


class Preempted(Exception):
    """SIGTERM landed; the in-flight batch finished and a checkpoint
    was flushed. The CLI converts this to EXIT_PREEMPTED."""

    def __init__(self, pass_id: int, batches_done: int,
                 save_dir: Optional[str] = None):
        self.pass_id = pass_id
        self.batches_done = batches_done
        self.save_dir = save_dir
        super().__init__(
            f"preempted at pass {pass_id} after {batches_done} "
            f"batches; checkpoint flushed"
            + (f" to {save_dir}" if save_dir else "")
        )


class Watchdog:
    """Host-side half of the watchdog: consumes the (loss, finite)
    pair the device step already produced and answers with the next
    rung of the ladder. Pure bookkeeping — no device work."""

    def __init__(self, config: Optional[WatchdogConfig] = None):
        self.config = config or WatchdogConfig()
        self.report = WatchdogReport()
        self._reg = _metrics.get_registry()
        # EWMA loss statistics
        self._mean: Optional[float] = None
        self._var = 0.0
        self._observed = 0
        # skip bookkeeping: consecutive bad batches (a healthy batch
        # refills nothing — the budget is per divergence episode,
        # reset only by a healthy batch)
        self._consecutive_skips = 0
        # LR backoff episode
        self._scale = 1.0
        self._rewarm_left = 0
        self._episode_spikes = 0
        # checkpoint promotion
        self._candidate_pass: Optional[int] = None
        self._candidate_healthy = 0
        self._good_pass: Optional[int] = None

    # ---- telemetry ----
    def record_event(self, kind: str, global_step: int,
                     **detail) -> None:
        """One ladder event, recorded everywhere at once: the report
        (postmortems), the registry counter (metricz), and the JSONL
        event stream (latency measurement). Used by the trainer too
        (rollback-unloadable aborts) so the three records can never
        disagree."""
        self.report.events.append(
            WatchdogEvent(kind, global_step, detail)
        )
        self._reg.counter("watchdog.events").inc(kind=kind)
        self._reg.event("watchdog", event=kind,
                        global_step=global_step, **detail)
        if kind in ANOMALY_RUNGS:
            # the dump happens AFTER the event above, so the bundle's
            # ring contains the rung that tripped it
            _flight.maybe_dump(f"watchdog_{kind}",
                               global_step=global_step, **detail)

    # ---- checkpoint promotion ----
    @property
    def good_pass(self) -> Optional[int]:
        """Newest checkpoint pass proven healthy — the rollback target."""
        return self._good_pass

    def on_checkpoint(self, pass_id: int) -> None:
        """A checkpoint for `pass_id` just committed. It becomes a
        *candidate*; only `good_batches` consecutive healthy batches
        promote it (a snapshot of already-diverging params must never
        become the rollback target)."""
        self._candidate_pass = pass_id
        self._candidate_healthy = 0

    def _promote_if_ready(self, global_step: int) -> None:
        if self._candidate_pass is None:
            return
        self._candidate_healthy += 1
        if self._candidate_healthy >= self.config.good_batches:
            self._good_pass = self._candidate_pass
            self.report.last_good_pass = self._good_pass
            self.record_event("promote", global_step,
                              pass_id=self._candidate_pass)
            self._candidate_pass = None

    def _demote_candidate(self) -> None:
        # an unhealthy batch while a candidate is pending: the
        # checkpoint may hold already-poisoned params — drop it
        self._candidate_pass = None

    # ---- LR ladder ----
    def lr_scale(self) -> float:
        """Multiplier for this batch's learning rate (1.0 on the happy
        path; `lr_backoff` right after a spike, linearly re-warming)."""
        return self._scale

    def _start_backoff(self, global_step: int) -> None:
        c = self.config
        self._scale = c.lr_backoff
        self._rewarm_left = max(c.lr_rewarm_batches, 1)
        self.report.backoffs += 1
        self.record_event(
            "backoff", global_step, lr_scale=c.lr_backoff,
            rewarm_batches=self._rewarm_left,
        )

    def _advance_rewarm(self) -> None:
        if self._rewarm_left <= 0:
            return
        self._rewarm_left -= 1
        if self._rewarm_left == 0:
            self._scale = 1.0
            self._episode_spikes = 0
        else:
            c = self.config
            frac = 1.0 - self._rewarm_left / max(c.lr_rewarm_batches, 1)
            self._scale = c.lr_backoff + (1.0 - c.lr_backoff) * frac

    # ---- rollback bookkeeping ----
    def on_rollback(self, pass_id: int, global_step: int) -> None:
        """The trainer restored `pass_id`. Reset every estimator — the
        post-rollback loss distribution is the checkpoint's, not the
        diverged run's."""
        self.report.rollbacks += 1
        self.record_event("rollback", global_step, pass_id=pass_id)
        self._mean = None
        self._var = 0.0
        self._observed = 0
        self._consecutive_skips = 0
        self._scale = 1.0
        self._rewarm_left = 0
        self._episode_spikes = 0
        # the restored checkpoint is good by construction (it was
        # promoted); keep it as the target for a repeat rollback
        self._candidate_pass = None

    # ---- the ladder ----
    def observe(self, loss: float, finite: bool,
                global_step: int) -> str:
        """One batch's verdict. Returns OK / SKIP / BACKOFF /
        ROLLBACK / ABORT. SKIP means the device already dropped the
        update; ROLLBACK/ABORT are requests the trainer must act on."""
        c = self.config
        if not finite or not math.isfinite(loss):
            self._demote_candidate()
            self._consecutive_skips += 1
            self.report.skipped_batches += 1
            self.record_event(
                "skip", global_step, loss=repr(loss),
                budget_left=c.skip_budget - self._consecutive_skips,
            )
            if self._consecutive_skips > c.skip_budget:
                return self._escalate(global_step,
                                      "skip budget exhausted")
            return SKIP

        # finite batch: advance the re-warm ramp before spike checks
        self._advance_rewarm()
        self._consecutive_skips = 0

        spike = False
        if self._mean is not None and self._observed >= c.warmup_batches:
            std = math.sqrt(max(self._var, 0.0))
            spike = (
                loss > self._mean + c.spike_sigma * std
                and loss > abs(self._mean) * c.spike_ratio
            )
        if spike:
            self._demote_candidate()
            self.report.spikes += 1
            self._episode_spikes += 1
            self.record_event(
                "spike", global_step, loss=loss, ewma_mean=self._mean,
                ewma_std=math.sqrt(max(self._var, 0.0)),
            )
            # the spiking loss is NOT folded into the EWMA — it would
            # drag the threshold up and mask a follow-on spike
            if self._episode_spikes >= c.spikes_to_rollback:
                return self._escalate(global_step,
                                      "repeated loss spikes")
            self._start_backoff(global_step)
            return BACKOFF

        # healthy batch: update EWMA mean/var, promote candidates
        if self._mean is None:
            self._mean = loss
        else:
            a = c.ewma_alpha
            d = loss - self._mean
            self._mean += a * d
            self._var = (1.0 - a) * (self._var + a * d * d)
        self._observed += 1
        self._promote_if_ready(global_step)
        return OK

    def _escalate(self, global_step: int, why: str) -> str:
        if (self._good_pass is None
                or self.report.rollbacks >= self.config.max_rollbacks):
            self.report.aborted = True
            self.report.abort_reason = (
                why + (": no good checkpoint to roll back to"
                       if self._good_pass is None
                       else f": max_rollbacks={self.config.max_rollbacks}"
                            " exceeded")
            )
            self.record_event("abort", global_step,
                              reason=self.report.abort_reason)
            return ABORT
        return ROLLBACK


class PreemptionGuard:
    """Context manager that converts SIGTERM into a checked flag.

    The handler only flips a bool — the in-flight jitted batch always
    completes, and the training loop performs the flush at a batch
    boundary (the only point where params/opt-state are consistent).
    Installing a handler is only legal on the main thread; elsewhere
    (e.g. a serving worker running a train loop) the guard degrades to
    an inert flag and SIGTERM keeps its process-default meaning."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = signals
        self._prev: dict = {}
        self.preempted = False
        self.installed = False

    def _handler(self, signum, frame):
        self.preempted = True

    def __enter__(self):
        if threading.current_thread() is threading.main_thread():
            for s in self._signals:
                self._prev[s] = signal.signal(s, self._handler)
            self.installed = True
        return self

    def __exit__(self, *exc):
        for s, prev in self._prev.items():
            try:
                signal.signal(s, prev)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self.installed = False
        return False
