"""Online sparse-CTR training over a ShardedEmbeddingTable
(ISSUE 20): the training half of the traffic -> trainer ->
checkpoint -> `FleetRouter.rollout()` loop, plus the
commit-acknowledged ledger that makes SIGKILL elasticity exact.

The model is the smallest honest CTR learner: logistic regression
whose per-feature weight is column 0 of the feature id's embedding
row. `click logit = sum_j table[id_j][0]`. Traffic is deterministic
(splitmix64 streams keyed by seed + batch index), so every
incarnation of a killed worker regenerates byte-identical batches —
what makes "zero batches lost or retrained" a checkable ledger
property instead of a vibe.

The ledger contract (the elastic robustness core):

- A batch b counts as TRAINED only when the sharded-table generation
  recording the state AFTER b has durably committed (manifest +
  every shard sha256-verified on disk). `poll_acks()` surfaces
  commits in order; the worker appends `{"trained": b}` to its
  ledger only then.
- Generations are written asynchronously (AsyncCheckpointer
  .save_table), so at SIGKILL some batches are computed but
  unacknowledged. The respawned rank recovers via `resume()` —
  quarantine-and-rebuild to the last good generation — and re-runs
  exactly the unacknowledged suffix. Re-running unacknowledged work
  is not retraining, the same way the fleet's re-routed
  un-acknowledged request is not a lost request.
- A commit can land without its ledger line (killed between fsync
  and append). `reconcile()` closes that window: acked-but-unlogged
  batches are derived from the recovered generation's meta and
  acknowledged as `reconciled` — from the durable manifest, never
  from re-execution.

Together: across any number of SIGKILLs, the union of ledger lines
is every batch EXACTLY once. tests/test_sparse_shard_elastic.py
kills mid-epoch and asserts batches_lost == batches_retrained == 0;
bench_multichip's `ctr_bigvocab` row measures the same protocol.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from paddle_tpu.parallel.sparse_shard import (
    ShardedEmbeddingTable, _mix64,
)
from paddle_tpu.trainer import async_checkpoint as _ac


def _unit(x) -> np.ndarray:
    """uint64 hash stream -> f64 uniform in [0, 1)."""
    return (_mix64(x) >> np.uint64(11)).astype(np.float64) * 2.0**-53


def true_weight(ids, scale: float = 0.9) -> np.ndarray:
    """The ground-truth per-id CTR weight the trainer must recover:
    deterministic +-scale keyed on the id hash."""
    ids = np.asarray(ids, np.uint64)
    sign = (_mix64(ids) & np.uint64(1)).astype(np.float64) * 2.0 - 1.0
    return sign * scale


def make_batch(seed: int, batch_index: int, batch_size: int,
               feats: int, hot_ids: np.ndarray) -> tuple:
    """Deterministic CTR batch `batch_index`: ids drawn from the hot
    set, labels Bernoulli(sigmoid(sum of true weights)) with a
    deterministic uniform draw. Same (seed, index) -> same batch on
    every incarnation."""
    hot_ids = np.asarray(hot_ids, np.int64)
    base = (np.uint64(seed) * np.uint64(0x51ED2701)
            + np.uint64(batch_index) * np.uint64(batch_size * feats + 1))
    draw = _mix64(base + np.arange(batch_size * feats, dtype=np.uint64))
    ids = hot_ids[(draw % np.uint64(len(hot_ids))).astype(np.int64)]
    ids = ids.reshape(batch_size, feats)
    logits = true_weight(ids).sum(axis=1)
    u = _unit(base + np.uint64(0xC0FFEE)
              + np.arange(batch_size, dtype=np.uint64))
    labels = (u < 1.0 / (1.0 + np.exp(-logits))).astype(np.float64)
    return ids, labels


def hot_id_set(seed: int, count: int, rows_total: int) -> np.ndarray:
    """The traffic's hot vocabulary: `count` distinct ids scattered
    across the FULL [0, rows_total) space (deterministic), so a
    100M–1B-row table is exercised end to end while only the hot set
    ever materializes."""
    draw = _mix64(np.uint64(seed) * np.uint64(0xABCD1234)
                  + np.arange(count * 2, dtype=np.uint64))
    ids = np.unique((draw % np.uint64(rows_total)).astype(np.int64))
    return ids[:count]


def predict_logits(table: ShardedEmbeddingTable, ids) -> np.ndarray:
    """[B, F] ids -> [B] click logits (column 0 of each row)."""
    emb = np.asarray(table.lookup(ids))
    return emb[..., 0].sum(axis=-1)


def weights_from_payloads(payloads) -> dict:
    """Flatten exported shard payloads (resident + spill) into the
    {feature id -> weight} map a serving replica scores with — the
    hot-swap artifact `FleetRouter.rollout()` points replicas at."""
    w = {}
    for p in payloads:
        for key_ids, key_rows in (("ids", "rows"),
                                  ("spill_ids", "spill_rows")):
            ids = np.asarray(p[key_ids]).tolist()
            rows = np.asarray(p[key_rows])
            for j, i in enumerate(ids):
                w[int(i)] = float(rows[j, 0])
    return w


def logloss(p: np.ndarray, y: np.ndarray) -> float:
    p = np.clip(np.asarray(p, np.float64), 1e-7, 1.0 - 1e-7)
    y = np.asarray(y, np.float64)
    return float(-(y * np.log(p) + (1 - y) * np.log(1 - p)).mean())


class OnlineCTRTrainer:
    """Glue: ShardedEmbeddingTable + async table generations + the
    commit-acknowledged ledger. Drives both the subprocess elastic
    worker (testing_faults.SHARDED_CTR_TRAINER_SRC) and the in-test
    online-learning loop."""

    def __init__(self, table: ShardedEmbeddingTable, save_dir: str,
                 checkpointer: _ac.AsyncCheckpointer = None):
        self.table = table
        self.save_dir = save_dir
        self.ckpt = checkpointer or _ac.AsyncCheckpointer(
            save_dir, queue_depth=4
        )
        self._pending = deque()  # (generation, meta) awaiting commit

    # ---- training ----
    def train_step(self, ids, labels) -> float:
        """One logistic SGD step on [B, F] ids / [B] labels; returns
        the pre-update logloss. d(loss)/d(logit) = p - y lands on
        column 0 of every occurrence's row; the table's update_fn
        owns the learning rate."""
        ids = np.asarray(ids, np.int64)
        labels = np.asarray(labels, np.float64)
        logits = predict_logits(self.table, ids)
        p = 1.0 / (1.0 + np.exp(-logits))
        g = ((p - labels) / len(labels)).astype(np.float32)
        grads = np.zeros(
            (ids.size, self.table.config.dim), np.float32
        )
        grads[:, 0] = np.repeat(g, ids.shape[1])
        self.table.update(ids.reshape(-1), grads)
        return logloss(p, labels)

    # ---- generations + ledger ----
    def save_generation(self, generation: int, next_batch: int,
                        extra_meta: dict = None) -> None:
        """Enqueue the async write of the state-after-batch
        `next_batch - 1` generation and remember it as pending (to be
        acknowledged only once committed)."""
        meta = {"next_batch": int(next_batch),
                **self.table.table_meta(), **(extra_meta or {})}
        self.ckpt.save_table(generation,
                             self.table.export_shards(), meta=meta)
        self._pending.append((generation, meta))

    def poll_acks(self) -> list:
        """Generations (in order) that have durably committed since
        the last poll — the moment their batches become TRAINED in
        the ledger. Non-blocking: in-flight writes stay pending."""
        out = []
        while self._pending:
            gen, meta = self._pending[0]
            ok, _ = _ac.verify_table_generation(self.save_dir, gen)
            if not ok:
                break
            self._pending.popleft()
            out.append((gen, meta))
        return out

    def drain(self) -> list:
        """Block until every enqueued generation committed (surface
        writer errors), then ack them all."""
        self.ckpt.wait()
        return self.poll_acks()

    def resume(self) -> tuple:
        """Quarantine-and-rebuild recovery: torn generations newer
        than the last good one are moved aside (reason names the
        shard), the table is restored from the last good generation.
        Returns (generation, meta, quarantined) — generation -1 on a
        cold start (fresh table untouched)."""
        gen, payloads, meta, quarantined = _ac.recover_table(
            self.save_dir
        )
        if gen >= 0:
            if int(meta.get("num_shards",
                            self.table.num_shards)) != \
                    self.table.num_shards:
                raise ValueError(
                    f"generation has {meta.get('num_shards')} table "
                    f"shards; this mesh has {self.table.num_shards}"
                )
            self.table.restore_shards(payloads)
        return gen, meta, quarantined

    def close(self):
        self.ckpt.close()
