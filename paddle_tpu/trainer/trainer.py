"""SGD trainer — the event-driven training loop.

Reference: python/paddle/v2/trainer.py:24,110,145-176 (SGD.train with
event_handler), driving the same semantics as the C++ Trainer pass/batch
loop (trainer/Trainer.cpp:261,492; TrainerInternal::trainOneBatch
TrainerInternal.cpp:66). One jit-compiled TrainStep replaces
forwardBackward + updater; the whole mesh runs it SPMD.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core import flags as _flags
from paddle_tpu.core import rng as _rng
from paddle_tpu.core.config import ModelConf, OptimizationConf
from paddle_tpu.core.stat import GLOBAL_STATS
from paddle_tpu.obs import metrics as _obs
from paddle_tpu.obs import tracing as _tracing
from paddle_tpu.obs.timeline import StepTimeline
from paddle_tpu.evaluators import create_evaluator
from paddle_tpu.network import Network
from paddle_tpu.optimizers import create_optimizer
from paddle_tpu.parallel.dp import TrainStep
from paddle_tpu.trainer import async_checkpoint as actp
from paddle_tpu.trainer import checkpoint as ckpt
from paddle_tpu.trainer import watchdog as wdg
from paddle_tpu.trainer.events import (
    BeginIteration,
    BeginPass,
    EndIteration,
    EndPass,
    TestResult,
)

log = logging.getLogger("paddle_tpu.trainer")

_BASE_PRNG_IMPL = None  # captured at first SGD init (process default)


class _NullPreemptionGuard:
    """Stand-in when there is no save_dir to flush to: SIGTERM keeps
    its process-default meaning."""

    preempted = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def _emit_step_spans(trace_id, trace_parent, tl, pass_id, batch_id,
                     global_step, t_data, t_rs):
    """Span tree for one SAMPLED training step (ISSUE 11): a
    `train.step` root over `train.data_wait` / `train.host_dispatch` /
    `train.device_step` children, stamped from the exact perf_counter
    boundaries the StepTimeline just accumulated. The loop uses
    perf_counter; spans want wall starts — convert via the current
    perf->wall offset (both clocks are process-local)."""
    now_pc = time.perf_counter()
    now_wall = time.time()

    def wall(t_pc):
        return now_wall - (now_pc - t_pc)

    root = _tracing.new_span_id()
    _tracing.emit_span(
        "train.step", trace_id, root, trace_parent,
        dur_s=now_pc - t_data, ts=wall(t_data),
        labels={"pass_id": pass_id, "batch_id": batch_id,
                "global_step": global_step, "sampled": True},
    )
    _tracing.emit_span(
        "train.data_wait", trace_id, _tracing.new_span_id(), root,
        dur_s=tl.last["data_wait"], ts=wall(t_data),
    )
    _tracing.emit_span(
        "train.host_dispatch", trace_id, _tracing.new_span_id(), root,
        dur_s=tl.last["host_dispatch"], ts=wall(t_rs),
    )
    _tracing.emit_span(
        "train.device_step", trace_id, _tracing.new_span_id(), root,
        dur_s=tl.last["device_step"],
        ts=wall(t_rs + tl.last["host_dispatch"]),
    )


class SGD:
    """Usage (mirrors paddle.v2.trainer.SGD):

        trainer = SGD(model_conf, opt_conf, mesh=mesh, evaluators=[...])
        trainer.train(reader=batched_reader, feeder=feeder,
                      num_passes=10, event_handler=handler)
    """

    def __init__(
        self,
        model_conf: ModelConf,
        opt_conf: OptimizationConf,
        mesh=None,
        evaluators: Optional[list] = None,
        seed: int = 0,
        params: Optional[dict] = None,
        watchdog=None,
        steps_per_dispatch: Optional[int] = None,
    ):
        """`watchdog`: None = follow the `watchdog` flag (default on);
        False disables; True or a `wdg.WatchdogConfig` enables with
        the given knobs. Enabled, the train step skips non-finite
        updates on device and `train` runs the escalation ladder
        (skip -> LR backoff -> rollback -> abort) plus SIGTERM-safe
        preemption (trainer/watchdog.py).

        `steps_per_dispatch`: None = the flag (default 1). N > 1 runs
        N consecutive batches as ONE jitted scan-of-steps dispatch
        (ROADMAP 5d: the bench trick promoted to a trainer option) —
        short-step models amortize the per-program dispatch floor
        N-fold while walking the bit-identical training trajectory
        (per-step RNG is derived inside the scan exactly as the
        sequential loop derives it). Events, evaluators and the
        watchdog still observe every batch; the differences are
        chunk-granular: LR backoff takes effect on the NEXT chunk,
        preemption checkpoints at chunk boundaries (un-dispatched
        buffered batches are replayed by the deterministic reader —
        still exactly-once), and per-step span trees are not emitted
        (a scan dispatch has no per-batch host boundary to stamp)."""
        if watchdog is None:
            watchdog = bool(_flags.get_flag("watchdog"))
        if watchdog is True:
            watchdog = wdg.WatchdogConfig()
        self.watchdog_conf = watchdog or None
        if steps_per_dispatch is None:
            steps_per_dispatch = int(
                _flags.get_flag("steps_per_dispatch") or 1
            )
        if steps_per_dispatch < 1:
            raise ValueError(
                f"steps_per_dispatch must be >= 1, got "
                f"{steps_per_dispatch}"
            )
        self.steps_per_dispatch = steps_per_dispatch
        self.last_watchdog_report: Optional[wdg.WatchdogReport] = None
        self._resume_skip_batches = 0
        self.net = Network(model_conf)
        self.opt_conf = opt_conf
        self.opt = create_optimizer(opt_conf, self.net.param_confs)
        self.mesh = mesh
        self.evaluator_confs = evaluators or []
        # FP-exception trap (TrainerMain.cpp:49 feenableexcept): jax
        # re-runs NaN-producing ops un-jitted and raises. Set from the
        # flag unconditionally so a previous trainer's setting does not
        # leak into this one.
        jax.config.update(
            "jax_debug_nans", bool(_flags.get_flag("trap_fp"))
        )
        # always sync (like trap_fp above): flag None restores whatever
        # impl the PROCESS started with (env/JAX config), not a
        # hardcoded default — so flag-less trainers never clobber a
        # user's JAX_DEFAULT_PRNG_IMPL choice
        global _BASE_PRNG_IMPL
        if _BASE_PRNG_IMPL is None:
            _BASE_PRNG_IMPL = jax.config.jax_default_prng_impl
        jax.config.update(
            "jax_default_prng_impl",
            _flags.get_flag("prng_impl") or _BASE_PRNG_IMPL,
        )
        key = _rng.root_key(seed or _flags.get_flag("seed"))
        init_key, self.step_key = jax.random.split(key)
        self.params = params if params is not None else self.net.init_params(init_key)
        self.state = self.net.init_state()
        self.opt_state = self.opt.init_state(self.params)
        eval_layers = {
            c[k]
            for c in self.evaluator_confs
            for k in ("input", "label", "query_id")
            if k in c
        }
        self.step_fn = TrainStep(
            self.net, self.opt, mesh=mesh, keep_outputs=eval_layers,
            watchdog=self.watchdog_conf is not None,
        )
        self.params, self.opt_state, self.state = self.step_fn.place(
            self.params, self.opt_state, self.state
        )
        self.global_step = 0

    # ---- eval-only forward (jitted separately, no grad) ----
    def _eval_forward(self, feed):
        if not hasattr(self, "_fwd"):
            from paddle_tpu.analysis.recompile_guard import (
                RecompileGuard,
            )

            eval_guard = self._eval_guard = RecompileGuard(
                "eval_forward"
            )
            keep = (
                set(self.net.output_names)
                | set(self.net.cost_names)
                | {
                    c[k]
                    for c in self.evaluator_confs
                    for k in ("input", "label", "query_id")
                    if k in c
                }
            )

            def fwd(params, state, feed):
                eval_guard.note(params, feed)
                outs, _ = self.net.forward(
                    params, feed, state=state, train=False
                )
                costs = [outs[n].value for n in self.net.cost_names]
                return {k: v for k, v in outs.items() if k in keep}, costs

            self._fwd = jax.jit(fwd)
        return self._fwd(self.params, self.state, feed)

    def _make_evaluators(self):
        return [create_evaluator(c) for c in self.evaluator_confs]

    def _log_parameter_stats(self, pass_id: int, batch_id: int) -> None:
        """Per-parameter value statistics every
        --show_parameter_stats_period batches (the reference's
        TrainerInternal.cpp:80-90 avg/max-abs dump; grads are
        step-internal here, so value stats are the observable)."""
        for name in sorted(self.params):
            v = self.params[name]
            log.info(
                "param stats pass %d batch %d %s: shape=%s "
                "avg_abs=%.6f max_abs=%.6f",
                pass_id, batch_id, name, tuple(v.shape),
                float(jnp.mean(jnp.abs(v))),
                float(jnp.max(jnp.abs(v))),
            )

    def train_batch(self, feed) -> float:
        """Run ONE jitted train step on an already-fed Arg dict and
        return the cost — the TrainerInternal::trainOneBatch unit
        (TrainerInternal.cpp:66), used by the --job=time harness."""
        cost, _finite, _outs = self.run_step(feed)
        return cost

    def run_step(self, feed, lr_scale: float = 1.0,
                 timeline=None) -> tuple:
        """One step on an already-fed Arg dict; returns
        (cost, finite, outs). The public stepping unit for external
        loops (paddle.v2's trainer drives this). In watchdog mode the
        step returns the 2-float health vector [loss, all_finite] —
        ONE device->host fetch carries both, so the finiteness verdict
        costs no extra transfer over the loss fetch the loop always
        made — and a non-finite batch's update was already skipped on
        device.

        `timeline`: an obs.StepTimeline splitting this step's wall
        time into host-dispatch (submitting the jitted program) vs
        device-step (blocked on results). On the timeline's sampled
        steps the params are fenced with block_until_ready so the
        update tail is measured too; every other step stays async
        beyond the loss fetch."""
        rng = _rng.split_for_step(self.step_key, self.global_step)
        t0 = time.perf_counter() if timeline is not None else 0.0
        (
            self.params,
            self.opt_state,
            self.state,
            loss,
            outs,
        ) = self.step_fn(
            self.params, self.opt_state, self.state, feed,
            self.global_step, rng, lr_scale=lr_scale,
        )
        self.global_step += 1
        if timeline is None:
            if self.step_fn.watchdog:
                health = np.asarray(loss)  # the single host fetch
                return float(health[0]), bool(health[1]), outs
            return float(loss), True, outs
        t1 = time.perf_counter()
        timeline.add_dispatch(t1 - t0)
        if self.step_fn.watchdog:
            health = np.asarray(loss)
            result = float(health[0]), bool(health[1]), outs
        else:
            result = float(loss), True, outs
        if timeline.fence_now(self.global_step):
            jax.block_until_ready(self.params)
        timeline.add_device(time.perf_counter() - t1)
        timeline.step_done()
        return result

    def run_steps(self, feeds, lr_scale: float = 1.0,
                  timeline=None) -> tuple:
        """Run len(feeds) consecutive steps in ONE jitted dispatch
        (lax.scan over the train step — multi-step pipelining,
        ROADMAP 5d). Returns (costs, finites, outs): per-batch cost
        and finiteness lists in step order (one [n]-row device->host
        fetch carries all of them), and the kept outputs with leaves
        stacked [n, ...] (slice leaf[i] for batch i's evaluator view).
        The per-step RNG/optimizer trajectory is identical to calling
        run_step n times. All feeds in one call must share one shape
        signature (they compile per distinct stacked shape)."""
        n = len(feeds)
        stacked = jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *feeds
        )
        t0 = time.perf_counter() if timeline is not None else 0.0
        (
            self.params,
            self.opt_state,
            self.state,
            losses,
            outs,
        ) = self.step_fn.multi(
            self.params, self.opt_state, self.state, stacked,
            self.global_step, self.step_key, lr_scale=lr_scale,
        )
        self.global_step += n
        t1 = time.perf_counter()
        if timeline is not None:
            timeline.add_dispatch(t1 - t0)
        health = np.asarray(losses)  # the single host fetch
        if self.step_fn.watchdog:
            costs = [float(h) for h in health[:, 0]]
            finites = [bool(h) for h in health[:, 1]]
        else:
            costs = [float(h) for h in health]
            finites = [True] * n
        if timeline is not None:
            if timeline.fence_now(self.global_step):
                jax.block_until_ready(self.params)
            timeline.add_device(time.perf_counter() - t1)
            for _ in range(n):
                timeline.step_done()
        return costs, finites, outs

    def train(
        self,
        reader: Callable,
        feeder: Callable,
        num_passes: int = 1,
        event_handler: Optional[Callable] = None,
        test_reader: Optional[Callable] = None,
        save_dir: Optional[str] = None,
        start_pass: int = 0,
        checkpoint_mode: Optional[str] = None,
        skip_batches: Optional[int] = None,
    ):
        """reader yields raw batches (lists of sample tuples); feeder
        converts them to Arg dicts.

        checkpoint_mode: None = the `checkpoint_mode` flag; "sync" =
        blocking per-pass save_pass; "async" = overlapped sharded
        writes (trainer/async_checkpoint.py) where only the
        device->host snapshot blocks the loop.

        skip_batches: batches of `start_pass` to skip before training
        resumes — the mid-pass preemption-resume offset. None = use the
        offset the last `resume()` recorded from a mid-pass checkpoint
        (0 when the checkpoint was an ordinary end-of-pass save)."""
        event_handler = event_handler or (lambda e: None)
        log_period = _flags.get_flag("log_period")
        ckpt_mode = checkpoint_mode or _flags.get_flag("checkpoint_mode")
        if ckpt_mode not in ("sync", "async"):
            raise ValueError(f"unknown checkpoint_mode {ckpt_mode!r}")
        if save_dir and ckpt_mode == "async":
            self._ensure_async_ckpt(save_dir)
        if skip_batches is None:
            skip_batches = self._resume_skip_batches
        self._resume_skip_batches = 0
        wd = (
            wdg.Watchdog(self.watchdog_conf)
            if self.watchdog_conf is not None else None
        )
        if wd is not None:
            self.last_watchdog_report = wd.report
        # per-step wall-time attribution (ISSUE 10): data-wait vs
        # host-dispatch vs device-step vs checkpoint-stall, fenced
        # every `timeline_sample_period` steps. Exposed for bench
        # drivers as `last_timeline`; totals feed the `trainer.*`
        # registry counters and one `timeline` event per pass.
        tl = StepTimeline(
            sample_period=_flags.get_flag("timeline_sample_period")
        )
        self.last_timeline = tl
        # one trace per train() call; sampled steps (the timeline's
        # fence points) each emit a span tree — train.step over
        # data_wait / host_dispatch / device_step — aligned with the
        # very timestamps the timeline accumulated, so the span view
        # and the fraction view can never disagree about a step.
        # Joins the launching process's trace when the carrier env
        # var is set (tracing.CARRIER_ENV), else starts its own.
        with _tracing.attach_from_env():
            cur = _tracing.current()
        trace_id = cur[0] if cur else _tracing.new_trace_id()
        trace_parent = cur[1] if cur else ""
        self.last_trace_id = trace_id
        # SIGTERM -> flag; checked at batch boundaries only, so the
        # in-flight jitted step always completes before the flush.
        # Installed only when there is somewhere to flush to.
        guard = (
            wdg.PreemptionGuard() if save_dir
            else _NullPreemptionGuard()
        )
        ok = False
        try:
          with guard:
            for pass_id in range(start_pass, num_passes):
                event_handler(BeginPass(pass_id))
                evals = self._make_evaluators()
                costs = []
                batch_iter = iter(reader())
                if self.steps_per_dispatch > 1:
                    self._run_pass_pipelined(
                        pass_id, start_pass, skip_batches, batch_iter,
                        feeder, event_handler, evals, costs, tl, wd,
                        guard, save_dir, ckpt_mode, log_period,
                    )
                    batch_iter = None  # drained
                batch_id = -1
                while batch_iter is not None:
                    t_data = time.perf_counter()
                    try:
                        raw = next(batch_iter)
                    except StopIteration:
                        break
                    batch_id += 1
                    if pass_id == start_pass and batch_id < skip_batches:
                        # already trained before the preemption (their
                        # work lives in the flushed checkpoint) — the
                        # deterministic reader replays them, the loop
                        # drops them
                        continue
                    # reader-next + feeder conversion = the input
                    # pipeline's blocking share of this step; the
                    # user's BeginIteration handler is deliberately
                    # outside it (its cost is not the reader's)
                    dt_reader = time.perf_counter() - t_data
                    event_handler(BeginIteration(pass_id, batch_id))
                    t_feed = time.perf_counter()
                    feed = feeder(raw)
                    tl.add_data_wait(
                        dt_reader + time.perf_counter() - t_feed
                    )
                    t_rs = time.perf_counter()
                    with GLOBAL_STATS.timer("train_step"):
                        cost, finite, outs = self.run_step(
                            feed, wd.lr_scale() if wd else 1.0,
                            timeline=tl,
                        )
                    if (tl.sample_period > 0
                            and self.global_step % tl.sample_period
                            == 0):
                        # sampled (fenced) step: the device is quiet
                        # and every segment of this step is measured —
                        # emit its span tree (no-op without a stream
                        # or flight recorder attached)
                        _emit_step_spans(
                            trace_id, trace_parent, tl, pass_id,
                            batch_id, self.global_step - 1, t_data,
                            t_rs,
                        )
                    if finite:
                        costs.append(cost)
                        for ev in evals:
                            ev.add_batch(outs, feed)
                    if wd is not None:
                        self._watchdog_act(
                            wd, cost, finite, save_dir, ckpt_mode,
                        )
                    results = (
                        {ev.name: ev.result() for ev in evals}
                        if (batch_id + 1) % log_period == 0
                        else {}
                    )
                    event_handler(
                        EndIteration(pass_id, batch_id, cost, results)
                    )
                    if (batch_id + 1) % log_period == 0:
                        log.info(
                            "pass %d batch %d cost %.5f %s",
                            pass_id,
                            batch_id,
                            float(np.mean(costs[-log_period:]))
                            if costs else float("nan"),
                            results,
                        )
                    stats_period = _flags.get_flag(
                        "show_parameter_stats_period"
                    )
                    if stats_period and (batch_id + 1) % stats_period == 0:
                        self._log_parameter_stats(pass_id, batch_id)
                    if guard.preempted:
                        # the in-flight batch completed and is counted
                        # in batch_id+1: the flush loses zero
                        # completed-batch work
                        self._preempt_flush(
                            save_dir, ckpt_mode, pass_id, batch_id + 1
                        )
                        raise wdg.Preempted(
                            pass_id, batch_id + 1, save_dir
                        )
                skip_batches = 0
                results = {ev.name: ev.result() for ev in evals}
                if test_reader is not None:
                    tr = self.test(test_reader, feeder)
                    event_handler(
                        TestResult(pass_id, tr["cost"], tr["evaluators"])
                    )
                if save_dir:
                    t_ck = time.perf_counter()
                    with GLOBAL_STATS.timer("checkpoint_save"):
                        if ckpt_mode == "async":
                            # every process commits its own shard; only the
                            # host snapshot inside save() blocks the loop
                            self._async_ckpt.save(
                                pass_id,
                                self.params,
                                self.opt_state,
                                self.state,
                                meta={"global_step": self.global_step},
                            )
                        else:
                            ckpt.save_pass(
                                save_dir,
                                pass_id,
                                jax.device_get(self.params),
                                jax.device_get(self.opt_state),
                                jax.device_get(self.state),
                                meta={"global_step": self.global_step},
                                save_only_one=_flags.get_flag("save_only_one"),
                            )
                    dt_ck = time.perf_counter() - t_ck
                    tl.add_checkpoint(dt_ck)
                    _tracing.emit_span(
                        "train.checkpoint", trace_id,
                        _tracing.new_span_id(), trace_parent,
                        dur_s=dt_ck,
                        labels={"pass_id": pass_id, "mode": ckpt_mode},
                    )
                    if wd is not None:
                        # candidate only: promoted to the rollback
                        # target after `good_batches` healthy batches
                        # (watchdog.py "good checkpoint" rule)
                        wd.on_checkpoint(pass_id)
                # per-pass timer report (the WITH_TIMER StatSet dump,
                # TrainerInternal.cpp:177 area / utils/Stat.h:189) —
                # reset after logging so each pass reports only itself
                log.info("pass %d %s", pass_id, GLOBAL_STATS.report())
                GLOBAL_STATS.reset()
                # one structured timeline record per pass on the
                # event stream (cumulative over this train() call),
                # plus the human-readable fractions in the log
                tl.emit_pass(pass_id, self.global_step)
                log.info("pass %d timeline %s", pass_id,
                         tl.fractions())
                event_handler(EndPass(pass_id, results))
                if pass_id == start_pass:
                    # warmup over: every steady-state shape (incl.
                    # the ragged reader tail) has traced once — arm
                    # the jit-cache-miss tracker (ISSUE 13; the
                    # `recompile_guard` flag: off/record/strict). A
                    # retrace from here on is a silent compile stall
                    # in the hot loop.
                    rg_mode = _flags.get_flag("recompile_guard")
                    if rg_mode and rg_mode != "off":
                        self.step_fn.recompile_guard.arm(
                            strict=(rg_mode == "strict")
                        )
            ok = True
        finally:
            # drain in-flight async writes on EVERY exit path so a
            # background failure surfaces here, with the training
            # stack attached, not in a daemon thread; when already
            # unwinding, a drain failure must not mask the
            # training error
            if save_dir and ckpt_mode == "async":
                if ok:
                    self._async_ckpt.wait()
                else:
                    try:
                        self._async_ckpt.wait()
                    except Exception:
                        log.exception(
                            "async checkpoint drain failed while "
                            "handling a training error"
                        )

    def _run_pass_pipelined(self, pass_id, start_pass, skip_batches,
                            batch_iter, feeder, event_handler, evals,
                            costs, tl, wd, guard, save_dir, ckpt_mode,
                            log_period):
        """One pass with steps_per_dispatch > 1: batches are buffered
        and dispatched as scan-of-steps chunks (run_steps). Per-batch
        semantics preserved: BeginIteration fires when a batch is
        collected (before its step runs), EndIteration/evaluators/
        watchdog observe every batch in order after its chunk lands.
        Chunk-granular differences are documented on __init__. A
        shape-signature change (e.g. a ragged final reader batch)
        flushes the buffer early, so mixed shapes cost one extra
        compile, never an error."""
        spd = self.steps_per_dispatch
        buf = []  # (batch_id, feed)
        sig = None
        done_upto = skip_batches  # batches of this pass fully trained
        stats_period = _flags.get_flag("show_parameter_stats_period")

        def _sig(feed):
            return (
                jax.tree_util.tree_structure(feed),
                tuple(
                    (getattr(x, "shape", None), getattr(x, "dtype", None))
                    for x in jax.tree_util.tree_leaves(feed)
                ),
            )

        def _check_preempt():
            if guard.preempted:
                # buffered batches were never dispatched — drop them;
                # the deterministic reader replays them after resume,
                # so every batch still trains exactly once
                self._preempt_flush(
                    save_dir, ckpt_mode, pass_id, done_upto
                )
                raise wdg.Preempted(pass_id, done_upto, save_dir)

        def flush():
            nonlocal buf, sig, done_upto
            if not buf:
                return
            with GLOBAL_STATS.timer("train_step"):
                cs, fs, outs = self.run_steps(
                    [f for _, f in buf],
                    wd.lr_scale() if wd else 1.0, timeline=tl,
                )
            observe = True
            for j, (bid, feed) in enumerate(buf):
                cost, finite = cs[j], fs[j]
                if finite:
                    costs.append(cost)
                    for ev in evals:
                        ev.add_batch(
                            jax.tree_util.tree_map(
                                lambda x: x[j], outs
                            ),
                            feed,
                        )
                if wd is not None and observe:
                    action = self._watchdog_act(
                        wd, cost, finite, save_dir, ckpt_mode
                    )
                    if action == wdg.ROLLBACK:
                        # the chunk's remaining batches trained on the
                        # now-rolled-back trajectory; their costs are
                        # discarded progress — stop feeding the ladder
                        observe = False
                results = (
                    {ev.name: ev.result() for ev in evals}
                    if (bid + 1) % log_period == 0 else {}
                )
                event_handler(EndIteration(pass_id, bid, cost, results))
                if (bid + 1) % log_period == 0:
                    log.info(
                        "pass %d batch %d cost %.5f %s", pass_id, bid,
                        float(np.mean(costs[-log_period:]))
                        if costs else float("nan"),
                        results,
                    )
                if stats_period and (bid + 1) % stats_period == 0:
                    self._log_parameter_stats(pass_id, bid)
            done_upto = buf[-1][0] + 1
            buf, sig = [], None

        batch_id = -1
        while True:
            _check_preempt()
            t_data = time.perf_counter()
            try:
                raw = next(batch_iter)
            except StopIteration:
                break
            batch_id += 1
            if pass_id == start_pass and batch_id < skip_batches:
                continue
            dt_reader = time.perf_counter() - t_data
            event_handler(BeginIteration(pass_id, batch_id))
            t_feed = time.perf_counter()
            feed = feeder(raw)
            tl.add_data_wait(dt_reader + time.perf_counter() - t_feed)
            fsig = _sig(feed)
            if buf and fsig != sig:
                flush()
            buf.append((batch_id, feed))
            sig = fsig
            if len(buf) >= spd:
                flush()
        flush()
        _check_preempt()

    def _watchdog_act(self, wd, cost, finite, save_dir, ckpt_mode):
        """Run the ladder on one batch's (cost, finite) verdict;
        perform the rollback here (the trainer owns params/resume);
        returns the ladder's action (the pipelined loop stops
        observing a chunk after ROLLBACK)."""
        action = wd.observe(cost, finite, self.global_step - 1)
        if action == wdg.ROLLBACK:
            target = wd.good_pass
            with GLOBAL_STATS.timer("watchdog_rollback"):
                if ckpt_mode == "async" and getattr(
                    self, "_async_ckpt", None
                ) is not None:
                    # commit in-flight writes (and surface write
                    # errors) before reading manifests back
                    self._async_ckpt.wait()
                try:
                    self.resume(save_dir, pass_id=target)
                except (FileNotFoundError, ValueError, OSError) as e:
                    # the promoted pass can be rotated away
                    # (save_only_one / keep_last) before a rollback
                    # needs it: out of rungs — abort with the report,
                    # never a raw load traceback
                    wd.report.aborted = True
                    wd.report.abort_reason = (
                        f"rollback target pass {target} unloadable "
                        f"({type(e).__name__}: {e}) — rotated away?"
                    )
                    wd.record_event("abort", self.global_step,
                                    reason=wd.report.abort_reason)
                    log.error("watchdog abort: %s",
                              wd.report.abort_reason)
                    raise wdg.WatchdogAbort(wd.report) from e
            log.warning(
                "watchdog: rolled back to checkpoint pass %d "
                "(global_step %d)", target, self.global_step,
            )
            wd.on_rollback(target, self.global_step)
        elif action == wdg.ABORT:
            log.error("watchdog abort: %s", wd.report.abort_reason)
            raise wdg.WatchdogAbort(wd.report)
        return action

    def _preempt_flush(self, save_dir, ckpt_mode, pass_id,
                       batches_done):
        """SIGTERM landed: flush a mid-pass checkpoint covering every
        COMPLETED batch, so the respawned process resumes at
        `batches_done` with zero lost work."""
        meta = {
            "global_step": self.global_step,
            "mid_pass": True,
            "batch_in_pass": batches_done,
        }
        with GLOBAL_STATS.timer("preempt_flush"):
            if ckpt_mode == "async":
                self._ensure_async_ckpt(save_dir)
                self._async_ckpt.save(
                    pass_id, self.params, self.opt_state, self.state,
                    meta=meta,
                )
                self._async_ckpt.wait()
            else:
                ckpt.save_pass(
                    save_dir, pass_id,
                    jax.device_get(self.params),
                    jax.device_get(self.opt_state),
                    jax.device_get(self.state),
                    meta=meta,
                )
        _obs.get_registry().counter("trainer.preemptions").inc()
        _obs.get_registry().event(
            "preempt_flush", global_step=self.global_step,
            pass_id=pass_id, batch_in_pass=batches_done,
        )
        log.warning(
            "preempted: flushed pass %d at batch %d to %s; exiting "
            "for resume", pass_id, batches_done, save_dir,
        )

    def recompile_violations(self) -> list:
        """Steady-state retraces recorded by the train step's armed
        recompile guard (the `recompile_guard` flag; ISSUE 13) —
        empty means the hot loop never recompiled after warmup."""
        return list(self.step_fn.recompile_guard.violations)

    def test(self, reader: Callable, feeder: Callable) -> dict:
        """Evaluation pass (reference: trainer/Tester.h)."""
        evals = self._make_evaluators()
        costs = []
        n = 0
        for raw in reader():
            feed = feeder(raw)
            outs, batch_costs = self._eval_forward(feed)
            costs.append(float(np.mean([np.mean(c) for c in batch_costs])))
            for ev in evals:
                ev.add_batch(outs, feed)
            n += 1
        return {
            "cost": float(np.mean(costs)) if costs else float("nan"),
            "evaluators": {ev.name: ev.result() for ev in evals},
        }

    def _ensure_async_ckpt(self, save_dir: str):
        cur = getattr(self, "_async_ckpt", None)
        if cur is not None and cur.save_dir == save_dir:
            return cur
        if cur is not None:
            cur.close()
        self._async_ckpt = actp.AsyncCheckpointer(
            save_dir,
            keep_last=1 if _flags.get_flag("save_only_one") else 0,
        )
        return self._async_ckpt

    def resume(self, save_dir: str, pass_id: int = -1) -> int:
        """Load a checkpoint; returns the next pass id (start_pass
        semantics of trainer/ParamUtil.h). Reads whichever format is
        newest and COMPLETE: async sharded passes (manifest-verified,
        torn shards skipped) or synchronous save_pass directories.

        A MID-PASS checkpoint (the preemption flush) returns its own
        pass id — the pass is unfinished — and records the number of
        already-trained batches; the next `train()` call skips exactly
        that many batches of its first pass, so a SIGTERM/resume cycle
        replays nothing and loses nothing."""
        self._resume_skip_batches = 0
        if pass_id >= 0:
            use_async = (
                pass_id in actp.list_passes(save_dir)
                and actp.verify_pass(save_dir, pass_id)[0]
            )
        else:
            async_latest = actp.latest_complete_pass(save_dir)
            sync_passes = ckpt.list_sync_passes(save_dir)
            use_async = async_latest >= 0 and (
                not sync_passes or async_latest >= sync_passes[-1]
            )
        if use_async:
            # pass the already-resolved id: load_pass(-1) would re-hash
            # every pass a second time to find the latest
            tree, meta = actp.load_pass(
                save_dir, pass_id if pass_id >= 0 else async_latest
            )
            params = tree["params"]
            opt_state = tree.get("opt_state")
            state = tree.get("state")
        else:
            params, opt_state, state, meta = ckpt.load_pass(
                save_dir, pass_id
            )
        self.params = {k: jax.numpy.asarray(v) for k, v in params.items()}
        if opt_state is not None:
            self.opt_state = jax.tree_util.tree_map(
                jax.numpy.asarray, opt_state
            )
        if state is not None:
            self.state = jax.tree_util.tree_map(jax.numpy.asarray, state)
        self.params, self.opt_state, self.state = self.step_fn.place(
            self.params, self.opt_state, self.state
        )
        self.global_step = meta.get("global_step", 0)
        if meta.get("mid_pass"):
            self._resume_skip_batches = int(
                meta.get("batch_in_pass", 0)
            )
            return meta["pass_id"]
        return meta["pass_id"] + 1


class Inferencer:
    """Inference runner (reference: python/paddle/v2/inference.py:9,93 and
    the C-API serving path capi/gradient_machine.h:73): load a merged
    model or pass (net, params), jit the forward, return numpy outputs."""

    def __init__(self, net: Network, params: dict, state=None, outputs=None):
        self.net = net
        self.params = params
        self.state = state or net.init_state()
        self.output_names = outputs or net.output_names

        def fwd(params, state, feed):
            outs, _ = self.net.forward(
                params, feed, state=state, train=False,
                outputs=self.output_names,
            )
            return {n: outs[n] for n in self.output_names}

        self._fwd = jax.jit(fwd)

    @classmethod
    def from_merged(cls, path: str, outputs=None):
        conf, params, state = ckpt.load_merged(path)
        return cls(Network(conf), params, state, outputs)

    def infer(self, feed: dict) -> dict:
        outs = self._fwd(self.params, self.state, feed)
        return {
            n: np.asarray(a.value if a.value is not None else a.ids)
            for n, a in outs.items()
        }
