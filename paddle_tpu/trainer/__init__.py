from paddle_tpu.trainer.events import (  # noqa: F401
    BeginIteration,
    BeginPass,
    EndIteration,
    EndPass,
    TestResult,
)
from paddle_tpu.trainer.watchdog import (  # noqa: F401
    EXIT_PREEMPTED,
    Preempted,
    Watchdog,
    WatchdogAbort,
    WatchdogConfig,
    WatchdogReport,
)

# SGD / AsyncCheckpointer import jax; resolve them lazily so
# `paddle_tpu.trainer.watchdog` stays importable without the device
# runtime (serving front ends, data workers — see obs import lint).
_LAZY = {
    "SGD": "paddle_tpu.trainer.trainer",
    "AsyncCheckpointer": "paddle_tpu.trainer.async_checkpoint",
    "AsyncCheckpointError": "paddle_tpu.trainer.async_checkpoint",
    "OnlineCTRTrainer": "paddle_tpu.trainer.online",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(
        f"module 'paddle_tpu.trainer' has no attribute {name!r}"
    )
