from paddle_tpu.trainer.events import (  # noqa: F401
    BeginIteration,
    BeginPass,
    EndIteration,
    EndPass,
    TestResult,
)
from paddle_tpu.trainer.async_checkpoint import (  # noqa: F401
    AsyncCheckpointer,
    AsyncCheckpointError,
)
from paddle_tpu.trainer.watchdog import (  # noqa: F401
    EXIT_PREEMPTED,
    Preempted,
    Watchdog,
    WatchdogAbort,
    WatchdogConfig,
    WatchdogReport,
)
from paddle_tpu.trainer.trainer import SGD  # noqa: F401
