"""Training events (reference: python/paddle/v2/event.py)."""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class BeginPass:
    pass_id: int


@dataclass
class EndPass:
    pass_id: int
    evaluator_results: dict = field(default_factory=dict)


@dataclass
class BeginIteration:
    pass_id: int
    batch_id: int


@dataclass
class EndIteration:
    pass_id: int
    batch_id: int
    cost: float
    evaluator_results: dict = field(default_factory=dict)


@dataclass
class TestResult:
    pass_id: int
    cost: float
    evaluator_results: dict = field(default_factory=dict)
