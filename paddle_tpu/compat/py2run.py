"""Execute an UNMODIFIED python-2-era reference script under python 3.

Reference drivers (v1_api_demo/quick_start/api_train.py,
gan/gan_trainer.py, vae/vae_train.py, ...) are python 2: print
statements, xrange, cPickle. The file on disk is never touched — the
source is mechanically converted at load time (lib2to3 fixers) exactly
like the config path injects xrange (compat/config_parser.py:566), then
exec'd with __name__ == '__main__'.
"""

from __future__ import annotations

import os
import sys
import warnings

# NOTE: no "xrange" fixer — xrange is injected into the exec globals
# instead (run_py2_script below), so callers can substitute a bounded
# range to keep demo training loops test-sized without editing the file.
_FIXES = [
    "print", "except", "imports", "has_key", "dict", "raise",
    "ne", "numliterals", "funcattrs", "itertools", "itertools_imports",
    "reduce", "basestring", "unicode", "zip", "map", "filter",
    "next",  # generator.next() -> next(generator)
]


def to_py3(src: str, name: str = "<py2 script>", force: bool = False) -> str:
    """Mechanical py2 -> py3 source conversion (no-op if already py3,
    unless `force` — a py2 file can be VALID py3 syntax with different
    semantics, e.g. `len(filter(...))` relying on filter returning a
    list; force runs the fixers regardless)."""
    if not force:
        try:
            compile(src, name, "exec")
            return src
        except SyntaxError:
            pass
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # lib2to3 deprecation
        from lib2to3.refactor import RefactoringTool

        rt = RefactoringTool([f"lib2to3.fixes.fix_{f}" for f in _FIXES])
        if not src.endswith("\n"):
            src += "\n"
        return str(rt.refactor_string(src, name))


def load_py2_module(path: str, name: str, extra_globals=None,
                    force: bool = False):
    """Import a python-2-era helper module (e.g. the mnist demo's
    mnist_util.py) with the same mechanical conversion + xrange
    injection, registering it in sys.modules so the driver script's
    own `import` resolves to it. `force` runs the fixers even when the
    source is syntactically valid py3 (generator.next() etc.)."""
    import types

    with open(path) as f:
        src = to_py3(f.read(), path, force=force)
    mod = types.ModuleType(name)
    mod.__file__ = os.path.abspath(path)
    mod.__dict__["xrange"] = range
    if extra_globals:
        mod.__dict__.update(extra_globals)
    exec(compile(src, path, "exec"), mod.__dict__)
    sys.modules[name] = mod
    return mod


def run_py2_script(path: str, argv=(), extra_globals=None, run_name="__main__"):
    """Exec the script at `path` as __main__ with sys.argv set.

    Returns the script's global namespace (so tests can call into it)."""
    with open(path) as f:
        src = to_py3(f.read(), path)
    code = compile(src, path, "exec")
    g = {
        "__name__": run_name,
        "__file__": os.path.abspath(path),
        "xrange": range,
    }
    if extra_globals:
        g.update(extra_globals)
    old_argv = sys.argv
    old_path = list(sys.path)
    sys.argv = [path] + list(argv)
    sys.path.insert(0, os.path.dirname(os.path.abspath(path)))
    try:
        exec(code, g)
    finally:
        sys.argv = old_argv
        sys.path[:] = old_path
    return g
