"""trainer_config_helpers-style layer functions.

Reference: python/paddle/trainer_config_helpers/layers.py — `*_layer`
functions taking `input=` keyword (single ref or list) plus attrs like
`act=SomeActivation()`, `param_attr=ParamAttr(...)`. This module maps
that surface onto paddle_tpu.dsl so v1-era config scripts run with
minimal edits:

    from paddle_tpu.compat.layers_v1 import *
    with model_scope() as m:
        img = data_layer(name="pixel", size=784)
        hidden = fc_layer(input=img, size=128, act=ReluActivation())
        out = fc_layer(input=hidden, size=10, act=SoftmaxActivation())
        cost = classification_cost(
            input=out, label=data_layer(name="label", size=10)
        )

Activation/ParamAttr objects mirror the reference's
trainer_config_helpers.activations/attrs classes.
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import ParameterConf

model_scope = dsl.model

__all__ = [
    "model_scope",
    "ParamAttr",
    "data_layer",
    "fc_layer",
    "embedding_layer",
    "addto_layer",
    "concat_layer",
    "dropout_layer",
    "img_conv_layer",
    "img_pool_layer",
    "img_cmrnorm_layer",
    "batch_norm_layer",
    "maxout_layer",
    "spp_layer",
    "block_expand_layer",
    "recurrent_layer",
    "lstmemory",
    "grumemory",
    "pooling_layer",
    "last_seq",
    "first_seq",
    "expand_layer",
    "seq_concat_layer",
    "seq_reshape_layer",
    "sub_seq_layer",
    "mixed_layer",
    "dotmul_operator",
    "multi_binary_label_cross_entropy",
    "full_matrix_projection",
    "trans_full_matrix_projection",
    "identity_projection",
    "slice_projection",
    "dotmul_projection",
    "scaling_projection",
    "table_projection",
    "context_projection",
    "tensor_layer",
    "cos_sim",
    "scaling_layer",
    "slope_intercept_layer",
    "interpolation_layer",
    "linear_comb_layer",
    "power_layer",
    "clip_layer",
    "row_conv_layer",
    "conv_shift_layer",
    "bilinear_interp_layer",
    "selective_fc_layer",
    "maxid_layer",
    "sampling_id_layer",
    "multiplex_layer",
    "nce_layer",
    "hsigmoid",
    "crf_layer",
    "crf_decoding_layer",
    "ctc_layer",
    "eos_layer",
    "priorbox_layer",
    "multibox_loss_layer",
    "detection_output_layer",
    "classification_cost",
    "cross_entropy",
    "square_error_cost",
    "mse_cost",
    "regression_cost",
    "rank_cost",
    "sum_cost",
    "prelu_layer",
    "gated_unit_layer",
    "repeat_layer",
    "kmax_sequence_score_layer",
    "simple_attention",
    "simple_lstm",
    "simple_gru",
    "simple_gru2",
    "bidirectional_lstm",
    "bidirectional_gru",
    "lstmemory_unit",
    "lstmemory_group",
    "gru_unit",
    "gru_group",
    "img_conv_bn_pool",
    "text_conv_pool",
    "sequence_conv_pool",
    "simple_img_conv_pool",
    "img_conv_group",
    "small_vgg",
    "vgg_16_network",
    "sub_nested_seq_layer",
    "warp_ctc_layer",
    "lambda_cost",
    "huber_cost",
    "cross_entropy_with_selfnorm",
    "smooth_l1_cost",
    "print_layer",
    "printer_layer",
    "pad_layer",
    "crop_layer",
    "trans_layer",
    "rotate_layer",
    "out_prod_layer",
    "row_l2_norm_layer",
    "sum_to_one_norm_layer",
    "conv_operator",
    "conv_projection",
    "AggregateLevel",
    "ExpandLevel",
    "IdentityActivation",
    "get_output_layer",
    "memory",
    "StaticInput",
    "SubsequenceInput",
    "recurrent_group",
    "beam_search",
    "GeneratedInput",
    "BaseGeneratedInput",
    # activations (attrs-style classes)
    "LinearActivation",
    "ReluActivation",
    "SigmoidActivation",
    "SoftmaxActivation",
    "TanhActivation",
    "STanhActivation",
    "BReluActivation",
    "SoftReluActivation",
    "AbsActivation",
    "SquareActivation",
    "ExpActivation",
]


# ---- activations (trainer_config_helpers/activations.py) ----

class _Act:
    name = ""

    def __init__(self):
        pass


def _make_act(cls_name, act_name):
    return type(cls_name, (_Act,), {"name": act_name})


LinearActivation = _make_act("LinearActivation", "")
ReluActivation = _make_act("ReluActivation", "relu")
SigmoidActivation = _make_act("SigmoidActivation", "sigmoid")
SoftmaxActivation = _make_act("SoftmaxActivation", "softmax")
TanhActivation = _make_act("TanhActivation", "tanh")
STanhActivation = _make_act("STanhActivation", "stanh")
BReluActivation = _make_act("BReluActivation", "brelu")
SoftReluActivation = _make_act("SoftReluActivation", "softrelu")
AbsActivation = _make_act("AbsActivation", "abs")
SquareActivation = _make_act("SquareActivation", "square")
ExpActivation = _make_act("ExpActivation", "exponential")
IdentityActivation = LinearActivation  # reference alias


class AggregateLevel:
    """(layers.py:253) TO_NO_SEQUENCE aggregates a (nested) sequence to
    one vector; TO_SEQUENCE aggregates each SUB-sequence to one
    timestep. String values match the reference proto ('non-seq' /
    'seq'); legacy aliases kept."""

    TO_NO_SEQUENCE = "non-seq"
    TO_SEQUENCE = "seq"
    EACH_TIMESTEP = TO_NO_SEQUENCE
    EACH_SEQUENCE = TO_SEQUENCE


class ExpandLevel:
    """(layers.py:1709)."""

    FROM_NO_SEQUENCE = AggregateLevel.TO_NO_SEQUENCE
    FROM_SEQUENCE = AggregateLevel.TO_SEQUENCE
    FROM_TIMESTEP = FROM_NO_SEQUENCE


def _agg_to_level(agg_level):
    """Map the v1 AggregateLevel to the internal level attr: TO_SEQUENCE
    ('seq') acts per SUB-sequence -> internal 'subseq'."""
    return "subseq" if agg_level == AggregateLevel.TO_SEQUENCE else "seq"


def _act(a) -> str:
    if a is None:
        return ""
    if isinstance(a, str):
        return a
    return a.name


def _act_or(a, default: str) -> str:
    """Default only when act was OMITTED: an explicit
    LinearActivation() (name "") must stay linear — the standard
    pre-batch-norm pattern depends on it."""
    return default if a is None else _act(a)


def ParamAttr(name=None, initial_std=None, initial_mean=0.0,
              learning_rate=1.0, l1_rate=None, l2_rate=None,
              is_static=False, sparse_update=False, initializer=None,
              **_):
    """(trainer_config_helpers/attrs.py ParamAttr; `initializer` is the
    v2 extension — a name -> ndarray callback, v2/attr.py)."""
    return ParameterConf(
        name=name or "",
        initial_std=initial_std,
        initial_mean=initial_mean,
        learning_rate=learning_rate,
        decay_rate_l1=l1_rate,
        decay_rate=l2_rate,
        is_static=is_static,
        sparse_update=sparse_update,
        initializer=initializer,
    )


def _one(input):
    assert not isinstance(input, (list, tuple)), (
        "this layer takes a single input"
    )
    return input


def _many(input):
    return list(input) if isinstance(input, (list, tuple)) else [input]


def _layer_size(ref):
    return ref.builder.conf.layer(ref.name).size


def _pool_type(obj, default="max"):
    """Map a v1 pooling-type object/string to a pool kind."""
    if obj is None:
        return default
    pn = getattr(obj, "name", str(obj)).lower()
    for cand, mapped in (
        ("sqrt", "sqrt_average"),
        ("avg", "avg"),
        ("max", "max"),
        ("sum", "sum"),
    ):
        if cand in pn:
            return mapped
    return default


# ---- layers ----

def data_layer(name, size, height=None, width=None, depth=None,
               is_ids=False, is_seq=False, has_subseq=False, **_):
    """v1 data_layer; `is_ids`/`is_seq` are compat extensions (in v1 the
    slot type came from the data provider declaration, which this
    framework expresses on the data layer itself)."""
    if height and width:
        dim = (height, width, (depth or size // (height * width)))
    else:
        dim = size
    return dsl.data(name, dim, is_seq=is_seq, is_ids=is_ids,
                    has_subseq=has_subseq)



def _bias(bias_attr):
    """bias_attr may be bool or a ParamAttr/ParameterConf carrying a
    shared name + initializer (the VAE config names every bias so
    copy_shared_parameters can match them across machines)."""
    if isinstance(bias_attr, ParameterConf):
        return True, bias_attr
    return bool(bias_attr), None


def fc_layer(input, size, act=None, name=None, bias_attr=True,
             param_attr=None, layer_attr=None, **_):
    # reference default activation for fc is tanh (layers.py:949
    # wrap_act_default); an explicit LinearActivation() stays linear
    b, bp = _bias(bias_attr)
    ins = _many(input)
    if isinstance(param_attr, (list, tuple)):
        # per-input param attrs (layers.py fc_layer accepts one per
        # edge; shared_lstm shares one softmax_param across both) —
        # attach each to its edge directly
        from paddle_tpu.core.config import InputConf

        assert len(param_attr) == len(ins), (
            f"fc_layer: {len(ins)} inputs but {len(param_attr)} "
            "param_attr entries (the reference requires one per edge)"
        )
        ins = [InputConf(name=x.name, parameter=p)
               for x, p in zip(ins, param_attr)]
        param_attr = None
    out = dsl.fc(*ins, size=size, name=name,
                 act=_act_or(act, "tanh"),
                 bias=b, bias_param=bp, param=param_attr)
    return _apply_layer_attr(out, layer_attr)


def _apply_layer_attr(out, layer_attr):
    """ExtraLayerAttribute(drop_rate=...) applies dropout on the layer
    output (config_parser's drop_rate semantics)."""
    rate = getattr(layer_attr, "drop_rate", None)
    if rate:
        return dsl.dropout(out, rate)
    return out


def embedding_layer(input, size, name=None, param_attr=None, **kw):
    # v1 derives the vocabulary from the input layer's size — never
    # guess a default (a too-small table silently corrupts training)
    x = _one(input)
    vocab = kw.get("vocab_size") or kw.get("dict_size")
    if not vocab:
        vocab = x.builder.conf.layer(x.name).size
    assert vocab, "embedding_layer: set the word data_layer's size"
    # in v1 the slot type (ids, sequence) came from the data-provider
    # declaration, not the config; a data layer fed into an embedding is
    # an id sequence, so annotate it retroactively (the provider's
    # input_types, when available, refine this via apply_data_types)
    lc = x.builder.conf.layer(x.name)
    if lc.type == "data" and not lc.attrs.get("is_ids"):
        lc.attrs["is_ids"] = True
        lc.attrs["is_seq"] = True
    return dsl.embedding(x, size=size, vocab_size=vocab,
                         name=name, param=param_attr)


def addto_layer(input, act=None, name=None, bias_attr=False, **_):
    return dsl.addto(*_many(input), name=name, act=_act(act),
                     bias=bool(bias_attr))


def concat_layer(input, act=None, bias_attr=False, name=None, **_):
    # v1 concat also accepts PROJECTIONS as inputs (layers.py
    # concat_layer); materialize each as a one-term sizeless mixed
    ins = [
        dsl.mixed(0, [x], bias=False) if isinstance(x, tuple) else x
        for x in _edges(input)
    ]
    return dsl.concat(*ins, name=name, act=_act(act),
                      bias=bool(bias_attr))


def dropout_layer(input, dropout_rate, name=None, **_):
    return dsl.dropout(_one(input), dropout_rate, name=name)


def img_conv_layer(input, filter_size, num_filters, stride=1, padding=0,
                   groups=1, dilation=1, act=None, name=None,
                   num_channels=None, bias_attr=True, param_attr=None,
                   trans=False, **_):
    if trans:
        # deconvolution (layers.py img_conv_layer trans=True -> exconvt,
        # the GAN generator's upsampling path)
        assert groups == 1 and dilation == 1, (
            "exconvt compat supports groups=1, dilation=1"
        )
        b, bp = _bias(bias_attr)
        out = dsl.conv_trans(_one(input), num_filters, filter_size,
                             stride=stride, padding=padding, name=name,
                             act=_act_or(act, "relu"), bias=b,
                             bias_param=bp, param=param_attr)
        if num_channels:
            lc = out.builder.conf.layer(out.name)
            lc.attrs["num_channels"] = num_channels
        return out
    return dsl.conv(_one(input), num_filters, filter_size, stride=stride,
                    padding=padding, groups=groups, dilation=dilation,
                    name=name, act=_act_or(act, "relu"),
                    num_channels=num_channels,
                    bias=bool(bias_attr), param=param_attr)


def img_pool_layer(input, pool_size, stride=None, padding=0,
                   pool_type=None, name=None, **_):
    return dsl.pool(_one(input), pool_size, stride=stride,
                    padding=padding, pool_type=_pool_type(pool_type),
                    name=name)


def img_cmrnorm_layer(input, size=5, scale=1e-4, power=0.75, name=None,
                      **_):
    return dsl.lrn(_one(input), size=size, scale=scale, power=power,
                   name=name)


def batch_norm_layer(input, act=None, name=None,
                     use_global_stats=False,
                     moving_average_fraction=0.9, **_):
    return dsl.batch_norm(
        _one(input), name=name, act=_act(act),
        use_global_stats=use_global_stats,
        moving_average_fraction=moving_average_fraction,
    )


def maxout_layer(input, groups, name=None, **_):
    return dsl.maxout(_one(input), groups, name=name)


def spp_layer(input, pyramid_height=3, pool_type=None, name=None, **_):
    return dsl.spp(_one(input), pyramid_height=pyramid_height,
                   pool_type=_pool_type(pool_type), name=name)


def block_expand_layer(input, block_x=1, block_y=1, stride_x=None,
                       stride_y=None, padding_x=0, padding_y=0,
                       name=None, **_):
    return dsl.block_expand(
        _one(input), (block_y, block_x),
        stride=(stride_y or block_y, stride_x or block_x),
        padding=(padding_y, padding_x), name=name,
    )


def recurrent_layer(input, size=None, act=None, reverse=False, name=None,
                    bias_attr=True, **_):
    x = _one(input)
    size = size or _layer_size(x)  # v1 infers from the input
    return dsl.recurrent(x, size, name=name,
                         act=_act_or(act, "tanh"), reversed=reverse,
                         bias=bool(bias_attr))


def lstmemory(input, size=None, act=None, gate_act=None, state_act=None,
              reverse=False, name=None, bias_attr=True, param_attr=None,
              **_):
    x = _one(input)
    size = size or _layer_size(x) // 4  # v1: input is the 4h projection
    return dsl.lstmemory(
        x, size, name=name, act=_act_or(act, "tanh"),
        gate_act=_act_or(gate_act, "sigmoid"),
        state_act=_act_or(state_act, "tanh"), reversed=reverse,
        bias=bool(bias_attr), param=param_attr,
    )


def grumemory(input, size=None, act=None, gate_act=None, reverse=False,
              name=None, bias_attr=True, param_attr=None, **_):
    x = _one(input)
    size = size or _layer_size(x) // 3  # v1: input is the 3h projection
    return dsl.grumemory(
        x, size, name=name, act=_act_or(act, "tanh"),
        gate_act=_act_or(gate_act, "sigmoid"), reversed=reverse,
        bias=bool(bias_attr), param=param_attr,
    )


def pooling_layer(input, pooling_type=None, name=None,
                  agg_level=AggregateLevel.TO_NO_SEQUENCE, stride=-1,
                  **_):
    # v1 default is MaxPooling (trainer_config_helpers pooling_layer)
    extra = {}
    if getattr(pooling_type, "output_max_index", False):
        assert not (stride and stride > 0), (
            "pooling_layer: output_max_index with stride is not "
            "supported (ambiguous output shape)"
        )
        extra["output_max_index"] = True
    if stride and stride > 0:
        extra["stride"] = stride
    return dsl.seq_pool(_one(input), pool_type=_pool_type(pooling_type),
                        level=_agg_to_level(agg_level), name=name,
                        **extra)


def last_seq(input, name=None,
             agg_level=AggregateLevel.TO_NO_SEQUENCE, stride=-1, **_):
    return dsl.last_seq(_one(input), name=name,
                        stride=max(stride, 0),
                        level=_agg_to_level(agg_level))


def first_seq(input, name=None,
              agg_level=AggregateLevel.TO_NO_SEQUENCE, stride=-1, **_):
    return dsl.first_seq(_one(input), name=name,
                         stride=max(stride, 0),
                         level=_agg_to_level(agg_level))


def expand_layer(input, expand_as, name=None,
                 expand_level=ExpandLevel.FROM_NO_SEQUENCE, **_):
    return dsl.expand(_one(input), expand_as, name=name,
                      level=("seq"
                             if expand_level == ExpandLevel.FROM_SEQUENCE
                             else "non-seq"))


def seq_concat_layer(a, b, name=None, **_):
    return dsl.seq_concat(a, b, name=name)


def seq_reshape_layer(input, reshape_size, name=None, **_):
    return dsl._add("seqreshape", [_one(input)], name=name, bias=False,
                    size=reshape_size)


def sub_seq_layer(input, offsets, sizes, name=None, **_):
    return dsl.sub_seq(_one(input), offsets, sizes, name=name)


class _MixedLayerBuilder:
    """`with mixed_layer() as m: m += projection` — the v1 helper's
    context-manager form (layers.py mixed_layer docstring). Terms are
    collected via `+=` and the real mixed layer is materialized on
    exit; afterwards the builder proxies the finished LayerRef (its
    .name), so it is usable anywhere a layer handle is."""

    def __init__(self, size, act, name, bias_attr):
        self._spec = (size, act, name, bias_attr)
        self._terms = []
        self._ref = None

    def __iadd__(self, term):
        self._terms.append(term)
        return self

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            return False
        size, act, name, bias_attr = self._spec
        self._ref = dsl.mixed(size, self._terms, name=name, act=_act(act),
                              bias=bool(bias_attr))
        return False

    @property
    def name(self):
        assert self._ref is not None, "mixed_layer context not exited yet"
        return self._ref.name

    @property
    def builder(self):
        return self._ref.builder

    @property
    def size(self):
        """Width of the finished layer (LayerOutput.size) — the group
        helpers infer their cell size from it (shared_lstm passes a
        mixed builder straight into lstmemory_group)."""
        assert self._ref is not None, "mixed_layer context not exited yet"
        return self._ref.size

    # arithmetic works like any layer handle (layer_math patches these
    # onto LayerRef; delegate to the finished ref)
    def __add__(self, other):
        return self._ref + other

    def __radd__(self, other):
        return self._ref.__radd__(other)

    def __sub__(self, other):
        return self._ref - other

    def __rsub__(self, other):
        return self._ref.__rsub__(other)

    def __mul__(self, other):
        return self._ref * other

    def __rmul__(self, other):
        return self._ref.__rmul__(other)


def _edges(input):
    """Mixed-layer input normalization: a single projection/operator
    edge is a (layer, proj[, extra]) tuple — don't let _many flatten
    it into bogus separate inputs (mixed_layer(input=table_projection(
    ...)) is the reference idiom, layers.py MixedLayerType)."""
    if (
        isinstance(input, tuple)
        and len(input) >= 2
        and isinstance(input[1], str)
    ):
        return [input]
    return _many(input)


def mixed_layer(size=0, input=None, act=None, name=None, bias_attr=False, **_):
    if input is None:
        return _MixedLayerBuilder(size, act, name, bias_attr)
    return dsl.mixed(size, _edges(input), name=name, act=_act(act),
                     bias=bool(bias_attr))


def dotmul_operator(a, b=None, scale=1.0, **_):
    """Mixed-layer elementwise-product operator (layers.py
    dotmul_operator; DotMulOperator.cpp). An operator term is a plain
    summand, so it materializes as an eltmul layer fed back through an
    identity projection."""
    x = dsl.eltmul(_one(a), _one(b if b is not None else a), scale=scale)
    return (x, "identity")


def multi_binary_label_cross_entropy(input, label, name=None, coeff=1.0, **_):
    return dsl.multi_binary_label_cross_entropy(
        _one(input), _one(label), name=name, coeff=coeff
    )


# ---- projections for mixed_layer (trainer_config_helpers/layers.py
# full_matrix_projection:552 etc.) — each returns the (layer, proj kind)
# edge spec dsl.mixed consumes ----

def full_matrix_projection(input, size=0, param_attr=None, **_):
    # the projection's own size/param ride the edge: a sizeless
    # mixed_layer infers its width from the projection's declared size
    # (reference mixed_layer(size=None) idiom), and a named param_attr
    # shares the projection weight across mixed layers (shared_lstm)
    extra = {}
    if param_attr is not None:
        extra["param"] = param_attr
    if size:
        extra["proj_size"] = size
    return (_one(input), "full_matrix", extra)


def trans_full_matrix_projection(input, size=0, param_attr=None, **_):
    extra = {}
    if param_attr is not None:
        extra["param"] = param_attr
    if size:
        extra["proj_size"] = size
    return (_one(input), "trans_full_matrix", extra)


def identity_projection(input, offset=None, size=None, **_):
    if offset is not None:
        # IdentityOffsetProjection (layers.py identity_projection
        # offset=): a single [offset, offset+size) slice
        end = offset + (size or (_layer_size(input) - offset))
        return (_one(input), "slice", {"slices": ((offset, end),)})
    return (_one(input), "identity")


def slice_projection(input, slices, **_):
    """(layers.py slice_projection; SliceProjection.cpp) — concat of
    [start, end) feature slices of the input."""
    for s, e in slices:
        assert 0 <= s < e, f"bad slice ({s}, {e})"
    return (_one(input), "slice",
            {"slices": tuple((int(s), int(e)) for s, e in slices)})


def dotmul_projection(input, param_attr=None, **_):
    if param_attr is not None:
        return (_one(input), "dotmul", {"param": param_attr})
    return (_one(input), "dotmul")


def scaling_projection(input, param_attr=None, **_):
    return (_one(input), "scaling")


def table_projection(input, size=0, param_attr=None, **_):
    x = _one(input)
    lc = x.builder.conf.layer(x.name)
    # ids slot feeding a lookup table: annotate a raw data layer the
    # same way embedding_layer does (v1 slot types came from the
    # provider declaration)
    if lc.type == "data" and not lc.attrs.get("is_ids"):
        lc.attrs["is_ids"] = True
        lc.attrs["is_seq"] = True
    extra = {"vocab_size": lc.size}
    if size:
        # a declared projection size fixes a sizeless host mixed
        # (table_projection(size=...) under concat, concat_table_b)
        extra["proj_size"] = size
    if param_attr is not None:
        extra["param"] = param_attr
    return (x, "table", extra)


def context_projection(input, context_len, context_start=None, **_):
    start = (-(context_len // 2)) if context_start is None else context_start
    return (_one(input), "context",
            {"context_length": context_len, "context_start": start})


def tensor_layer(a, b, size, act=None, name=None, bias_attr=True, **_):
    return dsl._add("tensor", [a, b], name=name, size=size,
                    act=_act(act), bias=bool(bias_attr))


def cos_sim(a, b, scale=1.0, size=1, name=None, **_):
    return dsl.cos_sim(a, b, scale=scale, size=size, name=name)


def scaling_layer(input, weight, name=None, **_):
    return dsl.scaling(weight, _one(input), name=name)


def slope_intercept_layer(input, slope=1.0, intercept=0.0, name=None, **_):
    return dsl.slope_intercept(_one(input), slope, intercept, name=name)


def interpolation_layer(input, weight, name=None, **_):
    a, b = _many(input)
    return dsl.interpolation(weight, a, b, name=name)


def linear_comb_layer(weights, vectors, size=None, name=None, **_):
    # v1 infers size = vectors.size / weights.size when omitted
    size = size or _layer_size(vectors) // max(_layer_size(weights), 1)
    return dsl.linear_comb(weights, vectors, size, name=name)


def power_layer(input, weight, name=None, **_):
    return dsl.power(weight, _one(input), name=name)


def clip_layer(input, min, max, name=None, **_):
    return dsl.clip(_one(input), min=min, max=max, name=name)


def row_conv_layer(input, context_len, name=None, param_attr=None, **_):
    return dsl.row_conv(_one(input), context_len, name=name,
                        param=param_attr)


def conv_shift_layer(a, b, name=None, **_):
    return dsl.conv_shift(a, b, name=name)


def bilinear_interp_layer(input, out_size_x, out_size_y, name=None, **_):
    return dsl.bilinear_interp(_one(input), out_size_x, out_size_y,
                               name=name)


def selective_fc_layer(input, size, select=None, act=None, name=None,
                       bias_attr=True, param_attr=None, **_):
    return dsl.selective_fc(_one(input), select, size=size,
                            act=_act(act), name=name,
                            bias=bool(bias_attr), param=param_attr)


def maxid_layer(input, name=None, **_):
    return dsl._add("max_id", [_one(input)], name=name, bias=False)


def sampling_id_layer(input, name=None, **_):
    return dsl._add("sampling_id", [_one(input)], name=name, bias=False)


def multiplex_layer(input, name=None, **_):
    return dsl._add("multiplex", _many(input), name=name, bias=False)


def nce_layer(input, label, num_classes=None, num_neg_samples=10,
              name=None, param_attr=None, bias_attr=True,
              neg_distribution=None, weight=None, **_):
    # v1 derives num_classes from the label layer's width when omitted
    num_classes = num_classes or _layer_size(label)
    return dsl._add("nce", [*_many(input), label], name=name,
                    size=num_classes, bias=bool(bias_attr),
                    param=param_attr, num_classes=num_classes,
                    num_neg_samples=num_neg_samples,
                    neg_distribution=neg_distribution)


def hsigmoid(input, label, num_classes=None, name=None, param_attr=None,
             bias_attr=True, **_):
    num_classes = num_classes or _layer_size(label)
    return dsl._add("hsigmoid", [*_many(input), label], name=name,
                    size=num_classes, bias=bool(bias_attr),
                    param=param_attr, num_classes=num_classes)


def crf_layer(input, label, size=None, param_attr=None, name=None, **_):
    # v1 infers size from the input's width when omitted
    # (trainer_config_helpers/layers.py crf_layer)
    size = size or _layer_size(_one(input))
    return dsl.crf(input, label, num_tags=size, name=name,
                   param=param_attr)


def crf_decoding_layer(input, size, label=None, param_attr=None,
                       name=None, **_):
    return dsl.crf_decoding(input, num_tags=size, label=label,
                            name=name, param=param_attr)


def ctc_layer(input, label, size=None, blank=0, norm_by_times=False,
              name=None, **_):
    size = size or _layer_size(_one(input))
    # v1 CTC consumes an already-softmaxed input (the config applies
    # SoftmaxActivation on the fc) — do NOT softmax again. name=None
    # auto-uniquifies (a fixed "cost" would collide across layers).
    return dsl._add("ctc", [input, label], name=name,
                    size=size, bias=False, blank=blank,
                    norm_by_times=norm_by_times, apply_softmax=False)


def eos_layer(input, eos_id, name=None, **_):
    return dsl.eos_id(_one(input), eos_id, name=name)


def warp_ctc_layer(input, label, size=None, blank=0,
                   norm_by_times=False, name=None, **_):
    """(layers.py warp_ctc_layer) — same lowering as ctc_layer; the
    warp-ctc/builtin split is a GPU-kernel distinction with no XLA
    analogue."""
    size = size or _layer_size(_one(input))
    # unlike ctc_layer, the warp-ctc contract integrates the softmax:
    # the config feeds LINEAR logits (reference layers.py
    # warp_ctc_layer doc), so the layer applies it
    return dsl._add("warp_ctc", [input, label], name=name, size=size,
                    bias=False, blank=blank,
                    norm_by_times=norm_by_times, apply_softmax=True)


def lambda_cost(input, score, NDCG_num=5, max_sort_size=-1, name=None,
                **_):
    """(layers.py lambda_cost; CostLayer.cpp LambdaCost)."""
    return dsl._add("lambda_cost", [_one(input), _one(score)],
                    name=name, bias=False, NDCG_num=NDCG_num,
                    max_sort_size=max_sort_size)


def huber_cost(input, label, name=None, coeff=1.0, **_):
    """(layers.py huber_cost — two-class Huber classification)."""
    return dsl._add("huber_classification", [_one(input), _one(label)],
                    name=name, bias=False, coeff=coeff)


def cross_entropy_with_selfnorm(input, label, name=None, coeff=1.0,
                                softmax_selfnorm_alpha=0.1, **_):
    return dsl._add(
        "multi_class_cross_entropy_with_selfnorm",
        [_one(input), _one(label)], name=name, bias=False, coeff=coeff,
        softmax_selfnorm_alpha=softmax_selfnorm_alpha,
    )


def smooth_l1_cost(input, label, name=None, coeff=1.0, **_):
    return dsl._add("smooth_l1", [_one(input), _one(label)],
                    name=name, bias=False, coeff=coeff)


def print_layer(input, format=None, name=None, **_):
    """(layers.py print_layer; PrintLayer.cpp) — identity that prints
    during execution (jax.debug.print under jit)."""
    for x in _many(input):
        dsl._add("print", [x], name=name, bias=False)
    # the reference returns None (print is a side effect)


# the primary spelling upstream (layers.py:1023 printer_layer;
# print_layer kept for v1 compat, :1046-1051) — v2 renames it `printer`
printer_layer = print_layer


def pad_layer(input, pad_c=None, pad_h=None, pad_w=None, name=None,
              **_):
    return dsl._add("pad", [_one(input)], name=name, bias=False,
                    pad_c=tuple(pad_c or (0, 0)),
                    pad_h=tuple(pad_h or (0, 0)),
                    pad_w=tuple(pad_w or (0, 0)))


def crop_layer(input, offset=None, axis=2, shape=None, name=None, **_):
    """(layers.py crop_layer) — crop input[0] to input[1]'s spatial
    shape (or explicit offset/shape)."""
    ins = _many(input)
    attrs = {}
    if offset is not None and shape is not None:
        attrs = {"crop_h": (offset[0], shape[0]),
                 "crop_w": (offset[1], shape[1])}
    return dsl._add("crop", ins, name=name, bias=False, **attrs)


def trans_layer(input, name=None, **_):
    # height/width resolve at build: the layer reads the input spec's
    # (H, W) dims when present, else infers a square from the width
    return dsl._add("trans", [_one(input)], name=name, bias=False)


def rotate_layer(input, height, width, name=None, **_):
    """(layers.py rotate_layer; RotateLayer.cpp) — rotate each
    height x width channel plane 90 degrees clockwise."""
    return dsl._add("rotate", [_one(input)], name=name, bias=False,
                    height=height, width=width)


def out_prod_layer(input1, input2, name=None, **_):
    """(layers.py out_prod_layer; OuterProdLayer.cpp) — flattened
    outer product of two vectors."""
    return dsl._add("out_prod", [_one(input1), _one(input2)],
                    name=name, bias=False)


def row_l2_norm_layer(input, name=None, **_):
    return dsl._add("row_l2_norm", [_one(input)], name=name, bias=False)


def sum_to_one_norm_layer(input, name=None, **_):
    return dsl._add("sum_to_one_norm", [_one(input)], name=name,
                    bias=False)


def conv_operator(img, filter, filter_size, num_filters,
                  num_channels=1, stride=1, padding=0, trans=False,
                  filter_size_y=None, stride_y=None, padding_y=None,
                  **_):
    """(layers.py conv_operator) — a mixed-layer term whose FILTER
    comes from the graph (per-example dynamic filters); materializes
    the conv_operator layer and feeds it back through an identity
    projection, like dotmul_operator."""
    ref = dsl._add(
        "conv_operator", [_one(img), _one(filter)], bias=False,
        num_filters=num_filters, num_channels=num_channels,
        filter_size=filter_size, stride=stride, padding=padding,
        trans=bool(trans),
    )
    # parse-time output size so a sizeless mixed_layer knows its width
    # immediately (reference ConvOperator computes it in the config
    # parser: num_filters * out_x * out_y over the square image)
    out_size = 0
    if not trans:
        import math

        pixels = _layer_size(img) // max(num_channels, 1)
        side = int(math.isqrt(pixels))
        if side * side == pixels:
            fy = filter_size_y or filter_size
            sy = stride_y or stride
            py = padding if padding_y is None else padding_y
            ox = (side + 2 * padding - filter_size) // stride + 1
            oy = (side + 2 * py - fy) // sy + 1
            if ox > 0 and oy > 0:
                out_size = num_filters * ox * oy
    if out_size:
        return (ref, "identity", {"proj_size": out_size})
    return (ref, "identity")


def conv_projection(input, filter_size, num_filters, num_channels=1,
                    stride=1, padding=0, groups=1, trans=False,
                    param_attr=None, **_):
    """(layers.py conv_projection) — learned-weight conv as a mixed
    term; materializes a conv (or conv-transpose) layer."""
    f = dsl.conv_trans if trans else dsl.conv
    kw = {} if trans else {"groups": groups}
    ref = f(_one(input), num_filters, filter_size, stride=stride,
            padding=padding, act="", bias=False, param=param_attr,
            num_channels=num_channels, **kw)
    # a projection has no bias of its own; the host mixed layer's bias
    # is SHARED per filter for conv projections (config_parser.py:2984
    # shared_biases=True, bias_size=sum(calc_bias_size))
    return (ref, "identity", {"conv_bias": num_filters})


def priorbox_layer(input, image, min_size, max_size=(), aspect_ratio=(),
                   variance=(0.1, 0.1, 0.2, 0.2), name=None, **_):
    return dsl.priorbox(_one(input), image, min_size, max_size,
                        aspect_ratio, variance, name=name)


def multibox_loss_layer(input_loc, input_conf, priorbox, label,
                        num_classes, overlap_threshold=0.5,
                        neg_pos_ratio=3.0, neg_overlap=0.5, name=None,
                        **kw):
    """Two forms: pass `gt_label=` (class-id layer) with `label` the
    [B,G,4] boxes (the explicit two-feed form), or the reference's
    single PACKED label layer (per box [label, x1, y1, x2, y2,
    difficult] — width a multiple of 6), which the layer splits on
    device (`packed_label` attr)."""
    gt_label = kw.get("gt_label")
    if gt_label is None:
        if isinstance(input_loc, (list, tuple)):
            input_loc = dsl.concat(*input_loc)
        if isinstance(input_conf, (list, tuple)):
            input_conf = dsl.concat(*input_conf)
        return dsl._add(
            "multibox_loss",
            [priorbox, label, label, input_loc, input_conf],
            name=name, bias=False, num_classes=num_classes,
            overlap_threshold=overlap_threshold,
            neg_pos_ratio=neg_pos_ratio, neg_overlap=neg_overlap,
            background_id=kw.get("background_id", 0),
            packed_label=True,
        )
    return dsl.multibox_loss(priorbox, label, gt_label, input_loc,
                             input_conf, num_classes, name=name,
                             overlap_threshold=overlap_threshold,
                             neg_pos_ratio=neg_pos_ratio,
                             neg_overlap=neg_overlap)


def detection_output_layer(input_loc, input_conf, priorbox, num_classes,
                           nms_threshold=0.45, nms_top_k=400,
                           keep_top_k=200, confidence_threshold=0.01,
                           name=None, **_):
    return dsl.detection_output(priorbox, input_loc, input_conf,
                                num_classes, name=name,
                                nms_threshold=nms_threshold,
                                nms_top_k=nms_top_k,
                                keep_top_k=keep_top_k,
                                confidence_threshold=confidence_threshold)


# ---- costs ----

def _effective_act(conf, name, depth=8):
    """The activation the values flowing out of `name` went through,
    traced through pass-through wrappers: single-input addto/dropout
    forwards its input's activation when itself linear, and a
    recurrent_group's output carries its step-net out-link's
    activation. Depth-capped; unknown -> ""."""
    while depth > 0:
        depth -= 1
        try:
            lc = conf.layer(name)
        except Exception:
            return ""
        if lc.active_type:
            return lc.active_type
        if lc.type == "recurrent_group":
            conf = lc.attrs["step_conf"]
            name = lc.attrs["out_links"][0]
            continue
        if (
            lc.type in ("addto", "seqlastins", "seqreverse")
            and len(lc.inputs) == 1
        ):
            # value-preserving wrappers: dropout/identity addto and
            # frame selectors (last_seq/first_seq/seq_reverse) carry
            # their input's distribution through unchanged
            name = lc.inputs[0].name
            continue
        return ""
    return ""


def classification_cost(input, label, name=None, coeff=1.0,
                        weight=None, **_):
    """Reference classification_cost = multi-class CE on the input
    DISTRIBUTION (the v1 idiom puts act=Softmax on the input fc;
    CostLayer.cpp MultiClassCrossEntropy reads probabilities). Route a
    softmax-activated input to the prob-CE layer — mapping it onto the
    fused logits-CE would double-softmax and floor the loss at
    -ln(sigmoid(max_margin)). A non-softmax input keeps the fused
    softmax+CE composite (same math the reference composes)."""
    x = _one(input)
    if _effective_act(x.builder.conf, x.name) == "softmax":
        return dsl.cross_entropy(x, label, name=name, coeff=coeff,
                                 weight=weight)
    return dsl.classification_cost(x, label, name=name, coeff=coeff,
                                   weight=weight)


def cross_entropy(input, label, name=None, coeff=1.0, **_):
    return dsl.cross_entropy(input, label, name=name, coeff=coeff)


def square_error_cost(input, label, name=None, coeff=1.0, **_):
    return dsl.square_error(input, label, name=name, coeff=coeff)


# reference layers.py:4042 mse_cost with alias regression_cost (:4077)
mse_cost = square_error_cost
regression_cost = square_error_cost


def rank_cost(left, right, label, name=None, coeff=1.0, **_):
    return dsl.rank_cost(left, right, label, name=name, coeff=coeff)


def sum_cost(input, name=None, coeff=1.0, **_):
    return dsl.sum_cost(_one(input), name=name, coeff=coeff)


def prelu_layer(input, partial_sum=0, name=None, param_attr=None, **_):
    return dsl.prelu(_one(input), name=name, partial_sum=partial_sum,
                     param=param_attr)


def gated_unit_layer(input, size, act=None, name=None, bias_attr=True,
                     **_):
    return dsl.gated_unit(_one(input), size, act=_act(act), name=name,
                          bias=bool(bias_attr))


def repeat_layer(input, num_repeats, name=None, **_):
    return dsl.repeat(_one(input), num_repeats, name=name)


def kmax_sequence_score_layer(input, beam_size=1, name=None, **_):
    return dsl.kmax_seq_score(_one(input), beam_size=beam_size,
                              name=name)


# ---- prebuilt networks, keyword style (networks.py) ----

def simple_lstm(input, size, name=None, act=None, reverse=False,
                lstm_cell_attr=None, **_):
    """(networks.py:548 simple_lstm)."""
    out = dsl.simple_lstm(_one(input), size, name=name,
                          act=_act_or(act, "tanh"), reversed=reverse)
    return _apply_layer_attr(out, lstm_cell_attr)


def simple_gru(input, size, name=None, act=None, gate_act=None,
               reverse=False, gru_cell_attr=None, **_):
    """(networks.py:975 simple_gru)."""
    out = dsl.simple_gru(_one(input), size, name=name,
                         act=_act_or(act, "tanh"),
                         gate_act=_act_or(gate_act, "sigmoid"),
                         reversed=reverse)
    return _apply_layer_attr(out, gru_cell_attr)


def lstmemory_unit(input, out_memory=None, name=None, size=None,
                   param_attr=None, act=None, gate_act=None,
                   state_act=None, lstm_bias_attr=True, **_):
    """(networks.py:633 lstmemory_unit) — one LSTM timestep for use
    inside recurrent_group steps; input is the 4h pre-projection.
    lstm_bias_attr may be a ParamAttr carrying a SHARED bias name
    (the reference shared_lstm config)."""
    b, bp = _bias(lstm_bias_attr)
    return dsl.lstmemory_unit(
        _one(input), size=size, name=name, out_memory=out_memory,
        act=_act_or(act, "tanh"), gate_act=_act_or(gate_act, "sigmoid"),
        state_act=_act_or(state_act, "tanh"), param=param_attr,
        bias=b, bias_param=bp,
    )


def lstmemory_group(input, size=None, name=None, out_memory=None,
                    reverse=False, param_attr=None, act=None,
                    gate_act=None, state_act=None, lstm_bias_attr=True,
                    **_):
    """(networks.py:744 lstmemory_group)."""
    b, bp = _bias(lstm_bias_attr)
    return dsl.lstmemory_group(
        _one(input), size=size, name=name, out_memory=out_memory,
        reversed=reverse, act=_act_or(act, "tanh"),
        gate_act=_act_or(gate_act, "sigmoid"),
        state_act=_act_or(state_act, "tanh"), param=param_attr,
        bias=b, bias_param=bp,
    )


def gru_unit(input, memory_boot=None, size=None, name=None,
             gru_bias_attr=True, gru_param_attr=None, act=None,
             gate_act=None, naive=False, **_):
    """(networks.py:840 gru_unit) — one GRU timestep for
    recurrent_group steps; input is the 3h pre-projection."""
    b, bp = _bias(gru_bias_attr)
    return dsl.gru_unit(
        _one(input), size=size, name=name, memory_boot=memory_boot,
        act=_act_or(act, "tanh"), gate_act=_act_or(gate_act, "sigmoid"),
        param=gru_param_attr, bias=b, bias_param=bp, naive=naive,
    )


def gru_group(input, memory_boot=None, size=None, name=None,
              reverse=False, gru_bias_attr=True, gru_param_attr=None,
              act=None, gate_act=None, naive=False, **_):
    """(networks.py:902 gru_group)."""
    b, bp = _bias(gru_bias_attr)
    return dsl.gru_group(
        _one(input), size=size, name=name, memory_boot=memory_boot,
        reversed=reverse, act=_act_or(act, "tanh"),
        gate_act=_act_or(gate_act, "sigmoid"), param=gru_param_attr,
        bias=b, bias_param=bp, naive=naive,
    )


def simple_gru2(input, size, name=None, reverse=False, act=None,
                gate_act=None, **_):
    """(networks.py:1061 simple_gru2)."""
    return dsl.simple_gru2(_one(input), size, name=name,
                           act=_act_or(act, "tanh"),
                           gate_act=_act_or(gate_act, "sigmoid"),
                           reversed=reverse)


def bidirectional_gru(input, size, name=None, return_seq=False,
                      fwd_act=None, fwd_gate_act=None, **_):
    """(networks.py:1122 bidirectional_gru). The fwd_* activations
    apply to both directions (the reference defaults both directions
    to the same activations unless overridden per side)."""
    return dsl.bidirectional_gru(_one(input), size, name=name,
                                 return_seq=return_seq,
                                 act=_act_or(fwd_act, "tanh"),
                                 gate_act=_act_or(fwd_gate_act,
                                                  "sigmoid"))


def img_conv_bn_pool(input, filter_size, num_filters, pool_size,
                     name=None, pool_type=None, act=None, groups=1,
                     conv_stride=1, conv_padding=0, num_channel=None,
                     conv_param_attr=None, pool_stride=1,
                     pool_padding=0, **_):
    """(networks.py:232 img_conv_bn_pool)."""
    return dsl.img_conv_bn_pool(
        _one(input), filter_size, num_filters, pool_size, name=name,
        pool_type=_pool_type(pool_type), act=_act_or(act, "relu"),
        groups=groups, conv_stride=conv_stride,
        conv_padding=conv_padding, num_channel=num_channel,
        conv_param=conv_param_attr, pool_stride=pool_stride,
        pool_padding=pool_padding,
    )


def text_conv_pool(input, context_len, hidden_size, name=None, **kw):
    """(networks.py:41 text_conv_pool = sequence_conv_pool alias)."""
    return sequence_conv_pool(input, context_len, hidden_size,
                              name=name, **kw)


def bidirectional_lstm(input, size, name=None, return_seq=False, **_):
    """(networks.py:1207 bidirectional_lstm). return_seq=False pools
    each direction's last frame, True concats the full sequences."""
    x = _one(input)
    if return_seq:
        return dsl.bidirectional_lstm(x, size, name=name)
    fwd = dsl.simple_lstm(x, size, name=(name or "bilstm") + "_fwd")
    bwd = dsl.simple_lstm(x, size, name=(name or "bilstm") + "_bwd",
                          reversed=True)
    return dsl.concat(dsl.last_seq(fwd), dsl.first_seq(bwd), name=name)


def sequence_conv_pool(input, context_len, hidden_size, name=None,
                       context_start=None, pool_type=None,
                       context_proj_layer_name=None,
                       context_proj_param_attr=False,
                       fc_layer_name=None, fc_param_attr=None,
                       fc_bias_attr=None, fc_act=None,
                       pool_bias_attr=None, **_):
    """Text conv-pool: context projection -> fc -> sequence pooling
    (networks.py:41 sequence_conv_pool — the quick_start CNN)."""
    x = _one(input)
    context_proj_layer_name = (
        context_proj_layer_name or f"{name}_conv_proj"
    )
    with mixed_layer(
        name=context_proj_layer_name,
        size=x.size * context_len,
        act=LinearActivation(),
    ) as m:
        m += context_projection(
            x, context_len=context_len, context_start=context_start
        )
    fl = fc_layer(
        name=fc_layer_name or f"{name}_conv_fc",
        input=m,
        size=hidden_size,
        act=fc_act or TanhActivation(),
        param_attr=fc_param_attr,
        bias_attr=fc_bias_attr if fc_bias_attr is not None else True,
    )
    return pooling_layer(name=name, input=fl, pooling_type=pool_type)


def simple_img_conv_pool(input, filter_size, num_filters, pool_size,
                         pool_stride=1, act=None, name=None, padding=0,
                         num_channel=None, **_):
    """(networks.py:145 simple_img_conv_pool)."""
    c = dsl.conv(_one(input), num_filters, filter_size, padding=padding,
                 act=_act_or(act, "relu"), num_channels=num_channel,
                 name=(name or "convpool") + "_conv")
    return dsl.pool(c, pool_size, pool_stride,
                    name=(name or "convpool") + "_pool")


def img_conv_group(input, conv_num_filter, conv_filter_size, pool_size,
                   pool_stride, conv_act=None, conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=None, pool_type=None,
                   num_channels=None, conv_padding=None, **_):
    """A VGG block (networks.py:333 img_conv_group)."""
    h = _one(input)
    n = len(conv_num_filter)
    fss = (conv_filter_size if isinstance(conv_filter_size, (list, tuple))
           else [conv_filter_size] * n)
    bns = (conv_with_batchnorm
           if isinstance(conv_with_batchnorm, (list, tuple))
           else [conv_with_batchnorm] * n)
    drops = (conv_batchnorm_drop_rate
             if isinstance(conv_batchnorm_drop_rate, (list, tuple))
             else [conv_batchnorm_drop_rate] * n)
    act = _act_or(conv_act, "relu")
    for i, (nf, fs, bn) in enumerate(zip(conv_num_filter, fss, bns)):
        pad = (conv_padding[i]
               if isinstance(conv_padding, (list, tuple))
               else conv_padding)
        if pad is None:
            pad = (fs - 1) // 2
        h = dsl.conv(h, nf, fs, padding=pad, act="" if bn else act,
                     num_channels=num_channels if i == 0 else None)
        if bn:
            h = dsl.batch_norm(h, act=act)
            if drops[i]:
                h = dsl.dropout(h, drops[i])
    return dsl.pool(h, pool_size, pool_stride,
                    pool_type=_pool_type(pool_type))


def simple_attention(encoded_sequence, encoded_proj, decoder_state,
                     transform_param_attr=None, softmax_param_attr=None,
                     weight_act=None, name=None, size=None, **_):
    return dsl.simple_attention(
        encoded_sequence, encoded_proj, decoder_state, name=name,
        weight_act=_act_or(weight_act, "tanh"),
        transform_param=transform_param_attr,
        softmax_param=softmax_param_attr, size=size,
    )


def sub_nested_seq_layer(input, selected_indices, name=None, **_):
    return dsl.sub_nested_seq(_one(input), selected_indices, name=name)


def get_output_layer(input, arg_name, name=None, **_):
    return dsl.get_output(_one(input), arg_name, name=name)


# ---- recurrence ----

def memory(name, size, boot_layer=None, **_):
    return dsl.memory(name, size=size, boot_layer=boot_layer)


class SubsequenceInput:
    """(layers.py SubsequenceInput) — marks a recurrent_group in-link
    whose OUTER iteration walks subsequences. The scan executor keys
    off the input spec's has_subseq (layers/recurrent_group.py), so
    this unwraps to the underlying layer at group-build time."""

    def __init__(self, input):
        self.input = _one(input)


def StaticInput(input, is_seq=False, size=None, **_):
    """(layers.py StaticInput) — whole-sequence read-only in-link."""
    return dsl.StaticInput(_one(input))


def recurrent_group(step, input, name=None, reverse=False, **_):
    ins = [x.input if isinstance(x, SubsequenceInput) else x
           for x in _many(input)]
    return dsl.recurrent_group(step, ins, name=name,
                               reversed=reverse)


class BaseGeneratedInput:
    """(layers.py BaseGeneratedInput)."""


class GeneratedInput(BaseGeneratedInput):
    """(layers.py:3744 GeneratedInput) — the beam_search in-link whose
    value at step t is the `embedding_name` embedding of the word the
    beam generated at t-1 (bos at t=0)."""

    def __init__(self, size, embedding_name, embedding_size, **_):
        self.size = size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


def beam_search(step, input, bos_id, eos_id, beam_size=1,
                max_length=500, name=None,
                num_results_per_sample=None, **_):
    """(layers.py:3893 beam_search) — declare a GENERATING recurrent
    group: `step` runs per decode step over beam candidates; the
    GeneratedInput position receives the embedded previously-generated
    word; other inputs are per-sequence statics. Recorded as a
    SubModelConf(is_generating=True) executed by
    api.SequenceGenerator (RecurrentGradientMachine.h:307
    generateSequence + beamSearch)."""
    from paddle_tpu.core.config import InputConf, LayerConf, SubModelConf

    ins = _many(input)
    gen_pos = [
        i for i, x in enumerate(ins)
        if isinstance(x, BaseGeneratedInput)
    ]
    assert len(gen_pos) == 1, (
        "beam_search needs exactly one GeneratedInput among its inputs"
    )
    gi = ins[gen_pos[0]]
    statics = []
    for i, x in enumerate(ins):
        if i == gen_pos[0]:
            continue
        # unwrap the v1/dsl StaticInput wrappers to the layer ref
        x = getattr(x, "input", x)
        x = getattr(x, "ref", x)
        statics.append(_one(x))
    g = dsl.current()
    gname = name or g.uniq("beam_search")
    out = g.add(
        LayerConf(
            name="__beam_search_predict__", type="gen_output",
            size=gi.size,
            inputs=[InputConf(name=s.name) for s in statics],
            attrs={"dim": (1,), "is_seq": True, "is_ids": True},
        )
    )
    g.conf.sub_models.append(
        SubModelConf(
            name=gname,
            layer_names=["__beam_search_predict__"],
            is_generating=True,
            attrs={
                "step": step,
                "gen_pos": gen_pos[0],
                "gen_size": gi.size,
                "embedding_name": gi.embedding_name,
                "embedding_size": gi.embedding_size,
                "static_layer_names": [s.name for s in statics],
                "bos_id": bos_id,
                "eos_id": eos_id,
                "beam_size": beam_size,
                "max_length": max_length,
                "num_results": num_results_per_sample or beam_size,
                "out_layer": "__beam_search_predict__",
            },
        )
    )
    return out


def small_vgg(input_image, num_channels, num_classes, **_):
    """(networks.py:435 small_vgg): 4 VGG blocks with batch-norm +
    per-conv dropout, pool, dropout, fc(512)+bn+relu, softmax fc."""

    def block(ipt, num_filter, times, dropouts, num_channels_=None):
        return img_conv_group(
            input=ipt,
            num_channels=num_channels_,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * times,
            conv_filter_size=3,
            conv_act=ReluActivation(),
            conv_with_batchnorm=True,
            conv_batchnorm_drop_rate=dropouts,
            pool_type="max",
        )

    tmp = block(input_image, 64, 2, [0.3, 0], num_channels)
    tmp = block(tmp, 128, 2, [0.4, 0])
    tmp = block(tmp, 256, 3, [0.4, 0.4, 0])
    tmp = block(tmp, 512, 3, [0.4, 0.4, 0])
    tmp = img_pool_layer(input=tmp, stride=2, pool_size=2)
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(input=tmp, size=512, act=LinearActivation())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = batch_norm_layer(input=tmp, act=ReluActivation())
    return fc_layer(input=tmp, size=num_classes,
                    act=SoftmaxActivation())


def vgg_16_network(input_image, num_channels, num_classes=1000, **_):
    """(networks.py:465 vgg_16_network)."""

    def block(ipt, num_filter, times, num_channels_=None):
        return img_conv_group(
            input=ipt,
            num_channels=num_channels_,
            pool_size=2,
            pool_stride=2,
            conv_num_filter=[num_filter] * times,
            conv_filter_size=3,
            conv_act=ReluActivation(),
            pool_type="max",
        )

    tmp = block(input_image, 64, 2, num_channels)
    tmp = block(tmp, 128, 2)
    tmp = block(tmp, 256, 3)
    tmp = block(tmp, 512, 3)
    tmp = block(tmp, 512, 3)
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    tmp = fc_layer(input=tmp, size=4096, act=ReluActivation())
    tmp = dropout_layer(input=tmp, dropout_rate=0.5)
    return fc_layer(input=tmp, size=num_classes,
                    act=SoftmaxActivation())
