"""trainer_config_helpers.layer_math — arithmetic on layer handles.

Reference: python/paddle/trainer_config_helpers/layer_math.py — unary
math ops (exp/log/abs/sigmoid/tanh/square/relu/sqrt/reciprocal) as
identity-projection mixed layers, plus +,-,* operators patched onto
LayerOutput: layer+scalar -> slope_intercept(intercept), layer+layer ->
sum of identity projections (with size-1 broadcast via repeat),
layer*scalar -> slope_intercept(slope), layer*size-1-layer ->
scaling_layer. Importing this module applies the same operators to
paddle_tpu's LayerRef (the reference patches its LayerOutput the same
way, layer_math.py:72-127).
"""

from __future__ import annotations

import numbers

from paddle_tpu import dsl
from paddle_tpu.compat import layers_v1 as _v1

__all__ = []


def _register_unary(op_name, act_name):
    def op(input, name=None):
        return dsl.mixed(
            0, [_v1.identity_projection(_v1._one(input))],
            name=name, act=act_name, bias=False,
        )

    op.__name__ = op_name
    globals()[op_name] = op
    __all__.append(op_name)


_register_unary("exp", "exponential")
_register_unary("log", "log")
_register_unary("abs", "abs")
_register_unary("sigmoid", "sigmoid")
_register_unary("tanh", "tanh")
_register_unary("square", "square")
_register_unary("relu", "relu")
_register_unary("sqrt", "sqrt")
_register_unary("reciprocal", "reciprocal")


def _size(ref):
    return ref.builder.conf.layer(ref.name).size


def _as_ref(x):
    """Unwrap the mixed-layer builder proxy to its LayerRef."""
    return x._ref if isinstance(x, _v1._MixedLayerBuilder) else x


def _add_op(layeroutput, other):
    layeroutput, other = _as_ref(layeroutput), _as_ref(other)
    if isinstance(other, numbers.Number):
        return dsl.slope_intercept(layeroutput, intercept=float(other))
    a, b = layeroutput, other
    if _size(a) != _size(b):
        if _size(b) == 1:
            b = dsl.repeat(b, _size(a))
        elif _size(a) == 1:
            a, b = b, dsl.repeat(a, _size(b))
        else:
            raise ValueError(
                "layers can be added only with equal sizes or one "
                f"size-1 operand (got {_size(a)} and {_size(b)})"
            )
    return dsl.addto(a, b)


def _sub_op(layeroutput, other):
    layeroutput, other = _as_ref(layeroutput), _as_ref(other)
    if isinstance(other, numbers.Number):
        return dsl.slope_intercept(layeroutput, intercept=-float(other))
    return _add_op(layeroutput, dsl.slope_intercept(other, slope=-1.0))


def _rsub_op(layeroutput, other):
    return _add_op(dsl.slope_intercept(_as_ref(layeroutput), slope=-1.0),
                   other)


def _mul_op(layeroutput, other):
    layeroutput, other = _as_ref(layeroutput), _as_ref(other)
    if isinstance(other, numbers.Number):
        return dsl.slope_intercept(layeroutput, slope=float(other))
    if _size(layeroutput) == 1:
        return dsl.scaling(layeroutput, other)
    if _size(other) == 1:
        return dsl.scaling(other, layeroutput)
    raise ValueError(
        "'*' needs a number or a size-1 layer operand (use "
        "dotmul_operator for elementwise products)"
    )


dsl.LayerRef.__add__ = _add_op
dsl.LayerRef.__radd__ = _add_op
dsl.LayerRef.__sub__ = _sub_op
dsl.LayerRef.__rsub__ = _rsub_op
dsl.LayerRef.__mul__ = _mul_op
dsl.LayerRef.__rmul__ = _mul_op
