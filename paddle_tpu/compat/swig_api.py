"""The `py_paddle.swig_paddle` API surface, TPU-native.

Reference: paddle/api/PaddleAPI.h:103,244,402 + paddle/api/Paddle.i
(the SWIG module the reference's API-driven demo drivers import:
v1_api_demo/quick_start/api_train.py:17, gan/gan_trainer.py:24,
vae/vae_train.py:24). Slot-indexed Arguments of Matrix/IVector wrap
numpy; GradientMachine/Trainer execute as jit-compiled paddle_tpu
Network/TrainStep programs instead of the C++ gserver stack.

Covered (what the four reference drivers exercise): initPaddle,
Matrix/Vector/IVector numpy bridges, Arguments with value/id slots and
sequence start positions, GradientMachine.createFromConfigProto /
forward / forwardTest / forwardBackward / parameter handles with
PARAMETER_VALUE buffers (copyFrom/copyToNumpyArray — the GAN's
copy_shared_parameters), loadParameters/randParameters, and
Trainer.create with the startTrain/startTrainPass/trainOneDataBatch/
finishTrainPass/startTestPeriod/testOneDataBatch/finishTestPeriod
loop.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np

from paddle_tpu.core import flags as _flags
from paddle_tpu.core import rng as _rng
from paddle_tpu.core.arg import Arg, pad_ragged
from paddle_tpu.network import Network
from paddle_tpu.optimizers import create_optimizer
from paddle_tpu.parallel.dp import TrainStep

log = logging.getLogger("paddle_tpu.api")

# --- constants (api/PaddleAPI.h enums) ---
PARAMETER_VALUE = 0
PARAMETER_GRADIENT = 1
PARAMETER_MOMENTUM = 2
PASS_TRAIN = 0
PASS_TEST = 1
PASS_GC = 2
CREATE_MODE_NORMAL = 0
CREATE_MODE_SGD_SPARSE_CPU_TRAINING = 3
NO_SPARSE_ID = -1


def initPaddle(*args):
    """api.initPaddle('--use_gpu=0', ...) — gflags-style strings
    (api/Paddle.i initPaddle). Flags with a paddle_tpu equivalent are
    applied; device-model-specific ones are accepted and ignored."""
    mapped = {
        "seed": ("seed", int),
        "log_period": ("log_period", int),
        "show_parameter_stats_period": ("show_parameter_stats_period", int),
        "beam_size": ("beam_size", int),
        "start_pass": ("start_pass", int),
    }
    for a in args:
        if not a.startswith("--"):
            continue
        k, _, v = a[2:].partition("=")
        if k in mapped:
            name, cast = mapped[k]
            _flags.set_flag(name, cast(v))


def isGpuVersion() -> bool:
    """api.isGpuVersion — whether a CUDA build is running. This build
    targets TPU via XLA; the GPU-specific re-run paths reference tests
    gate on this (test_data_feeder.py main) don't apply."""
    return False


def isUsingGpu() -> bool:
    """api.isUsingGpu — the use_gpu flag state (host buffers here are
    always numpy; the device side is XLA's)."""
    return False


def setUseGpu(flag: bool) -> None:
    """api.setUseGpu — accepted for parity; device placement is XLA's
    (the axon TPU backend is used whenever present)."""


class RangeError(Exception):
    """api Matrix/Vector out-of-range access (Paddle.i RangeError)."""


# sparse enums (Paddle.i / matrix.h)
SPARSE_NON_VALUE = 0
SPARSE_VALUE = 1
SPARSE_CSR = 0
SPARSE_CSC = 1


def _as2d(a: np.ndarray) -> np.ndarray:
    a = np.asarray(a)
    return a.reshape(a.shape[0], -1) if a.ndim != 2 else a


class Matrix:
    """Dense host matrix (api/PaddleAPI.h:103 Matrix; numpy bridge
    api/Paddle.i:142-165)."""

    def __init__(self, array):
        self._a = _as2d(np.asarray(array, np.float32))

    @classmethod
    def createDenseFromNumpy(cls, a, copy=True):
        return cls(np.array(a, np.float32, copy=copy))

    @classmethod
    def createCpuDenseFromNumpy(cls, a, copy=True):
        """copy=False SHARES memory with the numpy matrix
        (api/Paddle.i:142 zero-copy bridge)."""
        a = np.asarray(a)
        if not copy and a.dtype == np.float32 and a.ndim == 2:
            m = cls.__new__(cls)
            m._a = a
            return m
        return cls(np.array(a, np.float32))

    @classmethod
    def createGpuDenseFromNumpy(cls, a):
        return cls(np.array(a, np.float32))

    @classmethod
    def createDense(cls, data, height, width):
        a = np.asarray(data, np.float32)
        if a.size != height * width:
            # a short LAST batch: the reference's api loaders pass the
            # nominal batch height with fewer samples' data
            # (api/test/util.py loadMNISTTrainData's StopIteration
            # break); the rows that exist win — anything else is a
            # caller bug and must not be silently reshaped
            if a.size > height * width or a.size % width:
                raise ValueError(
                    f"createDense: {a.size} values do not form "
                    f"<= {height} rows of width {width}"
                )
            height = a.size // width
        return cls(a.reshape(height, width))

    @classmethod
    def createZero(cls, height, width):
        return cls(np.zeros((height, width), np.float32))

    @classmethod
    def createSparse(cls, height, width, nnz, non_value=True,
                     trans=False, useGpu=False):
        """CSR sparse matrix filled by sparseCopyFrom
        (api Matrix::createSparse + sparseCopyFrom)."""
        return SparseMatrix(
            [[] for _ in range(height)], width,
            with_values=not non_value,
        )

    def copyToNumpyMat(self) -> np.ndarray:
        return np.array(self._a)

    toNumpyMat = copyToNumpyMat

    def toNumpyMatInplace(self) -> np.ndarray:
        """The live buffer — mutations write through (Paddle.i
        toNumpyMatInplace shared-memory view)."""
        return self._a

    def copyFromNumpyMat(self, a):
        np.copyto(self._a, np.asarray(a, np.float32).reshape(self._a.shape))

    def get(self, x, y):
        """Reference api Matrix::get addressing: flat offset
        x*width + y, bounds-checked on the flat index."""
        h, w = self._a.shape
        flat = x * w + y
        if x < 0 or y < 0 or flat >= h * w:
            raise RangeError(f"get({x}, {y}) out of {h}x{w}")
        return float(self._a[flat // w, flat % w])

    def set(self, x, y, v):
        h, w = self._a.shape
        flat = x * w + y
        if x < 0 or y < 0 or flat >= h * w:
            raise RangeError(f"set({x}, {y}) out of {h}x{w}")
        self._a[flat // w, flat % w] = v

    def isGpu(self):
        return False

    def getData(self):
        return self._a.ravel()

    def getHeight(self):
        return self._a.shape[0]

    def getWidth(self):
        return self._a.shape[1]

    def isSparse(self):
        return False


class SparseMatrix(Matrix):
    """Row-sparse host matrix (api/Paddle.i createSparse;
    Matrix::getSparseRowCols). Built from per-row column-index lists
    (binary) or (col, value) pair lists (float); densifies lazily for
    the dense Matrix surface."""

    def __init__(self, rows, width, with_values=False):
        self._rows = [list(r) for r in rows]
        self._w = int(width)
        self._with_values = with_values
        self._dense = None

    @property
    def _a(self):
        if self._dense is None:
            d = np.zeros((len(self._rows), self._w), np.float32)
            for i, row in enumerate(self._rows):
                for e in row:
                    if self._with_values:
                        d[i, int(e[0])] = float(e[1])
                    else:
                        d[i, int(e)] = 1.0
            self._dense = d
        return self._dense

    def isSparse(self):
        return True

    def getSparseValueType(self):
        return SPARSE_VALUE if self._with_values else SPARSE_NON_VALUE

    def getSparseFormat(self):
        return SPARSE_CSR

    def sparseCopyFrom(self, rows, cols, values=()):
        """CSR triples -> row lists (api Matrix::sparseCopyFrom:
        `rows` are per-row offsets into cols/values)."""
        new_rows = []
        for i in range(len(rows) - 1):
            b, e = int(rows[i]), int(rows[i + 1])
            if self._with_values:
                new_rows.append(
                    [(int(c), float(v))
                     for c, v in zip(cols[b:e], values[b:e])]
                )
            else:
                new_rows.append([int(c) for c in cols[b:e]])
        self._rows = new_rows
        self._dense = None

    def getSparseRowCols(self, i):
        if self._with_values:
            return [int(c) for c, _ in self._rows[i]]
        return [int(c) for c in self._rows[i]]

    def getSparseRowColsVal(self, i):
        if self._with_values:
            return [(int(c), float(v)) for c, v in self._rows[i]]
        return [(int(c), 1.0) for c in self._rows[i]]


class _VectorBase:
    _dtype = np.float32

    def __init__(self, array):
        self._a = np.asarray(array, self._dtype).ravel()

    @classmethod
    def createVectorFromNumpy(cls, a, copy=True):
        return cls(np.array(a, cls._dtype, copy=copy))

    @classmethod
    def createCpuVectorFromNumpy(cls, a, copy=True):
        """copy=False SHARES memory with the numpy array."""
        a = np.asarray(a)
        if not copy and a.dtype == cls._dtype and a.ndim == 1:
            v = cls.__new__(cls)
            v._a = a
            return v
        return cls(np.array(a, cls._dtype))

    @classmethod
    def createGpuVectorFromNumpy(cls, a):
        return cls(np.array(a, cls._dtype))

    @classmethod
    def create(cls, data, useGpu=False):
        try:
            return cls(np.asarray(data, cls._dtype))
        except TypeError:  # generator input
            return cls(np.asarray(list(data), cls._dtype))

    @classmethod
    def createZero(cls, n, useGpu=False):
        return cls(np.zeros(n, cls._dtype))

    def copyToNumpyArray(self) -> np.ndarray:
        return np.array(self._a)

    toNumpyArray = copyToNumpyArray

    def toNumpyArrayInplace(self) -> np.ndarray:
        return self._a

    def getData(self) -> list:
        return self._a.tolist()

    def isGpu(self):
        return False

    def __getitem__(self, i):
        if i < 0 or i >= self._a.size:
            raise RangeError(f"index {i} out of {self._a.size}")
        v = self._a[i]
        return int(v) if self._dtype == np.int32 else float(v)

    def __setitem__(self, i, v):
        if i < 0 or i >= self._a.size:
            raise RangeError(f"index {i} out of {self._a.size}")
        self._a[i] = v

    def __iter__(self):
        return iter(self.getData())

    def __len__(self):
        return int(self._a.size)

    def copyFrom(self, other):
        self._a = np.array(other._a if isinstance(other, _VectorBase)
                           else other, self._dtype).ravel()

    def copyFromNumpyArray(self, a):
        self.copyFrom(np.asarray(a))


class Vector(_VectorBase):
    _dtype = np.float32


class IVector(_VectorBase):
    _dtype = np.int32


class Arguments:
    """Slot-indexed in/out arguments (api/PaddleAPI.h:244 Arguments,
    parameter/Argument.h:29). A slot is a dense Matrix, an id IVector,
    or a prepared paddle_tpu Arg (what DataProviderConverter emits);
    sequence slots carry start positions exactly like the reference
    (Argument.sequenceStartPositions)."""

    def __init__(self, n_slots: int = 0):
        self._slots = [dict() for _ in range(n_slots)]

    @classmethod
    def createArguments(cls, n):
        return cls(n)

    def resize(self, n):
        while len(self._slots) < n:
            self._slots.append({})
        del self._slots[n:]

    def getSlotNum(self):
        return len(self._slots)

    def _slot(self, i):
        if i >= len(self._slots):
            self.resize(i + 1)
        return self._slots[i]

    # --- setters ---
    def setSlotValue(self, i, m: Matrix):
        self._slot(i)["value"] = m

    def setSlotIds(self, i, v: IVector):
        self._slot(i)["ids"] = v

    def setSlotSequenceStartPositions(self, i, v: IVector):
        self._slot(i)["seq_starts"] = v

    def setSlotSubSequenceStartPositions(self, i, v: IVector):
        self._slot(i)["subseq_starts"] = v

    def setSlotFrameHeight(self, i, h: int):
        self._slot(i)["frame_h"] = int(h)

    def setSlotFrameWidth(self, i, w: int):
        self._slot(i)["frame_w"] = int(w)

    def getSlotFrameHeight(self, i=0) -> int:
        return self._slots[i].get("frame_h", 0)

    def getSlotFrameWidth(self, i=0) -> int:
        return self._slots[i].get("frame_w", 0)

    def _setSlotArg(self, i, arg: Arg):
        self._slot(i)["arg"] = arg

    # --- getters ---
    def getSlotValue(self, i) -> Matrix:
        s = self._slots[i]
        if "value" in s:
            return s["value"]
        return Matrix(_flatten_arg_value(s["arg"]))

    def getSlotIds(self, i) -> IVector:
        s = self._slots[i]
        if "ids" in s:
            return s["ids"]
        return IVector(_flatten_arg_ids(s["arg"]))

    def getSlotSequenceStartPositions(self, i) -> IVector:
        s = self._slots[i]
        if "seq_starts" in s:
            return s["seq_starts"]
        a = s["arg"]
        lens = np.asarray(a.seq_lens)
        return IVector(np.concatenate([[0], np.cumsum(lens)]))

    def sum(self) -> float:
        """Total of slot 0's values (api Arguments::sum — the cost
        accumulator the v2 loop divides by batch size)."""
        return float(np.sum(self.getSlotValue(0).copyToNumpyMat()))

    # --- feed conversion (internal) ---
    def _to_arg(self, i) -> Arg:
        s = self._slots[i]
        if "arg" in s:
            return s["arg"]
        starts = s.get("seq_starts")
        if "ids" in s:
            ids = s["ids"].copyToNumpyArray()
            if starts is None:
                return Arg(ids=ids)
            out, lens = pad_ragged(ids, starts.copyToNumpyArray())
            return Arg(ids=out, seq_lens=lens)
        v = s["value"].copyToNumpyMat()
        if starts is None:
            return Arg(value=v)
        out, lens = pad_ragged(v, starts.copyToNumpyArray())
        return Arg(value=out, seq_lens=lens)

    def _feed(self, names) -> dict:
        if len(names) < len(self._slots):
            raise ValueError(
                f"{len(self._slots)} slots fed but the network declares "
                f"only data layers {names}"
            )
        return {
            name: self._to_arg(i)
            for i, name in enumerate(names[: len(self._slots)])
        }


def _flatten_arg_value(a: Arg) -> np.ndarray:
    v = np.asarray(a.value)
    if a.seq_lens is None:
        return v.reshape(v.shape[0], -1)
    # sequence output: the reference layout is the padding-free
    # [sum(T_i), D] stack (Argument.h:84)
    lens = np.asarray(a.seq_lens)
    rows = [v[i, : lens[i]].reshape(lens[i], -1) for i in range(len(lens))]
    return np.concatenate(rows, axis=0) if rows else v.reshape(0, -1)


def _flatten_arg_ids(a: Arg) -> np.ndarray:
    ids = np.asarray(a.ids)
    if a.seq_lens is None or ids.ndim == 1:
        return ids.ravel()
    lens = np.asarray(a.seq_lens)
    return np.concatenate([ids[i, : lens[i]] for i in range(len(lens))])


class ParameterBuffer(Vector):
    """A live view of one parameter buffer (api Vector over
    Parameter::getBuf). copyFrom writes THROUGH to the owning machine —
    the GAN driver's copy_shared_parameters depends on that.
    toNumpyArrayInplace returns a registered host mirror whose
    mutations the machine syncs back before the next program run (the
    testTrain init_params idiom: mutate the inplace view, then
    forward)."""

    def __init__(self, gm: "GradientMachine", name: str, kind: int):
        self._gm = gm
        self._name = name
        self._kind = kind

    def _read(self) -> np.ndarray:
        if self._kind == PARAMETER_GRADIENT:
            g = self._gm._grads.get(self._name)
            return np.zeros(self._len(), np.float32) if g is None \
                else np.asarray(g).ravel()
        view = self._gm._inplace_views.get(self._name)
        if view is not None:
            return view
        return np.asarray(self._gm.params[self._name]).ravel()

    @property
    def _a(self) -> np.ndarray:  # the Vector surface reads live
        return self._read()

    def _len(self):
        return int(np.prod(self._gm.net.param_confs[self._name].dims))

    def __len__(self):
        return self._len()

    def copyToNumpyArray(self):
        return np.array(self._read(), np.float32)

    def toNumpyArrayInplace(self) -> np.ndarray:
        if self._kind != PARAMETER_VALUE:
            return self._read()
        views = self._gm._inplace_views
        if self._name not in views:
            views[self._name] = np.array(
                np.asarray(self._gm.params[self._name]).ravel(),
                np.float32,
            )
        return views[self._name]

    def __setitem__(self, i, v):
        if self._kind != PARAMETER_VALUE:
            raise ValueError("only PARAMETER_VALUE buffers are writable")
        if i < 0 or i >= self._len():
            raise RangeError(f"index {i} out of {self._len()}")
        self.toNumpyArrayInplace()[i] = v

    def copyFrom(self, other):
        src = other._read() if isinstance(other, ParameterBuffer) else (
            other._a if isinstance(other, _VectorBase) else np.asarray(other)
        )
        if self._kind != PARAMETER_VALUE:
            raise ValueError("only PARAMETER_VALUE buffers are writable")
        shape = self._gm.params[self._name].shape
        self._gm.params[self._name] = jax.numpy.asarray(
            np.asarray(src, np.float32).reshape(shape)
        )
        self._gm._refresh_views(self._name)

    def copyFromNumpyArray(self, a):
        self.copyFrom(np.asarray(a, np.float32))


class _ParamConfView:
    """What Parameter.getConfig() returns: the ParameterConf plus the
    proto-shim bridge (api Parameter::getConfig ->
    ParameterConfig.toProto; dims follow the reference's (1, n)
    convention for vector parameters)."""

    def __init__(self, pc):
        self._pc = pc

    def __getattr__(self, name):
        return getattr(self._pc, name)

    def toProto(self):
        from paddle.proto.ParameterConfig_pb2 import ParameterConfig

        dims = tuple(int(d) for d in self._pc.dims)
        if len(dims) == 1:
            dims = (1, dims[0])
        size = 1
        for d in dims:
            size *= d
        return ParameterConfig(
            name=self._pc.name, size=size, dims=list(dims),
            learning_rate=self._pc.learning_rate,
            is_static=self._pc.is_static,
            sparse_update=self._pc.sparse_update,
        )


class Parameter:
    def __init__(self, gm: "GradientMachine", name: str):
        self._gm = gm
        self._name = name

    def getName(self):
        return self._name

    def getID(self):
        """Position in the machine's parameter order (api
        Parameter::getID)."""
        return self._gm._param_names.index(self._name)

    def getSize(self):
        return int(np.prod(self._gm.net.param_confs[self._name].dims))

    def getBuf(self, kind):
        return ParameterBuffer(self._gm, self._name, kind)

    def getBufs(self):
        """(value, gradient) buffers — what the api update callback
        hands the optimizer (Parameter::getBufs)."""
        return (
            ParameterBuffer(self._gm, self._name, PARAMETER_VALUE),
            ParameterBuffer(self._gm, self._name, PARAMETER_GRADIENT),
        )

    def save(self, filename) -> bool:
        """Write the reference raw binary format
        (Parameter::save)."""
        from paddle_tpu.trainer.checkpoint import save_parameter_file

        self._gm._sync_views()
        save_parameter_file(
            filename, np.asarray(self._gm.params[self._name])
        )
        return True

    def load(self, filename) -> bool:
        """Read the reference raw binary format (Parameter::load)."""
        from paddle_tpu.trainer.checkpoint import load_parameter_file

        shape = self._gm.params[self._name].shape
        self._gm.params[self._name] = jax.numpy.asarray(
            load_parameter_file(filename, shape)
        )
        self._gm._refresh_views(self._name)
        return True

    def setValueUpdated(self):
        pass  # device copy already happened in ParameterBuffer.copyFrom

    def __len__(self):
        return self.getSize()

    def getConfig(self):
        return _ParamConfView(self._gm.net.param_confs[self._name])


class Evaluator:
    """api.Evaluator over the machine's implied metric set: the
    reference auto-attaches classification_error to every
    classification_cost (trainer_config_helpers layers.py
    classification_cost's evaluator default); eval() accumulates from
    the machine's last forward."""

    def __init__(self, confs):
        from paddle_tpu.evaluators import create_evaluator

        self._evals = [create_evaluator(c) for c in confs]
        self._started = False

    def start(self):
        for ev in self._evals:
            ev.start()
        self._started = True

    def finish(self):
        self._started = False

    def _add(self, outs, feed):
        for ev in self._evals:
            ev.add_batch(outs, feed)

    def getNames(self):
        return [ev.name for ev in self._evals]

    def getValue(self, name):
        for ev in self._evals:
            if ev.name == name:
                return ev.result()
        raise KeyError(name)

    def __repr__(self):
        return " ".join(
            f"{ev.name}={ev.result()}" for ev in self._evals
        ) or "<no evaluators>"


class GradientMachine:
    """api/PaddleAPI.h:402 GradientMachine over a jitted Network.

    seed=None defers to the global 'seed' flag (whose 0 means a fresh
    OS-entropy seed); an explicit seed — including 0 — is honored
    exactly and governs BOTH parameter init and the dropout rng."""

    def __init__(self, conf, seed: int | None = None):
        self.conf = conf
        if seed is not None:
            root = jax.random.PRNGKey(seed)
        else:
            # flag semantics: 0 = nondeterministic (core/flags.py)
            root = _rng.root_key(_flags.get_flag("seed"))
        init_key, self._rng_key = jax.random.split(root)
        self.net = Network(conf)
        self.params = self.net.init_params(init_key)
        self.state = self.net.init_state()
        self._grads: dict = {}
        self._last_rng = None  # rng of the latest forward (backward reuses)
        self._inplace_views: dict = {}  # name -> mutable host mirror
        self._param_names = sorted(self.net.param_confs)
        self._fwd_cache: dict = {}
        self._last = None  # (outs, feed) of the latest forward
        self._rng_step = 0
        # implied evaluators (classification_error per classification
        # cost), what the reference's makeEvaluator materializes
        self._eval_confs = []
        for lc in conf.layers:
            if lc.type == "classification_cost" and len(lc.inputs) >= 2:
                self._eval_confs.append({
                    "type": "classification_error",
                    "name": "classification_error",
                    "input": lc.inputs[0].name,
                    "label": lc.inputs[1].name,
                })
        self._keep = set(self.net.output_names) | {
            c["input"] for c in self._eval_confs
        }

    def _sync_views(self):
        """Flush registered toNumpyArrayInplace mirrors into params
        (mutate-then-run semantics of the inplace api)."""
        for name, v in self._inplace_views.items():
            shape = self.params[name].shape
            self.params[name] = jax.numpy.asarray(
                np.asarray(v, np.float32).reshape(shape)
            )

    def _refresh_views(self, name=None):
        """After params change OUTSIDE the mirrors (training step,
        load, copyFrom), copy the fresh values INTO any registered
        mirrors so user-held inplace arrays stay live (the reference's
        inplace view IS the parameter memory)."""
        names = [name] if name is not None else list(self._inplace_views)
        for n in names:
            v = self._inplace_views.get(n)
            if v is not None:
                np.copyto(v, np.asarray(self.params[n]).ravel())

    def makeEvaluator(self) -> Evaluator:
        return Evaluator(self._eval_confs)

    def eval(self, evaluator: Evaluator):
        assert self._last is not None, "eval() before any forward"
        evaluator._add(*self._last)

    @classmethod
    def createFromConfigProto(cls, conf, mode=CREATE_MODE_NORMAL,
                              enable_types=None):
        return cls(conf)

    # api GradientMachine::createByModelConfig — same constructor, the
    # mode/parameter-type hints are the reference's buffer plumbing
    createByModelConfig = None  # bound after class body

    # --- parameters ---
    def getParameterSize(self):
        return len(self._param_names)

    def getParameter(self, i: int) -> Parameter:
        return Parameter(self, self._param_names[i])

    def getParameters(self):
        return [Parameter(self, n) for n in self._param_names]

    def getParameterNames(self):
        return list(self._param_names)

    def getNonStaticParameters(self):
        return [
            Parameter(self, n)
            for n in self._param_names
            if not getattr(self.net.param_confs[n], "is_static", False)
        ]

    def randParameters(self, seed: int = 0):
        self.params = self.net.init_params(jax.random.PRNGKey(seed))

    def loadParameters(self, path: str):
        """Load from a paddle_tpu checkpoint: a save_dir with pass-*
        subdirs, one pass dir, or a merged model file
        (trainer/ParamUtil.h:77-93 loadParameters)."""
        from paddle_tpu.trainer import checkpoint as ckpt

        if os.path.isfile(path):
            _, params, state = ckpt.load_merged(path)
        elif any(n.startswith("pass-") for n in os.listdir(path)):
            # a save_dir of pass-* checkpoints: latest wins
            params, _, state, _ = ckpt.load_pass(path, -1)
        elif os.path.exists(os.path.join(path, "params.npz")):
            # a single pass-XXXXX dir given directly
            parent, leaf = os.path.split(path.rstrip("/"))
            params, _, state, _ = ckpt.load_pass(
                parent, int(leaf.split("-")[1])
            )
        else:
            raise FileNotFoundError(
                f"no checkpoint (pass-* dir or merged file) at {path!r}"
            )
        self.params = {k: jax.numpy.asarray(v) for k, v in params.items()}
        if state:
            self.state = jax.tree_util.tree_map(jax.numpy.asarray, state)

    # --- execution ---
    def _fwd(self, train: bool):
        key = ("fwd", train)
        if key not in self._fwd_cache:
            keep = self._keep

            def fwd(params, state, feed, rng):
                outs, new_state = self.net.forward(
                    params, feed, state=state, train=train, rng=rng
                )
                return (
                    {n: outs[n] for n in keep if n in outs},
                    new_state,
                )

            self._fwd_cache[key] = jax.jit(fwd)
        return self._fwd_cache[key]

    def _next_rng(self):
        self._rng_step += 1
        self._last_rng = _rng.split_for_step(
            self._rng_key, self._rng_step
        )
        return self._last_rng

    def forward(self, inArgs: Arguments, outArgs: Arguments, passType=None):
        self._sync_views()
        train = passType == PASS_TRAIN
        feed = inArgs._feed(self.net.input_names)
        outs, new_state = self._fwd(train)(
            self.params, self.state, feed, self._next_rng()
        )
        if train:
            # train-mode forward advances batch-norm running stats,
            # exactly like the reference GradientMachine
            self.state = new_state
        self._last = (outs, feed)
        outArgs.resize(len(self.net.output_names))
        for i, n in enumerate(self.net.output_names):
            a = outs[n]
            if a.ids is not None and a.value is None:
                outArgs.setSlotIds(i, IVector(_flatten_arg_ids(a)))
            else:
                outArgs.setSlotValue(i, Matrix(_flatten_arg_value(a)))
            outArgs._slot(i)["arg"] = a

    def forwardTest(self, inArgs: Arguments):
        self._sync_views()
        """Reference api: returns [{'id': ids, 'value': values}] per
        output layer (py_paddle util swig_paddle.py forwardTest)."""
        feed = inArgs._feed(self.net.input_names)
        outs, _ = self._fwd(False)(
            self.params, self.state, feed, self._next_rng()
        )
        self._last = (outs, feed)
        res = []
        for n in self.net.output_names:
            a = outs[n]
            d = {}
            if a.value is not None:
                v = _flatten_arg_value(a)
                d["value"] = v
                d["id"] = np.argmax(v, axis=-1)
            if a.ids is not None:
                d["id"] = _flatten_arg_ids(a)
            res.append(d)
        return res

    def backward(self, callback=None):
        """Gradient pass over the LAST forward's batch, then the
        per-parameter UpdateCallback (GradientMachine.h:72 backward;
        the api test drives forward + backward separately)."""
        assert self._last is not None, "backward() before forward()"
        self._sync_views()
        _, feed = self._last
        if "grad_only" not in self._fwd_cache:

            def go(params, state, feed, rng):
                (loss, (outs, new_state)), grads = jax.value_and_grad(
                    self.net.loss_fn, has_aux=True
                )(params, feed, state=state, train=True, rng=rng)
                return loss, grads

            self._fwd_cache["grad_only"] = jax.jit(go)
        _, grads = self._fwd_cache["grad_only"](
            self.params, self.state, feed,
            # the rng the preceding forward used — gradients must
            # belong to the activations the caller saw (same dropout
            # masks), as the reference backprops stored activations
            self._last_rng if self._last_rng is not None
            else self._next_rng(),
        )
        self._grads = grads
        if callback is not None:
            for n in self._param_names:
                callback(Parameter(self, n))

    def forwardBackward(self, inArgs: Arguments, outArgs: Arguments,
                        passType=None, callback=None):
        self._sync_views()
        feed = inArgs._feed(self.net.input_names)
        if "grad" not in self._fwd_cache:
            keep = self._keep

            def fb(params, state, feed, rng):
                (loss, (outs, new_state)), grads = jax.value_and_grad(
                    self.net.loss_fn, has_aux=True
                )(params, feed, state=state, train=True, rng=rng)
                return loss, grads, {
                    n: outs[n] for n in keep if n in outs
                }, new_state

            self._fwd_cache["grad"] = jax.jit(fb)
        loss, grads, outs, new_state = self._fwd_cache["grad"](
            self.params, self.state, feed, self._next_rng()
        )
        self._grads = grads
        self.state = new_state
        self._last = (outs, feed)
        outArgs.resize(len(self.net.output_names))
        for i, n in enumerate(self.net.output_names):
            outArgs.setSlotValue(i, Matrix(_flatten_arg_value(outs[n])))
        if callback is not None:
            # the per-parameter UpdateCallback (GradientMachine.h:72
            # backward(callback)): invoked once per parameter after
            # its gradient exists
            for n in self._param_names:
                callback(Parameter(self, n))
        return float(loss)

    def start(self):
        pass

    def finish(self):
        pass


class ParameterUpdater:
    """api/ParameterUpdater.cpp local updater: init(gm), then per batch
    startBatch -> (gm.forwardBackward) -> update(param)* ->
    finishBatch. The per-parameter update() calls mark parameters; the
    sharded optimizer applies once all marked (identical observable
    result, one fused XLA program). apply/restore/catchUpWith are the
    parameter-averaging window hooks (ThreadParameterUpdater.h:71)."""

    def __init__(self, opt_conf):
        self._opt_conf = opt_conf
        self._gm = None
        self.global_step = 0

    @classmethod
    def createLocalUpdater(cls, opt_conf):
        return cls(opt_conf)

    def init(self, gradient_machine: "GradientMachine"):
        self._gm = gradient_machine
        self._opt = create_optimizer(
            self._opt_conf, gradient_machine.net.param_confs
        )
        self._opt_state = self._opt.init_state(gradient_machine.params)
        self._marked = set()
        self._apply_fn = jax.jit(
            lambda g, p, s, i: self._opt.update(g, p, s, i)
        )

    def startPass(self):
        pass

    def finishPass(self):
        pass

    def startBatch(self, batch_size: int):
        self._marked = set()
        return PASS_TRAIN

    def update(self, param: "Parameter"):
        self._marked.add(param.getName())

    def finishBatch(self, cost: float = 0.0):
        gm = self._gm
        if self._marked and gm._grads:
            # unmarked parameters are simply absent from the grads dict:
            # the optimizer leaves them (and their momentum/decay/LR
            # state) untouched, matching the reference local updater
            # for drivers that skip update() on frozen params
            grads = {
                k: v for k, v in gm._grads.items() if k in self._marked
            }
            gm.params, self._opt_state = self._apply_fn(
                grads, gm.params, self._opt_state, self.global_step
            )
            self.global_step += 1

    # parameter-averaging window hooks — the averager is folded into
    # the optimizer state here; the explicit swap is a no-op
    def apply(self):
        pass

    def restore(self):
        pass

    def catchUpWith(self):
        pass

    def getParametersRemote(self, *a, **k):
        pass


class Trainer:
    """api/PaddleAPI.h Trainer: the startTrain/startTrainPass/
    trainOneDataBatch loop over a TrainerConfig + GradientMachine
    (trainer/Trainer.cpp:261 semantics)."""

    def __init__(self, config, gm: GradientMachine):
        self.config = config
        self.gm = gm
        self.opt = create_optimizer(config.opt, gm.net.param_confs)
        self.opt_state = self.opt.init_state(gm.params)
        self.step_fn = TrainStep(gm.net, self.opt)
        self.global_step = 0
        self._pass = 0
        self._batch = 0
        self._test_costs: list = []
        self._key = _rng.root_key(_flags.get_flag("seed"))

    @classmethod
    def create(cls, config, gm) -> "Trainer":
        return cls(config, gm)

    @classmethod
    def createByCommandLine(cls):
        raise NotImplementedError(
            "use Trainer.create(config, gradient_machine)"
        )

    def startTrain(self):
        pass

    def finishTrain(self):
        pass

    def startTrainPass(self):
        self._batch = 0

    def finishTrainPass(self):
        log.info("pass %d finished (%d batches)", self._pass, self._batch)
        self._pass += 1

    def trainOneDataBatch(self, size: int, args: Arguments):
        feed = args._feed(self.gm.net.input_names)
        rng = _rng.split_for_step(self._key, self.global_step)
        (
            self.gm.params,
            self.opt_state,
            self.gm.state,
            loss,
            _,
        ) = self.step_fn(
            self.gm.params, self.opt_state, self.gm.state, feed,
            self.global_step, rng,
        )
        self.global_step += 1
        self.gm._refresh_views()  # keep user-held inplace arrays live
        self._batch += 1
        self._last_cost = float(loss)
        self._last_outs = [
            {"value": np.asarray([self._last_cost * size])}
        ]
        if self._batch % _flags.get_flag("log_period") == 0:
            log.info("pass %d batch %d cost %.5f",
                     self._pass, self._batch, self._last_cost)
        return self._last_cost

    def getForwardOutput(self):
        """Latest forward outputs as [{'value': ndarray}] — the
        reference returns the out-args' value matrices; the train/test
        batch paths record the cost output (api Trainer::
        getForwardOutput)."""
        return getattr(self, "_last_outs", [])

    # --- test period (api Trainer::startTestPeriod) ---
    def startTestPeriod(self):
        self._test_costs = []

    def testOneDataBatch(self, size: int, args: Arguments):
        out = Arguments.createArguments(0)
        self.gm.forward(args, out, PASS_TEST)
        self._last_outs = [
            {"value": out.getSlotValue(i).copyToNumpyMat().ravel()}
            for i in range(out.getSlotNum())
        ]
        self._test_costs.append(out.sum() / max(size, 1))
        return self._test_costs[-1]

    def finishTestPeriod(self):
        if self._test_costs:
            log.info("test cost %.5f", float(np.mean(self._test_costs)))


# ---- raw-api config / optimizer surface (testGradientMachine.py,
#      testTrain.py, testTrainer.py) ---------------------------------


class TrainerConfig:
    """api TrainerConfig (api/Trainer.cpp createFromTrainerConfigFile):
    parse a config file, expose the model/optimization halves."""

    def __init__(self, tc):
        self._tc = tc

    @classmethod
    def createFromTrainerConfigFile(cls, path):
        from paddle_tpu.compat.config_parser import parse_config

        return cls(parse_config(path))

    def getModelConfig(self):
        return self._tc.model_config

    def getOptimizationConfig(self):
        return self._tc.opt_config

    def __getattr__(self, name):
        return getattr(self._tc, name)


class OptimizationConfig:
    """api OptimizationConfig — a pass-through over the framework's
    OptimizationConf (createFromProto accepts it directly)."""

    @staticmethod
    def createFromProto(opt_conf):
        return opt_conf


class ParameterOptimizer:
    """The api-level LOCAL optimizer the raw training loop drives per
    parameter (api/ParameterOptimizer.cpp: create/init/startPass/
    startBatch/update(bufs, config)/finishBatch/finishPass). Applies
    the config's learning rate as a plain first-order step on the
    (value, gradient) buffers — the in-place equivalent of the
    reference's per-parameter optimizer chain."""

    def __init__(self, opt_conf):
        self.conf = opt_conf

    @classmethod
    def create(cls, opt_conf):
        return cls(opt_conf)

    def getParameterTypes(self):
        return [PARAMETER_VALUE, PARAMETER_GRADIENT]

    def init(self, num_rows, param_config):
        pass

    def startPass(self):
        pass

    def finishPass(self):
        pass

    def startBatch(self, batch_size):
        self._batch_size = batch_size

    def finishBatch(self):
        pass

    def update(self, vecs, param_config, sparse_id=NO_SPARSE_ID):
        value, grad = vecs[0], vecs[1]
        lr = float(getattr(self.conf, "learning_rate", 0.01)) * float(
            getattr(param_config, "learning_rate", 1.0)
        )
        value.copyFrom(
            value.copyToNumpyArray() - lr * grad.copyToNumpyArray()
        )

    def needSpecialTraversal(self, param_config):
        return None


GradientMachine.createByModelConfig = classmethod(
    lambda cls, conf, mode=CREATE_MODE_NORMAL, enable_types=None:
    cls(conf)
)
