"""The `py_paddle.swig_paddle` API surface, TPU-native.

Reference: paddle/api/PaddleAPI.h:103,244,402 + paddle/api/Paddle.i
(the SWIG module the reference's API-driven demo drivers import:
v1_api_demo/quick_start/api_train.py:17, gan/gan_trainer.py:24,
vae/vae_train.py:24). Slot-indexed Arguments of Matrix/IVector wrap
numpy; GradientMachine/Trainer execute as jit-compiled paddle_tpu
Network/TrainStep programs instead of the C++ gserver stack.

Covered (what the four reference drivers exercise): initPaddle,
Matrix/Vector/IVector numpy bridges, Arguments with value/id slots and
sequence start positions, GradientMachine.createFromConfigProto /
forward / forwardTest / forwardBackward / parameter handles with
PARAMETER_VALUE buffers (copyFrom/copyToNumpyArray — the GAN's
copy_shared_parameters), loadParameters/randParameters, and
Trainer.create with the startTrain/startTrainPass/trainOneDataBatch/
finishTrainPass/startTestPeriod/testOneDataBatch/finishTestPeriod
loop.
"""

from __future__ import annotations

import logging
import os

import jax
import numpy as np

from paddle_tpu.core import flags as _flags
from paddle_tpu.core import rng as _rng
from paddle_tpu.core.arg import Arg, pad_ragged
from paddle_tpu.network import Network
from paddle_tpu.optimizers import create_optimizer
from paddle_tpu.parallel.dp import TrainStep

log = logging.getLogger("paddle_tpu.api")

# --- constants (api/PaddleAPI.h enums) ---
PARAMETER_VALUE = 0
PARAMETER_GRADIENT = 1
PARAMETER_MOMENTUM = 2
PASS_TRAIN = 0
PASS_TEST = 1
PASS_GC = 2
CREATE_MODE_NORMAL = 0
CREATE_MODE_SGD_SPARSE_CPU_TRAINING = 3
NO_SPARSE_ID = -1


def initPaddle(*args):
    """api.initPaddle('--use_gpu=0', ...) — gflags-style strings
    (api/Paddle.i initPaddle). Flags with a paddle_tpu equivalent are
    applied; device-model-specific ones are accepted and ignored."""
    mapped = {
        "seed": ("seed", int),
        "log_period": ("log_period", int),
        "show_parameter_stats_period": ("show_parameter_stats_period", int),
        "beam_size": ("beam_size", int),
        "start_pass": ("start_pass", int),
    }
    for a in args:
        if not a.startswith("--"):
            continue
        k, _, v = a[2:].partition("=")
        if k in mapped:
            name, cast = mapped[k]
            _flags.set_flag(name, cast(v))


def isGpuVersion() -> bool:
    """api.isGpuVersion — whether a CUDA build is running. This build
    targets TPU via XLA; the GPU-specific re-run paths reference tests
    gate on this (test_data_feeder.py main) don't apply."""
    return False


def setUseGpu(flag: bool) -> None:
    """api.setUseGpu — accepted for parity; device placement is XLA's
    (the axon TPU backend is used whenever present)."""


def _as2d(a: np.ndarray) -> np.ndarray:
    a = np.asarray(a)
    return a.reshape(a.shape[0], -1) if a.ndim != 2 else a


class Matrix:
    """Dense host matrix (api/PaddleAPI.h:103 Matrix; numpy bridge
    api/Paddle.i:142-165)."""

    def __init__(self, array):
        self._a = _as2d(np.asarray(array, np.float32))

    @classmethod
    def createDenseFromNumpy(cls, a, copy=True):
        return cls(np.array(a, np.float32, copy=copy))

    @classmethod
    def createDense(cls, data, height, width):
        return cls(np.asarray(data, np.float32).reshape(height, width))

    @classmethod
    def createZero(cls, height, width):
        return cls(np.zeros((height, width), np.float32))

    def copyToNumpyMat(self) -> np.ndarray:
        return np.array(self._a)

    toNumpyMat = copyToNumpyMat

    def getData(self):
        return self._a.ravel()

    def getHeight(self):
        return self._a.shape[0]

    def getWidth(self):
        return self._a.shape[1]

    def isSparse(self):
        return False


class SparseMatrix(Matrix):
    """Row-sparse host matrix (api/Paddle.i createSparse;
    Matrix::getSparseRowCols). Built from per-row column-index lists
    (binary) or (col, value) pair lists (float); densifies lazily for
    the dense Matrix surface."""

    def __init__(self, rows, width, with_values=False):
        self._rows = [list(r) for r in rows]
        self._w = int(width)
        self._with_values = with_values
        self._dense = None

    @property
    def _a(self):
        if self._dense is None:
            d = np.zeros((len(self._rows), self._w), np.float32)
            for i, row in enumerate(self._rows):
                for e in row:
                    if self._with_values:
                        d[i, int(e[0])] = float(e[1])
                    else:
                        d[i, int(e)] = 1.0
            self._dense = d
        return self._dense

    def isSparse(self):
        return True

    def getSparseRowCols(self, i):
        if self._with_values:
            return [int(c) for c, _ in self._rows[i]]
        return [int(c) for c in self._rows[i]]

    def getSparseRowColsVal(self, i):
        if self._with_values:
            return [(int(c), float(v)) for c, v in self._rows[i]]
        return [(int(c), 1.0) for c in self._rows[i]]


class _VectorBase:
    _dtype = np.float32

    def __init__(self, array):
        self._a = np.asarray(array, self._dtype).ravel()

    @classmethod
    def createVectorFromNumpy(cls, a, copy=True):
        return cls(np.array(a, cls._dtype, copy=copy))

    @classmethod
    def create(cls, data):
        return cls(np.asarray(data, cls._dtype))

    @classmethod
    def createZero(cls, n):
        return cls(np.zeros(n, cls._dtype))

    def copyToNumpyArray(self) -> np.ndarray:
        return np.array(self._a)

    toNumpyArray = copyToNumpyArray

    def __len__(self):
        return int(self._a.size)

    def copyFrom(self, other):
        self._a = np.array(other._a if isinstance(other, _VectorBase)
                           else other, self._dtype).ravel()


class Vector(_VectorBase):
    _dtype = np.float32


class IVector(_VectorBase):
    _dtype = np.int32


class Arguments:
    """Slot-indexed in/out arguments (api/PaddleAPI.h:244 Arguments,
    parameter/Argument.h:29). A slot is a dense Matrix, an id IVector,
    or a prepared paddle_tpu Arg (what DataProviderConverter emits);
    sequence slots carry start positions exactly like the reference
    (Argument.sequenceStartPositions)."""

    def __init__(self, n_slots: int = 0):
        self._slots = [dict() for _ in range(n_slots)]

    @classmethod
    def createArguments(cls, n):
        return cls(n)

    def resize(self, n):
        while len(self._slots) < n:
            self._slots.append({})
        del self._slots[n:]

    def getSlotNum(self):
        return len(self._slots)

    def _slot(self, i):
        if i >= len(self._slots):
            self.resize(i + 1)
        return self._slots[i]

    # --- setters ---
    def setSlotValue(self, i, m: Matrix):
        self._slot(i)["value"] = m

    def setSlotIds(self, i, v: IVector):
        self._slot(i)["ids"] = v

    def setSlotSequenceStartPositions(self, i, v: IVector):
        self._slot(i)["seq_starts"] = v

    def setSlotSubSequenceStartPositions(self, i, v: IVector):
        self._slot(i)["subseq_starts"] = v

    def setSlotFrameHeight(self, i, h: int):
        self._slot(i)["frame_h"] = int(h)

    def setSlotFrameWidth(self, i, w: int):
        self._slot(i)["frame_w"] = int(w)

    def getSlotFrameHeight(self, i) -> int:
        return self._slots[i].get("frame_h", 0)

    def getSlotFrameWidth(self, i) -> int:
        return self._slots[i].get("frame_w", 0)

    def _setSlotArg(self, i, arg: Arg):
        self._slot(i)["arg"] = arg

    # --- getters ---
    def getSlotValue(self, i) -> Matrix:
        s = self._slots[i]
        if "value" in s:
            return s["value"]
        return Matrix(_flatten_arg_value(s["arg"]))

    def getSlotIds(self, i) -> IVector:
        s = self._slots[i]
        if "ids" in s:
            return s["ids"]
        return IVector(_flatten_arg_ids(s["arg"]))

    def getSlotSequenceStartPositions(self, i) -> IVector:
        s = self._slots[i]
        if "seq_starts" in s:
            return s["seq_starts"]
        a = s["arg"]
        lens = np.asarray(a.seq_lens)
        return IVector(np.concatenate([[0], np.cumsum(lens)]))

    def sum(self) -> float:
        """Total of slot 0's values (api Arguments::sum — the cost
        accumulator the v2 loop divides by batch size)."""
        return float(np.sum(self.getSlotValue(0).copyToNumpyMat()))

    # --- feed conversion (internal) ---
    def _to_arg(self, i) -> Arg:
        s = self._slots[i]
        if "arg" in s:
            return s["arg"]
        starts = s.get("seq_starts")
        if "ids" in s:
            ids = s["ids"].copyToNumpyArray()
            if starts is None:
                return Arg(ids=ids)
            out, lens = pad_ragged(ids, starts.copyToNumpyArray())
            return Arg(ids=out, seq_lens=lens)
        v = s["value"].copyToNumpyMat()
        if starts is None:
            return Arg(value=v)
        out, lens = pad_ragged(v, starts.copyToNumpyArray())
        return Arg(value=out, seq_lens=lens)

    def _feed(self, names) -> dict:
        if len(names) < len(self._slots):
            raise ValueError(
                f"{len(self._slots)} slots fed but the network declares "
                f"only data layers {names}"
            )
        return {
            name: self._to_arg(i)
            for i, name in enumerate(names[: len(self._slots)])
        }


def _flatten_arg_value(a: Arg) -> np.ndarray:
    v = np.asarray(a.value)
    if a.seq_lens is None:
        return v.reshape(v.shape[0], -1)
    # sequence output: the reference layout is the padding-free
    # [sum(T_i), D] stack (Argument.h:84)
    lens = np.asarray(a.seq_lens)
    rows = [v[i, : lens[i]].reshape(lens[i], -1) for i in range(len(lens))]
    return np.concatenate(rows, axis=0) if rows else v.reshape(0, -1)


def _flatten_arg_ids(a: Arg) -> np.ndarray:
    ids = np.asarray(a.ids)
    if a.seq_lens is None or ids.ndim == 1:
        return ids.ravel()
    lens = np.asarray(a.seq_lens)
    return np.concatenate([ids[i, : lens[i]] for i in range(len(lens))])


class ParameterBuffer:
    """A live view of one parameter buffer (api Vector over
    Parameter::getBuf). copyFrom writes THROUGH to the owning machine —
    the GAN driver's copy_shared_parameters depends on that."""

    def __init__(self, gm: "GradientMachine", name: str, kind: int):
        self._gm = gm
        self._name = name
        self._kind = kind

    def _read(self) -> np.ndarray:
        if self._kind == PARAMETER_GRADIENT:
            g = self._gm._grads.get(self._name)
            return np.zeros(self._len(), np.float32) if g is None \
                else np.asarray(g).ravel()
        return np.asarray(self._gm.params[self._name]).ravel()

    def _len(self):
        return int(np.prod(self._gm.net.param_confs[self._name].dims))

    def __len__(self):
        return self._len()

    def copyToNumpyArray(self):
        return np.array(self._read(), np.float32)

    def copyFrom(self, other):
        src = other._read() if isinstance(other, ParameterBuffer) else (
            other._a if isinstance(other, _VectorBase) else np.asarray(other)
        )
        if self._kind != PARAMETER_VALUE:
            raise ValueError("only PARAMETER_VALUE buffers are writable")
        shape = self._gm.params[self._name].shape
        self._gm.params[self._name] = jax.numpy.asarray(
            np.asarray(src, np.float32).reshape(shape)
        )

    def copyFromNumpyArray(self, a):
        self.copyFrom(np.asarray(a, np.float32))


class Parameter:
    def __init__(self, gm: "GradientMachine", name: str):
        self._gm = gm
        self._name = name

    def getName(self):
        return self._name

    def getSize(self):
        return int(np.prod(self._gm.net.param_confs[self._name].dims))

    def getBuf(self, kind):
        return ParameterBuffer(self._gm, self._name, kind)

    def setValueUpdated(self):
        pass  # device copy already happened in ParameterBuffer.copyFrom

    def __len__(self):
        return self.getSize()

    def getConfig(self):
        return self._gm.net.param_confs[self._name]


class Evaluator:
    """api.Evaluator over the machine's implied metric set: the
    reference auto-attaches classification_error to every
    classification_cost (trainer_config_helpers layers.py
    classification_cost's evaluator default); eval() accumulates from
    the machine's last forward."""

    def __init__(self, confs):
        from paddle_tpu.evaluators import create_evaluator

        self._evals = [create_evaluator(c) for c in confs]
        self._started = False

    def start(self):
        for ev in self._evals:
            ev.start()
        self._started = True

    def finish(self):
        self._started = False

    def _add(self, outs, feed):
        for ev in self._evals:
            ev.add_batch(outs, feed)

    def getNames(self):
        return [ev.name for ev in self._evals]

    def getValue(self, name):
        for ev in self._evals:
            if ev.name == name:
                return ev.result()
        raise KeyError(name)

    def __repr__(self):
        return " ".join(
            f"{ev.name}={ev.result()}" for ev in self._evals
        ) or "<no evaluators>"


class GradientMachine:
    """api/PaddleAPI.h:402 GradientMachine over a jitted Network.

    seed=None defers to the global 'seed' flag (whose 0 means a fresh
    OS-entropy seed); an explicit seed — including 0 — is honored
    exactly and governs BOTH parameter init and the dropout rng."""

    def __init__(self, conf, seed: int | None = None):
        self.conf = conf
        if seed is not None:
            root = jax.random.PRNGKey(seed)
        else:
            # flag semantics: 0 = nondeterministic (core/flags.py)
            root = _rng.root_key(_flags.get_flag("seed"))
        init_key, self._rng_key = jax.random.split(root)
        self.net = Network(conf)
        self.params = self.net.init_params(init_key)
        self.state = self.net.init_state()
        self._grads: dict = {}
        self._param_names = sorted(self.net.param_confs)
        self._fwd_cache: dict = {}
        self._last = None  # (outs, feed) of the latest forward
        self._rng_step = 0
        # implied evaluators (classification_error per classification
        # cost), what the reference's makeEvaluator materializes
        self._eval_confs = []
        for lc in conf.layers:
            if lc.type == "classification_cost" and len(lc.inputs) >= 2:
                self._eval_confs.append({
                    "type": "classification_error",
                    "name": "classification_error",
                    "input": lc.inputs[0].name,
                    "label": lc.inputs[1].name,
                })
        self._keep = set(self.net.output_names) | {
            c["input"] for c in self._eval_confs
        }

    def makeEvaluator(self) -> Evaluator:
        return Evaluator(self._eval_confs)

    def eval(self, evaluator: Evaluator):
        assert self._last is not None, "eval() before any forward"
        evaluator._add(*self._last)

    @classmethod
    def createFromConfigProto(cls, conf, mode=CREATE_MODE_NORMAL,
                              enable_types=None):
        return cls(conf)

    # --- parameters ---
    def getParameterSize(self):
        return len(self._param_names)

    def getParameter(self, i: int) -> Parameter:
        return Parameter(self, self._param_names[i])

    def getParameters(self):
        return [Parameter(self, n) for n in self._param_names]

    def getParameterNames(self):
        return list(self._param_names)

    def getNonStaticParameters(self):
        return [
            Parameter(self, n)
            for n in self._param_names
            if not getattr(self.net.param_confs[n], "is_static", False)
        ]

    def randParameters(self, seed: int = 0):
        self.params = self.net.init_params(jax.random.PRNGKey(seed))

    def loadParameters(self, path: str):
        """Load from a paddle_tpu checkpoint: a save_dir with pass-*
        subdirs, one pass dir, or a merged model file
        (trainer/ParamUtil.h:77-93 loadParameters)."""
        from paddle_tpu.trainer import checkpoint as ckpt

        if os.path.isfile(path):
            _, params, state = ckpt.load_merged(path)
        elif any(n.startswith("pass-") for n in os.listdir(path)):
            # a save_dir of pass-* checkpoints: latest wins
            params, _, state, _ = ckpt.load_pass(path, -1)
        elif os.path.exists(os.path.join(path, "params.npz")):
            # a single pass-XXXXX dir given directly
            parent, leaf = os.path.split(path.rstrip("/"))
            params, _, state, _ = ckpt.load_pass(
                parent, int(leaf.split("-")[1])
            )
        else:
            raise FileNotFoundError(
                f"no checkpoint (pass-* dir or merged file) at {path!r}"
            )
        self.params = {k: jax.numpy.asarray(v) for k, v in params.items()}
        if state:
            self.state = jax.tree_util.tree_map(jax.numpy.asarray, state)

    # --- execution ---
    def _fwd(self, train: bool):
        key = ("fwd", train)
        if key not in self._fwd_cache:
            keep = self._keep

            def fwd(params, state, feed, rng):
                outs, new_state = self.net.forward(
                    params, feed, state=state, train=train, rng=rng
                )
                return (
                    {n: outs[n] for n in keep if n in outs},
                    new_state,
                )

            self._fwd_cache[key] = jax.jit(fwd)
        return self._fwd_cache[key]

    def _next_rng(self):
        self._rng_step += 1
        return _rng.split_for_step(self._rng_key, self._rng_step)

    def forward(self, inArgs: Arguments, outArgs: Arguments, passType=None):
        train = passType == PASS_TRAIN
        feed = inArgs._feed(self.net.input_names)
        outs, new_state = self._fwd(train)(
            self.params, self.state, feed, self._next_rng()
        )
        if train:
            # train-mode forward advances batch-norm running stats,
            # exactly like the reference GradientMachine
            self.state = new_state
        self._last = (outs, feed)
        outArgs.resize(len(self.net.output_names))
        for i, n in enumerate(self.net.output_names):
            a = outs[n]
            if a.ids is not None and a.value is None:
                outArgs.setSlotIds(i, IVector(_flatten_arg_ids(a)))
            else:
                outArgs.setSlotValue(i, Matrix(_flatten_arg_value(a)))
            outArgs._slot(i)["arg"] = a

    def forwardTest(self, inArgs: Arguments):
        """Reference api: returns [{'id': ids, 'value': values}] per
        output layer (py_paddle util swig_paddle.py forwardTest)."""
        feed = inArgs._feed(self.net.input_names)
        outs, _ = self._fwd(False)(
            self.params, self.state, feed, self._next_rng()
        )
        self._last = (outs, feed)
        res = []
        for n in self.net.output_names:
            a = outs[n]
            d = {}
            if a.value is not None:
                v = _flatten_arg_value(a)
                d["value"] = v
                d["id"] = np.argmax(v, axis=-1)
            if a.ids is not None:
                d["id"] = _flatten_arg_ids(a)
            res.append(d)
        return res

    def forwardBackward(self, inArgs: Arguments, outArgs: Arguments,
                        passType=None):
        feed = inArgs._feed(self.net.input_names)
        if "grad" not in self._fwd_cache:
            keep = self._keep

            def fb(params, state, feed, rng):
                (loss, (outs, new_state)), grads = jax.value_and_grad(
                    self.net.loss_fn, has_aux=True
                )(params, feed, state=state, train=True, rng=rng)
                return loss, grads, {
                    n: outs[n] for n in keep if n in outs
                }, new_state

            self._fwd_cache["grad"] = jax.jit(fb)
        loss, grads, outs, new_state = self._fwd_cache["grad"](
            self.params, self.state, feed, self._next_rng()
        )
        self._grads = grads
        self.state = new_state
        self._last = (outs, feed)
        outArgs.resize(len(self.net.output_names))
        for i, n in enumerate(self.net.output_names):
            outArgs.setSlotValue(i, Matrix(_flatten_arg_value(outs[n])))
        return float(loss)

    def start(self):
        pass

    def finish(self):
        pass


class ParameterUpdater:
    """api/ParameterUpdater.cpp local updater: init(gm), then per batch
    startBatch -> (gm.forwardBackward) -> update(param)* ->
    finishBatch. The per-parameter update() calls mark parameters; the
    sharded optimizer applies once all marked (identical observable
    result, one fused XLA program). apply/restore/catchUpWith are the
    parameter-averaging window hooks (ThreadParameterUpdater.h:71)."""

    def __init__(self, opt_conf):
        self._opt_conf = opt_conf
        self._gm = None
        self.global_step = 0

    @classmethod
    def createLocalUpdater(cls, opt_conf):
        return cls(opt_conf)

    def init(self, gradient_machine: "GradientMachine"):
        self._gm = gradient_machine
        self._opt = create_optimizer(
            self._opt_conf, gradient_machine.net.param_confs
        )
        self._opt_state = self._opt.init_state(gradient_machine.params)
        self._marked = set()
        self._apply_fn = jax.jit(
            lambda g, p, s, i: self._opt.update(g, p, s, i)
        )

    def startPass(self):
        pass

    def finishPass(self):
        pass

    def startBatch(self, batch_size: int):
        self._marked = set()
        return PASS_TRAIN

    def update(self, param: "Parameter"):
        self._marked.add(param.getName())

    def finishBatch(self, cost: float = 0.0):
        gm = self._gm
        if self._marked and gm._grads:
            # unmarked parameters are simply absent from the grads dict:
            # the optimizer leaves them (and their momentum/decay/LR
            # state) untouched, matching the reference local updater
            # for drivers that skip update() on frozen params
            grads = {
                k: v for k, v in gm._grads.items() if k in self._marked
            }
            gm.params, self._opt_state = self._apply_fn(
                grads, gm.params, self._opt_state, self.global_step
            )
            self.global_step += 1

    # parameter-averaging window hooks — the averager is folded into
    # the optimizer state here; the explicit swap is a no-op
    def apply(self):
        pass

    def restore(self):
        pass

    def catchUpWith(self):
        pass

    def getParametersRemote(self, *a, **k):
        pass


class Trainer:
    """api/PaddleAPI.h Trainer: the startTrain/startTrainPass/
    trainOneDataBatch loop over a TrainerConfig + GradientMachine
    (trainer/Trainer.cpp:261 semantics)."""

    def __init__(self, config, gm: GradientMachine):
        self.config = config
        self.gm = gm
        self.opt = create_optimizer(config.opt, gm.net.param_confs)
        self.opt_state = self.opt.init_state(gm.params)
        self.step_fn = TrainStep(gm.net, self.opt)
        self.global_step = 0
        self._pass = 0
        self._batch = 0
        self._test_costs: list = []
        self._key = _rng.root_key(_flags.get_flag("seed"))

    @classmethod
    def create(cls, config, gm) -> "Trainer":
        return cls(config, gm)

    @classmethod
    def createByCommandLine(cls):
        raise NotImplementedError(
            "use Trainer.create(config, gradient_machine)"
        )

    def startTrain(self):
        pass

    def finishTrain(self):
        pass

    def startTrainPass(self):
        self._batch = 0

    def finishTrainPass(self):
        log.info("pass %d finished (%d batches)", self._pass, self._batch)
        self._pass += 1

    def trainOneDataBatch(self, size: int, args: Arguments):
        feed = args._feed(self.gm.net.input_names)
        rng = _rng.split_for_step(self._key, self.global_step)
        (
            self.gm.params,
            self.opt_state,
            self.gm.state,
            loss,
            _,
        ) = self.step_fn(
            self.gm.params, self.opt_state, self.gm.state, feed,
            self.global_step, rng,
        )
        self.global_step += 1
        self._batch += 1
        self._last_cost = float(loss)
        if self._batch % _flags.get_flag("log_period") == 0:
            log.info("pass %d batch %d cost %.5f",
                     self._pass, self._batch, self._last_cost)
        return self._last_cost

    def getForwardOutput(self):
        return []

    # --- test period (api Trainer::startTestPeriod) ---
    def startTestPeriod(self):
        self._test_costs = []

    def testOneDataBatch(self, size: int, args: Arguments):
        out = Arguments.createArguments(0)
        self.gm.forward(args, out, PASS_TEST)
        self._test_costs.append(out.sum() / max(size, 1))
        return self._test_costs[-1]

    def finishTestPeriod(self):
        if self._test_costs:
            log.info("test cost %.5f", float(np.mean(self._test_costs)))
