"""v1 API compatibility: run 2017-era config files with minimal edits.

`paddle_tpu.compat.layers_v1` exposes the trainer_config_helpers naming
(`fc_layer(input=..., size=...)` keyword style) over the native DSL, so
a `simple_mnist.py`-style config can be exec'd against this framework.
"""

from paddle_tpu.compat import layers_v1  # noqa: F401
