"""Execute UNMODIFIED reference v1 config files.

The counterpart of python/paddle/trainer/config_parser.py:3724
`parse_config(config_file, config_arg_str)`: a config file written
against `paddle.trainer_config_helpers` (the 2017 authoring surface) is
exec'd as-is — `from paddle.trainer_config_helpers import *` resolves to
the shim package at the repo root, which re-exports
`paddle_tpu.compat.layers_v1` plus the settings/optimizer/data-source
surface defined here — and yields a `TrainerConfig` holding the
paddle_tpu `ModelConf` + `OptimizationConf` + data-source declarations.

Python-2-era configs are supported: `xrange` is injected into the exec
namespace, and `load_provider_module` execs provider modules the same
way so `@provider` generators using xrange run unmodified.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Optional

from paddle_tpu.core.config import ModelConf, OptimizationConf

__all__ = [
    "get_config_arg",
    "settings",
    "define_py_data_sources2",
    "TrainData",
    "TestData",
    "SimpleData",
    "ProtoData",
    "read_simple_data",
    "outputs",
    "inputs",
    "default_device",
    "Settings",
    "Inputs",
    "Outputs",
    "default_momentum",
    "default_decay_rate",
    "default_initial_std",
    "default_initial_mean",
    "parse_config",
    "load_provider_module",
    "TrainerConfig",
    "apply_data_types",
    "DataSources",
    # optimizer settings (trainer_config_helpers/optimizers.py)
    "MomentumOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "AdaGradOptimizer",
    "DecayedAdaGradOptimizer",
    "AdaDeltaOptimizer",
    "RMSPropOptimizer",
    "L1Regularization",
    "L2Regularization",
    # attrs / poolings (trainer_config_helpers/{attrs,poolings}.py)
    "ExtraAttr",
    "ExtraLayerAttribute",
    "ModelAverage",
    # evaluator declarations (trainer_config_helpers/evaluators.py)
    "classification_error_evaluator",
    "sum_evaluator",
    "column_sum_evaluator",
    "chunk_evaluator",
    "ctc_error_evaluator",
    "precision_recall_evaluator",
    "auc_evaluator",
    "pnpair_evaluator",
    "value_printer_evaluator",
    "gradient_printer_evaluator",
    "maxid_printer_evaluator",
    "maxframe_printer_evaluator",
    "seqtext_printer_evaluator",
    "classification_error_printer_evaluator",
    "MaxPooling",
    "AvgPooling",
    "SumPooling",
    "SqrtAvgPooling",
    "CudnnMaxPooling",
    "CudnnAvgPooling",
]


# ---- parse context -------------------------------------------------------

class _ParseCtx:
    def __init__(self, args: dict):
        self.args = args
        self.opt = OptimizationConf()
        self.data_sources: Optional[DataSources] = None
        self.train_data: Optional[dict] = None
        self.test_data: Optional[dict] = None
        self.outputs: list = []
        self.inputs: list = []
        self.evaluators: list = []


_stack: list = []  # innermost parse context last


def _ctx() -> Optional[_ParseCtx]:
    return _stack[-1] if _stack else None


def get_config_arg(name, type_=str, default=None):
    """--config_args interpolation (config_parser.py get_config_arg):
    values arrive as strings and are cast with `type_`."""
    ctx = _ctx()
    if ctx is None or name not in ctx.args:
        return default
    v = ctx.args[name]
    if type_ is bool:
        if isinstance(v, str):
            return v.strip().lower() not in ("", "0", "false", "no")
        return bool(v)
    return type_(v)


# ---- optimizer / regularization settings objects -------------------------

class _OptSetting:
    """Maps onto OptimizationConf fields."""

    fields: dict = {}


class MomentumOptimizer(_OptSetting):
    def __init__(self, momentum=0.9, sparse=False):
        self.fields = {"learning_method": "momentum", "momentum": momentum}


class AdamOptimizer(_OptSetting):
    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.fields = {
            "learning_method": "adam",
            "adam_beta1": beta1,
            "adam_beta2": beta2,
            "adam_epsilon": epsilon,
        }


class AdamaxOptimizer(_OptSetting):
    def __init__(self, beta1=0.9, beta2=0.999):
        self.fields = {
            "learning_method": "adamax",
            "adam_beta1": beta1,
            "adam_beta2": beta2,
        }


class AdaGradOptimizer(_OptSetting):
    def __init__(self, epsilon=1e-6):
        self.fields = {"learning_method": "adagrad", "ada_epsilon": epsilon}


class DecayedAdaGradOptimizer(_OptSetting):
    def __init__(self, rou=0.95, epsilon=1e-6):
        self.fields = {
            "learning_method": "decayed_adagrad",
            "ada_rou": rou,
            "ada_epsilon": epsilon,
        }


class AdaDeltaOptimizer(_OptSetting):
    def __init__(self, rou=0.95, epsilon=1e-6):
        self.fields = {
            "learning_method": "adadelta",
            "ada_rou": rou,
            "ada_epsilon": epsilon,
        }


class RMSPropOptimizer(_OptSetting):
    def __init__(self, rou=0.95, epsilon=1e-6):
        self.fields = {
            "learning_method": "rmsprop",
            "ada_rou": rou,
            "ada_epsilon": epsilon,
        }


class L2Regularization(_OptSetting):
    def __init__(self, rate):
        self.fields = {"l2_rate": rate}


class L1Regularization(_OptSetting):
    def __init__(self, rate):
        self.fields = {"l1_rate": rate}


class ModelAverage(_OptSetting):
    """settings(model_average=ModelAverage(...)) — the AverageOptimizer
    window (trainer_config_helpers/optimizers.py ModelAverage)."""

    def __init__(self, average_window, max_average_window=0,
                 do_average_in_cpu=False):
        self.fields = {
            "average_window": average_window,
            "max_average_window": max_average_window,
        }


def settings(batch_size=256, learning_rate=0.01, learning_method=None,
             regularization=None, gradient_clipping_threshold=None,
             learning_rate_decay_a=0.0, learning_rate_decay_b=0.0,
             learning_rate_schedule=None, learning_rate_args="",
             average_window=0, max_average_window=0,
             model_average=None, **_):
    """trainer_config_helpers `settings(...)` -> OptimizationConf
    (config_parser.py:3576 Settings)."""
    ctx = _ctx()
    assert ctx is not None, "settings() outside parse_config"
    o = ctx.opt
    o.batch_size = batch_size
    o.learning_rate = learning_rate
    o.learning_rate_decay_a = learning_rate_decay_a
    o.learning_rate_decay_b = learning_rate_decay_b
    if learning_rate_schedule:
        o.learning_rate_schedule = learning_rate_schedule
    o.learning_rate_args = learning_rate_args
    o.average_window = average_window
    o.max_average_window = max_average_window
    if gradient_clipping_threshold is not None:
        o.gradient_clipping_threshold = gradient_clipping_threshold
    for setting in (learning_method, regularization, model_average):
        if setting is not None:
            for k, v in setting.fields.items():
                setattr(o, k, v)
    return o


# ---- attrs / poolings ----------------------------------------------------

class ExtraLayerAttribute:
    """(trainer_config_helpers/attrs.py ExtraLayerAttribute)."""

    def __init__(self, error_clipping_threshold=None, drop_rate=None,
                 device=None, **_):
        self.error_clipping_threshold = error_clipping_threshold
        self.drop_rate = drop_rate
        self.device = device


ExtraAttr = ExtraLayerAttribute


class _Pooling:
    name = ""

    def __init__(self, output_max_index=False, **_):
        self.output_max_index = output_max_index


class MaxPooling(_Pooling):
    name = "max"


class AvgPooling(_Pooling):
    name = "avg"


class SumPooling(_Pooling):
    name = "sum"


class SqrtAvgPooling(_Pooling):
    name = "sqrt_average"


# cudnn-tagged spellings (poolings.py CudnnMaxPooling/CudnnAvgPooling);
# the device-specific implementation distinction is XLA's business
class CudnnMaxPooling(_Pooling):
    name = "cudnn_max"


class CudnnAvgPooling(_Pooling):
    name = "cudnn_avg"


# ---- data sources --------------------------------------------------------

@dataclass
class DataSources:
    """define_py_data_sources2 declaration
    (trainer_config_helpers/data_sources.py)."""

    train_list: Optional[str] = None
    test_list: Optional[str] = None
    module: str = ""
    obj: str = ""
    args: dict = field(default_factory=dict)
    search_dir: str = ""  # config file's directory: providers live there

    def _reader(self, file_list, obj=None):
        import paddle_tpu.data.reader as R

        mod = load_provider_module(self.module, self.search_dir)
        provider = getattr(mod, obj or self.obj)
        files = [
            ln.strip()
            for ln in open(file_list).read().splitlines()
            if ln.strip()
        ]
        return provider(files, **self.args), provider.input_types

    def train_reader(self):
        """(reader_creator, input_types) for the train list."""
        return self._reader(self.train_list)

    def test_reader(self):
        return self._reader(self.test_list)


# ---- v1 data declarations (config_parser.py TrainData/TestData;
#      SimpleData:986, ProtoData — the trainer-test configs' forms) ----

def SimpleData(files=None, feat_dim=None, context_len=None,
               buffer_capacity=None, **_):
    """Text samples 'label f1 .. fD' listed by a file-list
    (SimpleDataProvider, gserver/dataproviders/DataProvider.cpp:395)."""
    return {"type": "simple", "files": files, "feat_dim": feat_dim,
            "context_len": context_len or 0}


def ProtoData(files=None, type=None, **kw):
    """DataFormat.proto binary sample files listed by a file-list
    (ProtoDataProvider); decoded by data/proto_provider.py."""
    return {"type": type or "proto", "files": files, **kw}


def TrainData(decl, async_load_data=None, **_):
    ctx = _ctx()
    assert ctx is not None, "TrainData() outside parse_config"
    ctx.train_data = decl


def TestData(decl, async_load_data=None, **_):
    ctx = _ctx()
    assert ctx is not None, "TestData() outside parse_config"
    ctx.test_data = decl


def read_simple_data(filelist: str, feat_dim: int, context_len: int = 0):
    """Load every file in a SimpleData file-list: returns
    (features [N, feat_dim] float32, labels [N] int32). Line format is
    'label f1 .. fD' (DataProvider.cpp:404: label first). Context
    windows (context_len > 0) are not implemented — fail loudly rather
    than train on un-contextualized features."""
    import numpy as np

    if context_len:
        raise NotImplementedError(
            "SimpleData context_len > 0 (context-window expansion) is "
            "not supported; expand windows in the provider instead"
        )

    feats, labels = [], []
    for path in open(filelist).read().splitlines():
        path = path.strip()
        if not path:
            continue
        for line in open(path).read().splitlines():
            pieces = line.split()
            if len(pieces) != feat_dim + 1:
                raise ValueError(
                    f"{path}: got {len(pieces) - 1} features, "
                    f"config says {feat_dim}"
                )
            labels.append(int(pieces[0]))
            feats.append([float(p) for p in pieces[1:]])
    return (
        np.asarray(feats, np.float32),
        np.asarray(labels, np.int32),
    )


def define_py_data_sources2(train_list=None, test_list=None, module="",
                            obj="", args=None, **_):
    ctx = _ctx()
    assert ctx is not None, "define_py_data_sources2 outside parse_config"
    ctx.data_sources = DataSources(
        train_list=train_list,
        test_list=test_list,
        module=module,
        obj=obj,
        args=dict(args or {}),
    )
    return ctx.data_sources


# ---- evaluator declarations (trainer_config_helpers/evaluators.py) --

def _declare_evaluator(type_, input=None, label=None, name=None, **kw):
    ctx = _ctx()
    assert ctx is not None, "evaluator declared outside parse_config"
    if isinstance(input, (list, tuple)):
        # printer-style evaluators accept several inputs: one conf each
        return [
            _declare_evaluator(
                type_, x, label,
                f"{name}_{i}" if name and i else name, **kw
            )
            for i, x in enumerate(input)
        ]
    conf = {"type": type_}
    if name:
        conf["name"] = name
    if input is not None:
        conf["input"] = getattr(input, "name", input)
    if label is not None:
        conf["label"] = getattr(label, "name", label)
    for k, v in kw.items():
        if v is not None:
            conf[k] = v
    ctx.evaluators.append(conf)
    return conf


def classification_error_evaluator(input, label, name=None, **kw):
    return _declare_evaluator(
        "classification_error", input, label, name, **kw
    )


def sum_evaluator(input, name=None, **kw):
    return _declare_evaluator("sum", input, None, name, **kw)


def column_sum_evaluator(input, name=None, **kw):
    return _declare_evaluator("column_sum", input, None, name, **kw)


def chunk_evaluator(input, label, chunk_scheme, num_chunk_types,
                    name=None, excluded_chunk_types=None, **kw):
    return _declare_evaluator(
        "chunk", input, label, name, chunk_scheme=chunk_scheme,
        num_chunk_types=num_chunk_types,
        excluded_chunk_types=excluded_chunk_types, **kw
    )


def ctc_error_evaluator(input, label, name=None, **kw):
    return _declare_evaluator(
        "ctc_edit_distance", input, label, name, **kw
    )


def precision_recall_evaluator(input, label, name=None, **kw):
    return _declare_evaluator(
        "precision_recall", input, label, name, **kw
    )


def auc_evaluator(input, label, name=None, **kw):
    return _declare_evaluator("rankauc", input, label, name, **kw)


def pnpair_evaluator(input, label, info=None, query_id=None, name=None,
                     **kw):
    """The reference names the query-id slot `info`
    (trainer_config_helpers/evaluators.py pnpair_evaluator); accept
    both spellings."""
    q = info if info is not None else query_id
    assert q is not None, "pnpair_evaluator needs info= (query ids)"
    return _declare_evaluator(
        "pnpair", input, label, name,
        query_id=getattr(q, "name", q), **kw
    )


def value_printer_evaluator(input, name=None, **kw):
    return _declare_evaluator("value_printer", input, None, name, **kw)


def gradient_printer_evaluator(input, name=None, **kw):
    return _declare_evaluator(
        "gradient_printer", input, None, name, **kw
    )


def maxid_printer_evaluator(input, name=None, **kw):
    return _declare_evaluator("max_id_printer", input, None, name, **kw)


def maxframe_printer_evaluator(input, name=None, **kw):
    return _declare_evaluator(
        "max_frame_printer", input, None, name, **kw
    )


def seqtext_printer_evaluator(input, name=None, **kw):
    return _declare_evaluator(
        "seq_text_printer", input, None, name, **kw
    )


def classification_error_printer_evaluator(input, label, name=None, **kw):
    return _declare_evaluator(
        "classification_error_printer", input, label, name, **kw
    )


def outputs(*layer_refs):
    """Mark output/cost layers (trainer_config_helpers `outputs`)."""
    ctx = _ctx()
    assert ctx is not None, "outputs() outside parse_config"
    flat = []
    for r in layer_refs:
        flat += list(r) if isinstance(r, (list, tuple)) else [r]
    ctx.outputs = [getattr(r, "name", r) for r in flat]


def Settings(algorithm="sgd", batch_size=256, learning_rate=0.01,
             learning_method=None, learning_rate_decay_a=0.0,
             learning_rate_decay_b=0.0, learning_rate_schedule=None,
             **kw):
    """The raw config_parser `Settings(...)` spelling
    (config_parser.py:3576): like settings() but learning_method is a
    STRING and `algorithm` is accepted ('sgd' — async modes are out of
    scope, PARITY.md)."""
    ctx = _ctx()
    assert ctx is not None, "Settings() outside parse_config"
    o = ctx.opt
    o.batch_size = batch_size
    o.learning_rate = learning_rate
    if learning_method:
        o.learning_method = learning_method
    o.learning_rate_decay_a = learning_rate_decay_a
    o.learning_rate_decay_b = learning_rate_decay_b
    if learning_rate_schedule:
        o.learning_rate_schedule = learning_rate_schedule
    for k, v in kw.items():
        if hasattr(o, k) and v is not None:
            setattr(o, k, v)
    return o


def default_momentum(v: float) -> None:
    """config_parser default_momentum: the momentum used where no
    per-parameter momentum is configured."""
    ctx = _ctx()
    assert ctx is not None
    ctx.opt.momentum = v


def default_decay_rate(v: float) -> None:
    """config_parser default_decay_rate (L2)."""
    ctx = _ctx()
    assert ctx is not None
    ctx.opt.l2_rate = v


def default_initial_std(v: float) -> None:
    """config_parser default_initial_std. NOT threaded into implicit
    parameter creation: the framework's default init is already the
    reference's 'smart' 1/sqrt(fan_in); use per-param
    ParamAttr(initial_std=...) for exact control. Logged so silent
    divergence is visible."""
    import logging

    logging.getLogger("paddle_tpu.compat").info(
        "default_initial_std(%s): framework keeps smart init; set "
        "ParamAttr(initial_std=...) per parameter for exact parity", v,
    )


def default_initial_mean(v: float) -> None:
    """See default_initial_std — logged, not applied implicitly."""
    import logging

    if v:
        logging.getLogger("paddle_tpu.compat").warning(
            "default_initial_mean(%s) is not applied to implicitly "
            "created parameters; use ParamAttr(initial_mean=...)", v,
        )


def default_device(device: int) -> None:
    """v1 per-layer device placement default (config_parser.py
    default_device, consumed by ParallelNeuralNetwork). Devices are a
    mesh concern here (per-layer `out_sharding` GSPMD hints); the
    global default is a no-op under one compiled program."""
    ctx = _ctx()
    assert ctx is not None, "default_device() outside parse_config"


def inputs(*layer_refs):
    """Declare the network's input layers and their FEED ORDER
    (trainer_config_helpers `inputs`) — the order data-provider slots
    map onto data layers. Accepts refs or names (the raw config_parser
    `Inputs(...)` spelling)."""
    ctx = _ctx()
    assert ctx is not None, "inputs() outside parse_config"
    flat = []
    for r in layer_refs:
        flat += list(r) if isinstance(r, (list, tuple)) else [r]
    ctx.inputs = [getattr(r, "name", r) for r in flat]


# ---- the parser ----------------------------------------------------------

@dataclass
class TrainerConfig:
    """What parse_config returns: everything the trainer needs."""

    model: ModelConf
    opt: OptimizationConf
    data_sources: Optional[DataSources]
    args: dict
    evaluators: list = field(default_factory=list)
    # v1 TrainData/TestData declarations (SimpleData/ProtoData dicts)
    train_data: Optional[dict] = None
    test_data: Optional[dict] = None

    # -- the reference TrainerConfig proto surface the api drivers use
    #    (proto/TrainerConfig.proto; v1_api_demo/quick_start/api_train.py:80-84)
    @property
    def model_config(self):
        return self.model

    @property
    def opt_config(self):
        return self.opt

    def ClearField(self, name: str):
        if name in ("data_config", "test_data_config"):
            self.data_sources = None
        elif hasattr(self, name):
            setattr(self, name, None)

    def SerializeToString(self) -> bytes:
        """Deterministic wire form (the reference returns the
        TrainerConfig proto's SerializeToString,
        config_parser.py:3760). Dataclass reprs are deterministic, so
        equal configs serialize equal — the property
        parse_config_and_serialize callers rely on."""
        return repr(self).encode()


def _parse_args(config_args) -> dict:
    if not config_args:
        return {}
    if isinstance(config_args, dict):
        return dict(config_args)
    out = {}
    for pair in str(config_args).split(","):
        if not pair.strip():
            continue
        k, _, v = pair.partition("=")
        out[k.strip()] = v.strip()
    return out


def _raw_namespace() -> dict:
    """The exec namespace the reference injects into raw config files
    (everything this module exports + the raw Layer/Projection API +
    the helper-layer surface)."""
    import paddle_tpu.compat.layers_v1 as _l1
    import paddle_tpu.compat.raw_config as _raw

    import sys

    me = sys.modules[__name__]
    ns = {n: getattr(me, n) for n in __all__}
    ns.update({n: getattr(_l1, n) for n in _l1.__all__})
    ns.update({n: getattr(_raw, n) for n in _raw.__all__})
    return ns


def parse_config(config_file, config_args="") -> TrainerConfig:
    """Exec a v1 config file (config_parser.py:3724 parse_config).

    `config_file` may also be a callable (the reference parse_config
    accepts a function and calls it inside the parse scope —
    config_parser.py:3732 `if hasattr(trainer_config, '__call__')`;
    that is how config_parser_utils.parse_network_config drives it).
    `config_args` is the CLI `--config_args` string ("a=1,b=2") or a
    dict; values reach the config via `get_config_arg`. The file's own
    `from paddle.trainer_config_helpers import *` resolves through the
    repo-root `paddle` shim package. Relative paths in the config (dict
    files, data lists) resolve against the CURRENT working directory,
    exactly as `paddle train` resolved them."""
    from paddle_tpu import dsl

    ctx = _ParseCtx(_parse_args(config_args))
    _stack.append(ctx)
    # a config error inside an open raw RecurrentLayerGroupBegin scope
    # must not leak its sub-builder / group frame into later parses
    from paddle_tpu.compat import raw_config as _raw_mod

    dsl_depth = len(dsl._stack)
    group_depth = len(_raw_mod._group_stack)
    try:
        if callable(config_file):
            with dsl.model() as g:
                config_file()
        else:
            with open(config_file) as f:
                code = compile(f.read(), config_file, "exec")
            ns = {
                "__file__": os.path.abspath(config_file),
                "__name__": "__paddle_config__",
                "xrange": range,  # py2-era configs
            }
            # RAW configs (no imports) run inside the reference
            # parser's own namespace — seed the same surface; a
            # config's own `from ... import *` still shadows it
            ns.update(_raw_namespace())
            with dsl.model() as g:
                exec(code, ns)
        conf = g.conf
    finally:
        _stack.pop()
        # close leaked group scopes FIRST (each __exit__ pops its own
        # sub-builder; merely dropping the references would run the
        # suspended context managers' finally at GC time, popping
        # builders that are no longer top-of-stack)
        while len(_raw_mod._group_stack) > group_depth:
            _gname, _cm, *_rest = _raw_mod._group_stack.pop()
            try:
                _cm.__exit__(None, None, None)
            except Exception:
                pass
        del dsl._stack[dsl_depth:]
    if ctx.outputs:
        for name in ctx.outputs:
            if name not in conf.output_layer_names:
                conf.output_layer_names.append(name)
    if ctx.inputs:
        # inputs() fixes the data-layer FEED ORDER
        conf.input_layer_names = list(ctx.inputs)
    if ctx.data_sources is not None and not callable(config_file):
        ctx.data_sources.search_dir = os.path.dirname(
            os.path.abspath(config_file)
        )
    return TrainerConfig(
        model=conf, opt=ctx.opt, data_sources=ctx.data_sources,
        args=ctx.args, evaluators=ctx.evaluators,
        train_data=ctx.train_data, test_data=ctx.test_data,
    )


def apply_data_types(model: ModelConf, input_types) -> None:
    """Annotate the model's data layers from a provider's input_types —
    in v1 the slot type (dense/ids/sparse × seq level) came from the
    data-provider declaration (PyDataProvider2.py:47-214), not from the
    config's data_layer calls. `input_types` is a dict name->InputType
    or a list in SLOT order — which is the config's inputs()
    declaration (model.input_layer_names) when present, else data-layer
    declaration order."""
    data_layers = {
        lc.name: lc for lc in model.layers if lc.type == "data"
    }
    if isinstance(input_types, dict):
        pairs = [
            (data_layers[n], t)
            for n, t in input_types.items()
            if n in data_layers
        ]
    else:
        order = [
            n for n in (model.input_layer_names or data_layers)
            if n in data_layers
        ] or list(data_layers)
        pairs = [
            (data_layers[n], t) for n, t in zip(order, input_types)
        ]
    for lc, t in pairs:
        lc.attrs["is_ids"] = t.kind == "ids"
        lc.attrs["is_seq"] = t.seq >= 1
        lc.attrs["has_subseq"] = t.seq == 2


def load_provider_module(name_or_path: str, search_dir: str = ""):
    """Import a data-provider module the way the embedded interpreter
    did (PyDataProvider2.cpp loads the module by name with the config
    dir on sys.path) — but exec'd with `xrange` injected so py2-era
    providers run unmodified."""
    import types

    path = name_or_path
    if not path.endswith(".py"):
        path = os.path.join(search_dir, name_or_path + ".py")
    if not os.path.exists(path):
        raise FileNotFoundError(f"provider module not found: {path}")
    mod = types.ModuleType(os.path.basename(path)[:-3])
    mod.__file__ = path
    mod.__dict__["xrange"] = range
    with open(path) as f:
        code = compile(f.read(), path, "exec")
    exec(code, mod.__dict__)
    return mod


# raw config_parser spellings (config_parser.py Inputs/Outputs take
# layer NAMES)
Inputs = inputs
Outputs = outputs
