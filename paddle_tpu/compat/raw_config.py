"""The reference's RAW config API — the functions `config_parser.py`
injects into a config's exec namespace (no imports in the file; the
2015-era authoring surface under trainer_config_helpers):

    Layer(name=..., type="mixed", size=..., inputs=[
        FullMatrixProjection("src", parameter_name="w"), ...])
    Memory(name=..., size=...)
    RecurrentLayerGroupBegin/End(...)
    Evaluator(name=..., type="sum", inputs=...)

Reference: python/paddle/trainer/config_parser.py — @config_layer
classes (Layer dispatch :2910 MixedLayer et al.), Input/Projection
configs, Memory (:299-386 RNN groups), RecurrentLayerGroupBegin/End
(:368,:386), Evaluator (:1466). Layer `type` strings map 1:1 onto the
framework registry (the REGISTER_LAYER names test_registry_parity
sweeps), so the dispatch is a thin LayerConf constructor.

Exec'd configs receive these via parse_config's namespace seeding
(compat/config_parser.py), exactly as the reference execs configs
inside its own module namespace.
"""

from __future__ import annotations

from paddle_tpu import dsl
from paddle_tpu.core.config import InputConf, LayerConf, ParameterConf

__all__ = [
    "Layer",
    "Input",
    "Bias",
    "Memory",
    "FullMatrixProjection",
    "TransposedFullMatrixProjection",
    "TableProjection",
    "IdentityProjection",
    "DotMulProjection",
    "ContextProjection",
    "RecurrentLayerGroupBegin",
    "RecurrentLayerGroupEnd",
    "Evaluator",
    "model_type",
]


def _param(parameter_name=None, initial_std=None, initial_mean=0.0,
           learning_rate=1.0, decay_rate=None, decay_rate_l1=None,
           sparse_update=False, sparse_remote_update=False,
           initial_smart=False, is_static=False, **_):
    """Inline parameter attrs -> ParameterConf (config_parser Input's
    parameter fields). initial_smart = std 1/sqrt(fan_in), which is
    this framework's default when initial_std is unset."""
    if parameter_name is None and initial_std is None and not (
        sparse_update or sparse_remote_update or is_static
        or decay_rate is not None or decay_rate_l1 is not None
        or learning_rate != 1.0 or initial_mean
    ):
        return None
    return ParameterConf(
        name=parameter_name or "",
        initial_std=initial_std,
        initial_mean=initial_mean,
        learning_rate=learning_rate,
        decay_rate=decay_rate,
        decay_rate_l1=decay_rate_l1,
        sparse_update=bool(sparse_update),
        sparse_remote_update=bool(sparse_remote_update),
        is_static=bool(is_static),
    )


def Input(input_layer_name, **kw):
    return InputConf(name=input_layer_name, parameter=_param(**kw))


def Bias(**kw):
    """Layer(bias=Bias(parameter_name=...)) — a named/parametrized
    bias (shared across layers by name, like the rnn1.bias idiom)."""
    return _param(**kw) or ParameterConf(name="")


def _proj(kind):
    def proj(input_layer_name, size=0, **kw):
        attrs = {"proj": kind}
        if size:
            attrs["proj_size"] = size
        return InputConf(
            name=input_layer_name, parameter=_param(**kw), attrs=attrs
        )

    proj.__name__ = kind
    return proj


FullMatrixProjection = _proj("full_matrix")
TransposedFullMatrixProjection = _proj("trans_full_matrix")
IdentityProjection = _proj("identity")
DotMulProjection = _proj("dotmul")


def TableProjection(input_layer_name, size=0, **kw):
    g = dsl.current()
    src = g.conf.layer(input_layer_name)
    # an id slot feeding a lookup table (same annotation
    # table_projection applies on the helper surface)
    if src.type == "data" and not src.attrs.get("is_ids"):
        src.attrs["is_ids"] = True
        src.attrs["is_seq"] = True
    attrs = {"proj": "table", "vocab_size": src.size}
    if size:
        attrs["proj_size"] = size
    return InputConf(
        name=input_layer_name, parameter=_param(**kw), attrs=attrs
    )


def ContextProjection(input_layer_name, context_length, context_start=None,
                      **kw):
    return InputConf(
        name=input_layer_name,
        parameter=_param(**kw),
        attrs={
            "proj": "context",
            "context_length": context_length,
            "context_start": context_start,
        },
    )




def Layer(name=None, type=None, size=0, active_type="", bias=True,
          inputs=(), device=None, **attrs):
    """Raw layer constructor: `type` is the registry name (REGISTER_LAYER
    spelling); `inputs` are layer-name strings, Input(...)s, or
    projection edges; `bias` is True/False or Bias(...)."""
    assert name and type, "Layer() needs name= and type="
    g = dsl.current()
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    ics = [dsl._in(x) for x in inputs]
    bias_param = None
    bias_flag = bool(bias)
    if isinstance(bias, ParameterConf):
        bias_param, bias_flag = bias, True
    if type == "data":
        lc = LayerConf(
            name=name, type="data", size=size,
            attrs={"dim": (size,), "is_seq": False, "is_ids": False,
                   "has_subseq": False},
        )
        return g.add(lc)
    lc = LayerConf(
        name=name, type=type, size=size, inputs=ics,
        active_type=active_type, bias=bias_flag,
        bias_parameter=bias_param, device=device,
        attrs={k: v for k, v in attrs.items() if v is not None},
    )
    return g.add(lc)


def Memory(name, size, boot_bias=None, boot_bias_active_type="",
           boot_with_const_id=None, **_):
    """Raw memory declaration inside a recurrent layer group
    (config_parser.py Memory) — returns the LINK NAME projections
    reference (the reference returns '<name>+delay1'; layers must use
    the returned handle, not the literal)."""
    if boot_bias is not None or boot_with_const_id is not None:
        raise NotImplementedError(
            "raw Memory boot_bias/boot_with_const_id are not "
            "supported; boot via the helper-surface memory(boot_layer=)"
        )
    ref = dsl.memory(name, size)
    return ref.name


_group_stack: list = []


def RecurrentLayerGroupBegin(name, in_links, out_links, seq_reversed=False,
                             **_):
    """Open a recurrent layer group scope (config_parser.py:368
    RecurrentLayerGroupBegin): subsequent Layer() calls build the STEP
    network; in-link names resolve to per-step slices of the parent
    layers of the same name (the reference's ScatterAgent wiring)."""
    parent = dsl.current()
    cm = dsl.model()
    sub = cm.__enter__()
    sub._counts = parent._counts
    for ln in list(in_links):
        sz = parent.conf.layer(ln).size
        sub.add(
            LayerConf(name=ln, type="data", size=sz,
                      attrs={"dim": (sz,), "is_seq": False,
                             "is_ids": False})
        )
    _group_stack.append(
        (name, cm, sub, parent, list(in_links), list(out_links),
         bool(seq_reversed))
    )


def RecurrentLayerGroupEnd(name):
    """Close the group scope and materialize the scan layer under the
    out-link's name (so downstream raw layers referencing the out-link
    resolve), through the same group_layer_conf contract
    dsl.recurrent_group uses."""
    gname, cm, sub, parent, in_links, out_links, rev = _group_stack.pop()
    assert name == gname, f"group end {name!r} != begin {gname!r}"
    cm.__exit__(None, None, None)
    if len(out_links) != 1:
        raise NotImplementedError(
            "raw RecurrentLayerGroup supports exactly one out_link "
            f"(got {out_links}); secondary out-links are a "
            "recurrent_group(step) feature"
        )
    lc = dsl.group_layer_conf(
        out_links[0], sub, parent_inputs=in_links,
        in_links=in_links, static_links=[], out_links=out_links,
        reversed=rev,
    )
    return parent.add(lc)


def Evaluator(name=None, type=None, inputs=(), **kw):
    """Raw evaluator declaration (config_parser.py Evaluator) — the
    registry spelling of `type` matches REGISTER_EVALUATOR names."""
    from paddle_tpu.compat.config_parser import _declare_evaluator

    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    input_ = inputs[0] if inputs else None
    label = inputs[1] if len(inputs) > 1 else None
    return _declare_evaluator(type, input_, label, name, **kw)


def model_type(name):
    """model_type("nn"/"recurrent_nn") — executor choice is implicit
    here (one jit program either way); accepted for source parity."""
