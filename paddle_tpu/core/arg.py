"""Arg — the inter-layer data packet.

Capability equivalent of the reference's Argument
(paddle/parameter/Argument.h:29,71-93): value + optional integer ids +
sequence metadata for variable-length and nested sequences.

TPU-first redesign: the reference stores a flat [sum(T_i), D] value with
`sequenceStartPositions` offsets (padding-free, dynamic shapes). XLA wants
static shapes, so sequences are DENSE-PACKED: value is [B, T, ...] padded to
the bucket length, `seq_lens` is [B] int32, and masks are derived on demand.
Nested (sub-)sequences carry a second level: `subseq_lens` [B, S] giving the
length of each sub-sequence, padded with zeros. All framework kernels
(pooling, last-instance, softmax over sequence, scan recurrence, CTC/CRF)
respect the mask so padding never changes results — the same *semantics* as
padding-free, in a compiler-friendly layout.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Optional

import jax
import jax.numpy as jnp


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class Arg:
    # dense value: [B, ...] (non-seq) or [B, T, ...] (seq)
    value: Optional[jax.Array] = None
    # integer ids, same leading shape as value (sparse/index inputs)
    ids: Optional[jax.Array] = None
    # [B] int32 lengths; None => not a sequence
    seq_lens: Optional[jax.Array] = None
    # [B, S] int32 sub-sequence lengths (nested sequences); zero-padded
    subseq_lens: Optional[jax.Array] = None

    # -- properties --
    @property
    def is_seq(self) -> bool:
        return self.seq_lens is not None

    @property
    def has_subseq(self) -> bool:
        return self.subseq_lens is not None

    @property
    def batch(self) -> int:
        a = self.value if self.value is not None else self.ids
        return a.shape[0]

    @property
    def max_len(self) -> int:
        a = self.value if self.value is not None else self.ids
        return a.shape[1]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """[B, T] 1.0 where a timestep is real, 0.0 where padding."""
        assert self.is_seq
        t = self.max_len
        pos = jnp.arange(t, dtype=jnp.int32)[None, :]
        return (pos < self.seq_lens[:, None]).astype(dtype)

    def bool_mask(self) -> jax.Array:
        assert self.is_seq
        pos = jnp.arange(self.max_len, dtype=jnp.int32)[None, :]
        return pos < self.seq_lens[:, None]

    def with_value(self, value: jax.Array) -> "Arg":
        return replace(self, value=value)

    def total_tokens(self) -> jax.Array:
        """Number of real (unpadded) timesteps in the batch."""
        assert self.is_seq
        return jnp.sum(self.seq_lens)


def pad_ragged(flat, pos):
    """Flat [total, ...] rows + start positions (len B+1, first 0,
    last total) -> (padded [B, T, ...], lens [B]). The reference keeps
    the padding-free layout (Argument.sequenceStartPositions); XLA
    wants static shapes, so API boundaries (C ABI, SWIG compat) convert
    ragged to dense-packed here."""
    import numpy as np

    pos = np.asarray(pos)
    lens = np.diff(pos).astype(np.int32)
    b, t = len(lens), int(lens.max(initial=1))
    out = np.zeros((b, max(t, 1)) + flat.shape[1:], flat.dtype)
    for i in range(b):
        out[i, : lens[i]] = flat[pos[i] : pos[i + 1]]
    return out, lens


def non_seq(value: jax.Array) -> Arg:
    return Arg(value=value)


def seq(value: jax.Array, seq_lens: jax.Array) -> Arg:
    return Arg(value=value, seq_lens=jnp.asarray(seq_lens, jnp.int32))


def id_arg(ids: jax.Array, seq_lens=None) -> Arg:
    if seq_lens is not None:
        seq_lens = jnp.asarray(seq_lens, jnp.int32)
    return Arg(ids=jnp.asarray(ids, jnp.int32), seq_lens=seq_lens)


def sub_seq(value: jax.Array, subseq_lens: jax.Array,
            is_ids: bool = False) -> Arg:
    """Nested sequence: flat-packed [B, T, ...] value with [B, S]
    per-subsequence lengths (Argument.h:84-93
    subSequenceStartPositions). seq_lens is the flat total."""
    subseq_lens = jnp.asarray(subseq_lens, jnp.int32)
    lens = jnp.sum(subseq_lens, axis=1)
    if is_ids:
        return Arg(ids=jnp.asarray(value, jnp.int32), seq_lens=lens,
                   subseq_lens=subseq_lens)
    return Arg(value=value, seq_lens=lens, subseq_lens=subseq_lens)
