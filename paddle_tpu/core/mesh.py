"""Device mesh bootstrap.

Replaces the reference's entire communication stack — the in-node ring of
MultiGradientMachine (gserver/gradientmachines/MultiGradientMachine.cpp:389),
the C++ pserver star topology (paddle/pserver/LightNetwork.h:40), and the Go
pserver (go/pserver) — with a single ``jax.sharding.Mesh`` whose collectives
XLA compiles onto ICI/DCN.

Canonical axis names:
  data  — data parallel (batch split, grads psum'd)
  model — tensor/model parallel (weight shards)
  seq   — sequence/context parallel (ring attention / all-to-all)
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=False):
    """`jax.shard_map` across jax versions: new runtimes expose it at
    the top level with `check_vma`; this container's 0.4.37 only has
    `jax.experimental.shard_map` with the older `check_rep` spelling.
    One shim so every SPMD entry point (ring/ulysses attention,
    sharded embedding, pipeline stages) runs on both."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as sm_exp

    return sm_exp(f, mesh=mesh, in_specs=in_specs,
                  out_specs=out_specs, check_rep=check_vma)

_current_mesh: Optional[Mesh] = None


def make_mesh(shape: Optional[dict] = None, devices=None) -> Mesh:
    """Build a mesh. `shape` maps axis name -> size; a size of -1 takes
    every remaining device. Default: all devices on the `data` axis —
    the analogue of `trainer_count` data parallelism
    (reference: paddle/utils/Flags.cpp trainer_count)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if not shape:
        shape = {DATA_AXIS: n}
    names = list(shape.keys())
    sizes = list(shape.values())
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        if n % known != 0:
            raise ValueError(
                f"mesh shape {dict(zip(names, sizes))}: -1 cannot take the "
                f"remaining devices ({known} does not divide {n})"
            )
        sizes[sizes.index(-1)] = n // known
    prod = math.prod(sizes)
    if prod > n:
        raise ValueError(f"mesh shape {dict(zip(names, sizes))} > {n} devices")
    if prod < n:
        import logging

        logging.getLogger(__name__).warning(
            "mesh shape %s uses %d of %d devices (prefix sub-mesh)",
            dict(zip(names, sizes)), prod, n,
        )
    dev_array = np.asarray(devices[:prod]).reshape(sizes)
    return Mesh(dev_array, axis_names=tuple(names))


def set_mesh(mesh: Mesh) -> None:
    global _current_mesh
    _current_mesh = mesh


def get_mesh() -> Mesh:
    global _current_mesh
    if _current_mesh is None:
        _current_mesh = make_mesh()
    return _current_mesh


def _enable_cpu_collectives() -> None:
    """jax>=0.4.30 CPU backends refuse cross-process computations
    ("Multiprocess computations aren't implemented on the CPU
    backend") unless a collectives implementation is configured BEFORE
    the backend is created. When this jaxlib ships the gloo TCP
    collectives, turn them on so the multi-process CPU smoke path
    (launch/test_distributed_multiprocess) runs like it did on older
    runtimes. No-op on TPU/GPU platforms and on jaxlibs without gloo."""
    try:
        from jax._src.lib import xla_client as _xc

        if not hasattr(_xc._xla, "make_gloo_tcp_collectives"):
            return
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - best-effort compat shim
        pass


def distributed_init(coordinator_address=None, num_processes=None, process_id=None):
    """Multi-host control-plane bootstrap (replaces etcd registration of
    go/pserver/etcd_client.go and the sockets of pserver/LightNetwork.h)."""
    if coordinator_address is not None:
        _enable_cpu_collectives()
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
