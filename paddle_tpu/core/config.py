"""Config IR — the serializable model/trainer configuration.

The reference's IR is protobuf (proto/ModelConfig.proto:326,608 LayerConfig/
ModelConfig, proto/TrainerConfig.proto, proto/ParameterConfig.proto) emitted
by a Python DSL (python/paddle/trainer/config_parser.py:3724). We keep the
same three-tier design — user DSL -> serializable IR -> executor — but the IR
is plain dataclasses with JSON round-trip: the executor is jit-compiled JAX,
so there is no cross-language boundary that would require protobuf.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ParameterConf:
    """Per-parameter config (reference: proto/ParameterConfig.proto,
    paddle/parameter/Parameter.h:46)."""

    name: str = ""
    dims: tuple = ()
    learning_rate: float = 1.0  # per-parameter LR multiplier
    momentum: Optional[float] = None
    decay_rate: Optional[float] = None  # L2; None = use global
    decay_rate_l1: Optional[float] = None
    initial_mean: float = 0.0
    initial_std: Optional[float] = None  # None => 1/sqrt(fan_in)
    initial_strategy: str = "normal"  # normal | uniform | zero | constant
    initial_value: float = 0.0  # for constant strategy
    is_static: bool = False  # frozen parameter
    is_shared: bool = False
    sparse_update: bool = False  # row-sparse gradient (embeddings)
    sparse_remote_update: bool = False  # sharded-across-mesh table
    gradient_clipping_threshold: float = 0.0
    # static pruning hook (ParameterUpdaterHook.cpp:39): fraction of
    # weights zero-masked by initial magnitude; None = no pruning
    sparsity_ratio: Optional[float] = None
    # MoE expert weight [E, ...]: shard the leading expert dim over the
    # mesh model axis (expert parallelism)
    expert_sharded: bool = False
    # user callback name -> ndarray (reference ParameterAttribute
    # initializer, python/paddle/v2/attr + parameters.py:update hooks)
    initializer: Optional[object] = None

    def to_dict(self):
        d = dataclasses.asdict(self)
        d["dims"] = list(self.dims)
        return d


@dataclass
class InputConf:
    """One input edge of a layer (reference: proto/ModelConfig.proto
    LayerInputConfig)."""

    name: str  # producing layer name
    parameter: Optional[ParameterConf] = None  # weight on this edge, if any
    attrs: dict = field(default_factory=dict)  # conv/pool/proj specifics


@dataclass
class LayerConf:
    """One layer (reference: proto/ModelConfig.proto:326 LayerConfig).

    `attrs` carries layer-type-specific settings (kernel sizes, pool type,
    beam size, ...) that the proto kept in dedicated sub-messages.
    """

    name: str
    type: str
    size: int = 0
    inputs: list = field(default_factory=list)  # list[InputConf]
    active_type: str = ""  # "" = linear
    bias: bool = True
    bias_parameter: Optional[ParameterConf] = None
    drop_rate: float = 0.0
    device: Optional[int] = None  # model-parallel placement hint
    attrs: dict = field(default_factory=dict)

    def input_names(self):
        return [i.name for i in self.inputs]


@dataclass
class SubModelConf:
    """Recurrent-group sub-network (reference: proto/ModelConfig.proto:579
    SubModelConfig): layer names belonging to the group, in/out links and
    memory wiring."""

    name: str
    layer_names: list = field(default_factory=list)
    in_links: list = field(default_factory=list)  # [{layer_name, link_name}]
    out_links: list = field(default_factory=list)
    memories: list = field(default_factory=list)  # [{layer_name, link_name, boot_*}]
    reversed: bool = False
    is_generating: bool = False
    attrs: dict = field(default_factory=dict)


@dataclass
class ModelConf:
    """Whole-network config (reference: proto/ModelConfig.proto:608)."""

    layers: list = field(default_factory=list)  # list[LayerConf], topo order
    input_layer_names: list = field(default_factory=list)
    output_layer_names: list = field(default_factory=list)
    sub_models: list = field(default_factory=list)  # list[SubModelConf]

    def layer(self, name: str) -> LayerConf:
        for lc in self.layers:
            if lc.name == name:
                return lc
        raise KeyError(f"no layer named {name!r}")

    # ---- JSON round-trip ----
    def to_json(self) -> str:
        return json.dumps(_to_jsonable(self), indent=1, sort_keys=True)

    @staticmethod
    def from_json(s: str) -> "ModelConf":
        return _model_from_dict(json.loads(s))


@dataclass
class OptimizationConf:
    """Optimizer settings (reference: proto/TrainerConfig.proto
    OptimizationConfig; python/paddle/trainer_config_helpers/optimizers.py)."""

    batch_size: int = 1
    learning_method: str = "sgd"
    learning_rate: float = 0.01
    learning_rate_decay_a: float = 0.0
    learning_rate_decay_b: float = 0.0
    learning_rate_schedule: str = "constant"
    learning_rate_args: str = ""
    momentum: float = 0.0
    use_nesterov: bool = False
    l1_rate: float = 0.0
    l2_rate: float = 0.0
    ada_epsilon: float = 1e-6
    ada_rou: float = 0.95
    adam_beta1: float = 0.9
    adam_beta2: float = 0.999
    adam_epsilon: float = 1e-8
    gradient_clipping_threshold: float = 0.0
    average_window: float = 0.0
    max_average_window: int = 0
    num_batches_per_send_parameter: int = 1
    batches_per_pass: int = 0  # for pass_manual LR scheduling


@dataclass
class TrainerConf:
    """Top-level trainer config (reference: proto/TrainerConfig.proto)."""

    model: ModelConf = field(default_factory=ModelConf)
    opt: OptimizationConf = field(default_factory=OptimizationConf)
    num_passes: int = 1
    save_dir: Optional[str] = None


# ---- serialization helpers ----

_CLASSES = {
    "ParameterConf": ParameterConf,
    "InputConf": InputConf,
    "LayerConf": LayerConf,
    "SubModelConf": SubModelConf,
    "ModelConf": ModelConf,
    "OptimizationConf": OptimizationConf,
    "TrainerConf": TrainerConf,
}


def _to_jsonable(obj: Any) -> Any:
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        d = {"__cls__": type(obj).__name__}
        for f in dataclasses.fields(obj):
            d[f.name] = _to_jsonable(getattr(obj, f.name))
        return d
    if isinstance(obj, (list, tuple)):
        return [_to_jsonable(x) for x in obj]
    if isinstance(obj, dict):
        return {k: _to_jsonable(v) for k, v in obj.items()}
    if callable(obj):
        # session-only callbacks (ParameterConf.initializer, beam
        # hooks) don't persist — values they produced already live in
        # the checkpoint; a reloaded config falls back to the default
        # initialization path
        return None
    return obj


def _from_jsonable(obj: Any) -> Any:
    if isinstance(obj, dict):
        if "__cls__" in obj:
            cls = _CLASSES[obj["__cls__"]]
            kwargs = {
                k: _from_jsonable(v) for k, v in obj.items() if k != "__cls__"
            }
            if "dims" in kwargs:
                kwargs["dims"] = tuple(kwargs["dims"])
            return cls(**kwargs)
        return {k: _from_jsonable(v) for k, v in obj.items()}
    if isinstance(obj, list):
        return [_from_jsonable(x) for x in obj]
    return obj


def _model_from_dict(d: dict) -> ModelConf:
    out = _from_jsonable(d)
    assert isinstance(out, ModelConf)
    return out
