"""Profiling hooks.

Reference: compile-gated REGISTER_TIMER stats (utils/Stat.h:63,244 — our
core/stat.py) plus GPU profiler start/stop around nvprof capture
(cuda/include/hl_cuda.h:338-343, math/tests/test_GpuProfiler.cpp). TPU
equivalent: the JAX/XLA profiler writing XPlane traces viewable in
TensorBoard/xprof, with named scopes instead of REGISTER_TIMER macros.
"""

from __future__ import annotations

import contextlib

import jax

__all__ = ["start", "stop", "trace", "scope", "annotate_fn"]


def start(log_dir: str) -> None:
    """Begin an XPlane trace capture (hl_profiler_start analogue)."""
    jax.profiler.start_trace(log_dir)


def stop() -> None:
    """End the capture (hl_profiler_end analogue)."""
    jax.profiler.stop_trace()


@contextlib.contextmanager
def trace(log_dir: str):
    start(log_dir)
    try:
        yield
    finally:
        stop()


def scope(name: str):
    """Named region inside a trace — the REGISTER_TIMER_INFO analogue;
    shows as an annotation over the device ops it encloses."""
    return jax.profiler.TraceAnnotation(name)


def annotate_fn(name: str):
    """Decorator form of `scope`."""

    def deco(fn):
        def wrapped(*a, **kw):
            with jax.profiler.TraceAnnotation(name):
                return fn(*a, **kw)

        return wrapped

    return deco
