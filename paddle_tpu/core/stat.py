"""Timers and stats — now a VIEW over the unified metrics registry.

Capability match for the reference's Stat/StatSet + REGISTER_TIMER
macros (paddle/utils/Stat.h:63,114,244) and per-layer timing in
NeuralNetwork.cpp:248. Since ISSUE 10 the actual timer state lives in
`paddle_tpu.obs.metrics` (one registry histogram per timer, family
name `stat.<set>.<timer>`): every `REGISTER_TIMER`-style measurement
is simultaneously visible to the metrics snapshot / `metricz` /
event-stream machinery, and this module keeps only the reference's
*presentation* — the per-pass report text (TrainerInternal.cpp:177
area) is byte-compatible with the pre-registry format.

No duplicate timer plumbing: `StatInfo` holds no numbers of its own;
total/count/max/min/avg all read through to the registry histogram.
"""

from __future__ import annotations

import contextlib
import threading
import time

from paddle_tpu.obs import metrics as _metrics


class StatInfo:
    """View over one registry histogram (seconds)."""

    __slots__ = ("_hist",)

    def __init__(self, hist: _metrics.Histogram):
        self._hist = hist

    def add(self, dt: float):
        self._hist.observe(dt)

    @property
    def total(self) -> float:
        return self._hist.sum()

    @property
    def count(self) -> int:
        return self._hist.count()

    @property
    def max(self) -> float:
        return self._hist.max()

    @property
    def min(self) -> float:
        return self._hist.min()

    @property
    def avg(self) -> float:
        return self._hist.avg()


class StatSet:
    def __init__(self, name: str = "default", registry=None):
        self.name = name
        self._reg = registry or _metrics.get_registry()
        self._names: set = set()
        self._lock = threading.Lock()

    @property
    def _prefix(self) -> str:
        return f"stat.{self.name}."

    def stat(self, name: str) -> StatInfo:
        with self._lock:
            self._names.add(name)
        return StatInfo(self._reg.histogram(self._prefix + name))

    @contextlib.contextmanager
    def timer(self, name: str):
        stat = self.stat(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            stat.add(time.perf_counter() - t0)

    def report(self) -> str:
        lines = [f"=== StatSet[{self.name}] ==="]
        with self._lock:
            names = sorted(self._names)
        for name in names:
            s = StatInfo(self._reg.histogram(self._prefix + name))
            if not s.count:
                continue  # reset since last use: nothing to report
            lines.append(
                f"{name:40s} count={s.count:8d} total={s.total:10.4f}s "
                f"avg={s.avg * 1e3:9.3f}ms max={s.max * 1e3:9.3f}ms"
            )
        return "\n".join(lines)

    def reset(self):
        """Zero this set's registry series in place (held StatInfo
        views keep working — they read through to the same
        histograms)."""
        self._reg.reset_prefix(self._prefix)
        with self._lock:
            self._names.clear()


GLOBAL_STATS = StatSet("global")
timer = GLOBAL_STATS.timer
