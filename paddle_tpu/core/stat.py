"""Timers and stats.

Capability match for the reference's Stat/StatSet + REGISTER_TIMER macros
(paddle/utils/Stat.h:63,114,244) and per-layer timing in
NeuralNetwork.cpp:248. On TPU, intra-step timing belongs to the XLA
profiler; these host-side timers measure whole steps / phases and feed
the per-pass report the trainer logs (TrainerInternal.cpp:177 area).
"""

from __future__ import annotations

import contextlib
import threading
import time


class StatInfo:
    __slots__ = ("total", "count", "max", "min")

    def __init__(self):
        self.total = 0.0
        self.count = 0
        self.max = 0.0
        self.min = float("inf")

    def add(self, dt: float):
        self.total += dt
        self.count += 1
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0


class StatSet:
    def __init__(self, name: str = "default"):
        self.name = name
        self._stats: dict[str, StatInfo] = {}
        self._lock = threading.Lock()

    def stat(self, name: str) -> StatInfo:
        with self._lock:
            return self._stats.setdefault(name, StatInfo())

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.stat(name).add(time.perf_counter() - t0)

    def report(self) -> str:
        lines = [f"=== StatSet[{self.name}] ==="]
        for name in sorted(self._stats):
            s = self._stats[name]
            lines.append(
                f"{name:40s} count={s.count:8d} total={s.total:10.4f}s "
                f"avg={s.avg * 1e3:9.3f}ms max={s.max * 1e3:9.3f}ms"
            )
        return "\n".join(lines)

    def reset(self):
        with self._lock:
            self._stats.clear()


GLOBAL_STATS = StatSet("global")
timer = GLOBAL_STATS.timer
