"""Class registries keyed by type name.

Equivalent in spirit to the reference's ClassRegistrar
(paddle/utils/ClassRegistrar.h) and the REGISTER_LAYER /
REGISTER_EVALUATOR macros (paddle/gserver/layers/Layer.h:30-37,
paddle/gserver/evaluators/Evaluator.cpp), but a plain decorator-based
Python registry: TPU-side compute is jit-compiled functions, so there is
no need for per-device kernel registration.
"""

from __future__ import annotations


class Registry:
    def __init__(self, kind: str):
        self.kind = kind
        self._table: dict[str, type] = {}

    def register(self, *names):
        def deco(cls):
            for n in names:
                if n in self._table:
                    raise KeyError(f"duplicate {self.kind} type {n!r}")
                self._table[n] = cls
            cls.type_names = tuple(names)
            return cls

        return deco

    def get(self, name: str) -> type:
        try:
            return self._table[name]
        except KeyError:
            known = ", ".join(sorted(self._table))
            raise KeyError(
                f"unknown {self.kind} type {name!r}; registered: {known}"
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._table

    def names(self):
        return sorted(self._table)


LAYERS = Registry("layer")
ACTIVATIONS = Registry("activation")
EVALUATORS = Registry("evaluator")
OPTIMIZERS = Registry("optimizer")
LR_SCHEDULERS = Registry("lr_scheduler")
PROJECTIONS = Registry("projection")
OPERATORS = Registry("operator")
DATA_PROVIDERS = Registry("data_provider")
