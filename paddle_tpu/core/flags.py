"""Global process flags.

Mirrors the reference's 27 gflags (paddle/utils/Flags.cpp:18-82) in
capability: a typed global key/value store consulted by the trainer,
data pipeline and parallel runtime. TPU-specific flags replace
GPU-specific ones (use_gpu -> platform, trainer_count -> mesh shape).
"""

from __future__ import annotations

from typing import Any

_DEFAULTS: dict[str, Any] = {
    # device / mesh
    "platform": None,  # None = jax default; "cpu" forces host backend
    "mesh_shape": None,  # e.g. {"data": 8} — default: all devices on "data"
    # training loop
    "log_period": 100,
    "show_parameter_stats_period": 0,
    "test_period": 0,
    "seed": 0,  # 0 = nondeterministic seed from OS entropy
    # FP-exception trap (reference enables feenableexcept at trainer
    # start, trainer/TrainerMain.cpp:49): aborts on NaN-producing ops
    "trap_fp": False,
    # training watchdog (trainer/watchdog.py): on-device non-finite
    # skip + EWMA spike ladder + checkpoint rollback + SIGTERM-safe
    # preemption. False disables (raw 2017 semantics: a NaN batch
    # poisons the params silently).
    "watchdog": True,
    # PRNG implementation: None = jax default (threefry). "rbg" is
    # substantially faster on TPU for dropout-heavy models (~27% whole
    # -step on AlexNet) at the cost of weaker shard-stability guarantees
    "prng_impl": None,
    "save_dir": None,
    "saving_period": 1,
    "save_only_one": False,
    # "sync": training stalls for the whole serialize+write;
    # "async": only the device->host snapshot blocks, serialization and
    # the atomic-rename shard write overlap the next pass
    # (trainer/async_checkpoint.py)
    "checkpoint_mode": "sync",
    "start_pass": 0,
    # multi-step pipelining (ROADMAP 5d): >1 runs N consecutive train
    # steps as one jitted scan-of-steps dispatch (SGD steps_per_dispatch
    # default; the bench-trick promoted to a trainer option). Short-step
    # models stop paying the ~2-10 ms per-program dispatch floor per
    # batch; events/evaluators/watchdog still see every batch.
    "steps_per_dispatch": 1,
    # recompile guard (analysis/recompile_guard.py, ISSUE 13): after
    # the first pass (warmup — every expected shape incl. the ragged
    # reader tail has traced once) the trainer arms the TrainStep's
    # jit-cache-miss tracker. "off" = never arm; "record" = count
    # steady-state retraces (recompile_guard.violations metric +
    # SGD.recompile_violations()) without failing; "strict" = raise
    # RecompileError from inside the retrace — the bench/CI mode.
    "recompile_guard": "off",
    # per-step timeline attribution (obs/timeline.py): fence the
    # device with block_until_ready every N steps so device_step is
    # measured end-to-end while steady-state dispatch stays async.
    # 0 = never fence (fetches the loop makes anyway still count).
    "timeline_sample_period": 16,
    # distributed tracing (obs/tracing.py): trainer step spans ride
    # the timeline_sample_period fences; serving traces every request
    # that arrives WITH a carrier, plus every Nth anonymous request
    # when trace_serve_period > 0 (0 = carrier-bearing only)
    "trace_serve_period": 0,
    # flight recorder (obs/flight_recorder.py): ring size, dump rate
    # limit, dump-dir bound, and the guarded jax-profiler capture hook
    "flight_ring_capacity": 4096,
    "flight_min_dump_interval_s": 60.0,
    "flight_max_bundles": 8,
    "flight_profiler_capture": False,
    # anomaly thresholds that trip a flight-recorder dump on the
    # serving path: admitted-p99 SLO (ms over a 128-request sliding
    # window; 0 disables) and shed-rate spike (shed fraction over a
    # serve_shed_window_s window, needing >= 20 decisions)
    "serve_p99_slo_ms": 0,
    "serve_shed_rate_threshold": 0.5,
    "serve_shed_window_s": 5.0,
    # fleet SLO burn-rate monitor (obs/aggregate.py wired into
    # serving/fleet.py, ISSUE 17): availability target, the
    # fast/slow multi-window burn-rate pairs (Google-SRE style: an
    # alert needs the budget burning in BOTH the short window and its
    # long companion), and the incident-bundle dump discipline (same
    # rate-limit + bounded-dir contract as the flight recorder)
    "fleet_availability_target": 0.999,
    "fleet_burn_fast_window_s": 60.0,
    "fleet_burn_fast_threshold": 14.4,
    "fleet_burn_slow_window_s": 300.0,
    "fleet_burn_slow_threshold": 6.0,
    "fleet_burn_min_decisions": 20,
    "fleet_incident_min_interval_s": 60.0,
    "fleet_incident_max_bundles": 8,
    # data
    "prefetch_depth": 2,
    # kernels: None = auto (fused Pallas cells on TPU, lax.scan elsewhere)
    "use_pallas_rnn": None,
    # precision policy: params in float32, matmuls in bfloat16 by default
    "default_dtype": "float32",
    "matmul_precision": "default",
    # generation
    "beam_size": 1,
    # distributed control plane
    "coordinator_address": None,
    "process_id": 0,
    "num_processes": 1,
}

_flags: dict[str, Any] = dict(_DEFAULTS)


def get_flag(name: str) -> Any:
    if name not in _flags:
        raise KeyError(f"unknown flag {name!r}")
    return _flags[name]


def set_flag(name: str, value: Any) -> None:
    _flags[name] = value


def reset_flags() -> None:
    _flags.clear()
    _flags.update(_DEFAULTS)
