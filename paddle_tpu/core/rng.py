"""RNG plumbing: explicit, splittable JAX PRNG keys.

The reference seeds a global generator per thread (paddle/utils/Util.h
ThreadLocalRand); JAX is functional, so the trainer owns a root key and
splits per purpose (init / dropout / sampling) and per step.
"""

from __future__ import annotations

import os

import jax


def root_key(seed: int = 0) -> jax.Array:
    if seed == 0:
        seed = int.from_bytes(os.urandom(4), "little")
    return jax.random.key(seed)


def split_for_step(key: jax.Array, step) -> jax.Array:
    """Derive a per-step key (fold_in keeps it O(1) state)."""
    return jax.random.fold_in(key, step)
