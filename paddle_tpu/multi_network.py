"""MultiNetwork: several submodels trained jointly in one program.

Reference: gserver/gradientmachines/MultiNetwork.h — a GradientMachine
holding N sub-networks, forwarding each with its own in/out args and
summing their costs into one training signal (used for multi-task
setups). TPU-first: the submodels are merged into ONE ModelConf (layer
and parameter names prefixed per submodel, shared-parameter names left
untouched so submodels can share weights by name) and compiled as a
single XLA program — the jointly-trained equivalent without a special
executor.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

from paddle_tpu.core.config import InputConf, LayerConf, ModelConf


def merge_confs(
    confs: Dict[str, ModelConf],
    share_params: bool = True,
) -> ModelConf:
    """Merge named submodel configs into one ModelConf.

    Layer names become "<sub>/<layer>"; data-layer (feed) names are
    prefixed too, so each submodel keeps its own inputs. Explicit
    parameter names (user-set, e.g. shared embeddings) are preserved
    when `share_params` — identical names across submodels alias one
    parameter, exactly how MultiNetwork shares via the parameter map.
    Auto-named parameters (layer-derived "_<layer>.w0") follow their
    prefixed layer automatically.
    """
    merged = ModelConf()
    for sub, conf in confs.items():
        # referenceable names: the submodel's layers, plus every step-net
        # layer (recurrent-group secondary out_links surface under step
        # names), recursively for nested groups
        names = {lc.name for lc in conf.layers}
        stack = [
            lc.attrs["step_conf"]
            for lc in conf.layers
            if "step_conf" in lc.attrs
        ]
        while stack:
            sc = stack.pop()
            for slc in sc.layers:
                names.add(slc.name)
                if "step_conf" in slc.attrs:
                    stack.append(slc.attrs["step_conf"])

        def _ref(n):
            # extra outputs ("moe@aux") reference their producer layer
            # before the '@'; prefix whenever the base is local
            return n in names or n.split("@")[0] in names

        for lc in conf.layers:
            nlc = dataclasses.replace(
                lc,
                name=f"{sub}/{lc.name}",
                inputs=[
                    dataclasses.replace(
                        ic,
                        name=(
                            f"{sub}/{ic.name}" if _ref(ic.name) else ic.name
                        ),
                    )
                    for ic in lc.inputs
                ],
            )
            if "step_conf" in nlc.attrs:
                # recurrent groups carry layer-name references in attrs:
                # memories[].boot_layer names a PARENT layer; the step
                # net's own layer names must be prefixed too so two
                # merged submodels' auto-named step params ("_s.w0")
                # never alias
                nlc.attrs = _prefix_group_attrs(
                    sub, nlc.attrs, share_params
                )
            if not share_params:
                # privatize explicit param names per submodel
                for ic in nlc.inputs:
                    if ic.parameter is not None and ic.parameter.name:
                        ic.parameter = dataclasses.replace(
                            ic.parameter,
                            name=f"{sub}/{ic.parameter.name}",
                        )
                if nlc.bias_parameter is not None and nlc.bias_parameter.name:
                    nlc.bias_parameter = dataclasses.replace(
                        nlc.bias_parameter,
                        name=f"{sub}/{nlc.bias_parameter.name}",
                    )
            merged.layers.append(nlc)
        merged.input_layer_names.extend(
            f"{sub}/{n}" for n in conf.input_layer_names
        )
        merged.output_layer_names.extend(
            f"{sub}/{n}" for n in conf.output_layer_names
        )
    return merged


def _prefix_group_attrs(sub: str, attrs: dict, share_params: bool) -> dict:
    """Prefix the layer-name references inside a recurrent_group's attrs
    (layers/recurrent_group.py:19-27): step_conf layer names +
    in/static/out_links + memories' step-side "layer"/"link" get the
    submodel prefix; memories' parent-side "boot_layer" gets it too
    (the parent layer itself was just renamed)."""
    a = dict(attrs)
    p = lambda n: f"{sub}/{n}" if n else n
    step: ModelConf = a["step_conf"]
    new_step = ModelConf()
    for lc in step.layers:
        nlc = dataclasses.replace(
            lc,
            name=p(lc.name),
            inputs=[
                dataclasses.replace(ic, name=p(ic.name))
                for ic in lc.inputs
            ],
        )
        if "step_conf" in nlc.attrs:  # nested recurrent group
            nlc.attrs = _prefix_group_attrs(sub, nlc.attrs, share_params)
        if not share_params:
            for ic in nlc.inputs:
                if ic.parameter is not None and ic.parameter.name:
                    ic.parameter = dataclasses.replace(
                        ic.parameter, name=p(ic.parameter.name)
                    )
            if nlc.bias_parameter is not None and nlc.bias_parameter.name:
                nlc.bias_parameter = dataclasses.replace(
                    nlc.bias_parameter, name=p(nlc.bias_parameter.name)
                )
        new_step.layers.append(nlc)
    a["step_conf"] = new_step
    a["in_links"] = [p(n) for n in a.get("in_links", [])]
    a["static_links"] = [p(n) for n in a.get("static_links", [])]
    a["out_links"] = [p(n) for n in a.get("out_links", [])]
    a["memories"] = [
        {
            **m,
            "layer": p(m.get("layer")),
            "link": p(m.get("link")),
            "boot_layer": p(m.get("boot_layer")),
        }
        for m in a.get("memories", [])
    ]
    return a


def prefix_feed(sub: str, feed: dict) -> dict:
    """Rewrite a submodel's feed dict to merged names."""
    return {f"{sub}/{k}": v for k, v in feed.items()}
