"""MultiNetwork: several submodels trained jointly in one program.

Reference: gserver/gradientmachines/MultiNetwork.h — a GradientMachine
holding N sub-networks, forwarding each with its own in/out args and
summing their costs into one training signal (used for multi-task
setups). TPU-first: the submodels are merged into ONE ModelConf (layer
and parameter names prefixed per submodel, shared-parameter names left
untouched so submodels can share weights by name) and compiled as a
single XLA program — the jointly-trained equivalent without a special
executor.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Sequence

from paddle_tpu.core.config import InputConf, LayerConf, ModelConf


def merge_confs(
    confs: Dict[str, ModelConf],
    share_params: bool = True,
) -> ModelConf:
    """Merge named submodel configs into one ModelConf.

    Layer names become "<sub>/<layer>"; data-layer (feed) names are
    prefixed too, so each submodel keeps its own inputs. Explicit
    parameter names (user-set, e.g. shared embeddings) are preserved
    when `share_params` — identical names across submodels alias one
    parameter, exactly how MultiNetwork shares via the parameter map.
    Auto-named parameters (layer-derived "_<layer>.w0") follow their
    prefixed layer automatically.
    """
    merged = ModelConf()
    for sub, conf in confs.items():
        names = {lc.name for lc in conf.layers}
        for lc in conf.layers:
            nlc = dataclasses.replace(
                lc,
                name=f"{sub}/{lc.name}",
                inputs=[
                    dataclasses.replace(
                        ic,
                        name=(
                            f"{sub}/{ic.name}"
                            if ic.name in names
                            else ic.name
                        ),
                    )
                    for ic in lc.inputs
                ],
            )
            if not share_params:
                # privatize explicit param names per submodel
                for ic in nlc.inputs:
                    if ic.parameter is not None and ic.parameter.name:
                        ic.parameter = dataclasses.replace(
                            ic.parameter,
                            name=f"{sub}/{ic.parameter.name}",
                        )
                if nlc.bias_parameter is not None and nlc.bias_parameter.name:
                    nlc.bias_parameter = dataclasses.replace(
                        nlc.bias_parameter,
                        name=f"{sub}/{nlc.bias_parameter.name}",
                    )
            merged.layers.append(nlc)
        merged.input_layer_names.extend(
            f"{sub}/{n}" for n in conf.input_layer_names
        )
        merged.output_layer_names.extend(
            f"{sub}/{n}" for n in conf.output_layer_names
        )
    return merged


def prefix_feed(sub: str, feed: dict) -> dict:
    """Rewrite a submodel's feed dict to merged names."""
    return {f"{sub}/{k}": v for k, v in feed.items()}
