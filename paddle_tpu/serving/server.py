"""Continuous-batching inference server with overload protection.

The composition ROADMAP item 3 names: requests arrive continuously and
variable-length, are admitted into a BOUNDED queue (full queue =
explicit rejection, never unbounded growth), and a scheduler thread
packs compatible requests — same model, same length bucket, same hook
configuration — into batches dispatched to the cached bucketed decode
programs. SLO machinery, in dispatch order:

- **Load shedding at admission.** `submit` rejects with
  `ServeRejected("overloaded")` the instant the queue is full. Orca's
  and vLLM's admission story: overload shows up as fast explicit
  failures the client can retry elsewhere, not as latency collapse.
- **Deadline-aware batch formation.** Every request carries a
  deadline. At batch-formation time the scheduler drops requests whose
  deadline has passed OR whose remaining budget is smaller than the
  model's EWMA batch service time — expired work is rejected BEFORE it
  wastes a decode program, not after.
- **Bucketed continuous packing.** Sequence lengths round up to the
  feeder's buckets and batch sizes round up to power-of-two batch
  buckets, so the jit program cache stays bounded at
  O(len_buckets × batch_buckets) per model instead of one program per
  arrival shape.
- **Degradation ladder.** Rung 1: the jitted while-loop decode. Rung 2
  (hooks present, or rung 1 raised and `host_fallback`): host-stepped
  per-token decode (`host_decode.py`) — generation hooks run as plain
  Python, closing the "hook-bearing request gets no TPU path" hole.
  Rung 3: explicit failure.
- **Circuit breaker per model.** `breaker_threshold` consecutive
  dispatch failures quarantine the model: submits reject instantly
  with `ServeRejected("quarantined")` for `breaker_reset_s`, then one
  half-open probe batch decides re-close vs re-open — a model whose
  decode program is poisoned cannot eat the whole queue.
- **Drain on shutdown.** `shutdown(drain=True)` stops admission
  (rejects with "shutting_down"), lets the scheduler finish or
  deadline-reject everything queued, and joins the workers. Every
  request ever admitted reaches a terminal state — nothing leaks.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from paddle_tpu.analysis.lock_order import named_lock
from paddle_tpu.analysis.recompile_guard import RecompileError
from paddle_tpu.core import flags as _flags
from paddle_tpu.data.feeder import _bucket
from paddle_tpu.obs import metrics as _obs
from paddle_tpu.obs import tracing as _tracing
from paddle_tpu.obs import flight_recorder as _flight


class ServeRejected(Exception):
    """Explicit request rejection. `reason` is one of: overloaded,
    deadline, quarantined, shutting_down, unknown_model,
    unknown_hook."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}{': ' + detail if detail else ''}")
        self.reason = reason


class ServeError(Exception):
    """The request was dispatched but execution failed on every rung."""


@dataclass
class ServeConfig:
    max_queue: int = 64           # admission bound (requests)
    max_batch: int = 8            # per-dispatch batch cap
    default_deadline_s: float = 2.0
    buckets: tuple = (8, 16, 32, 64, 128)  # sequence-length buckets
    breaker_threshold: int = 3    # consecutive failures -> quarantine
    breaker_reset_s: float = 5.0  # quarantine window before half-open
    host_fallback: bool = True    # rung-2 on jitted dispatch failure
    workers: int = 1              # scheduler/dispatch threads
    # margin multiplier on the EWMA service time used by the
    # deadline-aware batch former (drop if remaining < ewma * margin)
    service_margin: float = 1.0

    def batch_bucket(self, n: int) -> int:
        b = 1
        while b < n and b < self.max_batch:
            b *= 2
        return min(b, self.max_batch)


_ids = itertools.count(1)


class PendingResult:
    """Handle returned by submit(): blocks in result(), or poll state.
    Terminal states: done / rejected / error."""

    __slots__ = ("id", "model", "ids", "bucket", "deadline", "hooks",
                 "hooks_key", "t_submit", "t_done", "_event", "_result",
                 "_exc", "trace_id", "parent_span", "span_id",
                 "t_popped")

    def __init__(self, model, ids, bucket, deadline, hooks, hooks_key,
                 trace=None):
        self.id = next(_ids)
        self.model = model
        self.ids = ids
        self.bucket = bucket
        self.deadline = deadline
        self.hooks = hooks
        self.hooks_key = hooks_key
        self.t_submit = time.monotonic()
        self.t_done = None
        # tracing: (trace_id, parent span from the carrier); span_id
        # is this request's pre-allocated `serve.request` root so
        # spans can be stamped post-hoc from any worker thread
        self.trace_id = trace[0] if trace else None
        self.parent_span = (trace[1] or "") if trace else ""
        self.span_id = _tracing.new_span_id() if trace else None
        self.t_popped = None  # set when batch formation picks it up
        self._event = threading.Event()
        self._result = None
        self._exc = None

    # -- completion (server side) --
    def _finish(self, result=None, exc=None):
        self._result, self._exc = result, exc
        self.t_done = time.monotonic()
        self._event.set()

    # -- consumption (client side) --
    @property
    def state(self) -> str:
        if not self._event.is_set():
            return "pending"
        if self._exc is None:
            return "done"
        if isinstance(self._exc, ServeRejected):
            return f"rejected:{self._exc.reason}"
        return "error"

    @property
    def latency_s(self) -> Optional[float]:
        return None if self.t_done is None else self.t_done - self.t_submit

    def result(self, timeout: float = None):
        if not self._event.wait(timeout):
            raise TimeoutError(f"request {self.id} still pending")
        if self._exc is not None:
            raise self._exc
        return self._result


class _Breaker:
    """Per-model circuit breaker: closed -> open after N consecutive
    failures -> half-open probe after reset_s -> closed on success.
    State transitions are counted in the process registry
    (`serving.breaker_opens{model=}` / `serving.dispatch_failures`).

    Thread-safe on its own lock (ISSUE 16): InferenceServer always
    called it under the admission lock, but the fleet router shares
    the class across its routing threads with no outer lock — two
    threads racing `try_probe()` in half-open must admit exactly one
    probe. The internal lock is a leaf (ordered strictly after
    `serving.admission` wherever both are held)."""

    def __init__(self, threshold: int, reset_s: float,
                 model: str = ""):
        self.threshold = threshold
        self.reset_s = reset_s
        self.model = model
        self.failures = 0
        self.opened_at = None
        self.probing = False
        # set on a closed->open transition; the dispatch path reads
        # and clears it OUTSIDE the server lock to fire the flight-
        # recorder dump (file I/O must not run under the hot lock)
        self.just_opened = False
        self._lock = named_lock("serving.breaker")

    @property
    def state(self) -> str:
        if self.opened_at is None:
            return "closed"
        if time.monotonic() - self.opened_at >= self.reset_s:
            return "half-open"
        return "open"

    def admits(self) -> bool:
        return self.state != "open"

    def try_probe(self) -> bool:
        """In half-open, exactly one in-flight probe batch at a time —
        the probing flag is checked-and-set under the breaker lock, so
        concurrent callers cannot both win."""
        with self._lock:
            st = self.state
            if st == "closed":
                return True
            if st == "half-open" and not self.probing:
                self.probing = True
                return True
            return False

    def record(self, ok: bool):
        """A failed record while open/half-open re-opens the breaker
        with the backoff window reset (opened_at moves to now): a
        failed probe buys a full fresh quarantine, not a shortened
        one."""
        with self._lock:
            self.probing = False
            if ok:
                self.failures = 0
                self.opened_at = None
            else:
                self.failures += 1
                _obs.get_registry().counter(
                    "serving.dispatch_failures"
                ).inc(model=self.model)
                if self.failures >= self.threshold:
                    was_open = self.opened_at is not None
                    self.opened_at = time.monotonic()
                    if not was_open:
                        self.just_opened = True
                        _obs.get_registry().counter(
                            "serving.breaker_opens"
                        ).inc(model=self.model)


@dataclass
class _ModelEntry:
    model: object
    breaker: _Breaker
    ewma_batch_s: float = 0.0     # EWMA dispatch service time
    dispatch_keys: set = field(default_factory=set)


class _AnomalyWatch:
    """Serving-side flight-recorder triggers (thresholds are flags;
    see core/flags.py): a shed-rate spike over a sliding window, and
    an admitted-p99 SLO breach over the last 128 request latencies.
    All methods are called OUTSIDE the server lock and never raise —
    anomaly detection must not be able to fail the request path. The
    recorder's own rate limit is the storm control; this class only
    decides "is this moment anomalous"."""

    MIN_DECISIONS = 20  # below this a window's shed rate is noise

    def __init__(self):
        self.window_s = float(_flags.get_flag("serve_shed_window_s"))
        self.shed_threshold = float(
            _flags.get_flag("serve_shed_rate_threshold")
        )
        self.p99_slo_s = float(_flags.get_flag("serve_p99_slo_ms")) / 1e3
        self._lock = threading.Lock()
        self._win_start = time.monotonic()
        self._admitted = 0
        self._shed = 0
        self._lats = deque(maxlen=128)

    def admission(self, shed: bool) -> None:
        fire = None
        with self._lock:
            if shed:
                self._shed += 1
            else:
                self._admitted += 1
            now = time.monotonic()
            if now - self._win_start >= self.window_s:
                total = self._admitted + self._shed
                rate = self._shed / total if total else 0.0
                if (total >= self.MIN_DECISIONS
                        and rate >= self.shed_threshold):
                    fire = (rate, total)
                self._win_start = now
                self._admitted = self._shed = 0
        if fire is not None:
            reg = _obs.get_registry()
            reg.event("serving", event="shed_spike",
                      shed_rate=round(fire[0], 3), decisions=fire[1])
            _flight.maybe_dump("shed_spike",
                               shed_rate=round(fire[0], 3),
                               decisions=fire[1])

    def latency(self, lat_s: float) -> None:
        if self.p99_slo_s <= 0:
            return
        fire = None
        with self._lock:
            self._lats.append(lat_s)
            if len(self._lats) >= self.MIN_DECISIONS:
                ordered = sorted(self._lats)
                p99 = ordered[int(0.99 * (len(ordered) - 1))]
                if p99 > self.p99_slo_s:
                    fire = p99
                    self._lats.clear()  # re-arm on fresh evidence
        if fire is not None:
            reg = _obs.get_registry()
            reg.event("serving", event="slo_breach",
                      p99_ms=round(fire * 1e3, 3),
                      slo_ms=round(self.p99_slo_s * 1e3, 3))
            _flight.maybe_dump("slo_breach",
                               p99_ms=round(fire * 1e3, 3),
                               slo_ms=round(self.p99_slo_s * 1e3, 3))


class InferenceServer:
    """Register models with add_model(), feed it with submit(), stop it
    with shutdown(). Thread-safe; owns `config.workers` scheduler
    threads. A model is any object with

        run_batch(ids [B, T_bucket] int32, lens [B] int32,
                  hooks, host: bool) -> list of per-row result dicts

    plus optional `named_hooks` (str -> BeamHooks, the TCP-addressable
    hook registry) and optional `engine` (a co-dispatch group — see
    models.MultiForwardHost)."""

    def __init__(self, config: ServeConfig = None):
        self.config = config or ServeConfig()
        self._models: dict = {}
        self._queue: deque = deque()
        # the admission-queue lock — a known lock (ISSUE 13):
        # instrumented under the faults shard's lock-order checker
        # (analysis/lock_order.py); the instrumented wrapper is
        # Condition-compatible
        self._lock = named_lock("serving.admission")
        self._work = threading.Condition(self._lock)
        self._draining = False
        self._stopped = False
        self._stats = {
            "admitted": 0, "completed": 0, "completed_host": 0,
            "shed_overload": 0, "shed_deadline": 0, "shed_quarantined": 0,
            "shed_shutdown": 0, "failed": 0, "batches": 0,
            "batches_codispatch": 0, "max_queue_depth": 0,
        }
        self._anomaly = _AnomalyWatch()
        # recent completed-request exemplars for the `tracez` scrape
        self._slow: deque = deque(maxlen=256)
        self._trace_seq = itertools.count(1)  # anonymous-trace sampler
        self._threads = [
            threading.Thread(target=self._worker, name=f"serve-{i}",
                             daemon=True)
            for i in range(self.config.workers)
        ]
        for t in self._threads:
            t.start()

    # ------------------------------------------------------------ API
    def add_model(self, name: str, model) -> None:
        with self._lock:
            self._models[name] = _ModelEntry(
                model=model,
                breaker=_Breaker(self.config.breaker_threshold,
                                 self.config.breaker_reset_s,
                                 model=name),
            )

    def swap_model(self, name: str, model) -> None:
        """Atomic hot-swap (ISSUE 16 rollout): replace `name`'s model
        behind the admission queue. Requests already queued dispatch
        on the NEW model (batch formation resolves the entry at pop
        time); batches already in flight complete on the old one —
        either way every admitted request reaches a terminal state,
        so a rollout loses nothing. The fresh entry also resets the
        breaker and the EWMA service time: they described the old
        program."""
        with self._lock:
            if name not in self._models:
                raise KeyError(f"unknown model {name!r}")
            self._models[name] = _ModelEntry(
                model=model,
                breaker=_Breaker(self.config.breaker_threshold,
                                 self.config.breaker_reset_s,
                                 model=name),
            )
        _obs.get_registry().counter("serving.model_swaps").inc(
            model=name
        )

    def submit(self, model: str, ids, deadline_s: float = None,
               hooks=None, hooks_name: str = None,
               trace=None) -> PendingResult:
        """Admit one request (ids: 1-D int sequence). Raises
        ServeRejected instead of queueing when the server cannot meet
        it — the explicit-shed contract.

        `trace`: an optional carrier dict ({"trace_id", "span_id"},
        the TCP frame's `trace` field) — the request's span tree joins
        the caller's trace. Without a carrier the thread's tracing
        context applies, and `trace_serve_period` > 0 additionally
        samples every Nth anonymous request into a fresh
        server-originated trace."""
        import numpy as np

        cfg = self.config
        reg = _obs.get_registry()
        tr = _tracing.extract(trace) if trace is not None else None
        if tr is None:
            cur = _tracing.current()
            if cur is not None:
                tr = cur
            else:
                period = _flags.get_flag("trace_serve_period")
                if period and next(self._trace_seq) % period == 0:
                    tr = (_tracing.new_trace_id(), "")
        # registry updates are published AFTER self._lock is released
        # (same rule as the completion path): the lock is the admission
        # hot spot, and the registry takes locks of its own
        try:
            with self._lock:
                if self._draining or self._stopped:
                    self._stats["shed_shutdown"] += 1
                    raise ServeRejected("shutting_down")
                entry = self._models.get(model)
                if entry is None:
                    raise ServeRejected("unknown_model", model)
                if hooks_name is not None:
                    named = getattr(entry.model, "named_hooks",
                                    None) or {}
                    hooks = named.get(hooks_name)
                    if hooks is None:
                        raise ServeRejected(
                            "unknown_hook",
                            f"model {model!r} has no hook "
                            f"{hooks_name!r}",
                        )
                if not entry.breaker.admits():
                    self._stats["shed_quarantined"] += 1
                    raise ServeRejected("quarantined", model)
                if len(self._queue) >= cfg.max_queue:
                    self._stats["shed_overload"] += 1
                    raise ServeRejected(
                        "overloaded", f"queue at bound {cfg.max_queue}"
                    )
                ids = np.asarray(ids, np.int32).reshape(-1)
                bucket = _bucket(max(len(ids), 1), cfg.buckets)
                deadline = time.monotonic() + (
                    deadline_s if deadline_s is not None
                    else cfg.default_deadline_s
                )
                hooks_key = (hooks_name or id(hooks)) \
                    if hooks is not None else None
                req = PendingResult(model, ids, bucket, deadline,
                                    hooks, hooks_key, trace=tr)
                self._queue.append(req)
                depth = len(self._queue)
                self._stats["admitted"] += 1
                self._stats["max_queue_depth"] = max(
                    self._stats["max_queue_depth"], depth
                )
                self._work.notify()
        except ServeRejected as e:
            reg.counter("serving.shed").inc(reason=e.reason)
            if tr is not None:
                # a shed request still leaves a span: rejection is a
                # terminal outcome, not a missing trace
                _tracing.emit_span(
                    "serve.request", tr[0], _tracing.new_span_id(),
                    tr[1], dur_s=0.0, status=e.reason,
                    labels={"model": model},
                )
            self._anomaly.admission(shed=True)
            raise
        reg.counter("serving.admitted").inc(model=model)
        reg.gauge("serving.queue_depth").set(depth)
        reg.gauge("serving.queue_depth_hwm").set_max(depth)
        self._anomaly.admission(shed=False)
        return req

    def arm_recompile_guard(self, strict: bool = False) -> list:
        """Arm every registered model's jit-cache-miss trackers
        (ISSUE 13): call after warmup traffic has touched every
        len/batch bucket the fleet serves. From then on a retrace —
        a bucket the warmup never saw, or a churned program cache —
        is recorded (`recompile_guard.violations` metric, flight-
        recorder trigger) and, with `strict`, raises RecompileError
        out of the dispatch so the failure is loud. Returns the
        guards armed; models registered later arm on the next call."""
        return [
            g.arm(strict=strict) for g in self._iter_recompile_guards()
        ]

    def disarm_recompile_guard(self) -> None:
        for g in self._iter_recompile_guards():
            g.disarm()

    def recompile_violations(self) -> list:
        out = []
        for g in self._iter_recompile_guards():
            out.extend(g.violations)
        return out

    def _iter_recompile_guards(self):
        with self._lock:
            entries = list(self._models.values())
        for entry in entries:
            for g in getattr(entry.model, "recompile_guards", ()):
                if g is not None:
                    yield g

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["queue_depth"] = len(self._queue)
            out["models"] = {
                n: {"breaker": e.breaker.state,
                    "ewma_batch_ms": round(e.ewma_batch_s * 1e3, 2),
                    "dispatch_keys": len(e.dispatch_keys)}
                for n, e in self._models.items()
            }
            return out

    def slow_exemplars(self, top: int = 10) -> list:
        """The `tracez` payload: the slowest of the last 256 completed
        requests, each carrying its trace_id (when traced) and its
        queued-vs-dispatch split — the "which requests were slow and
        where" answer without grepping a span stream."""
        with self._lock:
            recent = list(self._slow)
        recent.sort(key=lambda e: e["latency_ms"], reverse=True)
        return recent[: max(int(top), 1)]

    def shutdown(self, drain: bool = True, timeout: float = 30.0) -> None:
        """Stop admission; with drain=True finish (or deadline-reject)
        queued work, else reject everything queued. Idempotent."""
        with self._lock:
            self._draining = True
            if not drain:
                while self._queue:
                    self._reject_locked(self._queue.popleft(),
                                        "shutting_down")
            self._work.notify_all()
        deadline = time.monotonic() + timeout
        for t in self._threads:
            t.join(max(0.0, deadline - time.monotonic()))
        with self._lock:
            self._stopped = True
            # belt-and-braces: anything a worker left behind (join
            # timeout) is rejected, never silently dropped
            while self._queue:
                self._reject_locked(self._queue.popleft(), "shutting_down")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=True)

    # ------------------------------------------------------ scheduler
    def _reject_locked(self, req: PendingResult, reason: str):
        stat = "shed_shutdown" if reason == "shutting_down" \
            else f"shed_{reason}"
        self._stats[stat] = self._stats.get(stat, 0) + 1
        _obs.get_registry().counter("serving.shed").inc(reason=reason)
        req._finish(exc=ServeRejected(reason))
        if req.trace_id is not None:
            # admitted-then-rejected: the span still closes, covering
            # the whole admitted phase, with the rejection as status
            _tracing.emit_span(
                "serve.request", req.trace_id, req.span_id,
                req.parent_span, dur_s=req.t_done - req.t_submit,
                t0_mono=req.t_submit, status=reason,
                labels={"model": req.model, "id": req.id},
            )

    def _pop_batch_locked(self):
        """Form one dispatchable batch: FIFO head picks the key
        (model, bucket, hooks); compatible requests join up to
        max_batch. Deadline-expired or budget-short requests are
        rejected here — before dispatch. Returns (entry, key, reqs) or
        None. Skips (leaves queued) requests whose model breaker is
        open-with-probe-in-flight."""
        now = time.monotonic()
        cfg = self.config
        skipped = []
        head = None
        while self._queue:
            r = self._queue.popleft()
            entry = self._models.get(r.model)
            if entry is None:
                r._finish(exc=ServeRejected("unknown_model", r.model))
                continue
            margin = entry.ewma_batch_s * cfg.service_margin
            if now > r.deadline or now + margin > r.deadline:
                self._reject_locked(r, "deadline")
                continue
            if not entry.breaker.try_probe():
                if entry.breaker.state == "open":
                    self._reject_locked(r, "quarantined")
                else:
                    skipped.append(r)  # half-open, probe in flight
                continue
            head = (entry, r)
            break
        for r in reversed(skipped):
            self._queue.appendleft(r)
        if head is None:
            return None
        entry, first = head
        key = (first.model, first.bucket, first.hooks_key)
        first.t_popped = time.monotonic()
        batch = [first]
        if entry.breaker.state == "closed":
            rest = []
            while self._queue and len(batch) < cfg.max_batch:
                r = self._queue.popleft()
                if (r.model, r.bucket, r.hooks_key) == key:
                    margin = entry.ewma_batch_s * cfg.service_margin
                    if now + margin > r.deadline:
                        self._reject_locked(r, "deadline")
                    else:
                        r.t_popped = time.monotonic()
                        batch.append(r)
                else:
                    rest.append(r)
            for r in reversed(rest):
                self._queue.appendleft(r)
        return entry, key, batch

    def _pop_sibling_batches_locked(self, engine, exclude_model: str):
        """Co-dispatch: when the head batch belongs to a multi-model
        engine, opportunistically pull one hook-free batch for each
        sibling model so a single merged program serves several models'
        traffic (the `multi_network` batching-across-models story)."""
        extra = {}
        for name in getattr(engine, "names", ()):
            if name == exclude_model:
                continue
            entry = self._models.get(name)
            # only fully-healthy siblings join a co-dispatch: half-open
            # probes stay on the head path where they are capped at one
            # request and individually accounted
            if entry is None or entry.breaker.state != "closed":
                continue
            picked, rest, key = [], [], None
            now = time.monotonic()
            margin = entry.ewma_batch_s * self.config.service_margin
            while self._queue and len(picked) < self.config.max_batch:
                r = self._queue.popleft()
                if r.model != name or r.hooks_key is not None:
                    rest.append(r)
                    continue
                if now + margin > r.deadline:
                    # same budget rule as the head path: expired or
                    # budget-short work never reaches the program
                    self._reject_locked(r, "deadline")
                    continue
                if key is None:
                    key = r.bucket
                if r.bucket == key:
                    r.t_popped = time.monotonic()
                    picked.append(r)
                else:
                    rest.append(r)
            for r in reversed(rest):
                self._queue.appendleft(r)
            if picked:
                extra[name] = (entry, picked)
        return extra

    def _worker(self):
        while True:
            with self._work:
                while not self._queue and not self._draining:
                    self._work.wait(timeout=0.1)
                if not self._queue and self._draining:
                    return
                popped = self._pop_batch_locked()
                _obs.get_registry().gauge("serving.queue_depth").set(
                    len(self._queue)
                )
                if popped is None:
                    if self._queue:
                        # everything queued is parked behind a
                        # half-open probe: yield, don't hot-spin
                        self._work.wait(timeout=0.01)
                    continue
                entry, key, batch = popped
                engine = getattr(entry.model, "engine", None)
                extra = {}
                if engine is not None and key[2] is None:
                    extra = self._pop_sibling_batches_locked(
                        engine, key[0]
                    )
            self._dispatch(entry, key, batch, engine, extra)

    # ------------------------------------------------------- dispatch
    def _pack(self, batch, bucket):
        """[B_bucket, T_bucket] ids + [B] lens; rows beyond the real
        batch repeat row 0 (pure padding — results discarded)."""
        import numpy as np

        bb = self.config.batch_bucket(len(batch))
        ids = np.zeros((bb, bucket), np.int32)
        lens = np.zeros((bb,), np.int32)
        for i, r in enumerate(batch):
            ids[i, : len(r.ids)] = r.ids
            lens[i] = len(r.ids)
        for i in range(len(batch), bb):
            ids[i] = ids[0]
            lens[i] = lens[0]
        return ids, lens

    def _emit_request_spans(self, req, t0, t_end, status, path=None,
                            dispatch_span=None, batch_n=None):
        """Stamp one admitted request's span tree post-hoc from the
        monotonic timestamps the scheduler already recorded:
        serve.request (root, child of the client carrier) over
        serve.queued / serve.batch_form / serve.dispatch. Safe from
        any thread — nothing touches the thread-local context."""
        if req.trace_id is None:
            return
        tid, root = req.trace_id, req.span_id
        labels = {"model": req.model, "id": req.id}
        if path is not None:
            labels["path"] = path
        _tracing.emit_span(
            "serve.request", tid, root, req.parent_span,
            dur_s=req.t_done - req.t_submit, t0_mono=req.t_submit,
            status=status, labels=labels,
        )
        tp = req.t_popped if req.t_popped is not None else t0
        _tracing.emit_span(
            "serve.queued", tid, _tracing.new_span_id(), root,
            dur_s=max(tp - req.t_submit, 0.0), t0_mono=req.t_submit,
        )
        _tracing.emit_span(
            "serve.batch_form", tid, _tracing.new_span_id(), root,
            dur_s=max(t0 - tp, 0.0), t0_mono=tp,
        )
        _tracing.emit_span(
            "serve.dispatch", tid,
            dispatch_span or _tracing.new_span_id(), root,
            dur_s=max(t_end - t0, 0.0), t0_mono=t0,
            labels={"batch": batch_n} if batch_n else {},
        )

    def _fire_opened_breakers(self, groups):
        """Outside-the-lock half of the breaker-open anomaly: the
        transition was flagged under the lock; the event + flight
        dump (file I/O) happen here."""
        opened = []
        with self._lock:
            for name, (en, _reqs) in groups.items():
                if en.breaker.just_opened:
                    en.breaker.just_opened = False
                    opened.append(name)
        reg = _obs.get_registry()
        for name in opened:
            reg.event("serving", event="breaker_open", model=name)
            _flight.maybe_dump("breaker_open", model=name)

    def _dispatch(self, entry, key, batch, engine=None, extra=None):
        model_name, bucket, hooks_key = key
        hooks = batch[0].hooks
        host = hooks is not None  # rung 2 whenever hooks are present
        groups = {model_name: (entry, batch)}
        if extra:
            groups.update(extra)
        # decode-rung nesting: the first traced request's dispatch
        # span is pre-allocated and attached as thread context while
        # the model runs, so host_decode's per-token spans land under
        # it (the jitted rung is opaque — one dispatch span is all it
        # can show)
        rep = next(
            (r for _, (_e, reqs) in groups.items() for r in reqs
             if r.trace_id is not None), None,
        )
        rep_dispatch = _tracing.new_span_id() if rep is not None else None
        run_ctx = _tracing.attach(
            {"trace_id": rep.trace_id, "span_id": rep_dispatch}
            if rep is not None else None
        )
        t0 = time.monotonic()
        jit_failure_counted = False
        try:
            if engine is not None and len(groups) > 1:
                packed = {
                    name: self._pack(reqs, reqs[0].bucket)
                    for name, (_, reqs) in groups.items()
                }
                with run_ctx:
                    results = engine.run_group(packed)
                with self._lock:
                    self._stats["batches_codispatch"] += 1
            else:
                ids, lens = self._pack(batch, bucket)
                try:
                    with run_ctx:
                        rows = entry.model.run_batch(ids, lens, hooks,
                                                     host)
                except Exception as dispatch_exc:
                    if isinstance(dispatch_exc, RecompileError):
                        # a STRICT armed recompile guard must stay
                        # loud (ISSUE 13): the aborted trace cached
                        # nothing, so a host rescue here would
                        # silently repeat raise->fallback on every
                        # request for this bucket while feeding false
                        # breaker records
                        raise
                    if host or not self.config.host_fallback or not \
                            getattr(entry.model, "can_host", False):
                        raise
                    # rung 2: jitted program failed; host-stepped
                    # retry. The jit failure counts toward the breaker
                    # ONCE, here — the outer handler must not count
                    # the same dispatch again if the retry fails too.
                    with self._lock:
                        entry.breaker.record(False)
                    jit_failure_counted = True
                    with _tracing.attach(
                        {"trace_id": rep.trace_id,
                         "span_id": rep_dispatch}
                        if rep is not None else None
                    ):
                        rows = entry.model.run_batch(ids, lens, hooks,
                                                     True)
                    host = True
                results = {model_name: rows}
        except Exception as e:
            t_end = time.monotonic()
            failed = []
            with self._lock:
                for name, (en, reqs) in groups.items():
                    if not (jit_failure_counted and en is entry):
                        en.breaker.record(False)
                    self._stats["failed"] += len(reqs)
                    for r in reqs:
                        r._finish(exc=ServeError(
                            f"{type(e).__name__}: {e}"
                        ))
                        if r.trace_id is not None:
                            failed.append(r)
            for r in failed:
                self._emit_request_spans(
                    r, t0, t_end, status="error",
                    dispatch_span=rep_dispatch if r is rep else None,
                    batch_n=len(batch),
                )
            self._fire_opened_breakers(groups)
            return
        dt = time.monotonic() - t0
        # per-request latencies are collected under the lock but
        # published to the registry AFTER it: submit() contends on
        # self._lock, and the registry takes its own locks
        telemetry = []
        with self._lock:
            self._stats["batches"] += 1
            for name, (en, reqs) in groups.items():
                en.breaker.record(True)
                en.ewma_batch_s = (
                    dt if en.ewma_batch_s == 0.0
                    else 0.7 * en.ewma_batch_s + 0.3 * dt
                )
                en.dispatch_keys.add(
                    (reqs[0].bucket, self.config.batch_bucket(len(reqs)),
                     reqs[0].hooks_key is not None,
                     getattr(en.model, "tokens_per_dispatch", 1))
                )
                rows = results[name]
                lats = []
                waits = []
                for i, r in enumerate(reqs):
                    out = dict(rows[i])
                    out.setdefault("path", "host" if host else "jit")
                    r._finish(result=out)
                    self._stats["completed"] += 1
                    if host:
                        self._stats["completed_host"] += 1
                    lats.append(r.t_done - r.t_submit)
                    waits.append(max(t0 - r.t_submit, 0.0))
                telemetry.append((name, lats, waits, list(reqs)))
        t_end = t0 + dt
        path_label = "host" if host else "jit"
        reg = _obs.get_registry()
        reg.counter("serving.dispatch_s").inc(dt)
        for name, lats, waits, reqs in telemetry:
            for r in reqs:
                self._emit_request_spans(
                    r, t0, t_end, status="ok", path=path_label,
                    dispatch_span=rep_dispatch if r is rep else None,
                    batch_n=len(reqs),
                )
                lat = r.t_done - r.t_submit
                tp = r.t_popped if r.t_popped is not None else t0
                # lint: unlocked-ok — deque.append is atomic under
                # the GIL and exemplars tolerate interleaving; the
                # admission lock must not cover span bookkeeping
                self._slow.append({
                    "id": r.id,
                    "model": r.model,
                    "trace_id": r.trace_id,
                    "latency_ms": round(lat * 1e3, 3),
                    "queued_ms": round(
                        max(tp - r.t_submit, 0.0) * 1e3, 3
                    ),
                    "dispatch_ms": round(dt * 1e3, 3),
                    "path": path_label,
                    "ts": round(time.time(), 3),
                })
                self._anomaly.latency(lat)
            # occupancy bookkeeping: one formed batch per group, its
            # real (un-padded) request count alongside — mean
            # occupancy = batch_requests / batches, read by the
            # serve_loadtest bench row instead of recomputed there
            reg.counter("serving.batches").inc(model=name)
            reg.counter("serving.batch_requests").inc(
                len(lats), model=name
            )
            # admitted-request time attribution: queued vs executing
            # vs (residual) scheduling overhead
            reg.counter("serving.request_latency_s").inc(sum(lats))
            reg.counter("serving.request_queue_wait_s").inc(sum(waits))
            reg.counter("serving.request_dispatch_s").inc(
                dt * len(lats)
            )
            hist = reg.histogram("serving.admitted_latency_s")
            for lat in lats:
                hist.observe(lat, model=name)
        # a breaker can open on THIS dispatch even though it
        # completed: a jit failure rescued by the host fallback
        # counts toward the breaker mid-dispatch, so the open must be
        # fired from the success path too, not only the except path
        self._fire_opened_breakers(groups)
