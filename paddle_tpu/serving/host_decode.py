"""Host-stepped per-token beam decode — the hook-safe serving rung.

The jitted while-loop decode (`beam_search.BeamSearchDecoder`) runs
user beam hooks (`BeamHooks.adjust/drop/stop`, the reference's
registerBeamSearchControlCallbacks) through `jax.pure_callback` — which
the axon PJRT plugin rejects with UNIMPLEMENTED, so a hook-bearing
generation request previously got NO TPU path at all (VERDICT r5
Missing #1). This module is the degradation ladder's second rung: one
small jitted program per token step (the step net forward — still on
the accelerator), with the beam expansion, hook calls, and bookkeeping
on the host between steps. Semantics match `_decode_core` exactly —
finished-beam eos-extension, parent-conditioned memory carry, drop
truncation with NEG_INF, stop short-circuit, ties broken toward the
lower flat index like `lax.top_k` — so the two rungs are
interchangeable and only differ in dispatch cost (~1 program per token
instead of 1 per request batch).

Chunked dispatch (ISSUE 18): when the decoder carries
`tokens_per_dispatch=K > 1` and the request has no host callbacks
(adjust/drop/stop all None — a purely-JAX `logprob_fn` is fine, it
compiles into the program), this rung dispatches ONE jitted K-step
chunk program per K tokens (`BeamSearchDecoder._chunk_step_program`)
instead of one step-net program per token, cutting the rung's dispatch
chain from `max_len` to `ceil(max_len/K)`. Per-token `decode.token`
spans become per-chunk `decode.chunk` spans carrying a `tokens` label,
so trace_view critical paths and the serve-row span split stay
reconcilable. Hook-bearing requests keep the per-token path unchanged
— chunking never alters hook call semantics. Both paths record the
measured dispatch count on `dec.last_chain_depth`.
"""

from __future__ import annotations

import numpy as np

from paddle_tpu.beam_search import NEG_INF, BeamHooks
from paddle_tpu.obs import tracing as _tracing


def _step_fn(dec, b):
    """Jitted step-net forward for batch b: (params, static_feed, mems,
    words[B,K]) -> (prob [B*K, V], new mems). Cached on the decoder,
    keyed by (b, k) — the shapes the trace specializes on; jax.jit
    handles static-feed shape retraces within one entry."""
    import jax

    from paddle_tpu.core.arg import Arg

    cache = getattr(dec, "_host_step_cache", None)
    if cache is None:
        cache = dec._host_step_cache = {}
    key = (b, dec.k)
    if key not in cache:
        if len(cache) >= 8:  # same bound as the decode-program cache
            cache.pop(next(iter(cache)))
        net, k = dec._net, dec.k
        memories = dec.memories
        out_name = dec.out_name

        @jax.jit
        def step(params, static_feed, mems, words):
            feed = dict(static_feed)
            feed["@word"] = Arg(ids=words.reshape(b * k))
            for m in memories:
                feed[m["link"]] = Arg(value=mems[m["layer"]])
            outs, _ = net.forward(params, feed, train=False)
            prob = outs[out_name].value
            new_mems = {m["layer"]: outs[m["layer"]].value
                        for m in memories}
            return prob, new_mems

        cache[key] = step
    return cache[key]


def _top_k_stable(flat: np.ndarray, k: int):
    """Row-wise top-k, ties broken toward the LOWER index — the
    `lax.top_k` contract the jitted path relies on for beam order."""
    order = np.argsort(-flat, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(flat, order, axis=1), order


def _chunked_generate(dec, params, static_feed, mems, b, n_chunk):
    """Hook-free chunked host decode: one jitted K-step program per
    chunk, beam bookkeeping (expansion, top-k, memory carry, eos
    masking) INSIDE the program, only the per-substep (word, parent)
    trace and the finished flag coming host-side per chunk. The host
    replays the same seqs-reorder the per-token loop does — substeps
    past all-finished arrive as (word=eos, parent=identity), which
    replays as a no-op, exactly the jitted trace-buffer convention."""
    import jax.numpy as jnp

    k, t_max, eos = dec.k, dec.max_length, dec.eos_id
    words = jnp.full((b, k), dec.bos_id, jnp.int32)
    scores = jnp.full((b, k), NEG_INF, jnp.float32).at[:, 0].set(0.0)
    finished = jnp.zeros((b, k), bool)
    seqs = np.full((b, k, t_max), eos, np.int32)
    rows = np.arange(b)[:, None]
    traced = _tracing.current() is not None

    t0, dispatches = 0, 0
    while t0 < t_max:
        n = min(n_chunk, t_max - t0)  # ragged tail: shorter last chunk
        prog = dec._chunk_step_program(b, n)
        if traced:
            with _tracing.span("decode.chunk", t=t0, tokens=n, batch=b):
                ws, ps, words, scores, finished, mems = prog(
                    params, static_feed, mems, words, scores, finished,
                    jnp.int32(t0),
                )
        else:
            ws, ps, words, scores, finished, mems = prog(
                params, static_feed, mems, words, scores, finished,
                jnp.int32(t0),
            )
        dispatches += 1
        ws_np, ps_np = np.asarray(ws), np.asarray(ps)
        for j in range(n):
            seqs = seqs[rows, ps_np[j]]  # reorder history by parent
            seqs[:, :, t0 + j] = ws_np[j]
        t0 += n
        if np.asarray(finished).all():
            break
    dec.last_chain_depth = dispatches
    dec.last_steps = t0

    is_eos = seqs == eos
    any_eos = np.any(is_eos, axis=-1)
    first_eos = np.argmax(is_eos, axis=-1)
    lens = np.where(any_eos, first_eos + 1, t_max).astype(np.int32)
    return seqs, lens, np.asarray(scores)


def host_generate(dec, params, statics=None, boots=None, batch_size=None,
                  hooks: BeamHooks = None, tokens_per_dispatch=None):
    """Decode with the same inputs/outputs as `dec.generate`, stepping
    the loop from the host so `hooks` run as plain Python — no
    pure_callback, hence viable on runtimes that reject host callbacks.
    Returns (seqs [B, K, max_length] int32, lens [B, K] int32,
    scores [B, K] float32), beams sorted best-first; unwritten steps
    hold eos, matching the jitted program's trace buffers.

    `tokens_per_dispatch` (default: the decoder's own setting) selects
    the chunked path when > 1 and no host callbacks are present."""
    statics = statics or []
    hooks = hooks if hooks is not None else dec.hooks
    static_feed, mems_j, b = dec.prepare(statics, boots, batch_size)
    k, t_max, eos = dec.k, dec.max_length, dec.eos_id

    n_chunk = (tokens_per_dispatch if tokens_per_dispatch is not None
               else getattr(dec, "tokens_per_dispatch", 1))
    hookful = (hooks.adjust is not None or hooks.drop is not None
               or hooks.stop is not None)
    if n_chunk > 1 and not hookful:
        return _chunked_generate(dec, params, static_feed, mems_j, b,
                                 min(n_chunk, t_max))

    step = _step_fn(dec, b)

    mems = mems_j  # device-side between steps; only logits come host
    words = np.full((b, k), dec.bos_id, np.int32)
    scores = np.full((b, k), NEG_INF, np.float32)
    scores[:, 0] = 0.0
    finished = np.zeros((b, k), bool)
    seqs = np.full((b, k, t_max), eos, np.int32)

    # per-token tracing (only when a trace context is attached — the
    # serving scheduler attaches its dispatch span around this call):
    # each token step is one span, so the decode rung's time shows up
    # token-by-token in the request's critical path
    traced = _tracing.current() is not None

    dispatches = 0
    for t in range(t_max):
        if traced:
            with _tracing.span("decode.token", t=t, batch=b):
                prob, new_mems = step(params, static_feed, mems, words)
        else:
            prob, new_mems = step(params, static_feed, mems, words)
        dispatches += 1
        prob = np.asarray(prob)
        v = prob.shape[-1]
        logp = np.log(np.maximum(prob, 1e-20)).reshape(b, k, v)
        if dec.logprob_fn is not None:
            logp = np.asarray(dec.logprob_fn(logp, t), np.float32)
        if hooks.adjust is not None:
            logp = np.asarray(hooks.adjust(logp, t), np.float32)
        # finished beams only extend with eos at no cost
        fin_row = np.full((v,), NEG_INF, np.float32)
        fin_row[eos] = 0.0
        logp = np.where(finished[..., None], fin_row[None, None, :], logp)
        cand = scores[..., None] + logp
        top_scores, top_idx = _top_k_stable(cand.reshape(b, k * v), k)
        parent = (top_idx // v).astype(np.int64)
        word = (top_idx % v).astype(np.int32)

        rows = np.arange(b)[:, None]
        was_fin = finished[rows, parent]
        # parent-conditioned memory carry: a finished parent's state
        # rides through unchanged (the jitted path's `keep` select)
        sel_mems = {}
        for m in dec.memories:
            name = m["layer"]
            new = np.asarray(new_mems[name]).reshape(b, k, -1)
            prev = np.asarray(mems[name]).reshape(b, k, -1)
            sel = np.where(
                was_fin[..., None],
                prev[rows, parent],
                new[rows, parent],
            )
            sel_mems[name] = sel.reshape(b * k, -1)
        mems = sel_mems
        seqs = seqs[rows, parent]  # reorder history by parent beam
        seqs[:, :, t] = word
        new_fin = was_fin | (word == eos)
        scores = top_scores.astype(np.float32)
        if hooks.drop is not None:
            s2, drop_mask = hooks.drop(word.copy(), scores.copy(), t)
            scores = np.asarray(s2, np.float32)
            drop_mask = np.asarray(drop_mask, bool)
            scores = np.where(drop_mask, NEG_INF, scores)
            new_fin = new_fin | drop_mask
        finished = new_fin
        words = word
        if hooks.stop is not None and bool(
            hooks.stop(finished.copy(), scores.copy(), t)
        ):
            break
        if finished.all():
            break
    dec.last_chain_depth = dispatches
    dec.last_steps = dispatches

    is_eos = seqs == eos
    any_eos = np.any(is_eos, axis=-1)
    first_eos = np.argmax(is_eos, axis=-1)
    lens = np.where(any_eos, first_eos + 1, t_max).astype(np.int32)
    return seqs, lens, scores
