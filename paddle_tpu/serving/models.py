"""Served-model wrappers: what the scheduler dispatches to.

Two engine kinds compose the existing pieces:

- `GenerationModel` — beam-search generation over a
  `BeamSearchDecoder`. Rung 1 is the decoder's own jitted while-loop
  program (bounded decode-program cache, `beam_search.py`); rung 2 is
  the host-stepped path (`host_decode.py`) — per-token when hooks are
  present (pure_callback-free, so hook-bearing requests stay servable
  on runtimes that reject host callbacks), per-K-token-chunk when the
  decoder carries `tokens_per_dispatch > 1` (ISSUE 18) — taken for
  hook requests or when rung 1 fails and the server's `host_fallback`
  is on. An optional speculative rung (`speculative=` a
  SpeculativeGreedyDecoder + `draft_params`) serves hook-free greedy
  requests draft-first, token-for-token equal to the target's greedy
  output. An optional `encode` callable turns the packed source ids
  into the decoder's statics/boots (the seq2seq encoder forward);
  `tokens_per_dispatch` is part of the server's dispatch-key
  accounting the same way len/batch buckets are.

- `MultiForwardHost` — N forward-scoring submodels merged into ONE
  compiled program via `multi_network.merge_confs`, each submodel's
  requests packed with the bucketed `DataFeeder` and routed by
  `prefix_feed` names. The scheduler co-dispatches sibling models'
  pending batches through `run_group`, so one program launch serves
  several models' traffic — MultiNetwork's joint execution, serving-
  shaped.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np


class GenerationModel:
    """`decoder`: a BeamSearchDecoder (or models.text factory output).
    `encode(ids [B,T] i32, lens [B] i32) -> (statics list[Arg], boots
    dict)` builds the decoder conditioning; None means an
    unconditioned decoder (statics=[], batch_size=B). `named_hooks`
    maps wire-addressable hook names to BeamHooks — the TCP front end
    cannot ship callables, so hook-bearing requests name a hook the
    model registered at build time."""

    can_host = True
    engine = None

    def __init__(self, decoder, params, encode: Optional[Callable] = None,
                 named_hooks: Optional[Dict] = None, speculative=None,
                 draft_params=None, draft_encode: Optional[Callable] = None):
        self.decoder = decoder
        self.params = params
        self.encode = encode
        self.named_hooks = named_hooks or {}
        self.speculative = speculative
        self.draft_params = draft_params
        self.draft_encode = draft_encode
        if speculative is not None:
            assert draft_params is not None, (
                "speculative serving needs draft_params"
            )
            assert speculative.target is decoder, (
                "speculative.target must be the served decoder — "
                "anything else would serve a different model's tokens"
            )

    @property
    def tokens_per_dispatch(self):
        """K of the decode program's multi-token dispatch — part of
        the server's dispatch-key accounting (a K change is a new
        compiled-program family, exactly like a new len bucket)."""
        return getattr(self.decoder, "tokens_per_dispatch", 1)

    @property
    def recompile_guards(self):
        """The decoder's jit-cache-miss tracker (ISSUE 13), surfaced
        so InferenceServer.arm_recompile_guard can arm every served
        model's guards after warmup. Lazy: the guard exists once the
        first jitted decode program was built."""
        g = getattr(self.decoder, "_recompile_guard", None)
        out = (g,) if g is not None else ()
        if self.speculative is not None:
            out = out + tuple(self.speculative.recompile_guards)
        return out

    def run_batch(self, ids, lens, hooks, host: bool):
        from paddle_tpu.serving.host_decode import host_generate

        dec = self.decoder
        b = ids.shape[0]
        if self.encode is not None:
            statics, boots = self.encode(ids, lens)
            bs = None
        else:
            statics, boots, bs = [], None, b
        if host or hooks is not None:
            seqs, out_lens, scores = host_generate(
                dec, self.params, statics=statics, boots=boots,
                batch_size=bs, hooks=hooks,
            )
            path = "host"
        elif self.speculative is not None:
            if self.draft_encode is not None:
                d_statics, d_boots = self.draft_encode(ids, lens)
            else:
                d_statics, d_boots = None, None
            seqs, out_lens, scores = self.speculative.generate(
                self.params, self.draft_params, statics=statics,
                boots=boots, batch_size=bs, draft_statics=d_statics,
                draft_boots=d_boots,
            )
            path = "spec"
        else:
            seqs, out_lens, scores = dec.generate(
                self.params, statics=statics, boots=boots, batch_size=bs
            )
            path = "jit"
        seqs = np.asarray(seqs)
        out_lens = np.asarray(out_lens)
        scores = np.asarray(scores, np.float32)
        rows = []
        for i in range(b):
            n = int(out_lens[i, 0])
            rows.append({
                "tokens": seqs[i, 0, :n].tolist(),
                "score": float(scores[i, 0]),
                "path": path,
            })
        return rows


class _ForwardSub:
    """One submodel's face toward the server: run_batch packs this
    submodel alone; the scheduler upgrades to run_group when siblings
    have pending work."""

    can_host = False

    def __init__(self, host: "MultiForwardHost", name: str):
        self.engine = host
        self.name = name
        self.named_hooks = {}

    @property
    def recompile_guards(self):
        return self.engine.recompile_guards

    def run_batch(self, ids, lens, hooks, host: bool):
        out = self.engine.run_group({self.name: (ids, lens)})
        return out[self.name]


class MultiForwardHost:
    """confs: {name: ModelConf}; every submodel is a single-ids-input
    scorer (data layer `input_name`, output layer `output_name`).
    Parameters with explicit shared names alias across submodels
    exactly as MultiNetwork shared them. `init_params` (or a trained
    merged dict) provides the weights for the MERGED conf."""

    def __init__(self, confs: Dict[str, object], params=None,
                 input_names: Dict[str, str] = None,
                 output_names: Dict[str, str] = None, seed: int = 0):
        import jax

        from paddle_tpu.multi_network import merge_confs
        from paddle_tpu.network import Network

        self.confs = dict(confs)
        self.names = tuple(self.confs)
        self.input_names = input_names or {}
        self.output_names = output_names or {}
        self.merged = merge_confs(self.confs)
        self.net = Network(self.merged)
        self.params = (
            params if params is not None
            else self.net.init_params(jax.random.key(seed))
        )
        self._fwd_cache = {}
        from paddle_tpu.analysis.recompile_guard import RecompileGuard

        self._recompile_guard = RecompileGuard("serve_forward")

    @property
    def recompile_guards(self):
        return (self._recompile_guard,)

    def sub(self, name: str) -> _ForwardSub:
        assert name in self.confs, name
        return _ForwardSub(self, name)

    def _jit_fwd(self, want: tuple):
        """One jitted merged forward per output set (in practice one:
        every data layer is always fed) — a single compiled program
        launch per dispatch, with jax.jit handling shape-keyed
        retraces inside the entry."""
        fn = self._fwd_cache.get(want)
        if fn is None:
            import jax

            guard = self._recompile_guard

            def run(params, feed):
                # trace-time only (ISSUE 13): armed after warmup by
                # InferenceServer.arm_recompile_guard — a retrace in
                # steady state means a bucket the warmup never saw
                # (or a churned program cache) is paying a compile
                # inside the serving path
                guard.note(feed)
                outs, _ = self.net.forward(params, feed,
                                           outputs=list(want),
                                           train=False)
                return {w: outs[w].value for w in want}

            fn = self._fwd_cache[want] = jax.jit(run)
        return fn

    def _io(self, name):
        conf = self.confs[name]
        inp = self.input_names.get(name) or next(
            lc.name for lc in conf.layers if lc.type == "data"
        )
        out = self.output_names.get(name) or (
            conf.output_layer_names[-1] if conf.output_layer_names
            else conf.layers[-1].name
        )
        return inp, out

    def run_group(self, packed: Dict[str, tuple]) -> Dict[str, list]:
        """packed: {name: (ids [B,T] i32, lens [B] i32)} for the models
        with pending work. Absent submodels get a 1-row zero feed (the
        merged program needs every data layer); their outputs are
        discarded. One program launch serves every present model."""
        import jax.numpy as jnp

        from paddle_tpu.core.arg import Arg
        from paddle_tpu.multi_network import prefix_feed

        feed = {}
        want = []
        for name in self.names:
            inp, out = self._io(name)
            if name in packed:
                ids, lens = packed[name]
                sub_feed = {inp: Arg(
                    ids=jnp.asarray(ids, jnp.int32),
                    seq_lens=jnp.asarray(lens, jnp.int32),
                )}
            else:
                sub_feed = {inp: Arg(
                    ids=jnp.zeros((1, 1), jnp.int32),
                    seq_lens=jnp.ones((1,), jnp.int32),
                )}
            feed.update(prefix_feed(name, sub_feed))
            want.append(f"{name}/{out}")
        outs = self._jit_fwd(tuple(want))(self.params, feed)
        results = {}
        for name in packed:
            _, out = self._io(name)
            val = np.asarray(outs[f"{name}/{out}"])
            results[name] = [
                {"scores": val[i].ravel().tolist(), "path": "jit"}
                for i in range(val.shape[0])
            ]
        return results
