"""Fleet tier: a replica router with spill-before-shed, breaker-gated
rotation, telemetry-driven balancing, and zero-downtime rollout
(ISSUE 16, ROADMAP item 3 — the PAPER.md master/pserver capability
reproduced on the inference side).

Topology: `FleetRouter` fronts N independent replica processes, each
a full `InferenceServer` behind a `ServingTCPServer` socket. The
router holds, per replica:

- a `_Breaker` (the same class the server uses per model) tracking
  *transport* health: refused connects, resets, torn frames. A dead
  replica opens its breaker and is rotated out of candidate order;
  after `breaker_reset_s` the telemetry poller wins the half-open
  probe (`try_probe`) and a successful `metricz` scrape closes it —
  the replica rejoins rotation without any routed request having
  been gambled on it.
- a telemetry snapshot (queue depth, shed counts) scraped from the
  replica's own `metricz` endpoint by a background poller. Routing
  cost = replica queue depth + requests this router currently has in
  flight there, so a loaded or wedged replica naturally sinks in the
  candidate order even before its breaker trips.
- a client pool (one lazy TCP connection per concurrent caller).

Spill-before-shed: a request is tried on the best candidate first;
an `overloaded` response or a transport error moves it to the next
sibling instead of surfacing the shed. Only when every admitting
replica has refused does the router return `overloaded` — the fleet
sheds as a last resort, one replica shedding is just a routing hint.
A transport error mid-call additionally records a breaker failure,
so a SIGKILLed replica both loses the request to a sibling (zero
admitted requests lost) and starts accumulating toward rotation.

Rollout (`rollout(model, tag)`): replicas are swapped one at a time.
The router marks the replica draining (new requests flow to
siblings — no refused window for a polling client), waits for its
own in-flight count there to reach zero, sends the
`{"admin": "swap_model"}` frame (the server's swap is atomic behind
the admission queue; its queued requests dispatch on the new model),
then returns the replica to rotation. Zero admitted requests are
lost at either layer.

Trace propagation: every routed call runs under a `fleet.route` span,
so the client-side span, the router hop (with the chosen replica and
spill count as labels), and the replica's `serve.request` tree share
one trace_id.

Observability (ISSUE 17): the poller also feeds a `FleetMonitor` —
replica snapshots merged into one fleet view (`obs/aggregate`), a
multi-window SLO burn-rate monitor over the router's own routing
outcomes, and, on alert activation, a cross-process incident bundle:
every replica's flight ring gathered over the `flightz` frame,
stitched with the router's ring, the merged fleet view and the
per-replica breaker states into one rate-limited
`paddle-tpu-fleet-incident/v1` document (`tools/fleet_view.py` reads
it back as cross-process critical paths).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

from paddle_tpu.core import flags as _flags
from paddle_tpu.obs import aggregate as _agg
from paddle_tpu.obs import flight_recorder as _flight
from paddle_tpu.obs import metrics as _obs
from paddle_tpu.obs import tracing as _tracing
from paddle_tpu.serving.server import _Breaker
from paddle_tpu.serving.tcp import ServeClient


@dataclass
class FleetConfig:
    breaker_threshold: int = 3      # consecutive transport failures
    breaker_reset_s: float = 0.5    # quarantine before half-open
    poll_interval_s: float = 0.1    # metricz scrape cadence
    connect_timeout_s: float = 2.0
    request_timeout_s: float = 30.0
    scrape_timeout_s: float = 1.0
    max_spills: int = None          # extra replicas tried; None = all
    client_retries: int = 2         # per-connect retry (ServeClient)
    # ---- fleet observability (ISSUE 17) -------------------------
    monitor: bool = True            # run the SLO burn-rate monitor
    # None on any of these = resolved from the matching fleet_* /
    # serve_* flag at router construction
    availability_target: float = None
    slo_p99_ms: float = None        # 0 disables the p99 alert
    burn_windows: tuple = None      # ((short_s, long_s, threshold),..)
    burn_min_decisions: int = None
    incident_dir: str = None        # None = in-memory only
    incident_min_interval_s: float = None
    incident_max_bundles: int = None
    # scrape failures feeding rotation: after this many CONSECUTIVE
    # failed metricz scrapes the replica's stale telemetry is
    # discarded and its cost poisoned (None = breaker_threshold)
    scrape_breaker_failures: int = None


class ReplicaHandle:
    """Router-side state for one replica. The breaker and the client
    pool survive `set_address` (a replica restart keeps its history:
    the new process must pass the half-open probe to rejoin)."""

    def __init__(self, name: str, addr: str, cfg: FleetConfig):
        self.name = name
        self.addr = addr
        self.cfg = cfg
        self.breaker = _Breaker(cfg.breaker_threshold,
                                cfg.breaker_reset_s, model=name)
        self.draining = False
        self.telemetry: dict = {}
        self.metricz: dict = {}     # last full registry snapshot
        self.scrape_failures = 0    # CONSECUTIVE failed scrapes
        self.stale = False          # telemetry discarded as unusable
        self.inflight = 0
        self._lock = threading.Lock()
        self._pool: list = []

    def _new_client(self) -> ServeClient:
        return ServeClient(self.addr,
                           connect_timeout=self.cfg.connect_timeout_s,
                           retries=self.cfg.client_retries)

    def checkout(self) -> ServeClient:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return self._new_client()

    def checkin(self, client: ServeClient):
        with self._lock:
            self._pool.append(client)

    def discard(self, client: ServeClient):
        try:
            client.close()
        except Exception:
            pass

    def set_address(self, addr: str):
        """Point at a restarted replica. Pooled connections to the old
        process are dropped; breaker state is kept so the newcomer
        goes through probe-back-in rather than instantly absorbing
        live traffic."""
        with self._lock:
            self.addr = addr
            stale, self._pool = self._pool, []
        for c in stale:
            self.discard(c)

    def cost(self) -> float:
        """Routing cost: the replica's own reported queue depth plus
        what this router already has in flight there. A replica whose
        telemetry went stale (consecutive scrape failures) is
        poisoned to the back of the candidate order — unknown health
        must not masquerade as an empty queue (ISSUE 17 satellite)."""
        depth = 0
        tel = self.telemetry
        if isinstance(tel, dict):
            depth = tel.get("queue_depth", 0) or 0
        penalty = 1e6 if self.stale else 0.0
        return float(depth) + float(self.inflight) + penalty

    def close(self):
        with self._lock:
            stale, self._pool = self._pool, []
        for c in stale:
            self.discard(c)


@dataclass
class RolloutReport:
    """Structured evidence for a rollout: per-phase events went into
    the stream as they happened; this is the caller-facing summary.
    Mapping-style access (`report["r0"]`, `.values()`, `.items()`)
    reads the per-replica swap responses, so callers written against
    the old plain-dict return keep working."""

    model: str
    tag: str
    ok: bool
    duration_s: float
    results: dict       # replica -> swap response
    phases: list        # [{"phase","replica","t_s",...}, ...]
    per_replica: dict   # replica -> {"drain_s","swap_s","total_s"}

    def values(self):
        return self.results.values()

    def items(self):
        return self.results.items()

    def keys(self):
        return self.results.keys()

    def __getitem__(self, name):
        return self.results[name]

    def __contains__(self, name):
        return name in self.results

    def __len__(self):
        return len(self.results)


class FleetMonitor:
    """The fleet half of the observability plane (ISSUE 17): owns the
    snapshot aggregator (merged fleet view + scrape history), the SLO
    burn-rate monitor fed by the router's per-request decisions, and
    the incident-bundle writer. Runs entirely on the router's poller
    thread via `on_round()`; `record()` is the only hot-path call.

    When a burn-rate alert activates, `on_round` assembles a
    cross-process incident bundle: a `flightz` ring dump from every
    reachable replica, the router's own flight ring, the merged fleet
    view + scrape history, the active alerts and the per-replica
    router states — one `paddle-tpu-fleet-incident/v1` JSON document,
    rate-limited and dir-bounded by the same BoundedBundleDir
    discipline as flight bundles."""

    def __init__(self, config: FleetConfig, registry=None):
        self.config = config
        self._reg = registry or _obs.get_registry()
        target = (config.availability_target
                  if config.availability_target is not None
                  else _flags.get_flag("fleet_availability_target"))
        slo = (config.slo_p99_ms if config.slo_p99_ms is not None
               else _flags.get_flag("serve_p99_slo_ms"))
        windows = config.burn_windows
        if windows is None:
            fast = float(_flags.get_flag("fleet_burn_fast_window_s"))
            slow = float(_flags.get_flag("fleet_burn_slow_window_s"))
            windows = (
                (fast, fast * 5.0,
                 float(_flags.get_flag("fleet_burn_fast_threshold"))),
                (slow, slow * 6.0,
                 float(_flags.get_flag("fleet_burn_slow_threshold"))),
            )
        min_dec = (config.burn_min_decisions
                   if config.burn_min_decisions is not None
                   else _flags.get_flag("fleet_burn_min_decisions"))
        self.aggregator = _agg.FleetAggregator()
        self.burn = _agg.BurnRateMonitor(
            availability_target=target, p99_slo_ms=slo,
            windows=windows, min_decisions=min_dec,
            registry=self._reg,
        )
        self._dir = _flight.BoundedBundleDir(
            config.incident_dir,
            prefix="incident-",
            max_bundles=int(
                config.incident_max_bundles
                if config.incident_max_bundles is not None
                else _flags.get_flag("fleet_incident_max_bundles")
            ),
            min_interval_s=float(
                config.incident_min_interval_s
                if config.incident_min_interval_s is not None
                else _flags.get_flag("fleet_incident_min_interval_s")
            ),
            lock_name="obs.incident_dir",
        )
        self.alerts: list = []
        self.last_incident: dict = None
        self.last_incident_path: str = None

    def record(self, ok: bool, latency_s: float = None,
               replica: str = None) -> None:
        self.burn.record(ok, latency_s=latency_s, replica=replica)

    def on_round(self, router: "FleetRouter") -> None:
        """One monitor round, after the poller scraped every replica:
        merge the fresh snapshots, evaluate the burn windows, and on
        active alerts (rate-limited) write an incident bundle."""
        snaps = {
            name: h.metricz
            for name, h in router._handles.items() if h.metricz
        }
        if snaps:
            self.aggregator.observe(snaps)
        self.alerts = self.burn.evaluate()
        if self.alerts:
            self._maybe_incident(router, self.alerts)

    def _maybe_incident(self, router, alerts) -> str:
        seq = self._dir.try_begin()
        if seq is None:
            self._reg.counter("fleet.incidents_suppressed").inc()
            return None
        try:
            return self._incident(router, alerts, seq)
        except Exception:
            # an unwritable incident dir / dead replica mid-gather
            # must not take down the poller that noticed the problem
            self._reg.counter("fleet.incident_errors").inc()
            return None

    def _incident(self, router, alerts, seq) -> str:
        self._reg.counter("fleet.incidents").inc()
        # cross-process gather: every replica's flight ring over the
        # flightz frame (answered outside the admission queue — an
        # overloaded replica is exactly the one whose ring we need)
        rings = {}
        for name, h in router._handles.items():
            client = h.checkout()
            try:
                resp = client.flightz(
                    timeout=router.config.scrape_timeout_s)
                rings[name] = (resp.get("flightz", {})
                               if isinstance(resp, dict) else {})
                h.checkin(client)
            except Exception as e:
                h.discard(client)
                rings[name] = {"ok": False,
                               "error": f"{type(e).__name__}: {e}"}
        rec = _flight.get_flight_recorder()
        offending = _agg.offending_replica(alerts)
        bundle = {
            "schema": _agg.INCIDENT_SCHEMA,
            "reason": "burn_rate",
            "ts": round(time.time(), 6),
            "pid": os.getpid(),
            "seq": seq,
            "alerts": alerts,
            "offending": offending,
            "states": router.states(),
            "fleet": {
                "merged": self.aggregator.merged,
                "delta": self.aggregator.delta,
                "rates": self.aggregator.rates,
            },
            "history": self.aggregator.history()[-8:],
            "replicas": rings,
            "events": rec.snapshot() if rec is not None else [],
        }
        path = self._dir.write(seq, "burn_rate", bundle)
        self.last_incident = bundle
        self.last_incident_path = path
        self._reg.event("incident", reason="burn_rate",
                        offending=offending, path=path,
                        alerts=len(alerts))
        return path

    def state(self) -> dict:
        """Monitor view for `fleetz` / tests: burn windows, active
        alerts, incident accounting."""
        return {
            "burn": self.burn.state(),
            "alerts": self.alerts,
            "incident_dir": self._dir.dump_dir,
            "last_incident_path": self.last_incident_path,
        }


class FleetRouter:
    """Route requests across replicas; see the module docstring for
    the full contract. `replicas` maps name -> "host:port"."""

    def __init__(self, replicas: dict, config: FleetConfig = None):
        self.config = config or FleetConfig()
        self._handles = {
            name: ReplicaHandle(name, addr, self.config)
            for name, addr in replicas.items()
        }
        self.monitor = (FleetMonitor(self.config)
                        if self.config.monitor else None)
        self._rr = 0
        self._lock = threading.Lock()
        self._stopped = False
        self._poller = threading.Thread(
            target=self._poll_loop, name="fleet-poll", daemon=True
        )
        self._poller.start()

    # ------------------------------------------------------- telemetry
    def _poll_loop(self):
        while not self._stopped:
            for h in list(self._handles.values()):
                if self._stopped:
                    return
                self._scrape(h)
            if self.monitor is not None and not self._stopped:
                try:
                    self.monitor.on_round(self)
                except Exception:
                    # the monitor must never kill telemetry polling
                    _obs.get_registry().counter(
                        "fleet.monitor_errors").inc()
            time.sleep(self.config.poll_interval_s)

    def _scrape(self, h: ReplicaHandle):
        """One metricz scrape. Doubles as the half-open liveness
        probe: for a non-closed breaker the poller must win
        `try_probe()` first, so rotation-in is decided by a cheap
        scrape, never by gambling a routed request on a replica that
        just died."""
        if h.breaker.state != "closed" and not h.breaker.try_probe():
            return
        client = h.checkout()
        try:
            resp = client.metricz(timeout=self.config.scrape_timeout_s)
            stats = resp.get("stats", {}) if isinstance(resp, dict) else {}
            h.telemetry = stats
            h.metricz = (resp.get("metricz", {})
                         if isinstance(resp, dict) else {})
            h.scrape_failures = 0
            h.stale = False
            was_open = h.breaker.state != "closed"
            h.breaker.record(True)
            if was_open:
                _obs.get_registry().counter(
                    "fleet.rejoins").inc(replica=h.name)
            h.checkin(client)
        except Exception:
            # a failed scrape is NOT silent (ISSUE 17 satellite): it
            # is counted, it charges the same breaker that transport
            # failures charge (so N consecutive failures rotate the
            # replica out), and past the threshold the stale
            # telemetry is discarded — a replica we cannot see must
            # not keep looking cheap on its last known queue depth
            h.discard(client)
            h.breaker.record(False)
            h.scrape_failures += 1
            _obs.get_registry().counter("fleet.scrape_errors").inc(
                replica=h.name)
            limit = self.config.scrape_breaker_failures
            if limit is None:
                limit = self.config.breaker_threshold
            if h.scrape_failures >= limit:
                h.telemetry = {}
                h.metricz = {}
                h.stale = True

    # --------------------------------------------------------- routing
    def _candidates(self) -> list:
        """Admitting, non-draining replicas, cheapest first; round-
        robin rotation breaks ties so equal-cost replicas share load
        instead of the dict-order replica taking everything."""
        with self._lock:
            self._rr += 1
            rr = self._rr
        handles = list(self._handles.values())
        n = len(handles)
        rotated = handles[rr % n:] + handles[: rr % n] if n else []
        live = [h for h in rotated
                if not h.draining and h.breaker.admits()]
        return sorted(live, key=lambda h: h.cost())

    def call(self, model: str, ids, deadline_ms: int = None,
             hooks: str = None, timeout: float = None,
             trace=None) -> dict:
        """Route one request. Returns the replica's response dict; a
        fleet-level shed ({"ok": False, "error": "overloaded"}) only
        after every admitting replica refused or failed."""
        t0 = time.monotonic()
        with _tracing.span("fleet.route", model=model) as sp:
            resp = self._route(model, ids, deadline_ms, hooks,
                               timeout, trace, sp)
            if isinstance(resp, dict) and not resp.get("ok", False):
                sp.status = resp.get("error", "error")
        lat = time.monotonic() - t0
        ok = isinstance(resp, dict) and bool(resp.get("ok", False))
        replica = sp.labels.get("replica") or sp.labels.get("shed_by")
        if ok:
            # the router's OWN end-to-end timing of admitted requests
            # — the independent cross-check the bench row compares
            # against the fleet p99 merged from replica histograms
            _obs.get_registry().histogram(
                "fleet.request_latency_s").observe(lat, model=model)
        if self.monitor is not None:
            self.monitor.record(ok, latency_s=lat if ok else None,
                                replica=replica)
        return resp

    def _route(self, model, ids, deadline_ms, hooks, timeout,
               trace, sp) -> dict:
        reg = _obs.get_registry()
        cands = self._candidates()
        limit = len(cands) if self.config.max_spills is None \
            else min(len(cands), self.config.max_spills + 1)
        last_shed = None
        last_blame = None
        spills = 0
        for h in cands[:limit]:
            # half-open: only one probe request at a time may test a
            # recovering replica; everyone else spills past it
            if h.breaker.state != "closed" and not h.breaker.try_probe():
                continue
            with h._lock:
                h.inflight += 1
            client = h.checkout()
            try:
                resp = client.call(
                    model, ids, deadline_ms=deadline_ms, hooks=hooks,
                    timeout=timeout or self.config.request_timeout_s,
                    trace=trace,
                )
            except Exception:
                # transport death (SIGKILL, reset, torn frame): the
                # request was NOT acknowledged — retry it on a sibling
                # and charge the breaker
                h.discard(client)
                h.breaker.record(False)
                reg.counter("fleet.transport_errors").inc(
                    replica=h.name)
                last_blame = h.name
                spills += 1
                continue
            finally:
                with h._lock:
                    h.inflight -= 1
            h.checkin(client)
            if isinstance(resp, dict) and not resp.get("ok", False) \
                    and resp.get("error") in ("overloaded",
                                              "shutting_down"):
                # replica-level shed = fleet-level routing hint
                h.breaker.record(True)  # alive, just busy
                reg.counter("fleet.spills").inc(replica=h.name)
                last_shed = resp
                last_blame = h.name
                spills += 1
                continue
            h.breaker.record(True)
            reg.counter("fleet.routed").inc(replica=h.name)
            sp.labels["replica"] = h.name
            sp.labels["spills"] = spills
            return resp
        reg.counter("fleet.shed").inc()
        if last_blame is not None:
            # shed attribution for the burn monitor: the last replica
            # that refused or failed is the best available blame
            sp.labels["shed_by"] = last_blame
        if last_shed is not None:
            return dict(last_shed, fleet_spills=spills)
        return {"ok": False, "error": "overloaded",
                "detail": "no admitting replica", "fleet_spills": spills}

    # --------------------------------------------------------- rollout
    def rollout(self, model: str, tag: str = None,
                drain_timeout_s: float = 10.0) -> RolloutReport:
        """Zero-downtime hot swap of `model` across the fleet, one
        replica at a time. Returns a RolloutReport (mapping-style
        access reads the per-replica swap responses). Raises
        RuntimeError if any replica's swap fails — the fleet is then
        mixed-version and the caller must retry or roll back.

        Every phase — drain begin/end, swap, undrain — is emitted
        into the event stream / flight ring as it happens, so the
        zero-downtime claim is evidenced per replica with durations,
        not asserted after the fact (ISSUE 17)."""
        reg = _obs.get_registry()
        results = {}
        phases = []
        per_replica = {}
        t_start = time.monotonic()

        def emit(phase, replica, **extra):
            ev = {"phase": phase, "replica": replica, "model": model,
                  "t_s": round(time.monotonic() - t_start, 6), **extra}
            phases.append(ev)
            reg.event("rollout", **ev)

        for h in list(self._handles.values()):
            t_rep = time.monotonic()
            h.draining = True  # siblings absorb; no refused window
            emit("drain_begin", h.name)
            try:
                deadline = time.monotonic() + drain_timeout_s
                while time.monotonic() < deadline:
                    with h._lock:
                        if h.inflight == 0:
                            break
                    time.sleep(0.005)
                drain_s = time.monotonic() - t_rep
                emit("drain_end", h.name, dur_s=round(drain_s, 6))
                t_swap = time.monotonic()
                client = h.checkout()
                try:
                    msg = {"admin": "swap_model", "model": model}
                    if tag is not None:
                        msg["tag"] = tag
                    resp = client._roundtrip(
                        msg, timeout=self.config.request_timeout_s)
                except Exception as e:
                    h.discard(client)
                    emit("swap_failed", h.name,
                         error=f"{type(e).__name__}: {e}")
                    raise RuntimeError(
                        f"rollout: swap on {h.name} died: {e}") from e
                h.checkin(client)
                results[h.name] = resp
                swap_s = time.monotonic() - t_swap
                if not (isinstance(resp, dict) and resp.get("ok")):
                    emit("swap_failed", h.name,
                         error=str(resp.get("error")
                                   if isinstance(resp, dict) else resp))
                    raise RuntimeError(
                        f"rollout: swap on {h.name} refused: {resp}")
                emit("swap", h.name, dur_s=round(swap_s, 6), tag=tag)
                _obs.get_registry().counter("fleet.rollouts").inc(
                    replica=h.name, model=model)
                per_replica[h.name] = {
                    "drain_s": round(drain_s, 6),
                    "swap_s": round(swap_s, 6),
                    "total_s": round(time.monotonic() - t_rep, 6),
                }
            finally:
                h.draining = False
                emit("undrain", h.name)
        return RolloutReport(
            model=model, tag=tag, ok=True,
            duration_s=round(time.monotonic() - t_start, 6),
            results=results, phases=phases, per_replica=per_replica,
        )

    # ----------------------------------------------------- maintenance
    def set_address(self, name: str, addr: str):
        """Re-point a replica after a restart (keeps breaker state —
        the new process rejoins via the half-open probe)."""
        self._handles[name].set_address(addr)

    def handle(self, name: str) -> ReplicaHandle:
        return self._handles[name]

    def states(self) -> dict:
        """Per-replica router view (breaker state, cost, draining) —
        the fleet-level `metricz` complement."""
        return {
            name: {
                "addr": h.addr,
                "breaker": h.breaker.state,
                "draining": h.draining,
                "inflight": h.inflight,
                "queue_depth": (h.telemetry or {}).get("queue_depth"),
                "cost": h.cost(),
                "scrape_failures": h.scrape_failures,
                "stale": h.stale,
            }
            for name, h in self._handles.items()
        }

    def close(self):
        self._stopped = True
        self._poller.join(2.0)
        for h in self._handles.values():
            h.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
