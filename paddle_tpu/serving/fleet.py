"""Fleet tier: a replica router with spill-before-shed, breaker-gated
rotation, telemetry-driven balancing, and zero-downtime rollout
(ISSUE 16, ROADMAP item 3 — the PAPER.md master/pserver capability
reproduced on the inference side).

Topology: `FleetRouter` fronts N independent replica processes, each
a full `InferenceServer` behind a `ServingTCPServer` socket. The
router holds, per replica:

- a `_Breaker` (the same class the server uses per model) tracking
  *transport* health: refused connects, resets, torn frames. A dead
  replica opens its breaker and is rotated out of candidate order;
  after `breaker_reset_s` the telemetry poller wins the half-open
  probe (`try_probe`) and a successful `metricz` scrape closes it —
  the replica rejoins rotation without any routed request having
  been gambled on it.
- a telemetry snapshot (queue depth, shed counts) scraped from the
  replica's own `metricz` endpoint by a background poller. Routing
  cost = replica queue depth + requests this router currently has in
  flight there, so a loaded or wedged replica naturally sinks in the
  candidate order even before its breaker trips.
- a client pool (one lazy TCP connection per concurrent caller).

Spill-before-shed: a request is tried on the best candidate first;
an `overloaded` response or a transport error moves it to the next
sibling instead of surfacing the shed. Only when every admitting
replica has refused does the router return `overloaded` — the fleet
sheds as a last resort, one replica shedding is just a routing hint.
A transport error mid-call additionally records a breaker failure,
so a SIGKILLed replica both loses the request to a sibling (zero
admitted requests lost) and starts accumulating toward rotation.

Rollout (`rollout(model, tag)`): replicas are swapped one at a time.
The router marks the replica draining (new requests flow to
siblings — no refused window for a polling client), waits for its
own in-flight count there to reach zero, sends the
`{"admin": "swap_model"}` frame (the server's swap is atomic behind
the admission queue; its queued requests dispatch on the new model),
then returns the replica to rotation. Zero admitted requests are
lost at either layer.

Trace propagation: every routed call runs under a `fleet.route` span,
so the client-side span, the router hop (with the chosen replica and
spill count as labels), and the replica's `serve.request` tree share
one trace_id.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from paddle_tpu.obs import metrics as _obs
from paddle_tpu.obs import tracing as _tracing
from paddle_tpu.serving.server import _Breaker
from paddle_tpu.serving.tcp import ServeClient


@dataclass
class FleetConfig:
    breaker_threshold: int = 3      # consecutive transport failures
    breaker_reset_s: float = 0.5    # quarantine before half-open
    poll_interval_s: float = 0.1    # metricz scrape cadence
    connect_timeout_s: float = 2.0
    request_timeout_s: float = 30.0
    scrape_timeout_s: float = 1.0
    max_spills: int = None          # extra replicas tried; None = all
    client_retries: int = 2         # per-connect retry (ServeClient)


class ReplicaHandle:
    """Router-side state for one replica. The breaker and the client
    pool survive `set_address` (a replica restart keeps its history:
    the new process must pass the half-open probe to rejoin)."""

    def __init__(self, name: str, addr: str, cfg: FleetConfig):
        self.name = name
        self.addr = addr
        self.cfg = cfg
        self.breaker = _Breaker(cfg.breaker_threshold,
                                cfg.breaker_reset_s, model=name)
        self.draining = False
        self.telemetry: dict = {}
        self.inflight = 0
        self._lock = threading.Lock()
        self._pool: list = []

    def _new_client(self) -> ServeClient:
        return ServeClient(self.addr,
                           connect_timeout=self.cfg.connect_timeout_s,
                           retries=self.cfg.client_retries)

    def checkout(self) -> ServeClient:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return self._new_client()

    def checkin(self, client: ServeClient):
        with self._lock:
            self._pool.append(client)

    def discard(self, client: ServeClient):
        try:
            client.close()
        except Exception:
            pass

    def set_address(self, addr: str):
        """Point at a restarted replica. Pooled connections to the old
        process are dropped; breaker state is kept so the newcomer
        goes through probe-back-in rather than instantly absorbing
        live traffic."""
        with self._lock:
            self.addr = addr
            stale, self._pool = self._pool, []
        for c in stale:
            self.discard(c)

    def cost(self) -> float:
        """Routing cost: the replica's own reported queue depth plus
        what this router already has in flight there."""
        depth = 0
        tel = self.telemetry
        if isinstance(tel, dict):
            depth = tel.get("queue_depth", 0) or 0
        return float(depth) + float(self.inflight)

    def close(self):
        with self._lock:
            stale, self._pool = self._pool, []
        for c in stale:
            self.discard(c)


class FleetRouter:
    """Route requests across replicas; see the module docstring for
    the full contract. `replicas` maps name -> "host:port"."""

    def __init__(self, replicas: dict, config: FleetConfig = None):
        self.config = config or FleetConfig()
        self._handles = {
            name: ReplicaHandle(name, addr, self.config)
            for name, addr in replicas.items()
        }
        self._rr = 0
        self._lock = threading.Lock()
        self._stopped = False
        self._poller = threading.Thread(
            target=self._poll_loop, name="fleet-poll", daemon=True
        )
        self._poller.start()

    # ------------------------------------------------------- telemetry
    def _poll_loop(self):
        while not self._stopped:
            for h in list(self._handles.values()):
                if self._stopped:
                    return
                self._scrape(h)
            time.sleep(self.config.poll_interval_s)

    def _scrape(self, h: ReplicaHandle):
        """One metricz scrape. Doubles as the half-open liveness
        probe: for a non-closed breaker the poller must win
        `try_probe()` first, so rotation-in is decided by a cheap
        scrape, never by gambling a routed request on a replica that
        just died."""
        if h.breaker.state != "closed" and not h.breaker.try_probe():
            return
        client = h.checkout()
        try:
            resp = client.metricz(timeout=self.config.scrape_timeout_s)
            stats = resp.get("stats", {}) if isinstance(resp, dict) else {}
            h.telemetry = stats
            was_open = h.breaker.state != "closed"
            h.breaker.record(True)
            if was_open:
                _obs.get_registry().counter(
                    "fleet.rejoins").inc(replica=h.name)
            h.checkin(client)
        except Exception:
            h.discard(client)
            h.breaker.record(False)

    # --------------------------------------------------------- routing
    def _candidates(self) -> list:
        """Admitting, non-draining replicas, cheapest first; round-
        robin rotation breaks ties so equal-cost replicas share load
        instead of the dict-order replica taking everything."""
        with self._lock:
            self._rr += 1
            rr = self._rr
        handles = list(self._handles.values())
        n = len(handles)
        rotated = handles[rr % n:] + handles[: rr % n] if n else []
        live = [h for h in rotated
                if not h.draining and h.breaker.admits()]
        return sorted(live, key=lambda h: h.cost())

    def call(self, model: str, ids, deadline_ms: int = None,
             hooks: str = None, timeout: float = None,
             trace=None) -> dict:
        """Route one request. Returns the replica's response dict; a
        fleet-level shed ({"ok": False, "error": "overloaded"}) only
        after every admitting replica refused or failed."""
        with _tracing.span("fleet.route", model=model) as sp:
            resp = self._route(model, ids, deadline_ms, hooks,
                               timeout, trace, sp)
            if isinstance(resp, dict) and not resp.get("ok", False):
                sp.status = resp.get("error", "error")
            return resp

    def _route(self, model, ids, deadline_ms, hooks, timeout,
               trace, sp) -> dict:
        reg = _obs.get_registry()
        cands = self._candidates()
        limit = len(cands) if self.config.max_spills is None \
            else min(len(cands), self.config.max_spills + 1)
        last_shed = None
        spills = 0
        for h in cands[:limit]:
            # half-open: only one probe request at a time may test a
            # recovering replica; everyone else spills past it
            if h.breaker.state != "closed" and not h.breaker.try_probe():
                continue
            with h._lock:
                h.inflight += 1
            client = h.checkout()
            try:
                resp = client.call(
                    model, ids, deadline_ms=deadline_ms, hooks=hooks,
                    timeout=timeout or self.config.request_timeout_s,
                    trace=trace,
                )
            except Exception:
                # transport death (SIGKILL, reset, torn frame): the
                # request was NOT acknowledged — retry it on a sibling
                # and charge the breaker
                h.discard(client)
                h.breaker.record(False)
                reg.counter("fleet.transport_errors").inc(
                    replica=h.name)
                spills += 1
                continue
            finally:
                with h._lock:
                    h.inflight -= 1
            h.checkin(client)
            if isinstance(resp, dict) and not resp.get("ok", False) \
                    and resp.get("error") in ("overloaded",
                                              "shutting_down"):
                # replica-level shed = fleet-level routing hint
                h.breaker.record(True)  # alive, just busy
                reg.counter("fleet.spills").inc(replica=h.name)
                last_shed = resp
                spills += 1
                continue
            h.breaker.record(True)
            reg.counter("fleet.routed").inc(replica=h.name)
            sp.labels["replica"] = h.name
            sp.labels["spills"] = spills
            return resp
        reg.counter("fleet.shed").inc()
        if last_shed is not None:
            return dict(last_shed, fleet_spills=spills)
        return {"ok": False, "error": "overloaded",
                "detail": "no admitting replica", "fleet_spills": spills}

    # --------------------------------------------------------- rollout
    def rollout(self, model: str, tag: str = None,
                drain_timeout_s: float = 10.0) -> dict:
        """Zero-downtime hot swap of `model` across the fleet, one
        replica at a time. Returns {replica: swap-response}. Raises
        RuntimeError if any replica's swap fails — the fleet is then
        mixed-version and the caller must retry or roll back."""
        results = {}
        for h in list(self._handles.values()):
            h.draining = True  # siblings absorb; no refused window
            try:
                deadline = time.monotonic() + drain_timeout_s
                while time.monotonic() < deadline:
                    with h._lock:
                        if h.inflight == 0:
                            break
                    time.sleep(0.005)
                client = h.checkout()
                try:
                    msg = {"admin": "swap_model", "model": model}
                    if tag is not None:
                        msg["tag"] = tag
                    resp = client._roundtrip(
                        msg, timeout=self.config.request_timeout_s)
                except Exception as e:
                    h.discard(client)
                    raise RuntimeError(
                        f"rollout: swap on {h.name} died: {e}") from e
                h.checkin(client)
                results[h.name] = resp
                if not (isinstance(resp, dict) and resp.get("ok")):
                    raise RuntimeError(
                        f"rollout: swap on {h.name} refused: {resp}")
                _obs.get_registry().counter("fleet.rollouts").inc(
                    replica=h.name, model=model)
            finally:
                h.draining = False
        return results

    # ----------------------------------------------------- maintenance
    def set_address(self, name: str, addr: str):
        """Re-point a replica after a restart (keeps breaker state —
        the new process rejoins via the half-open probe)."""
        self._handles[name].set_address(addr)

    def handle(self, name: str) -> ReplicaHandle:
        return self._handles[name]

    def states(self) -> dict:
        """Per-replica router view (breaker state, cost, draining) —
        the fleet-level `metricz` complement."""
        return {
            name: {
                "addr": h.addr,
                "breaker": h.breaker.state,
                "draining": h.draining,
                "inflight": h.inflight,
                "queue_depth": (h.telemetry or {}).get("queue_depth"),
                "cost": h.cost(),
            }
            for name, h in self._handles.items()
        }

    def close(self):
        self._stopped = True
        self._poller.join(2.0)
        for h in self._handles.values():
            h.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
