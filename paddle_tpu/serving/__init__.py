"""Continuous-batching inference serving (ROADMAP item 3).

The pieces:
- `server` — bounded admission queue, load shedding, deadline-aware
  batch formation, bucketed continuous packing, per-model circuit
  breaker, drain-on-shutdown.
- `models` — GenerationModel (beam decode + host-stepped hook
  fallback) and MultiForwardHost (merged multi-model forward serving).
- `host_decode` — the per-token host-stepped decode rung (hooks
  without pure_callback).
- `tcp` — length-prefixed-JSON TCP front end + client.
- `fleet` — replica router: telemetry-balanced spill-before-shed,
  breaker-gated rotation, zero-downtime rollout (ISSUE 16).

CLI: `python -m paddle_tpu serve --config serve_conf.py [--port N]`
where the config defines `get_server() -> InferenceServer`.
"""

# Lazy exports (PEP 562): `server` transitively needs jax (batch
# formation uses data.feeder), but the TCP client and the fleetz /
# fleet_view operator surface must import without the device runtime
# (ISSUE 17) — so nothing here may eagerly drag server in.
_EXPORTS = {
    "InferenceServer": "paddle_tpu.serving.server",
    "PendingResult": "paddle_tpu.serving.server",
    "ServeConfig": "paddle_tpu.serving.server",
    "ServeError": "paddle_tpu.serving.server",
    "ServeRejected": "paddle_tpu.serving.server",
    "FleetConfig": "paddle_tpu.serving.fleet",
    "FleetRouter": "paddle_tpu.serving.fleet",
    "ReplicaHandle": "paddle_tpu.serving.fleet",
    "RolloutReport": "paddle_tpu.serving.fleet",
}


def __getattr__(name):
    if name in _EXPORTS:
        import importlib

        value = getattr(importlib.import_module(_EXPORTS[name]), name)
        globals()[name] = value
        return value
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
