"""Continuous-batching inference serving (ROADMAP item 3).

The pieces:
- `server` — bounded admission queue, load shedding, deadline-aware
  batch formation, bucketed continuous packing, per-model circuit
  breaker, drain-on-shutdown.
- `models` — GenerationModel (beam decode + host-stepped hook
  fallback) and MultiForwardHost (merged multi-model forward serving).
- `host_decode` — the per-token host-stepped decode rung (hooks
  without pure_callback).
- `tcp` — length-prefixed-JSON TCP front end + client.
- `fleet` — replica router: telemetry-balanced spill-before-shed,
  breaker-gated rotation, zero-downtime rollout (ISSUE 16).

CLI: `python -m paddle_tpu serve --config serve_conf.py [--port N]`
where the config defines `get_server() -> InferenceServer`.
"""

from paddle_tpu.serving.server import (  # noqa: F401
    InferenceServer,
    PendingResult,
    ServeConfig,
    ServeError,
    ServeRejected,
)
from paddle_tpu.serving.fleet import (  # noqa: F401
    FleetConfig,
    FleetRouter,
    ReplicaHandle,
)
