"""Continuous-batching LM engine over the paged KV pool (ISSUE 19).

`LMEngine` owns a fixed number of decode SLOTS — the width of the one
compiled decode program it dispatches per emitted token — and a queue
of admitted requests. Between any two decode dispatches it can:

- **admit** a queued request into a free slot: one bucketed prefill
  dispatch fills the request's pages and emits its first token;
- **evict** a live request mid-generation: its pages go back to the
  pool free list and the tokens it has emitted stay on the host, so
  the pool can serve someone else *now*;
- **readmit** an evicted request: its prompt + already-emitted tokens
  re-prefill as one sequence, which re-derives exactly the pool state
  the evicted chain had — the continuation is byte-identical to never
  having been evicted (pinned under the faults shard).

Inactive slots ride along in the decode dispatch as finished rows
pointing at a reserved scratch page; the program's `finished` lane
forces their lanes to eos and freezes their scores, so slot occupancy
can change between dispatches without recompiling (the decode program
is keyed only on slot count).

The measured cache story the decode bench row reports:
`cached_prefix_tokens` counts prefix tokens READ from pages by decode
dispatches (each one a full-prefix recompute the baseline would have
paid — `prefix_recompute_bytes_saved` prices them via
`models.lm.lm_prefix_token_recompute_bytes`); `reprefilled_tokens`
counts tokens a readmission had to recompute because eviction threw
its pages away. `cache_hit_frac = cached / (cached + reprefilled)`:
1.0 when nothing is evicted, and decode throughput measurably falls
with it as eviction pressure rises (the lm_decode bench row's
scaling points).

Module scope stays jax-free like every serving/ module: the device
work happens inside `decoding.kv_cache.PagedLM`, and the blocking
fetch of each dispatch's token lane is the engine's device-time
window (dispatch/device split, the satellite-6 rule).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.decoding.kv_cache import PagedLM, PoolExhausted

__all__ = ["LMEngine", "PagedLMModel"]


class _Seq:
    __slots__ = ("req_id", "prompt", "out", "score", "finished",
                 "pos", "pages", "slot", "evicted", "max_new",
                 "pending", "prefills")

    def __init__(self, req_id, prompt, max_new):
        self.req_id = req_id
        self.prompt = np.asarray(prompt, np.int32).ravel()
        self.out: List[int] = []
        self.score = 0.0
        self.finished = False
        self.pos = 0
        self.pages: List[int] = []
        self.slot: Optional[int] = None
        self.evicted = False
        self.max_new = int(max_new)
        self.pending = 0
        self.prefills = 0


class LMEngine:
    """slots: decode-program width (live requests per dispatch).
    auto_evict: on pool exhaustion, evict the live request with the
    fewest emitted tokens (cheapest to re-prefill) back to the queue
    instead of failing the admission."""

    def __init__(self, plm: PagedLM, slots: int = 4,
                 max_new: int = 32, auto_evict: bool = True):
        assert slots >= 1
        self.plm = plm
        self.cache = plm.cache
        self.num_slots = int(slots)
        self.max_new = int(max_new)
        self.auto_evict = auto_evict
        self.seqs: Dict[object, _Seq] = {}
        self.slots: List[Optional[object]] = [None] * self.num_slots
        self.queue = deque()
        self._scratch = self.cache.alloc(1)
        self.reprefilled_tokens = 0
        self.decode_dispatches = 0
        self.timeline = {"dispatch_s": 0.0, "device_s": 0.0}
        self._req_counter = 0

    # -- measured cache story ---------------------------------------
    @property
    def cache_hit_frac(self) -> float:
        hits = self.cache.cached_prefix_tokens
        miss = self.reprefilled_tokens
        return hits / (hits + miss) if (hits + miss) else 1.0

    @property
    def prefix_recompute_bytes_saved(self) -> int:
        from paddle_tpu.models.lm import lm_prefix_token_recompute_bytes

        per_tok = lm_prefix_token_recompute_bytes(self.plm.spec)
        return self.cache.cached_prefix_tokens * per_tok

    # -- request lifecycle ------------------------------------------
    def submit(self, prompt, max_new: Optional[int] = None,
               req_id=None):
        """Queue a request; it enters a slot (one prefill dispatch)
        as soon as one is free. Returns the request id."""
        if req_id is None:
            self._req_counter += 1
            req_id = f"r{self._req_counter}"
        assert req_id not in self.seqs, req_id
        self.seqs[req_id] = _Seq(
            req_id, prompt, max_new or self.max_new
        )
        self.queue.append(req_id)
        self.fill_slots()
        return req_id

    def evict(self, req_id, requeue: bool = True):
        """Drop a live request's pages back to the pool; its emitted
        tokens stay on the host. With requeue it re-prefills when a
        slot frees up; otherwise it parks until readmit()."""
        seq = self.seqs[req_id]
        assert seq.slot is not None and not seq.finished, req_id
        self.slots[seq.slot] = None
        seq.slot = None
        self.cache.free(seq.pages)
        seq.pages = []
        seq.evicted = True
        self.cache.evictions += 1
        if requeue:
            self.queue.append(req_id)

    def readmit(self, req_id):
        seq = self.seqs[req_id]
        assert seq.evicted and seq.slot is None, req_id
        self.queue.append(req_id)
        self.fill_slots()

    def fill_slots(self):
        """Admit queued requests into free slots — runs between any
        two decode dispatches (continuous batching)."""
        while self.queue and None in self.slots:
            req_id = self.queue[0]
            try:
                self._enter_slot(self.seqs[req_id])
            except PoolExhausted:
                if not (self.auto_evict and self._evict_cheapest()):
                    return
                continue
            self.queue.popleft()

    def _evict_cheapest(self) -> bool:
        live = [self.seqs[r] for r in self.slots if r is not None]
        if not live:
            return False
        victim = min(live, key=lambda s: len(s.out))
        self.evict(victim.req_id, requeue=True)
        return True

    def _enter_slot(self, seq: _Seq):
        slot = self.slots.index(None)
        toks = np.concatenate(
            [seq.prompt, np.asarray(seq.out, np.int32)]
        )
        bucket = self.cache.bucket_for(len(toks))
        ps = self.cache.page_size
        pages = self.cache.alloc(bucket // ps)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :len(toks)] = toks
        lens = np.asarray([len(toks)], np.int32)
        t0 = time.perf_counter()
        tok_d, sc_d = self.plm.prefill(padded, lens, [pages])
        t1 = time.perf_counter()
        tok = int(np.asarray(tok_d)[0])
        sc = float(np.asarray(sc_d)[0])
        t2 = time.perf_counter()
        self.timeline["dispatch_s"] += t1 - t0
        self.timeline["device_s"] += t2 - t1
        keep = self.cache.pages_for_len(len(toks))
        if len(pages) > keep:
            self.cache.free(pages[keep:])
            del pages[keep:]
        if seq.prefills > 0:
            self.reprefilled_tokens += int(len(toks))
        seq.prefills += 1
        seq.evicted = False
        seq.pages = pages
        seq.pos = len(toks)
        seq.pending = tok
        seq.score += sc
        seq.out.append(tok)
        if tok == self.plm.eos_id or len(seq.out) >= seq.max_new:
            seq.finished = True
            self.cache.free(seq.pages)
            seq.pages = []
            return
        self.slots[slot] = seq.req_id
        seq.slot = slot

    # -- the per-token dispatch -------------------------------------
    def step(self) -> int:
        """One fused decode dispatch across all slots. Returns the
        number of live rows it served (0 = nothing to do)."""
        # capacity pass FIRST: growing a row may evict another, so no
        # dispatch-array lane may be built until slot occupancy is
        # final (a stale lane would scatter into freed pages)
        for req_id in list(self.slots):
            if req_id is None:
                continue
            seq = self.seqs[req_id]
            if seq.slot is None:
                continue  # evicted by an earlier row's growth
            while True:
                try:
                    self.plm._grow([seq.pages], [seq.pos])
                    break
                except PoolExhausted:
                    if not (self.auto_evict
                            and self._evict_cheapest()):
                        self.evict(req_id, requeue=True)
                        break
                    if seq.slot is None:
                        break  # this row was the cheapest victim
        if not any(r is not None for r in self.slots):
            return 0
        r = self.num_slots
        eos = self.plm.eos_id
        tok = np.full((r,), eos, np.int32)
        pos = np.zeros((r,), np.int32)
        scores = np.zeros((r,), np.float32)
        finished = np.ones((r,), bool)
        page_lists = [list(self._scratch) for _ in range(r)]
        for i, req_id in enumerate(self.slots):
            if req_id is None:
                continue
            seq = self.seqs[req_id]
            tok[i] = seq.pending
            pos[i] = seq.pos
            scores[i] = seq.score
            finished[i] = False
            page_lists[i] = seq.pages
        t0 = time.perf_counter()
        nxt_d, sc_d, _fin_d = self.plm.decode_step(
            tok, pos, page_lists, scores, finished
        )
        t1 = time.perf_counter()
        nxt = np.asarray(nxt_d)
        sc = np.asarray(sc_d)
        t2 = time.perf_counter()
        self.timeline["dispatch_s"] += t1 - t0
        self.timeline["device_s"] += t2 - t1
        self.decode_dispatches += 1
        served = 0
        for i, req_id in enumerate(self.slots):
            if req_id is None:
                continue
            served += 1
            seq = self.seqs[req_id]
            seq.pos += 1
            seq.pending = int(nxt[i])
            seq.score = float(sc[i])
            seq.out.append(seq.pending)
            if seq.pending == eos or len(seq.out) >= seq.max_new:
                seq.finished = True
                self.slots[i] = None
                seq.slot = None
                self.cache.free(seq.pages)
                seq.pages = []
        self.fill_slots()
        return served

    def run(self, max_steps: Optional[int] = None):
        """Decode until every submitted request finishes."""
        budget = max_steps if max_steps is not None else (
            sum(s.max_new for s in self.seqs.values()) * 2
            + 16 * len(self.seqs)
        )
        steps = 0
        while self.step():
            steps += 1
            assert steps <= budget, "engine failed to converge"
        assert not self.queue, "queued requests never got a slot"
        return steps

    def result(self, req_id):
        seq = self.seqs[req_id]
        return {
            "tokens": list(seq.out),
            "score": float(seq.score),
            "finished": seq.finished,
            "prefills": seq.prefills,
        }

    def pop(self, req_id):
        out = self.result(req_id)
        del self.seqs[req_id]
        return out


class PagedLMModel:
    """Server-facing wrapper (the `GenerationModel` contract): packs
    each batch row through the continuous-batching engine so mid-call
    admissions/evictions happen between decode dispatches, not around
    whole requests."""

    can_host = False
    engine = None

    def __init__(self, plm: PagedLM, slots: int = 4,
                 max_new: int = 32):
        self.plm = plm
        self.lm_engine = LMEngine(plm, slots=slots, max_new=max_new)
        self.named_hooks = {}

    @property
    def tokens_per_dispatch(self):
        return 1

    @property
    def recompile_guards(self):
        return tuple(self.plm.recompile_guards)

    def run_batch(self, ids, lens, hooks, host: bool):
        assert hooks is None, "paged LM serving has no hook rung"
        eng = self.lm_engine
        b = ids.shape[0]
        req_ids = [
            eng.submit(ids[i, :int(lens[i])]) for i in range(b)
        ]
        eng.run()
        rows = []
        for rid in req_ids:
            res = eng.pop(rid)
            toks = res["tokens"]
            if toks and toks[-1] == self.plm.eos_id:
                toks = toks[:-1]
            rows.append({
                "tokens": toks,
                "score": res["score"],
                "path": "paged",
            })
        return rows
