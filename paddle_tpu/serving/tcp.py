"""TCP front end: length-prefixed JSON over a threaded socket server.

Frame = 4-byte LE length + UTF-8 JSON. Request:

    {"model": str, "ids": [int, ...], "deadline_ms": int?,
     "hooks": str?,            # hooks = a model-registered hook name
     "trace": {"trace_id": str, "span_id": str}?}   # trace carrier
  | {"metricz": true}          # telemetry scrape (no inference)
  | {"tracez": true, "top": int?}   # slow-request exemplars
  | {"flightz": true}          # flight-ring dump (incident stitch)
  | {"admin": "swap_model", "model": str, "tag": str?}  # hot-swap

Response:

    {"ok": true, "id": int, "tokens": [...], "score": float,
     "path": "jit"|"host", "latency_ms": float, "trace_id": str?}
  | {"ok": false, "error": "overloaded"|"deadline"|"quarantined"|
     "shutting_down"|"unknown_model"|"unknown_hook"|"execution"|
     "bad_request"}
  | {"ok": true, "metricz": <registry snapshot>, "stats": <server
     stats>}                   # for a metricz request
  | {"ok": true, "tracez": [exemplar, ...]}   # for a tracez request
  | {"ok": true, "flightz": {"pid": int, "enabled": bool,
     "events": [...], "capacity": int}}   # for a flightz request

The `trace` carrier makes one trace_id span the whole request path:
the client's `client.request` span, the server's `serve.request` root
and its queued / batch-form / dispatch / decode children all join the
caller's trace (obs/tracing.py). `tracez`, like `metricz`, is
answered OUTSIDE the admission queue: the slow-request exemplars
(latency + queued-vs-dispatch split + trace_id) stay scrapeable while
the server sheds.

`metricz` serves the process-wide obs registry (queue depth +
high-water mark, batch occupancy, shed/breaker counts, admitted-
latency histograms — plus whatever else the process recorded) without
touching the admission queue, so a scrape succeeds even when the
server is overloaded and shedding inference traffic.

Robustness contract (exercised by tests/test_serving_robustness.py
with FlakyProxy RST/delay faults): a client that vanishes — RST
mid-request, half-written frame, cut mid-response — costs the server
exactly one connection-handler thread unwinding on OSError. The
in-flight request still reaches a terminal state inside
InferenceServer (nothing leaks), and every other connection keeps
being served.
"""

from __future__ import annotations

import json
import os
import random
import socket
import struct
import threading
import time

from paddle_tpu.obs import flight_recorder as _flight
from paddle_tpu.obs import metrics as _obs
from paddle_tpu.obs import tracing as _tracing
# `server` transitively needs jax (batch formation); the CLIENT half
# of this module must stay importable without the device runtime
# (fleetz / fleet_view, ISSUE 17), so the server-side exception types
# resolve lazily — by the time ServingTCPServer handles a request,
# server.py is necessarily already imported (it wraps an
# InferenceServer instance).
if False:  # typing only — never executed
    from paddle_tpu.serving.server import InferenceServer  # noqa


def _server_errors():
    from paddle_tpu.serving.server import ServeError, ServeRejected

    return ServeError, ServeRejected

_MAX_FRAME = 1 << 24  # 16 MiB of JSON is garbage, not a request


def send_msg(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj).encode()
    sock.sendall(struct.pack("<I", len(body)) + body)


def recv_msg(sock: socket.socket):
    """One frame, or None on clean EOF. Raises ConnectionError on a
    torn frame or an absurd length."""
    hdr = b""
    while len(hdr) < 4:
        chunk = sock.recv(4 - len(hdr))
        if not chunk:
            if hdr:
                raise ConnectionError("torn frame header")
            return None
        hdr += chunk
    (n,) = struct.unpack("<I", hdr)
    if n > _MAX_FRAME:
        raise ConnectionError(f"frame length {n} exceeds limit")
    body = b""
    while len(body) < n:
        chunk = sock.recv(n - len(body))
        if not chunk:
            raise ConnectionError("connection closed mid-frame")
        body += chunk
    return json.loads(body.decode())


class ServingTCPServer:
    """Accept loop + one handler thread per connection, all daemonic.
    `stop()` closes the listener and the open connections —
    `stop(drain=True)` first waits (bounded) for in-flight requests
    to finish and their responses to flush, then joins the handler
    threads, so "zero admitted requests lost" is a guarantee rather
    than a timing accident (ISSUE 16). The underlying InferenceServer
    is NOT shut down here (the CLI owns its drain) so in-flight
    dispatches complete.

    `model_loader` (optional): callable `(model_name, tag) -> model`
    backing the `{"admin": "swap_model"}` frame — the zero-downtime
    rollout hook. The loader runs on the admin connection's handler
    thread while every other connection keeps being served; the swap
    itself is atomic inside InferenceServer.swap_model."""

    def __init__(self, server: InferenceServer, host: str = "127.0.0.1",
                 port: int = 0, model_loader=None):
        self.server = server
        self.model_loader = model_loader
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._stopped = False
        self._conns: list = []
        self._handlers: list = []
        self._inflight = 0
        self._lock = threading.Lock()
        self._thread = threading.Thread(
            target=self._accept_loop, name="serve-tcp", daemon=True
        )
        self._thread.start()

    def _accept_loop(self):
        while not self._stopped:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._lock:
                if self._stopped:
                    # raced stop_accepting() between accept() and
                    # registration: this connection would outlive
                    # stop()'s sweep of self._conns — close it here
                    # instead of serving it
                    try:
                        conn.close()
                    except OSError:
                        pass
                    return
                self._conns.append(conn)
                t = threading.Thread(target=self._serve_conn,
                                     args=(conn,), daemon=True)
                self._handlers.append(t)
                self._handlers = [
                    h for h in self._handlers if h.is_alive() or h is t
                ]
            t.start()

    def _serve_conn(self, conn: socket.socket):
        try:
            while True:
                try:
                    msg = recv_msg(conn)
                except (ConnectionError, OSError, ValueError):
                    return  # torn/garbage client: drop the connection
                if msg is None:
                    return
                # in-flight accounting covers handle AND the response
                # send: drain counts a request until its bytes left
                with self._lock:
                    self._inflight += 1
                try:
                    resp = self._handle(msg)
                    try:
                        send_msg(conn, resp)
                    except OSError:
                        return  # client gone mid-response: request
                        # already terminal server-side, nothing leaks
                finally:
                    with self._lock:
                        self._inflight -= 1
        finally:
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _handle(self, msg: dict) -> dict:
        if isinstance(msg, dict) and msg.get("metricz"):
            # telemetry scrape: answered outside the admission queue,
            # so it works during overload/drain
            return {
                "ok": True,
                "metricz": _obs.get_registry().snapshot(),
                "stats": self.server.stats(),
            }
        if isinstance(msg, dict) and msg.get("tracez"):
            # slow-request exemplars: also outside the admission queue
            try:
                top = int(msg.get("top", 10))
            except (TypeError, ValueError):
                return {"ok": False, "error": "bad_request",
                        "detail": f"top={msg.get('top')!r}"}
            return {
                "ok": True,
                "tracez": self.server.slow_exemplars(top=top),
            }
        if isinstance(msg, dict) and msg.get("flightz"):
            # flight-ring dump for cross-process incident stitching
            # (ISSUE 17): like metricz, answered OUTSIDE the admission
            # queue — an overloaded replica is exactly the one whose
            # ring an incident bundle needs
            rec = _flight.get_flight_recorder()
            return {
                "ok": True,
                "flightz": {
                    "pid": os.getpid(),
                    "enabled": rec is not None,
                    "events": rec.snapshot() if rec is not None else [],
                    "capacity": rec.capacity if rec is not None else 0,
                },
            }
        if isinstance(msg, dict) and msg.get("admin") == "swap_model":
            # zero-downtime hot swap: runs on this connection's handler
            # thread while every other connection keeps serving. The
            # actual switch is atomic inside InferenceServer.swap_model
            # (under the admission lock), so queued requests dispatch
            # against the new model and nothing is lost.
            name = msg.get("model")
            if not isinstance(name, str):
                return {"ok": False, "error": "bad_request",
                        "detail": "admin swap_model needs a model name"}
            if self.model_loader is None:
                return {"ok": False, "error": "no_loader",
                        "detail": "server started without a model_loader"}
            try:
                new_model = self.model_loader(name, msg.get("tag"))
                self.server.swap_model(name, new_model)
            except KeyError:
                return {"ok": False, "error": "unknown_model"}
            except Exception as e:
                return {"ok": False, "error": "swap_failed",
                        "detail": f"{type(e).__name__}: {e}"}
            return {"ok": True, "swapped": name,
                    "tag": msg.get("tag")}
        try:
            model = msg["model"]
            ids = msg["ids"]
            deadline_s = (
                msg["deadline_ms"] / 1e3 if "deadline_ms" in msg else None
            )
            hooks_name = msg.get("hooks")
            trace = msg.get("trace")
        except (KeyError, TypeError):
            return {"ok": False, "error": "bad_request"}
        ServeError, ServeRejected = _server_errors()
        try:
            req = self.server.submit(model, ids, deadline_s=deadline_s,
                                     hooks_name=hooks_name, trace=trace)
        except ServeRejected as e:
            return {"ok": False, "error": e.reason, "detail": str(e)}
        except Exception as e:
            # malformed payload (ids over the largest bucket, wrong
            # dtype, ...): the client gets bad_request, not a dropped
            # connection from a dead handler thread
            return {"ok": False, "error": "bad_request",
                    "detail": f"{type(e).__name__}: {e}"}
        try:
            # the scheduler enforces the deadline; the extra slack only
            # bounds a wedged dispatch so the handler thread cannot
            # block forever
            out = req.result(
                timeout=(req.deadline - req.t_submit) + 30.0
            )
        except ServeRejected as e:
            return {"ok": False, "error": e.reason, "id": req.id}
        except (ServeError, TimeoutError) as e:
            return {"ok": False, "error": "execution", "detail": str(e),
                    "id": req.id}
        resp = {"ok": True, "id": req.id,
                "latency_ms": round(req.latency_s * 1e3, 3)}
        if req.trace_id is not None:
            resp["trace_id"] = req.trace_id
        resp.update(out)
        return resp

    def stop_accepting(self, timeout: float = 1.0):
        """Close the listener only — established connections keep
        being served. Sets `_stopped` under the connection lock BEFORE
        closing the listener, so an accept() that races this call
        cannot register a new connection after `stop()` has swept
        `self._conns`; the accept thread is then joined (bounded) so
        no accept-loop activity overlaps the rest of the drain. The
        drain sequence is stop_accepting() ->
        InferenceServer.shutdown(drain=True) -> stop(drain=True), so
        clients with in-flight requests receive their drained
        responses instead of a reset. Idempotent."""
        with self._lock:
            self._stopped = True
        try:
            # shutdown() wakes a thread blocked in accept() (a bare
            # close() does not, on Linux); then release the fd
            self._listener.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._listener.close()
        except OSError:
            pass
        if self._thread is not threading.current_thread():
            self._thread.join(timeout)

    def stop(self, drain: bool = False, timeout: float = 5.0):
        """Tear down the front end. With `drain=True`, wait (up to
        `timeout` seconds) for in-flight requests — admitted frames
        whose response has not yet been sent — to reach zero before
        closing connections, then join handler threads with the
        remaining deadline. Idle keep-alive connections do not count
        as in-flight, so drain cannot be stalled by a client that is
        merely connected."""
        deadline = time.monotonic() + timeout
        self.stop_accepting(timeout=min(1.0, timeout))
        if drain:
            while time.monotonic() < deadline:
                with self._lock:
                    if self._inflight == 0:
                        break
                time.sleep(0.005)
        with self._lock:
            conns, self._conns = self._conns, []
            handlers, self._handlers = self._handlers, []
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        if drain:
            for h in handlers:
                h.join(max(0.0, deadline - time.monotonic()))


class ServeClient:
    """Blocking single-connection client (tests + load generator).
    Reconnects lazily after a connection error.

    `_connect` retries refused/reset connects with jittered
    exponential backoff (`retries` attempts beyond the first,
    doubling from `backoff_s` capped at `backoff_max_s`): the fleet
    router rides over a replica restart instead of failing the first
    request after a respawn. `retries=0` preserves fail-fast
    behavior for tests that assert a dead address errors
    immediately."""

    def __init__(self, addr: str, connect_timeout: float = 5.0,
                 retries: int = 3, backoff_s: float = 0.05,
                 backoff_max_s: float = 1.0,
                 admin_timeout: float = 5.0):
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        self._timeout = connect_timeout
        self._retries = max(0, int(retries))
        self._backoff_s = backoff_s
        self._backoff_max_s = backoff_max_s
        # admin frames (metricz/tracez/flightz) default to a BOUNDED
        # timeout distinct from the request path: a black-holed
        # replica must cost the fleet poller `admin_timeout`, not a
        # thread wedged forever (ISSUE 17 satellite, pinned with
        # FlakyProxy.black_hole)
        self._admin_timeout = admin_timeout
        self._sock = None

    def _connect(self):
        delay = self._backoff_s
        for attempt in range(self._retries + 1):
            try:
                sock = socket.create_connection(
                    (self._host, self._port), timeout=self._timeout
                )
                break
            except (ConnectionRefusedError, ConnectionResetError):
                if attempt == self._retries:
                    raise
                # full jitter on the low half so a fleet of clients
                # reconnecting to a restarted replica doesn't stampede
                time.sleep(delay * (0.5 + random.random() * 0.5))
                delay = min(delay * 2, self._backoff_max_s)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        self._sock = sock

    def call(self, model: str, ids, deadline_ms: int = None,
             hooks: str = None, timeout: float = None,
             trace=None) -> dict:
        """`trace`: None = inherit any active tracing context (the
        request joins it, with a `client.request` span around the
        roundtrip); True = force a fresh trace even without context;
        a carrier dict = join that remote trace; False = never
        trace."""
        msg = {"model": model, "ids": list(map(int, ids))}
        if deadline_ms is not None:
            msg["deadline_ms"] = int(deadline_ms)
        if hooks is not None:
            msg["hooks"] = hooks
        if isinstance(trace, dict):
            with _tracing.attach(trace):
                return self._traced_roundtrip(msg, timeout)
        if trace is True or (trace is None
                             and _tracing.current() is not None):
            return self._traced_roundtrip(msg, timeout)
        return self._roundtrip(msg, timeout)

    def _traced_roundtrip(self, msg: dict, timeout) -> dict:
        with _tracing.span("client.request",
                           model=msg.get("model", "")) as sp:
            msg["trace"] = _tracing.inject()
            resp = self._roundtrip(msg, timeout)
            if isinstance(resp, dict) and not resp.get("ok", False):
                sp.status = resp.get("error", "error")
            return resp

    def metricz(self, timeout: float = None) -> dict:
        """Scrape the server's registry snapshot + stats."""
        return self._roundtrip({"metricz": True},
                               self._admin(timeout))

    def tracez(self, top: int = 10, timeout: float = None) -> dict:
        """Scrape the server's slow-request exemplars."""
        return self._roundtrip({"tracez": True, "top": int(top)},
                               self._admin(timeout))

    def flightz(self, timeout: float = None) -> dict:
        """Fetch the server's flight-ring dump (incident stitching)."""
        return self._roundtrip({"flightz": True},
                               self._admin(timeout))

    def _admin(self, timeout):
        return timeout if timeout is not None else self._admin_timeout

    def _roundtrip(self, msg: dict, timeout: float = None) -> dict:
        if self._sock is None:
            self._connect()
        try:
            # set every call: None restores blocking mode, so a
            # timeout passed once cannot leak into later calls
            self._sock.settimeout(timeout)
            send_msg(self._sock, msg)
            resp = recv_msg(self._sock)
        except (OSError, ConnectionError):
            self.close()
            raise
        if resp is None:
            self.close()
            raise ConnectionError("server closed connection")
        return resp

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
