"""Sequence kernels over dense-packed [B, T, ...] batches.

Capability parity with the reference's sequence machinery — CUDA sequence
scatter/gather (paddle/cuda/src/hl_cuda_sequence.cu), sequence-aware layers
(SequencePoolLayer, SequenceLastInstanceLayer, ExpandLayer, ...), and
SequenceToBatch reordering (paddle/gserver/layers/SequenceToBatch.h) — but in
mask semantics on static shapes: every op takes [B, T, ...] plus seq_lens [B]
and guarantees padding positions never affect results.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _mask(seq_lens: jax.Array, t: int, dtype=jnp.float32) -> jax.Array:
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    return (pos < seq_lens[:, None]).astype(dtype)


def seq_sum(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """[B,T,D] -> [B,D] sum over real timesteps."""
    m = _mask(seq_lens, x.shape[1], x.dtype)
    return jnp.einsum("bt,bt...->b...", m, x)


def seq_avg(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    denom = jnp.maximum(seq_lens, 1).astype(x.dtype)
    return seq_sum(x, seq_lens) / denom[:, None]


def seq_sqrt_avg(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """sum / sqrt(len) — the reference's "SqrtAvgPooling"."""
    denom = jnp.sqrt(jnp.maximum(seq_lens, 1).astype(x.dtype))
    return seq_sum(x, seq_lens) / denom[:, None]


def seq_max(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    m = _mask(seq_lens, x.shape[1], x.dtype)
    neg = jnp.asarray(NEG_INF, x.dtype)
    masked = jnp.where(m[..., None] > 0, x, neg)
    return jnp.max(masked, axis=1)


def seq_last(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """[B,T,D] -> [B,D] value at t = len-1 (SequenceLastInstanceLayer)."""
    idx = jnp.maximum(seq_lens - 1, 0)
    return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]


def seq_first(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    del seq_lens
    return x[:, 0]


def expand_to_seq(x: jax.Array, seq_lens: jax.Array, t: int) -> jax.Array:
    """[B,D] -> [B,T,D] broadcast along time (ExpandLayer)."""
    out = jnp.broadcast_to(x[:, None], (x.shape[0], t) + x.shape[1:])
    return out * _mask(seq_lens, t, x.dtype)[(...,) + (None,) * (x.ndim - 1)]


def masked_softmax(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """Softmax over the time axis of [B,T] with padding masked out
    (the reference's sequence_softmax activation)."""
    m = _mask(seq_lens, x.shape[1], x.dtype)
    z = jnp.where(m > 0, x, jnp.asarray(NEG_INF, x.dtype))
    p = jax.nn.softmax(z, axis=1)
    return p * m


def reverse_seq(x: jax.Array, seq_lens: jax.Array) -> jax.Array:
    """Reverse each sequence in place, keeping padding at the tail
    (reference: SequenceReverseLayer / reversed recurrent groups)."""
    t = x.shape[1]
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    src = jnp.where(pos < seq_lens[:, None], seq_lens[:, None] - 1 - pos, pos)
    return jnp.take_along_axis(x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)


def seq_concat(a, a_lens, b, b_lens):
    """Concatenate two sequence batches along time per-row
    (SequenceConcatLayer). Output time dim = Ta + Tb (static)."""
    ta, tb = a.shape[1], b.shape[1]
    t_out = ta + tb
    out_lens = a_lens + b_lens
    pos = jnp.arange(t_out, dtype=jnp.int32)[None, :]  # [1, T_out]
    from_a = pos < a_lens[:, None]
    a_idx = jnp.clip(pos, 0, ta - 1)
    b_idx = jnp.clip(pos - a_lens[:, None], 0, tb - 1)
    extra = (1,) * (a.ndim - 2)
    a_gath = jnp.take_along_axis(a, a_idx.reshape(a_idx.shape + extra), axis=1)
    b_gath = jnp.take_along_axis(b, b_idx.reshape(b_idx.shape + extra), axis=1)
    valid = pos < out_lens[:, None]
    out = jnp.where(from_a.reshape(from_a.shape + extra), a_gath, b_gath)
    return out * valid.reshape(valid.shape + extra).astype(out.dtype), out_lens


def seq_shift(x: jax.Array, seq_lens: jax.Array, shift: int) -> jax.Array:
    """Per-sequence time shift with zero padding OUTSIDE each sequence's
    own [0, seq_len) — not the batch's [0, T): y[b,t] = x[b,t+shift] when
    both t and t+shift are inside sequence b, else 0. The building block
    for context projection (ContextProjection.h:28-40) and lookahead
    row conv; shifting the raw padded tensor instead would leak padding
    content from short sequences into valid timesteps."""
    T = x.shape[1]
    src = jnp.arange(T) + shift  # [T] source positions
    inside = (
        (src >= 0)
        & (src[None, :] < seq_lens[:, None])
        & (jnp.arange(T)[None, :] < seq_lens[:, None])
    )  # [B, T]
    src_c = jnp.clip(src, 0, T - 1)
    y = jnp.take(x, src_c, axis=1)
    return jnp.where(
        inside.reshape(inside.shape + (1,) * (x.ndim - 2)), y, 0
    )


def seq_slice_window(x, seq_lens, begin: int, size: int):
    """Static window slice along time (SeqSliceLayer, static case)."""
    sl = jnp.clip(seq_lens - begin, 0, size)
    return x[:, begin : begin + size], sl


def subseq_to_seq_lens(subseq_lens: jax.Array) -> jax.Array:
    """[B,S] nested lengths -> [B] total lengths."""
    return jnp.sum(subseq_lens, axis=1)


def subseq_pool(x, subseq_lens, op: str = "sum"):
    """Pool each sub-sequence: [B,T,D] + [B,S] -> [B,S,D], where the s-th
    output row pools x[t] for t in the s-th sub-sequence (the reference's
    sub-sequence pooling used by nested RecurrentGradientMachine,
    parameter/Argument.h:93 subSequenceStartPositions)."""
    b, t = x.shape[0], x.shape[1]
    s = subseq_lens.shape[1]
    ends = jnp.cumsum(subseq_lens, axis=1)  # [B,S]
    starts = ends - subseq_lens
    pos = jnp.arange(t, dtype=jnp.int32)[None, None, :]  # [1,1,T]
    inside = (pos >= starts[..., None]) & (pos < ends[..., None])  # [B,S,T]
    inside_f = inside.astype(x.dtype)
    if op == "sum":
        return jnp.einsum("bst,btd->bsd", inside_f, x)
    if op == "avg":
        denom = jnp.maximum(subseq_lens, 1).astype(x.dtype)[..., None]
        return jnp.einsum("bst,btd->bsd", inside_f, x) / denom
    if op == "sqrt_avg":
        denom = jnp.sqrt(jnp.maximum(subseq_lens, 1).astype(x.dtype))[..., None]
        return jnp.einsum("bst,btd->bsd", inside_f, x) / denom
    if op == "max":
        big = jnp.where(inside[..., None], x[:, None], jnp.asarray(NEG_INF, x.dtype))
        return jnp.max(big, axis=2)
    if op == "last":
        idx = jnp.maximum(ends - 1, 0)  # [B,S]
        return jnp.take_along_axis(x, idx[..., None], axis=1)
    if op == "first":
        return jnp.take_along_axis(x, starts[..., None], axis=1)
    raise ValueError(f"unknown subseq pool op {op!r}")
