"""Linear-chain CRF: negative log-likelihood and Viterbi decoding.

Reference: paddle/gserver/layers/LinearChainCRF.{h,cpp} (forward/backward
alpha-beta recursions), CRFLayer.cpp, CRFDecodingLayer.cpp. Parameter
layout matches the reference: w is [(num_tags + 2), num_tags] where row 0
holds start scores a, row 1 end scores b, rows 2.. the transition matrix
w[i,j] = score(tag i -> tag j).

TPU-first: log-domain forward recursion as a `lax.scan` over time with
masked carry (padding steps carry alpha through), logsumexp in fp32.
Backward comes from jax.grad of the log-partition — mathematically the
same marginals LinearChainCRF.cpp computes by explicit beta recursion.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import logsumexp


def _split(w):
    a = w[0]  # start [T]
    b = w[1]  # end [T]
    trans = w[2:]  # [T, T]
    return a, b, trans


def crf_log_likelihood(emit, labels, seq_lens, w):
    """emit: [B,T,N] unnormalized per-step tag scores; labels: [B,T] int;
    seq_lens: [B]. Returns [B] log p(labels | emit) (negative cost)."""
    return _crf_score(emit, labels, seq_lens, w) - crf_log_norm(
        emit, seq_lens, w
    )


def _crf_score(emit, labels, seq_lens, w):
    a, b, trans = _split(w)
    bsz, t, n = emit.shape
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    mask = (pos < seq_lens[:, None]).astype(emit.dtype)  # [B,T]
    picked = jnp.take_along_axis(emit, labels[..., None], axis=-1)[..., 0]
    score = jnp.sum(picked * mask, axis=1)
    score = score + a[labels[:, 0]]
    last = jnp.maximum(seq_lens - 1, 0)
    last_lab = jnp.take_along_axis(labels, last[:, None], axis=1)[:, 0]
    score = score + b[last_lab]
    # transitions between consecutive real steps
    tr = trans[labels[:, :-1], labels[:, 1:]]  # [B,T-1]
    score = score + jnp.sum(tr * mask[:, 1:], axis=1)
    return score


def crf_log_norm(emit, seq_lens, w):
    """log Z via masked forward recursion."""
    a, b, trans = _split(w)
    bsz, t, n = emit.shape
    alpha0 = a[None, :] + emit[:, 0]  # [B,N]
    pos = jnp.arange(1, t, dtype=jnp.int32)
    mask = (pos[None, :] < seq_lens[:, None]).astype(emit.dtype)  # [B,T-1]

    def step(alpha, inp):
        e_t, m_t = inp  # [B,N], [B]
        nxt = logsumexp(
            alpha[:, :, None] + trans[None, :, :], axis=1
        ) + e_t
        alpha = m_t[:, None] * nxt + (1 - m_t[:, None]) * alpha
        return alpha, None

    xs = (emit[:, 1:].swapaxes(0, 1), mask.swapaxes(0, 1))
    alpha, _ = jax.lax.scan(step, alpha0, xs)
    return logsumexp(alpha + b[None, :], axis=1)


def crf_decode(emit, seq_lens, w):
    """Viterbi: returns (best_paths [B,T] int32, best_scores [B])."""
    a, b, trans = _split(w)
    bsz, t, n = emit.shape
    delta0 = a[None, :] + emit[:, 0]
    pos = jnp.arange(1, t, dtype=jnp.int32)
    mask = pos[None, :] < seq_lens[:, None]  # [B,T-1] bool

    def step(delta, inp):
        e_t, m_t = inp
        cand = delta[:, :, None] + trans[None, :, :]  # [B,from,to]
        best_prev = jnp.argmax(cand, axis=1)  # [B,N]
        nxt = jnp.max(cand, axis=1) + e_t
        new_delta = jnp.where(m_t[:, None], nxt, delta)
        # on padded steps record identity backpointer
        bp = jnp.where(
            m_t[:, None], best_prev, jnp.arange(n, dtype=best_prev.dtype)[None, :]
        )
        return new_delta, bp

    xs = (emit[:, 1:].swapaxes(0, 1), mask.swapaxes(0, 1))
    delta, bps = jax.lax.scan(step, delta0, xs)  # bps: [T-1,B,N]
    final = delta + b[None, :]
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)  # [B]
    best_score = jnp.max(final, axis=1)

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        prev = prev.astype(jnp.int32)
        return prev, prev

    _, path_prefix = jax.lax.scan(back, last_tag, bps, reverse=True)
    # path_prefix[t] = best tag at step t (t in 0..T-2); append last tag
    paths = jnp.concatenate([path_prefix, last_tag[None, :]], axis=0)  # [T,B]
    return paths.swapaxes(0, 1), best_score
