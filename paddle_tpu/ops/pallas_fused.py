"""Fused BN->ReLU->1x1-conv GEMM kernels with stats epilogues (Mosaic).

The ResNet-50 MFU lever (PERF.md round-3 plan; reference CUDA analogue:
the hand-fused kernels under cuda/src/hl_cuda_cnn.cu). Round-3 profiling
showed convolutions are only 18% of the train step on v5e — the rest is
elementwise BN/ReLU/residual chains (42%) and BN-stats reductions (34%)
that XLA cannot fold into the conv kernels and does not multi-output-fuse.
A 1x1 convolution over NHWC is a GEMM over [N=B*H*W, Cin]; Mosaic lets
us put the whole bottleneck-glue chain inside that GEMM:

  input side:   z = act(u * scale + shift [+ residual])   (the PREVIOUS
                BatchNorm's normalize/affine + ReLU, and optionally the
                residual add) — u is read ONCE, z is never materialized
  matmul:       y = z @ w                                  (MXU, f32 acc)
  output side:  ssum = sum_n y, ssq = sum_n y*y            (the NEXT
                BatchNorm's statistics — no separate passes over y)

plus the custom VJP (two more pass-efficient GEMM kernels: dz/du/dscale/
dshift and dw, both recomputing z from u in registers instead of saving
it).

All shapes are padded row-wise to the block size; a row mask keeps
padding out of y and the statistics. Everything runs in interpret mode
on CPU (tests) and compiled Mosaic on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl


def _interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _block_rows(n: int, cin: int, cout: int, itemsize: int = 2) -> int:
    """Row-block size. Two failure modes bound it: too small and the
    grid's per-step fixed cost dominates (measured: bn=512 at
    N=802816/Cin=64 was grid-overhead-bound); too big and the kernel
    blows the 16 MiB scoped-VMEM stack (double-buffered in/out DMA
    blocks plus f32 compute temporaries). `itemsize` is the activation/
    weight dtype width — f32 inputs (non-AMP) double both the resident
    weight block and the row DMA buffers, so the budget shrinks."""
    # resident weight block (double-buffered) comes off the top
    budget = (8 << 20) - 2 * cin * cout * itemsize
    # per-row: in/out DMA blocks (u, du, y, dy at itemsize, x2 double
    # buffering) + f32 temporaries (z/dz/dy_eff)
    per_row = (4 * cin + 4 * cout) * itemsize + 8 * cin + 8 * cout
    for bn in (4096, 2048, 1024, 512, 256, 128, 64, 32, 8):
        if budget <= 0 or bn * per_row > budget or bn > max(n, 8):
            continue
        return bn
    return 8


def _pad_rows(x, bn):
    n = x.shape[0]
    pad = (-n) % bn
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    return x, n + pad


# --------------------------------------------------------------- fwd
def _fwd_kernel(n_valid, relu, has_res):
    def kernel(*refs):
        if has_res:
            u_ref, s_ref, t_ref, w_ref, r_ref, y_ref, s1_ref, s2_ref = refs
        else:
            u_ref, s_ref, t_ref, w_ref, y_ref, s1_ref, s2_ref = refs
        i = pl.program_id(0)
        bn = u_ref.shape[0]
        z = u_ref[...].astype(jnp.float32) * s_ref[...] + t_ref[...]
        if has_res:
            z = z + r_ref[...].astype(jnp.float32)
        if relu:
            z = jnp.maximum(z, 0.0)
        # mask padded rows out of the matmul AND the stats
        rows = lax.broadcasted_iota(jnp.int32, (bn, 1), 0) + i * bn
        z = jnp.where(rows < n_valid, z, 0.0)
        y = jnp.dot(
            z.astype(w_ref.dtype), w_ref[...],
            preferred_element_type=jnp.float32,
        )
        y_ref[...] = y.astype(y_ref.dtype)
        s1 = jnp.sum(y, axis=0, keepdims=True)
        s2 = jnp.sum(y * y, axis=0, keepdims=True)

        @pl.when(i == 0)
        def _init():
            s1_ref[...] = jnp.zeros_like(s1_ref)
            s2_ref[...] = jnp.zeros_like(s2_ref)

        s1_ref[...] += s1
        s2_ref[...] += s2

    return kernel


def _fwd_call(n, n_pad, bn, cin, cout, dtype, relu, has_res, interpret):
    grid = (n_pad // bn,)
    row_spec = pl.BlockSpec((bn, cin), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, cin), lambda i: (0, 0))
    w_spec = pl.BlockSpec((cin, cout), lambda i: (0, 0))
    out_specs = [
        pl.BlockSpec((bn, cout), lambda i: (i, 0)),
        pl.BlockSpec((1, cout), lambda i: (0, 0)),
        pl.BlockSpec((1, cout), lambda i: (0, 0)),
    ]
    in_specs = [row_spec, vec_spec, vec_spec, w_spec]
    if has_res:
        in_specs.append(row_spec)
    return pl.pallas_call(
        _fwd_kernel(n, relu, has_res),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, cout), dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )


def _fused_fwd_impl(u, scale, shift, w, res, relu):
    n, cin = u.shape
    cout = w.shape[1]
    bn = _block_rows(n, cin, cout, u.dtype.itemsize)
    u_p, n_pad = _pad_rows(u, bn)
    args = [
        u_p,
        scale.reshape(1, cin).astype(jnp.float32),
        shift.reshape(1, cin).astype(jnp.float32),
        # MXU in bf16 under AMP; full precision otherwise (tests)
        w.astype(u.dtype),
    ]
    if res is not None:
        args.append(_pad_rows(res, bn)[0])
    y, s1, s2 = _fwd_call(
        n, n_pad, bn, cin, cout, u.dtype, relu, res is not None,
        _interpret(),
    )(*args)
    return y[:n], s1[0], s2[0]


# --------------------------------------------------------------- bwd
def _bwd_dx_kernel(n_valid, relu, has_res):
    """du (+dres) + dscale/dshift: reads u, y, dy (+res); recomputes z's
    preactivation sign; dz = dy_eff @ w^T on the MXU."""

    def kernel(*refs):
        if has_res:
            (u_ref, s_ref, t_ref, w_ref, r_ref, y_ref, dy_ref, d1_ref,
             d2_ref, du_ref, dr_ref, ds_ref, dt_ref) = refs
        else:
            (u_ref, s_ref, t_ref, w_ref, y_ref, dy_ref, d1_ref, d2_ref,
             du_ref, ds_ref, dt_ref) = refs
        i = pl.program_id(0)
        bn = u_ref.shape[0]
        y = y_ref[...].astype(jnp.float32)
        dy_eff = dy_ref[...].astype(jnp.float32) + d1_ref[...] \
            + 2.0 * y * d2_ref[...]
        rows = lax.broadcasted_iota(jnp.int32, (bn, 1), 0) + i * bn
        dy_eff = jnp.where(rows < n_valid, dy_eff, 0.0)
        # dz = dy_eff @ w^T — contract over cout without materializing
        # the transpose
        dz = lax.dot_general(
            dy_eff.astype(w_ref.dtype), w_ref[...],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        u = u_ref[...].astype(jnp.float32)
        pre = u * s_ref[...] + t_ref[...]
        if has_res:
            pre = pre + r_ref[...].astype(jnp.float32)
        if relu:
            dz = dz * (pre > 0.0)
        du_ref[...] = (dz * s_ref[...]).astype(du_ref.dtype)
        if has_res:
            dr_ref[...] = dz.astype(dr_ref.dtype)
        ds = jnp.sum(dz * u, axis=0, keepdims=True)
        dt = jnp.sum(dz, axis=0, keepdims=True)

        @pl.when(i == 0)
        def _init():
            ds_ref[...] = jnp.zeros_like(ds_ref)
            dt_ref[...] = jnp.zeros_like(dt_ref)

        ds_ref[...] += ds
        dt_ref[...] += dt

    return kernel


def _bwd_dw_kernel(n_valid, relu, has_res):
    """dw += z^T @ dy_eff, z recomputed from u (never stored)."""

    def kernel(*refs):
        if has_res:
            (u_ref, s_ref, t_ref, r_ref, y_ref, dy_ref, d1_ref, d2_ref,
             dw_ref) = refs
        else:
            (u_ref, s_ref, t_ref, y_ref, dy_ref, d1_ref, d2_ref,
             dw_ref) = refs
        i = pl.program_id(0)
        bn = u_ref.shape[0]
        z = u_ref[...].astype(jnp.float32) * s_ref[...] + t_ref[...]
        if has_res:
            z = z + r_ref[...].astype(jnp.float32)
        if relu:
            z = jnp.maximum(z, 0.0)
        rows = lax.broadcasted_iota(jnp.int32, (bn, 1), 0) + i * bn
        z = jnp.where(rows < n_valid, z, 0.0)
        y = y_ref[...].astype(jnp.float32)
        dy_eff = dy_ref[...].astype(jnp.float32) + d1_ref[...] \
            + 2.0 * y * d2_ref[...]
        mxu = u_ref.dtype
        dw = lax.dot_general(
            z.astype(mxu), dy_eff.astype(mxu),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

        @pl.when(i == 0)
        def _init():
            dw_ref[...] = jnp.zeros_like(dw_ref)

        dw_ref[...] += dw

    return kernel


def _bwd_impl(relu, has_res, residuals, cotangents):
    u, scale, shift, w, res, y = residuals
    dy, d1, d2 = cotangents
    n, cin = u.shape
    cout = w.shape[1]
    bn = _block_rows(n, cin, cout, u.dtype.itemsize)
    u_p, n_pad = _pad_rows(u, bn)
    y_p, _ = _pad_rows(y, bn)
    dy_p, _ = _pad_rows(dy, bn)
    grid = (n_pad // bn,)
    interpret = _interpret()
    s2d = scale.reshape(1, cin).astype(jnp.float32)
    t2d = shift.reshape(1, cin).astype(jnp.float32)
    d1_2d = d1.reshape(1, cout).astype(jnp.float32)
    d2_2d = d2.reshape(1, cout).astype(jnp.float32)

    urow = pl.BlockSpec((bn, cin), lambda i: (i, 0))
    yrow = pl.BlockSpec((bn, cout), lambda i: (i, 0))
    cvec = pl.BlockSpec((1, cin), lambda i: (0, 0))
    ovec = pl.BlockSpec((1, cout), lambda i: (0, 0))
    wspec = pl.BlockSpec((cin, cout), lambda i: (0, 0))

    in_specs = [urow, cvec, cvec, wspec]
    args = [u_p, s2d, t2d, w.astype(u.dtype)]
    if has_res:
        in_specs.append(urow)
        args.append(_pad_rows(res, bn)[0])
    in_specs += [yrow, yrow, ovec, ovec]
    args += [y_p, dy_p, d1_2d, d2_2d]

    out_specs = [urow]
    out_shape = [jax.ShapeDtypeStruct((n_pad, cin), u.dtype)]
    if has_res:
        out_specs.append(urow)
        out_shape.append(jax.ShapeDtypeStruct((n_pad, cin), u.dtype))
    out_specs += [cvec, cvec]
    out_shape += [
        jax.ShapeDtypeStruct((1, cin), jnp.float32),
        jax.ShapeDtypeStruct((1, cin), jnp.float32),
    ]
    outs = pl.pallas_call(
        _bwd_dx_kernel(n, relu, has_res),
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    if has_res:
        du, dres, ds, dt = outs
        dres = dres[:n]
    else:
        du, ds, dt = outs
        dres = None
    du = du[:n]

    dw_in_specs = [urow, cvec, cvec]
    dw_args = [u_p, s2d, t2d]
    if has_res:
        dw_in_specs.append(urow)
        dw_args.append(_pad_rows(res, bn)[0])
    dw_in_specs += [yrow, yrow, ovec, ovec]
    dw_args += [y_p, dy_p, d1_2d, d2_2d]
    (dw,) = pl.pallas_call(
        _bwd_dw_kernel(n, relu, has_res),
        grid=grid,
        in_specs=dw_in_specs,
        out_specs=[pl.BlockSpec((cin, cout), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((cin, cout), jnp.float32)],
        interpret=interpret,
    )(*dw_args)
    return (
        du,
        ds[0].astype(scale.dtype),
        dt[0].astype(shift.dtype),
        dw.astype(w.dtype),
        dres,
    )


# ------------------------------------------------------- public op
@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _fused_core(u, scale, shift, w, res, relu):
    y, s1, s2 = _fused_fwd_impl(u, scale, shift, w, res, relu)
    return y, s1, s2


def _fused_core_fwd(u, scale, shift, w, res, relu):
    y, s1, s2 = _fused_fwd_impl(u, scale, shift, w, res, relu)
    return (y, s1, s2), (u, scale, shift, w, res, y)


def _fused_core_bwd(relu, residuals, cts):
    res = residuals[4]
    du, ds, dt, dw, dres = _bwd_impl(
        relu, res is not None, residuals, cts
    )
    return du, ds, dt, dw, dres


_fused_core.defvjp(_fused_core_fwd, _fused_core_bwd)


def bn_act_conv1x1(u, scale, shift, w, residual=None, act="relu"):
    """y, ssum, ssq = act(u*scale + shift [+ residual]) @ w with the
    output statistics accumulated in the kernel's epilogue.

    u: [N, Cin] (bf16 or f32); scale/shift: [Cin] (the previous BN's
    folded affine — pass ones/zeros for a plain conv+stats);
    w: [Cin, Cout]; residual: optional [N, Cin] added before the
    activation. act: "relu" or "" (linear). Differentiable in
    u/scale/shift/w/residual (custom VJP — two fused backward GEMMs).
    Returns y [N, Cout] in u's dtype, ssum/ssq [Cout] f32."""
    assert act in ("relu", ""), act
    return _fused_core(u, scale, shift, w, residual, act == "relu")
