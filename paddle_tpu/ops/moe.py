"""Mixture-of-Experts routing and expert-parallel FFN.

Beyond-reference capability (expert parallelism in the SURVEY §2
parallelism table). Switch-style fixed-capacity TOP-1 routing:
token->expert assignment becomes dense dispatch/combine einsum tensors
(static shapes, MXU-friendly), so XLA's GSPMD inserts the all-to-all
when the expert axis of the expert weights is sharded over the mesh.
Tokens route within fixed-size GROUPS (GShard's [G, S, ...] layout) so
dispatch tensors stay O(N * group_size) instead of O(N^2). Aux
load-balancing loss per GShard/Switch eq. 4.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def top1_routing(
    gate_logits: jax.Array,
    capacity: int,
    token_mask: jax.Array = None,
):
    """gate_logits [N, E] -> (dispatch [N, E, C] one-hot, combine
    [N, E, C] prob-weighted, aux_loss scalar).

    Tokens beyond an expert's capacity C are dropped (standard Switch
    behavior); position within the expert buffer is the token's rank
    among tokens routed to that expert. `token_mask` [N] (1 = real)
    excludes padded tokens BEFORE the rank cumsum so padding never
    consumes expert capacity or skews the balance statistics.
    """
    N, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.max(probs, axis=-1)  # [N]

    # rank accounting runs in float32 REGARDLESS of the activation
    # dtype: a bfloat16 cumsum loses integer exactness past 256 and
    # silently collides capacity slots under AMP
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)  # [N, E]
    if token_mask is not None:
        onehot = onehot * token_mask.astype(jnp.float32)[:, None]
    # rank of each token within its expert (0-based arrival order)
    pos = jnp.cumsum(onehot, axis=0) - onehot  # [N, E]
    pos_in_expert = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [N]
    keep = pos_in_expert < capacity
    if token_mask is not None:
        keep = keep & (token_mask > 0)
    gate = gate * keep

    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(pos_in_expert, capacity, dtype=jnp.float32)[
            :, None, :
        ]
        * keep[:, None, None]
    ).astype(probs.dtype)  # [N, E, C]
    combine = dispatch * gate[:, None, None]

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e,
    # statistics over REAL tokens only
    if token_mask is None:
        denom = float(N)
        probs_sum = jnp.sum(probs, axis=0)
    else:
        denom = jnp.maximum(jnp.sum(token_mask), 1.0)
        probs_sum = jnp.sum(probs * token_mask[:, None], axis=0)
    frac_tokens = jnp.sum(onehot, axis=0) / denom  # f_e
    frac_probs = probs_sum / denom  # p_e
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux




def moe_ffn(
    x: jax.Array,
    router_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    capacity_factor: float = 1.25,
    activation=jax.nn.relu,
    token_mask: jax.Array = None,
    group_size: int = 1024,
):
    """x [N, D]; router_w [D, E]; w_in [E, D, H]; w_out [E, H, D].
    Returns (y [N, D], aux_loss). token_mask [N] excludes padding from
    routing entirely.

    Tokens route within groups of S = min(group_size, N); N is padded up
    to a multiple of S with masked tokens, so dispatch/combine are
    [G, S, E, C] with
    C = cf*S/E: memory and FLOPs stay O(N * group_size), GShard's
    grouped layout, instead of O(N^2) for one global group.

    Shard w_in/w_out on the expert axis (PartitionSpec("model" | "expert"
    , ...)) for expert parallelism — the dispatch einsum then lowers to
    an all-to-all over ICI.
    """
    N = x.shape[0]
    E = router_w.shape[1]
    S = min(group_size, N)
    # pad to a multiple of S with MASKED tokens so grouping never
    # degenerates (a prime N must not collapse to one-token groups,
    # which would disable capacity discipline entirely)
    G = -(-N // S)
    pad = G * S - N
    mask = (
        token_mask.astype(jnp.float32)
        if token_mask is not None
        else jnp.ones((N,), jnp.float32)
    )
    if pad:
        x = jnp.concatenate(
            [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)]
        )
        mask = jnp.concatenate([mask, jnp.zeros((pad,), mask.dtype)])
    capacity = max(int(capacity_factor * S / E), 1)
    logits = (x @ router_w).reshape(G, S, E)
    xg = x.reshape(G, S, -1)
    mg = mask.reshape(G, S)
    dispatch, combine, aux = jax.vmap(
        lambda l, m: top1_routing(l, capacity, token_mask=m)
    )(logits, mg)
    # [G, E, C, D]: per-group expert input buffers
    xin = jnp.einsum("gsd,gsec->gecd", xg, dispatch)
    h = activation(jnp.einsum("gecd,edh->gech", xin, w_in))
    yout = jnp.einsum("gech,ehd->gecd", h, w_out)
    y = jnp.einsum("gecd,gsec->gsd", yout, combine)
    # aux weighted by each group's REAL token count: all-padding groups
    # contribute nothing, preserving the ungrouped loss semantics
    real_g = jnp.sum(mg, axis=1)
    aux = jnp.sum(aux * real_g) / jnp.maximum(jnp.sum(real_g), 1.0)
    return y.reshape(G * S, -1)[:N], aux
