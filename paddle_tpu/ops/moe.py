"""Mixture-of-Experts routing and expert-parallel FFN.

Beyond-reference capability (expert parallelism in the SURVEY §2
parallelism table). GShard-style fixed-capacity top-1/top-2 routing:
token->expert assignment becomes dense dispatch/combine einsum tensors
(static shapes, MXU-friendly), so XLA's GSPMD inserts the all-to-all
when the expert axis of the expert weights is sharded over the mesh.
Aux load-balancing loss per GShard/Switch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def top1_routing(
    gate_logits: jax.Array,
    capacity: int,
    token_mask: jax.Array = None,
):
    """gate_logits [N, E] -> (dispatch [N, E, C] one-hot, combine
    [N, E, C] prob-weighted, aux_loss scalar).

    Tokens beyond an expert's capacity C are dropped (standard Switch
    behavior); position within the expert buffer is the token's rank
    among tokens routed to that expert. `token_mask` [N] (1 = real)
    excludes padded tokens BEFORE the rank cumsum so padding never
    consumes expert capacity or skews the balance statistics.
    """
    N, E = gate_logits.shape
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # [N]
    gate = jnp.max(probs, axis=-1)  # [N]

    onehot = jax.nn.one_hot(expert, E, dtype=probs.dtype)  # [N, E]
    if token_mask is not None:
        onehot = onehot * token_mask[:, None]
    # rank of each token within its expert (0-based arrival order)
    pos = jnp.cumsum(onehot, axis=0) - onehot  # [N, E]
    pos_in_expert = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)  # [N]
    keep = pos_in_expert < capacity
    if token_mask is not None:
        keep = keep & (token_mask > 0)
    gate = gate * keep

    dispatch = (
        onehot[:, :, None]
        * jax.nn.one_hot(pos_in_expert, capacity, dtype=probs.dtype)[
            :, None, :
        ]
        * keep[:, None, None]
    )  # [N, E, C]
    combine = dispatch * gate[:, None, None]

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e,
    # statistics over REAL tokens only
    if token_mask is None:
        denom = float(N)
        probs_sum = jnp.sum(probs, axis=0)
    else:
        denom = jnp.maximum(jnp.sum(token_mask), 1.0)
        probs_sum = jnp.sum(probs * token_mask[:, None], axis=0)
    frac_tokens = jnp.sum(onehot, axis=0) / denom  # f_e
    frac_probs = probs_sum / denom  # p_e
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return dispatch, combine, aux


def moe_ffn(
    x: jax.Array,
    router_w: jax.Array,
    w_in: jax.Array,
    w_out: jax.Array,
    capacity_factor: float = 1.25,
    activation=jax.nn.relu,
    token_mask: jax.Array = None,
):
    """x [N, D]; router_w [D, E]; w_in [E, D, H]; w_out [E, H, D].
    Returns (y [N, D], aux_loss). token_mask [N] excludes padding from
    routing entirely.

    Shard w_in/w_out on the expert axis (PartitionSpec("model" | "expert"
    , ...)) for expert parallelism — the dispatch einsum then lowers to
    an all-to-all over ICI.
    """
    N = x.shape[0]
    E = router_w.shape[1]
    capacity = max(int(capacity_factor * N / E), 1)
    dispatch, combine, aux = top1_routing(
        x @ router_w, capacity, token_mask=token_mask
    )
    # [E, C, D]: expert input buffers
    xin = jnp.einsum("nd,nec->ecd", x, dispatch)
    h = activation(jnp.einsum("ecd,edh->ech", xin, w_in))
    yout = jnp.einsum("ech,ehd->ecd", h, w_out)
    y = jnp.einsum("ecd,nec->nd", yout, combine)
    return y, aux
