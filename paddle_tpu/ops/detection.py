"""SSD detection kernels: prior boxes, IoU, encode/decode, matching, NMS.

Reference: paddle/gserver/layers/DetectionUtil.cpp (jaccardOverlap:91,
encodeBBoxWithVar:112, decodeBBoxWithVar:137, matchBBox:234,
applyNMSFast:432, getDetectionIndices:466) and PriorBox.cpp:79-152.

TPU-first: everything is fixed-shape and jittable. Variable ground-truth
counts use a [B, G_max] mask instead of the reference's variable-length
label sequences; NMS runs as a bounded greedy `lax.fori_loop` producing a
keep mask rather than host-side vectors. Boxes are (xmin, ymin, xmax,
ymax), normalized to [0, 1].
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


def prior_boxes(
    layer_hw,
    image_hw,
    min_sizes,
    max_sizes,
    aspect_ratios,
    variances,
    flip: bool = True,
    clip: bool = True,
) -> np.ndarray:
    """[P, 8] rows of (box4, variance4) — PriorBox.cpp:95-145 write-stream
    ordering: per location, for each min_size: the min prior, then (if any
    max_sizes) one sqrt(min*max) prior per max_size nested inside that
    min_size iteration; after the min_size loop, aspect-ratio priors are
    emitted ONCE per location sized by the LAST min_size (the reference's
    `minSize` retains its final loop value at PriorBox.cpp:132-136).

    Note: for multi min/max-size configs the reference itself is broken —
    its declared output dim uses numPriors_ = len(ars) + (1 if max_sizes)
    (PriorBox.cpp:74-75), which undercounts what its own loop writes, so
    it overruns its buffer and truncates the copy. We return ALL priors
    the loop emits (internally consistent: downstream heads here size P
    from this array); single min/max configs match the reference
    bit-for-bit."""
    lh, lw = layer_hw
    ih, iw = image_hw
    step_w, step_h = iw / lw, ih / lh
    ars = [1.0]
    for ar in aspect_ratios:
        ars.append(ar)
        if flip:
            ars.append(1.0 / ar)
    if max_sizes:
        assert len(min_sizes) == len(max_sizes), (
            "PriorBox.cpp:117 requires len(min_sizes)==len(max_sizes)"
        )
    rows = []
    for h in range(lh):
        for w in range(lw):
            cx, cy = (w + 0.5) * step_w, (h + 0.5) * step_h
            for mn in min_sizes:
                rows.append((cx, cy, mn, mn))
                for mx in max_sizes or ():
                    m = math.sqrt(mn * mx)
                    rows.append((cx, cy, m, m))
            mn = min_sizes[-1]
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                rows.append(
                    (cx, cy, mn * math.sqrt(ar), mn / math.sqrt(ar))
                )
    r = np.asarray(rows, np.float32)
    boxes = np.stack(
        [
            (r[:, 0] - r[:, 2] / 2) / iw,
            (r[:, 1] - r[:, 3] / 2) / ih,
            (r[:, 0] + r[:, 2] / 2) / iw,
            (r[:, 1] + r[:, 3] / 2) / ih,
        ],
        axis=1,
    )
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    var = np.broadcast_to(
        np.asarray(variances, np.float32), boxes.shape
    ).copy()
    return np.concatenate([boxes, var], axis=1)


def iou_matrix(a: jax.Array, b: jax.Array) -> jax.Array:
    """[N, M] Jaccard overlap (DetectionUtil.cpp:91)."""
    ax1, ay1, ax2, ay2 = jnp.split(a, 4, axis=-1)  # [N,1]
    bx1, by1, bx2, by2 = (x[None, :, 0] for x in jnp.split(b, 4, axis=-1))
    iw = jnp.maximum(jnp.minimum(ax2, bx2) - jnp.maximum(ax1, bx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ay2, by2) - jnp.maximum(ay1, by1), 0.0)
    inter = iw * ih
    area_a = (ax2 - ax1) * (ay2 - ay1)
    area_b = (bx2 - bx1) * (by2 - by1)
    return inter / jnp.maximum(area_a + area_b - inter, 1e-10)


def encode_boxes(priors: jax.Array, variances: jax.Array, gt: jax.Array):
    """[P,4] regression targets (encodeBBoxWithVar)."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    return jnp.stack(
        [
            (gcx - pcx) / jnp.maximum(pw, 1e-10) / variances[:, 0],
            (gcy - pcy) / jnp.maximum(ph, 1e-10) / variances[:, 1],
            jnp.log(jnp.abs(gw / jnp.maximum(pw, 1e-10)) + 1e-10)
            / variances[:, 2],
            jnp.log(jnp.abs(gh / jnp.maximum(ph, 1e-10)) + 1e-10)
            / variances[:, 3],
        ],
        axis=1,
    )


def decode_boxes(priors: jax.Array, variances: jax.Array, loc: jax.Array):
    """[P,4] decoded boxes (decodeBBoxWithVar)."""
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    cx = variances[:, 0] * loc[:, 0] * pw + pcx
    cy = variances[:, 1] * loc[:, 1] * ph + pcy
    w = jnp.exp(variances[:, 2] * loc[:, 2]) * pw
    h = jnp.exp(variances[:, 3] * loc[:, 3]) * ph
    return jnp.stack(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=1
    )


def match_boxes(
    priors: jax.Array,
    gt_boxes: jax.Array,
    gt_mask: jax.Array,
    overlap_threshold: float,
):
    """(match_idx [P] int32 with -1 = unmatched, match_overlap [P]).

    DetectionUtil.cpp matchBBox:234 — bipartite phase: each ground truth
    claims its globally-best free prior (greedy on max overlap); then
    per-prediction phase: every still-free prior with best overlap >
    threshold takes its argmax ground truth.
    """
    P, G = priors.shape[0], gt_boxes.shape[0]
    ov = iou_matrix(priors, gt_boxes) * gt_mask[None, :]  # [P, G]

    def bipartite(carry, _):
        match_idx, gt_free = carry
        m = ov * gt_free[None, :] * (match_idx == -1)[:, None]
        flat = jnp.argmax(m)
        pi, gj = flat // G, flat % G
        valid = m[pi, gj] > 1e-6
        match_idx = jnp.where(
            valid, match_idx.at[pi].set(gj.astype(jnp.int32)), match_idx
        )
        gt_free = jnp.where(valid, gt_free.at[gj].set(0.0), gt_free)
        return (match_idx, gt_free), None

    init = (jnp.full((P,), -1, jnp.int32), gt_mask.astype(jnp.float32))
    (match_idx, _), _ = jax.lax.scan(bipartite, init, None, length=G)

    best_ov = jnp.max(ov, axis=1)
    best_gt = jnp.argmax(ov, axis=1).astype(jnp.int32)
    take = (match_idx == -1) & (best_ov > overlap_threshold)
    match_idx = jnp.where(take, best_gt, match_idx)
    return match_idx, best_ov


@partial(jax.jit, static_argnames=("top_k",))
def nms_mask(
    boxes: jax.Array,
    scores: jax.Array,
    threshold: float,
    top_k: int,
) -> jax.Array:
    """Greedy NMS keep-mask (applyNMSFast:432): scan scores descending,
    keep a box iff IoU with every already-kept box <= threshold; at most
    `top_k` kept. Returns [N] bool.

    Only the top_k highest-scoring candidates are considered at all, so
    the IoU matrix is k x k, not N x N — at SSD scale (P=8732, C=21) the
    full matrix per class would be ~6 GB."""
    N = boxes.shape[0]
    k = min(top_k, N)
    top_s, top_i = jax.lax.top_k(scores, k)
    cb = boxes[top_i]
    ov = iou_matrix(cb, cb)

    def body(i, keep):
        ok = jnp.all(jnp.where(keep, ov[i] <= threshold, True))
        ok = ok & (top_s[i] > 0)
        return keep.at[i].set(ok)

    keep_c = jax.lax.fori_loop(0, k, body, jnp.zeros((k,), bool))
    return jnp.zeros((N,), bool).at[top_i].set(keep_c)


def multibox_loss(
    loc_pred: jax.Array,
    conf_logits: jax.Array,
    priors: jax.Array,
    variances: jax.Array,
    gt_boxes: jax.Array,
    gt_labels: jax.Array,
    gt_mask: jax.Array,
    overlap_threshold: float = 0.5,
    neg_pos_ratio: float = 3.0,
    neg_overlap: float = 0.5,
    background_id: int = 0,
):
    """Per-image (loc_loss_sum, conf_loss_sum, num_matches).

    MultiBoxLossLayer.cpp:160-260 — smooth-L1 on matched priors vs
    encoded targets; softmax CE over matched priors (gt label) + hard
    negatives (background label), negatives chosen as the highest-
    conf-loss priors with overlap < neg_overlap, at most
    neg_pos_ratio * num_pos. Caller divides both sums by the global
    match count, exactly like locLoss_/confLoss_ normalization.
    """
    match_idx, match_ov = match_boxes(
        priors[:, :4], gt_boxes, gt_mask, overlap_threshold
    )
    pos = match_idx >= 0
    n_pos = jnp.sum(pos)

    safe_idx = jnp.maximum(match_idx, 0)
    gt_for_prior = gt_boxes[safe_idx]
    targets = encode_boxes(priors[:, :4], variances, gt_for_prior)
    d = jnp.abs(loc_pred - targets)
    sl1 = jnp.where(d < 1.0, 0.5 * d * d, d - 0.5)
    loc_loss = jnp.sum(jnp.where(pos[:, None], sl1, 0.0))

    lse = jax.scipy.special.logsumexp(conf_logits, axis=-1)
    label_for_prior = jnp.where(
        pos, gt_labels[safe_idx], background_id
    )
    ce = lse - jnp.take_along_axis(
        conf_logits, label_for_prior[:, None], axis=-1
    )[:, 0]
    pos_conf_loss = jnp.sum(jnp.where(pos, ce, 0.0))

    # hard negative mining on background CE
    bg_ce = lse - conf_logits[:, background_id]
    neg_cand = (~pos) & (match_ov < neg_overlap)
    neg_scores = jnp.where(neg_cand, bg_ce, -jnp.inf)
    n_neg = jnp.minimum(
        (neg_pos_ratio * n_pos).astype(jnp.int32), jnp.sum(neg_cand)
    )
    rank = jnp.argsort(jnp.argsort(-neg_scores))
    neg = neg_cand & (rank < n_neg)
    neg_conf_loss = jnp.sum(jnp.where(neg, bg_ce, 0.0))

    return loc_loss, pos_conf_loss + neg_conf_loss, n_pos


def detection_output(
    loc_pred: jax.Array,
    conf_logits: jax.Array,
    priors: jax.Array,
    variances: jax.Array,
    num_classes: int,
    background_id: int = 0,
    nms_threshold: float = 0.45,
    nms_top_k: int = 400,
    keep_top_k: int = 200,
    confidence_threshold: float = 0.01,
) -> jax.Array:
    """[keep_top_k, 6] rows (label, score, x1, y1, x2, y2), padded with
    score 0 (DetectionOutputLayer.cpp + getDetectionIndices:466): decode,
    per-class NMS over non-background classes, then keep the global
    top-k by score."""
    boxes = decode_boxes(priors[:, :4], variances, loc_pred)  # [P,4]
    probs = jax.nn.softmax(conf_logits, axis=-1)  # [P,C]

    def per_class(c):
        pc = jnp.take(probs, c, axis=1)
        s = jnp.where(
            (c != background_id) & (pc > confidence_threshold), pc, 0.0
        )
        keep = nms_mask(boxes, s, nms_threshold, nms_top_k)
        return jnp.where(keep, s, 0.0)

    kept = jax.vmap(per_class)(jnp.arange(num_classes))  # [C,P]
    flat = kept.reshape(-1)
    k = min(keep_top_k, flat.shape[0])
    top_s, top_i = jax.lax.top_k(flat, k)
    cls = (top_i // boxes.shape[0]).astype(jnp.float32)
    box = boxes[top_i % boxes.shape[0]]
    rows = jnp.concatenate(
        [cls[:, None], top_s[:, None], box], axis=1
    )
    out = jnp.zeros((keep_top_k, 6), jnp.float32)
    return out.at[:k].set(jnp.where(top_s[:, None] > 0, rows, 0.0))
