"""CTC loss (Connectionist Temporal Classification).

Reference: paddle/gserver/layers/LinearChainCTC.{h,cpp} (hand-written
log-domain alpha recursion, logMul/logAdd helpers) and the warp-ctc
wrapper (WarpCTCLayer.cpp, hl_warpctc_wrap.cc). One implementation here —
a `lax.scan` over time on the standard extended-label lattice [2L+1] in
log domain, batched and masked; no external library.

Conventions (matching LinearChainCTC.cpp): `blank` is a configurable
class index (the reference uses 0 for warpctc and numClasses_-1
internally; we default to 0 and expose it).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _logaddexp(a, b):
    return jnp.logaddexp(a, b)


def ctc_loss(log_probs, input_lens, labels, label_lens, blank=0):
    """log_probs: [B,T,C] log-softmax outputs; input_lens: [B];
    labels: [B,L] int32 (padded with anything); label_lens: [B].
    Returns [B] negative log likelihood."""
    bsz, t, c = log_probs.shape
    l = labels.shape[1]
    s = 2 * l + 1

    # extended label sequence: blank, l1, blank, l2, ..., blank
    ext = jnp.full((bsz, s), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    ext_valid = jnp.arange(s)[None, :] < (2 * label_lens + 1)[:, None]

    # can skip from s-2 to s: only when ext[s] is a label and != ext[s-2]
    can_skip = jnp.zeros((bsz, s), bool)
    can_skip = can_skip.at[:, 2:].set(
        (ext[:, 2:] != ext[:, :-2]) & (ext[:, 2:] != blank)
    )

    emit0 = jnp.take_along_axis(log_probs[:, 0], ext, axis=1)  # [B,S]
    alpha0 = jnp.full((bsz, s), NEG_INF)
    alpha0 = alpha0.at[:, 0].set(emit0[:, 0])
    has_label = label_lens > 0
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(has_label, emit0[:, 1], NEG_INF)
    )

    pos = jnp.arange(1, t, dtype=jnp.int32)
    step_mask = (pos[None, :] < input_lens[:, None])  # [B,T-1]

    def step(alpha, inp):
        lp_t, m_t = inp  # [B,C], [B]
        emit = jnp.take_along_axis(lp_t, ext, axis=1)  # [B,S]
        stay = alpha
        prev1 = jnp.concatenate(
            [jnp.full((bsz, 1), NEG_INF), alpha[:, :-1]], axis=1
        )
        prev2 = jnp.concatenate(
            [jnp.full((bsz, 2), NEG_INF), alpha[:, :-2]], axis=1
        )
        prev2 = jnp.where(can_skip, prev2, NEG_INF)
        new = _logaddexp(_logaddexp(stay, prev1), prev2) + emit
        new = jnp.where(ext_valid, new, NEG_INF)
        return jnp.where(m_t[:, None], new, alpha), None

    xs = (log_probs[:, 1:].swapaxes(0, 1), step_mask.swapaxes(0, 1))
    alpha, _ = jax.lax.scan(step, alpha0, xs)

    # final: sum of last blank and last label positions
    end_idx = 2 * label_lens  # last blank
    a_end = jnp.take_along_axis(alpha, end_idx[:, None], axis=1)[:, 0]
    lab_idx = jnp.maximum(2 * label_lens - 1, 0)
    a_lab = jnp.take_along_axis(alpha, lab_idx[:, None], axis=1)[:, 0]
    a_lab = jnp.where(has_label, a_lab, NEG_INF)
    ll = _logaddexp(a_end, a_lab)
    return -ll


def ctc_greedy_decode(log_probs, input_lens, blank=0):
    """Best-path decode: argmax per step, collapse repeats, drop blanks.
    Returns (paths [B,T] int32 padded with blank, lens [B])."""
    pred = jnp.argmax(log_probs, axis=-1).astype(jnp.int32)  # [B,T]
    bsz, t = pred.shape
    pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    valid = pos < input_lens[:, None]
    prev = jnp.concatenate(
        [jnp.full((bsz, 1), -1, jnp.int32), pred[:, :-1]], axis=1
    )
    keep = valid & (pred != blank) & (pred != prev)

    # compact kept tokens to the left (stable) via sort on (not keep, pos)
    order = jnp.argsort(jnp.where(keep, pos, t + pos), axis=1)
    gathered = jnp.take_along_axis(pred, order, axis=1)
    lens = jnp.sum(keep, axis=1).astype(jnp.int32)
    out_pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    out = jnp.where(out_pos < lens[:, None], gathered, blank)
    return out, lens
