"""Activation functions.

Parity with the reference's 14 activation classes
(paddle/gserver/activations/ActivationFunction.cpp:94-438): sigmoid,
softmax, sequence_softmax, relu, brelu, tanh, stanh, softrelu, abs,
square, exponential, reciprocal, sqrt, log (+ linear = identity).

Forward-only definitions: backward comes from jax.grad, unlike the
reference's paired forward/backward methods.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import nn as jnn

from paddle_tpu.core.registry import ACTIVATIONS

_FUNCS = {}


def register_activation(name):
    def deco(fn):
        _FUNCS[name] = fn
        ACTIVATIONS.register(name)(type("Act_" + name, (), {"fn": staticmethod(fn)}))
        return fn

    return deco


def get(name: str):
    if name in ("", "linear", None):
        return lambda x: x
    try:
        return _FUNCS[name]
    except KeyError:
        raise KeyError(
            f"unknown activation {name!r}; known: {sorted(_FUNCS)}"
        ) from None


register_activation("sigmoid")(jnn.sigmoid)
register_activation("relu")(jnn.relu)
register_activation("tanh")(jnp.tanh)
register_activation("abs")(jnp.abs)
register_activation("square")(jnp.square)
register_activation("exponential")(jnp.exp)
register_activation("sqrt")(jnp.sqrt)
register_activation("log")(jnp.log)


@register_activation("softmax")
def softmax(x):
    return jnn.softmax(x, axis=-1)


@register_activation("brelu")
def brelu(x):
    # bounded relu: min(max(x, 0), 24) (ActivationFunction.cpp BRelu)
    return jnp.clip(x, 0.0, 24.0)


@register_activation("stanh")
def stanh(x):
    # scaled tanh: 1.7159 * tanh(2/3 x)
    return 1.7159 * jnp.tanh(x * (2.0 / 3.0))


@register_activation("softrelu")
def softrelu(x):
    # log(1 + exp(x)), input clipped to +-40 as in the reference
    return jnn.softplus(jnp.clip(x, -40.0, 40.0))


@register_activation("reciprocal")
def reciprocal(x):
    return 1.0 / x


@register_activation("sequence_softmax")
def sequence_softmax_unmasked(x):
    """Placeholder registration — real sequence softmax needs the mask and
    lives in ops.sequence_ops.masked_softmax; layers route there when the
    input is a sequence."""
    return jnn.softmax(x, axis=-1)
