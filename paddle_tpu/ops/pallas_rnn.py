"""Fused LSTM/GRU sequence kernels in Pallas.

TPU-native equivalent of the reference's fused recurrent CUDA cells
(cuda/src/hl_cuda_lstm.cu, cuda/include/hl_gpu_gru.cuh): the whole time
loop runs inside ONE kernel, with hidden/cell state pinned in VMEM and the
per-step recurrent matmul on the MXU — no HBM round-trip of h/c/gate
intermediates between steps, which is what the XLA `lax.scan` lowering
pays for.

Numerics match the `lax.scan` reference implementations (`lstm_ref`,
`gru_ref`) exactly — masked-carry semantics included: at padded timesteps
the state carries through unchanged and the output is zeroed (the
SequenceToBatch contract, gserver/layers/SequenceToBatch.h).

Backward: `jax.custom_vjp` recomputes through the reference scan — exact
gradients at the cost of one recompute (the standard rematerialization
trade; forward/inference gets the full kernel win).

Gate orders match the layer/bias layouts in layers/recurrent.py:
LSTM [i, f, g, o] with peepholes (wci, wcf, wco); GRU [u, r | c].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_BUDGET = 8 * 1024 * 1024  # soft per-block budget (VMEM is ~16MB)


def _batch_block(b: int, t: int, feat: int, out: int) -> int:
    """Largest divisor of `b` whose x+y blocks fit the VMEM budget."""
    per_row = (t * feat + t * out + 8 * out) * 4
    cap = max(1, _VMEM_BUDGET // max(per_row, 1))
    bb = 1
    for d in range(1, b + 1):
        if b % d == 0 and d <= cap:
            bb = d
    return bb


# ---------------------------------------------------------------- LSTM

def lstm_ref(x, w, gb, wci, wcf, wco, lens):
    """Reference scan. x: [B,T,4h] pre-projected input; w: [h,4h];
    gb: [4h]; peepholes [h] each; lens: [B] int32. Returns y [B,T,h]."""
    h = w.shape[0]
    t_max = x.shape[1]
    mask = (
        jnp.arange(t_max, dtype=jnp.int32)[None, :] < lens[:, None]
    ).astype(x.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        g = x_t + jnp.dot(h_prev, w) + gb
        gi, gf, gg, go = jnp.split(g, 4, axis=-1)
        i = jax.nn.sigmoid(gi + wci * c_prev)
        f = jax.nn.sigmoid(gf + wcf * c_prev)
        cand = jnp.tanh(gg)
        c = f * c_prev + i * cand
        o = jax.nn.sigmoid(go + wco * c)
        out = o * jnp.tanh(c)
        m = m_t[:, None]
        h_new = m * out + (1 - m) * h_prev
        c_new = m * c + (1 - m) * c_prev
        return (h_new, c_new), out * m

    bsz = x.shape[0]
    z = jnp.zeros((bsz, h), x.dtype)
    _, ys = lax.scan(
        step, (z, z), (x.swapaxes(0, 1), mask.swapaxes(0, 1))
    )
    return ys.swapaxes(0, 1)


def _lstm_kernel(x_ref, w_ref, b_ref, lens_ref, y_ref, h_scr, c_scr):
    bb, t_max, h4 = x_ref.shape
    h = h4 // 4
    h_scr[:] = jnp.zeros_like(h_scr)
    c_scr[:] = jnp.zeros_like(c_scr)
    gb = b_ref[0, : 4 * h]
    wci = b_ref[0, 4 * h : 5 * h]
    wcf = b_ref[0, 5 * h : 6 * h]
    wco = b_ref[0, 6 * h : 7 * h]
    lens = lens_ref[:, 0]

    def body(t, _):
        x_t = x_ref[:, t, :]
        h_prev = h_scr[:]
        c_prev = c_scr[:]
        g = (
            x_t
            + jnp.dot(h_prev, w_ref[:], preferred_element_type=jnp.float32)
            + gb
        )
        gi = g[:, :h]
        gf = g[:, h : 2 * h]
        gg = g[:, 2 * h : 3 * h]
        go = g[:, 3 * h :]
        i = jax.nn.sigmoid(gi + wci * c_prev)
        f = jax.nn.sigmoid(gf + wcf * c_prev)
        cand = jnp.tanh(gg)
        c = f * c_prev + i * cand
        o = jax.nn.sigmoid(go + wco * c)
        out = o * jnp.tanh(c)
        m = (t < lens).astype(jnp.float32)[:, None]
        h_scr[:] = m * out + (1 - m) * h_prev
        c_scr[:] = m * c + (1 - m) * c_prev
        # state stays float32 in VMEM; the output ref may be bfloat16
        # under AMP — cast at the store
        y_ref[:, t, :] = (out * m).astype(y_ref.dtype)
        return 0

    lax.fori_loop(0, t_max, body, 0)


def _lstm_fwd_kernel(x, w, b7, lens, *, interpret):
    # Mosaic compiles this kernel for float32; under bf16 AMP upcast in
    # (the cell math runs float32 internally regardless) and cast the
    # sequence output back
    orig = x.dtype
    if orig == jnp.bfloat16:
        x = x.astype(jnp.float32)
        w = w.astype(jnp.float32)
        b7 = b7.astype(jnp.float32)
    bsz, t_max, h4 = x.shape
    h = h4 // 4
    bb = _batch_block(bsz, t_max, h4, h)
    grid = (bsz // bb,)
    return pl.pallas_call(
        _lstm_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, t_max, h4), lambda i: (i, 0, 0)),
            pl.BlockSpec((h, h4), lambda i: (0, 0)),
            pl.BlockSpec((1, 7 * h), lambda i: (0, 0)),
            pl.BlockSpec((bb, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bb, t_max, h), lambda i: (i, 0, 0)),
        # NOTE: a bf16 output ref would halve output HBM traffic, but
        # the Mosaic toolchain on this TPU fails to compile bf16 stores
        # from this kernel (remote_compile 500) — so the kernel emits
        # float32 and XLA converts after. Revisit when Mosaic allows it.
        out_shape=jax.ShapeDtypeStruct((bsz, t_max, h), x.dtype),
        scratch_shapes=[
            pltpu.VMEM((bb, h), jnp.float32),
            pltpu.VMEM((bb, h), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, b7, lens).astype(orig)


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def lstm_fused(x, w, gb, wci, wcf, wco, lens, interpret=False):
    b7 = jnp.concatenate([gb, wci, wcf, wco])[None, :]
    return _lstm_fwd_kernel(
        x, w, b7, lens[:, None].astype(jnp.int32), interpret=interpret
    )


def _lstm_fused_fwd(x, w, gb, wci, wcf, wco, lens, interpret):
    y = lstm_fused(x, w, gb, wci, wcf, wco, lens, interpret)
    return y, (x, w, gb, wci, wcf, wco, lens)


def _lstm_fused_bwd(interpret, res, dy):
    x, w, gb, wci, wcf, wco, lens = res
    _, vjp = jax.vjp(lambda *a: lstm_ref(*a, lens), x, w, gb, wci, wcf, wco)
    return (*vjp(dy), None)


lstm_fused.defvjp(_lstm_fused_fwd, _lstm_fused_bwd)


# ---------------------------------------------------------------- GRU

def gru_ref(x, w_g, w_c, b, lens):
    """Reference scan. x: [B,T,3h] as [u,r,c]; w_g: [h,2h]; w_c: [h,h];
    b: [3h]; lens [B]. Returns y [B,T,h]."""
    h = w_c.shape[0]
    t_max = x.shape[1]
    mask = (
        jnp.arange(t_max, dtype=jnp.int32)[None, :] < lens[:, None]
    ).astype(x.dtype)

    def step(h_prev, inp):
        x_t, m_t = inp
        xu, xr, xc = jnp.split(x_t + b, 3, axis=-1)
        gur = jnp.dot(h_prev, w_g)
        u = jax.nn.sigmoid(xu + gur[:, :h])
        r = jax.nn.sigmoid(xr + gur[:, h:])
        c = jnp.tanh(xc + jnp.dot(r * h_prev, w_c))
        out = u * h_prev + (1 - u) * c
        m = m_t[:, None]
        h_new = m * out + (1 - m) * h_prev
        return h_new, out * m

    bsz = x.shape[0]
    z = jnp.zeros((bsz, h), x.dtype)
    _, ys = lax.scan(step, z, (x.swapaxes(0, 1), mask.swapaxes(0, 1)))
    return ys.swapaxes(0, 1)


def _gru_kernel(x_ref, wg_ref, wc_ref, b_ref, lens_ref, y_ref, h_scr):
    bb, t_max, h3 = x_ref.shape
    h = h3 // 3
    h_scr[:] = jnp.zeros_like(h_scr)
    b = b_ref[0, :]
    lens = lens_ref[:, 0]

    def body(t, _):
        x_t = x_ref[:, t, :] + b
        h_prev = h_scr[:]
        xu = x_t[:, :h]
        xr = x_t[:, h : 2 * h]
        xc = x_t[:, 2 * h :]
        gur = jnp.dot(
            h_prev, wg_ref[:], preferred_element_type=jnp.float32
        )
        u = jax.nn.sigmoid(xu + gur[:, :h])
        r = jax.nn.sigmoid(xr + gur[:, h:])
        c = jnp.tanh(
            xc
            + jnp.dot(
                r * h_prev, wc_ref[:], preferred_element_type=jnp.float32
            )
        )
        out = u * h_prev + (1 - u) * c
        m = (t < lens).astype(jnp.float32)[:, None]
        h_scr[:] = m * out + (1 - m) * h_prev
        # float32 VMEM state; output ref may be bfloat16 under AMP
        y_ref[:, t, :] = (out * m).astype(y_ref.dtype)
        return 0

    lax.fori_loop(0, t_max, body, 0)


def _gru_fwd_kernel(x, w_g, w_c, b, lens, *, interpret):
    # same bf16-AMP upcast as the LSTM kernel
    orig = x.dtype
    if orig == jnp.bfloat16:
        x = x.astype(jnp.float32)
        w_g = w_g.astype(jnp.float32)
        w_c = w_c.astype(jnp.float32)
        b = b.astype(jnp.float32)
    bsz, t_max, h3 = x.shape
    h = h3 // 3
    bb = _batch_block(bsz, t_max, h3, h)
    grid = (bsz // bb,)
    return pl.pallas_call(
        _gru_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, t_max, h3), lambda i: (i, 0, 0)),
            pl.BlockSpec((h, 2 * h), lambda i: (0, 0)),
            pl.BlockSpec((h, h), lambda i: (0, 0)),
            pl.BlockSpec((1, 3 * h), lambda i: (0, 0)),
            pl.BlockSpec((bb, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bb, t_max, h), lambda i: (i, 0, 0)),
        # float32 out + convert: see the Mosaic bf16-store note in
        # _lstm_fwd_kernel
        out_shape=jax.ShapeDtypeStruct((bsz, t_max, h), x.dtype),
        scratch_shapes=[pltpu.VMEM((bb, h), jnp.float32)],
        interpret=interpret,
    )(x, w_g, w_c, b[None, :], lens).astype(orig)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def gru_fused(x, w_g, w_c, b, lens, interpret=False):
    return _gru_fwd_kernel(
        x, w_g, w_c, b, lens[:, None].astype(jnp.int32), interpret=interpret
    )


def _gru_fused_fwd(x, w_g, w_c, b, lens, interpret):
    y = gru_fused(x, w_g, w_c, b, lens, interpret)
    return y, (x, w_g, w_c, b, lens)


def _gru_fused_bwd(interpret, res, dy):
    x, w_g, w_c, b, lens = res
    _, vjp = jax.vjp(lambda *a: gru_ref(*a, lens), x, w_g, w_c, b)
    return (*vjp(dy), None)


gru_fused.defvjp(_gru_fused_fwd, _gru_fused_bwd)


def use_fused_default() -> bool:
    """Auto policy: fused kernels on real TPU, scan elsewhere."""
    try:
        plat = jax.devices()[0].platform
    except Exception:
        return False
    return plat not in ("cpu", "gpu")
