"""Fused LSTM/GRU sequence kernels in Pallas.

TPU-native equivalent of the reference's fused recurrent CUDA cells
(cuda/src/hl_cuda_lstm.cu, cuda/include/hl_gpu_gru.cuh): the whole time
loop runs inside ONE kernel, with hidden/cell state pinned in VMEM and the
per-step recurrent matmul on the MXU — no HBM round-trip of h/c/gate
intermediates between steps, which is what the XLA `lax.scan` lowering
pays for.

Numerics match the `lax.scan` reference implementations (`lstm_ref`,
`gru_ref`) exactly — masked-carry semantics included: at padded timesteps
the state carries through unchanged and the output is zeroed (the
SequenceToBatch contract, gserver/layers/SequenceToBatch.h).

Layout: the grid is (batch blocks, time blocks); time blocks stream
through VMEM (double-buffered by the Pallas pipeline) while the h/c
carry lives in VMEM scratch across the whole time sweep, so VMEM usage
is O(bb·tb·h) regardless of sequence length. Batch and time are padded
to multiples of 8 (Mosaic's sublane constraint); padded rows/steps are
masked out by the length mask, so padding is numerically free.

Backward (LSTM): a REVERSE-time Pallas kernel (`_lstm_bwd_kernel`) —
time blocks visited back-to-front via the index map, gates recomputed
from the saved y/c sequences (one extra matmul per step, the standard
memory/FLOP trade), dW/db accumulated across the whole grid in resident
output blocks. GRU backward still recomputes through the scan reference.

When the plan does not fit VMEM (forward: w alone is h·4h floats;
backward keeps w AND the dW accumulator resident, so it falls back
earlier, around h~512-700) the
fused path falls back to `lax.scan` — at that size the per-step matmul
is MXU-bound anyway, which is exactly when the fusion win vanishes.

Gate orders match the layer/bias layouts in layers/recurrent.py:
LSTM [i, f, g, o] with peepholes (wci, wcf, wco); GRU [u, r | c].
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_VMEM_BUDGET = 10 * 1024 * 1024  # soft planning budget (VMEM is ~16MB)
# the backward keeps BOTH w and the resident dW accumulator in VMEM
# (h=512: 2 x 4MB) — give it a larger share so h=512 training stays on
# the kernel path; Mosaic still owns the hard limit
_VMEM_BUDGET_BWD = 13 * 1024 * 1024


def _round8(n: int) -> int:
    return -(-n // 8) * 8


def _plan(b: int, t: int, h: int, tok_bytes: int, fixed_bytes: int,
          budget: int = None):
    """Choose (bb, tb, Bp, Tp): batch block, time block, padded dims.

    Constraints (Mosaic): bb and tb multiples of 8 (or the full padded
    dim). Preference: the largest bb (per-step recurrent matmul is
    [bb, h] @ [h, 4h] — more rows, better MXU utilization), then the
    largest tb (fewer grid steps). Returns None if even the minimal
    block overflows the budget (weights too big for VMEM -> caller
    falls back to the scan path)."""
    if budget is None:  # resolved at call time (tests patch the global)
        budget = _VMEM_BUDGET
    bp = _round8(b)
    t8 = _round8(t)
    tb_options = [t8] + [x for x in (256, 128, 64, 32, 16, 8) if x < t8]
    bb_options = [bb for bb in range(bp, 7, -8) if bp % bb == 0]
    for bb in bb_options:
        for tb in tb_options:
            if fixed_bytes + bb * tb * tok_bytes <= budget:
                tp = -(-t // tb) * tb
                return bb, tb, bp, tp
    return None


def _pad_bt(x, bp, tp):
    """Zero-pad [B, T, ...] to [Bp, Tp, ...]."""
    pads = [(0, bp - x.shape[0]), (0, tp - x.shape[1])]
    pads += [(0, 0)] * (x.ndim - 2)
    if bp == x.shape[0] and tp == x.shape[1]:
        return x
    return jnp.pad(x, pads)


# ---------------------------------------------------------------- LSTM

def lstm_ref(x, w, gb, wci, wcf, wco, lens):
    """Reference scan. x: [B,T,4h] pre-projected input; w: [h,4h];
    gb: [4h]; peepholes [h] each; lens: [B] int32. Returns y [B,T,h]."""
    h = w.shape[0]
    t_max = x.shape[1]
    mask = (
        jnp.arange(t_max, dtype=jnp.int32)[None, :] < lens[:, None]
    ).astype(x.dtype)

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        g = x_t + jnp.dot(h_prev, w) + gb
        gi, gf, gg, go = jnp.split(g, 4, axis=-1)
        i = jax.nn.sigmoid(gi + wci * c_prev)
        f = jax.nn.sigmoid(gf + wcf * c_prev)
        cand = jnp.tanh(gg)
        c = f * c_prev + i * cand
        o = jax.nn.sigmoid(go + wco * c)
        out = o * jnp.tanh(c)
        m = m_t[:, None]
        h_new = m * out + (1 - m) * h_prev
        c_new = m * c + (1 - m) * c_prev
        return (h_new, c_new), out * m

    bsz = x.shape[0]
    z = jnp.zeros((bsz, h), x.dtype)
    _, ys = lax.scan(
        step, (z, z), (x.swapaxes(0, 1), mask.swapaxes(0, 1))
    )
    return ys.swapaxes(0, 1)


def _make_lstm_fwd_kernel(emit_c: bool):
    """One (batch block, time block) step. Carries h/c in VMEM scratch
    across the time sweep; emits the masked output y and (training
    only) the carried cell sequence c for the backward kernel —
    inference skips the c store to halve output HBM traffic."""

    def kernel(x_ref, w_ref, b_ref, lens_ref, y_ref, *rest):
        if emit_c:
            c_ref, h_scr, c_scr = rest
        else:
            h_scr, c_scr = rest
        bb, tb, h4 = x_ref.shape
        h = h4 // 4
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            h_scr[:] = jnp.zeros_like(h_scr)
            c_scr[:] = jnp.zeros_like(c_scr)

        gb = b_ref[0, : 4 * h]
        wci = b_ref[0, 4 * h : 5 * h]
        wcf = b_ref[0, 5 * h : 6 * h]
        wco = b_ref[0, 6 * h : 7 * h]
        lens = lens_ref[:, 0]
        t0 = j * tb

        def body(tt, _):
            x_t = x_ref[:, tt, :]
            h_prev = h_scr[:]
            c_prev = c_scr[:]
            g = (
                x_t
                + jnp.dot(
                    h_prev, w_ref[:], preferred_element_type=jnp.float32
                )
                + gb
            )
            i = jax.nn.sigmoid(g[:, :h] + wci * c_prev)
            f = jax.nn.sigmoid(g[:, h : 2 * h] + wcf * c_prev)
            cand = jnp.tanh(g[:, 2 * h : 3 * h])
            c = f * c_prev + i * cand
            o = jax.nn.sigmoid(g[:, 3 * h :] + wco * c)
            out = o * jnp.tanh(c)
            m = (t0 + tt < lens).astype(jnp.float32)[:, None]
            h_scr[:] = m * out + (1 - m) * h_prev
            c_scr[:] = m * c + (1 - m) * c_prev
            y_ref[:, tt, :] = (out * m).astype(y_ref.dtype)
            if emit_c:
                c_ref[:, tt, :] = c_scr[:].astype(c_ref.dtype)
            return 0

        lax.fori_loop(0, tb, body, 0)

    return kernel


_lstm_fwd_kernel = _make_lstm_fwd_kernel(emit_c=True)
_lstm_fwd_kernel_noc = _make_lstm_fwd_kernel(emit_c=False)


def _lstm_bwd_kernel(
    x_ref, w_ref, b_ref, lens_ref, y_ref, yp_ref, c_ref, cp_ref, dy_ref,
    dx_ref, dw_ref, db_ref, dh_scr, dc_scr, dg_scr, hp_scr, db_scr,
):
    """Reverse-time LSTM backward. Grid blocks arrive back-to-front in
    time (see the reversed index maps); within a block, steps run in
    reverse. Gates are recomputed from x and the saved y/c sequences.
    yp/cp are the PREVIOUS time block of y/c (their last row supplies
    h_{t-1}/c_{t-1} at the block boundary). dW/db accumulate into
    resident output blocks across the whole grid."""
    bb, tb, h4 = x_ref.shape
    h = h4 // 4
    i_blk = pl.program_id(0)
    j = pl.program_id(1)
    nt = pl.num_programs(1)
    # reversed sweep: this grid step handles time block k = nt-1-j
    k = nt - 1 - j
    t0 = k * tb

    @pl.when(j == 0)
    def _init_carry():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = jnp.zeros_like(dc_scr)

    @pl.when((i_blk == 0) & (j == 0))
    def _init_outs():
        dw_ref[:] = jnp.zeros_like(dw_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    db_scr[:] = jnp.zeros_like(db_scr)

    gb = b_ref[0, : 4 * h]
    wci = b_ref[0, 4 * h : 5 * h]
    wcf = b_ref[0, 5 * h : 6 * h]
    wco = b_ref[0, 6 * h : 7 * h]
    lens = lens_ref[:, 0]
    w = w_ref[:]

    def body(s, _):
        tt = tb - 1 - s
        t = t0 + tt
        m = (t < lens).astype(jnp.float32)[:, None]
        first = t == 0
        # h_{t-1}, c_{t-1}: previous row of this block, or the last row
        # of the previous time block at the boundary, or zeros at t=0
        tt_prev = jnp.maximum(tt - 1, 0)
        in_blk = (tt > 0).astype(jnp.float32)
        h_prev_blk = y_ref[:, tt_prev, :]
        c_prev_blk = c_ref[:, tt_prev, :]
        h_prev_edge = yp_ref[:, tb - 1, :]
        c_prev_edge = cp_ref[:, tb - 1, :]
        zero = jnp.float32(0.0)
        live = jnp.where(first, zero, 1.0)
        h_prev = live * (
            in_blk * h_prev_blk + (1 - in_blk) * h_prev_edge
        )
        c_prev = live * (
            in_blk * c_prev_blk + (1 - in_blk) * c_prev_edge
        )
        # recompute the forward cell (valid wherever m = 1)
        g = (
            x_ref[:, tt, :]
            + jnp.dot(h_prev, w, preferred_element_type=jnp.float32)
            + gb
        )
        ig = jax.nn.sigmoid(g[:, :h] + wci * c_prev)
        fg = jax.nn.sigmoid(g[:, h : 2 * h] + wcf * c_prev)
        cand = jnp.tanh(g[:, 2 * h : 3 * h])
        c_t = fg * c_prev + ig * cand
        og = jax.nn.sigmoid(g[:, 3 * h :] + wco * c_t)
        tanh_c = jnp.tanh(c_t)
        # backward through the step
        dh_in = dh_scr[:]
        dc_in = dc_scr[:]
        dout = m * (dh_in + dy_ref[:, tt, :])
        dg_o = dout * tanh_c * og * (1 - og)
        dc_tot = m * dc_in + dout * og * (1 - tanh_c * tanh_c) + dg_o * wco
        dg_i = dc_tot * cand * ig * (1 - ig)
        dg_f = dc_tot * c_prev * fg * (1 - fg)
        dg_g = dc_tot * ig * (1 - cand * cand)
        dg = jnp.concatenate([dg_i, dg_f, dg_g, dg_o], axis=-1)
        dx_ref[:, tt, :] = dg.astype(dx_ref.dtype)
        dg_scr[:, tt, :] = dg
        hp_scr[:, tt, :] = h_prev
        # carries for step t-1
        dh_scr[:] = (1 - m) * dh_in + lax.dot_general(
            dg, w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dc_scr[:] = dc_tot * fg + dg_i * wci + dg_f * wcf + (1 - m) * dc_in
        # bias + peephole partials for this step
        db_scr[0, : 4 * h] += jnp.sum(dg, axis=0)
        db_scr[0, 4 * h : 5 * h] += jnp.sum(dg_i * c_prev, axis=0)
        db_scr[0, 5 * h : 6 * h] += jnp.sum(dg_f * c_prev, axis=0)
        db_scr[0, 6 * h : 7 * h] += jnp.sum(dg_o * c_t, axis=0)
        return 0

    lax.fori_loop(0, tb, body, 0)
    # block-level reductions into the resident outputs
    hp2 = hp_scr[:].reshape(bb * tb, h)
    dg2 = dg_scr[:].reshape(bb * tb, h4)
    dw_ref[:] += lax.dot_general(
        hp2, dg2, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    db_ref[:] += db_scr[:]


def _lstm_plan(bsz, t_max, h):
    # fwd tokens: x 4h in + (y, c) 2h out, double-buffered
    tok = 2 * 4 * (4 * h + 2 * h)
    fixed = 4 * (h * 4 * h + 7 * h) + 8 * 8 * h  # w + b7 + h/c scratch
    return _plan(bsz, t_max, h, tok, fixed)


def _lstm_bwd_plan(bsz, t_max, h):
    # in: x 4h, y h, yp h, c h, cp h, dy h; out: dx 4h -> 13h tokens,
    # double-buffered; plus dg/hp scratch 5h tokens (single)
    tok = 2 * 4 * 13 * h + 4 * 5 * h
    fixed = 4 * (2 * h * 4 * h + 2 * 7 * h) + 8 * 8 * h
    return _plan(bsz, t_max, h, tok, fixed, budget=_VMEM_BUDGET_BWD)


def _lstm_fwd_pallas(x, w, b7, lens, *, interpret, want_c):
    """Returns (y, c_seq) — c_seq None unless `want_c` (training path
    saving the cell sequence for the backward kernel) — or None if
    infeasible."""
    orig = x.dtype
    bsz, t_max, h4 = x.shape
    h = h4 // 4
    plan = _lstm_plan(bsz, t_max, h)
    if plan is None:
        return None
    bb, tb, bp, tp = plan
    if orig == jnp.bfloat16:
        x, w, b7 = (a.astype(jnp.float32) for a in (x, w, b7))
    xp = _pad_bt(x, bp, tp)
    lensp = jnp.pad(lens, ((0, bp - bsz), (0, 0)))
    grid = (bp // bb, tp // tb)
    blk = pl.BlockSpec((bb, tb, h), lambda i, j: (i, j, 0))
    out = pl.pallas_call(
        _lstm_fwd_kernel if want_c else _lstm_fwd_kernel_noc,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, tb, h4), lambda i, j: (i, j, 0)),
            pl.BlockSpec((h, h4), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 7 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0)),
        ],
        out_specs=[blk, blk] if want_c else blk,
        out_shape=(
            [
                jax.ShapeDtypeStruct((bp, tp, h), jnp.float32),
                jax.ShapeDtypeStruct((bp, tp, h), jnp.float32),
            ]
            if want_c
            else jax.ShapeDtypeStruct((bp, tp, h), jnp.float32)
        ),
        scratch_shapes=[
            pltpu.VMEM((bb, h), jnp.float32),
            pltpu.VMEM((bb, h), jnp.float32),
        ],
        interpret=interpret,
    )(xp, w, b7, lensp)
    if want_c:
        y, c = out
        return y[:bsz, :t_max].astype(orig), c[:bsz, :t_max]
    return out[:bsz, :t_max].astype(orig), None


def _lstm_bwd_pallas(x, w, b7, lens, y, c_seq, dy, *, interpret):
    """Returns (dx, dw, db7) or None if infeasible."""
    orig = x.dtype
    bsz, t_max, h4 = x.shape
    h = h4 // 4
    plan = _lstm_bwd_plan(bsz, t_max, h)
    if plan is None:
        return None
    bb, tb, bp, tp = plan
    # measured on v5e: with bb < 32 the per-step [bb,h]@[h,4h] matmul
    # under-fills the MXU and the kernel loses to the scan-recompute
    # backward (h=512/bb=16: 19.9ms vs 13.8ms scan; h=256/bb>=32 the
    # kernel wins 1.56x) — fall back unless the batch block is wide.
    # interpret mode (CPU tests) keeps the kernel path regardless.
    if bb < 32 and not interpret:
        return None
    f32 = jnp.float32
    # everything in f32 inside the kernel — including w/b7, matching
    # the forward's bf16-AMP upcast
    w = w.astype(f32)
    b7 = b7.astype(f32)
    xp = _pad_bt(x.astype(f32), bp, tp)
    yp_ = _pad_bt(y.astype(f32), bp, tp)
    cp_ = _pad_bt(c_seq.astype(f32), bp, tp)
    dyp = _pad_bt(dy.astype(f32), bp, tp)
    lensp = jnp.pad(lens, ((0, bp - bsz), (0, 0)))
    nt = tp // tb
    rev = lambda i, j: (i, nt - 1 - j, 0)  # noqa: E731
    # previous time block (one earlier in real time); clamped at 0 —
    # its stale values are masked inside the kernel at t == 0
    prev = lambda i, j: (i, jnp.maximum(nt - 2 - j, 0), 0)  # noqa: E731
    grid = (bp // bb, nt)
    dx, dw, db7 = pl.pallas_call(
        _lstm_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, tb, h4), rev),
            pl.BlockSpec((h, h4), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 7 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bb, tb, h), rev),
            pl.BlockSpec((bb, tb, h), prev),
            pl.BlockSpec((bb, tb, h), rev),
            pl.BlockSpec((bb, tb, h), prev),
            pl.BlockSpec((bb, tb, h), rev),
        ],
        out_specs=[
            pl.BlockSpec((bb, tb, h4), rev),
            pl.BlockSpec((h, h4), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 7 * h), lambda i, j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, tp, h4), jnp.float32),
            jax.ShapeDtypeStruct((h, h4), jnp.float32),
            jax.ShapeDtypeStruct((1, 7 * h), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bb, h), f32),
            pltpu.VMEM((bb, h), f32),
            pltpu.VMEM((bb, tb, h4), f32),
            pltpu.VMEM((bb, tb, h), f32),
            pltpu.VMEM((1, 7 * h), f32),
        ],
        interpret=interpret,
    )(xp, w, b7, lensp, yp_, yp_, cp_, cp_, dyp)
    return dx[:bsz, :t_max].astype(orig), dw, db7


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def lstm_fused(x, w, gb, wci, wcf, wco, lens, interpret=False):
    b7 = jnp.concatenate([gb, wci, wcf, wco])[None, :]
    out = _lstm_fwd_pallas(
        x, w, b7, lens[:, None].astype(jnp.int32), interpret=interpret,
        want_c=False,
    )
    if out is None:  # weights too large for VMEM: scan is MXU-bound
        return lstm_ref(x, w, gb, wci, wcf, wco, lens)
    return out[0]


def _lstm_fused_fwd(x, w, gb, wci, wcf, wco, lens, interpret):
    b7 = jnp.concatenate([gb, wci, wcf, wco])[None, :]
    out = _lstm_fwd_pallas(
        x, w, b7, lens[:, None].astype(jnp.int32), interpret=interpret,
        want_c=True,
    )
    if out is None:
        y = lstm_ref(x, w, gb, wci, wcf, wco, lens)
        return y, (x, w, gb, wci, wcf, wco, lens, None, None)
    y, c_seq = out
    return y, (x, w, gb, wci, wcf, wco, lens, y, c_seq)


def _lstm_fused_bwd(interpret, res, dy):
    x, w, gb, wci, wcf, wco, lens, y, c_seq = res
    h = w.shape[0]
    if y is not None:
        b7 = jnp.concatenate([gb, wci, wcf, wco])[None, :]
        out = _lstm_bwd_pallas(
            x, w, b7, lens[:, None].astype(jnp.int32), y, c_seq, dy,
            interpret=interpret,
        )
        if out is not None:
            dx, dw, db7 = out
            dgb = db7[0, : 4 * h].astype(gb.dtype)
            dwci = db7[0, 4 * h : 5 * h].astype(wci.dtype)
            dwcf = db7[0, 5 * h : 6 * h].astype(wcf.dtype)
            dwco = db7[0, 6 * h : 7 * h].astype(wco.dtype)
            return (dx, dw.astype(w.dtype), dgb, dwci, dwcf, dwco, None)
    _, vjp = jax.vjp(lambda *a: lstm_ref(*a, lens), x, w, gb, wci, wcf, wco)
    return (*vjp(dy), None)


lstm_fused.defvjp(_lstm_fused_fwd, _lstm_fused_bwd)


# ---------------------------------------------------------------- GRU

def gru_ref(x, w_g, w_c, b, lens):
    """Reference scan. x: [B,T,3h] as [u,r,c]; w_g: [h,2h]; w_c: [h,h];
    b: [3h]; lens [B]. Returns y [B,T,h]."""
    h = w_c.shape[0]
    t_max = x.shape[1]
    mask = (
        jnp.arange(t_max, dtype=jnp.int32)[None, :] < lens[:, None]
    ).astype(x.dtype)

    def step(h_prev, inp):
        x_t, m_t = inp
        xu, xr, xc = jnp.split(x_t + b, 3, axis=-1)
        gur = jnp.dot(h_prev, w_g)
        u = jax.nn.sigmoid(xu + gur[:, :h])
        r = jax.nn.sigmoid(xr + gur[:, h:])
        c = jnp.tanh(xc + jnp.dot(r * h_prev, w_c))
        out = u * h_prev + (1 - u) * c
        m = m_t[:, None]
        h_new = m * out + (1 - m) * h_prev
        return h_new, out * m

    bsz = x.shape[0]
    z = jnp.zeros((bsz, h), x.dtype)
    _, ys = lax.scan(step, z, (x.swapaxes(0, 1), mask.swapaxes(0, 1)))
    return ys.swapaxes(0, 1)


def _gru_kernel(x_ref, wg_ref, wc_ref, b_ref, lens_ref, y_ref, h_scr):
    bb, tb, h3 = x_ref.shape
    h = h3 // 3
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        h_scr[:] = jnp.zeros_like(h_scr)

    b = b_ref[0, :]
    lens = lens_ref[:, 0]
    t0 = j * tb

    def body(tt, _):
        x_t = x_ref[:, tt, :] + b
        h_prev = h_scr[:]
        xu = x_t[:, :h]
        xr = x_t[:, h : 2 * h]
        xc = x_t[:, 2 * h :]
        gur = jnp.dot(
            h_prev, wg_ref[:], preferred_element_type=jnp.float32
        )
        u = jax.nn.sigmoid(xu + gur[:, :h])
        r = jax.nn.sigmoid(xr + gur[:, h:])
        c = jnp.tanh(
            xc
            + jnp.dot(
                r * h_prev, wc_ref[:], preferred_element_type=jnp.float32
            )
        )
        out = u * h_prev + (1 - u) * c
        m = (t0 + tt < lens).astype(jnp.float32)[:, None]
        h_scr[:] = m * out + (1 - m) * h_prev
        y_ref[:, tt, :] = (out * m).astype(y_ref.dtype)
        return 0

    lax.fori_loop(0, tb, body, 0)


def _gru_plan(bsz, t_max, h):
    tok = 2 * 4 * (3 * h + h)  # x in + y out, double-buffered
    fixed = 4 * (h * 2 * h + h * h + 3 * h) + 4 * 8 * h
    return _plan(bsz, t_max, h, tok, fixed)


def _gru_bwd_kernel(
    x_ref, wg_ref, wc_ref, b_ref, lens_ref, y_ref, yp_ref, dy_ref,
    dx_ref, dwg_ref, dwc_ref, db_ref,
    dh_scr, dgg_scr, dgc_scr, hp_scr, rh_scr, db_scr,
):
    """Reverse-time GRU backward (mirrors _lstm_bwd_kernel): gates
    recomputed from x and the saved output sequence (h_{t-1} = y[t-1]
    wherever the mask is live, previous block's last row at the
    boundary), dW_g/dW_c/db accumulated in resident output blocks."""
    bb, tb, h3 = x_ref.shape
    h = h3 // 3
    i_blk = pl.program_id(0)
    j = pl.program_id(1)
    nt = pl.num_programs(1)
    k = nt - 1 - j
    t0 = k * tb

    @pl.when(j == 0)
    def _init_carry():
        dh_scr[:] = jnp.zeros_like(dh_scr)

    @pl.when((i_blk == 0) & (j == 0))
    def _init_outs():
        dwg_ref[:] = jnp.zeros_like(dwg_ref)
        dwc_ref[:] = jnp.zeros_like(dwc_ref)
        db_ref[:] = jnp.zeros_like(db_ref)

    db_scr[:] = jnp.zeros_like(db_scr)
    b = b_ref[0, :]
    lens = lens_ref[:, 0]
    w_g = wg_ref[:]
    w_c = wc_ref[:]

    def body(s, _):
        tt = tb - 1 - s
        t = t0 + tt
        m = (t < lens).astype(jnp.float32)[:, None]
        tt_prev = jnp.maximum(tt - 1, 0)
        in_blk = (tt > 0).astype(jnp.float32)
        live = jnp.where(t == 0, 0.0, 1.0)
        h_prev = live * (
            in_blk * y_ref[:, tt_prev, :]
            + (1 - in_blk) * yp_ref[:, tb - 1, :]
        )
        # recompute the forward gates
        xb = x_ref[:, tt, :] + b
        gur = jnp.dot(h_prev, w_g, preferred_element_type=jnp.float32)
        u = jax.nn.sigmoid(xb[:, :h] + gur[:, :h])
        r = jax.nn.sigmoid(xb[:, h : 2 * h] + gur[:, h:])
        rh = r * h_prev
        c = jnp.tanh(
            xb[:, 2 * h :]
            + jnp.dot(rh, w_c, preferred_element_type=jnp.float32)
        )
        # backward through the step
        dh_in = dh_scr[:]
        dout = m * (dh_in + dy_ref[:, tt, :])
        du = dout * (h_prev - c)
        dc = dout * (1 - u)
        dg_c = dc * (1 - c * c)
        drh = lax.dot_general(
            dg_c, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dr = drh * h_prev
        dg_u = du * u * (1 - u)
        dg_r = dr * r * (1 - r)
        dg_ur = jnp.concatenate([dg_u, dg_r], axis=-1)
        dh_prev = (
            (1 - m) * dh_in
            + drh * r
            + dout * u
            + lax.dot_general(
                dg_ur, w_g, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        )
        dx = jnp.concatenate([dg_ur, dg_c], axis=-1)
        dx_ref[:, tt, :] = dx.astype(dx_ref.dtype)
        dgg_scr[:, tt, :] = dg_ur
        dgc_scr[:, tt, :] = dg_c
        hp_scr[:, tt, :] = h_prev
        rh_scr[:, tt, :] = rh
        dh_scr[:] = dh_prev
        db_scr[0, :] += jnp.sum(dx, axis=0)
        return 0

    lax.fori_loop(0, tb, body, 0)
    hp2 = hp_scr[:].reshape(bb * tb, h)
    rh2 = rh_scr[:].reshape(bb * tb, h)
    dwg_ref[:] += lax.dot_general(
        hp2, dgg_scr[:].reshape(bb * tb, 2 * h),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    dwc_ref[:] += lax.dot_general(
        rh2, dgc_scr[:].reshape(bb * tb, h),
        (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
    )
    db_ref[:] += db_scr[:]


def _gru_bwd_plan(bsz, t_max, h):
    # in: x 3h, y h, yp h, dy h; out dx 3h -> 9h tokens double-buffered;
    # scratch dgg 2h + dgc h + hp h + rh h = 5h tokens (single)
    tok = 2 * 4 * 9 * h + 4 * 5 * h
    fixed = 4 * (2 * (h * 2 * h + h * h) + 2 * 3 * h) + 4 * 8 * h
    return _plan(bsz, t_max, h, tok, fixed, budget=_VMEM_BUDGET_BWD)


def _gru_bwd_pallas(x, w_g, w_c, b, lens, y, dy, *, interpret):
    orig = x.dtype
    bsz, t_max, h3 = x.shape
    h = h3 // 3
    plan = _gru_bwd_plan(bsz, t_max, h)
    if plan is None:
        return None
    bb, tb, bp, tp = plan
    # same MXU-fill gate as the LSTM backward (measured on v5e)
    if bb < 32 and not interpret:
        return None
    f32 = jnp.float32
    wg_dt, wc_dt = w_g.dtype, w_c.dtype  # cotangents match the primals
    w_g = w_g.astype(f32)
    w_c = w_c.astype(f32)
    b2 = b.astype(f32)[None, :]
    xp = _pad_bt(x.astype(f32), bp, tp)
    yp_ = _pad_bt(y.astype(f32), bp, tp)
    dyp = _pad_bt(dy.astype(f32), bp, tp)
    lensp = jnp.pad(lens, ((0, bp - bsz), (0, 0)))
    nt = tp // tb
    rev = lambda i, j: (i, nt - 1 - j, 0)  # noqa: E731
    prev = lambda i, j: (i, jnp.maximum(nt - 2 - j, 0), 0)  # noqa: E731
    grid = (bp // bb, nt)
    dx, dwg, dwc, db3 = pl.pallas_call(
        _gru_bwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, tb, h3), rev),
            pl.BlockSpec((h, 2 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((h, h), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 3 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0)),
            pl.BlockSpec((bb, tb, h), rev),
            pl.BlockSpec((bb, tb, h), prev),
            pl.BlockSpec((bb, tb, h), rev),
        ],
        out_specs=[
            pl.BlockSpec((bb, tb, h3), rev),
            pl.BlockSpec((h, 2 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((h, h), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 3 * h), lambda i, j: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, tp, h3), f32),
            jax.ShapeDtypeStruct((h, 2 * h), f32),
            jax.ShapeDtypeStruct((h, h), f32),
            jax.ShapeDtypeStruct((1, 3 * h), f32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bb, h), f32),
            pltpu.VMEM((bb, tb, 2 * h), f32),
            pltpu.VMEM((bb, tb, h), f32),
            pltpu.VMEM((bb, tb, h), f32),
            pltpu.VMEM((bb, tb, h), f32),
            pltpu.VMEM((1, 3 * h), f32),
        ],
        interpret=interpret,
    )(xp, w_g, w_c, b2, lensp, yp_, yp_, dyp)
    return (
        dx[:bsz, :t_max].astype(orig),
        dwg.astype(wg_dt),
        dwc.astype(wc_dt),
        db3[0],
    )


def _gru_fwd_kernel(x, w_g, w_c, b, lens, *, interpret):
    orig = x.dtype
    bsz, t_max, h3 = x.shape
    h = h3 // 3
    plan = _gru_plan(bsz, t_max, h)
    if plan is None:
        return None
    bb, tb, bp, tp = plan
    if orig == jnp.bfloat16:
        x, w_g, w_c, b = (
            a.astype(jnp.float32) for a in (x, w_g, w_c, b)
        )
    xp = _pad_bt(x, bp, tp)
    lensp = jnp.pad(lens, ((0, bp - bsz), (0, 0)))
    grid = (bp // bb, tp // tb)
    y = pl.pallas_call(
        _gru_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bb, tb, h3), lambda i, j: (i, j, 0)),
            pl.BlockSpec((h, 2 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((h, h), lambda i, j: (0, 0)),
            pl.BlockSpec((1, 3 * h), lambda i, j: (0, 0)),
            pl.BlockSpec((bb, 1), lambda i, j: (i, 0)),
        ],
        out_specs=pl.BlockSpec((bb, tb, h), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bp, tp, h), jnp.float32),
        scratch_shapes=[pltpu.VMEM((bb, h), jnp.float32)],
        interpret=interpret,
    )(xp, w_g, w_c, b[None, :], lensp)
    return y[:bsz, :t_max].astype(orig)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def gru_fused(x, w_g, w_c, b, lens, interpret=False):
    y = _gru_fwd_kernel(
        x, w_g, w_c, b, lens[:, None].astype(jnp.int32), interpret=interpret
    )
    if y is None:  # weights too large for VMEM
        return gru_ref(x, w_g, w_c, b, lens)
    return y


def _gru_fused_fwd(x, w_g, w_c, b, lens, interpret):
    y = gru_fused(x, w_g, w_c, b, lens, interpret)
    plan = _gru_plan(x.shape[0], x.shape[1], w_c.shape[0])
    # y came from the kernel only if the fwd plan was feasible
    return y, (x, w_g, w_c, b, lens, y if plan is not None else None)


def _gru_fused_bwd(interpret, res, dy):
    x, w_g, w_c, b, lens, y = res
    if y is not None:
        out = _gru_bwd_pallas(
            x, w_g, w_c, b, lens[:, None].astype(jnp.int32), y, dy,
            interpret=interpret,
        )
        if out is not None:
            dx, dwg, dwc, db3 = out
            return (dx, dwg, dwc, db3.astype(b.dtype), None)
    _, vjp = jax.vjp(lambda *a: gru_ref(*a, lens), x, w_g, w_c, b)
    return (*vjp(dy), None)


gru_fused.defvjp(_gru_fused_fwd, _gru_fused_bwd)


def use_fused_default() -> bool:
    """Auto policy: fused kernels on real TPU, scan elsewhere."""
    try:
        plat = jax.devices()[0].platform
    except Exception:
        return False
    return plat not in ("cpu", "gpu")
