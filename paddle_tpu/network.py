"""Network: config -> executable pure functions.

The analogue of the reference's NeuralNetwork gradient machine
(gserver/gradientmachines/NeuralNetwork.cpp:68,235,285): build layers from
ModelConf, walk them in topological order for forward. There is no
hand-written backward walk — `loss_fn` is differentiated with jax.grad and
the whole step jit-compiles to one XLA program (the TPU-idiomatic
equivalent of forward+backward+update fusion).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core import flags as _flags
from paddle_tpu.core.arg import Arg
from paddle_tpu.core.config import ModelConf, ParameterConf
from paddle_tpu.layers.base import Ctx, create_layer, init_parameter


def _cast_arg(a: Arg, dtype) -> Arg:
    """Cast an Arg's float value to `dtype` (ids/lens untouched)."""
    if a.value is None or a.value.dtype not in (
        jnp.float32,
        jnp.bfloat16,
    ):
        return a
    if a.value.dtype == dtype:
        return a
    return a.with_value(a.value.astype(dtype))

# ensure all layer types are registered
import paddle_tpu.layers  # noqa: F401


class Network:
    def __init__(self, conf: ModelConf):
        self.conf = conf
        self.layers = {}
        self.specs = {}
        self._extra_producer: dict[str, str] = {}
        self.param_confs: dict[str, ParameterConf] = {}  # global name -> conf
        self.layer_params: dict[str, dict] = {}  # layer -> {slot: global name}
        self._stateful: dict[str, object] = {}
        order = []
        for lc in conf.layers:
            layer = create_layer(lc, conf)
            self.layers[lc.name] = layer
            for n in lc.input_names():
                if n not in self.specs:
                    raise KeyError(
                        f"layer {lc.name!r} input {n!r} is not defined above it "
                        f"(layers must be in topological order)"
                    )
            in_specs = [self.specs[n] for n in lc.input_names()]
            spec, pcs = layer.build(in_specs)
            self.specs[lc.name] = spec
            slot_map = {}
            for slot, pc in pcs.items():
                if pc is None:
                    continue
                if pc.name in self.param_confs:
                    # shared parameter: dims must agree
                    prev = self.param_confs[pc.name]
                    assert tuple(prev.dims) == tuple(pc.dims), (
                        f"shared param {pc.name} dim mismatch"
                    )
                else:
                    self.param_confs[pc.name] = pc
                slot_map[slot] = pc.name
            self.layer_params[lc.name] = slot_map
            if hasattr(layer, "init_state"):
                self._stateful[lc.name] = layer
            if hasattr(layer, "extra_output_specs"):
                for xname, xspec in layer.extra_output_specs().items():
                    if xname in self.specs:
                        raise KeyError(
                            f"extra output {xname!r} of layer {lc.name!r} "
                            f"collides with an existing layer name"
                        )
                    self.specs[xname] = xspec
                    self._extra_producer[xname] = lc.name
            order.append(lc.name)
        self.order = order
        self.output_names = list(conf.output_layer_names) or (
            [order[-1]] if order else []
        )
        self.cost_names = [
            n for n in order if getattr(self.layers[n], "is_cost", False)
        ]
        # Declared outputs built FROM cost layers by layer arithmetic
        # (e.g. the VAE's `outputs(reconstruct_error(...) + KL_loss(...))`
        # where the KL term is scaled 0.5× via slope_intercept) are the
        # training objective themselves: the reference's cost is the sum
        # of the OUTPUT arguments (TrainerInternal.cpp:135 Argument::sum),
        # so such an output replaces its cost-layer ancestors in the
        # loss — counting the unscaled ancestors would mis-weight it.
        derived = []
        absorbed = set()
        for out_name in self.output_names:
            out_name = self._extra_producer.get(out_name, out_name)
            if getattr(self.layers.get(out_name), "is_cost", False):
                continue
            anc = set()
            frontier = [out_name]
            while frontier:
                n = frontier.pop()
                n = self._extra_producer.get(n, n)
                if n in anc:
                    continue
                anc.add(n)
                frontier.extend(self.conf.layer(n).input_names())
            cost_anc = [c for c in self.cost_names if c in anc]
            if cost_anc:
                derived.append(out_name)
                absorbed.update(cost_anc)
        if derived:
            self.cost_names = [
                n for n in self.cost_names if n not in absorbed
            ] + derived
        self.input_names = list(conf.input_layer_names) or [
            lc.name for lc in conf.layers if lc.type == "data"
        ]

    # ---- parameters & state ----
    def init_params(self, key: jax.Array, dtype=jnp.float32) -> dict:
        params = {}
        names = sorted(self.param_confs)
        keys = jax.random.split(key, max(len(names), 1))
        for k, name in zip(keys, names):
            params[name] = init_parameter(k, self.param_confs[name], dtype)
        return params

    def init_state(self) -> dict:
        return {name: layer.init_state() for name, layer in self._stateful.items()}

    def _layer_param_view(self, name: str, params: dict) -> dict:
        return {slot: params[g] for slot, g in self.layer_params[name].items()}


    # ---- execution ----
    def forward(
        self,
        params: dict,
        feed: dict,
        *,
        state: Optional[dict] = None,
        train: bool = False,
        rng: Optional[jax.Array] = None,
        outputs: Optional[list] = None,
    ):
        """Run all layers. Returns (outputs: {layer_name: Arg}, new_state).

        `feed` maps data-layer names to Arg. Mirrors NeuralNetwork::forward
        (NeuralNetwork.cpp:235) with passType train/test folded into
        Ctx.train."""
        if state is None:
            state = self.init_state()
        # Mixed precision (flags "matmul_precision" = "bfloat16"): master
        # params stay float32; per consuming edge below, compute-layer
        # operands are cast to bfloat16 (halved HBM traffic, single-pass
        # MXU) while cost-layer operands stay float32 so targets and
        # loss math keep full precision. The casts are inside the
        # autodiff region, so grads flow back to the float32 masters
        # (classic master-weight AMP).
        amp = _flags.get_flag("matmul_precision") in ("bfloat16", "bf16")
        ctx = Ctx(train=train, rng=rng, state=state)
        outs: dict[str, Arg] = {}
        if outputs is not None:
            # run only the ancestor closure of the requested outputs
            # (inference prunes cost layers and their label inputs)
            run = set()
            frontier = list(outputs)
            while frontier:
                n = frontier.pop()
                n = self._extra_producer.get(n, n)  # extra out -> its group
                if n in run:
                    continue
                run.add(n)
                frontier.extend(self.conf.layer(n).input_names())
            order = [n for n in self.order if n in run]
        else:
            order = self.order
        needed = {
            n
            for ln in order
            for n in self.conf.layer(ln).input_names()
        }
        for name in order:
            lc = self.conf.layer(name)
            if lc.type == "data":
                if name in feed:
                    outs[name] = feed[name]
                elif name in needed:
                    raise KeyError(
                        f"data layer {name!r} is consumed by the network but "
                        f"missing from feed (fed: {sorted(feed)})"
                    )
                continue
            inputs = [outs[n] for n in lc.input_names()]
            layer_params = self._layer_param_view(name, params)
            layer = self.layers[name]
            if amp:
                # per consuming EDGE: cost layers see float32 (targets
                # straight from the feed keep full precision even if the
                # same data layer also feeds compute layers), everything
                # else computes in bfloat16
                to = (
                    jnp.float32
                    if getattr(layer, "is_cost", False)
                    else jnp.bfloat16
                )
                inputs = [_cast_arg(a, to) for a in inputs]
                layer_params = {
                    k: (
                        v.astype(to)
                        if v.dtype in (jnp.float32, jnp.bfloat16)
                        else v
                    )
                    for k, v in layer_params.items()
                }
            try:
                with jax.named_scope(f"{lc.type}:{name}"):
                    outs[name] = layer.forward(layer_params, inputs, ctx)
            except Exception as e:
                # the layer-stack-on-crash context of the reference's
                # CustomStackTrace (utils/CustomStackTrace.h:51, pushed
                # per layer in NeuralNetwork.cpp:249-251)
                e.add_note(
                    f"  while running layer {name!r} "
                    f"(type={lc.type!r}, inputs={lc.input_names()})"
                )
                raise
            spec = lc.attrs.get("out_sharding")
            if spec is not None:
                # Per-layer placement hint — the GSPMD replacement for the
                # reference's ParallelNeuralNetwork per-layer `device` attr
                # (gserver/gradientmachines/ParallelNeuralNetwork.h:34).
                from jax.sharding import PartitionSpec
                from paddle_tpu.core.mesh import get_mesh
                from paddle_tpu.parallel.sharding import constrain

                outs[name] = constrain(
                    outs[name], get_mesh(), PartitionSpec(*spec)
                )
            extra = getattr(layer, "_extra_outs", None)
            if extra:
                outs.update(extra)
        new_state = {**ctx.state, **ctx.updated_state}
        return outs, new_state

    def loss_fn(self, params, feed, state=None, train=True, rng=None):
        """Scalar batch-mean cost over all cost layers — what
        TrainerInternal reduces via Argument::sum (TrainerInternal.cpp:135).
        Returns (loss, (outputs, new_state))."""
        outs, new_state = self.forward(
            params, feed, state=state, train=train, rng=rng
        )
        assert self.cost_names, "network has no cost layer"
        total = 0.0
        for n in self.cost_names:
            total = total + jnp.mean(outs[n].value)
        return total, (outs, new_state)
